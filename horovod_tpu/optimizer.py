"""DistributedOptimizer and variable broadcast — the framework adapter layer.

Reference parity
----------------
* ``hvd.DistributedOptimizer`` wraps any ``tf.train.Optimizer`` and
  allreduces each gradient before the wrapped optimizer applies it, only when
  ``size() > 1`` (``horovod/tensorflow/__init__.py:127-226``); the Keras
  variant dynamically subclasses the user's optimizer class so checkpoints
  restore without Horovod installed (``horovod/keras/__init__.py:66-87``).
* ``hvd.broadcast_global_variables(root)`` = grouped assign of
  ``broadcast(var, root)`` over every variable
  (``horovod/tensorflow/__init__.py:82-90``);
  ``BroadcastGlobalVariablesHook`` runs it right after session creation
  (``__init__.py:93-124``).

TPU-native design
-----------------
The optimizer layer is an **optax gradient transformation**: composable,
functional, and jit-traceable. ``DistributedOptimizer(opt)`` returns an optax
``GradientTransformation`` whose ``update`` first allreduces gradients over
the ``"hvd"`` ICI axis — with reference-semantics fusion bucketing
(64 MiB / same-dtype / order-preserving, see ``ops/fusion.py``) — then
defers to the wrapped transformation. Sparse gradients
(:class:`~horovod_tpu.ops.sparse.IndexedSlices` leaves) take the
two-allgather path (``horovod/tensorflow/__init__.py:61-72``) unless
``sparse_as_dense=True`` densifies them first.

Because optax state is a pure pytree, the Keras "dynamic subclass"
checkpoint-compatibility trick has a simpler equivalent: the wrapped
transformation's state **is** the inner optimizer's state, unchanged, so
checkpoints restore with plain optax, without this framework installed.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from . import runtime
from .ops.collectives import broadcast as _broadcast
from .ops.fusion import (ZeroPlan, fused_allgather_params, fused_allreduce,
                         fused_reduce_scatter, plan_exchange, plan_grad_sync,
                         plan_zero, resolve_wire_dtype, shard_params,
                         wire_dtype_name, zero_emit_order, zero_stack_global,
                         zero_stacked_spec, zero_unstack_global)
from .runtime import AXIS
from .ops.sparse import IndexedSlices, allreduce_indexed_slices
from .utils import config as _config


def _is_sparse_leaf(x) -> bool:
    return isinstance(x, IndexedSlices)


class Compression:
    """Gradient compression for the cross-chip allreduce.

    TPU-era extra (no analog in reference v0.11.2; later Horovod grew
    ``Compression.fp16``): ``Compression.bf16`` casts float gradients wider
    than 16 bits to bfloat16 — the MXU/ICI-native 16-bit type — before the
    fused allreduce and restores the original dtype after, halving
    interconnect bytes per step. Accumulation inside the XLA all-reduce is
    f32 on TPU, so the loss of precision is the single round-trip cast.

    Prefer ``wire_dtype=`` for new code: it casts at the BUCKET level
    (the fusion plan is unchanged, scales are applied in fp32, and the
    reduced result returns to fp32 before anything downstream touches
    it), adds an ``fp8`` format, and composes with ``zero=True`` — on the
    ZeRO plane ``Compression.bf16`` is accepted as an alias for
    ``wire_dtype="bf16"`` (see :func:`DistributedOptimizer`).
    """

    class none:  # noqa: N801 — enum-style namespace
        @staticmethod
        def compress(t):
            return t, None

        @staticmethod
        def decompress(t, ctx):
            return t

    class bf16:  # noqa: N801
        @staticmethod
        def compress(t):
            if (hasattr(t, "dtype")
                    and jnp.issubdtype(t.dtype, jnp.floating)
                    and jnp.dtype(t.dtype).itemsize > 2):
                return t.astype(jnp.bfloat16), t.dtype
            return t, None

        @staticmethod
        def decompress(t, ctx):
            return t.astype(ctx) if ctx is not None else t


# ---------------------------------------------------------------------------
# ZeRO-1 sharded optimizer (Rajbhandari et al. 2020 stage 1; Xu et al. 2020's
# weight-update sharding): every rank holds 1/N of the optimizer state, the
# gradient exchange becomes reduce-scatter + all-gather over the same fused
# buckets (same bytes on the wire as the all-reduce), and the optimizer math
# runs on 1/N of the elements. See ops/fusion.py for the bucket plane and
# docs/performance.md for when to flip it on.
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ZeroShardedState:
    """Rank-sharded optimizer state: the wrapped transformation's state over
    this world's flat bucket shards, plus the static bucket layout.

    ``inner`` is the wrapped optax state whose array leaves live in the
    stacked-shard layout ``[nshards, shard_len]`` — the leading axis is
    split one shard per rank over the world mesh (``P(AXIS)``), so each
    device holds ``1/nshards`` of every optimizer-state array. In a tpurun
    env-world each independent process holds only its OWN shard
    (``[1, shard_len]`` locally). Scalar leaves (e.g. Adam's step count)
    stay replicated. ``plan`` (static aux data) records the bucket layout
    so update/checkpoint can rebuild full trees.
    """

    inner: Any
    plan: ZeroPlan


jax.tree_util.register_dataclass(
    ZeroShardedState, data_fields=("inner",), meta_fields=("plan",))


def _zero_shard_leaf_buckets(inner, plan: ZeroPlan) -> List[Optional[int]]:
    """Map each flattened leaf of ``inner`` to the bucket whose stacked
    shard array it mirrors, or None for non-shard leaves (scalars).

    Elementwise optax transformations keep per-parameter state in subtrees
    shaped exactly like the params they were initialized on — here, the
    tuple of stacked ``(nshards, shard_len_i)`` bucket arrays — and those
    subtrees flatten contiguously in bucket order. Two buckets can share a
    stacked shape while differing in true (unpadded) length, so shape
    alone cannot identify a bucket; position within a contiguous run can,
    and is what checkpoint canonicalization needs to strip each bucket's
    padding correctly (:func:`zero_to_canonical`).
    """
    shard_shapes = plan.shard_shapes()
    nb = len(shard_shapes)
    out: List[Optional[int]] = []
    run = 0  # next bucket index expected in the current params-shaped run
    for leaf in jax.tree_util.tree_leaves(inner):
        shape = tuple(np.shape(leaf))
        if nb and shape == shard_shapes[run]:
            out.append(run)
            run = (run + 1) % nb
        elif nb and shape == shard_shapes[0]:
            out.append(0)
            run = 1 % nb
        else:
            out.append(None)
            run = 0
    return out


def zero_to_canonical(state: ZeroShardedState, *,
                      placeholders: bool = False) -> ZeroShardedState:
    """World-agnostic checkpoint form of a ZeRO state: every stacked
    ``[nshards, shard_len]`` shard leaf becomes the flat UNPADDED
    ``[true_len]`` vector, which is identical regardless of the world size
    that wrote it — so a checkpoint saved at world N restores (re-sharded)
    at world M. Scalar leaves pass through. ``placeholders=True`` emits
    ``np.zeros`` stand-ins (for building orbax restore templates without
    touching device data). No-op for env-world local-shard states (their
    leaves are ``[1, shard_len]`` with ``nshards > 1`` — only this rank's
    slice exists locally, so there is nothing world-agnostic to write).

    Hybrid (N-D mesh) plans extend the form to 2-D: the canonical vector
    is the flat concatenation of the bucket's GLOBAL leaves — the
    per-tp-coordinate dp stacks are unstacked and reassembled into the
    unsharded arrays first (:func:`~horovod_tpu.ops.fusion.
    zero_unstack_global`) — so the bytes are identical across BOTH world
    sizes and (dp, tp) mesh reshapes: a ``(dp=4, tp=2)`` checkpoint
    restores at ``(dp=2, tp=4)``."""
    plan = state.plan
    ids = _zero_shard_leaf_buckets(state.inner, plan)
    leaves, treedef = jax.tree_util.tree_flatten(state.inner)
    canon_sizes = plan.canonical_sizes()
    out = []
    for leaf, b in zip(leaves, ids):
        if b is None:
            out.append(leaf)
        elif placeholders:
            out.append(np.zeros((canon_sizes[b],),
                                np.dtype(plan.dtypes[plan.buckets[b][0]])))
        elif plan.hybrid:
            globals_ = zero_unstack_global(np.asarray(leaf), plan, b)
            out.append(np.concatenate([np.ravel(g) for g in globals_])
                       if len(globals_) > 1 else np.ravel(globals_[0]))
        else:
            out.append(jnp.reshape(leaf, (-1,))[:plan.sizes[b]])
    return ZeroShardedState(inner=treedef.unflatten(out), plan=plan)


def zero_from_canonical(canonical: Any,
                        template: ZeroShardedState) -> ZeroShardedState:
    """Re-shard a canonical (flat, unpadded) ZeRO state onto ``template``'s
    world: each flat leaf is zero-padded to the template plan's padded
    length, stacked ``[nshards, shard_len]``, and placed with the template
    leaf's sharding when it has one (the live state's ``P(AXIS)`` layout).
    ``canonical`` may be the structurally-restored orbax tree (containers
    as dicts/lists) — leaves are paired positionally with the template's.
    """
    plan = template.plan
    ids = _zero_shard_leaf_buckets(template.inner, plan)
    t_leaves, treedef = jax.tree_util.tree_flatten(template.inner)
    c_leaves = jax.tree_util.tree_leaves(canonical)
    if len(c_leaves) != len(t_leaves):
        raise ValueError(
            f"ZeRO state mismatch: checkpoint has {len(c_leaves)} "
            f"optimizer-state leaves, this world's template has "
            f"{len(t_leaves)} — was the checkpoint written by a different "
            f"optimizer?")
    canon_sizes = plan.canonical_sizes()
    out = []
    for c, t, b in zip(c_leaves, t_leaves, ids):
        if b is None:
            out.append(c)
            continue
        flat = np.asarray(c).reshape(-1)
        if flat.size != canon_sizes[b]:
            raise ValueError(
                f"ZeRO shard length mismatch: checkpoint leaf has "
                f"{flat.size} elements, this world's bucket {b} expects "
                f"{canon_sizes[b]} — the fusion bucket plan differs "
                f"(HOROVOD_FUSION_THRESHOLD and the mesh AXIS NAMES must "
                f"match the saving run, and the model must be unchanged; "
                f"dp/tp SIZE reshapes are fine, dropping or adding an "
                f"axis name changes the spec groups and is not)")
        if plan.hybrid:
            # 2-D canonical: split the flat global vector back into the
            # bucket's global leaves, then re-stack for THIS mesh's
            # (dp, tp) split.
            globals_full = [None] * len(plan.shapes)
            off = 0
            for j in plan.buckets[b]:
                n = int(np.prod(plan.global_shapes[j]))
                globals_full[j] = flat[off:off + n].reshape(
                    plan.global_shapes[j])
                off += n
            stacked = zero_stack_global(globals_full, plan, b)
        else:
            pad = plan.padded[b] - plan.sizes[b]
            if pad:
                flat = np.concatenate([flat, np.zeros((pad,), flat.dtype)])
            stacked = flat.reshape(plan.nshards, plan.shard_len(b))
        if isinstance(t, jax.Array):
            stacked = jax.device_put(stacked, t.sharding)
        out.append(stacked)
    return ZeroShardedState(inner=treedef.unflatten(out), plan=plan)


def _axes_bound(names) -> bool:
    """True when every named mesh axis is bound in the current trace —
    the generalization of ``runtime._in_world_trace`` to hybrid meshes."""
    from .utils.compat import axis_size as _axsz
    try:
        for n in ((names,) if isinstance(names, str) else tuple(names)):
            _axsz(n)
        return True
    except Exception:  # noqa: BLE001 — unbound axis raises NameError-ish
        return False


def partition_optimizer(optimizer: optax.GradientTransformation,
                        *,
                        average: bool = True,
                        fusion_threshold: Optional[int] = None,
                        accum_steps: int = 1,
                        wire_dtype=None,
                        overlap: bool = False,
                        axis_name: str = AXIS,
                        mesh=None,
                        param_specs=None,
                        scatter_axis: str = "dp",
                        skip_axes: Tuple[str, ...] = ()
                        ) -> optax.GradientTransformation:
    """Wrap an optax optimizer with ZeRO-1 sharded updates.

    ``init_fn`` materializes only this rank's optimizer-state shard
    (``1/size()`` of the bytes per device — single-controller worlds place
    the stacked shards ``P(AXIS)`` over the mesh, env-world processes
    build just their own slice). ``update_fn`` reduce-scatters the
    gradient tree over the fused buckets
    (:func:`~horovod_tpu.ops.fusion.fused_reduce_scatter`), runs the
    wrapped transformation on the local flat shards, and all-gathers the
    updated shards back into a full update tree — so
    ``optax.apply_updates(params, updates)`` keeps its contract and every
    replica ends bit-identical.

    Constraints (raised eagerly): dense gradients only (no
    ``IndexedSlices`` leaves — densify upstream), and the wrapped
    transformation must be ELEMENTWISE over its parameters (sgd, momentum,
    adam, adamw, ... — anything whose update of element ``i`` depends only
    on element ``i``'s gradient/state/param): the optimizer math sees flat
    bucket shards, not the original tree, so per-layer logic (multi-
    transform masks keyed on the tree, global-norm clipping) would compute
    per-SHARD instead. ``update`` must run inside the compiled step
    (``make_train_step(zero=True)``) when the world is larger than one.

    ``wire_dtype`` (``"bf16"``/``"fp8"``) runs the reduce-scatter in
    reduced precision with the received shard cast back to fp32 before
    the optax update (fp32 shard accumulation); the update all-gather
    stays at full precision so every replica still ends bit-identical.
    ``overlap=True`` issues the per-bucket scatters in backward-readiness
    order behind ``optimization_barrier`` pins (bucket membership — and
    therefore the sharded-state layout and checkpoint canonical form —
    never changes); pair it with ``make_train_step(overlap=True)``, which
    supplies the backward-completion order probe.

    ``mesh=`` + ``param_specs=`` switch to the N-D hybrid plane: the
    optimizer state shards over the mesh's ``scatter_axis`` (dp) for
    tp-sharded and replicated params alike — the plan groups leaves by
    their PartitionSpec so each bucket's reduce-scatter runs over ``dp``
    only, replicated buckets take their tp-side psum on the 1/dp shard,
    and tp-sharded buckets' stacked state arrays split over BOTH axes
    (``P(dp, tp)``), so no chip ever materializes another tp rank's
    state. ``param_specs`` may be the spec tree or a callable
    ``params -> spec tree``. Pair with ``make_train_step(mesh=,
    param_specs=)``; env-world (tpurun) hybrid is not supported.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    prescale = None if accum_steps <= 1 else 1.0 / accum_steps
    wire = resolve_wire_dtype(wire_dtype)
    if mesh is not None and param_specs is None:
        raise ValueError(
            "partition_optimizer(mesh=...) requires param_specs= — the "
            "spec tree is what keys the per-leaf collective plan")
    if mesh is not None and not average:
        raise ValueError(
            "the spec-grouped hybrid plane defines averaging semantics "
            "via per-group denominators — average=False has no meaning "
            "there")

    def _nshards() -> int:
        return runtime.size() if runtime.is_initialized() else 1

    def _hybrid_init(params):
        from jax.sharding import NamedSharding, PartitionSpec as P
        if runtime.is_initialized() and runtime.world().env_world:
            raise ValueError(
                "hybrid (mesh=) ZeRO is single-controller only: the "
                "env-world plane has no tp axis to shard weights over — "
                "run without tpurun, one process driving all chips")
        specs = param_specs(params) if callable(param_specs) \
            else param_specs
        n = int(mesh.shape[scatter_axis])
        plan = plan_zero(params, n, fusion_threshold, specs=specs,
                         mesh=mesh, scatter_axis=scatter_axis,
                         skip_axes=skip_axes)
        leaves = plan.treedef.flatten_up_to(params)
        if any(isinstance(l, jax.core.Tracer) for l in leaves):
            raise ValueError(
                "hybrid ZeRO state must be initialized eagerly (the "
                "stacked shard layout is assembled host-side from the "
                "global params) — call init outside jit")
        stacked = []
        for i in range(len(plan.buckets)):
            arr = zero_stack_global(leaves, plan, i)
            stacked.append(jax.device_put(
                arr, NamedSharding(mesh, zero_stacked_spec(plan, i))))
        inner = optimizer.init(tuple(stacked))
        # Commit every inner leaf to the hybrid mesh: shard leaves keep
        # the stacked layout (dp × the bucket's tp-like axes), scalars
        # (Adam count) replicate — one device set for jit dispatch AND
        # for these trees to work as restore templates.
        ids = _zero_shard_leaf_buckets(inner, plan)
        ileaves, itd = jax.tree_util.tree_flatten(inner)
        placed = []
        for leaf, b in zip(ileaves, ids):
            sharding = NamedSharding(
                mesh, P() if b is None else zero_stacked_spec(plan, b))
            placed.append(jax.device_put(jnp.asarray(leaf), sharding))
        return ZeroShardedState(inner=itd.unflatten(placed), plan=plan)

    def init_fn(params):
        if mesh is not None:
            return _hybrid_init(params)
        n = _nshards()
        plan = plan_zero(params, n, fusion_threshold)
        env_world = runtime.is_initialized() and runtime.world().env_world
        rank = runtime.world().controller_rank if env_world else None
        leaves = plan.treedef.flatten_up_to(params)
        from .ops.fusion import _fuse_bucket
        stacked = []
        for i in range(len(plan.buckets)):
            flat = _fuse_bucket(leaves, plan, i)
            s = plan.shard_len(i)
            if env_world:
                # One independent process per rank: materialize ONLY this
                # rank's slice — true 1/N host+device memory.
                arr = flat[rank * s:(rank + 1) * s].reshape(1, s)
            else:
                arr = jnp.reshape(flat, (n, s))
                if (runtime.is_initialized() and n > 1
                        and not isinstance(arr, jax.core.Tracer)):
                    # Place the stacked shards split over the world mesh
                    # up front: each device holds 1/N of every
                    # optimizer-state array from step 0.
                    from jax.sharding import NamedSharding
                    from jax.sharding import PartitionSpec as P
                    arr = jax.device_put(
                        arr, NamedSharding(runtime.mesh(), P(axis_name)))
            stacked.append(arr)
        inner = optimizer.init(tuple(stacked))
        if (not env_world and runtime.is_initialized() and n > 1):
            # Shard leaves inherited the stacked arrays' P(AXIS) layout
            # through the inner init's zeros_like; commit the scalar
            # leaves (e.g. Adam's count) to the same mesh replicated, so
            # the whole state shares one device set — required both for
            # jit dispatch and for these trees to serve as restore
            # templates (restore_sharded places leaves from the
            # template's sharding).
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P
            shard_shapes = set(plan.shard_shapes())
            rep = NamedSharding(runtime.mesh(), P())
            inner = jax.tree_util.tree_map(
                lambda l: l if (isinstance(l, jax.core.Tracer)
                                or tuple(np.shape(l)) in shard_shapes)
                else jax.device_put(l, rep), inner)
        return ZeroShardedState(inner=inner, plan=plan)

    def update_fn(grads, state: ZeroShardedState, params=None, **extra):
        if params is None:
            raise ValueError(
                "ZeRO update requires params: each rank slices its flat "
                "parameter shard locally for the wrapped optimizer "
                "(weight decay etc.) — call update(grads, state, params)")
        finite_out = extra.pop("finite_out", None)
        grad_order = extra.pop("grad_order", None)
        plan = state.plan
        axis = plan.scatter_axis if plan.scatter_axis is not None \
            else axis_name
        needs_trace = plan.nshards > 1 or bool(plan.nonscatter)
        if needs_trace and not _axes_bound(axis):
            raise ValueError(
                "ZeRO updates must run inside the compiled step (the "
                "reduce-scatter/all-gather pair is an in-trace collective "
                "over the mesh) — build the step with "
                "make_train_step(zero=True) (hybrid: make_train_step("
                "mesh=, param_specs=)), or use the env-world plane "
                "which drives the exchange from the host")
        if _axes_bound(axis):
            from .utils.compat import axis_size
            world = int(axis_size(axis))
            if world != plan.nshards:
                raise ValueError(
                    f"optimizer state was partitioned for a world of "
                    f"{plan.nshards} but this step runs over {world} "
                    f"{axis!r} rank(s) — initialize the state after "
                    f"hvd.init() / on the mesh the step runs over")
        need_finite = finite_out is not None
        emit = zero_emit_order(plan, grad_order) \
            if (overlap or grad_order is not None) else None
        out = fused_reduce_scatter(
            grads, plan, average=average, axis_name=axis,
            prescale=prescale, return_finite=need_finite,
            wire_dtype=wire, emit_order=emit)
        grad_shards, local_finite = out if need_finite else (out, None)
        p_shards = shard_params(params, plan, axis_name=axis)
        # The inner state's array leaves are per-device [1, shard_len]
        # blocks of the stacked layout; present the flat shards the same
        # way so elementwise state updates broadcast shape-exactly.
        gs = tuple(g.reshape(1, -1) for g in grad_shards)
        ps = tuple(p.reshape(1, -1) for p in p_shards)
        upd_shards, new_inner = optimizer.update(gs, state.inner, ps)
        flat_upd = [u.reshape(-1) for u in upd_shards]
        gathered = fused_allgather_params(
            flat_upd, plan, axis_name=axis,
            and_finite=local_finite if need_finite else None)
        if need_finite:
            updates, all_finite = gathered
            finite_out["all_finite"] = all_finite
        else:
            updates = gathered
        return updates, ZeroShardedState(inner=new_inner, plan=plan)

    update_fn.accum_steps = accum_steps
    update_fn.supports_finite_out = True
    update_fn.zero = True
    # Knob stamps: make_train_step reads these to thread the backward-
    # completion probe (overlap) and the env-world plane reads wire_dtype
    # to cast its host payloads.
    update_fn.wire_dtype = wire_dtype_name(wire)
    update_fn.overlap = overlap
    update_fn.supports_grad_order = True
    # The env-world plane drives the collectives from the host and needs
    # direct access to the wrapped transformation's shard update.
    update_fn.inner_update = optimizer.update
    # Hybrid stamps: make_train_step auto-detects the mesh/spec plane from
    # the optimizer exactly like it auto-detects zero.
    update_fn.mesh = mesh
    update_fn.param_specs = param_specs
    update_fn.scatter_axis = scatter_axis
    update_fn.hybrid = mesh is not None
    return optax.GradientTransformation(init_fn, update_fn)


def _hybrid_allreduce_optimizer(optimizer, *, mesh, param_specs, skip_axes,
                                fusion_threshold, accum_steps, wire,
                                overlap) -> optax.GradientTransformation:
    """The replicated-update half of the hybrid plane (zero=False):
    gradients ride the spec-grouped fused psum plan
    (:func:`~horovod_tpu.ops.fusion.fused_allreduce` with
    ``reduce_axes=``), the wrapped transformation updates a full replica.
    State leaves mirror the params, so they are committed to the hybrid
    mesh with the SAME PartitionSpecs — tp-sharded weights' momenta shard
    over tp too."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    prescale = None if accum_steps <= 1 else 1.0 / accum_steps

    def _specs_for(params):
        return param_specs(params) if callable(param_specs) else param_specs

    def init_fn(params):
        state = optimizer.init(params)
        specs = _specs_for(params)

        def _place(leaf, spec):
            if isinstance(leaf, jax.core.Tracer):
                return leaf
            return jax.device_put(jnp.asarray(leaf),
                                  NamedSharding(mesh, spec))

        return optax.tree_map_params(
            optimizer, lambda s, sp: _place(s, sp), state, specs,
            transform_non_params=lambda s: _place(s, P()))

    def update_fn(grads, state, params=None, **extra):
        finite_out = extra.pop("finite_out", None)
        grad_order = extra.pop("grad_order", None)
        specs = _specs_for(params if params is not None else grads)
        spec_leaves = jax.tree_util.tree_flatten(
            specs, is_leaf=lambda x: isinstance(x, P))[0]
        syncs = plan_grad_sync(spec_leaves, mesh, skip_axes=skip_axes)
        kw = dict(average=True, fusion_threshold=fusion_threshold,
                  prescale=prescale, wire_dtype=wire,
                  overlap=overlap, grad_order=grad_order,
                  reduce_axes=syncs)
        if finite_out is None:
            grads = fused_allreduce(grads, **kw)
        else:
            grads, all_finite = fused_allreduce(
                grads, return_finite=True, **kw)
            finite_out["all_finite"] = all_finite
        return optimizer.update(grads, state, params, **extra)

    update_fn.accum_steps = accum_steps
    update_fn.supports_finite_out = True
    update_fn.wire_dtype = wire_dtype_name(wire)
    update_fn.overlap = overlap
    update_fn.supports_grad_order = True
    update_fn.mesh = mesh
    update_fn.param_specs = param_specs
    update_fn.skip_axes = tuple(skip_axes)
    update_fn.hybrid = True
    # Uniform stamp with the 1-D plane: a host-plane executor driving
    # this optimizer reads the same planner (the hybrid ICI executor
    # builds its richer spec-grouped syncs in update_fn itself).
    update_fn.exchange_plan = functools.partial(
        plan_exchange, fusion_threshold=fusion_threshold)
    # The step builder derives opt-state PartitionSpecs by mapping the
    # param specs over the state with optax.tree_map_params — that needs
    # the WRAPPED transformation (this wrapper's init would device_put
    # optax's structure-probe placeholders).
    update_fn.inner_transform = optimizer
    return optax.GradientTransformation(init_fn, update_fn)


def DistributedOptimizer(optimizer: optax.GradientTransformation,
                         *,
                         average: bool = True,
                         fusion_threshold: Optional[int] = None,
                         sparse_as_dense: bool = False,
                         compression: Any = Compression.none,
                         accum_steps: int = 1,
                         zero: bool = False,
                         wire_dtype=None,
                         overlap: Optional[bool] = None,
                         axis_name: str = AXIS,
                         mesh=None,
                         param_specs=None,
                         skip_axes: Tuple[str, ...] = ()
                         ) -> optax.GradientTransformation:
    """Wrap an optax optimizer with fused gradient allreduce.

    Parity: ``hvd.DistributedOptimizer`` (``horovod/tensorflow/__init__.py:
    127-186``) — gradients are averaged across ranks before being applied;
    a no-op when ``size() == 1`` (``__init__.py:180-182``). Call inside the
    jitted train step under ``shard_map`` over the world mesh.
    ``compression=Compression.bf16`` halves allreduce bytes (see
    :class:`Compression`).

    ``accum_steps`` is the reference's ``backward_passes_per_step``: the
    caller feeds ``update`` the *sum* of N per-microbatch gradients and one
    fused allreduce fires per accumulated step, averaged by the **global
    microbatch count** (``accum_steps × size``) — the ``1/accum_steps`` is
    folded into the fused bucket traversal (:func:`fused_allreduce`'s
    ``prescale``) and ``average=True`` supplies the ``1/size``. Drive your
    own accumulation loop with this knob, or use
    ``make_train_step(accum_steps=N)`` which scans microbatches inside the
    compiled step and performs the microbatch mean itself (do NOT set both:
    the gradients would be divided by N twice).

    ``wire_dtype`` (``"bf16"``, ``"fp8"``; default ``HVD_WIRE_DTYPE``) puts
    float gradient buckets on the wire in reduced precision: scales are
    applied in fp32, one cast on send, and the reduced result is cast back
    to the gradient dtype immediately after — fp32 accumulation everywhere
    downstream (``docs/performance.md`` "Overlap & wire formats"). Unlike
    ``compression`` it never changes the bucket plan; don't set both on
    the all-reduce plane (the double cast would be ambiguous — it raises).

    ``overlap`` (default ``HVD_OVERLAP``) arms backward-overlapped bucket
    emission: per-bucket collectives issue in backward-completion order
    behind ``optimization_barrier`` pins so wire time hides behind the
    remaining backward compute. The completion order itself is probed by
    ``make_train_step(overlap=True)`` — set it there (or via the env var)
    and this wrapper picks it up from the step's ``grad_order`` channel.

    ``zero=True`` switches to ZeRO-1 sharded updates
    (:func:`partition_optimizer`): the fused all-reduce becomes a fused
    reduce-scatter + all-gather over the SAME buckets (same bytes on the
    wire), each rank holds and updates ``1/size()`` of the optimizer state,
    and the returned state is a :class:`ZeroShardedState`. Build the step
    with ``make_train_step(zero=True)`` (or ``HVD_ZERO=1``). Composes with
    ``accum_steps``, the bad-step guard, ``wire_dtype`` (the scatter rides
    the wire dtype with fp32 shard accumulation before the optax update;
    the update all-gather stays full-precision so replicas end
    bit-identical) and ``overlap``; ``compression=Compression.bf16`` is
    accepted as an alias for ``wire_dtype="bf16"`` here. Sparse gradients
    must be densified (``sparse_as_dense=True``).

    ``mesh=`` + ``param_specs=`` arm the N-D hybrid plane (ISSUE 8): the
    gradient exchange becomes the spec-grouped collective plan — each
    leaf psums over exactly the mesh axes it is replicated across
    (tp-sharded weight grads over ``dp`` only, with the psum-transpose
    correction folded into the bucket prescale), leaves bucket within
    their spec group, and with ``zero=True`` the optimizer state shards
    over ``dp`` for tp-sharded params too (:func:`partition_optimizer`).
    ``param_specs`` is a PartitionSpec tree mirroring the params (or a
    callable ``params -> tree``); pair with ``make_train_step(mesh=,
    param_specs=)``, which auto-detects the plane from this optimizer's
    stamp.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    wire = resolve_wire_dtype(
        wire_dtype if wire_dtype is not None
        else _config.wire_dtype_default())
    if overlap is None:
        overlap = _config.overlap_enabled()
    if mesh is not None:
        if param_specs is None:
            raise ValueError(
                "DistributedOptimizer(mesh=...) requires param_specs= — "
                "the spec tree keys the per-leaf collective plan")
        if not average:
            raise ValueError(
                "the spec-grouped hybrid plane defines averaging "
                "semantics via per-group denominators — average=False "
                "has no meaning there")
        if sparse_as_dense or (not zero
                               and compression is not Compression.none):
            raise ValueError(
                "the hybrid (mesh=) plane supports dense gradients and "
                "wire_dtype= only (compression= casts whole leaves "
                "before bucketing, which the spec-grouped plan replaces)")

    if zero:
        if compression is Compression.bf16:
            # The old eager rejection is gone: a bf16-compressed scatter
            # IS the bf16 wire format — the received shard is cast back to
            # fp32 before the optax update, so the f32 accumulation the
            # fused all-reduce path keeps is preserved here too.
            if wire is None:
                wire = jnp.dtype(jnp.bfloat16)
            elif wire != jnp.dtype(jnp.bfloat16):
                raise ValueError(
                    f"compression=Compression.bf16 (the bf16 wire alias) "
                    f"conflicts with wire_dtype={wire_dtype_name(wire)!r} "
                    f"— set wire_dtype alone")
        elif compression is not Compression.none:
            raise ValueError(
                "unsupported compression for zero=True: the ZeRO plane "
                "expresses compression as a wire format — use "
                "wire_dtype='bf16'/'fp8' (Compression.bf16 is accepted "
                "as an alias)")
        part = partition_optimizer(
            optimizer, average=average, fusion_threshold=fusion_threshold,
            accum_steps=accum_steps, wire_dtype=wire, overlap=overlap,
            axis_name=axis_name, mesh=mesh, param_specs=param_specs,
            skip_axes=skip_axes)
        if not sparse_as_dense:
            return part

        def _densify(grads):
            return jax.tree_util.tree_map(
                lambda l: l.to_dense() if _is_sparse_leaf(l) else l,
                grads, is_leaf=_is_sparse_leaf)

        def zero_update(grads, state, params=None, **extra):
            return part.update(_densify(grads), state, params, **extra)

        for attr in ("accum_steps", "supports_finite_out", "zero",
                     "inner_update", "wire_dtype", "overlap",
                     "supports_grad_order", "mesh", "param_specs",
                     "scatter_axis", "hybrid"):
            setattr(zero_update, attr, getattr(part.update, attr))
        # The env-world plane flattens grads itself (it never enters this
        # wrapper) and consults the stamp to densify before bucketing.
        zero_update.sparse_as_dense = True
        return optax.GradientTransformation(part.init, zero_update)

    if wire is not None and compression is not Compression.none:
        raise ValueError(
            "compression= and wire_dtype= both set: compression casts "
            "whole leaves before bucketing while wire_dtype casts each "
            "bucket at the collective (fp32 scales and accumulation) — "
            "pick one (wire_dtype is the recommended form)")

    if mesh is not None:
        return _hybrid_allreduce_optimizer(
            optimizer, mesh=mesh, param_specs=param_specs,
            skip_axes=skip_axes, fusion_threshold=fusion_threshold,
            accum_steps=accum_steps, wire=wire, overlap=overlap)

    def init_fn(params):
        return optimizer.init(params)

    def update_fn(grads, state, params=None, **extra):
        # ``finite_out``: the bad-step guard's side channel. When
        # ``make_train_step(guard_nonfinite=True)`` passes a dict here,
        # the fused allreduce additionally derives the world-wide
        # all-finite flag from the ALREADY-reduced buckets (same psum
        # round, zero extra collectives — see fused_allreduce) and this
        # function deposits it under ``"all_finite"`` for the step to
        # gate params/opt_state on. In-trace only: the dict holds a
        # tracer for the duration of the surrounding trace.
        finite_out = extra.pop("finite_out", None)
        grad_order = extra.pop("grad_order", None)
        kw = dict(average=average, fusion_threshold=fusion_threshold,
                  sparse_as_dense=sparse_as_dense, compression=compression,
                  accum_steps=accum_steps, axis_name=axis_name,
                  wire_dtype=wire, overlap=overlap, grad_order=grad_order)
        if finite_out is None:
            grads = allreduce_gradients(grads, **kw)
        else:
            grads, all_finite = allreduce_gradients(
                grads, return_finite=True, **kw)
            finite_out["all_finite"] = all_finite
        return optimizer.update(grads, state, params, **extra)

    # Stamp the knob where make_train_step can see it: setting accum_steps
    # on BOTH layers would silently divide gradients by N twice.
    update_fn.accum_steps = accum_steps
    # Capability stamp for the guard: make_train_step only threads the
    # finite_out channel into optimizers that declare it (a plain optax
    # transformation would choke on the unknown kwarg).
    update_fn.supports_finite_out = True
    # Knob stamps: the step builder reads overlap to arm its grad-order
    # probe; the env-world plane reads wire_dtype to cast host payloads.
    update_fn.wire_dtype = wire_dtype_name(wire)
    update_fn.overlap = overlap
    update_fn.supports_grad_order = True
    # The env-world executor interprets THIS plan (one planner, two
    # executors): same membership and denominators as the compiled
    # fused-allreduce, carried by the stamped optimizer so the two planes
    # cannot drift (the ZeRO state carries its ZeroPlan the same way).
    update_fn.exchange_plan = functools.partial(
        plan_exchange, axis_name=axis_name,
        fusion_threshold=fusion_threshold)
    return optax.GradientTransformation(init_fn, update_fn)


def allreduce_gradients(grads,
                        average: bool = True,
                        fusion_threshold: Optional[int] = None,
                        sparse_as_dense: bool = False,
                        compression: Any = Compression.none,
                        accum_steps: int = 1,
                        axis_name: str = AXIS,
                        return_finite: bool = False,
                        wire_dtype=None,
                        overlap: bool = False,
                        grad_order: Optional[Tuple[int, ...]] = None):
    """Allreduce a gradient pytree: dense leaves via fused flat buckets,
    sparse leaves via allgather (``horovod/tensorflow/__init__.py:61-79``).
    ``accum_steps > 1`` divides by the local microbatch count (the caller
    passes a gradient *sum* over N backward passes) as a prescale fused
    into the bucket traversal. ``return_finite=True`` additionally
    returns the world-wide all-finite scalar derived inside the same
    traversal (see :func:`~horovod_tpu.ops.fusion.fused_allreduce`).
    ``wire_dtype``/``overlap``/``grad_order`` pass through to the fused
    traversal (low-precision wire + backward-overlapped emission); the
    size-1 fast path ignores the wire — nothing travels, so nothing
    quantizes."""
    prescale = None if accum_steps <= 1 else 1.0 / accum_steps
    if runtime.is_initialized() and runtime.size() == 1 \
            and not runtime._in_world_trace():
        # size()==1 fast path (__init__.py:180-182) — but the microbatch
        # mean is not a cross-rank concern and must still happen, and
        # neither is finiteness: check the (scaled) local tree directly.
        if prescale is None and not return_finite:
            return grads
        from .ops.fusion import _prescale_array

        def _scale(l):
            if prescale is None:
                return l
            if _is_sparse_leaf(l):
                return IndexedSlices(_prescale_array(l.values, prescale),
                                     l.indices, l.dense_shape)
            return _prescale_array(l, prescale)
        scaled = jax.tree_util.tree_map(_scale, grads,
                                        is_leaf=_is_sparse_leaf)
        if not return_finite:
            return scaled
        finite = jnp.ones((), jnp.bool_)
        for l in jax.tree_util.tree_leaves(scaled,
                                           is_leaf=_is_sparse_leaf):
            v = l.values if _is_sparse_leaf(l) else l
            if jnp.issubdtype(v.dtype, jnp.inexact):
                finite = finite & jnp.all(jnp.isfinite(v))
        return scaled, finite

    if sparse_as_dense:
        grads = jax.tree_util.tree_map(
            lambda l: l.to_dense() if _is_sparse_leaf(l) else l,
            grads, is_leaf=_is_sparse_leaf)

    # Structural (tree_map) compression round-trip: the ctx tree mirrors the
    # gradient tree leaf-for-leaf (wrapped in an opaque holder so a None ctx
    # is still a leaf), so restoration cannot depend on flatten ordering.
    class _Ctx:
        __slots__ = ("dtype",)

        def __init__(self, dtype):
            self.dtype = dtype

    ctx_tree = jax.tree_util.tree_map(
        lambda l: _Ctx(None if _is_sparse_leaf(l)
                       else compression.compress(l)[1]),
        grads, is_leaf=_is_sparse_leaf)
    compressed = jax.tree_util.tree_map(
        lambda l: l if _is_sparse_leaf(l) else compression.compress(l)[0],
        grads, is_leaf=_is_sparse_leaf)
    # fused_allreduce buckets dense leaves and routes IndexedSlices leaves
    # through the two-allgather sparse path.
    reduced = fused_allreduce(compressed, average=average,
                              fusion_threshold=fusion_threshold,
                              axis_name=axis_name, prescale=prescale,
                              return_finite=return_finite,
                              wire_dtype=wire_dtype, overlap=overlap,
                              grad_order=grad_order)
    if return_finite:
        reduced, all_finite = reduced
    out = jax.tree_util.tree_map(
        lambda l, c: l if _is_sparse_leaf(l)
        else compression.decompress(l, c.dtype),
        reduced, ctx_tree, is_leaf=_is_sparse_leaf)
    return (out, all_finite) if return_finite else out


def broadcast_global_variables(variables, root_rank: int = 0,
                               axis_name: str = AXIS):
    """Broadcast every leaf of a pytree from ``root_rank``.

    Parity: ``hvd.broadcast_global_variables``
    (``horovod/tensorflow/__init__.py:82-90``) — used right after
    initialization or checkpoint restore so all ranks start from rank 0's
    weights (§5.4 consistency protocol).
    """
    return jax.tree_util.tree_map(
        lambda v: _broadcast(v, root_rank=root_rank, axis_name=axis_name),
        variables)


# Alias matching modern naming; same semantics.
broadcast_parameters = broadcast_global_variables


def broadcast_optimizer_state(opt_state, root_rank: int = 0,
                              axis_name: str = AXIS):
    """Broadcast optimizer state (momenta etc.) from ``root_rank`` — the
    optax analog of broadcasting optimizer slot variables, which the
    reference gets for free because slots are global variables
    (``horovod/tensorflow/__init__.py:82-90``)."""
    return broadcast_global_variables(opt_state, root_rank, axis_name)
