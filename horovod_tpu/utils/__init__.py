"""Utilities: config (env knobs), timeline (Chrome tracing), validation."""
