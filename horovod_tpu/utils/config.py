"""Environment-variable configuration.

The reference has no config files or CLI flags — everything is plain env vars
(SURVEY §5.6): ``HOROVOD_TIMELINE`` (``mpi_ops.cc:1275``),
``HOROVOD_FUSION_THRESHOLD`` (``mpi_ops.cc:1281-1284``; 0 disables fusion,
``docs/tensor-fusion.md:24-28``), plus launcher-provided rank/size env vars that
the tests read (``mpi_ops_test.py:31-63`` reads ``PMI_RANK``/
``OMPI_COMM_WORLD_RANK`` etc.).

We keep the same names where semantics match, and add ``HVD_*`` launcher vars
(set by ``tpurun``) playing the role of the MPI launcher's env.
"""

from __future__ import annotations

import os

# Default tensor-fusion threshold: 64 MiB (mpi_ops.cc:165).
DEFAULT_FUSION_THRESHOLD: int = 64 * 1024 * 1024

# Coordinator stall-warning threshold: 60 s (STALL_WARNING_TIME, mpi_ops.cc:228).
DEFAULT_STALL_WARNING_SECS: float = 60.0

# Background tick period: 5 ms (mpi_ops.cc:1295). Our host coordination core
# uses the same default tick.
DEFAULT_TICK_SECS: float = 0.005


def _int_env(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        return default


def fusion_threshold_bytes() -> int:
    """``HOROVOD_FUSION_THRESHOLD`` override (mpi_ops.cc:1281-1284).

    0 disables fusion entirely (docs/tensor-fusion.md:24-28).
    """
    return _int_env("HOROVOD_FUSION_THRESHOLD", DEFAULT_FUSION_THRESHOLD)


def timeline_path() -> str | None:
    """``HOROVOD_TIMELINE`` — path for the Chrome-tracing file (mpi_ops.cc:1275).

    Written by the coordinator (rank 0) only (docs/timeline.md:7-11).
    """
    return os.environ.get("HOROVOD_TIMELINE") or None


def restart_epoch() -> int:
    """``HVD_RESTART_EPOCH`` — which (re)launch of the world this is;
    exported by ``tpurun --restarts`` (0 on the first launch / unset).
    The single parse shared by the elastic recovery API and the fault
    injector's ``@epoch`` gating — they must always agree."""
    try:
        return int(os.environ.get("HVD_RESTART_EPOCH", "0") or 0)
    except ValueError:
        return 0


def guard_nonfinite() -> bool:
    """``HVD_GUARD_NONFINITE`` — default for the in-jit bad-step guard
    (``make_train_step(guard_nonfinite=...)``): skip the optimizer update
    (params/opt_state bit-unchanged) whenever any replica's gradients
    carry NaN/Inf. Off unless set to 1/true/yes — the guard itself adds
    no collectives, but containment makes ``Trainer.fit`` fetch one
    scalar per step to track consecutive skips."""
    return os.environ.get("HVD_GUARD_NONFINITE", "").lower() in (
        "1", "true", "yes", "on")


def zero_enabled() -> bool:
    """``HVD_ZERO`` — default for ZeRO-1 sharded optimizer updates
    (``create_train_state(zero=...)`` / ``make_train_step(zero=...)``):
    the gradient exchange becomes reduce-scatter + all-gather over the
    fused buckets and each rank holds 1/size() of the optimizer state
    (``docs/performance.md``). Off unless set to 1/true/yes/on."""
    return os.environ.get("HVD_ZERO", "").lower() in (
        "1", "true", "yes", "on")


def overlap_enabled() -> bool:
    """``HVD_OVERLAP`` — default for backward-overlapped bucket collectives
    (``make_train_step(overlap=...)``): per-bucket gradient collectives are
    emitted in backward-completion order behind ``optimization_barrier``
    pins so XLA's scheduler hides wire time behind the remaining backward
    compute (``docs/performance.md`` "Overlap & wire formats"). Off unless
    set to 1/true/yes/on."""
    return os.environ.get("HVD_OVERLAP", "").lower() in (
        "1", "true", "yes", "on")


def wire_dtype_default() -> str | None:
    """``HVD_WIRE_DTYPE`` — default low-precision wire format for gradient
    collectives (``DistributedOptimizer(wire_dtype=...)``): ``bf16`` or
    ``fp8`` (e4m3, per-bucket dynamic scaling); empty/``fp32`` means full
    precision. Resolution/validation lives in
    :func:`horovod_tpu.ops.fusion.resolve_wire_dtype`."""
    raw = os.environ.get("HVD_WIRE_DTYPE", "").strip().lower()
    return raw or None


# Consecutive skipped (non-finite) steps tolerated before Trainer.fit
# rolls back to the last verified checkpoint / raises NonFiniteGradError.
DEFAULT_MAX_BAD_STEPS: int = 5


def max_bad_steps() -> int:
    """``HVD_MAX_BAD_STEPS`` — consecutive bad-step budget for the
    containment path (default 5): a transient NaN burst shorter than this
    is absorbed by skip-steps alone; a longer storm means the params (or
    the data pipeline) are already wrong and the run rolls back to the
    last verified checkpoint instead of skipping forever."""
    return max(1, _int_env("HVD_MAX_BAD_STEPS", DEFAULT_MAX_BAD_STEPS))


def metrics_port() -> int:
    """``HVD_METRICS_PORT`` — base port of the per-rank metrics HTTP
    listeners (rank *r* serves ``GET /metrics`` on ``base + r``; see
    :mod:`horovod_tpu.obs.http`). 0/unset disables — training jobs pay
    nothing unless an operator asks for the scrape surface."""
    return max(0, _int_env("HVD_METRICS_PORT", 0))


def stall_warning_secs() -> float:
    raw = os.environ.get("HOROVOD_STALL_CHECK_TIME")
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return DEFAULT_STALL_WARNING_SECS


# ---------------------------------------------------------------------------
# Launcher-provided process env (tpurun equivalent of mpirun's PMI/OMPI vars).
# Tests mirror the reference's pattern of reading launcher env with defaults
# (0, 1) when not launched distributed (mpi_ops_test.py:31-63).
# ---------------------------------------------------------------------------

_RANK_VARS = ("HVD_RANK", "PMI_RANK", "OMPI_COMM_WORLD_RANK")
_SIZE_VARS = ("HVD_SIZE", "PMI_SIZE", "OMPI_COMM_WORLD_SIZE")
_LOCAL_RANK_VARS = ("HVD_LOCAL_RANK", "OMPI_COMM_WORLD_LOCAL_RANK")


def _first_env(names, default: int) -> int:
    for n in names:
        v = os.environ.get(n)
        if v is not None and v != "":
            try:
                return int(v)
            except ValueError:
                continue
    return default


def launcher_rank(default: int = 0) -> int:
    return _first_env(_RANK_VARS, default)


def launcher_size(default: int = 1) -> int:
    return _first_env(_SIZE_VARS, default)


def launcher_local_rank(default: int = 0) -> int:
    return _first_env(_LOCAL_RANK_VARS, default)


def coordinator_address() -> str | None:
    """Rendezvous address for the multi-process control plane (DCN/TCP).

    Plays the role MPI's out-of-band wire-up plays for the reference
    (``MPI_Init``, ``mpi_ops.cc:1251``).
    """
    return os.environ.get("HVD_COORD_ADDR") or None
