"""Shared LR-schedule / warmup / momentum-correction math.

ONE implementation of the reference's schedule semantics
(``horovod/keras/callbacks.py:90-259``), consumed by both adapter layers:

* :class:`horovod_tpu.callbacks.LearningRateScheduleCallback` (optax
  hyperparam-state plumbing), and
* :class:`horovod_tpu.keras.LearningRateScheduleCallback` (Keras 3
  optimizer-variable plumbing).

The adapters own only the get/set plumbing for their optimizer
representation; the *decisions* — when to adjust, to what value, and how
to momentum-correct (Goyal et al. 1706.02677 §3: while a batch runs at
lr' = lr·m, momentum is scaled by ``new_lr/old_lr`` and restored after
the batch) — live here so they cannot drift apart.
"""

from __future__ import annotations

from typing import Callable, Optional


class LRScheduleCore:
    """Schedule state machine: LR = ``initial_lr * multiplier(epoch)``
    between ``start_epoch`` and ``end_epoch``.

    ``staircase=True`` adjusts once per epoch (batch 0) at integer epoch;
    ``staircase=False`` adjusts every batch at fractional
    ``epoch + batch/steps_per_epoch`` (parity:
    ``horovod/keras/callbacks.py:155-199``).
    """

    def __init__(self, multiplier, start_epoch: int = 0,
                 end_epoch: Optional[int] = None, staircase: bool = True,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None):
        if not callable(multiplier):
            staircase = True
            self.multiplier = lambda epoch: multiplier
        else:
            self.multiplier = multiplier
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.steps_per_epoch = steps_per_epoch
        self.initial_lr: Optional[float] = None
        self.current_epoch = 0
        self.restore_momentum: Optional[float] = None

    def train_begin(self, initial_lr: float) -> None:
        self.initial_lr = initial_lr
        if not self.staircase and not self.steps_per_epoch:
            raise ValueError(
                "steps_per_epoch is required for staircase=False "
                "(smooth per-batch adjustment)")

    def epoch_begin(self, epoch: int) -> None:
        self.current_epoch = epoch

    def target_lr(self, batch: int) -> Optional[float]:
        """The LR this batch should run at, or ``None`` for no adjustment
        (outside the schedule window, or staircase off-batch)."""
        e = self.current_epoch
        if e < self.start_epoch or (self.end_epoch is not None
                                    and e >= self.end_epoch):
            return None
        if self.staircase:
            if batch != 0:
                return None
            return self.initial_lr * self.multiplier(e)
        return self.initial_lr * self.multiplier(
            e + float(batch) / self.steps_per_epoch)

    def corrected_momentum(self, old_lr: float, new_lr: float,
                           momentum: Optional[float]) -> Optional[float]:
        """Momentum to run the adjusted batch with (``m·new_lr/old_lr``),
        remembering the value :meth:`momentum_to_restore` hands back after
        the batch. ``None`` = no correction (disabled, no momentum in the
        optimizer, or old_lr unusable)."""
        if not self.momentum_correction or momentum is None \
                or not old_lr > 0:
            return None
        self.restore_momentum = momentum
        return momentum * new_lr / old_lr

    def momentum_to_restore(self) -> Optional[float]:
        """The pre-correction momentum to reinstate at batch end (once),
        or ``None``."""
        m, self.restore_momentum = self.restore_momentum, None
        return m


def warmup_multiplier(warmup_epochs: int,
                      steps_per_epoch_fn: Callable[[], int],
                      size_fn: Callable[[], int]):
    """Goyal et al. gradual-warmup multiplier ``lr/size → lr`` over
    ``warmup_epochs`` (parity: ``horovod/keras/callbacks.py:213-247``),
    shifted by one step so each epoch ends on a round multiplier::

        lr'(epoch) = lr/size * (epoch * (size-1)/warmup + 1)

    ``steps_per_epoch_fn``/``size_fn`` are callables so values resolved at
    train time (trainer-provided steps, a lazily-initialized world) are
    honored.
    """
    def multiplier(epoch: float) -> float:
        size = size_fn()
        epoch += 1.0 / steps_per_epoch_fn()
        return 1.0 / size * (epoch * (size - 1) / warmup_epochs + 1)
    return multiplier
