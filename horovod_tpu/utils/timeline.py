"""Horovod Timeline: Chrome-tracing (catapult) JSON writer.

Reference parity (``timeline.h``/``timeline.cc``, SURVEY §5.1):

* Enabled by ``HOROVOD_TIMELINE=<file>``, written by the coordinator
  (rank 0) only, yet shows all workers' readiness
  (``mpi_ops.cc:1275-1278``, ``docs/timeline.md:7-11``).
* Each tensor is a fake "process" (pid) with a metadata event naming it
  (``timeline.cc:59-76``); a tensor-name→pid table keeps files small
  (``timeline.h:83``).
* Per-tensor state machine UNKNOWN→NEGOTIATING→TOP_LEVEL→ACTIVITY
  (``timeline.h:37-42``).
* Phase 1 "NEGOTIATE_<OP>": begin event on first request, an instant event
  per rank as it reports ready (``NegotiateRankReady``,
  ``timeline.cc:118-125``), end when all ranks are in.
* Phase 2: top-level op event with nested activities (QUEUE, SCHEDULE,
  MEMCPY_IN_FUSION_BUFFER, …; ``mpi_ops.cc:623-635``,
  ``docs/timeline.md:25-43``).
* ``End`` logs the output dtype+shape (``timeline.cc:203-220``); writes are
  mutex-guarded; ~1 s flush interval (``timeline.h:35``).

TPU adaptation: negotiation events come from the host coordination plane
(or are synthesized instantly in single-controller mode where no negotiation
exists); compute-phase boundaries come from dispatch timestamps — XLA owns
on-chip scheduling, so fine-grained on-device phases belong to the JAX
profiler, which this trace is designed to be merged with.
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import threading
import time
from typing import Iterable, Optional


class _State:
    UNKNOWN = 0
    NEGOTIATING = 1
    TOP_LEVEL = 2
    ACTIVITY = 3


# Host-plane phase names (beyond the reference's collective activities):
# the overlapped training hot path emits these so a trace shows WHAT the
# host was doing while the device ran — input staging and checkpointing,
# the two host activities PR 3 moved off the step's critical path.
H2D = "H2D"                      # prefetch thread: host→device batch copy
CKPT_SNAPSHOT = "CKPT_SNAPSHOT"  # step loop: device→host state snapshot
CKPT_WRITE = "CKPT_WRITE"        # background writer: orbax write + GC
BAD_STEP = "BAD_STEP"            # guard: non-finite grads, update skipped


@contextlib.contextmanager
def maybe_op(tl: Optional["Timeline"], tensor_name: str, op_kind: str):
    """Scoped :meth:`Timeline.op` that no-ops when ``tl`` is None — the
    emitters on the training hot path (prefetch thread, checkpoint writer)
    run with or without a timeline and must not branch at every call site.
    Each concurrent emitter uses its own ``tensor_name`` row, so the
    per-row state machine never sees interleaved ops from two threads."""
    if tl is None:
        yield None
        return
    with tl.op(tensor_name, op_kind):
        yield tl


class TimelineStateError(RuntimeError):
    """Illegal timeline transition — a B event would be left unbalanced
    (the reference asserts these transitions, ``timeline.h:37-42`` enforced
    in ``timeline.cc:118-135``)."""


class Timeline:
    """Chrome-tracing writer (JSON array format, streaming).

    The per-tensor state machine UNKNOWN→NEGOTIATING→TOP_LEVEL→ACTIVITY is
    ENFORCED (not just tracked): a call out of order raises
    :class:`TimelineStateError` instead of silently writing an unbalanced
    B/E stream. Activities nest; ``_depth`` counts open activity frames.
    Every duration/instant event carries ``tid: 0`` — Perfetto and some
    catapult builds require a tid to pair B/E events within a pid.
    """

    FLUSH_INTERVAL_SECS = 1.0  # timeline.h:35

    def __init__(self, path: str):
        self._lock = threading.Lock()
        self._file = open(path, "w")
        self._file.write("[\n")
        self._start = time.monotonic()
        self._pids: dict[str, int] = {}
        self._states: dict[str, int] = {}
        self._depth: dict[str, int] = {}
        self._last_flush = self._start
        self._closed = False
        # Crash safety: the ~1 s flush cadence means a killed rank loses
        # the buffered tail of its trace — the very events that explain
        # the death. An atexit close catches normal-but-uncloseed exits;
        # the fatal-signal path is covered by the flight recorder's
        # crash hooks (runtime.init registers self.flush there).
        atexit.register(self.close)

    # -- low-level ---------------------------------------------------------

    def _ts_us(self) -> int:
        return int((time.monotonic() - self._start) * 1e6)

    def _emit(self, ev: dict) -> None:
        with self._lock:
            if self._closed:
                return
            self._file.write(json.dumps(ev) + ",\n")
            now = time.monotonic()
            if now - self._last_flush > self.FLUSH_INTERVAL_SECS:
                self._file.flush()
                self._last_flush = now

    def flush(self, fsync: bool = True) -> None:
        """Push buffered events to disk NOW (fsync by default): called
        from error paths (:meth:`abort`) and crash hooks, where "the OS
        probably would have written it" is not good enough — the reader
        is a post-mortem."""
        with self._lock:
            if self._closed:
                return
            try:
                self._file.flush()
                if fsync:
                    os.fsync(self._file.fileno())
            except (OSError, ValueError):
                pass  # a dying process keeps dying
            self._last_flush = time.monotonic()

    def _pid(self, tensor_name: str) -> int:
        pid = self._pids.get(tensor_name)
        if pid is None:
            pid = len(self._pids)
            self._pids[tensor_name] = pid
            # Metadata event registering the tensor as a pseudo-process
            # (timeline.cc:59-76).
            self._emit({"name": "process_name", "ph": "M", "pid": pid,
                        "args": {"name": tensor_name}})
            self._emit({"name": "process_sort_index", "ph": "M", "pid": pid,
                        "args": {"sort_index": pid}})
        return pid

    def _expect(self, tensor_name: str, allowed: tuple, call: str) -> None:
        state = self._states.get(tensor_name, _State.UNKNOWN)
        if state not in allowed:
            names = {0: "UNKNOWN", 1: "NEGOTIATING", 2: "TOP_LEVEL",
                     3: "ACTIVITY"}
            raise TimelineStateError(
                f"timeline: {call}({tensor_name!r}) illegal in state "
                f"{names[state]} (allowed: "
                f"{'/'.join(names[s] for s in allowed)})")

    # -- negotiation phase (timeline.cc:107-140) ---------------------------

    def negotiate_start(self, tensor_name: str, op_kind: str) -> None:
        pid = self._pid(tensor_name)
        self._expect(tensor_name, (_State.UNKNOWN,), "negotiate_start")
        self._states[tensor_name] = _State.NEGOTIATING
        self._emit({"name": f"NEGOTIATE_{op_kind}", "ph": "B", "pid": pid,
                    "tid": 0, "ts": self._ts_us()})

    def negotiate_rank_ready(self, tensor_name: str, rank: int) -> None:
        pid = self._pid(tensor_name)
        self._expect(tensor_name, (_State.NEGOTIATING,),
                     "negotiate_rank_ready")
        self._emit({"name": str(rank), "ph": "i", "pid": pid, "tid": 0,
                    "ts": self._ts_us(), "s": "p"})

    def negotiate_end(self, tensor_name: str) -> None:
        pid = self._pid(tensor_name)
        self._expect(tensor_name, (_State.NEGOTIATING,), "negotiate_end")
        self._states[tensor_name] = _State.UNKNOWN
        self._emit({"name": "", "ph": "E", "pid": pid, "tid": 0,
                    "ts": self._ts_us()})

    def negotiate_instant(self, tensor_name: str, op_kind: str,
                          ready_ranks: Iterable[int] = ()) -> None:
        """Single-controller mode: SPMD needs no negotiation; record the
        would-be negotiation as an instantaneous phase for trace parity."""
        self.negotiate_start(tensor_name, op_kind)
        for r in ready_ranks:
            self.negotiate_rank_ready(tensor_name, r)
        self.negotiate_end(tensor_name)

    # -- processing phase (timeline.cc:142-220) ----------------------------

    def start(self, tensor_name: str, op_kind: str) -> None:
        pid = self._pid(tensor_name)
        self._expect(tensor_name, (_State.UNKNOWN,), "start")
        self._states[tensor_name] = _State.TOP_LEVEL
        self._depth[tensor_name] = 0
        self._emit({"name": op_kind, "ph": "B", "pid": pid, "tid": 0,
                    "ts": self._ts_us()})

    def activity_start(self, tensor_name: str, activity: str) -> None:
        pid = self._pid(tensor_name)
        self._expect(tensor_name, (_State.TOP_LEVEL, _State.ACTIVITY),
                     "activity_start")
        self._states[tensor_name] = _State.ACTIVITY
        self._depth[tensor_name] = self._depth.get(tensor_name, 0) + 1
        self._emit({"name": activity, "ph": "B", "pid": pid, "tid": 0,
                    "ts": self._ts_us()})

    def activity_end(self, tensor_name: str) -> None:
        pid = self._pid(tensor_name)
        self._expect(tensor_name, (_State.ACTIVITY,), "activity_end")
        depth = self._depth.get(tensor_name, 1) - 1
        self._depth[tensor_name] = depth
        self._states[tensor_name] = (
            _State.TOP_LEVEL if depth == 0 else _State.ACTIVITY)
        self._emit({"name": "", "ph": "E", "pid": pid, "tid": 0,
                    "ts": self._ts_us()})

    def end(self, tensor_name: str, output=None) -> None:
        """End the top-level event, logging output dtype+shape
        (timeline.cc:203-220)."""
        pid = self._pid(tensor_name)
        self._expect(tensor_name, (_State.TOP_LEVEL,), "end")
        args = {}
        if output is not None:
            shape = getattr(output, "shape", None)
            dtype = getattr(output, "dtype", None)
            if shape is not None:
                args["shape"] = list(shape)
            if dtype is not None:
                args["dtype"] = str(dtype)
        self._states[tensor_name] = _State.UNKNOWN
        ev = {"name": "", "ph": "E", "pid": pid, "tid": 0,
              "ts": self._ts_us()}
        if args:
            ev["args"] = args
        self._emit(ev)

    def abort(self, tensor_name: str, error: Optional[str] = None) -> None:
        """Close every open B event for ``tensor_name`` after a dispatch
        failure so the trace stays balanced (error paths must not corrupt
        the stream). Safe to call in any state."""
        state = self._states.get(tensor_name, _State.UNKNOWN)
        if state == _State.UNKNOWN:
            return
        pid = self._pid(tensor_name)
        if state == _State.NEGOTIATING:
            self.negotiate_end(tensor_name)
            self.flush(fsync=True)
            return
        while self._depth.get(tensor_name, 0) > 0:
            self.activity_end(tensor_name)
        ev = {"name": "", "ph": "E", "pid": pid, "tid": 0,
              "ts": self._ts_us()}
        if error:
            ev["args"] = {"error": error}
        self._states[tensor_name] = _State.UNKNOWN
        self._emit(ev)
        # An abort usually precedes a death (dispatch failure, world
        # ABORT): make the trace durable now instead of trusting the
        # 1 s cadence to get another turn.
        self.flush(fsync=True)

    # -- scoped helpers (serving plane) ------------------------------------
    #
    # The training-side emitters drive the state machine from callbacks
    # spread across the dispatch path, so they use the raw begin/end calls
    # above. The serving plane (horovod_tpu.serve) brackets whole code
    # regions — QUEUE → PAD → XLA_EXECUTE → RESPOND inside one INFERENCE
    # op — where scope-exit safety matters more: an exception mid-phase
    # must not leave a B event unbalanced.

    @contextlib.contextmanager
    def op(self, tensor_name: str, op_kind: str):
        """Scoped top-level event; aborts (balanced close + error arg) if
        the body raises."""
        self.start(tensor_name, op_kind)
        try:
            yield self
        except BaseException as e:
            self.abort(tensor_name, error=repr(e))
            raise
        else:
            self.end(tensor_name)

    @contextlib.contextmanager
    def activity(self, tensor_name: str, name: str):
        """Scoped nested activity under an open :meth:`op`."""
        self.activity_start(tensor_name, name)
        try:
            yield self
        finally:
            if self._states.get(tensor_name) == _State.ACTIVITY:
                self.activity_end(tensor_name)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            try:
                atexit.unregister(self.close)
            except Exception:  # noqa: BLE001 — interpreter may be exiting
                pass
            # Chrome's trace viewer tolerates the trailing comma; close the
            # array for strict-JSON consumers.
            self._file.write("{}]\n")
            self._file.close()
