"""JAX version compatibility shims.

``HVD_COMPAT_LEVEL`` forces the resolution level so CI can exercise the
older-API code paths under a current jax (``ci.sh`` runs a leg with
``HVD_COMPAT_LEVEL=private``; see README "Version matrix"):

* unset/``public`` — prefer the public symbol (current jax);
* ``private`` — skip the public symbol, resolve the pre-export private
  path (jax versions where ``all_gather_invariant`` existed but was not
  yet public);
* ``plain`` — plain ``all_gather`` (pre-VMA jax, where shard_map's
  ``out_specs=P()`` did not require the invariant marking; under a
  current VMA-checking jax this level is expected to fail type checks —
  it exists for running the suite against an actually-old jax install,
  not for simulation).
"""

from __future__ import annotations

import os

from jax import lax


def _resolve_all_gather_invariant():
    """``all_gather`` whose output is marked replicated (invariant) over the
    axis, so ``shard_map(..., out_specs=P())`` type-checks under VMA
    analysis. Public in newer JAX; fall back to the private symbol, then to
    plain ``all_gather`` (pre-VMA versions don't need the distinction)."""
    level = os.environ.get("HVD_COMPAT_LEVEL", "public")
    if level not in ("public", "private", "plain"):
        raise ValueError(
            f"HVD_COMPAT_LEVEL must be public|private|plain, got {level!r}")
    if level == "public":
        fn = getattr(lax, "all_gather_invariant", None)
        if fn is not None:
            return fn
        level = "private"
    forced_private = os.environ.get("HVD_COMPAT_LEVEL") == "private"
    if level == "private":
        try:
            from jax._src.lax.parallel import all_gather_invariant
            return all_gather_invariant
        except ImportError:
            if forced_private:
                # A forced level must not silently degrade to `plain` (the
                # level documented to fail under VMA): fail with the real
                # signal — this jax dropped the private symbol.
                raise ImportError(
                    "HVD_COMPAT_LEVEL=private: this jax has neither a "
                    "public nor a private all_gather_invariant; the "
                    "private-path CI leg no longer applies to it")
    return lax.all_gather


all_gather_invariant = _resolve_all_gather_invariant()
