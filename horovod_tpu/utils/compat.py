"""JAX version compatibility shims.

``HVD_COMPAT_LEVEL`` forces the resolution level so CI can exercise the
older-API code paths under a current jax (``ci.sh`` runs a leg with
``HVD_COMPAT_LEVEL=private``; see README "Version matrix"):

* unset/``public`` — prefer the public symbol (current jax);
* ``private`` — skip the public symbol, resolve the pre-export private
  path (jax versions where ``all_gather_invariant`` existed but was not
  yet public);
* ``plain`` — plain ``all_gather`` (pre-VMA jax, where shard_map's
  ``out_specs=P()`` did not require the invariant marking; under a
  current VMA-checking jax this level is expected to fail type checks —
  it exists for running the suite against an actually-old jax install,
  not for simulation).
"""

from __future__ import annotations

import os

import jax
from jax import lax


def _resolve_all_gather_invariant():
    """``all_gather`` whose output is marked replicated (invariant) over the
    axis, so ``shard_map(..., out_specs=P())`` type-checks under VMA
    analysis. Public in newer JAX; fall back to the private symbol, then to
    plain ``all_gather`` (pre-VMA versions don't need the distinction)."""
    level = os.environ.get("HVD_COMPAT_LEVEL", "public")
    if level not in ("public", "private", "plain"):
        raise ValueError(
            f"HVD_COMPAT_LEVEL must be public|private|plain, got {level!r}")
    if level == "public":
        fn = getattr(lax, "all_gather_invariant", None)
        if fn is not None:
            return fn
        level = "private"
    forced_private = os.environ.get("HVD_COMPAT_LEVEL") == "private"
    if level == "private":
        try:
            from jax._src.lax.parallel import all_gather_invariant
            return all_gather_invariant
        except ImportError:
            if forced_private:
                # A forced level must not silently degrade to `plain` (the
                # level documented to fail under VMA): fail with the real
                # signal — this jax dropped the private symbol.
                raise ImportError(
                    "HVD_COMPAT_LEVEL=private: this jax has neither a "
                    "public nor a private all_gather_invariant; the "
                    "private-path CI leg no longer applies to it")
    return lax.all_gather


all_gather_invariant = _resolve_all_gather_invariant()


def _resolve_shard_map():
    """``jax.shard_map`` moved to the top level in newer JAX; on older
    versions it lives at ``jax.experimental.shard_map.shard_map``. The
    whole framework (and its test suite) calls the top-level spelling, so
    besides returning the callable we GRAFT it onto the ``jax`` module
    when absent — this module is imported by ``horovod_tpu/__init__``, so
    any code running after ``import horovod_tpu`` sees a working
    ``jax.shard_map`` on every supported jax."""
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn
    import functools

    from jax.experimental.shard_map import shard_map as experimental_fn

    @functools.wraps(experimental_fn)
    def _compat_shard_map(f, *args, **kwargs):
        # New-jax spelling of the check knob maps onto the old one…
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        # …and the framework is written against the newer VMA replication
        # checker (all_gather_invariant etc.); the old experimental
        # checker rejects those specs, so disable it on the graft path —
        # correctness is covered by the VMA leg on current jax.
        kwargs.setdefault("check_rep", False)
        return experimental_fn(f, *args, **kwargs)

    jax.shard_map = _compat_shard_map
    return _compat_shard_map


shard_map = _resolve_shard_map()


def _resolve_axis_size():
    """``lax.axis_size`` (newer jax) — on older versions the same value
    comes from ``jax.core.axis_frame(name)``, which returns the mapped
    axis size as a plain int. Grafted onto ``jax.lax`` when absent, for
    the same reason as the ``shard_map`` graft above."""
    fn = getattr(lax, "axis_size", None)
    if fn is not None:
        return fn
    import jax.core as _core

    def _compat_axis_size(axis_name):
        if isinstance(axis_name, (tuple, list)):
            n = 1
            for a in axis_name:
                n *= _core.axis_frame(a)
            return n
        return _core.axis_frame(axis_name)

    lax.axis_size = _compat_axis_size
    return _compat_axis_size


axis_size = _resolve_axis_size()


def _graft_pallas_compiler_params() -> None:
    """Newer jax renamed ``pltpu.TPUCompilerParams`` →
    ``pltpu.CompilerParams``; the kernels call the new spelling. Graft it
    when absent (same policy as the ``jax.shard_map`` graft above).
    Pallas is optional on exotic builds, so resolution failures just
    leave the kernels' own ``_HAS_PALLAS`` guard to handle it."""
    try:
        from jax.experimental.pallas import tpu as pltpu
    except Exception:  # pragma: no cover — no pallas in this build
        return
    if (not hasattr(pltpu, "CompilerParams")
            and hasattr(pltpu, "TPUCompilerParams")):
        pltpu.CompilerParams = pltpu.TPUCompilerParams


_graft_pallas_compiler_params()


def jax_distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` (newer jax) with a fallback to
    the distributed client's global state on versions that predate the
    public predicate."""
    fn = getattr(jax.distributed, "is_initialized", None)
    if fn is not None:
        return bool(fn())
    try:
        from jax._src.distributed import global_state
        return global_state.client is not None
    except Exception:  # pragma: no cover — very old/unknown layouts
        return False
