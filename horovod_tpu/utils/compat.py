"""JAX version compatibility shims."""

from __future__ import annotations

from jax import lax


def _resolve_all_gather_invariant():
    """``all_gather`` whose output is marked replicated (invariant) over the
    axis, so ``shard_map(..., out_specs=P())`` type-checks under VMA
    analysis. Public in newer JAX; fall back to the private symbol, then to
    plain ``all_gather`` (pre-VMA versions don't need the distinction)."""
    fn = getattr(lax, "all_gather_invariant", None)
    if fn is not None:
        return fn
    try:
        from jax._src.lax.parallel import all_gather_invariant
        return all_gather_invariant
    except ImportError:
        return lax.all_gather


all_gather_invariant = _resolve_all_gather_invariant()
