"""Elastic recovery API — the idiomatic-JAX analog of ``hvd.elastic.run``.

The reference Horovod (v0.11.2) has no recovery story: a dead rank hangs
``MPI_Allreduce`` forever, and Elastic Horovod was built years later to
fix exactly that. This module is the TPU-native counterpart, sized for
the fail-fast world this framework now runs in:

* the coordination plane aborts on worker death
  (:class:`~horovod_tpu.exceptions.WorkerFailureError`, naming the dead
  rank) instead of hanging;
* ``tpurun --restarts N`` relaunches the whole world on a fresh
  coordinator port, exporting ``HVD_RESTART_EPOCH``;
* this module carries the training state across that boundary:
  :class:`ElasticState` commits (params, opt_state, step) through
  :mod:`horovod_tpu.parallel.checkpoint`, and :func:`run_with_recovery`
  restores the last committed state after a restart and resumes.

Commit cadence follows CheckFreq's low-overhead model (Mohan et al.,
FAST '21): commit every ``commit_every`` steps, keep a small retention
window, and on restore agree on the highest step EVERY rank has (ranks
can be one commit apart when the failure lands mid-write).

Usage (the whole loop re-runs after a supervised restart)::

    import horovod_tpu as hvd
    from horovod_tpu import elastic

    hvd.init()
    state = elastic.ElasticState(params, opt_state,
                                 directory="/tmp/elastic", commit_every=1)

    def train(state):
        while state.step < TOTAL_STEPS:
            state.params, state.opt_state = train_step(
                state.params, state.opt_state, batch_for(state.step))
            state.advance()        # step += 1, commit on cadence
        return state.params

    params = elastic.run_with_recovery(train, state)
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import Any, Callable, Optional

from . import runtime
from .exceptions import (CheckpointCorruptError, StalledError,
                         TransportError, WorkerFailureError)
from .obs import flightrec as _flightrec
from .obs.registry import registry as _metrics_registry

RECOVERABLE = (WorkerFailureError, StalledError, TransportError)


def _m(kind: str, name: str, help_: str):
    """Lazy named metric on the process-default registry (commits and
    restores are rare; a registry lookup per event is fine here)."""
    return getattr(_metrics_registry(), kind)(name, help_)


def _log(msg: str) -> None:
    """Operator-facing INFO line (stdout, flushed — the same channel the
    launcher and fault drills use, so chaos-test greps see one stream)."""
    print(f"[elastic] {msg}", flush=True)


def restart_epoch() -> int:
    """Which (re)launch of the world this is (``HVD_RESTART_EPOCH``,
    exported by ``tpurun``; 0 when unset or on the first launch)."""
    from .utils import config as _config
    return _config.restart_epoch()


class ElasticState:
    """Committable training state: params, optimizer state, step counter.

    Parity: ``hvd.elastic.TensorFlowKerasState`` — a mutable bag of
    trainable state with ``commit()``/``restore()``, here built on the
    sharded checkpointer (:mod:`horovod_tpu.parallel.checkpoint`) so the
    same object works for replicated DP *and* hybrid-mesh layouts.

    In a ``tpurun`` env-world every rank is an independent JAX process, so
    each rank commits to its own subdirectory (``<dir>/rank_<r>``); in a
    ``jax.distributed`` world orbax coordinates all processes into one
    directory. ``restore()`` agrees cross-rank on the highest step every
    rank has committed, so a failure mid-write can roll back at most
    ``commit_every`` steps — never diverge.

    ZeRO (rank-sharded) optimizer state composes: ``opt_state`` may carry
    :class:`~horovod_tpu.optimizer.ZeroShardedState` nodes. Commits write
    the canonical world-agnostic form with the same per-shard integrity
    manifest (so the verified fallback walk covers the sharded state
    too), and a single-controller restore RE-SHARDS onto whatever world
    size the restarted run has — an elastic restart that comes back with
    fewer chips resumes from the same bytes. Env-world commits hold only
    this rank's physical shard and therefore restore at the same world
    size only (``docs/checkpointing.md``).
    """

    def __init__(self, params: Any, opt_state: Any = None, step: int = 0,
                 *, directory: Optional[str] = None, commit_every: int = 1,
                 max_to_keep: int = 3, writer: Any = None):
        self.params = params
        self.opt_state = opt_state
        self.step = int(step)
        self.directory = os.path.abspath(
            directory or os.environ.get("HVD_ELASTIC_DIR") or ".hvd_elastic")
        self.commit_every = max(1, int(commit_every))
        self.max_to_keep = max_to_keep
        # Optional horovod_tpu.trainer.AsyncCheckpointer: commits snapshot
        # device→host here and serialize on the writer thread, keeping the
        # two-phase contract — the marker is written by the writer's
        # on_durable hook, strictly after the checkpoint bytes are down.
        self.writer = writer
        # Committed-but-corrupt checkpoints skipped by the verified
        # fallback walk (bit rot / truncation AFTER the two-phase commit
        # finished — the marker proves the write completed, the manifest
        # proves what the bytes said then).
        self.discarded_corrupt = 0
        # Steps THIS rank's walk has proven against their manifests. The
        # cross-rank min in latest_committed can land BELOW this rank's
        # own verified candidate (another rank's commit lagged) — such a
        # step must still be verified at restore time.
        self._verified_steps: set = set()

    # -- layout ------------------------------------------------------------
    def _dir(self) -> str:
        if runtime.is_initialized() and runtime.world().env_world:
            # Independent JAX processes: each rank owns a private copy
            # (orbax would race on a shared path with no jax.distributed
            # world to coordinate the writers).
            return os.path.join(self.directory,
                                f"rank_{runtime.world().process_index}")
        return self.directory

    # -- commit / restore --------------------------------------------------
    # Two-phase commit (CheckFreq discipline): the checkpoint write is NOT
    # the commit — a rank killed mid-write (the supervisor tears siblings
    # down with SIGTERM/SIGKILL) can leave a torn tree that a naive
    # "latest directory" scan would trust. The marker file is written only
    # after a successful save; restore considers marker-bearing steps only.

    def _marker(self, step: int) -> str:
        return os.path.join(self._dir(), f"ckpt_{int(step)}.committed")

    def commit(self) -> str:
        """Commit the current (params, opt_state) at ``step``.

        Synchronous by default (durable on return). With a ``writer``, the
        device→host snapshot happens here and the orbax write + marker +
        retention run on the writer thread — durable after
        ``self.wait()`` — with the write→marker ordering preserved because
        the marker hangs off the writer's on-durable hook."""
        from .parallel import checkpoint as _ckpt
        step = self.step
        _m("counter", "hvd_commits_total",
           "Elastic two-phase commits started").inc()
        _flightrec.record("commit", step=step)
        if self.writer is None:
            path = _ckpt.save_sharded(self._dir(), step, self.params,
                                      self.opt_state,
                                      max_to_keep=self.max_to_keep)
            self._mark_durable(step, path)
            return path
        if (runtime.is_initialized() and runtime.process_count() > 1
                and not runtime.world().env_world):
            # jax.distributed world: params may span non-addressable
            # devices (device_get would raise) and the orbax write is a
            # COLLECTIVE all processes must join — a per-process background
            # thread cannot honor either. Fail with the remedy instead of
            # crashing on the first sharded leaf.
            raise ValueError(
                "ElasticState(writer=...) is supported on single-controller "
                "and tpurun env-world runs only; on a jax.distributed "
                "multi-process world the sharded checkpoint write is a "
                "collective — drop the writer to commit synchronously")
        host_params, host_opt = _ckpt.snapshot_to_host(
            (self.params, self.opt_state), timeline=self.writer.timeline)
        path = _ckpt._ckpt_path(self._dir(), step)
        self.writer.submit(
            lambda: _ckpt.save_sharded(self._dir(), step, host_params,
                                       host_opt,
                                       max_to_keep=self.max_to_keep),
            on_durable=lambda: self._mark_durable(step, path))
        return path

    def _mark_durable(self, step: int, path: str) -> None:
        """Phase 2 of the commit: marker + retention, only ever called
        after the checkpoint bytes for ``step`` are fully written."""
        from .trainer import apply_retention
        with open(self._marker(step), "w") as f:
            f.write(str(step))
            f.flush()
            os.fsync(f.fileno())
        if (runtime.is_initialized() and runtime.world().env_world
                and runtime.world().controller_rank != 0):
            # save_sharded applies retention on rank 0 only (one writer in
            # a shared directory — which is exactly right for the
            # jax.distributed layout); env-world ranks own PRIVATE
            # directories that would otherwise grow without bound, so
            # each non-root applies retention to its own.
            apply_retention(self._dir(), path, self.max_to_keep)
        # Drop markers whose checkpoint directory retention deleted.
        for s in self._marked_steps():
            if not os.path.isdir(os.path.join(self._dir(), f"ckpt_{s}")):
                try:
                    os.unlink(self._marker(s))
                except OSError:
                    pass
        # Deterministic corruption drills (HVD_FAULT_SPEC ckpt:* clauses)
        # fire here — strictly AFTER the two-phase commit finished, which
        # is the scenario the verified fallback exists for: a marker that
        # promises bytes the disk no longer honors.
        from .testing import faults as _faults
        _faults.ckpt_hook(step, os.path.join(self._dir(), f"ckpt_{step}"),
                          self._marker(step))

    def wait(self) -> None:
        """Barrier for async commits: returns once every enqueued commit is
        durable (checkpoint bytes AND marker), re-raising writer errors.
        No-op without a writer."""
        if self.writer is not None:
            self.writer.wait()

    def _marked_steps(self):
        base = self._dir()
        if not os.path.isdir(base):
            return []
        steps = []
        for n in os.listdir(base):
            if n.startswith("ckpt_") and n.endswith(".committed"):
                try:
                    steps.append(int(n[len("ckpt_"):-len(".committed")]))
                except ValueError:
                    continue
        return sorted(steps)

    def _local_latest(self, verify: bool = True) -> Optional[int]:
        """Newest step with a marker, its checkpoint directory, AND (when
        ``verify``) bytes that match the integrity manifest.

        The verified fallback walk: a committed step whose checkpoint
        fails verification — truncated by a dying writer's filesystem,
        bit-flipped on disk, or deliberately corrupted by a
        ``ckpt:*`` fault drill — is logged, counted in
        ``discarded_corrupt``, and SKIPPED, so the newest-checkpoint
        corruption costs one walk iteration instead of the whole run.
        Each verification is a full read of that checkpoint; the walk
        runs once per restore attempt, not per training step.
        """
        from .parallel import checkpoint as _ckpt
        base = self._dir()
        for s in reversed(self._marked_steps()):
            path = os.path.join(base, f"ckpt_{s}")
            if not os.path.isdir(path):
                continue
            if not verify:
                return s
            try:
                _ckpt.verify_checkpoint(path)
            except CheckpointCorruptError as e:
                self.discarded_corrupt += 1
                _m("counter", "hvd_discarded_corrupt_total",
                   "Committed-but-corrupt checkpoints skipped by the "
                   "verified fallback walk").inc()
                _flightrec.record("discard_corrupt", step=s)
                print(f"[elastic] committed step {s} failed integrity "
                      f"verification — discarding and walking back "
                      f"({e})", file=sys.stderr, flush=True)
                continue
            self._verified_steps.add(s)
            return s
        return None

    def advance(self, n: int = 1) -> None:
        """Bump the step counter and commit on the ``commit_every`` cadence
        (call once per completed training step)."""
        self.step += n
        if self.step % self.commit_every == 0:
            self.commit()

    def latest_committed(self) -> Optional[int]:
        """Highest step EVERY rank has committed AND can verify (None =
        no common verified commit).

        A failure can land between one rank's commit and another's, so
        per-rank latests may differ by one commit; the world-wide minimum
        is the only step all ranks can restore together. Only steps whose
        two-phase commit finished (marker present) count — a torn write
        from a rank killed mid-checkpoint is invisible here — and each
        rank additionally verifies its candidate against the integrity
        manifest, walking back past committed-but-corrupt steps
        (:meth:`_local_latest`).
        """
        self.wait()  # async commits in flight count once durable, not before
        mine = self._local_latest()
        if runtime.is_initialized() and runtime.process_count() > 1:
            from .ops.collectives import allgather_object
            steps = allgather_object(mine)
            if any(s is None for s in steps):
                return None
            return min(steps)
        return mine

    def restore(self, step: Optional[int] = None) -> "ElasticState":
        """Restore params/opt_state/step from the last common VERIFIED
        commit (or an explicit ``step``) onto the current trees'
        shardings.

        With ``step=None`` the restore skips re-verification only when
        this rank's fallback walk already proved the chosen step (one
        full read, not two); the cross-rank min can land BELOW this
        rank's verified candidate — another rank's commit lagged — and
        such a step IS verified here before being trusted. An explicit
        ``step`` is always verified and raises
        :class:`~horovod_tpu.exceptions.CheckpointCorruptError` if its
        bytes no longer match the manifest — the caller asked for THAT
        step, so walking back silently would violate the request.
        """
        from .parallel import checkpoint as _ckpt
        self.wait()
        explicit = step is not None
        if step is None:
            step = self.latest_committed()
        if step is None:
            raise FileNotFoundError(
                f"no committed elastic state under {self.directory} "
                f"survived integrity verification"
                if self.discarded_corrupt else
                f"no committed elastic state under {self.directory}")
        self._restore_step(int(step), force_verify=explicit)
        return self

    def _restore_step(self, step: int, force_verify: bool = False) -> None:
        """Restore ``step`` onto the current trees, verifying unless this
        rank's fallback walk already proved that exact step — the one
        place the restore-vs-reverify decision lives (both :meth:`restore`
        and :func:`run_with_recovery` come through here)."""
        from .parallel import checkpoint as _ckpt
        self.params, self.opt_state, self.step = _ckpt.restore_sharded(
            self._dir(), self.params, self.opt_state, step=step,
            verify=force_verify or step not in self._verified_steps)
        _m("counter", "hvd_restores_total",
           "Elastic restores completed (recovery, rollback, resume)"
           ).inc()
        _flightrec.record("restore", step=int(step))


# ---------------------------------------------------------------------------
# Live elastic resize — grow/shrink the world in place, without a restart.
#
# The standard elastic-training shape (Horovod Elastic / TorchElastic)
# rebuilt on this framework's own planes: the resize intent arrives through
# the coordinator's v7 admin plane (operator RPC, or tpurun translating
# SIGUSR1/SIGUSR2 spot-preemption signals) or the deterministic fault
# injector (``resize:*`` drills); ranks learn of it at a STEP BOUNDARY from
# a one-atomic-load poll (the notice rides the heartbeat/ack plane — zero
# extra collectives on the hot path), agree on a quiesce step, finish the
# in-flight step, commit through the existing two-phase ElasticState
# commit, canonicalize ZeRO state host-side
# (:func:`~horovod_tpu.optimizer.zero_to_canonical` — the same
# world-agnostic form the checkpoints use), re-form the world (mesh
# re-init in place; the supervising tpurun spawns/reaps processes in the
# env-world case) and re-shard the optimizer state onto the new world via
# :func:`~horovod_tpu.optimizer.zero_from_canonical` — surviving ranks
# never touch disk for state they already hold; only grow-joined ranks
# receive the canonical bytes (over the wire, from rank 0). Seconds of
# pause + one recompile instead of minutes of full restart.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ResizeRequest:
    """A pending live resize, as observed at a step boundary."""

    target_world: int
    generation: int                   # monotonic resize counter
    coord_port: Optional[int] = None  # env-world: NEW world's coordinator port
    quiesce_step: Optional[int] = None  # agreed world-wide stop step


@dataclasses.dataclass
class Rebuilt:
    """What the caller's ``rebuild(new_world)`` hook returns: fresh
    world-correct TEMPLATES (structure + sharding; values are overwritten
    by the in-place re-shard) plus whatever the training loop needs to
    continue — typically the re-jitted train step."""

    params: Any
    opt_state: Any = None
    train_step: Any = None
    extra: Any = None


_RESIZE_UNSUPPORTED_WORLD = (
    "live resize is supported for tpurun env-worlds and "
    "single-controller worlds; a jax.distributed multi-process "
    "world cannot re-form its global runtime in place — use "
    "tpurun --restarts with the world-agnostic canonical "
    "checkpoint instead")


def _normalize_rebuilt(out) -> Rebuilt:
    if isinstance(out, Rebuilt):
        return out
    if isinstance(out, tuple):
        return Rebuilt(*out)
    return Rebuilt(params=out)


def resize_generation() -> int:
    """How many live resizes this process's world has been through
    (``HVD_RESIZE_GENERATION``; set by tpurun on grow-spawned ranks and by
    the in-place re-form on surviving ranks)."""
    try:
        return int(os.environ.get("HVD_RESIZE_GENERATION", "0") or 0)
    except ValueError:
        return 0


def _is_zero_node(x) -> bool:
    from .optimizer import ZeroShardedState
    return isinstance(x, ZeroShardedState)


def _host_params(params):
    import jax
    import numpy as np
    return jax.tree_util.tree_map(np.asarray, params)


def _env_local_buckets(zs):
    """Map env-world LOCAL-shard leaves (``[1, shard_len]`` — one
    independent process per rank holds only its own row of the stacked
    layout) to their buckets. The env-world analog of
    ``optimizer._zero_shard_leaf_buckets``, which deliberately maps only
    the full stacked layout (the checkpoint flows rely on local-shard
    states canonicalizing as a no-op); the live-resize path is the one
    place local shards must be identified, gathered and re-sliced."""
    import jax
    import numpy as np
    plan = zs.plan
    local = [(1, plan.shard_len(b)) for b in range(len(plan.buckets))]
    nb = len(local)
    out, run = [], 0
    for leaf in jax.tree_util.tree_leaves(zs.inner):
        shape = tuple(np.shape(leaf))
        if nb and shape == local[run]:
            out.append(run)
            run = (run + 1) % nb
        elif nb and shape == local[0]:
            out.append(0)
            run = 1 % nb
        else:
            out.append(None)
            run = 0
    return out


def _zs_is_local(zs) -> bool:
    """Whether a ZeRO node is in the env-world local-shard layout (holds
    only this rank's ``[1, shard_len]`` rows of a ``nshards > 1`` plan)."""
    import jax
    import numpy as np
    if zs.plan.nshards <= 1:
        return False
    for leaf, b in zip(jax.tree_util.tree_leaves(zs.inner),
                       _env_local_buckets(zs)):
        if b is not None and np.shape(leaf)[0] == 1:
            return True
    return False


def _canonicalize_opt(opt_state, *, env_world: bool, generation: int,
                      placeholders: bool = False):
    """Host-side, world-agnostic copy of an optimizer state: ZeRO nodes
    become their canonical (flat, unpadded) form, everything else moves to
    host numpy. In an env-world, each rank holds only its own ``[1, L]``
    physical shard, so canonicalizing first ALL-GATHERS the stacked shards
    over the host plane (retiring ranks contribute their shard before they
    exit — the canonical deltas ride the wire, never the disk).
    ``placeholders=True`` emits canonical-SHAPED zero stand-ins (a
    grow-joiner's side of the state broadcast), sized from the plan alone
    so they work for any physical layout."""
    import jax
    import numpy as np
    if opt_state is None:
        return None
    from .optimizer import ZeroShardedState, zero_to_canonical

    def _gather_env_shards(zs: "ZeroShardedState") -> "ZeroShardedState":
        from .ops import collectives as C
        import jax.numpy as jnp
        ids = _env_local_buckets(zs)
        leaves, treedef = jax.tree_util.tree_flatten(zs.inner)
        out = []
        for i, (leaf, b) in enumerate(zip(leaves, ids)):
            if b is None:
                out.append(np.asarray(leaf))
                continue
            # [1, shard_len] local slice -> [nshards, shard_len] stacked.
            out.append(np.asarray(C.allgather(
                jnp.asarray(leaf), name=f"resize{generation}_zg{i}")))
        return ZeroShardedState(inner=treedef.unflatten(out), plan=zs.plan)

    def _canon_placeholders(zs: "ZeroShardedState") -> "ZeroShardedState":
        # Canonical-shaped stand-ins built from the PLAN alone
        # (zero_to_canonical's placeholders only cover the stacked
        # layout — its bucket mapper deliberately ignores local-shard
        # leaves, which a grow-joiner's env-world template has).
        from .optimizer import _zero_shard_leaf_buckets
        plan = zs.plan
        ids = _env_local_buckets(zs) if _zs_is_local(zs) \
            else _zero_shard_leaf_buckets(zs.inner, plan)
        leaves, treedef = jax.tree_util.tree_flatten(zs.inner)
        canon_sizes = plan.canonical_sizes()
        out = [np.zeros((canon_sizes[b],),
                        np.dtype(plan.dtypes[plan.buckets[b][0]]))
               if b is not None else np.asarray(leaf)
               for leaf, b in zip(leaves, ids)]
        return ZeroShardedState(inner=treedef.unflatten(out), plan=plan)

    def _one(x):
        if isinstance(x, ZeroShardedState):
            if placeholders:
                return _canon_placeholders(x)
            if env_world and _zs_is_local(x):
                x = _gather_env_shards(x)
            canon = zero_to_canonical(x)
            return ZeroShardedState(
                inner=jax.tree_util.tree_map(np.asarray, canon.inner),
                plan=canon.plan)
        return np.asarray(x) if hasattr(x, "dtype") else x

    return jax.tree_util.tree_map(_one, opt_state, is_leaf=_is_zero_node)


def _env_from_canonical(canon, template_zs):
    """Re-shard a canonical (flat, unpadded) ZeRO state onto an env-world
    LOCAL-shard template: pad + re-stack for the template plan's world,
    then keep only this rank's ``[1, shard_len]`` row — each host
    materializes 1/N of the state, never the whole stack."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from .optimizer import ZeroShardedState
    plan = template_zs.plan
    ids = _env_local_buckets(template_zs)
    t_leaves, treedef = jax.tree_util.tree_flatten(template_zs.inner)
    c_leaves = jax.tree_util.tree_leaves(canon)
    if len(c_leaves) != len(t_leaves):
        raise ValueError(
            f"ZeRO state mismatch: canonical state has {len(c_leaves)} "
            f"optimizer-state leaves, this world's template has "
            f"{len(t_leaves)} — was the state written by a different "
            f"optimizer?")
    r = runtime.world().controller_rank if runtime.is_initialized() else 0
    canon_sizes = plan.canonical_sizes()
    out = []
    for c, t, b in zip(c_leaves, t_leaves, ids):
        if b is None:
            out.append(jnp.asarray(c))
            continue
        flat = np.asarray(c).reshape(-1)
        if flat.size != canon_sizes[b]:
            raise ValueError(
                f"ZeRO shard length mismatch: canonical leaf has "
                f"{flat.size} elements, this world's bucket {b} expects "
                f"{canon_sizes[b]} — the fusion bucket plan differs "
                f"(HOROVOD_FUSION_THRESHOLD must match and the model must "
                f"be unchanged across the resize)")
        pad = plan.padded[b] - plan.sizes[b]
        if pad:
            flat = np.concatenate([flat, np.zeros((pad,), flat.dtype)])
        row = flat.reshape(plan.nshards, plan.shard_len(b))[r:r + 1]
        out.append(jnp.asarray(row))
    return ZeroShardedState(inner=treedef.unflatten(out), plan=plan)


def _place_params(host_params, template):
    """Host values onto the template's shardings (the new world's layout)."""
    import jax
    import jax.numpy as jnp

    def _one(t, h):
        if isinstance(t, jax.Array):
            return jax.device_put(h, t.sharding)
        return jnp.asarray(h)

    return jax.tree_util.tree_map(_one, template, host_params)


def _reshard_opt(host_opt, template_opt):
    """Re-shard the canonical host optimizer state onto the new world's
    templates: ZeRO nodes via :func:`zero_from_canonical` (which pads,
    re-stacks and places per the template plan — including the env-world
    own-row slice), plain leaves via device placement."""
    import jax
    import jax.numpy as jnp
    if template_opt is None:
        return None
    from .optimizer import ZeroShardedState, zero_from_canonical

    def _one(t, h):
        if isinstance(t, ZeroShardedState):
            canon = h.inner if isinstance(h, ZeroShardedState) else h
            if _zs_is_local(t):
                return _env_from_canonical(canon, t)
            return zero_from_canonical(canon, t)
        if isinstance(t, jax.Array):
            return jax.device_put(h, t.sharding)
        return jnp.asarray(h) if hasattr(t, "dtype") else h

    return jax.tree_util.tree_map(_one, template_opt, host_opt,
                                  is_leaf=_is_zero_node)


def _sync_state_over_plane(step: int, host_params, host_opt,
                           generation: int):
    """Broadcast (step, params, canonical opt) from new-world rank 0 over
    the host coordination plane — how grow-joined ranks receive the
    in-flight training state without any rank touching disk. Every rank of
    the NEW world participates (broadcast semantics); survivors already
    hold the bytes, joiners present canonical-shaped placeholders.
    Returns the synced (step, host_params, host_opt)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from .ops import collectives as C

    header = C.broadcast_object(
        {"step": int(step)}, root_rank=0,
        name=f"resize{generation}_hdr")
    leaves, treedef = jax.tree_util.tree_flatten(host_params)
    synced = [np.asarray(C.broadcast(jnp.asarray(l), root_rank=0,
                                     name=f"resize{generation}_p{i}"))
              for i, l in enumerate(leaves)]
    host_params = treedef.unflatten(synced)
    if host_opt is not None:
        o_leaves, o_treedef = jax.tree_util.tree_flatten(host_opt)
        o_synced = [np.asarray(C.broadcast(jnp.asarray(l), root_rank=0,
                                           name=f"resize{generation}_o{i}"))
                    for i, l in enumerate(o_leaves)]
        host_opt = o_treedef.unflatten(o_synced)
    return int(header["step"]), host_params, host_opt


class ResizeCoordinator:
    """Step-boundary ingress + quiesce protocol of the live-resize plane.

    Usage (the elastic while-loop; ``Trainer(resize=...)`` wires the same
    calls into its fit loop)::

        rc = elastic.ResizeCoordinator(state, rebuild=rebuild)
        while state.step < TOTAL:
            ...train one step...
            state.advance()
            rebuilt = rc.step_boundary(state.step)
            if rebuilt is not None:          # world was just resized
                train_step = rebuilt.train_step or train_step

    ``rebuild(new_world)`` runs AFTER the world re-forms and must return
    fresh world-correct templates (:class:`Rebuilt`, or a
    ``(params, opt_state[, train_step[, extra]])`` tuple) — e.g. re-run
    ``create_train_state`` / ``partition_optimizer`` init and
    ``make_train_step`` at the new size. Values are then overwritten in
    place from the quiesced state; only the layout comes from the rebuild.
    With ``rebuild=None`` the existing host values are re-materialized
    as-is (enough for replicated env-world states; ZeRO states REQUIRE a
    rebuild — their physical layout is world-shaped).

    The poll is one atomic load; the quiesce-step agreement (one tiny MAX
    allreduce over the host plane, async-submitted so it never blocks a
    rank that observed the notice earlier than its peers) runs only once a
    resize is actually pending — the training hot path pays nothing.
    """

    def __init__(self, state: ElasticState, *,
                 rebuild: Optional[Callable[[int], Any]] = None,
                 devices_fn: Optional[Callable[[int], Any]] = None):
        self.state = state
        self.rebuild = rebuild
        # Single-controller device picker for the new world (defaults to
        # the first ``target`` of jax.devices()); hybrid-mesh callers pick
        # their own device grid inside ``rebuild`` instead.
        self.devices_fn = devices_fn
        self._pending: Optional[ResizeRequest] = None
        self._proposal = None        # in-flight quiesce-agreement handle
        self._proposed_at: Optional[int] = None
        self._local_request: Optional[int] = None
        # A fault-drill target whose admin RPC failed transiently: the
        # fault clause fires once, so the RETRY must be carried here or
        # the drill would be silently dropped.
        self._drill_retry: Optional[int] = None
        self.resizes_completed = 0

    # -- programmatic ingress (tests, notebooks, schedulers) ---------------
    def request(self, target_world: int) -> None:
        """Request a live resize from inside the job: env-worlds route it
        through the coordinator's admin RPC (the same path an operator's
        ``request_resize`` takes), single-controller worlds record it
        locally and quiesce at the next step boundary."""
        if target_world < 1:
            raise ValueError(
                f"resize target must be >= 1 rank, got {target_world}")
        w = runtime.world()
        if w.env_world and w.coord is not None:
            from .coord.client import request_resize
            from .utils import config as _config
            request_resize(_config.coordinator_address(), target_world)
            return
        if w.process_count > 1:
            raise ValueError(_RESIZE_UNSUPPORTED_WORLD)
        if target_world == w.size:
            _log(f"resize request ignored: world is already size "
                 f"{w.size}")
            return
        self._local_request = int(target_world)

    # -- step-boundary protocol --------------------------------------------
    def _observe(self, step: int) -> Optional[ResizeRequest]:
        from .testing import faults as _faults
        w = runtime.world() if runtime.is_initialized() else None
        world_size = w.size if w is not None else 1
        target = _faults.resize_hook(step, world_size)
        if target is None:
            # The fault clause fires exactly once; a drill whose RPC
            # failed transiently retries from here.
            target, self._drill_retry = self._drill_retry, None
        if target is not None and w is not None and w.env_world \
                and w.coord is not None:
            # Env-world drill: route through the REAL admin ingress so the
            # whole plane (RPC -> notice -> ack piggyback) is exercised;
            # rank 0 self-requests, everyone learns via the notice.
            if w.process_index == 0:
                from .coord.client import request_resize
                from .utils import config as _config
                try:
                    request_resize(_config.coordinator_address(), target)
                except Exception as e:  # noqa: BLE001 — drill ingress
                    if "refused resize" in str(e):
                        # Definitive rejection (bad target, conflicting
                        # pending): retrying cannot change the answer.
                        _log(f"resize drill rejected by the coordinator "
                             f"({e}); dropping the drill")
                    else:
                        self._drill_retry = target
                        _log(f"resize drill RPC failed ({e}); retrying "
                             f"at the next step boundary")
            target = None  # wait for the coordinator's notice like everyone
        if target is None and self._local_request is not None:
            target = self._local_request
            self._local_request = None
        if target is not None:
            return ResizeRequest(target_world=int(target),
                                 generation=resize_generation() + 1)
        if w is not None and w.coord is not None:
            pr = w.coord.pending_resize()
            if pr is not None:
                return ResizeRequest(target_world=pr.target_world,
                                     coord_port=pr.coord_port or None,
                                     generation=pr.generation)
        return None

    def poll(self, step: int) -> Optional[ResizeRequest]:
        """Cheap step-boundary check. Returns the pending request once one
        is known (its ``quiesce_step`` fills in after the world-wide
        agreement completes); None on the hot path."""
        if self._pending is None:
            req = self._observe(step)
            if req is None:
                return None
            self._pending = req
            w = runtime.world() if runtime.is_initialized() else None
            multi = w is not None and w.coord is not None \
                and w.process_count > 1
            _log(f"resize pending: world "
                 f"{w.size if w else 1} -> {req.target_world} "
                 f"(generation {req.generation}); quiescing at a step "
                 f"boundary")
            if multi:
                # Ranks can observe the notice a step apart; agree on the
                # world-wide quiesce step with one tiny MAX allreduce.
                # Async submit: a rank must NOT block here while a peer
                # may still be inside this step's training collectives —
                # it redeems at its NEXT boundary, by when every peer has
                # observed the notice and submitted its own proposal.
                import numpy as np
                from .ops.collectives import Op
                self._proposal = w.coord.submit(
                    "allreduce", np.asarray([step + 1], np.int64),
                    f"resize{req.generation}_quiesce", op=Op.MAX)
                self._proposed_at = step
            else:
                self._pending = dataclasses.replace(
                    self._pending, quiesce_step=step)
        if (self._pending.quiesce_step is None
                and self._proposal is not None
                and step > self._proposed_at):
            import numpy as np
            w = runtime.world()
            agreed = int(np.asarray(w.coord.wait(self._proposal))[0])
            self._proposal = None
            self._pending = dataclasses.replace(
                self._pending, quiesce_step=max(agreed, step))
            _log(f"resize: world agreed to quiesce at step "
                 f"{self._pending.quiesce_step}")
        return self._pending

    def due(self, step: int) -> bool:
        return (self._pending is not None
                and self._pending.quiesce_step is not None
                and step >= self._pending.quiesce_step)

    def step_boundary(self, step: int, *, params=None,
                      opt_state=None) -> Optional[Rebuilt]:
        """The trainer-loop quiesce hook: call once per completed step with
        the current step count (and, when the loop owns the live trees —
        ``Trainer.fit`` does — the current params/opt_state to sync into
        the elastic state). Returns the :class:`Rebuilt` templates when a
        resize just executed, else None."""
        req = self.poll(step)
        if req is None or not self.due(step):
            return None
        if params is not None:
            self.state.params = params
        if opt_state is not None:
            self.state.opt_state = opt_state
        self.state.step = int(step)
        return self.execute(self._pending)

    # -- the quiesce protocol ----------------------------------------------
    def execute(self, req: ResizeRequest) -> Rebuilt:
        """Quiesce → recommit → canonicalize → re-form → re-shard.

        Called at the agreed step boundary on every rank of the OLD world.
        Retiring env-world ranks (rank >= target) contribute their ZeRO
        shards to the canonical form, then exit cleanly (SystemExit(0) —
        the supervising tpurun reaps them as benign). Surviving ranks
        re-form the coordination plane on the new port / re-init the local
        mesh and re-shard in place. Any failure after the recommit falls
        back to the full VERIFIED restore walk — the recommit is the
        correctness anchor."""
        import jax
        state = self.state
        w = runtime.world()
        old_world, env = w.size, w.env_world
        if w.process_count > 1 and not env:
            raise ValueError(_RESIZE_UNSUPPORTED_WORLD)
        target, gen = req.target_world, req.generation
        my_rank = w.process_index
        new_devs = None
        if not env:
            # Validate the new device set BEFORE tearing the old world
            # down: an oversized grow target (typo'd request) must reject
            # here, not kill a running job after shutdown.
            new_devs = list(self.devices_fn(target) if self.devices_fn
                            else jax.devices()[:target])
            if len(new_devs) < target:
                self._pending = None  # raise once, not at every boundary
                self._proposal = None
                raise ValueError(
                    f"cannot grow to world {target}: only {len(new_devs)} "
                    f"devices available (single-controller resize is "
                    f"bounded by the visible device count)")
        _log(f"resize: quiesced at step {state.step}; recommitting and "
             f"canonicalizing before re-forming the world "
             f"({old_world} -> {target}, generation {gen})")
        _flightrec.record("resize_quiesce", step=int(state.step),
                          old_world=old_world, target=target,
                          generation=gen)
        # Recommit at the quiesce step through the unchanged two-phase
        # commit (drains any async writer first): the verified-restore
        # anchor if anything below fails, and the resume point if a rank
        # dies mid-resize and the supervisor falls back to a full restart.
        state.wait()
        state.commit()
        state.wait()
        # Host-side canonical copies (ZeRO shards allgathered over the old
        # plane in env-worlds — retiring ranks included).
        host_params = _host_params(state.params)
        host_opt = _canonicalize_opt(state.opt_state, env_world=env,
                                     generation=gen)
        host_step = int(state.step)
        coord_host = ""
        if env:
            from .utils import config as _config
            addr = _config.coordinator_address() or "127.0.0.1"
            coord_host = addr.partition(":")[0] or "127.0.0.1"
        # Old world down. From here until re-init there is no plane; the
        # recommit above is the safety net.
        runtime.shutdown()
        if env and my_rank >= target:
            _log(f"resize: rank {my_rank} retiring at step {host_step} "
                 f"(world {old_world} -> {target}, generation {gen})")
            sys.stdout.flush()
            raise SystemExit(0)
        try:
            if env:
                if not req.coord_port:
                    raise ValueError(
                        "env-world resize request carries no coordinator "
                        "port for the new world (notice missing?)")
                os.environ["HVD_SIZE"] = str(target)
                os.environ["HVD_COORD_ADDR"] = \
                    f"{coord_host}:{req.coord_port}"
                os.environ["HVD_RESIZE_GENERATION"] = str(gen)
                runtime.init()
            else:
                runtime.init(devices=new_devs)
            rebuilt = self._rebuild_templates(target, host_params,
                                              host_opt)
            if env and target > old_world:
                # Grow: ship (step, params, canonical opt) to the joined
                # ranks over the new plane — no disk involved. Shrink
                # needs no sync: every survivor already holds the full
                # canonical state.
                host_step, host_params, host_opt = _sync_state_over_plane(
                    host_step, host_params, host_opt, gen)
            state.params = _place_params(host_params, rebuilt.params)
            state.opt_state = _reshard_opt(host_opt, rebuilt.opt_state)
            state.step = host_step
            self._pending = None
            self._proposal = None
            self.resizes_completed += 1
            _m("counter", "hvd_resizes_total",
               "Live elastic resizes completed").inc()
            _flightrec.record("resize_complete", step=int(state.step),
                              world=target, generation=gen)
            _log(f"resize complete: re-sharded optimizer state in place "
                 f"onto world {target} (generation {gen}); resuming at "
                 f"step {state.step} without restart")
            return rebuilt
        except RECOVERABLE:
            # The plane died under the resize (e.g. a racing kill): local
            # recovery is impossible — surface to run_with_recovery so the
            # supervisor restarts the world and the VERIFIED restore walk
            # resumes from the recommit.
            raise
        except SystemExit:
            raise
        except Exception as e:  # noqa: BLE001 — fallback is the contract
            _flightrec.record("resize_fallback", target=target,
                              error=repr(e))
            _log(f"resize: in-place re-shard failed ({e!r}); falling back "
                 f"to full verified restore of the quiesce commit")
            if not runtime.is_initialized():
                raise
            if runtime.world().process_count > 1:
                # Multi-process world: the local restore's cross-rank
                # agreement would be a collective the OTHER ranks (which
                # may have resized successfully and returned to training)
                # never join — an asymmetric failure would hang the world
                # instead of recovering it. Exit to the supervisor: the
                # whole world relaunches and resumes from the quiesce
                # recommit via the verified walk.
                _log("resize: fallback on a multi-process world exits for "
                     "a supervised restart (a rank-local restore would "
                     "desynchronize the plane)")
                raise
            rebuilt = self._rebuild_templates(target, host_params,
                                              host_opt)
            state.params = rebuilt.params
            state.opt_state = rebuilt.opt_state
            state.restore()   # verified walk; raises if even that fails
            self._pending = None
            self._proposal = None
            self.resizes_completed += 1
            _m("counter", "hvd_resizes_total",
               "Live elastic resizes completed").inc()
            _log(f"resize complete (via verified restore fallback): "
                 f"world {target}, resuming at step {state.step}")
            return rebuilt

    def _rebuild_templates(self, target: int, host_params,
                           host_opt) -> Rebuilt:
        if self.rebuild is not None:
            return _normalize_rebuilt(self.rebuild(target))
        if host_opt is not None and any(
                _is_zero_node(x) for x in _tree_nodes(host_opt)):
            raise ValueError(
                "resizing a ZeRO-sharded optimizer state requires "
                "ResizeCoordinator(rebuild=...): the sharded layout is "
                "world-shaped, so the new world's templates must be "
                "rebuilt (re-run the optimizer init / create_train_state "
                "at the new size)")
        return Rebuilt(params=host_params, opt_state=host_opt)


def _tree_nodes(tree):
    import jax
    return jax.tree_util.tree_leaves(
        tree, is_leaf=_is_zero_node)


def resize_join(state: ElasticState) -> ElasticState:
    """Join an in-flight world as a grow-spawned rank (tpurun sets
    ``HVD_RESIZE_GENERATION`` on ranks it adds mid-run). The joiner's own
    freshly-initialized trees are already world-correct templates; the
    live (step, params, canonical opt) arrives over the coordination plane
    from rank 0 — no rank reads disk. Called automatically by
    :func:`run_with_recovery`."""
    gen = resize_generation()
    w = runtime.world()
    _log(f"resize: rank {w.process_index} joining world {w.size} at "
         f"generation {gen}; receiving live state over the plane")
    host_opt = _canonicalize_opt(state.opt_state, env_world=False,
                                 generation=gen, placeholders=True)
    step, host_params, host_opt = _sync_state_over_plane(
        0, _host_params(state.params), host_opt, gen)
    state.params = _place_params(host_params, state.params)
    state.opt_state = _reshard_opt(host_opt, state.opt_state)
    state.step = step
    # Commit immediately: until this rank has its own committed state, a
    # full-world crash-restart would find its directory empty and drag the
    # cross-rank restore agreement back to step 0.
    state.commit()
    state.wait()
    _log(f"resize: joined at step {step} and committed")
    return state


def run_with_recovery(train_fn: Callable[[ElasticState], Any],
                      state: ElasticState):
    """Run ``train_fn(state)`` with checkpoint-recovery semantics.

    The analog of ``hvd.elastic.run``: before running, if a committed
    state exists (always true after a supervised restart that got past
    the first commit), restore it so ``train_fn`` resumes from the last
    committed step rather than step 0. If the world dies underneath the
    loop — :class:`WorkerFailureError` (a rank died / went silent),
    :class:`StalledError`, or :class:`TransportError` — tear the local
    world down cleanly and re-raise, so the process exits nonzero and
    ``tpurun --restarts N`` relaunches everything; the relaunched world
    lands back here and resumes.

    Returns whatever ``train_fn`` returns on success.
    """
    joining = (resize_generation() > 0 and runtime.is_initialized()
               and runtime.world().env_world
               and state._local_latest(verify=False) is None)
    if joining:
        # A grow-spawned rank joining an in-flight resize (tpurun set
        # HVD_RESIZE_GENERATION and this rank has never committed): the
        # live state arrives over the plane, not from disk — the
        # surviving ranks are mid-resize waiting in the same broadcast.
        resize_join(state)
        committed = None
    else:
        committed = state.latest_committed()  # one cross-rank agreement
    if committed is not None:
        # _restore_step skips the second verify pass only when THIS
        # rank's walk proved the agreed step; the cross-rank min can be
        # a step this rank never verified (its own candidate was newer),
        # and a corrupt local copy of it must raise, not restore.
        state._restore_step(int(committed))
        if state.discarded_corrupt:
            print(f"[elastic] discarded {state.discarded_corrupt} "
                  f"committed-but-corrupt checkpoint(s); resuming from "
                  f"verified step {state.step}", flush=True)
        # Operators must be able to tell a clean resume from a fallback
        # walk WITHOUT DEBUG: the restore-walk outcome is logged on every
        # recovery, not only when verification re-ran.
        _log(f"recovery: resumed from committed step {state.step} "
             f"(restore walk: discarded_corrupt={state.discarded_corrupt}"
             f", {'fallback walk engaged' if state.discarded_corrupt else 'clean latest commit'})")
        if restart_epoch() > 0:
            print(f"[elastic] restart epoch {restart_epoch()}: resumed "
                  f"from committed step {state.step}", flush=True)
    elif not joining:
        _log("recovery: no committed state found — starting from "
             "scratch (restore walk: nothing to restore)")
    try:
        return train_fn(state)
    except RECOVERABLE as e:
        sys.stderr.write(
            f"[elastic] world failure at step {state.step}: {e}\n"
            f"[elastic] exiting for supervised restart (run under "
            f"tpurun --restarts N to resume from the last committed "
            f"step)\n")
        _flightrec.record("world_failure", step=int(state.step),
                          error=repr(e))
        # Crash-safe teardown (shutdown tolerates a dead coordinator) so
        # the relaunched world starts from a clean slate; error= dumps
        # the flight recorder FIRST — this rank's post-mortem record,
        # naming its last completed step (obs.flightrec).
        runtime.shutdown(error=e)
        raise
