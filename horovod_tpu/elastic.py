"""Elastic recovery API — the idiomatic-JAX analog of ``hvd.elastic.run``.

The reference Horovod (v0.11.2) has no recovery story: a dead rank hangs
``MPI_Allreduce`` forever, and Elastic Horovod was built years later to
fix exactly that. This module is the TPU-native counterpart, sized for
the fail-fast world this framework now runs in:

* the coordination plane aborts on worker death
  (:class:`~horovod_tpu.exceptions.WorkerFailureError`, naming the dead
  rank) instead of hanging;
* ``tpurun --restarts N`` relaunches the whole world on a fresh
  coordinator port, exporting ``HVD_RESTART_EPOCH``;
* this module carries the training state across that boundary:
  :class:`ElasticState` commits (params, opt_state, step) through
  :mod:`horovod_tpu.parallel.checkpoint`, and :func:`run_with_recovery`
  restores the last committed state after a restart and resumes.

Commit cadence follows CheckFreq's low-overhead model (Mohan et al.,
FAST '21): commit every ``commit_every`` steps, keep a small retention
window, and on restore agree on the highest step EVERY rank has (ranks
can be one commit apart when the failure lands mid-write).

Usage (the whole loop re-runs after a supervised restart)::

    import horovod_tpu as hvd
    from horovod_tpu import elastic

    hvd.init()
    state = elastic.ElasticState(params, opt_state,
                                 directory="/tmp/elastic", commit_every=1)

    def train(state):
        while state.step < TOTAL_STEPS:
            state.params, state.opt_state = train_step(
                state.params, state.opt_state, batch_for(state.step))
            state.advance()        # step += 1, commit on cadence
        return state.params

    params = elastic.run_with_recovery(train, state)
"""

from __future__ import annotations

import os
import sys
from typing import Any, Callable, Optional

from . import runtime
from .exceptions import (CheckpointCorruptError, StalledError,
                         TransportError, WorkerFailureError)

RECOVERABLE = (WorkerFailureError, StalledError, TransportError)


def restart_epoch() -> int:
    """Which (re)launch of the world this is (``HVD_RESTART_EPOCH``,
    exported by ``tpurun``; 0 when unset or on the first launch)."""
    from .utils import config as _config
    return _config.restart_epoch()


class ElasticState:
    """Committable training state: params, optimizer state, step counter.

    Parity: ``hvd.elastic.TensorFlowKerasState`` — a mutable bag of
    trainable state with ``commit()``/``restore()``, here built on the
    sharded checkpointer (:mod:`horovod_tpu.parallel.checkpoint`) so the
    same object works for replicated DP *and* hybrid-mesh layouts.

    In a ``tpurun`` env-world every rank is an independent JAX process, so
    each rank commits to its own subdirectory (``<dir>/rank_<r>``); in a
    ``jax.distributed`` world orbax coordinates all processes into one
    directory. ``restore()`` agrees cross-rank on the highest step every
    rank has committed, so a failure mid-write can roll back at most
    ``commit_every`` steps — never diverge.

    ZeRO (rank-sharded) optimizer state composes: ``opt_state`` may carry
    :class:`~horovod_tpu.optimizer.ZeroShardedState` nodes. Commits write
    the canonical world-agnostic form with the same per-shard integrity
    manifest (so the verified fallback walk covers the sharded state
    too), and a single-controller restore RE-SHARDS onto whatever world
    size the restarted run has — an elastic restart that comes back with
    fewer chips resumes from the same bytes. Env-world commits hold only
    this rank's physical shard and therefore restore at the same world
    size only (``docs/checkpointing.md``).
    """

    def __init__(self, params: Any, opt_state: Any = None, step: int = 0,
                 *, directory: Optional[str] = None, commit_every: int = 1,
                 max_to_keep: int = 3, writer: Any = None):
        self.params = params
        self.opt_state = opt_state
        self.step = int(step)
        self.directory = os.path.abspath(
            directory or os.environ.get("HVD_ELASTIC_DIR") or ".hvd_elastic")
        self.commit_every = max(1, int(commit_every))
        self.max_to_keep = max_to_keep
        # Optional horovod_tpu.trainer.AsyncCheckpointer: commits snapshot
        # device→host here and serialize on the writer thread, keeping the
        # two-phase contract — the marker is written by the writer's
        # on_durable hook, strictly after the checkpoint bytes are down.
        self.writer = writer
        # Committed-but-corrupt checkpoints skipped by the verified
        # fallback walk (bit rot / truncation AFTER the two-phase commit
        # finished — the marker proves the write completed, the manifest
        # proves what the bytes said then).
        self.discarded_corrupt = 0
        # Steps THIS rank's walk has proven against their manifests. The
        # cross-rank min in latest_committed can land BELOW this rank's
        # own verified candidate (another rank's commit lagged) — such a
        # step must still be verified at restore time.
        self._verified_steps: set = set()

    # -- layout ------------------------------------------------------------
    def _dir(self) -> str:
        if runtime.is_initialized() and runtime.world().env_world:
            # Independent JAX processes: each rank owns a private copy
            # (orbax would race on a shared path with no jax.distributed
            # world to coordinate the writers).
            return os.path.join(self.directory,
                                f"rank_{runtime.world().process_index}")
        return self.directory

    # -- commit / restore --------------------------------------------------
    # Two-phase commit (CheckFreq discipline): the checkpoint write is NOT
    # the commit — a rank killed mid-write (the supervisor tears siblings
    # down with SIGTERM/SIGKILL) can leave a torn tree that a naive
    # "latest directory" scan would trust. The marker file is written only
    # after a successful save; restore considers marker-bearing steps only.

    def _marker(self, step: int) -> str:
        return os.path.join(self._dir(), f"ckpt_{int(step)}.committed")

    def commit(self) -> str:
        """Commit the current (params, opt_state) at ``step``.

        Synchronous by default (durable on return). With a ``writer``, the
        device→host snapshot happens here and the orbax write + marker +
        retention run on the writer thread — durable after
        ``self.wait()`` — with the write→marker ordering preserved because
        the marker hangs off the writer's on-durable hook."""
        from .parallel import checkpoint as _ckpt
        step = self.step
        if self.writer is None:
            path = _ckpt.save_sharded(self._dir(), step, self.params,
                                      self.opt_state,
                                      max_to_keep=self.max_to_keep)
            self._mark_durable(step, path)
            return path
        if (runtime.is_initialized() and runtime.process_count() > 1
                and not runtime.world().env_world):
            # jax.distributed world: params may span non-addressable
            # devices (device_get would raise) and the orbax write is a
            # COLLECTIVE all processes must join — a per-process background
            # thread cannot honor either. Fail with the remedy instead of
            # crashing on the first sharded leaf.
            raise ValueError(
                "ElasticState(writer=...) is supported on single-controller "
                "and tpurun env-world runs only; on a jax.distributed "
                "multi-process world the sharded checkpoint write is a "
                "collective — drop the writer to commit synchronously")
        host_params, host_opt = _ckpt.snapshot_to_host(
            (self.params, self.opt_state), timeline=self.writer.timeline)
        path = _ckpt._ckpt_path(self._dir(), step)
        self.writer.submit(
            lambda: _ckpt.save_sharded(self._dir(), step, host_params,
                                       host_opt,
                                       max_to_keep=self.max_to_keep),
            on_durable=lambda: self._mark_durable(step, path))
        return path

    def _mark_durable(self, step: int, path: str) -> None:
        """Phase 2 of the commit: marker + retention, only ever called
        after the checkpoint bytes for ``step`` are fully written."""
        from .trainer import apply_retention
        with open(self._marker(step), "w") as f:
            f.write(str(step))
            f.flush()
            os.fsync(f.fileno())
        if (runtime.is_initialized() and runtime.world().env_world
                and runtime.world().controller_rank != 0):
            # save_sharded applies retention on rank 0 only (one writer in
            # a shared directory — which is exactly right for the
            # jax.distributed layout); env-world ranks own PRIVATE
            # directories that would otherwise grow without bound, so
            # each non-root applies retention to its own.
            apply_retention(self._dir(), path, self.max_to_keep)
        # Drop markers whose checkpoint directory retention deleted.
        for s in self._marked_steps():
            if not os.path.isdir(os.path.join(self._dir(), f"ckpt_{s}")):
                try:
                    os.unlink(self._marker(s))
                except OSError:
                    pass
        # Deterministic corruption drills (HVD_FAULT_SPEC ckpt:* clauses)
        # fire here — strictly AFTER the two-phase commit finished, which
        # is the scenario the verified fallback exists for: a marker that
        # promises bytes the disk no longer honors.
        from .testing import faults as _faults
        _faults.ckpt_hook(step, os.path.join(self._dir(), f"ckpt_{step}"),
                          self._marker(step))

    def wait(self) -> None:
        """Barrier for async commits: returns once every enqueued commit is
        durable (checkpoint bytes AND marker), re-raising writer errors.
        No-op without a writer."""
        if self.writer is not None:
            self.writer.wait()

    def _marked_steps(self):
        base = self._dir()
        if not os.path.isdir(base):
            return []
        steps = []
        for n in os.listdir(base):
            if n.startswith("ckpt_") and n.endswith(".committed"):
                try:
                    steps.append(int(n[len("ckpt_"):-len(".committed")]))
                except ValueError:
                    continue
        return sorted(steps)

    def _local_latest(self, verify: bool = True) -> Optional[int]:
        """Newest step with a marker, its checkpoint directory, AND (when
        ``verify``) bytes that match the integrity manifest.

        The verified fallback walk: a committed step whose checkpoint
        fails verification — truncated by a dying writer's filesystem,
        bit-flipped on disk, or deliberately corrupted by a
        ``ckpt:*`` fault drill — is logged, counted in
        ``discarded_corrupt``, and SKIPPED, so the newest-checkpoint
        corruption costs one walk iteration instead of the whole run.
        Each verification is a full read of that checkpoint; the walk
        runs once per restore attempt, not per training step.
        """
        from .parallel import checkpoint as _ckpt
        base = self._dir()
        for s in reversed(self._marked_steps()):
            path = os.path.join(base, f"ckpt_{s}")
            if not os.path.isdir(path):
                continue
            if not verify:
                return s
            try:
                _ckpt.verify_checkpoint(path)
            except CheckpointCorruptError as e:
                self.discarded_corrupt += 1
                print(f"[elastic] committed step {s} failed integrity "
                      f"verification — discarding and walking back "
                      f"({e})", file=sys.stderr, flush=True)
                continue
            self._verified_steps.add(s)
            return s
        return None

    def advance(self, n: int = 1) -> None:
        """Bump the step counter and commit on the ``commit_every`` cadence
        (call once per completed training step)."""
        self.step += n
        if self.step % self.commit_every == 0:
            self.commit()

    def latest_committed(self) -> Optional[int]:
        """Highest step EVERY rank has committed AND can verify (None =
        no common verified commit).

        A failure can land between one rank's commit and another's, so
        per-rank latests may differ by one commit; the world-wide minimum
        is the only step all ranks can restore together. Only steps whose
        two-phase commit finished (marker present) count — a torn write
        from a rank killed mid-checkpoint is invisible here — and each
        rank additionally verifies its candidate against the integrity
        manifest, walking back past committed-but-corrupt steps
        (:meth:`_local_latest`).
        """
        self.wait()  # async commits in flight count once durable, not before
        mine = self._local_latest()
        if runtime.is_initialized() and runtime.process_count() > 1:
            from .ops.collectives import allgather_object
            steps = allgather_object(mine)
            if any(s is None for s in steps):
                return None
            return min(steps)
        return mine

    def restore(self, step: Optional[int] = None) -> "ElasticState":
        """Restore params/opt_state/step from the last common VERIFIED
        commit (or an explicit ``step``) onto the current trees'
        shardings.

        With ``step=None`` the restore skips re-verification only when
        this rank's fallback walk already proved the chosen step (one
        full read, not two); the cross-rank min can land BELOW this
        rank's verified candidate — another rank's commit lagged — and
        such a step IS verified here before being trusted. An explicit
        ``step`` is always verified and raises
        :class:`~horovod_tpu.exceptions.CheckpointCorruptError` if its
        bytes no longer match the manifest — the caller asked for THAT
        step, so walking back silently would violate the request.
        """
        from .parallel import checkpoint as _ckpt
        self.wait()
        explicit = step is not None
        if step is None:
            step = self.latest_committed()
        if step is None:
            raise FileNotFoundError(
                f"no committed elastic state under {self.directory} "
                f"survived integrity verification"
                if self.discarded_corrupt else
                f"no committed elastic state under {self.directory}")
        self._restore_step(int(step), force_verify=explicit)
        return self

    def _restore_step(self, step: int, force_verify: bool = False) -> None:
        """Restore ``step`` onto the current trees, verifying unless this
        rank's fallback walk already proved that exact step — the one
        place the restore-vs-reverify decision lives (both :meth:`restore`
        and :func:`run_with_recovery` come through here)."""
        from .parallel import checkpoint as _ckpt
        self.params, self.opt_state, self.step = _ckpt.restore_sharded(
            self._dir(), self.params, self.opt_state, step=step,
            verify=force_verify or step not in self._verified_steps)


def run_with_recovery(train_fn: Callable[[ElasticState], Any],
                      state: ElasticState):
    """Run ``train_fn(state)`` with checkpoint-recovery semantics.

    The analog of ``hvd.elastic.run``: before running, if a committed
    state exists (always true after a supervised restart that got past
    the first commit), restore it so ``train_fn`` resumes from the last
    committed step rather than step 0. If the world dies underneath the
    loop — :class:`WorkerFailureError` (a rank died / went silent),
    :class:`StalledError`, or :class:`TransportError` — tear the local
    world down cleanly and re-raise, so the process exits nonzero and
    ``tpurun --restarts N`` relaunches everything; the relaunched world
    lands back here and resumes.

    Returns whatever ``train_fn`` returns on success.
    """
    committed = state.latest_committed()  # one cross-rank agreement round
    if committed is not None:
        # _restore_step skips the second verify pass only when THIS
        # rank's walk proved the agreed step; the cross-rank min can be
        # a step this rank never verified (its own candidate was newer),
        # and a corrupt local copy of it must raise, not restore.
        state._restore_step(int(committed))
        if state.discarded_corrupt:
            print(f"[elastic] discarded {state.discarded_corrupt} "
                  f"committed-but-corrupt checkpoint(s); resuming from "
                  f"verified step {state.step}", flush=True)
        if restart_epoch() > 0:
            print(f"[elastic] restart epoch {restart_epoch()}: resumed "
                  f"from committed step {state.step}", flush=True)
    try:
        return train_fn(state)
    except RECOVERABLE as e:
        sys.stderr.write(
            f"[elastic] world failure at step {state.step}: {e}\n"
            f"[elastic] exiting for supervised restart (run under "
            f"tpurun --restarts N to resume from the last committed "
            f"step)\n")
        # Crash-safe teardown (shutdown tolerates a dead coordinator) so
        # the relaunched world starts from a clean slate.
        runtime.shutdown()
        raise
