"""Deterministic fault injection, driven by ``HVD_FAULT_SPEC``.

The fault-tolerance subsystem (heartbeats, world abort, supervised
restart, elastic recovery) is only trustworthy if every failure path can
be exercised on CPU in CI — the reference had no way to kill a rank
deterministically, so its stall handling shipped warn-only and untested.
This module turns an env spec into precise failures:

    HVD_FAULT_SPEC=rank=2:kill@step=3          # SIGKILL rank 2 at step 3
    HVD_FAULT_SPEC=rank=1:mute@step=2          # rank 1 goes silent (alive)
    HVD_FAULT_SPEC=coord:mute@step=2           # coordinator stops acking
    HVD_FAULT_SPEC=coord:delay_ms=50           # slow coordination plane
    HVD_FAULT_SPEC=rank=0:exit@step=4@epoch=1  # only on restart epoch 1
    HVD_FAULT_SPEC=ckpt:truncate@step=5        # tear the step-5 checkpoint
    HVD_FAULT_SPEC=ckpt:flip@step=5            # flip one byte in it
    HVD_FAULT_SPEC=ckpt:drop_marker@step=5     # lose its commit marker
    HVD_FAULT_SPEC=resize:shrink=2@step=3      # live-shrink the world by 2
    HVD_FAULT_SPEC=resize:grow=4@step=3        # live-grow the world by 4
    HVD_FAULT_SPEC=resize:world=2@step=3       # live-resize to exactly 2
    HVD_FAULT_SPEC=replica_kill=r1@stream=3    # serving: kill replica r1's
                                               #   engine loop at its 3rd stream
    HVD_FAULT_SPEC=replica_hang=r0@stream=2    # serving: hang the loop instead
    HVD_FAULT_SPEC=replica_proc_kill=r1@stream=3  # serving: SIGKILL the
                                               #   subprocess replica's worker
    HVD_FAULT_SPEC=slow_step=50                # serving: 50 ms per decode step

Grammar: comma-separated clauses, each ``rank=<r>:<action>@step=<s>``,
``coord:mute@step=<s>`` / ``coord:delay_ms=<n>``,
``ckpt:<truncate|flip|drop_marker>@step=<s>``,
``resize:<shrink|grow|world>=<k>@step=<s>``, or a serving-plane clause
``replica_kill=<name>@stream=<k>`` / ``replica_hang=<name>@stream=<k>``
/ ``replica_proc_kill=<name>@stream=<k>`` / ``slow_step=<ms>``.
Step-scoped actions
REQUIRE ``@step`` (a clause that could never fire is rejected loudly);
``delay_ms`` is unconditional — it has no step context and rejects
``@step``. Every clause takes an optional ``@epoch=<e>`` suffix
(default 0) matched against ``HVD_RESTART_EPOCH`` — so a kill drill fires
on the first launch and NOT again after ``tpurun --restarts`` relaunches
the world.

``ckpt`` clauses corrupt the just-committed checkpoint for the matching
step, strictly AFTER the two-phase commit completes (marker on disk) —
modeling post-commit bit rot / torn replication, the failure class the
integrity manifests + verified fallback restore exist for. They fire on
every rank (each env-world rank owns a private checkpoint copy).

Serving-plane clauses (``replica_kill`` / ``replica_hang`` /
``replica_proc_kill`` / ``slow_step``) fire inside a
:class:`horovod_tpu.serve.generate.GenerationEngine` loop — the chaos
analog of a serving replica dying, wedging, or running slow under
load. For thread replicas "kill" is an abrupt loop-thread death (the
thread exits WITHOUT failing its handles — a crashed process cannot
deliver failures; the stranded streams are exactly what the fleet
router's deterministic failover must resume) and "hang" parks the loop
forever with heartbeats-of-a-sort still flowing (the thread stays
alive — only the in-process liveness probe's stale-beat verdict can
catch it). ``replica_proc_kill`` is the out-of-process analog: the
engine loop dumps its post-mortem and then SIGKILLs its OWN process —
only meaningful inside a :mod:`horovod_tpu.serve.proc_replica` worker
(the clause reaches the child because spawned workers inherit the
parent environment), where the parent-side liveness plane must detect
the dead pid and failover-replay the child's streams.
``@stream=<k>`` scopes the trigger to the replica's k-th ADMITTED
stream, so the kill always lands mid-stream, deterministically.
``slow_step=<ms>`` sleeps in every engine loop iteration on EVERY
replica (no ``@stream`` — it models a slow chip, not an event).
:func:`serve_hook` is called once per engine loop iteration.

``resize`` clauses inject a live elastic resize at the matching step
boundary — the chaos-drill analog of a spot-preemption notice
(``kill -USR1`` on tpurun) or an operator's admin RPC. ``shrink=K`` /
``grow=K`` are relative (world − K / world + K, the
"K chips preempted / K chips granted" shapes); ``world=N`` is absolute.
:func:`resize_hook` is polled by
:class:`horovod_tpu.elastic.ResizeCoordinator` at step boundaries; in a
tpurun env-world only rank 0 acts on it (it sends the admin RPC to its
own coordinator, so the drill exercises the REAL ingress path end to
end), in a single-controller world the hook's target is applied
directly. Compose with ``rank=<r>:kill@step=<s>`` to race a resize
against a worker death (the quiesce must fall back to the verified
restore walk).

Actions:

* ``kill``  — ``SIGKILL`` this process: the kernel closes its sockets, the
  coordinator sees the disconnect and aborts the world (fast path).
* ``exit``  — ``os._exit(1)``: same, with a nonzero code of our choosing.
* ``hang``  — sleep forever while heartbeats keep flowing: the *stall*
  scenario (``HOROVOD_STALL_TIMEOUT`` / stall warnings), not a death.
* ``mute``  — stop heartbeats, then sleep forever: the process and its
  socket stay alive but the rank goes silent on the liveness plane — the
  only way to exercise the ``HVD_HEARTBEAT_TIMEOUT`` abort path (a kill
  trips the faster disconnect path instead).
* ``delay_ms=<n>`` — (``coord`` target) sleep ``n`` ms in every
  coordination-plane submit, simulating a slow/congested control plane.
* ``mute`` on the ``coord`` target — rank 0 stops acking heartbeats, so
  every client independently detects a dead coordinator.

Hooks: :func:`step_hook` is called once per training step by
``Trainer.fit`` and by elastic training loops; :func:`coord_delay` is
called by ``CoordClient.submit``; :func:`ckpt_hook` is called by
``ElasticState`` right after each two-phase commit finishes. All are
near-zero-cost no-ops when ``HVD_FAULT_SPEC`` is unset.
"""

from __future__ import annotations

import dataclasses
import glob
import os
import signal
import time
from typing import List, Optional

ENV_VAR = "HVD_FAULT_SPEC"

_ACTIONS = ("kill", "exit", "hang", "mute", "delay_ms",
            "truncate", "flip", "drop_marker",
            "shrink", "grow", "world")
_CKPT_ACTIONS = ("truncate", "flip", "drop_marker")
_RESIZE_ACTIONS = ("shrink", "grow", "world")
_SERVE_ACTIONS = ("replica_kill", "replica_hang", "replica_proc_kill",
                  "slow_step")


@dataclasses.dataclass(frozen=True)
class Fault:
    target: str              # "rank", "coord", "ckpt", "resize" or "serve"
    rank: Optional[int]      # rank the fault applies to (None for coord)
    action: str              # one of _ACTIONS / _SERVE_ACTIONS
    step: Optional[int]      # fire at this step (None = unconditional)
    epoch: int = 0           # fire only on this HVD_RESTART_EPOCH
    value: int = 0           # delay_ms / slow_step payload
    name: Optional[str] = None    # serving replica name (serve target)
    stream: Optional[int] = None  # fire at this admitted-stream count


class FaultSpecError(ValueError):
    """Malformed ``HVD_FAULT_SPEC`` — loud, like every other env knob."""


def _parse_serve_clause(clause: str) -> Fault:
    """One serving-plane clause: ``replica_kill=<name>@stream=<k>`` /
    ``replica_hang=<name>@stream=<k>`` /
    ``replica_proc_kill=<name>@stream=<k>`` (real SIGKILL of a
    subprocess replica's worker) / ``slow_step=<ms>`` — same
    loud-validation standard as the training-plane grammar (a drill
    that could never fire is a spec bug, not a no-op)."""
    parts = clause.split("@")
    action, _, val = parts[0].partition("=")
    stream: Optional[int] = None
    epoch = 0
    for cond in parts[1:]:
        key, _, cval = cond.partition("=")
        try:
            if key == "stream":
                stream = int(cval)
            elif key == "epoch":
                epoch = int(cval)
            else:
                raise FaultSpecError(
                    f"{ENV_VAR}: unknown condition {cond!r} in clause "
                    f"{clause!r} (expected stream=<k> or epoch=<n>)")
        except ValueError:
            raise FaultSpecError(
                f"{ENV_VAR}: bad condition {cond!r} in clause "
                f"{clause!r}") from None
    if action == "slow_step":
        try:
            ms = int(val)
        except ValueError:
            raise FaultSpecError(
                f"{ENV_VAR}: bad delay in clause {clause!r} (expected "
                f"slow_step=<ms>)") from None
        if ms < 1:
            raise FaultSpecError(
                f"{ENV_VAR}: slow_step={ms} in clause {clause!r} — the "
                f"per-step delay must be >= 1 ms")
        if stream is not None:
            # The delay applies to EVERY loop iteration on EVERY replica
            # (a slow chip, not an event); accepting @stream would
            # silently drop the condition.
            raise FaultSpecError(
                f"{ENV_VAR}: slow_step does not support @stream (clause "
                f"{clause!r}) — the delay applies to every engine loop "
                f"iteration")
        return Fault(target="serve", rank=None, action="slow_step",
                     step=None, epoch=epoch, value=ms)
    if not val:
        raise FaultSpecError(
            f"{ENV_VAR}: clause {clause!r} — {action} needs a replica "
            f"name ({action}=<name>@stream=<k>)")
    if stream is None or stream < 1:
        # serve_hook fires on an admitted-stream count, so a clause
        # without @stream>=1 could never fire deterministically.
        raise FaultSpecError(
            f"{ENV_VAR}: {action} requires @stream=<k> with k >= 1 "
            f"(clause {clause!r}); the kill must land on a definite "
            f"stream to be a drill")
    return Fault(target="serve", rank=None, action=action, step=None,
                 epoch=epoch, name=val, stream=stream)


def parse_spec(text: str) -> List[Fault]:
    faults: List[Fault] = []
    for clause in filter(None, (c.strip() for c in text.split(","))):
        if any(clause.startswith(a + "=") for a in _SERVE_ACTIONS):
            # Serving-plane clauses carry no '<target>:' prefix — the
            # action name IS the discriminator.
            faults.append(_parse_serve_clause(clause))
            continue
        target, _, rest = clause.partition(":")
        rank: Optional[int] = None
        if target.startswith("rank="):
            try:
                rank = int(target[len("rank="):])
            except ValueError:
                raise FaultSpecError(
                    f"{ENV_VAR}: bad rank in clause {clause!r}") from None
            target = "rank"
        elif target not in ("coord", "ckpt", "resize"):
            raise FaultSpecError(
                f"{ENV_VAR}: clause {clause!r} must start with "
                f"'rank=<r>:', 'coord:', 'ckpt:' or 'resize:'")
        if not rest:
            raise FaultSpecError(f"{ENV_VAR}: clause {clause!r} has no action")
        parts = rest.split("@")
        action, step, epoch, value = parts[0], None, 0, 0
        if action.startswith("delay_ms="):
            try:
                value = int(action[len("delay_ms="):])
            except ValueError:
                raise FaultSpecError(
                    f"{ENV_VAR}: bad delay in clause {clause!r}") from None
            action = "delay_ms"
        elif any(action.startswith(a + "=") for a in _RESIZE_ACTIONS):
            key, _, val = action.partition("=")
            try:
                value = int(val)
            except ValueError:
                raise FaultSpecError(
                    f"{ENV_VAR}: bad {key} value in clause {clause!r} "
                    f"(expected {key}=<positive int>)") from None
            if value < 1:
                raise FaultSpecError(
                    f"{ENV_VAR}: {key}={value} in clause {clause!r} — a "
                    f"resize delta/target must be >= 1 (a world cannot "
                    f"shrink by zero or resize to zero ranks)")
            action = key
        elif action in _RESIZE_ACTIONS:
            raise FaultSpecError(
                f"{ENV_VAR}: clause {clause!r} — {action} needs a value "
                f"({action}=<k>); a resize with no size tests nothing")
        if action not in _ACTIONS:
            raise FaultSpecError(
                f"{ENV_VAR}: unknown action {action!r} in clause "
                f"{clause!r}; expected one of {_ACTIONS}")
        for cond in parts[1:]:
            key, _, val = cond.partition("=")
            try:
                if key == "step":
                    step = int(val)
                elif key == "epoch":
                    epoch = int(val)
                else:
                    raise FaultSpecError(
                        f"{ENV_VAR}: unknown condition {cond!r} in clause "
                        f"{clause!r} (expected step=<n> or epoch=<n>)")
            except ValueError:
                raise FaultSpecError(
                    f"{ENV_VAR}: bad condition {cond!r} in clause "
                    f"{clause!r}") from None
        if target == "rank" and rank is None:
            raise FaultSpecError(
                f"{ENV_VAR}: rank clause {clause!r} missing rank number")
        if (action in _CKPT_ACTIONS) != (target == "ckpt"):
            # Checkpoint corruption only makes sense on the ckpt target
            # (it fires from the commit hook, not the step hook), and the
            # ckpt target supports nothing else.
            raise FaultSpecError(
                f"{ENV_VAR}: clause {clause!r} — actions {_CKPT_ACTIONS} "
                f"require (and are the only actions of) the 'ckpt:' "
                f"target")
        if (action in _RESIZE_ACTIONS) != (target == "resize"):
            # Same discipline for the resize plane: shrink/grow/world fire
            # from the step-boundary resize hook, not the rank/coord/ckpt
            # hooks, and the resize target supports nothing else (killing
            # a rank is a failure, not a resize).
            raise FaultSpecError(
                f"{ENV_VAR}: clause {clause!r} — actions {_RESIZE_ACTIONS} "
                f"require (and are the only actions of) the 'resize:' "
                f"target")
        if action == "delay_ms" and step is not None:
            # The delay applies to EVERY submit (there is no step context
            # inside the coordination-plane client); accepting @step here
            # would silently drop the condition.
            raise FaultSpecError(
                f"{ENV_VAR}: delay_ms does not support @step (clause "
                f"{clause!r}) — the delay applies to every "
                f"coordination-plane submit")
        if action != "delay_ms" and step is None:
            # step_hook only fires on an exact step match, so a clause
            # without @step could never fire — a drill that silently
            # tests nothing. Same loud-validation standard as above.
            raise FaultSpecError(
                f"{ENV_VAR}: {action} requires @step=<n> (clause "
                f"{clause!r}); without it the fault would never fire")
        faults.append(Fault(target=target, rank=rank, action=action,
                            step=step, epoch=epoch, value=value))
    return faults


# Parsed-spec cache keyed by the raw env value, so tests can mutate the
# env between worlds while the hot no-fault path stays one dict lookup.
_cache: dict = {}
_fired: set = set()


def _active() -> List[Fault]:
    raw = os.environ.get(ENV_VAR) or ""
    if raw not in _cache:
        _cache[raw] = parse_spec(raw) if raw else []
    return _cache[raw]


def _restart_epoch() -> int:
    from ..utils import config as _config
    return _config.restart_epoch()


def _my_rank() -> int:
    from .. import runtime
    from ..utils import config as _config
    if runtime.is_initialized():
        return runtime.world().process_index
    return _config.launcher_rank(default=0)


def _fire(fault: Fault) -> None:
    tag = f"epoch {_restart_epoch()} step {fault.step}"
    from ..obs import flightrec
    flightrec.record("fault", action=fault.action, rank=_my_rank(),
                     step=fault.step)
    if fault.action in ("kill", "exit"):
        # Flight-recorder dump BEFORE the trigger: SIGKILL is untrappable
        # by the kernel's contract, so the drilled rank's own ring would
        # otherwise be lost. A real preemption delivers SIGTERM first
        # (which the obs.flightrec signal hook catches); the injector
        # stands in for that notice — the drill's "dead" rank leaves the
        # same hvd_flightrec.rank{N}.json a preempted rank would, naming
        # its final completed step. Survivors additionally dump on the
        # WorkerFailureError the abort hands them.
        flightrec.dump(reason=f"fault injection: {fault.action} at {tag}")
        flightrec.run_crash_hooks()
    if fault.action == "kill":
        print(f"[faults] rank {_my_rank()}: SIGKILL at {tag}", flush=True)
        os.kill(os.getpid(), signal.SIGKILL)
    elif fault.action == "exit":
        print(f"[faults] rank {_my_rank()}: exit(1) at {tag}", flush=True)
        os._exit(1)
    elif fault.action in ("hang", "mute"):
        client = None
        from .. import runtime
        if runtime.is_initialized():
            client = runtime.world().coord
        if fault.action == "mute":
            if fault.target == "coord":
                if client is not None:
                    print(f"[faults] rank {_my_rank()}: coordinator mutes "
                          f"heartbeat-acks at {tag}", flush=True)
                    client.mute_coordinator_acks(True)
                return  # the coordinator keeps serving; clients abort
            if client is not None:
                client.mute_heartbeats(True)
        print(f"[faults] rank {_my_rank()}: {fault.action} (sleeping "
              f"forever) at {tag}", flush=True)
        while True:  # parked until the launcher or the test kills us
            time.sleep(3600)


def step_hook(step: int) -> None:
    """Fire any fault scoped to this process at training step ``step``.

    Called by ``Trainer.fit`` after each batch and by elastic training
    loops; a no-op (one dict lookup) unless ``HVD_FAULT_SPEC`` is set.
    """
    faults = _active()
    if not faults:
        return
    epoch = _restart_epoch()
    for i, f in enumerate(faults):
        if f.target in ("ckpt", "resize", "serve"):
            continue  # fire from ckpt_hook / resize_hook / serve_hook
        if f.action == "delay_ms" or f.step != step or f.epoch != epoch:
            continue
        if f.target == "rank" and f.rank != _my_rank():
            continue
        if f.target == "coord" and _my_rank() != 0:
            continue  # the coordinator lives in rank 0's process
        key = (i, epoch)
        if key in _fired:
            continue
        _fired.add(key)
        _fire(f)


def reset() -> None:
    """Forget which faults already fired (tests re-run drills in one
    process; production worlds never need this)."""
    _fired.clear()


def _ckpt_data_file(ckpt_dir: str) -> Optional[str]:
    """The checkpoint's largest array-data file — the corruption target.

    Prefers tensorstore ``d/`` chunk files (real array bytes, the case
    integrity CRCs — not orbax — must catch); falls back to the largest
    file of any kind (truncating metadata models a torn write).
    """
    chunks = [f for f in glob.glob(os.path.join(ckpt_dir, "**", "d", "*"),
                                   recursive=True) if os.path.isfile(f)]
    if not chunks:
        chunks = [f for f in glob.glob(os.path.join(ckpt_dir, "**", "*"),
                                       recursive=True)
                  if os.path.isfile(f)
                  and os.path.basename(f) != "hvd_manifest.json"]
    return max(chunks, key=os.path.getsize, default=None)


def _corrupt_checkpoint(fault: Fault, ckpt_dir: str, marker: str) -> None:
    tag = f"epoch {_restart_epoch()} step {fault.step}"
    if fault.action == "drop_marker":
        print(f"[faults] rank {_my_rank()}: dropping commit marker "
              f"{os.path.basename(marker)} at {tag}", flush=True)
        try:
            os.unlink(marker)
        except OSError:
            pass
        return
    victim = _ckpt_data_file(ckpt_dir)
    if victim is None:
        print(f"[faults] rank {_my_rank()}: no data file to corrupt "
              f"under {ckpt_dir} at {tag}", flush=True)
        return
    size = os.path.getsize(victim)
    if fault.action == "truncate":
        print(f"[faults] rank {_my_rank()}: truncating "
              f"{os.path.relpath(victim, ckpt_dir)} {size}->{size // 2} "
              f"bytes at {tag}", flush=True)
        with open(victim, "r+b") as f:
            f.truncate(size // 2)
    else:  # flip
        off = size // 2
        with open(victim, "r+b") as f:
            f.seek(off)
            b = f.read(1) or b"\x00"
            f.seek(off)
            # Increment, not XOR: in a shared-directory jax.distributed
            # world every rank's commit hook corrupts the SAME byte, and
            # an even number of self-inverting XORs would restore it —
            # a drill that silently tests nothing. k increments stay
            # corrupt for any k not a multiple of 256.
            f.write(bytes([(b[0] + 1) & 0xFF]))
        print(f"[faults] rank {_my_rank()}: flipped byte {off} of "
              f"{os.path.relpath(victim, ckpt_dir)} at {tag}", flush=True)


def ckpt_hook(step: int, ckpt_dir: str, marker: str) -> None:
    """Fire any ``ckpt:*`` clause scoped to the checkpoint just committed
    at ``step``. Called by ``ElasticState`` immediately after the
    two-phase commit finishes (bytes + manifest + marker all durable), so
    the corruption models post-commit rot — the marker keeps promising
    bytes the disk no longer honors, which the verified fallback restore
    must survive. No-op (one dict lookup) unless ``HVD_FAULT_SPEC`` has a
    ``ckpt:`` clause."""
    faults = _active()
    if not faults:
        return
    epoch = _restart_epoch()
    for i, f in enumerate(faults):
        if f.target != "ckpt" or f.step != step or f.epoch != epoch:
            continue
        key = (i, epoch)
        if key in _fired:
            continue
        _fired.add(key)
        _corrupt_checkpoint(f, ckpt_dir, marker)


def resize_hook(step: int, world_size: int) -> Optional[int]:
    """Target world size of any ``resize:*`` clause firing at ``step``,
    or None. Called once per step boundary by
    :class:`horovod_tpu.elastic.ResizeCoordinator` (near-zero-cost no-op
    unless the spec has a resize clause).

    ``shrink=K``/``grow=K`` are relative to ``world_size`` (the
    spot-preemption shape: K chips lost/granted); ``world=N`` is
    absolute. A clause that resolves to a target < 1 raises loudly — a
    drill that asks for an impossible world must not be silently
    clamped. A target equal to the current world is logged and skipped
    (already that size — nothing to drill)."""
    faults = _active()
    if not faults:
        return None
    epoch = _restart_epoch()
    for i, f in enumerate(faults):
        if f.target != "resize" or f.step != step or f.epoch != epoch:
            continue
        key = (i, epoch)
        if key in _fired:
            continue
        _fired.add(key)
        if f.action == "shrink":
            target = world_size - f.value
        elif f.action == "grow":
            target = world_size + f.value
        else:
            target = f.value
        if target < 1:
            raise FaultSpecError(
                f"{ENV_VAR}: resize clause {f.action}={f.value} at step "
                f"{step} resolves to target world {target} from world "
                f"{world_size} — a world needs at least 1 rank")
        if target == world_size:
            print(f"[faults] resize drill at step {step}: world is "
                  f"already {world_size} — nothing to do", flush=True)
            return None
        print(f"[faults] rank {_my_rank()}: injecting live resize "
              f"{world_size} -> {target} at epoch {epoch} step {step}",
              flush=True)
        from ..obs import flightrec
        flightrec.record("fault", action="resize", step=step,
                         world=world_size, target=target)
        return target
    return None


def serve_hook(replica: str, streams_admitted: int) -> Optional[str]:
    """Fire any serving-plane clause scoped to engine ``replica`` —
    called once per :class:`~horovod_tpu.serve.generate.
    GenerationEngine` loop iteration (near-zero-cost no-op unless the
    spec has a serve clause). Returns ``"kill"`` (the loop must die
    abruptly, stranding its handles — the deterministic-failover drill
    shape), ``"proc_kill"`` (the loop must SIGKILL its OWN process
    after dumping a post-mortem — the subprocess-replica drill: the
    parent sees a dead pid, not a flipped flag), ``"hang"`` (the loop
    must park forever with its thread alive — only a stale-beat
    liveness probe catches it), or None. ``slow_step`` clauses sleep
    here directly, every call.

    ``streams_admitted`` is the replica's cumulative count of streams
    admitted into decode slots; a ``@stream=k`` clause fires once that
    count reaches k — i.e. with stream k mid-flight, deterministically.
    """
    faults = _active()
    if not faults:
        return None
    epoch = _restart_epoch()
    out: Optional[str] = None
    for i, f in enumerate(faults):
        if f.target != "serve" or f.epoch != epoch:
            continue
        if f.action == "slow_step":
            time.sleep(f.value / 1000.0)
            continue
        if f.name != replica or streams_admitted < (f.stream or 0):
            continue
        key = (i, epoch)
        if key in _fired:
            continue
        _fired.add(key)
        from ..obs import flightrec
        flightrec.record("fault", action=f.action, replica=replica,
                         stream=f.stream)
        print(f"[faults] serving replica {replica}: {f.action} at "
              f"admitted stream {f.stream} (epoch {epoch})", flush=True)
        out = {"replica_kill": "kill",
               "replica_proc_kill": "proc_kill"}.get(f.action, "hang")
    return out


def coord_delay() -> None:
    """Sleep per ``coord:delay_ms=<n>`` — called from every coordination-
    plane submit; no-op unless the spec targets the coordinator."""
    faults = _active()
    if not faults:
        return
    epoch = _restart_epoch()
    for f in faults:
        if (f.target == "coord" and f.action == "delay_ms"
                and f.epoch == epoch):
            time.sleep(f.value / 1000.0)
