"""Test-support utilities (deterministic fault injection, drills).

Nothing here runs in production paths unless explicitly enabled via env
(``HVD_FAULT_SPEC``); the hooks are no-ops otherwise.
"""

from . import faults  # noqa: F401
