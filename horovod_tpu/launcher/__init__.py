"""tpurun — the launcher replacing ``mpirun`` (reference: ``docs/running.md``).

The reference is launched as ``mpirun -np N -H host:slots python train.py``
with OpenMPI wiring rank/size env into every process. ``tpurun`` spawns one
process per chip on a TPU VM (or N local processes for CPU testing) and sets:

* ``HVD_RANK`` / ``HVD_SIZE`` / ``HVD_LOCAL_RANK`` — the process grid
  (parity: ``OMPI_COMM_WORLD_RANK`` etc., read by tests
  ``mpi_ops_test.py:31-63``).
* ``HVD_COORD_ADDR`` — rendezvous address of the host coordination plane
  (the out-of-band wire-up role MPI plays for the reference).
* with ``--jax-distributed``: ``JAX_COORDINATOR_ADDRESS`` /
  ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` so ``jax.distributed`` forms a
  global device mesh and *compiled* collectives span processes over ICI/DCN.
  Without it, processes are independent JAX worlds and cross-rank collectives
  ride the host plane only (the reference's model: 1 process = 1 GPU,
  ``README.md:62-64``).

Usage::

    python -m horovod_tpu.launcher -np 4 python examples/mnist.py
    tpurun -np 4 python train.py          # if bin/ on PATH
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
from typing import List, Optional


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _chips_per_host() -> int:
    """Local chip count (local_rank domain — the analog of
    MPI_Comm_split_type(SHARED) sizing, mpi_ops.cc:1263-1267).

    Deliberately does NOT import jax: initializing a TPU backend in the
    launcher would hold the chips and every spawned rank would fail with
    "TPU already in use". Count device nodes instead.
    """
    import glob
    override = os.environ.get("HVD_CHIPS_PER_HOST")
    if override:
        try:
            return max(1, int(override))
        except ValueError:
            pass
    for pattern in ("/dev/accel*", "/dev/vfio/[0-9]*"):
        n = len(glob.glob(pattern))
        if n:
            return n
    return 1


def launch(np_: int, command: List[str], *, coord_port: Optional[int] = None,
           jax_distributed: bool = False, cpu: bool = False,
           node_rank: int = 0, nnodes: int = 1,
           coordinator: Optional[str] = None,
           extra_env: Optional[dict] = None) -> int:
    """Spawn ``np_`` local ranks of ``command`` with the world env wired up.

    Multi-host: run tpurun on every host with the same ``--coordinator
    host0:port`` and ``--nnodes N``, giving each host its ``--node-rank``
    (the role of ``mpirun -H host1:4,host2:4``, reference
    ``docs/running.md:15-45``). World size = nnodes · np_; this host's ranks
    are ``node_rank·np_ .. node_rank·np_+np_-1``.

    Returns the first nonzero exit code (0 if all succeeded).
    """
    world = nnodes * np_
    if coordinator:
        coord_host, _, cport = coordinator.partition(":")
        coord_addr = f"{coord_host}:{cport or 29521}"
        jd_addr = f"{coord_host}:{int(cport or 29521) + 1}"
    else:
        coord_addr = f"127.0.0.1:{coord_port or _free_port()}"
        jd_addr = f"127.0.0.1:{_free_port()}" if jax_distributed else None
    procs = []

    def _terminate(signum, frame):
        for p in procs:
            p.terminate()
    old = signal.signal(signal.SIGTERM, _terminate)

    try:
        for local_rank in range(np_):
            rank = node_rank * np_ + local_rank
            env = dict(os.environ)
            env.update(extra_env or {})
            env["HVD_RANK"] = str(rank)
            env["HVD_SIZE"] = str(world)
            env["HVD_LOCAL_RANK"] = str(
                local_rank % max(1, _chips_per_host() if not cpu else np_))
            env["HVD_COORD_ADDR"] = coord_addr
            if cpu:
                # CPU testing mode (reference CI: mpirun -np 2 on localhost
                # CPU-only, .travis.yml:84-91).
                env["JAX_PLATFORMS"] = "cpu"
            if jax_distributed:
                env["JAX_COORDINATOR_ADDRESS"] = jd_addr
                env["JAX_NUM_PROCESSES"] = str(world)
                env["JAX_PROCESS_ID"] = str(rank)
            procs.append(subprocess.Popen(command, env=env))
        rc = 0
        for p in procs:
            p.wait()
            if p.returncode and not rc:
                rc = p.returncode
        return rc
    finally:
        signal.signal(signal.SIGTERM, old)
        for p in procs:
            if p.poll() is None:
                p.terminate()


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tpurun",
        description="Launch N ranks of a training script on this host "
                    "(mpirun replacement; see docs/running.md parity).")
    parser.add_argument("-np", type=int, required=True,
                        help="number of ranks (processes) to spawn")
    parser.add_argument("--cpu", action="store_true",
                        help="force JAX CPU backend in ranks (CI/testing)")
    parser.add_argument("--jax-distributed", action="store_true",
                        help="also form a jax.distributed world so compiled "
                             "collectives span processes")
    parser.add_argument("--coord-port", type=int, default=None)
    parser.add_argument("--node-rank", type=int, default=0,
                        help="this host's index among --nnodes hosts")
    parser.add_argument("--nnodes", type=int, default=1,
                        help="total hosts in the job (world = nnodes * np)")
    parser.add_argument("--coordinator", default=None,
                        help="host0:port rendezvous shared by all hosts "
                             "(required when nnodes > 1)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="the command to run, e.g. python train.py")
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")
    if args.nnodes > 1 and not args.coordinator:
        parser.error("--nnodes > 1 requires --coordinator host0:port")
    return launch(args.np, args.command, coord_port=args.coord_port,
                  jax_distributed=args.jax_distributed, cpu=args.cpu,
                  node_rank=args.node_rank, nnodes=args.nnodes,
                  coordinator=args.coordinator)


if __name__ == "__main__":
    sys.exit(main())
