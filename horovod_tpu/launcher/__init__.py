"""tpurun — the launcher replacing ``mpirun`` (reference: ``docs/running.md``).

The reference is launched as ``mpirun -np N -H host:slots python train.py``
with OpenMPI wiring rank/size env into every process. ``tpurun`` spawns one
process per chip on a TPU VM (or N local processes for CPU testing) and sets:

* ``HVD_RANK`` / ``HVD_SIZE`` / ``HVD_LOCAL_RANK`` — the process grid
  (parity: ``OMPI_COMM_WORLD_RANK`` etc., read by tests
  ``mpi_ops_test.py:31-63``).
* ``HVD_COORD_ADDR`` — rendezvous address of the host coordination plane
  (the out-of-band wire-up role MPI plays for the reference).
* with ``--jax-distributed``: ``JAX_COORDINATOR_ADDRESS`` /
  ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` so ``jax.distributed`` forms a
  global device mesh and *compiled* collectives span processes over ICI/DCN.
  Without it, processes are independent JAX worlds and cross-rank collectives
  ride the host plane only (the reference's model: 1 process = 1 GPU,
  ``README.md:62-64``).

Usage::

    python -m horovod_tpu.launcher -np 4 python examples/mnist.py
    tpurun -np 4 python train.py          # if bin/ on PATH
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from typing import List, Optional

# How long a worker gets between terminate() and kill() during teardown —
# enough for JAX runtimes to flush, short enough that a wedged worker
# cannot hold the job hostage.
TERMINATE_GRACE_SECS = 5.0

# After the FIRST worker failure, how long the siblings get to exit on
# their own before the supervisor terminates them. The coordination
# plane's ABORT reaches them within milliseconds and each then exits with
# the named WorkerFailureError — reaping instantly would race that and
# destroy the diagnosis; only ranks still alive after the grace (wedged,
# or not blocked in a collective) get the terminate→kill escalation.
FAILFAST_GRACE_SECS = 3.0


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _chips_per_host() -> int:
    """Local chip count (local_rank domain — the analog of
    MPI_Comm_split_type(SHARED) sizing, mpi_ops.cc:1263-1267).

    Deliberately does NOT import jax: initializing a TPU backend in the
    launcher would hold the chips and every spawned rank would fail with
    "TPU already in use". Count device nodes instead.
    """
    import glob
    override = os.environ.get("HVD_CHIPS_PER_HOST")
    if override:
        try:
            return max(1, int(override))
        except ValueError:
            pass
    for pattern in ("/dev/accel*", "/dev/vfio/[0-9]*"):
        n = len(glob.glob(pattern))
        if n:
            return n
    return 1


def _reap(procs: List[subprocess.Popen],
          grace_secs: float = TERMINATE_GRACE_SECS) -> None:
    """Terminate-then-kill every still-running worker, and REAP them all.

    terminate() alone is not cleanup: a worker blocked in a collective (or
    ignoring SIGTERM) survives it, and an unreaped child is a zombie
    holding its pipes open. Escalation: SIGTERM → wait up to
    ``grace_secs`` → SIGKILL → wait (SIGKILL cannot be ignored, so the
    final wait always returns).
    """
    for p in procs:
        if p.poll() is None:
            try:
                p.terminate()
            except OSError:
                pass
    deadline = time.monotonic() + grace_secs
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                pass
    for p in procs:
        if p.poll() is None:
            try:
                p.kill()
            except OSError:
                pass
    for p in procs:
        try:
            p.wait()
        except OSError:
            pass


def _launch_once(np_: int, command: List[str], *,
                 coord_port: Optional[int], jax_distributed: bool,
                 cpu: bool, node_rank: int, nnodes: int,
                 coordinator: Optional[str], extra_env: Optional[dict],
                 restart_epoch: int) -> "tuple[int, bool]":
    """One supervised world launch: spawn, watch ALL ranks, fail fast.

    The seed's wait loop blocked on workers in spawn order: rank 3 dying
    first went unnoticed until ranks 0-2 exited — which, pre-abort, they
    never did (the reference's dead-rank-hangs-MPI failure mode). Here the
    supervisor polls every worker; on the FIRST failure it tears the
    surviving siblings down (terminate → kill escalation) so the job exits
    nonzero within seconds, not never.
    """
    world = nnodes * np_
    if coordinator:
        coord_host, _, cport = coordinator.partition(":")
        coord_addr = f"{coord_host}:{cport or 29521}"
        jd_addr = f"{coord_host}:{int(cport or 29521) + 1}"
    else:
        coord_addr = f"127.0.0.1:{coord_port or _free_port()}"
        jd_addr = f"127.0.0.1:{_free_port()}" if jax_distributed else None
    procs: List[subprocess.Popen] = []
    interrupted = {"sig": None}

    def _forward(signum, frame):
        # Forward the launcher's own termination (Ctrl-C / SIGTERM from a
        # job scheduler) to every worker; the supervision loop then reaps
        # with the usual escalation.
        interrupted["sig"] = signum
        for p in procs:
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass

    old_term = signal.signal(signal.SIGTERM, _forward)
    old_int = signal.signal(signal.SIGINT, _forward)

    try:
        for local_rank in range(np_):
            rank = node_rank * np_ + local_rank
            env = dict(os.environ)
            env.update(extra_env or {})
            env["HVD_RANK"] = str(rank)
            env["HVD_SIZE"] = str(world)
            env["HVD_LOCAL_RANK"] = str(
                local_rank % max(1, _chips_per_host() if not cpu else np_))
            env["HVD_COORD_ADDR"] = coord_addr
            # Which (re)launch of the world this is; read by the elastic
            # recovery API and the fault injector's @epoch condition.
            env["HVD_RESTART_EPOCH"] = str(restart_epoch)
            if cpu:
                # CPU testing mode (reference CI: mpirun -np 2 on localhost
                # CPU-only, .travis.yml:84-91).
                env["JAX_PLATFORMS"] = "cpu"
            if jax_distributed:
                env["JAX_COORDINATOR_ADDRESS"] = jd_addr
                env["JAX_NUM_PROCESSES"] = str(world)
                env["JAX_PROCESS_ID"] = str(rank)
            procs.append(subprocess.Popen(command, env=env))

        # Supervision loop: any-order exit detection.
        rc = 0
        while True:
            running = 0
            for p in procs:
                code = p.poll()
                if code is None:
                    running += 1
                elif code and not rc:
                    rc = code
            if rc or not running or interrupted["sig"] is not None:
                break
            time.sleep(0.05)
        if rc and running:
            # Let the world's own abort cascade surface the diagnosis
            # (WorkerFailureError naming the dead rank) before tearing the
            # survivors down.
            deadline = time.monotonic() + FAILFAST_GRACE_SECS
            while time.monotonic() < deadline and any(
                    p.poll() is None for p in procs):
                time.sleep(0.05)
            running = sum(1 for p in procs if p.poll() is None)
            if running:
                sys.stderr.write(
                    f"tpurun: a worker exited with code {rc}; terminating "
                    f"{running} surviving rank(s)\n")
        _reap(procs)
        if not rc:
            for p in procs:
                if p.returncode and not rc:
                    rc = p.returncode
        if interrupted["sig"] is not None and not rc:
            rc = 128 + int(interrupted["sig"])
        # The interruption flag travels alongside rc: an operator's Ctrl-C
        # / scheduler SIGTERM must never be mistaken for a worker failure
        # (which --restarts would relaunch).
        return rc, interrupted["sig"] is not None
    finally:
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)
        _reap(procs)


def launch(np_: int, command: List[str], *, coord_port: Optional[int] = None,
           jax_distributed: bool = False, cpu: bool = False,
           node_rank: int = 0, nnodes: int = 1,
           coordinator: Optional[str] = None,
           extra_env: Optional[dict] = None,
           restarts: int = 0) -> int:
    """Spawn ``np_`` local ranks of ``command`` with the world env wired up.

    Multi-host: run tpurun on every host with the same ``--coordinator
    host0:port`` and ``--nnodes N``, giving each host its ``--node-rank``
    (the role of ``mpirun -H host1:4,host2:4``, reference
    ``docs/running.md:15-45``). World size = nnodes · np_; this host's ranks
    are ``node_rank·np_ .. node_rank·np_+np_-1``.

    Fault tolerance: every launch is supervised — the first failing rank
    tears down its siblings so a dead rank can never hang the job (the
    reference's MPI world does exactly that). With ``restarts > 0`` a
    failed world is relaunched up to ``restarts`` times on a FRESH
    coordinator port (the dead coordinator's socket may linger in
    TIME_WAIT) with exponential backoff, exporting ``HVD_RESTART_EPOCH``
    so workers resume from their last committed
    :class:`horovod_tpu.elastic.ElasticState` — the Elastic-Horovod role.

    Returns the first nonzero exit code (0 if all succeeded).
    """
    rc = 0
    for epoch in range(restarts + 1):
        # Restart on a fresh port: the explicit multi-host --coordinator
        # address is pinned by the operator (every host must agree), but a
        # local auto-picked port is never reused across epochs.
        rc, interrupted = _launch_once(
            np_, command,
            coord_port=coord_port if epoch == 0 else None,
            jax_distributed=jax_distributed, cpu=cpu, node_rank=node_rank,
            nnodes=nnodes, coordinator=coordinator, extra_env=extra_env,
            restart_epoch=epoch)
        if interrupted:
            # Operator interruption (Ctrl-C / scheduler SIGTERM) is a
            # command to STOP, not a failure to retry — never relaunch.
            break
        if rc == 0 or epoch == restarts:
            break
        backoff = min(1.0 * (2 ** epoch), 30.0)
        sys.stderr.write(
            f"tpurun: world failed with exit code {rc} (restart epoch "
            f"{epoch}); relaunching in {backoff:.1f}s "
            f"({restarts - epoch} restart(s) left)\n")
        time.sleep(backoff)
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tpurun",
        description="Launch N ranks of a training script on this host "
                    "(mpirun replacement; see docs/running.md parity).")
    parser.add_argument("-np", type=int, required=True,
                        help="number of ranks (processes) to spawn")
    parser.add_argument("--cpu", action="store_true",
                        help="force JAX CPU backend in ranks (CI/testing)")
    parser.add_argument("--jax-distributed", action="store_true",
                        help="also form a jax.distributed world so compiled "
                             "collectives span processes")
    parser.add_argument("--coord-port", type=int, default=None)
    parser.add_argument("--node-rank", type=int, default=0,
                        help="this host's index among --nnodes hosts")
    parser.add_argument("--nnodes", type=int, default=1,
                        help="total hosts in the job (world = nnodes * np)")
    parser.add_argument("--coordinator", default=None,
                        help="host0:port rendezvous shared by all hosts "
                             "(required when nnodes > 1)")
    parser.add_argument("--restarts", type=int, default=0,
                        help="relaunch the whole world up to N times after "
                             "a failure (fresh coordinator port, "
                             "exponential backoff, HVD_RESTART_EPOCH "
                             "exported); pair with "
                             "horovod_tpu.elastic.run_with_recovery to "
                             "resume from the last committed state")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="the command to run, e.g. python train.py")
    args = parser.parse_args(argv)
    if not args.command:
        parser.error("no command given")
    if args.nnodes > 1 and not args.coordinator:
        parser.error("--nnodes > 1 requires --coordinator host0:port")
    if args.restarts < 0:
        parser.error("--restarts must be >= 0")
    return launch(args.np, args.command, coord_port=args.coord_port,
                  jax_distributed=args.jax_distributed, cpu=args.cpu,
                  node_rank=args.node_rank, nnodes=args.nnodes,
                  coordinator=args.coordinator, restarts=args.restarts)


if __name__ == "__main__":
    sys.exit(main())
