"""tpurun — the launcher replacing ``mpirun`` (reference: ``docs/running.md``).

The reference is launched as ``mpirun -np N -H host:slots python train.py``
with OpenMPI wiring rank/size env into every process. ``tpurun`` spawns one
process per chip on a TPU VM (or N local processes for CPU testing) and sets:

* ``HVD_RANK`` / ``HVD_SIZE`` / ``HVD_LOCAL_RANK`` — the process grid
  (parity: ``OMPI_COMM_WORLD_RANK`` etc., read by tests
  ``mpi_ops_test.py:31-63``).
* ``HVD_COORD_ADDR`` — rendezvous address of the host coordination plane
  (the out-of-band wire-up role MPI plays for the reference).
* with ``--jax-distributed``: ``JAX_COORDINATOR_ADDRESS`` /
  ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID`` so ``jax.distributed`` forms a
  global device mesh and *compiled* collectives span processes over ICI/DCN.
  Without it, processes are independent JAX worlds and cross-rank collectives
  ride the host plane only (the reference's model: 1 process = 1 GPU,
  ``README.md:62-64``).

Usage::

    python -m horovod_tpu.launcher -np 4 python examples/mnist.py
    tpurun -np 4 python train.py          # if bin/ on PATH
"""

from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time
from typing import List, Optional

# How long a worker gets between terminate() and kill() during teardown —
# enough for JAX runtimes to flush, short enough that a wedged worker
# cannot hold the job hostage.
TERMINATE_GRACE_SECS = 5.0

# After the FIRST worker failure, how long the siblings get to exit on
# their own before the supervisor terminates them. The coordination
# plane's ABORT reaches them within milliseconds and each then exits with
# the named WorkerFailureError — reaping instantly would race that and
# destroy the diagnosis; only ranks still alive after the grace (wedged,
# or not blocked in a collective) get the terminate→kill escalation.
FAILFAST_GRACE_SECS = 3.0


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _chips_per_host() -> int:
    """Local chip count (local_rank domain — the analog of
    MPI_Comm_split_type(SHARED) sizing, mpi_ops.cc:1263-1267).

    Deliberately does NOT import jax: initializing a TPU backend in the
    launcher would hold the chips and every spawned rank would fail with
    "TPU already in use". Count device nodes instead.
    """
    import glob
    override = os.environ.get("HVD_CHIPS_PER_HOST")
    if override:
        try:
            return max(1, int(override))
        except ValueError:
            pass
    for pattern in ("/dev/accel*", "/dev/vfio/[0-9]*"):
        n = len(glob.glob(pattern))
        if n:
            return n
    return 1


def _reap(procs: List[subprocess.Popen],
          grace_secs: float = TERMINATE_GRACE_SECS) -> None:
    """Terminate-then-kill every still-running worker, and REAP them all.

    terminate() alone is not cleanup: a worker blocked in a collective (or
    ignoring SIGTERM) survives it, and an unreaped child is a zombie
    holding its pipes open. Escalation: SIGTERM → wait up to
    ``grace_secs`` → SIGKILL → wait (SIGKILL cannot be ignored, so the
    final wait always returns).
    """
    for p in procs:
        if p.poll() is None:
            try:
                p.terminate()
            except OSError:
                pass
    deadline = time.monotonic() + grace_secs
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(0.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                pass
    for p in procs:
        if p.poll() is None:
            try:
                p.kill()
            except OSError:
                pass
    for p in procs:
        try:
            p.wait()
        except OSError:
            pass


class _ResizeSupervisor:
    """Launcher-side state machine of the live-resize plane.

    Watches the coordinator's admin status (~2 RPCs/second of local TCP —
    nothing on the training hot path) so BOTH ingress forms work: resize
    signals delivered to tpurun itself (SIGUSR1 shrink / SIGUSR2 grow,
    the spot-preemption shape) and an operator's direct ``request_resize``
    RPC to the coordinator. On a pending grow it spawns the missing ranks
    wired to the NEW world's coordinator port; on a shrink it only reaps
    the retiring ranks' clean exits. When the OLD coordinator disappears
    (all old-world ranks re-formed), the supervisor follows the plane to
    the new port and updates its notion of the world — so a later crash
    restart relaunches the RESIZED world, and a later resize signal
    computes its target from the current size.
    """

    POLL_SECS = 0.5

    def __init__(self, coord_addr: str, world: int,
                 cap: Optional[int] = None, enabled: bool = True):
        self.coord_addr = coord_addr
        self.world = world
        self.initial_world = world
        self.cap = cap
        self.enabled = enabled
        self._seen_gen = 0
        self._pending: Optional[tuple] = None  # (target, port, generation)
        # Old plane observed down while quiescing: the resize is only
        # COMMITTED once the NEW world's coordinator answers — a job that
        # finishes (cleanly or not) in the same window must not be
        # misread as a successful re-form.
        self._confirming = False
        self._last_poll = 0.0
        # Ranks spawned for the CURRENT pending grow: they become real
        # world members when the resize commits; until then an abandoned
        # resize must reap them (they never joined anything — their
        # eventual connect-timeout exit is not a job failure).
        self._spawned: list = []
        self._reap: list = []

    def drain_reap(self) -> list:
        """Ranks whose spawned-but-never-joined processes the supervision
        loop must terminate and forget (filled by :meth:`abandon`)."""
        out, self._reap = self._reap, []
        return out

    def signal(self, signum: int) -> list:
        """Translate SIGUSR1/SIGUSR2 into an admin resize RPC. Returns
        grow spawns like :meth:`poll` (the RPC reply carries the pending
        triple, so the signal path never depends on winning a race with
        the quiescing world's teardown)."""
        if not self.enabled:
            sys.stderr.write(
                "tpurun: resize signal ignored — live resize supports "
                "single-node env-worlds (no --nnodes/--jax-distributed); "
                "use --restarts + the world-agnostic checkpoint to "
                "reshape such jobs\n")
            return []
        if self._pending is not None:
            sys.stderr.write(
                f"tpurun: resize signal ignored — resize to "
                f"{self._pending[0]} already in flight\n")
            return []
        if signum == signal.SIGUSR1:
            # Floor 2: a multi-process world cannot live-resize to a
            # single rank (the coordination plane needs >= 2; the
            # coordinator rejects target 1 with the -np 1 remedy).
            target = max(2, self.world // 2)
        else:
            cap = self.cap if self.cap is not None else self.initial_world
            # A grow signal must never shrink: a cap below the current
            # world (possible after operator RPC-driven grows) clamps the
            # grow to a no-op, not a downsize.
            target = max(self.world, min(max(cap, 1), self.world * 2))
        if target == self.world:
            sys.stderr.write(
                f"tpurun: resize signal is a no-op at world {self.world} "
                f"(shrink floor 2 / grow cap "
                f"{self.cap if self.cap is not None else self.initial_world}"
                f" — raise --max-np to grow further)\n")
            return []
        kind = "shrink" if target < self.world else "grow"
        sys.stderr.write(
            f"tpurun: {kind} signal — requesting live resize "
            f"{self.world} -> {target}\n")
        try:
            from ..coord.client import request_resize
            out = request_resize(self.coord_addr, target, timeout=5.0)
        except Exception as e:  # noqa: BLE001 — supervision must survive
            sys.stderr.write(
                f"tpurun: resize request failed ({e}); the world is "
                f"unchanged — retry once training is underway\n")
            return []
        return self._adopt(out.get("pending_target"), out.get("coord_port"),
                           out.get("generation"))

    def _adopt(self, target, port, gen) -> list:
        """Record a newly observed pending resize; returns the grow
        spawns (rank, generation, new-world coordinator address)."""
        if (not target or not port or gen is None
                or gen <= self._seen_gen or self._pending is not None):
            return []
        self._pending = (target, port, gen)
        host = self.coord_addr.partition(":")[0] or "127.0.0.1"
        sys.stderr.write(
            f"tpurun: live resize {self.world} -> {target} pending "
            f"(generation {gen}); supervising the re-form — no "
            f"restart\n")
        # Grow: spawn the missing ranks now, aimed at the NEW world's
        # coordinator; they come up while the old world quiesces.
        self._spawned = list(range(self.world, target))
        return [(r, target, gen, f"{host}:{port}")
                for r in range(self.world, target)]

    def target(self) -> int:
        """The world size being resized to (current world when idle)."""
        return self._pending[0] if self._pending else self.world

    def abandon(self, rc: int = 0) -> None:
        """The in-flight resize is dead (a rank failed, or the world
        finished first): keep the OLD world size — on a failure
        ``--restarts`` relaunches it and the quiesce recommit restores
        through the verified walk. Spawned-but-unjoined grow ranks are
        queued for reaping (:meth:`drain_reap`) so their connect-timeout
        exits cannot mislabel the run."""
        if self._pending is None:
            return
        target, _, gen = self._pending
        confirming = self._confirming
        self._pending = None
        self._confirming = False
        self._seen_gen = gen
        self._reap.extend(self._spawned)
        self._spawned = []
        if rc:
            sys.stderr.write(
                f"tpurun: live resize to {target} ABANDONED — a rank "
                f"died mid-resize (exit code {rc}); the world fails over "
                f"to the supervised-restart path (verified restore from "
                f"the quiesce recommit)\n")
        elif confirming:
            # The old plane went down and the job then finished before
            # the new coordinator could be probed: with a short enough
            # post-resize run the supervisor cannot tell "resized then
            # completed" from "completed before quiescing" — both are
            # clean ends; the ranks' own logs carry the truth.
            sys.stderr.write(
                f"tpurun: world exited while live resize to {target} "
                f"was in flight (job complete; no restart performed)\n")
        else:
            sys.stderr.write(
                f"tpurun: live resize to {target} abandoned — the world "
                f"exited before the quiesce boundary was reached\n")

    def retired(self, rank: int) -> bool:
        """Whether ``rank``'s clean exit is a shrink retirement (benign —
        reap and forget) rather than end-of-training."""
        return self.enabled and rank >= self.target()

    def poll(self, healthy: bool = True) -> list:
        """Advance the state machine; returns the grow spawns (usually
        empty). ``healthy`` is the supervision loop's view of the ranks
        that must SURVIVE the pending resize — an unreachable old
        coordinator only counts as "resize committed" while they are all
        alive; otherwise the world died mid-resize and ``--restarts``
        must relaunch the OLD world from the quiesce recommit."""
        if not self.enabled:
            return []
        now = time.monotonic()
        if now - self._last_poll < self.POLL_SECS and not self._confirming:
            # Confirming bypasses the poll gate: the re-formed world may
            # run only briefly (short jobs, drills) and the commit must be
            # observed inside that window.
            return []
        self._last_poll = now
        from ..coord.client import resize_status
        host = self.coord_addr.partition(":")[0] or "127.0.0.1"
        if self._pending is None:
            try:
                st = resize_status(self.coord_addr, timeout=2.0,
                                   supervisor=True)
            except Exception:  # noqa: BLE001 — not up yet / transitioning
                return []
            return self._adopt(st.get("pending_target"),
                               st.get("coord_port"), st.get("generation"))
        target, port, gen = self._pending
        if not healthy:
            self.abandon()
            return []
        if not self._confirming:
            try:
                resize_status(self.coord_addr, timeout=2.0,
                          supervisor=True)
                return []  # old plane still up: still quiescing
            except Exception:  # noqa: BLE001 — old coordinator gone
                # Either the ranks tore the old plane down to re-form, or
                # the job is exiting. Don't decide yet — confirm against
                # the NEW world's coordinator.
                self._confirming = True
                return []
        try:
            st = resize_status(f"{host}:{port}", timeout=2.0,
                               supervisor=True)
        except Exception:  # noqa: BLE001 — new world still forming
            return []
        if st.get("world") != target:
            return []  # not our coordinator (yet)
        # The NEW coordinator answers with the resized world: committed.
        self.world = target
        self.coord_addr = f"{host}:{port}"
        self._seen_gen = gen
        self._pending = None
        self._confirming = False
        self._spawned = []  # joiners are real world members now
        sys.stderr.write(
            f"tpurun: live resize to {target} committed "
            f"(coordinator now at {self.coord_addr}); surviving "
            f"ranks kept their processes — resize is not a restart\n")
        return []


def _fleet_poller(world: int, metrics_port: Optional[int],
                  interval: float, ranks=None):
    """Build the ``--metrics-summary`` fleet poller when a metrics base
    port is known (flag or inherited ``HVD_METRICS_PORT``); None
    otherwise. ``ranks`` restricts the scrape to this node's rank block
    on multi-host launches (remote ranks' listeners are not on this
    loopback). Imported lazily — the launcher must not pull the obs
    stack unless asked."""
    base = metrics_port
    if not base:
        try:
            base = int(os.environ.get("HVD_METRICS_PORT", "0") or 0)
        except ValueError:
            base = 0
    if not base:
        sys.stderr.write(
            "tpurun: --metrics-summary needs a metrics base port "
            "(--metrics-port or HVD_METRICS_PORT) — no fleet view\n")
        return None
    from ..obs.summary import FleetPoller
    return FleetPoller("127.0.0.1", base, world, timeout=max(
        0.2, min(2.0, interval / 2)), ranks=ranks)


def _launch_once(np_: int, command: List[str], *,
                 coord_port: Optional[int], jax_distributed: bool,
                 cpu: bool, node_rank: int, nnodes: int,
                 coordinator: Optional[str], extra_env: Optional[dict],
                 restart_epoch: int,
                 max_np: Optional[int] = None,
                 metrics_summary: bool = False,
                 metrics_port: Optional[int] = None,
                 metrics_interval: float = 10.0) -> "tuple[int, bool, int]":
    """One supervised world launch: spawn, watch ALL ranks, fail fast.

    The seed's wait loop blocked on workers in spawn order: rank 3 dying
    first went unnoticed until ranks 0-2 exited — which, pre-abort, they
    never did (the reference's dead-rank-hangs-MPI failure mode). Here the
    supervisor polls every worker; on the FIRST failure it tears the
    surviving siblings down (terminate → kill escalation) so the job exits
    nonzero within seconds, not never.

    Live resize (single-node env-worlds): SIGUSR1/SIGUSR2 on the launcher
    halve/double the world (spot-preemption-style shrink/grow), translated
    into the coordinator's admin RPC; the supervision loop also POLLS the
    coordinator's resize status, so an operator's direct
    ``request_resize`` RPC is honored too — on a grow the launcher spawns
    the missing ranks (wired to the NEW world's coordinator port), on a
    shrink it simply reaps the retiring ranks' clean exits. No process
    that survives a resize is ever torn down — resize is not a restart.
    Returns ``(rc, interrupted, final_world)`` so ``--restarts`` relaunches
    at the CURRENT world size.
    """
    world = nnodes * np_
    if coordinator:
        coord_host, _, cport = coordinator.partition(":")
        coord_addr = f"{coord_host}:{cport or 29521}"
        jd_addr = f"{coord_host}:{int(cport or 29521) + 1}"
    else:
        coord_addr = f"127.0.0.1:{coord_port or _free_port()}"
        jd_addr = f"127.0.0.1:{_free_port()}" if jax_distributed else None
    procs: dict = {}  # rank -> Popen (resize adds/retires entries)
    interrupted = {"sig": None}
    resize_sig = {"sig": None}

    def _forward(signum, frame):
        # Forward the launcher's own termination (Ctrl-C / SIGTERM from a
        # job scheduler) to every worker; the supervision loop then reaps
        # with the usual escalation.
        interrupted["sig"] = signum
        for p in procs.values():
            if p.poll() is None:
                try:
                    p.terminate()
                except OSError:
                    pass

    def _resize_signal(signum, frame):
        # Spot-preemption-style resize request; translated to the admin
        # RPC by the supervision loop (not here — a signal handler must
        # not do socket IO).
        resize_sig["sig"] = signum

    old_term = signal.signal(signal.SIGTERM, _forward)
    old_int = signal.signal(signal.SIGINT, _forward)
    old_usr1 = signal.signal(signal.SIGUSR1, _resize_signal)
    old_usr2 = signal.signal(signal.SIGUSR2, _resize_signal)
    fleet_stop = None   # set below; the finally must see it even when
    fleet_world = {"w": world}   # the spawn loop raises first

    def _rank_env(rank: int, cur_world: int, addr: str,
                  resize_generation: int = 0) -> dict:
        env = dict(os.environ)
        env.update(extra_env or {})
        if metrics_port:
            # Each rank's obs listener binds metrics_port + rank
            # (horovod_tpu.obs.http); the flag is the launcher-side
            # spelling of HVD_METRICS_PORT.
            env["HVD_METRICS_PORT"] = str(metrics_port)
        env["HVD_RANK"] = str(rank)
        env["HVD_SIZE"] = str(cur_world)
        env["HVD_LOCAL_RANK"] = str(
            rank % max(1, _chips_per_host() if not cpu else cur_world))
        env["HVD_COORD_ADDR"] = addr
        # Which (re)launch of the world this is; read by the elastic
        # recovery API and the fault injector's @epoch condition.
        env["HVD_RESTART_EPOCH"] = str(restart_epoch)
        if resize_generation:
            # Grow-spawned mid-resize: the rank joins the in-flight world
            # over the wire (elastic.resize_join) instead of restoring.
            env["HVD_RESIZE_GENERATION"] = str(resize_generation)
        if cpu:
            # CPU testing mode (reference CI: mpirun -np 2 on localhost
            # CPU-only, .travis.yml:84-91).
            env["JAX_PLATFORMS"] = "cpu"
        if jax_distributed:
            env["JAX_COORDINATOR_ADDRESS"] = jd_addr
            env["JAX_NUM_PROCESSES"] = str(cur_world)
            env["JAX_PROCESS_ID"] = str(rank)
        return env

    try:
        for local_rank in range(np_):
            rank = node_rank * np_ + local_rank
            env = _rank_env(rank, world, coord_addr)
            # Preserve the historical local_rank derivation for the
            # initial spawn (rank-block layout across nodes).
            env["HVD_LOCAL_RANK"] = str(
                local_rank % max(1, _chips_per_host() if not cpu else np_))
            procs[rank] = subprocess.Popen(command, env=env)

        # Supervision loop: any-order exit detection + resize supervision.
        resize = _ResizeSupervisor(
            coord_addr=coord_addr, world=world, cap=max_np,
            enabled=(nnodes == 1 and not jax_distributed))
        # --metrics-summary runs on its OWN daemon thread: a hung rank
        # listener (up to ranks × 2 s of blocking scrapes) must never
        # stall the 0.05 s fail-fast poll that tears dead worlds down.
        if metrics_summary:
            local_ranks = (None if nnodes == 1 else
                           range(node_rank * np_, (node_rank + 1) * np_))
            fleet = _fleet_poller(world, metrics_port, metrics_interval,
                                  ranks=local_ranks)
            if fleet is not None:
                import threading
                fleet_stop = threading.Event()

                def _fleet_loop():
                    fleet_stop.wait(min(metrics_interval, 2.0))
                    while not fleet_stop.is_set():
                        fleet.set_world(fleet_world["w"])
                        try:
                            sys.stderr.write(
                                f"tpurun: {fleet.line()}\n")
                        except Exception:  # noqa: BLE001 — telemetry
                            pass           # must never kill supervision
                        fleet_stop.wait(metrics_interval)

                threading.Thread(target=_fleet_loop, daemon=True,
                                 name="tpurun-fleet").start()
        rc = 0
        while True:
            running = 0
            for r, p in list(procs.items()):
                code = p.poll()
                if code is None:
                    running += 1
                elif code == 0 and resize.retired(r):
                    # A rank retiring at a shrink boundary: clean exit,
                    # remove from supervision (its rank index may be
                    # re-spawned by a later grow).
                    p.wait()
                    del procs[r]
                    sys.stderr.write(
                        f"tpurun: rank {r} retired (live shrink to "
                        f"{resize.target()})\n")
                elif code and not rc:
                    rc = code
            if rc:
                # A rank failed: if a resize was in flight it is dead too
                # — say so (and keep the OLD world size) before the
                # supervision loop exits into teardown/relaunch.
                resize.abandon(rc)
            if rc or not running or interrupted["sig"] is not None:
                break
            spawn = []
            if resize_sig["sig"] is not None:
                sig, resize_sig["sig"] = resize_sig["sig"], None
                spawn.extend(resize.signal(sig))
            # Ranks that must survive the resize (all of them when idle):
            # their death turns "old coordinator unreachable" from
            # "resize committed" into "world failed mid-resize". (rc is
            # always 0 here — a nonzero rc abandons and breaks above —
            # this covers a death the scan has not coded yet.)
            healthy = all(
                p.poll() is None for r, p in procs.items()
                if r < resize.target())
            spawn.extend(resize.poll(healthy=healthy))
            for rank, target, gen, addr in spawn:
                sys.stderr.write(
                    f"tpurun: live grow — spawning rank {rank} into world "
                    f"{target} (generation {gen}, coordinator "
                    f"{addr})\n")
                procs[rank] = subprocess.Popen(
                    command, env=_rank_env(rank, target, addr,
                                           resize_generation=gen))
            for r in resize.drain_reap():
                # Spawned for a resize that was abandoned: never joined a
                # world, so terminate and forget — their connect-timeout
                # exit must not read as a job failure.
                p = procs.pop(r, None)
                if p is not None:
                    _reap([p])
            world = resize.world
            fleet_world["w"] = world
            time.sleep(0.05)
        if rc and running:
            # Let the world's own abort cascade surface the diagnosis
            # (WorkerFailureError naming the dead rank) before tearing the
            # survivors down.
            deadline = time.monotonic() + FAILFAST_GRACE_SECS
            while time.monotonic() < deadline and any(
                    p.poll() is None for p in procs.values()):
                time.sleep(0.05)
            running = sum(1 for p in procs.values() if p.poll() is None)
            if running:
                sys.stderr.write(
                    f"tpurun: a worker exited with code {rc}; terminating "
                    f"{running} surviving rank(s)\n")
        _reap(list(procs.values()))
        if not rc:
            for p in procs.values():
                if p.returncode and not rc:
                    rc = p.returncode
        if interrupted["sig"] is not None and not rc:
            rc = 128 + int(interrupted["sig"])
        # The interruption flag travels alongside rc: an operator's Ctrl-C
        # / scheduler SIGTERM must never be mistaken for a worker failure
        # (which --restarts would relaunch). The final PER-NODE rank count
        # travels too: a crash AFTER a live resize relaunches at the
        # resized world, not the original one. Resize is single-node only,
        # so on multi-node launches this is always the original np_ —
        # returning the GLOBAL world there would multiply the world on
        # every restart (launch() feeds it back as the next epoch's
        # per-node count).
        return rc, interrupted["sig"] is not None, \
            (world if nnodes == 1 else np_)
    finally:
        if fleet_stop is not None:
            fleet_stop.set()
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)
        signal.signal(signal.SIGUSR1, old_usr1)
        signal.signal(signal.SIGUSR2, old_usr2)
        _reap(list(procs.values()))


def launch(np_: int, command: List[str], *, coord_port: Optional[int] = None,
           jax_distributed: bool = False, cpu: bool = False,
           node_rank: int = 0, nnodes: int = 1,
           coordinator: Optional[str] = None,
           extra_env: Optional[dict] = None,
           restarts: int = 0,
           max_np: Optional[int] = None,
           metrics_summary: bool = False,
           metrics_port: Optional[int] = None,
           metrics_interval: float = 10.0) -> int:
    """Spawn ``np_`` local ranks of ``command`` with the world env wired up.

    Multi-host: run tpurun on every host with the same ``--coordinator
    host0:port`` and ``--nnodes N``, giving each host its ``--node-rank``
    (the role of ``mpirun -H host1:4,host2:4``, reference
    ``docs/running.md:15-45``). World size = nnodes · np_; this host's ranks
    are ``node_rank·np_ .. node_rank·np_+np_-1``.

    Fault tolerance: every launch is supervised — the first failing rank
    tears down its siblings so a dead rank can never hang the job (the
    reference's MPI world does exactly that). With ``restarts > 0`` a
    failed world is relaunched up to ``restarts`` times on a FRESH
    coordinator port (the dead coordinator's socket may linger in
    TIME_WAIT) with exponential backoff, exporting ``HVD_RESTART_EPOCH``
    so workers resume from their last committed
    :class:`horovod_tpu.elastic.ElasticState` — the Elastic-Horovod role.

    Returns the first nonzero exit code (0 if all succeeded).
    """
    import random
    rc = 0
    np_cur = np_
    # Restart backoff: exponential base, CAPPED (HVD_RESTART_BACKOFF_MAX
    # seconds, default 30 — under repeated preemption an unbounded 2^n
    # sleep quickly dwarfs the restart it delays) and JITTERED ±50% so a
    # fleet of preempted jobs does not relaunch in lockstep against the
    # same scheduler. The chosen delay is logged.
    try:
        backoff_cap = float(os.environ.get("HVD_RESTART_BACKOFF_MAX",
                                           "30") or 30)
    except ValueError:
        backoff_cap = 30.0
    backoff_cap = max(0.0, backoff_cap)
    for epoch in range(restarts + 1):
        # Restart on a fresh port: the explicit multi-host --coordinator
        # address is pinned by the operator (every host must agree), but a
        # local auto-picked port is never reused across epochs.
        rc, interrupted, np_cur = _launch_once(
            np_cur, command,
            coord_port=coord_port if epoch == 0 else None,
            jax_distributed=jax_distributed, cpu=cpu, node_rank=node_rank,
            nnodes=nnodes, coordinator=coordinator, extra_env=extra_env,
            restart_epoch=epoch, max_np=max_np,
            metrics_summary=metrics_summary, metrics_port=metrics_port,
            metrics_interval=metrics_interval)
        if interrupted:
            # Operator interruption (Ctrl-C / scheduler SIGTERM) is a
            # command to STOP, not a failure to retry — never relaunch.
            break
        if rc == 0 or epoch == restarts:
            break
        base = min(1.0 * (2 ** epoch), backoff_cap)
        backoff = min(backoff_cap, base * random.uniform(0.5, 1.5))
        sys.stderr.write(
            f"tpurun: world failed with exit code {rc} (restart epoch "
            f"{epoch}); relaunching {np_cur} rank(s) in {backoff:.1f}s "
            f"(base {base:.1f}s, jitter ±50%, cap {backoff_cap:.0f}s; "
            f"{restarts - epoch} restart(s) left)\n")
        time.sleep(backoff)
    return rc


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="tpurun",
        description="Launch N ranks of a training script on this host "
                    "(mpirun replacement; see docs/running.md parity).")
    parser.add_argument("-np", type=int, required=True,
                        help="number of ranks (processes) to spawn")
    parser.add_argument("--cpu", action="store_true",
                        help="force JAX CPU backend in ranks (CI/testing)")
    parser.add_argument("--jax-distributed", action="store_true",
                        help="also form a jax.distributed world so compiled "
                             "collectives span processes")
    parser.add_argument("--coord-port", type=int, default=None)
    parser.add_argument("--node-rank", type=int, default=0,
                        help="this host's index among --nnodes hosts")
    parser.add_argument("--nnodes", type=int, default=1,
                        help="total hosts in the job (world = nnodes * np)")
    parser.add_argument("--coordinator", default=None,
                        help="host0:port rendezvous shared by all hosts "
                             "(required when nnodes > 1)")
    parser.add_argument("--restarts", type=int, default=0,
                        help="relaunch the whole world up to N times after "
                             "a failure (fresh coordinator port, capped + "
                             "jittered exponential backoff "
                             "[HVD_RESTART_BACKOFF_MAX], HVD_RESTART_EPOCH "
                             "exported); pair with "
                             "horovod_tpu.elastic.run_with_recovery to "
                             "resume from the last committed state")
    parser.add_argument("--max-np", type=int, default=None,
                        help="grow ceiling for live resize: SIGUSR2 "
                             "doubles the world up to this many ranks "
                             "(default: the initial -np). A direct admin "
                             "RPC (coord.client.request_resize) is not "
                             "capped — the operator named an exact size")
    parser.add_argument("--metrics-port", type=int, default=None,
                        help="base port of the per-rank /metrics "
                             "listeners: rank r serves on port+r "
                             "(exports HVD_METRICS_PORT to every rank; "
                             "see docs/observability.md)")
    parser.add_argument("--metrics-summary", action="store_true",
                        help="scrape every rank's /metrics and print one "
                             "aggregated fleet line. With a command: "
                             "every --metrics-interval seconds while "
                             "supervising. WITHOUT a command: one shot "
                             "against an already-running job's ranks, "
                             "then exit (needs -np + --metrics-port or "
                             "HVD_METRICS_PORT). Pointed at a serving "
                             "fleet's /metrics port (-np 1), prints the "
                             "replica-centric fleet line instead")
    parser.add_argument("--metrics-interval", type=float, default=10.0,
                        help="seconds between fleet lines under "
                             "--metrics-summary (default 10)")
    parser.add_argument("command", nargs=argparse.REMAINDER,
                        help="the command to run, e.g. python train.py")
    args = parser.parse_args(argv)
    if args.metrics_interval <= 0:
        parser.error("--metrics-interval must be > 0")
    if args.metrics_summary and not args.command:
        # One-shot fleet view of a job launched elsewhere: scrape the N
        # rank listeners once, print the line, exit 0 when any rank
        # answered (an all-dead fleet is worth a nonzero exit — the
        # operator asked "how is it doing" and the answer is "it isn't").
        fleet = _fleet_poller(args.np, args.metrics_port,
                              args.metrics_interval)
        if fleet is None:
            return 2
        line = fleet.line()
        print(f"tpurun: {line}", flush=True)
        # Structured verdict, not prose-parsing: exit 1 only when NO
        # training rank answered. A serving-fleet scrape that answered
        # is a live endpoint whatever its replica count says — exit 0.
        return 0 if (fleet.last_mode == "serving"
                     or fleet.last_up > 0) else 1
    if not args.command:
        parser.error("no command given")
    if args.nnodes > 1 and not args.coordinator:
        parser.error("--nnodes > 1 requires --coordinator host0:port")
    if args.restarts < 0:
        parser.error("--restarts must be >= 0")
    if args.max_np is not None and args.max_np < args.np:
        parser.error("--max-np must be >= -np (it is the grow ceiling)")
    return launch(args.np, args.command, coord_port=args.coord_port,
                  jax_distributed=args.jax_distributed, cpu=args.cpu,
                  node_rank=args.node_rank, nnodes=args.nnodes,
                  coordinator=args.coordinator, restarts=args.restarts,
                  max_np=args.max_np,
                  metrics_summary=args.metrics_summary,
                  metrics_port=args.metrics_port,
                  metrics_interval=args.metrics_interval)


if __name__ == "__main__":
    sys.exit(main())
