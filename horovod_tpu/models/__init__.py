"""Model families matching the reference's example workloads
(``examples/``: MNIST CNNs, CIFAR ResNet v1/v2, ImageNet ResNet-50,
skip-gram word2vec), implemented as flax.linen modules designed for the MXU
(bfloat16 activations, static shapes, XLA-fusable blocks)."""

from .mnist import MnistCNN  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet,
    BasicBlock,
    BottleneckBlock,
    PreActBlock,
    cifar_resnet_v1,
    cifar_resnet_v2,
    resnet50,
    resnet101,
)
from .word2vec import SkipGram, embedding_grads_as_slices  # noqa: F401
