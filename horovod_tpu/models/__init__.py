"""Model families matching the reference's example workloads and benchmark
table (``examples/``: MNIST CNNs, CIFAR ResNet v1/v2, ImageNet ResNet-50,
skip-gram word2vec; ``docs/benchmarks.md``: Inception V3, ResNet-101,
VGG-16), implemented as flax.linen modules designed for the MXU (bfloat16
activations, static shapes, XLA-fusable blocks)."""

from .mnist import MnistCNN  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet,
    BasicBlock,
    BottleneckBlock,
    PreActBlock,
    cifar_resnet_v1,
    cifar_resnet_v2,
    resnet50,
    resnet101,
)
from .vgg import VGG, vgg16, vgg19  # noqa: F401
from .inception import InceptionV3, inception_v3  # noqa: F401
from .word2vec import SkipGram, embedding_grads_as_slices  # noqa: F401
