"""Inception V3 — the reference's headline benchmark model.

Reference parity: Inception V3 leads the reference's 128-GPU scaling table
(90% efficiency — ``README.md:21-26``, ``docs/benchmarks.md:5-6``),
benchmarked via ``tf_cnn_benchmarks --model inception3``. Architecture per
Szegedy et al. (arXiv:1512.00567) as realized by tf.slim's ``inception_v3``
(the implementation tf_cnn_benchmarks used): BN after every conv, factorized
7×7 branches in the 17×17 stages, expanded 3×3 splits in the 8×8 stages.

TPU-native design: flax module, bf16 activations / f32 params; the many
small parallel branches are exactly the fusion-friendly graph XLA schedules
well on TPU.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class ConvBN(nn.Module):
    """conv → BN → relu, the slim ``conv2d`` unit of inception_v3."""

    features: int
    kernel: Tuple[int, int] = (1, 1)
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.features, self.kernel, self.strides,
                    padding=self.padding, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-3, dtype=self.dtype)(x)
        return nn.relu(x)


class InceptionA(nn.Module):
    """35×35 mixed block (slim Mixed_5b/5c/5d)."""

    pool_features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = functools.partial(ConvBN, dtype=self.dtype)
        b1 = cbn(64)(x, train)
        b5 = cbn(48)(x, train)
        b5 = cbn(64, (5, 5))(b5, train)
        b3 = cbn(64)(x, train)
        b3 = cbn(96, (3, 3))(b3, train)
        b3 = cbn(96, (3, 3))(b3, train)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        bp = cbn(self.pool_features)(bp, train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class ReductionA(nn.Module):
    """35→17 grid reduction (slim Mixed_6a)."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = functools.partial(ConvBN, dtype=self.dtype)
        b3 = cbn(384, (3, 3), (2, 2), padding="VALID")(x, train)
        bd = cbn(64)(x, train)
        bd = cbn(96, (3, 3))(bd, train)
        bd = cbn(96, (3, 3), (2, 2), padding="VALID")(bd, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionB(nn.Module):
    """17×17 mixed block with factorized 7×7 (slim Mixed_6b..6e)."""

    channels_7x7: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = functools.partial(ConvBN, dtype=self.dtype)
        c = self.channels_7x7
        b1 = cbn(192)(x, train)
        b7 = cbn(c)(x, train)
        b7 = cbn(c, (1, 7))(b7, train)
        b7 = cbn(192, (7, 1))(b7, train)
        bd = cbn(c)(x, train)
        bd = cbn(c, (7, 1))(bd, train)
        bd = cbn(c, (1, 7))(bd, train)
        bd = cbn(c, (7, 1))(bd, train)
        bd = cbn(192, (1, 7))(bd, train)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        bp = cbn(192)(bp, train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class ReductionB(nn.Module):
    """17→8 grid reduction (slim Mixed_7a)."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = functools.partial(ConvBN, dtype=self.dtype)
        b3 = cbn(192)(x, train)
        b3 = cbn(320, (3, 3), (2, 2), padding="VALID")(b3, train)
        b7 = cbn(192)(x, train)
        b7 = cbn(192, (1, 7))(b7, train)
        b7 = cbn(192, (7, 1))(b7, train)
        b7 = cbn(192, (3, 3), (2, 2), padding="VALID")(b7, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionC(nn.Module):
    """8×8 mixed block with expanded 3×3 splits (slim Mixed_7b/7c)."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = functools.partial(ConvBN, dtype=self.dtype)
        b1 = cbn(320)(x, train)
        b3 = cbn(384)(x, train)
        b3 = jnp.concatenate([cbn(384, (1, 3))(b3, train),
                              cbn(384, (3, 1))(b3, train)], axis=-1)
        bd = cbn(448)(x, train)
        bd = cbn(384, (3, 3))(bd, train)
        bd = jnp.concatenate([cbn(384, (1, 3))(bd, train),
                              cbn(384, (3, 1))(bd, train)], axis=-1)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding="SAME")
        bp = cbn(192)(bp, train)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class InceptionV3(nn.Module):
    """Inception V3 for 299×299 inputs (224 also works — global pool)."""

    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    dropout_rate: float = 0.2

    @nn.compact
    def __call__(self, x, train: bool = True):
        cbn = functools.partial(ConvBN, dtype=self.dtype)
        x = x.astype(self.dtype)
        # Stem (slim Conv2d_1a..MaxPool_5a).
        x = cbn(32, (3, 3), (2, 2), padding="VALID")(x, train)
        x = cbn(32, (3, 3), padding="VALID")(x, train)
        x = cbn(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = cbn(80)(x, train)
        x = cbn(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        # 35×35.
        x = InceptionA(32, dtype=self.dtype)(x, train)
        x = InceptionA(64, dtype=self.dtype)(x, train)
        x = InceptionA(64, dtype=self.dtype)(x, train)
        x = ReductionA(dtype=self.dtype)(x, train)
        # 17×17.
        x = InceptionB(128, dtype=self.dtype)(x, train)
        x = InceptionB(160, dtype=self.dtype)(x, train)
        x = InceptionB(160, dtype=self.dtype)(x, train)
        x = InceptionB(192, dtype=self.dtype)(x, train)
        x = ReductionB(dtype=self.dtype)(x, train)
        # 8×8.
        x = InceptionC(dtype=self.dtype)(x, train)
        x = InceptionC(dtype=self.dtype)(x, train)
        # Head.
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


def inception_v3(num_classes: int = 1000, **kw) -> InceptionV3:
    """Inception V3 (reference headline model, ``docs/benchmarks.md:5-6``)."""
    return InceptionV3(num_classes=num_classes, **kw)
