"""VGG — the reference's third benchmark family.

Reference parity: VGG-16 is one of the three models in the reference's
headline 128-GPU scaling table (79% efficiency — ``README.md:26``,
``docs/benchmarks.md:6``), benchmarked via ``tf_cnn_benchmarks
--model vgg16``. VGG's huge dense head (~120M of its ~138M params) is what
drags its allreduce scaling below the convnets' 90% — which makes it the
stress model for gradient-fusion bandwidth.

TPU-native design: flax module, bf16 activations / f32 params like the
ResNets; the conv stacks are plain 3×3/SAME chains XLA tiles onto the MXU.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# Convs per stage (filters double per stage up to 512).
_CFG = {
    11: (1, 1, 2, 2, 2),
    13: (2, 2, 2, 2, 2),
    16: (2, 2, 3, 3, 3),
    19: (2, 2, 4, 4, 4),
}


class VGG(nn.Module):
    """VGG-D family (11/13/16/19 layers) for 224×224 inputs."""

    depth: int = 16
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    dense_features: Sequence[int] = (4096, 4096)
    dropout_rate: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.depth not in _CFG:
            raise ValueError(
                f"VGG depth must be one of {sorted(_CFG)}; got {self.depth}")
        conv = functools.partial(nn.Conv, kernel_size=(3, 3), padding="SAME",
                                 dtype=self.dtype)
        x = x.astype(self.dtype)
        filters = 64
        for stage, n_convs in enumerate(_CFG[self.depth]):
            for i in range(n_convs):
                x = nn.relu(conv(min(filters, 512),
                                 name=f"conv{stage + 1}_{i + 1}")(x))
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
            filters *= 2
        x = x.reshape((x.shape[0], -1))
        for i, feats in enumerate(self.dense_features):
            x = nn.relu(nn.Dense(feats, dtype=self.dtype,
                                 name=f"fc{i + 6}")(x))
            x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        # Final logits in float32 for a numerically stable softmax/loss.
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


def vgg16(num_classes: int = 1000, **kw) -> VGG:
    """VGG-16 (reference benchmark model, ``docs/benchmarks.md:6``)."""
    return VGG(depth=16, num_classes=num_classes, **kw)


def vgg19(num_classes: int = 1000, **kw) -> VGG:
    return VGG(depth=19, num_classes=num_classes, **kw)
