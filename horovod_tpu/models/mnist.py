"""MNIST conv net — the reference's smallest end-to-end workload.

Parity: the conv net in ``examples/tensorflow_mnist.py:29-54`` (two 5x5 conv
+ pool stages, 1024-unit dense, 10-way logits) and ``examples/keras_mnist.py``
(3x3 convs, dropout). One model serves both example families.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class MnistCNN(nn.Module):
    """Conv net matching ``tensorflow_mnist.py``'s ``conv_model``."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        # Accepts [B, 784] or [B, 28, 28, 1].
        if x.ndim == 2:
            x = x.reshape((-1, 28, 28, 1))
        x = x.astype(self.dtype)
        x = nn.Conv(32, (5, 5), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), padding="SAME")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(1024)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x
