"""ResNet model family — CIFAR ResNet v1/v2 (20/56/110) and ImageNet ResNet-50.

Reference parity
----------------
* ``examples/keras-cifar10-resnet.py`` builds ResNet v1 (6n+2) and v2 (9n+2)
  for CIFAR-10 (``keras-cifar10-resnet.py:52-63`` documents the accuracy
  table: 20v1 92.16%, 56v1 92.71%, 110v1 92.65%, 56v2 93.01%, 110v2 93.15%).
* ``examples/keras_imagenet_resnet50.py`` trains stock Keras ResNet-50 with
  the Goyal et al. recipe (``keras_imagenet_resnet50.py:32-37, 113-122``).

TPU-native design
-----------------
flax.linen modules with a ``dtype`` knob (bfloat16 activations by default on
TPU — the MXU's native input type; params stay float32). Convs and matmuls
are left to XLA to tile onto the MXU; BatchNorm uses a mutable ``batch_stats``
collection, and under data parallelism the running stats are synchronized with
a cross-replica mean via ``axis_name`` (the modern equivalent of what the
reference delegates to per-replica Keras BN plus weight broadcast).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    """ResNet v1 basic block: conv-bn-relu, conv-bn, add, relu."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides, padding="SAME")(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), padding="SAME")(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="shortcut")(residual)
            residual = self.norm(name="shortcut_bn")(residual)
        return self.act(y + residual)


class BottleneckBlock(nn.Module):
    """ResNet v1 bottleneck (1x1 -> 3x3 -> 1x1 x4), used by ResNet-50."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides, padding="SAME")(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale so each block starts as identity
        # (Goyal et al. trick used by the reference recipe's lineage).
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="shortcut")(residual)
            residual = self.norm(name="shortcut_bn")(residual)
        return self.act(y + residual)


class PreActBlock(nn.Module):
    """ResNet v2 pre-activation bottleneck (bn-relu-conv ordering),
    the ``resnet_v2`` of ``keras-cifar10-resnet.py``."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        y = self.norm()(x)
        y = self.act(y)
        residual = x
        if self.strides != (1, 1) or x.shape[-1] != self.filters * 4:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="shortcut")(y)
        y = self.conv(self.filters, (1, 1))(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides, padding="SAME")(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        return y + residual


class ResNet(nn.Module):
    """Generic ResNet.

    ``stage_sizes`` counts blocks per stage; ``block_cls`` picks the block
    flavor. ``cifar_stem=True`` uses the 3x3/stride-1 stem (CIFAR, 32x32
    inputs); otherwise the 7x7/stride-2 + maxpool ImageNet stem.
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 10
    num_filters: int = 64
    cifar_stem: bool = False
    # Space-to-depth stem (the standard TPU ResNet optimization, e.g.
    # MLPerf ResNet-50 submissions): fold 2x2 image patches into channels
    # ([N,H,W,3] -> [N,H/2,W/2,12]) and replace the 7x7/stride-2 stem conv
    # with an equivalent-receptive-field 4x4/stride-1 conv. A 3-channel
    # stride-2 conv uses ~2% of the MXU's 128 input lanes and dominates
    # like 15-20% of step time; the s2d form quadruples channel depth and
    # removes the stride. Same downstream network; trains from scratch
    # like the original (the 4x4x12 kernel is the zero-padded 8x8x3
    # reparametrization of the 7x7x3 one).
    stem_space_to_depth: bool = False
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype,
            axis_name=self.axis_name if train else None)

        x = x.astype(self.dtype)
        if self.cifar_stem:
            x = conv(self.num_filters, (3, 3), padding="SAME", name="stem")(x)
        else:
            if self.stem_space_to_depth:
                n, h, w, c = x.shape
                if h % 2 or w % 2:
                    raise ValueError(
                        f"stem_space_to_depth folds 2x2 patches and needs "
                        f"even spatial dims; got {h}x{w} (pad or resize "
                        f"the input, or use the standard stem)")
                x = x.reshape(n, h // 2, 2, w // 2, 2, c)
                x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2,
                                                          4 * c)
                x = conv(self.num_filters, (4, 4), (1, 1),
                         padding=[(2, 1), (2, 1)], name="stem_s2d")(x)
            else:
                x = conv(self.num_filters, (7, 7), (2, 2),
                         padding=[(3, 3), (3, 3)], name="stem")(x)
            x = norm(name="stem_bn")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        if self.cifar_stem and self.block_cls is not PreActBlock:
            x = norm(name="stem_bn")(x)
            x = nn.relu(x)

        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = self.block_cls(
                    self.num_filters * 2 ** i, strides=strides,
                    conv=conv, norm=norm)(x)

        if self.block_cls is PreActBlock:
            x = norm(name="final_bn")(x)
            x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        # Final logits in float32 for numerically stable softmax/loss.
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


# ---------------------------------------------------------------------------
# CIFAR ResNet v1 (6n+2) / v2 (9n+2) — keras-cifar10-resnet.py parity.
# depth 20 -> n=3, 56 -> n=9, 110 -> n=18 (v1); v2 uses 9n+2.
# ---------------------------------------------------------------------------

def cifar_resnet_v1(depth: int = 20, num_classes: int = 10, **kw) -> ResNet:
    """ResNet v1 for CIFAR (``keras-cifar10-resnet.py`` resnet_v1,
    depth = 6n+2 ∈ {20, 56, 110})."""
    if (depth - 2) % 6 != 0:
        raise ValueError("v1 depth must be 6n+2 (e.g. 20, 56, 110)")
    n = (depth - 2) // 6
    return ResNet(stage_sizes=[n, n, n], block_cls=BasicBlock,
                  num_classes=num_classes, num_filters=16, cifar_stem=True,
                  **kw)


def cifar_resnet_v2(depth: int = 56, num_classes: int = 10, **kw) -> ResNet:
    """ResNet v2 (pre-activation) for CIFAR (``keras-cifar10-resnet.py``
    resnet_v2, depth = 9n+2 ∈ {56, 110})."""
    if (depth - 2) % 9 != 0:
        raise ValueError("v2 depth must be 9n+2 (e.g. 56, 110)")
    n = (depth - 2) // 9
    return ResNet(stage_sizes=[n, n, n], block_cls=PreActBlock,
                  num_classes=num_classes, num_filters=16, cifar_stem=True,
                  **kw)


def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    """ImageNet ResNet-50 — the reference's north-star workload
    (``keras_imagenet_resnet50.py``)."""
    return ResNet(stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock,
                  num_classes=num_classes, num_filters=64, **kw)


def resnet101(num_classes: int = 1000, **kw) -> ResNet:
    """ResNet-101 (the reference's benchmark model, ``docs/benchmarks.md``)."""
    return ResNet(stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock,
                  num_classes=num_classes, num_filters=64, **kw)
