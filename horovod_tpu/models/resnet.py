"""ResNet model family — CIFAR ResNet v1/v2 (20/56/110) and ImageNet ResNet-50.

Reference parity
----------------
* ``examples/keras-cifar10-resnet.py`` builds ResNet v1 (6n+2) and v2 (9n+2)
  for CIFAR-10 (``keras-cifar10-resnet.py:52-63`` documents the accuracy
  table: 20v1 92.16%, 56v1 92.71%, 110v1 92.65%, 56v2 93.01%, 110v2 93.15%).
* ``examples/keras_imagenet_resnet50.py`` trains stock Keras ResNet-50 with
  the Goyal et al. recipe (``keras_imagenet_resnet50.py:32-37, 113-122``).

TPU-native design
-----------------
flax.linen modules with a ``dtype`` knob (bfloat16 activations by default on
TPU — the MXU's native input type; params stay float32). Convs and matmuls
are left to XLA to tile onto the MXU; BatchNorm uses a mutable ``batch_stats``
collection, and under data parallelism the running stats are synchronized with
a cross-replica mean via ``axis_name`` (the modern equivalent of what the
reference delegates to per-replica Keras BN plus weight broadcast).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops import pallas_conv

ModuleDef = Any


class _FusedConv1x1(nn.Module):
    """1x1 conv via the fused Pallas kernel (``ops/pallas_conv.py``).

    Owns the SAME variable tree as ``nn.Conv(features, (1,1),
    use_bias=False)`` — params/{name}/kernel [1,1,Cin,Cout] — so a model
    built with ``conv_backend="fused"`` is checkpoint- and
    param-compatible with the stock XLA path (the knob is purely a
    performance choice).

    Returns ``(y, s1, s2, count)``: the conv output plus its streamed
    per-channel sum / sum-of-squares and the row count, feeding the
    consumer :class:`_FoldedBN` without a separate stats pass over y.
    """

    features: int
    dtype: Any = jnp.bfloat16
    kernel_init: Callable = nn.initializers.lecun_normal()

    @nn.compact
    def __call__(self, x, ab=None, relu_prologue: bool = True):
        n, h, w, c = x.shape
        kernel = self.param("kernel", self.kernel_init,
                            (1, 1, c, self.features), jnp.float32)
        x2 = x.reshape(-1, c).astype(self.dtype)
        y, s1, s2 = pallas_conv.fused_linear_bn_act(
            x2, kernel.reshape(c, self.features), ab, relu=relu_prologue)
        return (y.reshape(n, h, w, self.features), s1, s2, x2.shape[0])


class _FoldedBN(nn.Module):
    """BatchNorm as a folded per-channel affine ``a*y + b``.

    Owns the SAME variables as ``nn.BatchNorm`` (params scale/bias,
    batch_stats mean/var — float32, momentum/epsilon semantics matching
    flax: biased variance, running update ``m*ra + (1-m)*batch``) but
    instead of materializing the normalized tensor it returns ``(a, b)``
    with ``a = scale*rsqrt(var+eps)``, ``b = bias - mean*a`` for the
    consumer to fuse (a Pallas prologue or an XLA elementwise chain).

    Batch statistics come either from a producer kernel's streamed moments
    (``s1``/``s2``/``count``) or from a raw tensor ``x`` (one XLA
    reduction pass — used after the 3x3 conv, whose output the fused 1x1
    consumer reads anyway).
    """

    use_running_average: bool = False
    momentum: float = 0.9
    epsilon: float = 1e-5
    axis_name: Optional[str] = None
    scale_init: Callable = nn.initializers.ones

    @nn.compact
    def __call__(self, s1=None, s2=None, count=None, x=None):
        if x is not None:
            xf = x.astype(jnp.float32)
            axes = tuple(range(x.ndim - 1))
            mean = jnp.mean(xf, axis=axes)
            mean2 = jnp.mean(xf * xf, axis=axes)
        else:
            mean = s1[0] / count
            mean2 = s2[0] / count
        features = mean.shape[-1]
        scale = self.param("scale", self.scale_init, (features,),
                           jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (features,),
                          jnp.float32)
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros((features,), jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones((features,), jnp.float32))
        if self.use_running_average:
            mu, var = ra_mean.value, ra_var.value
        else:
            if self.axis_name is not None:
                mean = jax.lax.pmean(mean, self.axis_name)
                mean2 = jax.lax.pmean(mean2, self.axis_name)
            mu = mean
            var = mean2 - mu * mu
            if not self.is_initializing():
                m = self.momentum
                ra_mean.value = m * ra_mean.value + (1 - m) * mu
                ra_var.value = m * ra_var.value + (1 - m) * var
        a = scale * jax.lax.rsqrt(var + self.epsilon)
        b = bias - mu * a
        return a, b


class BasicBlock(nn.Module):
    """ResNet v1 basic block: conv-bn-relu, conv-bn, add, relu."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides, padding="SAME")(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), padding="SAME")(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="shortcut")(residual)
            residual = self.norm(name="shortcut_bn")(residual)
        return self.act(y + residual)


class BottleneckBlock(nn.Module):
    """ResNet v1 bottleneck (1x1 -> 3x3 -> 1x1 x4), used by ResNet-50.

    ``fused=True`` routes the training-mode 1x1 convs through the fused
    Pallas conv+BN+ReLU kernel (``ops/pallas_conv.py``) so the stage's
    activation maps make two HBM transits per conv instead of four —
    the traffic-reduction lever the measured ResNet-50 roofline identifies
    (``docs/benchmarks.md``). The fused branch declares the SAME variable
    tree as the stock branch (explicit ``name=`` scopes), so params and
    checkpoints are interchangeable between backends; eval mode and
    non-tilable shapes always use the stock XLA branch.
    """

    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu
    fused: bool = False
    # Which of the block's 1x1 convs route through the fused kernel — a
    # measurement sub-knob (per-conv-site attribution of the pallas
    # -boundary tax, docs/benchmarks.md r5). A module attribute rather
    # than a trace-time env read so it participates in jit cache keys
    # and cannot silently diverge across ranks (ADVICE r5); bench.py
    # maps the HVD_FUSED_PARTS env sweep onto it at model construction.
    fused_parts: Tuple[str, ...] = ("reduce", "expand", "shortcut")

    def _fuse_settings(self):
        """The conv/norm configuration when the fused branch applies, else
        None (custom conv/norm/act flavors keep the stock semantics)."""
        conv_kw = getattr(self.conv, "keywords", None)
        norm_kw = getattr(self.norm, "keywords", None)
        if (getattr(self.conv, "func", None) is not nn.Conv
                or getattr(self.norm, "func", None) is not nn.BatchNorm):
            return None
        if conv_kw.get("use_bias", True) or self.act is not nn.relu:
            return None
        if norm_kw.get("use_running_average", False):
            return None  # eval: BN folds to a constant affine, XLA fuses it
        # Overrides the fused modules do not replicate (f32 params,
        # lecun_normal kernels, fast-variance f32 stats) must fall back to
        # the stock branch rather than silently diverge from it.
        if any(k in conv_kw for k in
               ("param_dtype", "kernel_init", "precision")):
            return None
        if any(k in norm_kw for k in
               ("param_dtype", "scale_init", "bias_init")) \
                or not norm_kw.get("use_fast_variance", True):
            return None
        return dict(dtype=conv_kw.get("dtype", jnp.float32),
                    momentum=norm_kw.get("momentum", 0.99),
                    epsilon=norm_kw.get("epsilon", 1e-5),
                    axis_name=norm_kw.get("axis_name"))

    def _fused_call(self, x, st):
        parts = self.fused_parts
        dtype = st["dtype"]
        bn = functools.partial(
            _FoldedBN, use_running_average=False, momentum=st["momentum"],
            epsilon=st["epsilon"], axis_name=st["axis_name"])
        f = self.filters
        # 1x1 reduce: raw input in, stats epilogue out.
        if "reduce" in parts:
            y, s1, s2, cnt = _FusedConv1x1(f, dtype=dtype,
                                           name="Conv_0")(x)
            a1, b1 = bn(name="BatchNorm_0")(s1, s2, cnt)
        else:
            y = self.conv(f, (1, 1), name="Conv_0")(x)
            a1, b1 = bn(name="BatchNorm_0")(x=y)
        z = nn.relu(a1 * y.astype(jnp.float32) + b1).astype(dtype)
        # 3x3 (carries the stride): XLA's conv — compute-bound at these
        # shapes, not worth a hand kernel; its BN stats are one XLA
        # reduction pass, folded into the next conv's prologue.
        y = self.conv(f, (3, 3), self.strides, padding="SAME",
                      name="Conv_1")(z)
        a2, b2 = bn(name="BatchNorm_1")(x=y)
        # 1x1 expand: BN+ReLU prologue (never materializes relu(bn(y))),
        # stats epilogue (never re-reads the 4f-channel output).
        if "expand" in parts:
            y, s1, s2, cnt = _FusedConv1x1(4 * f, dtype=dtype,
                                           name="Conv_2")(
                y, jnp.stack([a2, b2]))
            a3, b3 = bn(name="BatchNorm_2",
                        scale_init=nn.initializers.zeros)(s1, s2, cnt)
        else:
            z2 = nn.relu(a2 * y.astype(jnp.float32) + b2).astype(dtype)
            y = self.conv(4 * f, (1, 1), name="Conv_2")(z2)
            a3, b3 = bn(name="BatchNorm_2",
                        scale_init=nn.initializers.zeros)(x=y)
        if x.shape[-1] != 4 * f or self.strides != (1, 1):
            if "shortcut" in parts:
                xs = x[:, ::self.strides[0], ::self.strides[1], :]
                ys, s1s, s2s, cnts = _FusedConv1x1(
                    4 * f, dtype=dtype, name="shortcut")(xs)
                a4, b4 = bn(name="shortcut_bn")(s1s, s2s, cnts)
            else:
                ys = self.conv(4 * f, (1, 1), self.strides,
                               name="shortcut")(x)
                a4, b4 = bn(name="shortcut_bn")(x=ys)
            residual = a4 * ys.astype(jnp.float32) + b4
        else:
            residual = x.astype(jnp.float32)
        # Block tail (normalize + residual add + relu): one XLA loop fusion.
        return nn.relu(a3 * y.astype(jnp.float32) + b3
                       + residual).astype(dtype)

    @nn.compact
    def __call__(self, x):
        if self.fused:
            st = self._fuse_settings()
            n, h, w, _ = x.shape
            m = n * h * w
            sh, sw = self.strides
            ok = (st is not None and pallas_conv.fusable(m)
                  and pallas_conv.fusable(m // (sh * sw))
                  and h % sh == 0 and w % sw == 0)
            if ok:
                return self._fused_call(x, st)
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides, padding="SAME")(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last BN scale so each block starts as identity
        # (Goyal et al. trick used by the reference recipe's lineage).
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="shortcut")(residual)
            residual = self.norm(name="shortcut_bn")(residual)
        return self.act(y + residual)


class PreActBlock(nn.Module):
    """ResNet v2 pre-activation bottleneck (bn-relu-conv ordering),
    the ``resnet_v2`` of ``keras-cifar10-resnet.py``."""

    filters: int
    strides: Tuple[int, int] = (1, 1)
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        y = self.norm()(x)
        y = self.act(y)
        residual = x
        if self.strides != (1, 1) or x.shape[-1] != self.filters * 4:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="shortcut")(y)
        y = self.conv(self.filters, (1, 1))(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides, padding="SAME")(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        return y + residual


class ResNet(nn.Module):
    """Generic ResNet.

    ``stage_sizes`` counts blocks per stage; ``block_cls`` picks the block
    flavor. ``cifar_stem=True`` uses the 3x3/stride-1 stem (CIFAR, 32x32
    inputs); otherwise the 7x7/stride-2 + maxpool ImageNet stem.
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 10
    num_filters: int = 64
    cifar_stem: bool = False
    # Space-to-depth stem (the standard TPU ResNet optimization, e.g.
    # MLPerf ResNet-50 submissions): fold 2x2 image patches into channels
    # ([N,H,W,3] -> [N,H/2,W/2,12]) and replace the 7x7/stride-2 stem conv
    # with an equivalent-receptive-field 4x4/stride-1 conv. A 3-channel
    # stride-2 conv uses ~2% of the MXU's 128 input lanes and dominates
    # like 15-20% of step time; the s2d form quadruples channel depth and
    # removes the stride. Same downstream network; trains from scratch
    # like the original (the 4x4x12 kernel is the zero-padded 8x8x3
    # reparametrization of the 7x7x3 one).
    stem_space_to_depth: bool = False
    dtype: Any = jnp.bfloat16
    axis_name: Optional[str] = None
    # "xla" = stock convs; "fused" = route training-mode 1x1 convs in
    # bottleneck blocks through the fused Pallas conv+BN+ReLU kernel
    # (checkpoint-compatible — see BottleneckBlock). ``fused_stages``
    # selects which stages fuse: the default is the large-spatial-map
    # stages 0-1 where the 1x1 convs are HBM-bound (measured r5 profile:
    # fusing the deep compute-bound stages too REGRESSES ~2x — XLA's
    # MXU-rich conv kernels win there and every pallas boundary costs
    # layout copies; see docs/benchmarks.md).
    conv_backend: str = "xla"
    fused_stages: Sequence[int] = (0, 1)
    # Per-site fusion selection forwarded to BottleneckBlock (see there).
    fused_parts: Sequence[str] = ("reduce", "expand", "shortcut")

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype,
            axis_name=self.axis_name if train else None)

        x = x.astype(self.dtype)
        if self.cifar_stem:
            x = conv(self.num_filters, (3, 3), padding="SAME", name="stem")(x)
        else:
            if self.stem_space_to_depth:
                n, h, w, c = x.shape
                if h % 2 or w % 2:
                    raise ValueError(
                        f"stem_space_to_depth folds 2x2 patches and needs "
                        f"even spatial dims; got {h}x{w} (pad or resize "
                        f"the input, or use the standard stem)")
                x = x.reshape(n, h // 2, 2, w // 2, 2, c)
                x = x.transpose(0, 1, 3, 2, 4, 5).reshape(n, h // 2, w // 2,
                                                          4 * c)
                x = conv(self.num_filters, (4, 4), (1, 1),
                         padding=[(2, 1), (2, 1)], name="stem_s2d")(x)
            else:
                x = conv(self.num_filters, (7, 7), (2, 2),
                         padding=[(3, 3), (3, 3)], name="stem")(x)
            x = norm(name="stem_bn")(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        if self.cifar_stem and self.block_cls is not PreActBlock:
            x = norm(name="stem_bn")(x)
            x = nn.relu(x)

        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                extra = {}
                if (self.conv_backend == "fused"
                        and self.block_cls is BottleneckBlock
                        and i in self.fused_stages):
                    extra["fused"] = True
                    extra["fused_parts"] = tuple(self.fused_parts)
                x = self.block_cls(
                    self.num_filters * 2 ** i, strides=strides,
                    conv=conv, norm=norm, **extra)(x)

        if self.block_cls is PreActBlock:
            x = norm(name="final_bn")(x)
            x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        # Final logits in float32 for numerically stable softmax/loss.
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x


# ---------------------------------------------------------------------------
# CIFAR ResNet v1 (6n+2) / v2 (9n+2) — keras-cifar10-resnet.py parity.
# depth 20 -> n=3, 56 -> n=9, 110 -> n=18 (v1); v2 uses 9n+2.
# ---------------------------------------------------------------------------

def cifar_resnet_v1(depth: int = 20, num_classes: int = 10, **kw) -> ResNet:
    """ResNet v1 for CIFAR (``keras-cifar10-resnet.py`` resnet_v1,
    depth = 6n+2 ∈ {20, 56, 110})."""
    if (depth - 2) % 6 != 0:
        raise ValueError("v1 depth must be 6n+2 (e.g. 20, 56, 110)")
    n = (depth - 2) // 6
    return ResNet(stage_sizes=[n, n, n], block_cls=BasicBlock,
                  num_classes=num_classes, num_filters=16, cifar_stem=True,
                  **kw)


def cifar_resnet_v2(depth: int = 56, num_classes: int = 10, **kw) -> ResNet:
    """ResNet v2 (pre-activation) for CIFAR (``keras-cifar10-resnet.py``
    resnet_v2, depth = 9n+2 ∈ {56, 110})."""
    if (depth - 2) % 9 != 0:
        raise ValueError("v2 depth must be 9n+2 (e.g. 56, 110)")
    n = (depth - 2) // 9
    return ResNet(stage_sizes=[n, n, n], block_cls=PreActBlock,
                  num_classes=num_classes, num_filters=16, cifar_stem=True,
                  **kw)


def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    """ImageNet ResNet-50 — the reference's north-star workload
    (``keras_imagenet_resnet50.py``)."""
    return ResNet(stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock,
                  num_classes=num_classes, num_filters=64, **kw)


def resnet101(num_classes: int = 1000, **kw) -> ResNet:
    """ResNet-101 (the reference's benchmark model, ``docs/benchmarks.md``)."""
    return ResNet(stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock,
                  num_classes=num_classes, num_filters=64, **kw)
