"""Skip-gram word2vec — the reference's sparse-gradient workload.

Parity: ``examples/tensorflow_word2vec.py`` (skip-gram with NCE loss over a
50k vocabulary; its embedding gradients are ``tf.IndexedSlices``, which is
what exercises the sparse allgather path,
``horovod/tensorflow/__init__.py:61-72``).

TPU-native design: embeddings are a plain [vocab, dim] param; the loss uses
sampled negatives (static ``num_sampled`` shape, XLA-friendly — TF's NCE
sampler is replaced by caller-provided negative ids so the step stays
shape-static). :func:`embedding_grads_as_slices` converts the dense embedding
gradient of a batch into an :class:`~horovod_tpu.ops.sparse.IndexedSlices`
(the touched rows and their grads) so ``DistributedOptimizer`` takes the
two-allgather sparse path just as the reference does.
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.sparse import IndexedSlices


class SkipGram(nn.Module):
    """Skip-gram with sampled-softmax (NCE-style) loss."""

    vocab_size: int = 50000
    embedding_size: int = 128

    @nn.compact
    def __call__(self, center_ids, context_ids, negative_ids):
        """Returns the mean NCE-style loss for a batch.

        Args:
          center_ids:   [B] int ids of center words.
          context_ids:  [B] int ids of true context words (positives).
          negative_ids: [B, K] int ids of sampled negatives.
        """
        emb = self.param(
            "embeddings",
            # U[-1, 1) zero-mean init (tensorflow_word2vec.py:157 parity).
            lambda key, shape: jax.random.uniform(
                key, shape, minval=-1.0, maxval=1.0),
            (self.vocab_size, self.embedding_size))
        nce_w = self.param(
            "nce_weights",
            nn.initializers.truncated_normal(
                stddev=1.0 / jnp.sqrt(self.embedding_size)),
            (self.vocab_size, self.embedding_size))
        nce_b = self.param("nce_biases", nn.initializers.zeros,
                           (self.vocab_size,))

        h = emb[center_ids]                                   # [B, D]
        pos_logit = jnp.einsum("bd,bd->b", h, nce_w[context_ids]) \
            + nce_b[context_ids]                              # [B]
        neg_logit = jnp.einsum("bd,bkd->bk", h, nce_w[negative_ids]) \
            + nce_b[negative_ids]                             # [B, K]

        pos_loss = jax.nn.softplus(-pos_logit)
        neg_loss = jnp.sum(jax.nn.softplus(neg_logit), axis=-1)
        return jnp.mean(pos_loss + neg_loss)


def embedding_grads_as_slices(dense_grad: jax.Array,
                              touched_ids: jax.Array) -> IndexedSlices:
    """Convert a dense [vocab, dim] embedding gradient into IndexedSlices
    over the batch's touched rows — recreating the sparse form TF produces
    natively (``tf.IndexedSlices``), which routes ``DistributedOptimizer``
    through the reference's two-allgather sparse path."""
    values = dense_grad[touched_ids]
    return IndexedSlices(values=values, indices=touched_ids,
                         dense_shape=tuple(dense_grad.shape))
