"""horovod_tpu — a TPU-native distributed training framework with the
capability surface of Horovod v0.11.2 (reference: ``/root/reference``).

Public API parity with ``horovod/tensorflow/__init__.py:34-43``::

    import horovod_tpu as hvd
    hvd.init()
    hvd.size(); hvd.rank(); hvd.local_rank()
    hvd.allreduce(t); hvd.allgather(t); hvd.broadcast(t, root_rank)
    hvd.broadcast_global_variables(params, root_rank)
    opt = hvd.DistributedOptimizer(optax_optimizer)

Design: the world is a 1-D ``jax.sharding.Mesh`` over every chip (axis
``"hvd"``); collectives are XLA collectives over ICI inside compiled code and
cached compiled dispatches (single-controller) or a host DCN coordination
plane (multi-process) eagerly. See ``runtime.py`` and ``ops/``.
"""

from .version import __version__  # noqa: F401

# Imported for its side effects FIRST: grafts newer-jax API spellings
# (jax.shard_map, lax.axis_size, pltpu.CompilerParams) onto older jax
# installs before any framework module references them.
from .utils import compat as _compat  # noqa: F401

from .runtime import (  # noqa: F401
    AXIS,
    init,
    shutdown,
    is_initialized,
    size,
    rank,
    local_rank,
    process_index,
    process_count,
    mesh,
    world,
)
from .ops.collectives import (  # noqa: F401
    Op,
    allreduce,
    allgather,
    allgather_ragged,
    broadcast,
    alltoall,
    reducescatter,
    grouped_allreduce,
    allreduce_async_,
    allgather_async_,
    broadcast_async_,
    synchronize,
    broadcast_object,
    allgather_object,
)
from .ops.sparse import IndexedSlices  # noqa: F401
from .ops.fusion import (  # noqa: F401
    BucketSchedule,
    GradSync,
    plan_grad_sync,
    plan_schedule,
    probe_grad_order,
    resolve_wire_dtype,
)
from .optimizer import (  # noqa: F401
    Compression,
    DistributedOptimizer,
    ZeroShardedState,
    allreduce_gradients,
    broadcast_global_variables,
    broadcast_parameters,
    broadcast_optimizer_state,
    partition_optimizer,
)
from . import callbacks  # noqa: F401
from . import data  # noqa: F401
from . import elastic  # noqa: F401
from . import hooks  # noqa: F401
from .hooks import BroadcastGlobalVariablesHook  # noqa: F401
from . import models  # noqa: F401
from . import obs  # noqa: F401
from . import serve  # noqa: F401
from . import training  # noqa: F401
from .trainer import (  # noqa: F401
    AsyncCheckpointer,
    Trainer,
    save_checkpoint,
    restore_checkpoint,
    latest_checkpoint_step,
)
from .exceptions import (  # noqa: F401
    HorovodError,
    NotInitializedError,
    FailedPreconditionError,
    TransportError,
    StalledError,
    WorkerFailureError,
    ServerOverloadedError,
    DeadlineExceededError,
    ServerClosedError,
    FailoverExhaustedError,
    CheckpointCorruptError,
    CheckpointTimeoutError,
    NonFiniteGradError,
)
