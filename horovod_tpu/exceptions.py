"""Error taxonomy.

Mirrors the reference's error surface:

* not-initialized errors from the C ABI (``mpi_ops.cc:1530-1536`` —
  ``CheckInitialized`` returns FailedPrecondition "Horovod has not been
  initialized").
* cross-rank mismatch errors produced by coordinator validation
  (``ConstructMPIResponse``, ``mpi_ops.cc:266-474``) which surface to the
  calling op as ``tf.errors.FailedPreconditionError``.
* transport/library failures (``MPI_CHECK``/``CUDA_CHECK``/``NCCL_CHECK``,
  ``mpi_ops.cc:535-572``) which surface as Unknown errors.
"""


class HorovodError(Exception):
    """Base class for all framework errors."""


class NotInitializedError(HorovodError):
    """Raised when the process API is used before ``init()``.

    Parity: ``mpi_ops.py:85-88`` raises ValueError('Horovod has not been
    initialized; use hvd.init().'); the C side returns -1
    (``mpi_ops.cc:1539-1566``).
    """

    def __init__(self, what: str = "Horovod-TPU"):
        super().__init__(
            f"{what} has not been initialized; use horovod_tpu.init()."
        )


class FailedPreconditionError(HorovodError):
    """Cross-rank inconsistency detected during collective negotiation.

    Parity: the ERROR response path of ``ConstructMPIResponse``
    (``mpi_ops.cc:266-474``) → ``PerformOperation`` ERROR branch
    (``mpi_ops.cc:1141-1148``) → TF FailedPreconditionError on every rank.
    """


class TransportError(HorovodError):
    """Failure in the host coordination transport (DCN/TCP plane).

    Parity: ``MPI_CHECK`` converting MPI failures to errors::Unknown
    (``mpi_ops.cc:535-546``).
    """


class WorkerFailureError(TransportError):
    """A rank (or the coordinator) died or went silent; the world aborted.

    Subclasses :class:`TransportError`: worker death is detected on the
    transport plane (socket close / missed heartbeats), and pre-existing
    ``except TransportError`` handlers must keep catching a dead rank —
    they just lose the per-rank diagnosis the subclass adds.

    Raised by every blocked or future coordination-plane call once the
    rank-0 coordinator broadcasts an ABORT — because a rank's socket
    closed without a clean shutdown (process crashed/killed) or a rank
    went silent past ``HVD_HEARTBEAT_TIMEOUT`` — or when this rank itself
    stops receiving heartbeat-acks from the coordinator. The message
    names the dead party.

    The reference has no analog: a dead rank hangs ``MPI_Allreduce``
    forever and ``CheckForStalledTensors`` only warns
    (``mpi_ops.cc:1153-1196``). Recovery: exit nonzero, let
    ``tpurun --restarts N`` relaunch the world, and resume from the last
    committed :class:`horovod_tpu.elastic.ElasticState`.
    """


class ReplicaTimeoutError(TransportError):
    """A subprocess serving replica did not answer within its transport
    timeout.

    Raised by :class:`horovod_tpu.serve.proc_replica.ProcReplicaClient`
    when an HTTP round trip to the child worker times out (connect or
    read). Deliberately a *distinct* class from generic transport
    failures: :meth:`horovod_tpu.serve.router.ReplicaHandle.load` maps
    any other stats-surface exception to the ``1 << 30`` busy sentinel
    (route around it and move on), but a TIMEOUT means the child may be
    hung — the handle marks itself suspect and runs an immediate
    liveness check so a wedged process is evicted within one poll
    instead of being dispatch-demoted forever.
    """


class ServerOverloadedError(HorovodError):
    """The inference server's admission queue is full.

    Raised synchronously by :meth:`horovod_tpu.serve.Engine.submit` when
    the bounded request queue is at capacity — the load-shedding half of
    the serving backpressure contract (:mod:`horovod_tpu.serve`). Callers
    should treat it as retryable after backoff (HTTP 503 semantics; the
    bundled HTTP front end maps it exactly there). The reference has no
    serving plane; this extends the taxonomy the same way
    :class:`StalledError` extends the collective plane.
    """


class DeadlineExceededError(HorovodError):
    """A queued inference request's deadline expired before execution.

    Delivered through the request's future (never raised on the engine
    thread): the batcher drops expired requests at dequeue so a stale
    request cannot occupy a batch slot that an in-deadline request needs.
    Maps to HTTP 504 in the bundled front end.
    """


class ServerClosedError(HorovodError):
    """The inference server is shut down (or shutting down).

    Raised by ``submit`` after ``shutdown()`` began, and delivered to any
    still-pending futures when a shutdown is NOT a graceful drain
    (``shutdown(drain=False)``). Distinct from
    :class:`ServerOverloadedError` because it is terminal, not retryable.
    """


class PreemptedError(HorovodError):
    """A generation stream was evicted from its decode slot by a
    higher-priority admission and could not be resumed within the
    engine's preemption retry budget.

    Raised through the stream's handle by the
    :class:`horovod_tpu.serve.GenerationEngine` preemption plane with
    terminal reason ``preempted_exhausted`` — the scheduling analog of
    :class:`FailoverExhaustedError`: the eviction itself is invisible
    to a client (the engine captures the stream's envelope exactly like
    a replica-death failover and replays it bit-identically), so only a
    stream preempted MORE times than ``GenerationConfig.
    preempt_retries`` ever sees this error. Under a
    :class:`horovod_tpu.serve.FleetRouter` it is additionally a
    failover cause: the stranded envelope is re-dispatched to another
    replica before the budget verdict lands, so a preemption on one
    replica can complete on a quieter one.
    """


class FailoverExhaustedError(HorovodError):
    """A generation stream stranded by replica death could not be
    resumed anywhere: it failed on its retry budget's worth of replicas
    (or the replay itself failed terminally on every attempt).

    Delivered through the stream's handle by the
    :class:`horovod_tpu.serve.FleetRouter` failover plane — the
    serving-plane analog of exhausting ``tpurun --restarts``. Distinct
    from :class:`ServerOverloadedError` on purpose: overload means "the
    fleet is full, back off and retry"; this means "this STREAM died N
    times and the router refuses to retry-storm it" — counted separately
    (``hvd_failover_total{outcome="exhausted"}``) so a dashboard can
    tell load shedding from failover churn. The client must re-submit
    from scratch if it still wants the result.
    """


class CheckpointCorruptError(HorovodError):
    """A checkpoint's bytes do not match its integrity manifest.

    Raised by the verify half of the checkpoint integrity plane
    (:func:`horovod_tpu.parallel.checkpoint.verify_checkpoint`): every
    save writes a per-leaf checksum manifest alongside the bytes, and a
    restore that finds a truncated file, a flipped bit, or a
    structure/dtype/shape mismatch raises this instead of silently
    resuming from poisoned state. The message names the checkpoint path
    and the first offending leaf.

    The reference's resume scan trusts whatever directory listing it
    finds (``keras_imagenet_resnet50.py:47-56``) — a torn write from a
    killed rank restores as garbage. Here the elastic restore chain
    (:meth:`horovod_tpu.elastic.ElasticState.restore`) catches this and
    walks back to the newest checkpoint that DOES verify, so a corrupt
    newest checkpoint costs one restore attempt, not the run.
    """

    def __init__(self, path: str, detail: str = ""):
        self.path = path
        self.detail = detail
        msg = f"checkpoint {path} failed integrity verification"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class CheckpointTimeoutError(HorovodError):
    """An async checkpoint write did not become durable within the
    caller's deadline.

    Raised by :meth:`horovod_tpu.trainer.AsyncCheckpointer.wait` when a
    ``timeout=`` is given and the background writer is still in flight
    when it expires — a hung filesystem (dead NFS mount, wedged object
    store) otherwise blocks the durability barrier forever. The write
    itself is NOT cancelled: the writer thread keeps going, and a later
    ``wait()`` observes whatever it eventually did (success or the
    re-raised error).
    """


class NonFiniteGradError(HorovodError):
    """Too many consecutive non-finite-gradient steps with no checkpoint
    to roll back to.

    The in-jit bad-step guard (``make_train_step(guard_nonfinite=True)``)
    skips the optimizer update whenever any replica's gradients carry a
    NaN/Inf, leaving params bit-unchanged. ``Trainer.fit`` counts
    consecutive skips; after ``HVD_MAX_BAD_STEPS`` of them it rolls back
    to the last verified elastic checkpoint — or, when no
    :class:`horovod_tpu.elastic.ElasticState` is attached, raises this:
    a persistent NaN source (bad data shard, broken loss scale, flaky
    chip) is not going to fix itself, and silently skipping forever
    would burn the reservation training nothing.
    """


class StalledError(HorovodError):
    """A collective waited past the hard stall deadline (strict mode).

    Enabled by ``HOROVOD_STALL_TIMEOUT=<seconds>`` (0 = off, the default):
    an eager collective whose response does not arrive within the deadline
    — e.g. because another rank never announced it — raises this instead
    of blocking forever. The reference only warns
    (``CheckForStalledTensors``, ``mpi_ops.cc:1153-1196``); the hard
    timeout is a TPU-era extension for fail-fast fleet jobs.
    """
