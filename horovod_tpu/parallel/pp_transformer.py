"""Pipelined transformer LM: dp × pp × tp composed over one mesh.

Completes the parallelism matrix (the sibling `transformer.py` composes
dp × sp × tp × ep): transformer layers are partitioned into `pp` stages
driven by the 1F1B-style memory-bounded schedule
(:func:`horovod_tpu.parallel.pipeline.one_f_one_b`), with Megatron tensor
parallelism inside each stage and data parallelism over the batch. One
compiled SPMD program: `ppermute` stage handoffs, per-layer tp `psum`s and
the dp gradient `pmean` all ride ICI under XLA's scheduler.

Embedding and the loss head (final RMS norm + tied unembed) live OUTSIDE
the pipeline so every stage runs the same uniform block structure (the
lockstep-SPMD requirement): the embedding's gradient is assembled from the
head's unembed contribution (last pp rank) plus the input-side cotangents
(pp rank 0) that `one_f_one_b` returns — summed with one `psum` over pp.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.pallas_attention import flash_attention
from .pipeline import one_f_one_b
from .transformer import TransformerConfig, _rms_norm, dense_nll


def _axes(mesh: Mesh):
    return set(mesh.axis_names)


def init_pp_params(rng, cfg: TransformerConfig, n_stages: int):
    """Parameters in the pipeline layout: per-layer weights stacked as
    [n_stages, layers_per_stage, ...]; embed/lnf replicated (the head)."""
    if cfg.n_layers % n_stages:
        raise ValueError(f"n_layers={cfg.n_layers} must divide into "
                         f"pp={n_stages} stages")
    lps = cfg.n_layers // n_stages
    k = jax.random.split(rng, 6)
    d, f = cfg.d_model, cfg.d_ff

    def norm(key, shape, s):
        return jax.random.normal(key, shape) * s

    return {
        "embed": norm(k[0], (cfg.vocab, d), 0.02),
        "lnf": jnp.ones((d,)),
        "stages": {
            "ln1": jnp.ones((n_stages, lps, d)),
            "wqkv": norm(k[1], (n_stages, lps, d, 3 * d), d ** -0.5),
            "wo": norm(k[2], (n_stages, lps, d, d), d ** -0.5),
            "ln2": jnp.ones((n_stages, lps, d)),
            "w1": norm(k[3], (n_stages, lps, d, f), d ** -0.5),
            "w2": norm(k[4], (n_stages, lps, f, d), f ** -0.5),
        },
    }


def pp_param_specs(mesh: Mesh) -> dict:
    """PartitionSpec tree for :func:`init_pp_params`: stage dim over pp,
    Megatron column/row sharding over tp, head replicated."""
    tp = "tp" if "tp" in _axes(mesh) else None
    return {
        "embed": P(),
        "lnf": P(),
        "stages": {
            "ln1": P("pp", None, None),
            "wqkv": P("pp", None, None, tp),   # column: heads over tp
            "wo": P("pp", None, tp, None),     # row: one psum recombines
            "ln2": P("pp", None, None),
            "w1": P("pp", None, None, tp),
            "w2": P("pp", None, tp, None),
        },
    }


def make_pp_transformer_train_step(cfg: TransformerConfig, mesh: Mesh,
                                   optimizer: optax.GradientTransformation,
                                   n_microbatches: int):
    """Build ``(init_state, step)`` for the pipelined transformer.

    ``step(params, opt_state, tokens, labels)`` runs one 1F1B update and
    returns ``(params, opt_state, loss)``; tokens/labels are global
    [B, T] int32 sharded over dp, with B divisible by
    dp_size * n_microbatches.
    """
    axes = _axes(mesh)
    if "pp" not in axes:
        raise ValueError("mesh must have a 'pp' axis")
    S = mesh.shape["pp"]
    tp_size = mesh.shape.get("tp", 1)
    has_tp = "tp" in axes
    if cfg.n_heads % tp_size:
        raise ValueError(f"n_heads={cfg.n_heads} must divide tp={tp_size}")
    n_heads_local = cfg.n_heads // tp_size
    d_head = cfg.d_model // cfg.n_heads
    M = n_microbatches
    specs = pp_param_specs(mesh)
    batch_spec = P("dp" if "dp" in axes else None, None)

    def _block(layer_i, stage_leaves, x):
        """One transformer block (pre-norm attention + FFN) from the
        stage's stacked leaves; tp column/row sharding inside."""
        g = lambda name: stage_leaves[name][0, layer_i]  # noqa: E731
        h = _rms_norm(x, g("ln1"))
        qkv = h @ g("wqkv").astype(cfg.dtype)
        B, T, _ = qkv.shape
        # HEAD-major column layout [D, H, 3, dh]: a tp column-slice then
        # holds whole heads (each with its own q,k,v), so the sharded
        # model computes the SAME function as tp=1 from the same weights
        # (checkpoints stay portable across mesh shapes).
        qkv = qkv.reshape(B, T, n_heads_local, 3, d_head)
        attn = flash_attention(qkv[..., 0, :], qkv[..., 1, :],
                               qkv[..., 2, :], causal=True,
                               backend=cfg.attn_backend).astype(cfg.dtype)
        proj = attn.reshape(B, T, n_heads_local * d_head) \
            @ g("wo").astype(cfg.dtype)
        if has_tp:
            proj = lax.psum(proj, "tp")
        x = x + proj
        h = _rms_norm(x, g("ln2"))
        up = jax.nn.gelu(h @ g("w1").astype(cfg.dtype))
        down = up @ g("w2").astype(cfg.dtype)
        if has_tp:
            down = lax.psum(down, "tp")
        return x + down

    lps = cfg.n_layers // S

    def stage_fn(stage_leaves, act):
        for i in range(lps):
            act = _block(i, stage_leaves, act)
        return act

    def head_loss(act, labels, head):
        h = _rms_norm(act, head["lnf"])
        logits = jnp.matmul(h.astype(cfg.unembed_dtype),
                            head["embed"].T.astype(cfg.unembed_dtype),
                            preferred_element_type=jnp.float32)
        return jnp.mean(dense_nll(logits, labels))

    def _step(params, opt_state, tokens, labels):
        B, T = tokens.shape
        mb = B // M
        tok_m = tokens.reshape(M, mb, T)
        y_m = labels.reshape(M, mb, T)
        head = {"embed": params["embed"], "lnf": params["lnf"]}

        # Tokens (not embeddings) ride the microbatch buffer: inject_fn
        # embeds per microbatch at stage-0 injection, and the input
        # cotangents stream straight into a [vocab, D] scatter-add — no
        # O(M) activation-sized buffer exists, preserving the schedule's
        # O(S) memory bound end to end.
        def inject(toks):
            return params["embed"][toks].astype(cfg.dtype)

        def accumulate_embed_grad(acc, bi, din):
            return acc.at[tok_m[bi].reshape(-1)].add(
                din.astype(acc.dtype).reshape(-1, cfg.d_model))

        loss, sg, hg, d_embed_in = one_f_one_b(
            stage_fn, params["stages"], tok_m, y_m, head_loss,
            axis_name="pp", head_params=head, inject_fn=inject,
            input_grad_acc=(jnp.zeros_like(params["embed"]),
                            accumulate_embed_grad))

        # Embedding gradient = head (unembed) contribution on the last pp
        # rank + input-lookup contribution on pp rank 0, merged by ONE
        # psum over pp (zeros elsewhere). lnf rides the same psum.
        hg = jax.tree_util.tree_map(lambda g: lax.psum(g, "pp"), hg)
        d_embed = hg["embed"] + lax.psum(d_embed_in, "pp")

        grads = {"embed": d_embed, "lnf": hg["lnf"], "stages": sg}

        # Shared spec-driven sync (see parallel/mesh.py): pmean over each
        # leaf's replicated axes (never pp — each stage owns its weights)
        # + the tp psum-transpose correction.
        from .mesh import grad_sync_by_spec
        grads = grad_sync_by_spec(grads, specs, axes, skip_axes=("pp",))
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = lax.pmean(loss, tuple(a for a in axes if a != "pp"))
        return params, opt_state, loss

    def _opt_specs(opt_state):
        # Derivable from any opt_state with the right STRUCTURE, so the
        # checkpoint-restore path (params/opt_state from disk, init_state
        # never called) works too.
        return optax.tree_map_params(
            optimizer, lambda _, s: s, opt_state, specs,
            transform_non_params=lambda _: P())

    def init_state(rng):
        params = init_pp_params(rng, cfg, S)
        params = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs, is_leaf=lambda x: isinstance(x, P))
        opt_state = optimizer.init(params)
        opt_state = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(jnp.asarray(x),
                                        NamedSharding(mesh, s)),
            opt_state, _opt_specs(opt_state),
            is_leaf=lambda x: isinstance(x, P))
        return params, opt_state

    fn_box = {}

    def step(params, opt_state, tokens, labels):
        if "fn" not in fn_box:
            ospecs = _opt_specs(opt_state)
            fn_box["fn"] = jax.jit(jax.shard_map(
                _step, mesh=mesh,
                in_specs=(specs, ospecs, batch_spec, batch_spec),
                out_specs=(specs, ospecs, P()),
                check_vma=False))
        return fn_box["fn"](params, opt_state, tokens, labels)

    return init_state, step
