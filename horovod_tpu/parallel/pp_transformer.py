"""Pipelined transformer LM: dp × pp × tp composed over one mesh.

Completes the parallelism matrix (the sibling `transformer.py` composes
dp × sp × tp × ep): transformer layers are partitioned into `pp` stages
driven by the 1F1B-style memory-bounded schedule
(:func:`horovod_tpu.parallel.pipeline.one_f_one_b`), with Megatron tensor
parallelism inside each stage and data parallelism over the batch. One
compiled SPMD program: `ppermute` stage handoffs, per-layer tp `psum`s and
the dp gradient `pmean` all ride ICI under XLA's scheduler.

Embedding and the loss head (final RMS norm + tied unembed) live OUTSIDE
the pipeline so every stage runs the same uniform block structure (the
lockstep-SPMD requirement): the embedding's gradient is assembled from the
head's unembed contribution (last pp rank) plus the input-side cotangents
(pp rank 0) that `one_f_one_b` returns — summed with one `psum` over pp.

Gradient sync is the unified spec-grouped collective plan (ISSUE 20): the
step interprets the same `GradSync`/`ZeroPlan` data every other plane does
(`DistributedOptimizer(mesh=, param_specs=)` → `plan_grad_sync` →
`fused_allreduce(reduce_axes=)`), with `pp` excluded from every allreduce
reduce set — each stage owns its weights. The per-leaf
`grad_sync_by_spec` walk this file used to run stays exported from
`parallel.mesh` as the empirical reference the plan's denominators are
pinned against in tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.pallas_attention import flash_attention
# Re-exported reference (not called in the step body): the per-leaf
# empirical sync rule the fused GradSync plan is parity-pinned against.
from .mesh import grad_sync_by_spec  # noqa: F401
from .pipeline import one_f_one_b
from .transformer import TransformerConfig, _rms_norm, dense_nll


def _axes(mesh: Mesh):
    return set(mesh.axis_names)


def init_pp_params(rng, cfg: TransformerConfig, n_stages: int):
    """Parameters in the pipeline layout: per-layer weights stacked as
    [n_stages, layers_per_stage, ...]; embed/lnf replicated (the head)."""
    if cfg.n_layers % n_stages:
        raise ValueError(f"n_layers={cfg.n_layers} must divide into "
                         f"pp={n_stages} stages")
    lps = cfg.n_layers // n_stages
    k = jax.random.split(rng, 6)
    d, f = cfg.d_model, cfg.d_ff

    def norm(key, shape, s):
        return jax.random.normal(key, shape) * s

    return {
        "embed": norm(k[0], (cfg.vocab, d), 0.02),
        "lnf": jnp.ones((d,)),
        "stages": {
            "ln1": jnp.ones((n_stages, lps, d)),
            "wqkv": norm(k[1], (n_stages, lps, d, 3 * d), d ** -0.5),
            "wo": norm(k[2], (n_stages, lps, d, d), d ** -0.5),
            "ln2": jnp.ones((n_stages, lps, d)),
            "w1": norm(k[3], (n_stages, lps, d, f), d ** -0.5),
            "w2": norm(k[4], (n_stages, lps, f, d), f ** -0.5),
        },
    }


def pp_param_specs(mesh: Mesh) -> dict:
    """PartitionSpec tree for :func:`init_pp_params`: stage dim over pp,
    Megatron column/row sharding over tp, head replicated."""
    tp = "tp" if "tp" in _axes(mesh) else None
    return {
        "embed": P(),
        "lnf": P(),
        "stages": {
            "ln1": P("pp", None, None),
            "wqkv": P("pp", None, None, tp),   # column: heads over tp
            "wo": P("pp", None, tp, None),     # row: one psum recombines
            "ln2": P("pp", None, None),
            "w1": P("pp", None, None, tp),
            "w2": P("pp", None, tp, None),
        },
    }


def make_pp_transformer_train_step(cfg: TransformerConfig, mesh: Mesh,
                                   optimizer: optax.GradientTransformation,
                                   n_microbatches: int,
                                   *,
                                   zero: bool = False,
                                   wire_dtype=None,
                                   overlap=None,
                                   guard_nonfinite=None,
                                   fusion_threshold=None):
    """Build ``(init_state, step)`` for the pipelined transformer.

    ``step(params, opt_state, tokens, labels)`` runs one 1F1B update and
    returns ``(params, opt_state, loss)``; tokens/labels are global
    [B, T] int32 sharded over dp, with B divisible by
    dp_size * n_microbatches.

    Gradient sync interprets the unified spec-grouped collective plan:
    leaves fuse only within their reduce-axis group
    (:func:`~horovod_tpu.ops.fusion.plan_grad_sync` keyed by
    :func:`pp_param_specs`, ``pp`` excluded — each stage owns its
    weights), so on a (dp, pp, tp) mesh the default plan carries TWO
    bucket collectives (replicated head/norm leaves psum over (dp, tp);
    tp-sharded matrices over dp with the psum-transpose correction in the
    bucket prescale) instead of one per leaf. Same composition matrix as
    the core stack:

    * ``zero=True`` — ZeRO-1 over dp: the spec-grouped ``ZeroPlan`` with
      pp riding as a real shard axis of the stacked state (stage leaves
      shard over (pp, tp); the head leaves take the full (dp, pp, tp)
      reduce, numerically equal to the pp-skip mean because the step's
      explicit pp psum already made them pp-identical).
    * ``wire_dtype=`` — bf16/fp8 bucket wire, fp32 scales + accumulation.
    * ``overlap=`` — barrier-chained per-bucket emission; the 1F1B scan
      hides backward-completion order from the probe, so emission runs in
      plan order (reorder-free, still unmergeable by XLA's combiner).
    * ``guard_nonfinite=`` (default ``HVD_GUARD_NONFINITE``) — skip-step
      guard; the allreduce plan never reduces over pp, so the verdict is
      folded with ONE scalar pmin over pp — the only collective the guard
      adds here (the ZeRO plan's flags already fold over its nonscatter
      axes).
    * accum — native: ``n_microbatches`` IS the accumulation shape (1F1B
      sums M microbatch gradients before the one exchange); there is no
      separate accum_steps knob to double-divide with.
    """
    from ..optimizer import DistributedOptimizer
    from ..utils import config as _config

    axes = _axes(mesh)
    if "pp" not in axes:
        raise ValueError("mesh must have a 'pp' axis")
    S = mesh.shape["pp"]
    tp_size = mesh.shape.get("tp", 1)
    has_tp = "tp" in axes
    if cfg.n_heads % tp_size:
        raise ValueError(f"n_heads={cfg.n_heads} must divide tp={tp_size}")
    n_heads_local = cfg.n_heads // tp_size
    d_head = cfg.d_model // cfg.n_heads
    M = n_microbatches
    specs = pp_param_specs(mesh)
    batch_spec = P("dp" if "dp" in axes else None, None)
    if guard_nonfinite is None:
        guard_nonfinite = _config.guard_nonfinite()
    # The allreduce plan skips pp (stage weights are never replicated
    # across it); the ZeRO plan instead carries pp as a shard axis — the
    # stacked [dp, ns·shard_len] state layout must tile over every mesh
    # axis the stage weights are actually split across.
    dist_opt = DistributedOptimizer(
        optimizer, zero=zero, wire_dtype=wire_dtype, overlap=overlap,
        fusion_threshold=fusion_threshold, mesh=mesh, param_specs=specs,
        skip_axes=() if zero else ("pp",))

    def _block(layer_i, stage_leaves, x):
        """One transformer block (pre-norm attention + FFN) from the
        stage's stacked leaves; tp column/row sharding inside."""
        g = lambda name: stage_leaves[name][0, layer_i]  # noqa: E731
        h = _rms_norm(x, g("ln1"))
        qkv = h @ g("wqkv").astype(cfg.dtype)
        B, T, _ = qkv.shape
        # HEAD-major column layout [D, H, 3, dh]: a tp column-slice then
        # holds whole heads (each with its own q,k,v), so the sharded
        # model computes the SAME function as tp=1 from the same weights
        # (checkpoints stay portable across mesh shapes).
        qkv = qkv.reshape(B, T, n_heads_local, 3, d_head)
        attn = flash_attention(qkv[..., 0, :], qkv[..., 1, :],
                               qkv[..., 2, :], causal=True,
                               backend=cfg.attn_backend).astype(cfg.dtype)
        proj = attn.reshape(B, T, n_heads_local * d_head) \
            @ g("wo").astype(cfg.dtype)
        if has_tp:
            proj = lax.psum(proj, "tp")
        x = x + proj
        h = _rms_norm(x, g("ln2"))
        up = jax.nn.gelu(h @ g("w1").astype(cfg.dtype))
        down = up @ g("w2").astype(cfg.dtype)
        if has_tp:
            down = lax.psum(down, "tp")
        return x + down

    lps = cfg.n_layers // S

    def stage_fn(stage_leaves, act):
        for i in range(lps):
            act = _block(i, stage_leaves, act)
        return act

    def head_loss(act, labels, head):
        h = _rms_norm(act, head["lnf"])
        logits = jnp.matmul(h.astype(cfg.unembed_dtype),
                            head["embed"].T.astype(cfg.unembed_dtype),
                            preferred_element_type=jnp.float32)
        return jnp.mean(dense_nll(logits, labels))

    def _step(params, opt_state, tokens, labels):
        B, T = tokens.shape
        mb = B // M
        tok_m = tokens.reshape(M, mb, T)
        y_m = labels.reshape(M, mb, T)
        head = {"embed": params["embed"], "lnf": params["lnf"]}

        # Tokens (not embeddings) ride the microbatch buffer: inject_fn
        # embeds per microbatch at stage-0 injection, and the input
        # cotangents stream straight into a [vocab, D] scatter-add — no
        # O(M) activation-sized buffer exists, preserving the schedule's
        # O(S) memory bound end to end.
        def inject(toks):
            return params["embed"][toks].astype(cfg.dtype)

        def accumulate_embed_grad(acc, bi, din):
            return acc.at[tok_m[bi].reshape(-1)].add(
                din.astype(acc.dtype).reshape(-1, cfg.d_model))

        loss, sg, hg, d_embed_in = one_f_one_b(
            stage_fn, params["stages"], tok_m, y_m, head_loss,
            axis_name="pp", head_params=head, inject_fn=inject,
            input_grad_acc=(jnp.zeros_like(params["embed"]),
                            accumulate_embed_grad))

        # Embedding gradient = head (unembed) contribution on the last pp
        # rank + input-lookup contribution on pp rank 0, merged by ONE
        # psum over pp (zeros elsewhere). lnf rides the same psum.
        hg = jax.tree_util.tree_map(lambda g: lax.psum(g, "pp"), hg)
        d_embed = hg["embed"] + lax.psum(d_embed_in, "pp")

        grads = {"embed": d_embed, "lnf": hg["lnf"], "stages": sg}

        # One plan, every plane: the spec-grouped GradSync/ZeroPlan
        # interpretation replaces the old per-leaf grad_sync_by_spec walk
        # — same denominators (parity-pinned against it in tests), fused
        # buckets, one collective per spec group.
        finite_out = {} if guard_nonfinite else None
        upd_kw = {} if finite_out is None else {"finite_out": finite_out}
        updates, new_opt_state = dist_opt.update(
            grads, opt_state, params, **upd_kw)
        new_params = optax.apply_updates(params, updates)
        if finite_out is not None:
            all_finite = finite_out["all_finite"]
            if not zero:
                # The allreduce plan never reduces over pp, so per-stage
                # verdicts must fold once for a mesh-wide skip decision
                # (divergent decisions would corrupt the pp-replicated
                # head leaves).
                all_finite = lax.pmin(
                    all_finite.astype(jnp.int32), "pp") > 0

            def _keep(new, old):
                return jnp.where(all_finite, new, old)
            new_params = jax.tree_util.tree_map(_keep, new_params, params)
            new_opt_state = jax.tree_util.tree_map(
                _keep, new_opt_state, opt_state)
            loss = jnp.where(all_finite, loss, jnp.zeros_like(loss))
        params, opt_state = new_params, new_opt_state
        loss = lax.pmean(loss, tuple(a for a in axes if a != "pp"))
        return params, opt_state, loss

    def _opt_specs(opt_state):
        # Derivable from any opt_state with the right STRUCTURE, so the
        # checkpoint-restore path (params/opt_state from disk, init_state
        # never called) works too; handles both the mirrored replicated
        # state and the ZeRO stacked-shard layout.
        from .. import training
        return training._hybrid_opt_specs(dist_opt, opt_state, specs)

    def init_state(rng):
        params = init_pp_params(rng, cfg, S)
        params = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs, is_leaf=lambda x: isinstance(x, P))
        # dist_opt.init commits the state to the mesh itself (param specs
        # mirrored leaf-for-leaf; ZeRO stacks + dp-shards per the plan).
        return params, dist_opt.init(params)

    fn_box = {}

    def _jitted(opt_state):
        if "fn" not in fn_box:
            ospecs = _opt_specs(opt_state)
            fn_box["fn"] = jax.jit(jax.shard_map(
                _step, mesh=mesh,
                in_specs=(specs, ospecs, batch_spec, batch_spec),
                out_specs=(specs, ospecs, P()),
                check_vma=False))
        return fn_box["fn"]

    def step(params, opt_state, tokens, labels):
        return _jitted(opt_state)(params, opt_state, tokens, labels)

    # AOT handle (jax .lower convention) for HLO-pinned tests.
    step.lower = lambda params, opt_state, tokens, labels: _jitted(
        opt_state).lower(params, opt_state, tokens, labels)

    return init_state, step
