"""Multi-axis device meshes: dp / tp / pp / sp / ep.

Beyond reference parity (the reference is data-parallel only, SURVEY §2.4);
these are the TPU-era parallelism axes the framework exposes so long-context
and large-model training are first-class. A hybrid mesh lays ranks out so
that the fastest-varying (innermost) axes map to physically close chips —
tensor/sequence parallelism wants ICI-neighbor bandwidth, data parallelism
tolerates DCN.

Axis names (canonical across the framework):

- ``dp`` — data parallel (gradient psum; the reference's world axis)
- ``tp`` — tensor parallel (Megatron-style sharded matmuls)
- ``pp`` — pipeline parallel (stage-to-stage ppermute)
- ``sp`` — sequence/context parallel (ring attention / all-to-all)
- ``ep`` — expert parallel (MoE dispatch over all_to_all)
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("dp", "pp", "ep", "sp", "tp")


def create_hybrid_mesh(dp: int = 1, tp: int = 1, pp: int = 1, sp: int = 1,
                       ep: int = 1,
                       devices: Optional[Sequence] = None) -> Mesh:
    """Build a named mesh over the axes with size > 1 (plus ``dp`` always).

    Axis order is outermost→innermost ``(dp, pp, ep, sp, tp)``: tp/sp vary
    fastest so they land on ICI-adjacent chips; dp is outermost so its
    collectives can ride DCN across hosts ("How to Scale Your Model" mesh
    recipe).

    Every axis feeds the same spec-grouped gradient-sync plan
    (``ops/fusion.plan_grad_sync``): a leaf psums over exactly the axes
    it is replicated across, so growing the mesh — 3-D dp×tp×pp for the
    pipelined family, ``ep`` for MoE experts — changes PartitionSpecs,
    never step-body collective code (parity-pinned in
    tests/test_parallel.py).
    """
    devs = list(devices) if devices is not None else list(jax.devices())
    sizes = {"dp": dp, "pp": pp, "ep": ep, "sp": sp, "tp": tp}
    total = math.prod(sizes.values())
    if total != len(devs):
        knobs = {"dp": "dp= (bench.py --mesh, examples --dp)",
                 "pp": "pp= (examples --pp)",
                 "ep": "ep= (set n_experts to the ep size)",
                 "sp": "sp= (examples --sp)",
                 "tp": "tp= (bench.py --tp/--mesh, examples --tp)"}
        detail = ", ".join(f"{a}={sizes[a]} via {knobs[a]}" for a in AXES
                           if sizes[a] != 1) or "all axes at their default 1"
        raise ValueError(
            f"mesh {sizes} needs {total} devices, have {len(devs)}: the "
            f"axis sizes ({detail}) must multiply to the visible device "
            f"count — adjust the knobs above, or the device count "
            f"(JAX_PLATFORMS / --xla_force_host_platform_device_count), "
            f"or pass an explicit devices= subset")
    names = tuple(a for a in AXES if sizes[a] > 1) or ("dp",)
    shape = tuple(sizes[a] for a in names)
    return Mesh(np.array(devs).reshape(shape), names)


def axis_size(mesh: Mesh, name: str) -> int:
    """Size of ``name`` on ``mesh``; 1 for a canonical axis the mesh does
    not carry. A name that is neither on the mesh nor in :data:`AXES`
    raises — a typo ('dpp') must not silently read as "absent, size 1"
    and quietly skip a collective."""
    if name in mesh.shape:
        return int(mesh.shape[name])
    if name not in AXES:
        raise ValueError(
            f"unknown mesh axis {name!r}: this mesh has "
            f"{tuple(mesh.axis_names)} and the canonical axis names are "
            f"{AXES} (absent canonical axes have size 1)")
    return 1


def named_sharding_tree(mesh: Mesh, tree, spec_fn=None):
    """A tree of ``NamedSharding`` matching ``tree``'s structure.

    ``spec_fn(path, leaf) -> PartitionSpec | None`` picks each leaf's
    layout (``path`` is the ``jax.tree_util`` key-path tuple); ``None``
    (and the default ``spec_fn=None``) means fully replicated. This is
    the placement half of the serve/restore path: training code gets its
    shardings from the step builder, but a restore-for-inference has no
    step to inherit from — the checkpoint tree plus a rule is the whole
    specification.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    def shard(path, leaf):
        spec = spec_fn(path, leaf) if spec_fn is not None else None
        return NamedSharding(mesh, spec if spec is not None else P())

    return jax.tree_util.tree_map_with_path(shard, tree)


def grad_sync_by_spec(grads, specs, mesh_axes, *, skip_axes=(),
                      wire_dtype=None):
    """Gradient sync for spec-sharded parameter trees (runs INSIDE
    shard_map). The per-leaf EMPIRICAL REFERENCE of the sync rule: every
    production plane now interprets the fused spec-grouped plan
    (``ops/fusion.plan_grad_sync`` → ``GradSync``, one collective per
    reduce-axis group) instead of calling this walk, but this function
    remains the ground truth the plan's membership and denominators are
    parity-pinned against in tests — the collective-gradient math is
    subtle enough that an executable reference is how drift gets caught.

    Each leaf's gradient is averaged (``pmean``) over every mesh axis the
    leaf is REPLICATED across (all axes not in its own PartitionSpec and
    not in ``skip_axes`` — e.g. ``pp``, where each stage owns its own
    weights outright).

    tp-sharded leaves additionally divide by the tp axis size: under
    full-manual shard_map (check_vma=False) the transpose of the
    row-parallel ``psum`` is ``psum``, so the replicated cotangent
    entering each tp-local matmul arrives multiplied by tp — one spurious
    factor of tp on every tp-sharded weight's gradient (verified
    empirically: tp=2 vs tp=1 from identical params gave exactly 2x
    before this correction; replicated leaves are unaffected because
    their per-rank partials go through the pmean above, and the factor
    does not compound across layers because partial cotangents are
    re-summed — not amplified — by the next psum transpose).

    ``wire_dtype`` (``"bf16"``/``"fp8"``) runs each replicated-axis
    gradient average on the wire in reduced precision — same contract as
    the fused-bucket planes (``ops/fusion.py``): the ``1/world`` average
    and any fp8 dynamic scale are applied in fp32 before one cast on
    send, and the reduced result returns to the leaf's dtype immediately
    after. tp-sharded leaves' compiler-inserted psums are untouched
    (those carry activations' cotangents, not the gradient exchange).
    """
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..ops.fusion import _wire_applies, _wire_sum, resolve_wire_dtype

    wire = resolve_wire_dtype(wire_dtype)

    def sync(spec, g):
        leaf_axes = {ax for s in spec if s
                     for ax in ((s,) if isinstance(s, str) else s)}
        over = tuple(a for a in mesh_axes
                     if a not in leaf_axes and a not in skip_axes)
        if over:
            if _wire_applies(g.dtype, wire):
                world = 1
                for a in over:
                    world *= int(lax.axis_size(a))
                g = _wire_sum(g, over, wire, prescale=1.0 / world)
            else:
                g = lax.pmean(g, over)
        if "tp" in leaf_axes and "tp" in mesh_axes:
            g = g / lax.axis_size("tp")
        return g

    return jax.tree_util.tree_map(sync, specs, grads,
                                  is_leaf=lambda x: isinstance(x, P))
