"""Parallel transformer LM: dp × tp × sp × ep composed over one mesh.

Net-new TPU capability demonstrating the framework's multi-axis parallelism
(the reference is dp-only, SURVEY §2.4): batch sharded over ``dp``, sequence
over ``sp`` (ring attention), Megatron column/row weight sharding over
``tp``, and optionally a top-1 MoE FFN over ``ep``. The train step is one
compiled SPMD program (``shard_map`` over the mesh) whose collectives —
gradient ``pmean`` over dp/sp, ``psum`` of row-parallel matmuls over tp,
``ppermute`` K/V rings over sp, ``all_to_all`` MoE dispatch over ep — all
ride ICI under XLA's scheduler.

Params are global jax.Arrays placed with `NamedSharding` spec trees
(`param_specs`); tp-sharded weights never exist unsharded on any chip.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .moe import moe_ffn
from .ring import ring_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 1024
    d_model: int = 128
    n_heads: int = 8
    n_layers: int = 2
    d_ff: int = 512
    n_experts: int = 0          # 0 = dense MLP; >0 = MoE over the ep axis
    dtype: Any = jnp.bfloat16
    # "pallas" so TRAINING never materializes [T, T] scores for backward
    # (the flash custom VJP recomputes tiles); untilable shapes still fall
    # back to XLA dense inside flash_attention.
    attn_backend: str = "pallas"
    # Rematerialize each layer in backward, saving only matmul outputs
    # (dots_saveable): recomputes the cheap elementwise chains, trading
    # negligible FLOPs for most of the activation memory.
    remat: bool = False
    # The tied-head unembed matmul dtype. bf16 keeps the [*, vocab] matmul
    # on the fast MXU path (f32 accumulation either way); logits and the
    # softmax stay f32.
    unembed_dtype: Any = jnp.float32
    # >0: compute the LM cross-entropy in vocab chunks of this width with
    # an online log-sum-exp, never materializing the [B, T, vocab] f32
    # logits (2.1 GB at the bench config — the tensor that capped the
    # bench batch at 8). The chunk body is jax.checkpoint'd, so backward
    # recomputes each chunk's logits instead of saving them: ~+1 unembed
    # matmul of FLOPs for O(vocab/chunk) less live memory. Must divide
    # vocab. 0 = dense (one [*, vocab] logits tensor; nll computed as
    # logsumexp - picked_logit, no logp materialization).
    loss_chunk: int = 0


def _axes(mesh: Mesh):
    return set(mesh.axis_names)


def init_params(rng, cfg: TransformerConfig) -> Dict:
    """Global (unsharded-shape) parameter pytree; place with
    :func:`param_specs` + ``jax.device_put`` before use."""
    k = jax.random.split(rng, 4 + 6 * cfg.n_layers)
    ki = iter(range(len(k)))
    norm = lambda key, shape, s: (jax.random.normal(k[key], shape) * s)  # noqa: E731
    params: Dict[str, Any] = {
        "embed": norm(next(ki), (cfg.vocab, cfg.d_model), 0.02),
        "lnf": jnp.ones((cfg.d_model,)),
        "layers": [],
    }
    for _ in range(cfg.n_layers):
        layer = {
            "ln1": jnp.ones((cfg.d_model,)),
            "wqkv": norm(next(ki), (cfg.d_model, 3 * cfg.d_model),
                         cfg.d_model ** -0.5),
            "wo": norm(next(ki), (cfg.d_model, cfg.d_model),
                       cfg.d_model ** -0.5),
            "ln2": jnp.ones((cfg.d_model,)),
        }
        if cfg.n_experts:
            layer["gate"] = norm(next(ki), (cfg.d_model, cfg.n_experts),
                                 cfg.d_model ** -0.5)
            # Leading expert dim shards over ep (one expert per ep rank).
            layer["w1"] = norm(next(ki),
                               (cfg.n_experts, cfg.d_model, cfg.d_ff),
                               cfg.d_model ** -0.5)
            layer["w2"] = norm(next(ki),
                               (cfg.n_experts, cfg.d_ff, cfg.d_model),
                               cfg.d_ff ** -0.5)
        else:
            layer["w1"] = norm(next(ki), (cfg.d_model, cfg.d_ff),
                               cfg.d_model ** -0.5)
            layer["w2"] = norm(next(ki), (cfg.d_ff, cfg.d_model),
                               cfg.d_ff ** -0.5)
        params["layers"].append(layer)
    return params


def param_specs(cfg: TransformerConfig, mesh: Mesh) -> Dict:
    """PartitionSpec tree matching :func:`init_params`: Megatron column
    (out-dim) / row (in-dim) sharding over tp; experts over ep; everything
    else replicated (dp/sp replicate params)."""
    tp = "tp" if "tp" in _axes(mesh) else None
    ep = "ep" if "ep" in _axes(mesh) else None
    specs: Dict[str, Any] = {
        "embed": P(),
        "lnf": P(),
        "layers": [],
    }
    for _ in range(cfg.n_layers):
        layer = {
            "ln1": P(),
            "wqkv": P(None, tp),   # column-parallel: heads shard over tp
            "wo": P(tp, None),     # row-parallel: one psum recombines
            "ln2": P(),
        }
        if cfg.n_experts:
            layer["gate"] = P()
            layer["w1"] = P(ep, None, None)
            layer["w2"] = P(ep, None, None)
        else:
            layer["w1"] = P(None, tp)
            layer["w2"] = P(tp, None)
        specs["layers"].append(layer)
    return specs


def _rms_norm(x, scale):
    x32 = x.astype(jnp.float32)
    rms = jnp.sqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
    return ((x32 / rms) * scale).astype(x.dtype)


def forward_hidden(params, tokens, cfg: TransformerConfig, mesh: Mesh):
    """Runs INSIDE shard_map: ``tokens`` [B_local, T_local] int32.
    Returns (final hidden states [B_local, T_local, d_model] — the
    pre-unembed activations — and the MoE aux loss). The chunked-loss
    path consumes this directly so the [*, vocab] logits never
    materialize; :func:`forward` layers the tied unembed on top."""
    axes = _axes(mesh)
    has_tp = "tp" in axes
    has_sp = "sp" in axes
    has_ep = "ep" in axes
    n_heads_local = cfg.n_heads // (mesh.shape.get("tp", 1))
    d_head = cfg.d_model // cfg.n_heads

    def _layer_fwd(layer, x):
        from ..ops.pallas_attention import (flash_attention,
                                            flash_attention_qkv,
                                            qkv_flash_tilable)
        h = _rms_norm(x, layer["ln1"])
        qkv = h @ layer["wqkv"].astype(cfg.dtype)     # [B, T, 3·D/tp]
        B, T, _ = qkv.shape
        # HEAD-major column layout [D, H, 3, dh]: a tp column-slice holds
        # whole heads (each with its own q,k,v), so the sharded model
        # computes the SAME function as tp=1 from the same weights
        # (checkpoints stay portable across mesh shapes).
        if (not has_sp and cfg.attn_backend == "pallas"
                and qkv_flash_tilable(T, d_head)):
            # Packed path: the kernel consumes the projection output
            # directly (head-major columns) and returns [B, T, H·dh] — no
            # [B,T,H,dh] <-> [BH,T,dh] transposes on either side
            # (~11 ms/step of layout copies at the LM bench config).
            attn = flash_attention_qkv(qkv, n_heads_local,
                                       causal=True).astype(cfg.dtype)
        else:
            qkv = qkv.reshape(B, T, n_heads_local, 3, d_head)
            q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
            if has_sp:
                attn = ring_attention(q, k, v, axis_name="sp", causal=True)
            else:
                # Single-shard attention: the Pallas blockwise kernel by
                # default (scores never hit HBM in forward OR backward);
                # untilable shapes fall back to XLA dense inside.
                attn = flash_attention(q, k, v, causal=True,
                                       backend=cfg.attn_backend
                                       ).astype(cfg.dtype)
            attn = attn.reshape(B, T, n_heads_local * d_head)
        proj = attn @ layer["wo"].astype(cfg.dtype)
        if has_tp:
            proj = lax.psum(proj, "tp")               # row-parallel combine
        x = x + proj
        return _ffn(layer, x, B, T)

    def _ffn(layer, x, B, T):
        h = _rms_norm(x, layer["ln2"])
        if has_ep and cfg.n_experts:
            flat = h.reshape(-1, cfg.d_model)
            y, aux = moe_ffn(flat, layer["gate"].astype(cfg.dtype),
                             layer["w1"][0].astype(cfg.dtype),
                             layer["w2"][0].astype(cfg.dtype),
                             axis_name="ep")
            x = x + y.reshape(B, T, cfg.d_model)
        else:
            aux = jnp.zeros((), jnp.float32)
            up = jax.nn.gelu(h @ layer["w1"].astype(cfg.dtype))
            down = up @ layer["w2"].astype(cfg.dtype)
            if has_tp:
                down = lax.psum(down, "tp")
            x = x + down
        return x, aux

    if cfg.remat:
        _layer_fwd = jax.checkpoint(
            _layer_fwd, policy=jax.checkpoint_policies.dots_saveable)

    x = params["embed"][tokens].astype(cfg.dtype)     # [B, T, D]
    aux_total = jnp.zeros((), jnp.float32)
    for layer in params["layers"]:
        x, aux = _layer_fwd(layer, x)
        aux_total = aux_total + aux

    x = _rms_norm(x, params["lnf"])
    return x, aux_total


def forward(params, tokens, cfg: TransformerConfig, mesh: Mesh):
    """Full forward: hidden states through the tied unembed.
    Returns (logits [B_local, T_local, vocab], moe_aux_loss)."""
    x, aux_total = forward_hidden(params, tokens, cfg, mesh)
    # Tied head: bf16 MXU pass with f32 accumulation when unembed_dtype is
    # bf16; logits are f32 either way for a stable softmax.
    logits = jnp.matmul(x.astype(cfg.unembed_dtype),
                        params["embed"].T.astype(cfg.unembed_dtype),
                        preferred_element_type=jnp.float32)
    return logits, aux_total


def dense_nll(logits, labels):
    """Per-token -log p(label): lse - picked_logit, NOT
    -take(log_softmax) — the log_softmax form materializes a full
    [*, vocab] f32 logp tensor (2.1 GB at the bench config, profiled at
    ~6.5 ms/step of pure HBM) only to gather one element per row.
    logsumexp reduces in one pass and the gather reads the raw logits;
    gradients are identical (softmax - onehot) either way. Shared by the
    dp/sp/tp/ep family here and the pipeline family's head loss
    (``pp_transformer.py``)."""
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, labels[..., None],
                                 axis=-1)[..., 0]
    return lse - picked


def chunked_nll(x, embed, labels, cfg: TransformerConfig):
    """Per-token −log p(label) over a tied unembedding, computed in vocab
    chunks with an online log-sum-exp so the [N, vocab] f32 logits never
    exist at once (the memory-bound tensor of LM training; the same
    running max/sum recurrence flash attention uses, applied to the loss).

    The chunk body is ``jax.checkpoint``'d: autodiff through the scan
    would otherwise stash every chunk's logits — the full logits tensor
    again — as residuals; with remat, backward replays each chunk's
    unembed matmul instead (one extra [N, d] × [d, C] pass per chunk).
    """
    orig_shape = x.shape[:-1]
    d = x.shape[-1]
    xf = x.reshape(-1, d)
    vocab = embed.shape[0]
    # Clamp labels into [0, vocab): the dense path's take_along_axis clips
    # out-of-range indices to a real logit, while an unclamped chunked scan
    # would treat such a label as absent from every chunk (ll stays 0, nll
    # becomes the full lse) — toggling loss_chunk must not change the loss
    # on any input.
    lab = jnp.clip(labels.reshape(-1), 0, vocab - 1)
    n = xf.shape[0]
    chunk = cfg.loss_chunk
    if vocab % chunk:
        raise ValueError(
            f"loss_chunk={chunk} must divide vocab={vocab}")
    n_chunks = vocab // chunk
    wch = embed.reshape(n_chunks, chunk, d)

    def body(carry, inp):
        m, s, ll = carry
        i, w = inp
        logits = jnp.matmul(xf.astype(cfg.unembed_dtype),
                            w.T.astype(cfg.unembed_dtype),
                            preferred_element_type=jnp.float32)  # [N, C]
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = (s * jnp.exp(m - m_new)
             + jnp.sum(jnp.exp(logits - m_new[:, None]), axis=-1))
        off = i * chunk
        in_chunk = (lab >= off) & (lab < off + chunk)
        idx = jnp.clip(lab - off, 0, chunk - 1)
        picked = jnp.take_along_axis(logits, idx[:, None], axis=-1)[:, 0]
        ll = ll + jnp.where(in_chunk, picked, 0.0)
        return (m_new, s, ll), None

    init = (jnp.full((n,), -1e30, jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.zeros((n,), jnp.float32))
    (m, s, ll), _ = lax.scan(jax.checkpoint(body), init,
                             (jnp.arange(n_chunks), wch))
    lse = m + jnp.log(s)
    return (lse - ll).reshape(orig_shape)


# ---------------------------------------------------------------------------
# Autoregressive generation: the prefill/decode pair over a slot-indexed KV
# cache (the model layer under horovod_tpu.serve.generate's continuous-
# batching engine; the paged block-table variants live in kv_blocks.py and
# share these helpers). Pure functions of (params, cache) — the cache is a
# plain pytree so it jits, donates, and shards like any other state. Unlike
# the training forward these run OUTSIDE shard_map: params placed with
# ``param_specs`` NamedShardings partition the matmuls under GSPMD, and
# ``kv_cache_specs`` shards the cache's head axis over ``tp`` to match the
# column-parallel wqkv layout (a tp column-slice holds whole heads).
# Dense models only (n_experts=0); sequence parallelism does not apply to
# single-token decode.
# ---------------------------------------------------------------------------


def _gen_weights(params):
    """Generation-path view of ``params``: int8-quantized leaves (the
    ``restore_for_inference(dtype="int8")`` wire format) dequantize here,
    INSIDE the jitted forward — weights stay int8 in HBM and XLA fuses the
    per-channel scale multiply into the consuming matmul."""
    from ..ops.quant import dequantize_tree
    return dequantize_tree(params)


def _check_dense(cfg: TransformerConfig, what: str):
    if cfg.n_experts:
        raise NotImplementedError(
            f"{what} supports dense FFNs only (cfg.n_experts="
            f"{cfg.n_experts}); the MoE dispatch has no incremental-decode "
            f"path yet")


def init_kv_cache(cfg: TransformerConfig, max_slots: int, max_len: int,
                  dtype: Any = None) -> Dict:
    """Fresh per-layer K/V cache for ``max_slots`` concurrent sequences of
    up to ``max_len`` tokens (prompt + generated).

    Returns ``{"k", "v": [n_layers, max_slots, max_len, n_heads, d_head],
    "lengths": [max_slots] int32}`` — ``lengths[s]`` is how many positions
    of slot ``s`` hold real K/V. Rows beyond a slot's length are garbage by
    contract (padded prefill writes land there) and are masked out of every
    attention; a slot's row is rewritten by the next ``prefill`` into it,
    so slots recycle without clearing.

    This is the CONTIGUOUS layout: every slot reserves ``max_len`` rows
    up front, so concurrent capacity is bounded by worst-case sequence
    length. :mod:`.kv_blocks` holds the paged sibling (fixed-size block
    pool + per-slot block tables, bit-identical streams) for workloads
    where typical requests run far short of ``max_len``."""
    _check_dense(cfg, "init_kv_cache")
    d_head = cfg.d_model // cfg.n_heads
    shape = (cfg.n_layers, max_slots, max_len, cfg.n_heads, d_head)
    kv_dtype = cfg.dtype if dtype is None else dtype
    return {"k": jnp.zeros(shape, kv_dtype),
            "v": jnp.zeros(shape, kv_dtype),
            "lengths": jnp.zeros((max_slots,), jnp.int32)}


def kv_cache_specs(cfg: TransformerConfig, mesh: Mesh) -> Dict:
    """PartitionSpec tree matching :func:`init_kv_cache`: the head axis
    shards over ``tp`` (mirroring ``param_specs``' column-parallel wqkv —
    each tp rank caches exactly the heads it computes); slots and
    positions stay replicated."""
    tp = "tp" if "tp" in _axes(mesh) else None
    kv = P(None, None, None, tp, None)
    return {"k": kv, "v": kv, "lengths": P()}


def _no_delta(li, name, x, y):
    """Default adapter hook: the base matmul output passes through
    untouched (see :mod:`.lora` for the LoRA delta callbacks)."""
    return y


def _prompt_forward(params, tokens, cfg: TransformerConfig, store_kv,
                    delta=None, attend=None):
    """Shared prompt-phase forward for the contiguous and paged prefills
    (``params`` already through :func:`_gen_weights`): per layer the
    computed K/V is handed to ``store_kv(li, k, v)`` (k/v
    ``[T, n_heads, d_head]``) — the ONLY layout-specific piece — and the
    attention is the same self-contained ``flash_attention`` either way,
    so both layouts' prefill logits are bitwise identical by
    construction (the cross-layout contract tests/test_paged_kv.py
    pins). ``delta(li, name, x, y)`` adjusts each target matmul's output
    (the LoRA hook; the default passes ``y`` through bit-unchanged).
    ``attend(li, q)`` replaces the self-contained causal attention with
    a caller-supplied read (q ``[1, T, n_heads, d_head]`` → attn of the
    same shape) — the chunked-prefill hook: ``store_kv`` runs FIRST, so
    the hook may gather the just-stored rows back out of a paged pool
    and attend across an arbitrary prefix span. Returns logits
    ``[T, vocab]`` f32."""
    from ..ops.pallas_attention import flash_attention
    dl = _no_delta if delta is None else delta
    T = tokens.shape[0]
    d_head = cfg.d_model // cfg.n_heads
    x = params["embed"][tokens][None].astype(cfg.dtype)     # [1, T, D]
    for li, layer in enumerate(params["layers"]):
        h = _rms_norm(x, layer["ln1"])
        qkv = dl(li, "wqkv", h, h @ layer["wqkv"].astype(cfg.dtype))
        qkv = qkv.reshape(1, T, cfg.n_heads, 3, d_head)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        store_kv(li, k[0], v[0])
        if attend is not None:
            attn = attend(li, q).astype(cfg.dtype)
        else:
            attn = flash_attention(
                q, k, v, causal=True,
                backend=cfg.attn_backend).astype(cfg.dtype)
        a_flat = attn.reshape(1, T, cfg.n_heads * d_head)
        x = x + dl(li, "wo", a_flat,
                   a_flat @ layer["wo"].astype(cfg.dtype))
        h2 = _rms_norm(x, layer["ln2"])
        up = jax.nn.gelu(dl(li, "w1", h2,
                            h2 @ layer["w1"].astype(cfg.dtype)))
        x = x + dl(li, "w2", up, up @ layer["w2"].astype(cfg.dtype))
    x = _rms_norm(x, params["lnf"])
    return jnp.matmul(x.astype(cfg.unembed_dtype),
                      params["embed"].T.astype(cfg.unembed_dtype),
                      preferred_element_type=jnp.float32)[0]


def _step_forward(params, last_tokens, cfg: TransformerConfig, mix,
                  delta=None):
    """Shared decode-step forward (``params`` already through
    :func:`_gen_weights`): ``mix(li, q, k, v)`` does the layout-specific
    cache write + attention read (q/k/v ``[S, n_heads, d_head]`` → attn
    of the same shape); everything else — the layer math both
    bit-identity contracts ride on — exists exactly once.
    ``delta(li, name, x, y)`` adjusts each target matmul's output (the
    batched per-slot LoRA hook; row-independent by construction, so the
    alone-vs-mixed bit-identity survives it). Returns logits
    ``[S, vocab]`` f32."""
    dl = _no_delta if delta is None else delta
    S = last_tokens.shape[0]
    d_head = cfg.d_model // cfg.n_heads
    x = params["embed"][last_tokens].astype(cfg.dtype)      # [S, D]
    for li, layer in enumerate(params["layers"]):
        h = _rms_norm(x, layer["ln1"])
        qkv = dl(li, "wqkv", h, h @ layer["wqkv"].astype(cfg.dtype)
                 ).reshape(S, cfg.n_heads, 3, d_head)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
        attn = mix(li, q, k, v)
        a_flat = attn.reshape(S, cfg.n_heads * d_head)
        x = x + dl(li, "wo", a_flat,
                   a_flat @ layer["wo"].astype(cfg.dtype))
        h2 = _rms_norm(x, layer["ln2"])
        up = jax.nn.gelu(dl(li, "w1", h2,
                            h2 @ layer["w1"].astype(cfg.dtype)))
        x = x + dl(li, "w2", up, up @ layer["w2"].astype(cfg.dtype))
    x = _rms_norm(x, params["lnf"])
    return jnp.matmul(x.astype(cfg.unembed_dtype),
                      params["embed"].T.astype(cfg.unembed_dtype),
                      preferred_element_type=jnp.float32)


def prefill(params, tokens, cache: Dict, slot, cfg: TransformerConfig,
            length=None, *, adapters=None, adapter_idx=None,
            lora=None) -> Tuple[Dict, Any]:
    """Run the full prompt through the model, writing every position's K/V
    into ``cache`` at ``slot``.

    Args:
      tokens: [T] int32 prompt, optionally padded (``T`` is the compiled
        bucket; any pad token id works — padded positions' K/V are written
        but masked by ``length`` until real decode steps overwrite them).
      slot: int32 scalar — which cache row to fill (traced, so one
        compiled program serves every slot).
      length: true prompt length (int32 scalar; defaults to ``T``).
      adapters: optional stacked LoRA table (:mod:`.lora`); with it,
        ``adapter_idx`` (int32 scalar, ``-1``/None = base) picks the
        tenant's delta — data, not a compile key, so one compiled
        program serves every tenant.
      lora: the :class:`~.lora.LoraConfig` the table was built with
        (required with ``adapters``).

    Returns ``(cache', logits [T, vocab] f32)`` — logits at EVERY prompt
    position, matching one-shot :func:`forward` (the parity contract
    tests/test_generate.py pins); sampling reads row ``length - 1``.
    Reads nothing from ``cache`` rows, so a prefill's logits are
    independent of what other slots hold (the continuous-batching
    invariance contract).
    """
    _check_dense(cfg, "prefill")
    from .lora import make_delta
    delta = make_delta("prompt", adapters,
                       -1 if adapter_idx is None else adapter_idx,
                       lora, cfg)
    params = _gen_weights(params)
    T = tokens.shape[0]
    if T > cache["k"].shape[2]:
        raise ValueError(
            f"prompt bucket {T} exceeds the cache max_len "
            f"{cache['k'].shape[2]}")
    length = jnp.asarray(T if length is None else length, jnp.int32)
    slot = jnp.asarray(slot, jnp.int32)
    k_cache, v_cache = cache["k"], cache["v"]
    zero = jnp.zeros((), jnp.int32)       # x64 mode: indices must agree

    def store(li, k, v):
        nonlocal k_cache, v_cache
        idx = (jnp.asarray(li, jnp.int32), slot, zero, zero, zero)
        k_cache = lax.dynamic_update_slice(
            k_cache, k.astype(k_cache.dtype)[None, None], idx)
        v_cache = lax.dynamic_update_slice(
            v_cache, v.astype(v_cache.dtype)[None, None], idx)

    logits = _prompt_forward(params, tokens, cfg, store, delta=delta)
    lengths = cache["lengths"].at[slot].set(length)
    return {"k": k_cache, "v": v_cache, "lengths": lengths}, logits


def _cached_attention(q, k_cache, v_cache, positions):
    """One query token per slot against that slot's cache row: q [S, H, d],
    k/v_cache [S, M, H, d], positions [S] (index of the just-written
    token; attends 0..position inclusive). Same numerics as the training
    attention (f32 scores, 1/sqrt(d) scale, -1e30 mask, f32 softmax and
    value matmul, cast back) — the prefill/decode parity depends on it."""
    d = q.shape[-1]
    s = jnp.einsum("shd,smhd->shm", q.astype(jnp.float32),
                   k_cache.astype(jnp.float32)) * (float(d) ** -0.5)
    m = jnp.arange(k_cache.shape[1], dtype=jnp.int32)
    s = jnp.where(m[None, None, :] <= positions[:, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("shm,smhd->shd", p, v_cache.astype(jnp.float32))
    return out.astype(q.dtype)


def decode_step(params, last_tokens, cache: Dict, positions,
                cfg: TransformerConfig, *, adapters=None,
                adapter_idx=None, lora=None) -> Tuple[Dict, Any]:
    """One autoregressive step for every slot at once: embed each slot's
    last sampled token, write its K/V at ``positions[s]``, attend over the
    slot's cache (masked to ``<= positions[s]``), and return next-token
    logits.

    Args:
      last_tokens: [S] int32 — per-slot previous token (S = max_slots; the
        shape is FIXED, which is what makes continuous batching work: one
        compiled program regardless of occupancy).
      positions: [S] int32 — per-slot write index (== current length);
        ``-1`` marks an inactive slot, whose output row is garbage to be
        ignored (its scratch write lands at index 0 of a row that the next
        prefill into that slot rewrites before it is ever read).
      adapters: optional stacked LoRA table (:mod:`.lora`); with it,
        ``adapter_idx`` ([S] int32, ``-1`` = base row; None = all base)
        gathers each slot's delta — a mixed-adapter batch stays THIS one
        compiled program.
      lora: the :class:`~.lora.LoraConfig` the table was built with
        (required with ``adapters``).

    Returns ``(cache', logits [S, vocab] f32)``. Every per-slot row of the
    computation depends only on that slot's token, position, cache row and
    adapter row, so a request's token stream is bit-identical whether it
    decodes alone or alongside a full batch (the invariance
    tests/test_generate.py and tests/test_adapters.py pin).
    """
    _check_dense(cfg, "decode_step")
    S = last_tokens.shape[0]
    from .lora import make_delta
    delta = make_delta(
        "step", adapters,
        jnp.full((S,), -1, jnp.int32) if adapter_idx is None
        else adapter_idx, lora, cfg)
    params = _gen_weights(params)
    active = positions >= 0
    pos = jnp.where(active, positions, 0).astype(jnp.int32)
    rows = jnp.arange(S, dtype=jnp.int32)
    k_cache, v_cache = cache["k"], cache["v"]

    def mix(li, q, k, v):
        nonlocal k_cache, v_cache
        k_cache = k_cache.at[li, rows, pos].set(k.astype(k_cache.dtype))
        v_cache = v_cache.at[li, rows, pos].set(v.astype(v_cache.dtype))
        return _cached_attention(q, k_cache[li], v_cache[li], pos)

    logits = _step_forward(params, last_tokens, cfg, mix, delta=delta)
    lengths = jnp.where(active, pos + 1, cache["lengths"]
                        ).astype(jnp.int32)
    return {"k": k_cache, "v": v_cache, "lengths": lengths}, logits


def verify_step(params, draft_tokens, cache: Dict, positions,
                cfg: TransformerConfig, *, adapters=None,
                adapter_idx=None, lora=None) -> Tuple[Dict, Any]:
    """Speculative-decoding verify pass: score ``W = k + 1`` positions
    per slot in ONE forward.

    Args:
      draft_tokens: [S, W] int32 — per slot, column 0 is the slot's last
        sampled token (what ``decode_step`` would consume) and columns
        1..k its drafted continuation; unused tail columns are padding
        (any valid token id — their rows are never read by the host).
      positions: [S] int32 as in :func:`decode_step` (``-1`` inactive).

    Each column ``j`` writes its K/V at ``positions[s] + j`` and attends
    the slot's cache masked to ``<= positions[s] + j`` — writes landing
    at/past ``max_len`` are DROPPED by the scatter (out-of-bounds
    updates), so padded tail columns near the cache edge can never
    corrupt live rows.

    Returns ``(cache', logits [S, W, vocab] f32)`` — row ``j`` is the
    next-token distribution after consuming ``draft_tokens[s, :j + 1]``.

    Bit-identity contract: the ``W`` query columns are FLATTENED onto
    the slot axis, so every per-row matmul/norm and the cached
    attention run at exactly the decode-step shapes over exactly the
    per-row data sequential decode would see — logits row ``j`` and the
    K/V bytes written at ``positions[s] + j`` are bitwise identical to
    ``decode_step`` having consumed those tokens one at a time
    (tests/test_spec.py pins both). The cost is attention reading a
    ``W``-replicated cache view; a fused multi-query kernel is the
    hardware follow-up, gated behind this same signature.

    ``lengths`` bookkeeping is conservative under speculation: only
    column 0's position is claimed (the host decides the accepted count
    AFTER this program ran); the next step's write advances it past the
    accepted tokens. Rows between are written-but-unclaimed, which the
    "rows beyond lengths are garbage" contract already allows.
    """
    _check_dense(cfg, "verify_step")
    S, W = draft_tokens.shape
    from .lora import make_delta
    # Per-(slot, column) rows flatten to [S*W]; each column inherits its
    # slot's adapter row so the LoRA delta stays row-independent.
    aidx = (jnp.full((S,), -1, jnp.int32) if adapter_idx is None
            else adapter_idx)
    delta = make_delta("step", adapters, jnp.repeat(aidx, W), lora, cfg)
    params = _gen_weights(params)
    active = positions >= 0
    pos = jnp.where(active, positions, 0).astype(jnp.int32)
    rows = jnp.arange(S, dtype=jnp.int32)
    offs = jnp.arange(W, dtype=jnp.int32)   # x64 mode: indices must agree
    wpos = pos[:, None] + offs[None, :]                      # [S, W]
    flat_pos = wpos.reshape(S * W)
    k_cache, v_cache = cache["k"], cache["v"]

    def mix(li, q, k, v):
        nonlocal k_cache, v_cache
        k2 = k.reshape(S, W, k.shape[-2], k.shape[-1])
        v2 = v.reshape(S, W, v.shape[-2], v.shape[-1])
        k_cache = k_cache.at[li, rows[:, None], wpos].set(
            k2.astype(k_cache.dtype))
        v_cache = v_cache.at[li, rows[:, None], wpos].set(
            v2.astype(v_cache.dtype))
        # Each flat row (s, j) attends slot s's FULL cache row (with all
        # W fresh writes visible) under its own mask — the same [M] view
        # sequential decode at position pos+j reads.
        kg = jnp.repeat(k_cache[li], W, axis=0)
        vg = jnp.repeat(v_cache[li], W, axis=0)
        return _cached_attention(q, kg, vg, flat_pos)

    logits = _step_forward(params, draft_tokens.reshape(S * W), cfg, mix,
                           delta=delta)
    lengths = jnp.where(active, pos + 1, cache["lengths"]
                        ).astype(jnp.int32)
    return ({"k": k_cache, "v": v_cache, "lengths": lengths},
            logits.reshape(S, W, -1))


def make_parallel_train_step(cfg: TransformerConfig, mesh: Mesh,
                             optimizer: optax.GradientTransformation,
                             aux_weight: float = 0.01,
                             wire_dtype=None,
                             *,
                             zero: bool = False,
                             accum_steps: int = 1,
                             guard_nonfinite=None,
                             overlap=None,
                             fusion_threshold=None):
    """Build (init_state, step): the compiled multi-axis training step.

    ``init_state(rng)`` returns (params, opt_state) as global sharded
    arrays; ``step(params, opt_state, tokens, labels)`` runs one update and
    returns (params, opt_state, loss). tokens/labels are global
    [B, T] int32, sharded (dp, sp).

    This family is a THIN WRAPPER over the core stack (ISSUE 8): the loss
    is handed to ``training.make_train_step(mesh=, param_specs=)`` and
    everything below the loss — spec-grouped fused collectives,
    ``zero=True`` ZeRO-1 sharding of the optimizer state over ``dp``
    (tp-sharded params included), ``accum_steps`` microbatch scanning,
    the ``guard_nonfinite`` bad-step guard (default:
    ``HVD_GUARD_NONFINITE``), ``overlap`` emission and ``wire_dtype``
    reduced-precision wire — is the ONE implementation the flax plane
    runs; the duplicated grad-sync/update logic this file used to carry
    is gone. On a skipped (non-finite) step the returned loss is 0 and
    params/opt_state come back bit-unchanged.

    ``wire_dtype`` (``"bf16"``/``"fp8"``; see ``docs/performance.md``
    "Overlap & wire formats") runs the data-parallel gradient averages in
    reduced wire precision with fp32 scales and fp32 result accumulation.
    """
    from .. import training
    from ..optimizer import DistributedOptimizer

    axes = _axes(mesh)
    if cfg.n_experts and "ep" in axes \
            and cfg.n_experts != mesh.shape["ep"]:
        raise ValueError(
            f"n_experts={cfg.n_experts} must equal the ep mesh axis size "
            f"{mesh.shape['ep']} (one expert per ep rank)")
    # Batch dim shards over dp AND ep (GShard layout: ep ranks carry
    # distinct tokens; experts see everyone's via the all_to_all); sequence
    # dim over sp.
    batch_axes = tuple(a for a in ("dp", "ep") if a in axes)
    batch_spec = P(batch_axes if len(batch_axes) > 1
                   else (batch_axes[0] if batch_axes else None),
                   "sp" if "sp" in axes else None)
    specs = param_specs(cfg, mesh)

    dist_opt = DistributedOptimizer(
        optimizer, zero=zero, wire_dtype=wire_dtype, overlap=overlap,
        fusion_threshold=fusion_threshold, mesh=mesh, param_specs=specs)

    def _loss_fn(params, tokens, labels):
        if cfg.loss_chunk:
            x, aux = forward_hidden(params, tokens, cfg, mesh)
            nll = chunked_nll(x, params["embed"], labels, cfg)
        else:
            logits, aux = forward(params, tokens, cfg, mesh)
            nll = dense_nll(logits, labels)
        loss = jnp.mean(nll) + aux_weight * aux
        return loss

    def _vag(params, batch_stats, tokens, labels, rng):
        # The core step's value_and_grad contract; the transformer has no
        # batch statistics and owns its remat (cfg.remat) and rng-free
        # forward, so stats/logits ride as None.
        def lf(p):
            return _loss_fn(p, tokens, labels), (None, None)
        return jax.value_and_grad(lf, has_aux=True)(params)

    core = training.make_train_step(
        None, dist_opt, mesh=mesh, param_specs=specs,
        batch_spec=batch_spec, donate=False, accum_steps=accum_steps,
        guard_nonfinite=guard_nonfinite, overlap=overlap,
        _value_and_grad=_vag)

    def init_state(rng):
        params = init_params(rng, cfg)
        params = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs, is_leaf=lambda x: isinstance(x, P))
        return params, dist_opt.init(params)

    def _state(params, opt_state):
        return training.TrainState(step=jnp.zeros((), jnp.int32),
                                   params=params, opt_state=opt_state,
                                   batch_stats=None)

    def step(params, opt_state, tokens, labels):
        st, metrics = core(_state(params, opt_state), (tokens, labels))
        return st.params, st.opt_state, metrics["loss"]

    # AOT handle (jax .lower convention) for HLO-pinned tests.
    step.lower = lambda params, opt_state, tokens, labels: core.lower(
        _state(params, opt_state), (tokens, labels))
    return init_state, step
