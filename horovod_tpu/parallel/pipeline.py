"""Pipeline parallelism: GPipe-style microbatch pipelining over ``pp``.

Net-new TPU capability (absent from the reference). Layers are partitioned
into S stages, one per pp rank; activations flow stage-to-stage with
``ppermute`` (one ICI hop). A step processes M microbatches in
M + S - 1 ticks (the classic GPipe schedule: bubble fraction
(S-1)/(M+S-1)); every tick every stage computes, so utilization approaches
1 as M grows. Differentiable end-to-end — ``jax.grad`` through the loop
yields the reverse schedule automatically (ppermute transposes to the
reverse permutation).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def gpipe(stage_fn: Callable, stage_params, x_micro, *,
          axis_name: str = "pp"):
    """Run microbatches through the pipeline.

    Args:
      stage_fn: ``(params, act) -> act`` — one stage's computation (every
        rank runs the same structure on its own ``stage_params``).
      stage_params: this rank's stage parameters.
      x_micro: [M, mb, ...] microbatched input (replicated across pp; only
        stage 0 consumes it).
      axis_name: pipeline mesh axis (size S).

    Returns [M, mb, ...] — the last stage's outputs, broadcast to every pp
    rank (so the loss can be computed replicated).
    """
    S = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    M = x_micro.shape[0]
    act_shape = x_micro.shape[1:]
    perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(t, carry):
        buf, outs = carry
        # Stage 0 injects microbatch t (clipped; masked out past M).
        inject = x_micro[jnp.clip(t, 0, M - 1)]
        first = jnp.logical_and(r == 0, t < M)
        inp = jnp.where(first, inject, buf)
        act = stage_fn(stage_params, inp)
        # Last stage emits microbatch (t - (S-1)) at this tick.
        idx = t - (S - 1)
        emit = jnp.logical_and(r == S - 1, idx >= 0)
        safe = jnp.clip(idx, 0, M - 1)
        outs = outs.at[safe].set(jnp.where(emit, act, outs[safe]))
        # Hand activations to the next stage.
        buf = lax.ppermute(act, axis_name, perm)
        return buf, outs

    buf0 = jnp.zeros(act_shape, x_micro.dtype)
    outs0 = jnp.zeros((M,) + act_shape, x_micro.dtype)
    _, outs = lax.fori_loop(0, M + S - 1, tick, (buf0, outs0))

    # Broadcast the last stage's outputs to all pp ranks (one-hot psum).
    outs = lax.psum(jnp.where(r == S - 1, outs, jnp.zeros_like(outs)),
                    axis_name)
    return outs
