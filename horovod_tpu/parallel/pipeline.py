"""Pipeline parallelism over ``pp``: GPipe and a 1F1B-style schedule.

Net-new TPU capability (absent from the reference). Layers are partitioned
into S stages, one per pp rank; activations flow stage-to-stage with
``ppermute`` (one ICI hop).

* :func:`gpipe` — the classic schedule: M microbatches in M + S - 1 ticks
  (bubble fraction (S-1)/(M+S-1)). Differentiable end-to-end: ``jax.grad``
  through the loop yields the reverse schedule automatically — but the
  autodiff saves every tick's activations, so TRAINING memory grows O(M).
* :func:`one_f_one_b` — a 1F1B-style training step (PipeDream-flush /
  Megatron's non-interleaved schedule, adapted to lockstep SPMD): each
  "double tick" every stage runs one forward and one backward, backwards
  chasing forwards S-1 ticks behind. Only the INPUT activation of each
  in-flight microbatch is saved (the stage forward is recomputed inside
  its VJP), so activation memory is O(S) microbatches per stage instead of
  O(M) — the property that makes pipeline training usable when M is large.
  Compute is the same ~3 forwards/microbatch as gpipe-under-remat.

Both schedules are gradient-sync-free by design: they move activations
and cotangents (``ppermute``), never gradients. The caller owns the
exchange — the pipelined transformer interprets the unified spec-grouped
collective plan for it (``parallel/pp_transformer.py``, ISSUE 20) — so
the schedule composes unchanged on the full 3-D dp×tp×pp mesh
(parity-pinned against the dp-only reference in tests/test_parallel.py).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def gpipe(stage_fn: Callable, stage_params, x_micro, *,
          axis_name: str = "pp"):
    """Run microbatches through the pipeline.

    Args:
      stage_fn: ``(params, act) -> act`` — one stage's computation (every
        rank runs the same structure on its own ``stage_params``).
      stage_params: this rank's stage parameters.
      x_micro: [M, mb, ...] microbatched input (replicated across pp; only
        stage 0 consumes it).
      axis_name: pipeline mesh axis (size S).

    Returns [M, mb, ...] — the last stage's outputs, broadcast to every pp
    rank (so the loss can be computed replicated).
    """
    S = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    M = x_micro.shape[0]
    act_shape = x_micro.shape[1:]
    perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(t, carry):
        buf, outs = carry
        # Stage 0 injects microbatch t (clipped; masked out past M).
        inject = x_micro[jnp.clip(t, 0, M - 1)]
        first = jnp.logical_and(r == 0, t < M)
        inp = jnp.where(first, inject, buf)
        act = stage_fn(stage_params, inp)
        # Last stage emits microbatch (t - (S-1)) at this tick.
        idx = t - (S - 1)
        emit = jnp.logical_and(r == S - 1, idx >= 0)
        safe = jnp.clip(idx, 0, M - 1)
        outs = outs.at[safe].set(jnp.where(emit, act, outs[safe]))
        # Hand activations to the next stage.
        buf = lax.ppermute(act, axis_name, perm)
        return buf, outs

    buf0 = jnp.zeros(act_shape, x_micro.dtype)
    outs0 = jnp.zeros((M,) + act_shape, x_micro.dtype)
    _, outs = lax.fori_loop(0, M + S - 1, tick, (buf0, outs0))

    # Broadcast the last stage's outputs to all pp ranks (one-hot psum).
    outs = lax.psum(jnp.where(r == S - 1, outs, jnp.zeros_like(outs)),
                    axis_name)
    return outs


def one_f_one_b(stage_fn: Callable, stage_params, x_micro, y_micro,
                loss_fn: Callable, *, axis_name: str = "pp",
                head_params=None, inject_fn: Callable = None,
                input_grad_acc: Optional[Tuple] = None,
                return_input_grads: bool = False):
    """Memory-bounded pipelined TRAINING step (1F1B-style schedule).

    Args:
      stage_fn: ``(params, act) -> act`` — one stage's computation.
      stage_params: this rank's stage parameters (any pytree).
      x_micro: [M, mb, ...] microbatched input (stage 0 consumes it).
        With ``inject_fn``, this can be the RAW input (e.g. token ids) —
        the per-microbatch activation is produced on demand, so no
        O(M)-sized activation buffer ever exists.
      y_micro: [M, mb, ...] microbatched labels (last stage consumes it).
      loss_fn: per-microbatch loss applied to the LAST stage's output —
        ``(act, y) -> scalar``, or ``(act, y, head_params) -> scalar``
        when ``head_params`` is given (a trainable loss head — e.g. final
        norm + tied unembedding — living outside the pipeline).
      axis_name: pipeline mesh axis (size S).
      head_params: optional pytree of loss-head parameters; their
        gradients are returned (nonzero on the LAST pp rank — psum over
        pp to share, which also merges them with any input-side
        contribution to the same replicated tree).
      inject_fn: optional ``x_micro[i] -> act`` map applied at stage-0
        injection (an embedding lookup, a vision stem). Differentiation
        into it goes through ``input_grad_acc`` / ``return_input_grads``
        cotangents.
      input_grad_acc: optional ``(acc0, update)`` pair streaming the
        stage-0 input cotangents into a fixed-size accumulator instead of
        buffering all M of them: ``update(acc, i, din) -> acc`` is called
        once per backward microbatch with ``din`` already masked to zeros
        off pp rank 0 / off schedule (e.g. scatter-add into an embedding
        gradient). The final ``acc / M`` is returned. Keeps the O(S)
        memory bound that is the schedule's point.
      return_input_grads: also return d loss / d (injected input)
        ([M, mb, ...] activation-sized, nonzero on pp rank 0 — psum over
        pp to share). Prefer ``input_grad_acc`` when M is large.

    Returns ``(loss, grads[, head_grads][, acc][, x_grads])``: the mean
    loss over microbatches (identical on every pp rank) and this rank's
    ``stage_params`` gradients of it.

    Schedule (global double-tick clock ``d``): stage ``r`` runs forward of
    microbatch ``f = d - r`` and backward of microbatch
    ``b = d - (2S - 2 - r)`` — backwards trail the last stage's forwards,
    propagating one stage per tick, so at most ``2(S - r)`` microbatches
    are in flight per stage and only their input activations are kept (the
    forward is recomputed inside the VJP, the standard 1F1B + recompute
    trade). Total ticks: ``M + 2S - 2``.
    """
    S = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    M = x_micro.shape[0]
    if inject_fn is None:
        inject_fn = lambda x: x  # noqa: E731
    act_aval = jax.eval_shape(inject_fn, jax.eval_shape(
        lambda a: a[0], x_micro))
    act_shape, act_dtype = act_aval.shape, act_aval.dtype
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [((i + 1) % S, i) for i in range(S)]
    K = 2 * S  # saved-input ring depth >= max in-flight (2(S-r))
    with_head = head_params is not None

    def _head_loss(act, y, head):
        return loss_fn(act, y, head) if with_head else loss_fn(act, y)

    def dtick(d, carry):
        (in_buf, gin_buf, saved, grad_acc, head_acc, ig_acc, xg_buf,
         loss_acc) = carry

        # ---- forward of microbatch f = d - r ---------------------------
        f = d - r
        f_valid = jnp.logical_and(f >= 0, f < M)
        fi = jnp.clip(f, 0, M - 1)
        x_in = jnp.where(r == 0, inject_fn(x_micro[fi]), in_buf)
        # Remember the input for this microbatch's backward (ring slot).
        saved = saved.at[fi % K].set(
            jnp.where(f_valid, x_in, saved[fi % K]))
        act = stage_fn(stage_params, x_in)

        # ---- backward of microbatch b = d - (2S - 2 - r) ---------------
        b = d - (2 * S - 2 - r)
        b_valid = jnp.logical_and(b >= 0, b < M)
        bi = jnp.clip(b, 0, M - 1)
        a_in = saved[bi % K]
        primal, vjp = jax.vjp(stage_fn, stage_params, a_in)
        # Cotangent: the last stage differentiates the loss at its
        # (recomputed) output; every other stage uses the grad that
        # arrived from downstream last tick.
        (loss_val, (dact, dhead)) = jax.value_and_grad(
            _head_loss, argnums=(0, 2) if with_head else (0,))(
                primal, y_micro[bi], head_params) \
            if with_head else _vg_no_head(primal, y_micro[bi])
        ct = jnp.where(r == S - 1, dact.astype(gin_buf.dtype), gin_buf)
        dp, din = vjp(ct)
        last_b = jnp.logical_and(b_valid, r == S - 1)
        grad_acc = jax.tree_util.tree_map(
            lambda ga, g: ga + jnp.where(b_valid, g, jnp.zeros_like(g)),
            grad_acc, dp)
        if with_head:
            head_acc = jax.tree_util.tree_map(
                lambda ha, g: ha + jnp.where(last_b, g, jnp.zeros_like(g)),
                head_acc, dhead)
        first_b = jnp.logical_and(b_valid, r == 0)
        if input_grad_acc is not None:
            din_masked = jnp.where(first_b, din, jnp.zeros_like(din))
            ig_acc = input_grad_acc[1](ig_acc, bi, din_masked)
        if return_input_grads:
            xg_buf = xg_buf.at[bi].set(
                jnp.where(first_b, din.astype(xg_buf.dtype), xg_buf[bi]))
        loss_acc = loss_acc + jnp.where(last_b, loss_val, 0.0)

        # ---- neighbor exchange (one fwd hop, one bwd hop per tick) -----
        in_buf = lax.ppermute(act, axis_name, fwd_perm)
        gin_buf = lax.ppermute(din, axis_name, bwd_perm)
        return (in_buf, gin_buf, saved, grad_acc, head_acc, ig_acc,
                xg_buf, loss_acc)

    def _vg_no_head(act, y):
        loss_val, dact = jax.value_and_grad(loss_fn)(act, y)
        return loss_val, (dact, None)

    carry0 = (
        jnp.zeros(act_shape, act_dtype),                # in_buf
        # Cotangents carry the activation dtype (vjp of stage_fn at a
        # bf16 input yields bf16), so the buffer must match or the
        # fori_loop carry type check rejects the trace.
        jnp.zeros(act_shape, act_dtype),                # gin_buf
        jnp.zeros((K,) + act_shape, act_dtype),         # saved inputs
        jax.tree_util.tree_map(jnp.zeros_like, stage_params),
        (jax.tree_util.tree_map(jnp.zeros_like, head_params)
         if with_head else jnp.zeros((), jnp.float32)),
        (input_grad_acc[0] if input_grad_acc is not None
         else jnp.zeros((), jnp.float32)),
        (jnp.zeros((M,) + act_shape, act_dtype)
         if return_input_grads else jnp.zeros((), jnp.float32)),
        jnp.zeros((), jnp.float32),
    )
    (_, _, _, grad_acc, head_acc, ig_acc, xg_buf, loss_acc) = \
        lax.fori_loop(0, M + 2 * S - 2, dtick, carry0)

    # Mean over microbatches; loss broadcast from the last stage.
    loss = lax.psum(jnp.where(r == S - 1, loss_acc, 0.0), axis_name) / M
    grads = jax.tree_util.tree_map(lambda g: g / M, grad_acc)
    out = (loss, grads)
    if with_head:
        out = out + (jax.tree_util.tree_map(lambda g: g / M, head_acc),)
    if input_grad_acc is not None:
        out = out + (jax.tree_util.tree_map(lambda a: a / M, ig_acc),)
    if return_input_grads:
        out = out + (xg_buf / M,)
    return out
