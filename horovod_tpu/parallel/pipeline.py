"""Pipeline parallelism over ``pp``: GPipe and a 1F1B-style schedule.

Net-new TPU capability (absent from the reference). Layers are partitioned
into S stages, one per pp rank; activations flow stage-to-stage with
``ppermute`` (one ICI hop).

* :func:`gpipe` — the classic schedule: M microbatches in M + S - 1 ticks
  (bubble fraction (S-1)/(M+S-1)). Differentiable end-to-end: ``jax.grad``
  through the loop yields the reverse schedule automatically — but the
  autodiff saves every tick's activations, so TRAINING memory grows O(M).
* :func:`one_f_one_b` — a 1F1B-style training step (PipeDream-flush /
  Megatron's non-interleaved schedule, adapted to lockstep SPMD): each
  "double tick" every stage runs one forward and one backward, backwards
  chasing forwards S-1 ticks behind. Only the INPUT activation of each
  in-flight microbatch is saved (the stage forward is recomputed inside
  its VJP), so activation memory is O(S) microbatches per stage instead of
  O(M) — the property that makes pipeline training usable when M is large.
  Compute is the same ~3 forwards/microbatch as gpipe-under-remat.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def gpipe(stage_fn: Callable, stage_params, x_micro, *,
          axis_name: str = "pp"):
    """Run microbatches through the pipeline.

    Args:
      stage_fn: ``(params, act) -> act`` — one stage's computation (every
        rank runs the same structure on its own ``stage_params``).
      stage_params: this rank's stage parameters.
      x_micro: [M, mb, ...] microbatched input (replicated across pp; only
        stage 0 consumes it).
      axis_name: pipeline mesh axis (size S).

    Returns [M, mb, ...] — the last stage's outputs, broadcast to every pp
    rank (so the loss can be computed replicated).
    """
    S = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    M = x_micro.shape[0]
    act_shape = x_micro.shape[1:]
    perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(t, carry):
        buf, outs = carry
        # Stage 0 injects microbatch t (clipped; masked out past M).
        inject = x_micro[jnp.clip(t, 0, M - 1)]
        first = jnp.logical_and(r == 0, t < M)
        inp = jnp.where(first, inject, buf)
        act = stage_fn(stage_params, inp)
        # Last stage emits microbatch (t - (S-1)) at this tick.
        idx = t - (S - 1)
        emit = jnp.logical_and(r == S - 1, idx >= 0)
        safe = jnp.clip(idx, 0, M - 1)
        outs = outs.at[safe].set(jnp.where(emit, act, outs[safe]))
        # Hand activations to the next stage.
        buf = lax.ppermute(act, axis_name, perm)
        return buf, outs

    buf0 = jnp.zeros(act_shape, x_micro.dtype)
    outs0 = jnp.zeros((M,) + act_shape, x_micro.dtype)
    _, outs = lax.fori_loop(0, M + S - 1, tick, (buf0, outs0))

    # Broadcast the last stage's outputs to all pp ranks (one-hot psum).
    outs = lax.psum(jnp.where(r == S - 1, outs, jnp.zeros_like(outs)),
                    axis_name)
    return outs


def one_f_one_b(stage_fn: Callable, stage_params, x_micro, y_micro,
                loss_fn: Callable, *, axis_name: str = "pp"):
    """Memory-bounded pipelined TRAINING step (1F1B-style schedule).

    Args:
      stage_fn: ``(params, act) -> act`` — one stage's computation.
      stage_params: this rank's stage parameters (any pytree).
      x_micro: [M, mb, ...] microbatched input (stage 0 consumes it).
      y_micro: [M, mb, ...] microbatched labels (last stage consumes it).
      loss_fn: ``(act, y) -> scalar`` per-microbatch loss, applied to the
        LAST stage's output.
      axis_name: pipeline mesh axis (size S).

    Returns ``(loss, grads)``: the mean loss over microbatches (identical
    on every pp rank) and this rank's ``stage_params`` gradients of it.

    Schedule (global double-tick clock ``d``): stage ``r`` runs forward of
    microbatch ``f = d - r`` and backward of microbatch
    ``b = d - (2S - 2 - r)`` — backwards trail the last stage's forwards,
    propagating one stage per tick, so at most ``2(S - r)`` microbatches
    are in flight per stage and only their input activations are kept (the
    forward is recomputed inside the VJP, the standard 1F1B + recompute
    trade). Total ticks: ``M + 2S - 2``.
    """
    S = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    M = x_micro.shape[0]
    act_shape = x_micro.shape[1:]
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [((i + 1) % S, i) for i in range(S)]
    K = 2 * S  # saved-input ring depth >= max in-flight (2(S-r))

    def dtick(d, carry):
        in_buf, gin_buf, saved, grad_acc, loss_acc = carry

        # ---- forward of microbatch f = d - r ---------------------------
        f = d - r
        f_valid = jnp.logical_and(f >= 0, f < M)
        fi = jnp.clip(f, 0, M - 1)
        x_in = jnp.where(r == 0, x_micro[fi], in_buf)
        # Remember the input for this microbatch's backward (ring slot).
        saved = saved.at[fi % K].set(
            jnp.where(f_valid, x_in, saved[fi % K]))
        act = stage_fn(stage_params, x_in)

        # ---- backward of microbatch b = d - (2S - 2 - r) ---------------
        b = d - (2 * S - 2 - r)
        b_valid = jnp.logical_and(b >= 0, b < M)
        bi = jnp.clip(b, 0, M - 1)
        a_in = saved[bi % K]
        primal, vjp = jax.vjp(stage_fn, stage_params, a_in)
        # Cotangent: the last stage differentiates the loss at its
        # (recomputed) output; every other stage uses the grad that
        # arrived from downstream last tick.
        loss_val, dact = jax.value_and_grad(loss_fn)(primal, y_micro[bi])
        ct = jnp.where(r == S - 1, dact.astype(gin_buf.dtype), gin_buf)
        dp, din = vjp(ct)
        grad_acc = jax.tree_util.tree_map(
            lambda ga, g: ga + jnp.where(b_valid, g, jnp.zeros_like(g)),
            grad_acc, dp)
        loss_acc = loss_acc + jnp.where(
            jnp.logical_and(b_valid, r == S - 1), loss_val, 0.0)

        # ---- neighbor exchange (one fwd hop, one bwd hop per tick) -----
        in_buf = lax.ppermute(act, axis_name, fwd_perm)
        gin_buf = lax.ppermute(din, axis_name, bwd_perm)
        return in_buf, gin_buf, saved, grad_acc, loss_acc

    carry0 = (
        jnp.zeros(act_shape, x_micro.dtype),            # in_buf
        # Cotangents carry the activation dtype (vjp of stage_fn at a
        # bf16 input yields bf16), so the buffer must match or the
        # fori_loop carry type check rejects the trace.
        jnp.zeros(act_shape, x_micro.dtype),            # gin_buf
        jnp.zeros((K,) + act_shape, x_micro.dtype),     # saved inputs
        jax.tree_util.tree_map(jnp.zeros_like, stage_params),
        jnp.zeros((), jnp.float32),
    )
    _, _, _, grad_acc, loss_acc = lax.fori_loop(
        0, M + 2 * S - 2, dtick, carry0)

    # Mean over microbatches; loss broadcast from the last stage.
    loss = lax.psum(jnp.where(r == S - 1, loss_acc, 0.0), axis_name) / M
    grads = jax.tree_util.tree_map(lambda g: g / M, grad_acc)
    return loss, grads
