"""Sharding-aware checkpoint/resume for the hybrid-mesh transformer.

The replicated-DP path checkpoints through ``trainer.save_checkpoint``
(rank-0 numpy write + broadcast-on-restore — the reference's §5.4
protocol, ``keras_imagenet_resnet50.py:47-56``). The hybrid-mesh
(dp x sp x tp x ep / pp) training state is different: params and optimizer
state are GLOBAL jax.Arrays laid out by ``NamedSharding`` over the mesh —
gathering them to one host numpy tree would defeat the point of sharding
(and OOM at scale). Here orbax writes each array with its sharding
(every process writes its addressable shards) and restores arrays BACK
onto the target mesh layout taken from a template tree, so a run can
restart on the same mesh shape and bit-continue.

Resume protocol parity: ``latest_step`` is the rank-0 scan of the
reference, and in a multi-process world ``restore_sharded`` broadcasts
the resolved step from rank 0 (object broadcast over the coordination
plane) so every process resumes the same epoch even if the filesystem
view races.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax
import numpy as np

from .. import runtime
from ..trainer import apply_retention, latest_checkpoint_step


def _ckpt_path(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), f"ckpt_{step}")


def snapshot_to_host(tree: Any, timeline: Any = None) -> Any:
    """The snapshot half of an async checkpoint (``CKPT_SNAPSHOT`` timeline
    phase): one bulk device→host fetch of a pytree into numpy.

    This is the ONLY part of a save that needs the live device state — the
    returned host copy is immutable, so the training loop may donate or
    overwrite the device buffers while a background writer (e.g.
    :class:`horovod_tpu.trainer.AsyncCheckpointer`) serializes. A single
    ``jax.device_get`` over the whole tree batches the D2H transfers
    instead of syncing leaf-by-leaf.
    """
    from ..utils import timeline as _tl
    with _tl.maybe_op(timeline, "ckpt.snapshot", _tl.CKPT_SNAPSHOT):
        return jax.device_get(tree)


def save_sharded(directory: str, step: int, params: Any,
                 opt_state: Any, max_to_keep: Optional[int] = None) -> str:
    """Write the sharded (params, opt_state) trees at ``step``.

    Every process participates (orbax writes each process's addressable
    shards); retention mirrors ``trainer.save_checkpoint`` and runs on
    rank 0 only.
    """
    import orbax.checkpoint as ocp
    path = _ckpt_path(directory, step)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, {"params": params, "opt_state": opt_state},
               force=True)
    if (not runtime.is_initialized()
            or runtime.world().controller_rank == 0):
        apply_retention(directory, path, max_to_keep)
    return path


def restore_sharded(directory: str, params_template: Any,
                    opt_state_template: Any,
                    step: Optional[int] = None
                    ) -> Tuple[Any, Any, int]:
    """Restore (params, opt_state) onto the template trees' shardings.

    ``*_template`` supply structure, dtypes and target ``NamedSharding``s
    — the trees ``init_state`` returns work directly (their values are
    discarded). Returns ``(params, opt_state, step)``; in a multi-process
    world the resolved step comes from rank 0's directory scan, so all
    ranks agree even when the shared filesystem is eventually consistent.
    """
    import orbax.checkpoint as ocp
    if step is None:
        step = latest_checkpoint_step(directory)
    if runtime.is_initialized() and runtime.size() > 1:
        from ..ops.collectives import broadcast_object
        step = broadcast_object(step, root_rank=0)
    if step is None:
        raise FileNotFoundError(f"no checkpoints under {directory}")
    path = _ckpt_path(directory, int(step))
    template = {"params": params_template, "opt_state": opt_state_template}

    def _restore_args(x):
        if isinstance(x, jax.Array) or isinstance(x, jax.ShapeDtypeStruct):
            return ocp.ArrayRestoreArgs(sharding=x.sharding,
                                        global_shape=x.shape,
                                        dtype=x.dtype)
        return ocp.RestoreArgs()

    ckptr = ocp.PyTreeCheckpointer()
    restored = ckptr.restore(
        path, item=template,
        restore_args=jax.tree_util.tree_map(_restore_args, template))
    return restored["params"], restored["opt_state"], int(step)


def restore_for_inference(directory: str, step: Optional[int] = None, *,
                          mesh=None, spec_fn=None) -> Any:
    """Load a checkpoint's serving state — the restore entry point behind
    :mod:`horovod_tpu.serve`.

    Reads the newest (or ``step``-selected) ``ckpt_<step>`` under
    ``directory`` and returns the model *variables* dict the inference
    ``apply`` consumes: ``{"params": ...}`` plus ``"batch_stats"`` when
    the checkpoint carries BN statistics. Works on both checkpoint
    flavors this framework writes — the replicated ``save_checkpoint``
    TrainState pytree (``{step, params, opt_state, batch_stats}``) and
    the hybrid-mesh ``save_sharded`` tree (``{params, opt_state}``) —
    because serving needs neither the optimizer state nor the step: the
    training-only subtrees are dropped unread rather than restored and
    discarded.

    With ``mesh`` set, every leaf is placed as a global ``jax.Array``
    laid out by :func:`horovod_tpu.parallel.mesh.named_sharding_tree`
    (``spec_fn`` picks per-leaf ``PartitionSpec``s; default fully
    replicated) — so a model too big for one chip serves sharded across
    the slice with zero model-code changes. Without ``mesh``, plain host
    numpy comes back (single-host serving).
    """
    import orbax.checkpoint as ocp
    if step is None:
        step = latest_checkpoint_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = _ckpt_path(directory, int(step))
    ckptr = ocp.PyTreeCheckpointer()
    # Structure first (metadata reads no array bytes), then a PARTIAL
    # restore of just the serving subtrees: for an Adam-style optimizer
    # the opt_state is ~2x the params, so a full read would triple the
    # restore I/O and peak host memory of every server start.
    meta = ckptr.metadata(path)
    if "params" not in meta:
        raise ValueError(
            f"{path} has no 'params' subtree — not a checkpoint this "
            f"framework wrote (keys: {sorted(meta)})")
    item = {k: meta[k] for k in ("params", "batch_stats")
            if meta.get(k) is not None}
    variables = ckptr.restore(
        path, item=item, transforms={},
        restore_args=jax.tree_util.tree_map(lambda _: ocp.RestoreArgs(),
                                            item))
    if mesh is None:
        return variables
    from .mesh import named_sharding_tree
    shardings = named_sharding_tree(mesh, variables, spec_fn)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(np.asarray(x), s),
        variables, shardings)
