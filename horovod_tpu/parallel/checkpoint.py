"""Sharding-aware checkpoint/resume for the hybrid-mesh transformer.

The replicated-DP path checkpoints through ``trainer.save_checkpoint``
(rank-0 numpy write + broadcast-on-restore — the reference's §5.4
protocol, ``keras_imagenet_resnet50.py:47-56``). The hybrid-mesh
(dp x sp x tp x ep / pp) training state is different: params and optimizer
state are GLOBAL jax.Arrays laid out by ``NamedSharding`` over the mesh —
gathering them to one host numpy tree would defeat the point of sharding
(and OOM at scale). Here orbax writes each array with its sharding
(every process writes its addressable shards) and restores arrays BACK
onto the target mesh layout taken from a template tree, so a run can
restart on the same mesh shape and bit-continue.

Resume protocol parity: ``latest_step`` is the rank-0 scan of the
reference, and in a multi-process world ``restore_sharded`` broadcasts
the resolved step from rank 0 (object broadcast over the coordination
plane) so every process resumes the same epoch even if the filesystem
view races.
"""

from __future__ import annotations

import json
import os
import sys
import zlib
from typing import Any, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import runtime
from ..exceptions import CheckpointCorruptError
from ..trainer import apply_retention, latest_checkpoint_step


def _ckpt_path(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), f"ckpt_{step}")


# ---------------------------------------------------------------------------
# Integrity manifests (Check-N-Run-style, Eisenman et al. NSDI '22): every
# save writes a per-leaf checksum manifest alongside the checkpoint bytes, so
# a restore can PROVE the bytes it is about to trust are the bytes that were
# written — torn writes, truncation and bit rot are routine at fleet scale,
# and orbax's tensorstore layout does not end-to-end-checksum array data (a
# flipped byte in a ``d/`` chunk restores "successfully" as garbage).
# ---------------------------------------------------------------------------

MANIFEST_NAME = "hvd_manifest.json"


def _leaf_crc(leaf: Any) -> Optional[int]:
    """CRC32 of a leaf's canonical serialized bytes, or None when the leaf
    is not host-readable (a non-fully-addressable jax.Array in a
    multi-process world — its record still pins structure/dtype/shape)."""
    if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
        return None
    arr = np.asarray(leaf)
    return zlib.crc32(np.ascontiguousarray(arr).tobytes())


def _leaf_records(tree: Any) -> List[dict]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    records = []
    for path, leaf in flat:
        arr_like = (leaf if isinstance(leaf, jax.Array)
                    else np.asarray(leaf))
        records.append({
            "path": jax.tree_util.keystr(path),
            "shape": list(np.shape(arr_like)),
            "dtype": str(np.asarray(leaf).dtype
                         if not isinstance(leaf, jax.Array)
                         else leaf.dtype),
            "crc32": _leaf_crc(leaf),
        })
    return records


def write_manifest(path: str, tree: Any, step: Optional[int] = None,
                   extra_meta: Optional[dict] = None) -> str:
    """Write the integrity manifest for the checkpoint at ``path``.

    Called by both checkpoint flavors (``trainer.save_checkpoint`` and
    :func:`save_sharded`) strictly AFTER the orbax write finalizes and
    strictly BEFORE the elastic two-phase commit marker — a marker-bearing
    step therefore always has a manifest, and a crash at any point leaves
    either no manifest (step not committed, invisible to restore) or a
    complete one. The manifest lives INSIDE the checkpoint directory so
    retention GC removes it with the bytes it describes.

    Records the tree's per-leaf CRC32/shape/dtype plus the world and mesh
    shape that wrote it (diagnostic metadata: elastic restarts may
    legitimately restore onto a different world, so verification checks
    leaves, not worlds).
    """
    meta: dict = {"format": 1, "leaves": _leaf_records(tree)}
    if extra_meta:
        meta.update(extra_meta)
    if step is not None:
        meta["step"] = int(step)
    if runtime.is_initialized():
        meta["world_size"] = runtime.size()
        try:
            meta["mesh_shape"] = dict(runtime.mesh().shape)
        except Exception:  # noqa: BLE001 — metadata only, never fatal
            meta["mesh_shape"] = None
    manifest_path = os.path.join(path, MANIFEST_NAME)
    tmp = manifest_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, manifest_path)
    return manifest_path


def read_manifest(path: str) -> Optional[dict]:
    """Load the manifest for the checkpoint at ``path``; None when the
    checkpoint predates integrity manifests (legacy, unverifiable)."""
    manifest_path = os.path.join(path, MANIFEST_NAME)
    if not os.path.exists(manifest_path):
        return None
    try:
        with open(manifest_path) as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        raise CheckpointCorruptError(
            path, f"unreadable manifest {MANIFEST_NAME}: {e!r}") from e


def _verify_leaves(path: str, manifest: dict, restored_tree: Any,
                   subset: bool = False) -> int:
    """Match restored leaves against manifest records; raises
    :class:`CheckpointCorruptError` naming the first offending leaf.

    Matching is a multiset over (shape, dtype, crc), not a path-by-path
    walk: orbax restores container types structurally (dataclasses and
    NamedTuples come back as dicts/lists), so save-time and restore-time
    keypaths need not be comparable — but the bytes must be. ``subset``
    allows the restored tree to cover only part of the manifest (the
    partial ``restore_for_inference`` read). Returns the number of leaves
    whose CRC was actually checked.
    """
    expected: dict = {}
    for rec in manifest.get("leaves", []):
        key = (tuple(rec["shape"]), str(rec["dtype"]))
        expected.setdefault(key, []).append(rec)
    flat, _ = jax.tree_util.tree_flatten_with_path(restored_tree)
    if not subset:
        n_expected = sum(len(v) for v in expected.values())
        if len(flat) != n_expected:
            raise CheckpointCorruptError(
                path, f"manifest records {n_expected} leaves but the "
                      f"checkpoint restored {len(flat)}")
    checked = 0
    for keypath, leaf in flat:
        name = jax.tree_util.keystr(keypath)
        arr = np.asarray(leaf)
        key = (tuple(arr.shape), str(arr.dtype))
        candidates = expected.get(key)
        if not candidates:
            # A scalar's container type may not round-trip (0-d float32
            # saved as a python scalar restores as float64) — retry under
            # each manifest dtype with a value-preserving cast.
            recast = [(k, rs) for k, rs in expected.items()
                      if k[0] == tuple(arr.shape) and rs]
            for k, rs in recast:
                try:
                    cast = np.asarray(leaf, dtype=np.dtype(k[1]))
                except (TypeError, ValueError):
                    continue
                crc = zlib.crc32(np.ascontiguousarray(cast).tobytes())
                hit = next((r for r in rs if r["crc32"] == crc), None)
                if hit is not None:
                    rs.remove(hit)
                    checked += 1
                    break
            else:
                raise CheckpointCorruptError(
                    path, f"leaf {name} with shape {arr.shape} dtype "
                          f"{arr.dtype} matches no manifest record")
            continue
        crc = zlib.crc32(np.ascontiguousarray(arr).tobytes())
        hit = next((r for r in candidates if r["crc32"] == crc), None)
        if hit is None:
            # Unverifiable records (crc None — non-addressable at save
            # time) match any leaf of their shape/dtype.
            hit = next((r for r in candidates if r["crc32"] is None), None)
            if hit is None:
                want = ", ".join(r["path"] for r in candidates[:3])
                raise CheckpointCorruptError(
                    path, f"leaf {name} (shape {arr.shape}, dtype "
                          f"{arr.dtype}) CRC mismatch — bytes differ from "
                          f"what the manifest recorded for {want}")
            candidates.remove(hit)
            continue
        candidates.remove(hit)
        checked += 1
    return checked


def verify_checkpoint(path: str, *, allow_unverified: bool = True) -> bool:
    """Verify the checkpoint at ``path`` against its integrity manifest.

    Reads the full checkpoint into host memory (raw numpy, no template)
    and checks every leaf's CRC32/shape/dtype plus the leaf count against
    the manifest. Raises :class:`CheckpointCorruptError` naming the path
    and the offending leaf on any mismatch — including an orbax read that
    fails outright (truncated metadata, missing chunk files).

    Returns True when verification ran, False for a manifest-less legacy
    checkpoint (tolerated when ``allow_unverified``, raised otherwise).
    This is a full-read operation: the restore chain calls it once per
    restore attempt, not per step.
    """
    import orbax.checkpoint as ocp
    if not os.path.isdir(path):
        raise CheckpointCorruptError(path, "checkpoint directory missing")
    manifest = read_manifest(path)
    if manifest is None:
        if allow_unverified:
            return False
        raise CheckpointCorruptError(
            path, f"no {MANIFEST_NAME} — cannot verify integrity")
    try:
        restored = ocp.PyTreeCheckpointer().restore(path)
    except CheckpointCorruptError:
        raise
    except Exception as e:  # noqa: BLE001 — any read failure IS corruption
        raise CheckpointCorruptError(
            path, f"unreadable checkpoint: {type(e).__name__}: {e}") from e
    _verify_leaves(path, manifest, restored)
    return True


# ---------------------------------------------------------------------------
# ZeRO (rank-sharded) optimizer state: checkpoints store the WORLD-AGNOSTIC
# canonical form — each stacked [nshards, shard_len] shard array becomes the
# flat unpadded vector it encodes, identical no matter how many ranks wrote
# it — so an elastic restart may restore at a different world size and the
# restore re-shards onto the new world's layout (docs/checkpointing.md).
# ---------------------------------------------------------------------------


def _is_zero_state(x) -> bool:
    from ..optimizer import ZeroShardedState
    return isinstance(x, ZeroShardedState)


def _has_zero_state(tree: Any) -> bool:
    return any(_is_zero_state(l) for l in jax.tree_util.tree_leaves(
        tree, is_leaf=_is_zero_state))


def _zero_mesh_meta(tree: Any) -> Optional[dict]:
    """Mesh layout of the tree's first ZeRO plan (diagnostic metadata for
    the manifest): shard count plus, on a hybrid mesh, the scatter axis
    and the nonscatter axis sizes — so a mesh-reshape restore can log
    exactly what it is re-sharding across. None for ZeRO-free trees."""
    for l in jax.tree_util.tree_leaves(tree, is_leaf=_is_zero_state):
        if _is_zero_state(l):
            meta = {"nshards": int(l.plan.nshards)}
            if l.plan.hybrid:
                meta["scatter_axis"] = l.plan.scatter_axis
                meta["nonscatter"] = {a: int(n)
                                      for a, n in l.plan.nonscatter}
            return meta
    return None


def _zero_stays_sharded(x) -> bool:
    """A ZeRO node whose stacked arrays are not fully addressable (a
    jax.distributed world where other processes own part of them) cannot
    be canonicalized on this host — it is written AND restored in the
    sharded layout (orbax handles both collectively), and such
    checkpoints restore at the same world size only. Save and restore
    must take the same branch, so both consult this predicate."""
    return any(isinstance(l, jax.Array) and not l.is_fully_addressable
               for l in jax.tree_util.tree_leaves(x.inner))


def _canonicalize_zero(tree: Any, placeholders: bool = False) -> Any:
    """Replace every :class:`~horovod_tpu.optimizer.ZeroShardedState` node
    with its canonical (flat, unpadded, world-agnostic) form. Nodes kept
    sharded by :func:`_zero_stays_sharded` pass through unchanged — also
    when building restore templates (``placeholders=True``), since the
    checkpoint's bytes are then in the sharded layout too. No-op for
    trees without ZeRO state."""
    from ..optimizer import zero_to_canonical

    def _one(x):
        if not _is_zero_state(x) or _zero_stays_sharded(x):
            return x
        return zero_to_canonical(x, placeholders=placeholders)

    return jax.tree_util.tree_map(_one, tree, is_leaf=_is_zero_state)


def _restore_zero(template_tree: Any, restored_tree: Any) -> Any:
    """Re-shard canonically-restored ZeRO nodes onto ``template_tree``'s
    world layout (stacking + padding + the template leaves' shardings);
    nodes restored in the sharded layout (:func:`_zero_stays_sharded`)
    and all other restored leaves pass through untouched."""
    from ..optimizer import zero_from_canonical

    def _one(t, r):
        if _is_zero_state(t) and not _zero_stays_sharded(t):
            return zero_from_canonical(r.inner, t)
        return r

    return jax.tree_util.tree_map(_one, template_tree, restored_tree,
                                  is_leaf=_is_zero_state)


def snapshot_to_host(tree: Any, timeline: Any = None) -> Any:
    """The snapshot half of an async checkpoint (``CKPT_SNAPSHOT`` timeline
    phase): one bulk device→host fetch of a pytree into numpy.

    This is the ONLY part of a save that needs the live device state — the
    returned host copy is immutable, so the training loop may donate or
    overwrite the device buffers while a background writer (e.g.
    :class:`horovod_tpu.trainer.AsyncCheckpointer`) serializes. A single
    ``jax.device_get`` over the whole tree batches the D2H transfers
    instead of syncing leaf-by-leaf.
    """
    from ..utils import timeline as _tl
    with _tl.maybe_op(timeline, "ckpt.snapshot", _tl.CKPT_SNAPSHOT):
        return jax.device_get(tree)


def save_sharded(directory: str, step: int, params: Any,
                 opt_state: Any, max_to_keep: Optional[int] = None) -> str:
    """Write the sharded (params, opt_state) trees at ``step``.

    Every process participates (orbax writes each process's addressable
    shards); retention mirrors ``trainer.save_checkpoint`` and runs on
    rank 0 only. After the orbax write finalizes, rank 0 writes the
    per-leaf integrity manifest (:func:`write_manifest`) into the
    checkpoint directory — strictly before any elastic commit marker, so
    a marker-bearing step is always verifiable.

    ZeRO optimizer state is written in its canonical world-agnostic form
    (:func:`_canonicalize_zero`: flat unpadded bucket vectors; on hybrid
    meshes the 2-D form — flat GLOBAL bucket vectors, identical across
    (dp, tp) reshapes), so the manifest CRCs — and therefore
    :func:`verify_checkpoint` and the elastic fallback walk — hold across
    world-size changes AND mesh reshapes, and :func:`restore_sharded` can
    re-shard onto a different world or mesh. The manifest records the
    writing plan's mesh layout (``zero_mesh``) so the restore can log the
    reshape it performs.
    """
    import orbax.checkpoint as ocp
    path = _ckpt_path(directory, step)
    live = {"params": params, "opt_state": opt_state}
    zero_mesh = _zero_mesh_meta(live)
    tree = _canonicalize_zero(live)
    if all(not isinstance(l, jax.Array) or l.is_fully_addressable
           for l in jax.tree_util.tree_leaves(tree)):
        # One bulk device→host fetch feeds BOTH the orbax write and the
        # manifest CRCs; letting the manifest's per-leaf np.asarray run
        # against the device tree would transfer the whole state a
        # second time per commit. Restore placement is unaffected —
        # restore_sharded lays leaves out from the TEMPLATE's
        # ArrayRestoreArgs, not the saved arrays' sharding. Skipped in
        # multi-process worlds: the orbax save must see the global
        # jax.Arrays there (each process contributes its shards), and
        # non-addressable leaves never pay a host fetch anyway (their
        # manifest CRC is None).
        tree = snapshot_to_host(tree)
    ckptr = ocp.PyTreeCheckpointer()
    ckptr.save(path, tree, force=True)
    if (not runtime.is_initialized()
            or runtime.world().controller_rank == 0
            or runtime.world().env_world):
        # Rank 0 owns the shared directory in a jax.distributed world;
        # env-world ranks each own a PRIVATE directory and must manifest
        # their own copy (elastic restore verifies per-rank).
        write_manifest(path, tree, step=step,
                       extra_meta={"zero_mesh": zero_mesh}
                       if zero_mesh else None)
    if (not runtime.is_initialized()
            or runtime.world().controller_rank == 0):
        apply_retention(directory, path, max_to_keep)
    return path


def restore_sharded(directory: str, params_template: Any,
                    opt_state_template: Any,
                    step: Optional[int] = None,
                    verify: bool = True
                    ) -> Tuple[Any, Any, int]:
    """Restore (params, opt_state) onto the template trees' shardings.

    ``*_template`` supply structure, dtypes and target ``NamedSharding``s
    — the trees ``init_state`` returns work directly (their values are
    discarded). Returns ``(params, opt_state, step)``; in a multi-process
    world the resolved step comes from rank 0's directory scan, so all
    ranks agree even when the shared filesystem is eventually consistent.

    ``verify`` (default on) checks the integrity manifest first and
    raises :class:`~horovod_tpu.exceptions.CheckpointCorruptError` on a
    mismatch instead of silently resuming from garbage; pass False when
    the caller already verified this step (the elastic fallback walk).

    ZeRO optimizer state restores through its canonical world-agnostic
    form and is RE-SHARDED onto the template's world: a checkpoint
    committed by an 8-rank run restores into a 4-rank (or 16-rank)
    world's :class:`~horovod_tpu.optimizer.ZeroShardedState` templates,
    provided the model and ``HOROVOD_FUSION_THRESHOLD`` (the bucket
    plan) are unchanged.
    """
    import orbax.checkpoint as ocp
    if step is None:
        step = latest_checkpoint_step(directory)
    if runtime.is_initialized() and runtime.size() > 1:
        from ..ops.collectives import broadcast_object
        step = broadcast_object(step, root_rank=0)
    if step is None:
        raise FileNotFoundError(f"no checkpoints under {directory}")
    path = _ckpt_path(directory, int(step))
    if verify:
        verify_checkpoint(path)
    template = {"params": params_template, "opt_state": opt_state_template}
    # ZeRO nodes restore via np placeholders in the canonical layout (the
    # checkpoint's format); everything else keeps the template leaf and
    # its sharding.
    canon_template = _canonicalize_zero(template, placeholders=True)
    if _has_zero_state(template):
        manifest = read_manifest(path)
        saved_world = manifest.get("world_size") if manifest else None
        if (runtime.is_initialized() and saved_world is not None
                and saved_world != runtime.size()):
            print(f"[ckpt] re-sharding ZeRO optimizer state: checkpoint "
                  f"written by a world of {saved_world}, restoring into "
                  f"{runtime.size()}", file=sys.stderr, flush=True)
        saved_zm = manifest.get("zero_mesh") if manifest else None
        cur_zm = _zero_mesh_meta(template)
        if saved_zm is not None and cur_zm is not None \
                and saved_zm != cur_zm:
            # 2-D canonical form at work: same global bytes, new (dp, tp)
            # split — e.g. a (dp=4, tp=2) checkpoint restoring at
            # (dp=2, tp=4).
            print(f"[ckpt] re-sharding ZeRO optimizer state across mesh "
                  f"reshape: {saved_zm} -> {cur_zm}",
                  file=sys.stderr, flush=True)

    def _restore_args(x):
        if isinstance(x, jax.Array) or isinstance(x, jax.ShapeDtypeStruct):
            return ocp.ArrayRestoreArgs(sharding=x.sharding,
                                        global_shape=x.shape,
                                        dtype=x.dtype)
        return ocp.RestoreArgs()

    ckptr = ocp.PyTreeCheckpointer()
    restored = ckptr.restore(
        path, item=canon_template,
        restore_args=jax.tree_util.tree_map(_restore_args, canon_template))
    restored = _restore_zero(template, restored)
    return restored["params"], restored["opt_state"], int(step)


# ---------------------------------------------------------------------------
# LoRA adapter persistence (the multi-tenant serving plane): one directory
# per adapter, manifest-CRC-verified exactly like the base checkpoints, so
# a hot-load can PROVE the delta it is about to serve. An adapter is tiny
# (rank-r pairs; parallel/lora.py has the math) — the full-read verify that
# would be expensive per training commit costs microseconds here.
# ---------------------------------------------------------------------------


def adapter_path(directory: str, name: str) -> str:
    """Where the adapter ``name`` lives under ``directory``
    (``adapter_<name>``, next to the base ``ckpt_<step>`` dirs). The
    name rule is shared with :class:`~horovod_tpu.serve.adapters.
    AdapterRegistry` (one identifier grammar everywhere an adapter name
    travels — paths, labels, prefix-reuse salts)."""
    from .lora import check_adapter_name
    check_adapter_name(name)
    return os.path.join(os.path.abspath(directory), f"adapter_{name}")


def save_adapter(directory: str, name: str, adapter: Any) -> str:
    """Write the adapter tree to ``<directory>/adapter_<name>`` with its
    integrity manifest (:func:`write_manifest` — same ordering contract
    as the base flavors: manifest strictly after the orbax write
    finalizes). Base checkpoints in the same directory are untouched;
    returns the adapter path."""
    import orbax.checkpoint as ocp
    path = adapter_path(directory, name)
    tree = jax.tree_util.tree_map(np.asarray, adapter)
    ocp.PyTreeCheckpointer().save(path, tree, force=True)
    write_manifest(path, tree, extra_meta={"adapter_name": name})
    return path


def restore_adapter(directory: str, name: str, *,
                    verify: bool = True) -> Any:
    """Read the adapter ``name`` back as a host tree, CRC-verifying every
    leaf against its manifest first (the same verify walk the base
    restore chain uses): a corrupt adapter raises
    :class:`~horovod_tpu.exceptions.CheckpointCorruptError` naming the
    path and the offending leaf — and the base weights it would have
    ridden on are never touched, so one tenant's rotted delta cannot
    take the whole engine down."""
    import orbax.checkpoint as ocp
    path = adapter_path(directory, name)
    if not os.path.isdir(path):
        raise FileNotFoundError(f"no adapter {name!r} under {directory} "
                                f"(looked for {path})")
    try:
        restored = ocp.PyTreeCheckpointer().restore(path)
    except Exception as e:  # noqa: BLE001 — any read failure IS corruption
        raise CheckpointCorruptError(
            path, f"unreadable adapter: {type(e).__name__}: {e}") from e
    if verify:
        manifest = read_manifest(path)
        if manifest is None:
            raise CheckpointCorruptError(
                path, f"no {MANIFEST_NAME} — cannot verify adapter "
                      f"integrity")
        _verify_leaves(path, manifest, restored)
    return restored


#: restore_for_inference's serving dtypes. None = as stored; "int8" is
#: weight-only per-channel quantization (ops/quant.py) the generation
#: forward dequantizes in-jit.
INFERENCE_DTYPES = (None, "fp32", "bf16", "int8")


def _inference_cast(variables: Any, dtype: Optional[str]) -> Any:
    """Apply the serving dtype AFTER restore+CRC-verify: manifests record
    the stored fp32 bytes, so verification must never see the quantized
    or downcast view (the int8 round-trip contract)."""
    if dtype is None:
        return variables
    if dtype == "int8":
        from ..ops.quant import quantize_tree
        return quantize_tree(variables)
    target = {"fp32": np.float32, "bf16": jnp.bfloat16}[dtype]

    def _one(x):
        a = np.asarray(x)
        return a.astype(target) if np.issubdtype(a.dtype, np.floating) \
            else a

    return jax.tree_util.tree_map(_one, variables)


def restore_for_inference(directory: str, step: Optional[int] = None, *,
                          mesh=None, spec_fn=None,
                          dtype: Optional[str] = None) -> Any:
    """Load a checkpoint's serving state — the restore entry point behind
    :mod:`horovod_tpu.serve`.

    Reads the newest (or ``step``-selected) ``ckpt_<step>`` under
    ``directory`` and returns the model *variables* dict the inference
    ``apply`` consumes: ``{"params": ...}`` plus ``"batch_stats"`` when
    the checkpoint carries BN statistics. Works on both checkpoint
    flavors this framework writes — the replicated ``save_checkpoint``
    TrainState pytree (``{step, params, opt_state, batch_stats}``) and
    the hybrid-mesh ``save_sharded`` tree (``{params, opt_state}``) —
    because serving needs neither the optimizer state nor the step: the
    training-only subtrees are dropped unread rather than restored and
    discarded.

    ``dtype`` picks the serving precision (:data:`INFERENCE_DTYPES`;
    validated eagerly, before any checkpoint I/O): ``None`` serves the
    stored dtypes, ``"fp32"``/``"bf16"`` cast every float leaf, and
    ``"int8"`` quantizes matmul weights (float leaves of ndim >= 2) to
    :class:`~horovod_tpu.ops.quant.QuantizedTensor` — int8 payload +
    per-channel f32 scales that the generation forward dequantizes
    in-jit (weights stay int8 in HBM). Quantization happens strictly
    AFTER manifest verification: CRCs are checked against the stored
    fp32 leaves, never the quantized view, so ``verify_checkpoint`` and
    the int8 serving path see the same bytes.

    With ``mesh`` set, every leaf is placed as a global ``jax.Array``
    laid out by :func:`horovod_tpu.parallel.mesh.named_sharding_tree`
    (``spec_fn`` picks per-leaf ``PartitionSpec``s; default fully
    replicated) — so a model too big for one chip serves sharded across
    the slice with zero model-code changes. Without ``mesh``, plain host
    numpy comes back (single-host serving).

    A truncated or otherwise unreadable checkpoint raises
    :class:`~horovod_tpu.exceptions.CheckpointCorruptError` naming the
    path — never a raw orbax/tensorstore traceback — and when an
    integrity manifest is present the restored serving subtrees are
    CRC-verified against it (a subset check: the training-only subtrees
    stay unread, which is the point of the partial restore).
    """
    if dtype not in INFERENCE_DTYPES:
        raise ValueError(
            f"restore_for_inference dtype={dtype!r} is not supported; "
            f"supported: {INFERENCE_DTYPES} (None = as stored)")
    import orbax.checkpoint as ocp
    if step is None:
        step = latest_checkpoint_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = _ckpt_path(directory, int(step))
    ckptr = ocp.PyTreeCheckpointer()
    # Structure first (metadata reads no array bytes), then a PARTIAL
    # restore of just the serving subtrees: for an Adam-style optimizer
    # the opt_state is ~2x the params, so a full read would triple the
    # restore I/O and peak host memory of every server start.
    try:
        meta = ckptr.metadata(path)
    except Exception as e:  # noqa: BLE001 — surface as corruption, named
        raise CheckpointCorruptError(
            path, f"unreadable checkpoint metadata: "
                  f"{type(e).__name__}: {e}") from e
    if "params" not in meta:
        raise ValueError(
            f"{path} has no 'params' subtree — not a checkpoint this "
            f"framework wrote (keys: {sorted(meta)})")
    item = {k: meta[k] for k in ("params", "batch_stats")
            if meta.get(k) is not None}
    try:
        variables = ckptr.restore(
            path, item=item, transforms={},
            restore_args=jax.tree_util.tree_map(lambda _: ocp.RestoreArgs(),
                                                item))
    except Exception as e:  # noqa: BLE001
        raise CheckpointCorruptError(
            path, f"unreadable checkpoint: {type(e).__name__}: {e}") from e
    manifest = read_manifest(path)
    if manifest is not None:
        _verify_leaves(path, manifest, variables, subset=True)
    variables = _inference_cast(variables, dtype)
    if mesh is None:
        return variables
    from .mesh import named_sharding_tree
    shardings = named_sharding_tree(mesh, variables, spec_fn)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(np.asarray(x), s),
        variables, shardings)
