"""LoRA adapters for the generation transformer: M fine-tunes, one base.

"Millions of users" in practice means thousands of fine-tuned variants
of ONE base model, not one model per tenant (ROADMAP item 5). Full
fine-tunes don't fit that shape — every variant would cost a second copy
of the base weights in HBM and its own engine — but rank-``r`` LoRA
deltas do: a tenant's fine-tune is ``W + (alpha/r) · A @ B`` per target
matrix, where ``A [d_in, r]`` and ``B [r, d_out]`` cost
``4·r·(d_in + d_out)`` bytes against the ``4·d_in·d_out`` of the full
matrix — at ``r=8`` on a 4096-wide model that is ~250x smaller, so
hundreds of tenants share one resident base.

The serving-critical property is HOW the delta is applied. Stacking M
adapters into ``[M, d_in, r]`` / ``[M, r, d_out]`` tables and giving
every decode slot an ``adapter_idx`` (``-1`` = base) turns tenant
identity into *data*: the delta is a gather + two batched low-rank
matmuls inside the SAME jitted ``prefill``/``decode_step``, so a
mixed-adapter decode batch stays ONE fixed-shape compiled program —
adapter_idx is never a compile key, and hot-loading a tenant never
recompiles anything (the contract ``tests/test_adapters.py`` pins).

Per-slot rows stay numerically independent (the gather takes row ``s``'s
own ``A``/``B``; both einsums contract within a row), so a tenant's
stream is bit-identical whether it decodes alone, in a mixed-adapter
batch, or interleaved with base traffic — base rows are guarded with a
``where`` select (never ``y + 0.0``, which would flip a ``-0.0``), so a
base stream through an adapter-enabled engine is bit-identical to one
through a plain engine.

Adapter param trees mirror ``transformer.init_params``'s layer list:
``{"layers": [{target: {"a": [d_in, r], "b": [r, d_out]}}, ...]}`` with
targets drawn from :data:`LORA_TARGETS` (the four dense matmuls of each
block). Device-table lifecycle (capacity, hot-load/evict, refcounts,
quotas) lives in :class:`horovod_tpu.serve.adapters.AdapterRegistry`;
persistence with the manifest-CRC walk in
``parallel.checkpoint.save_adapter``/``restore_adapter``.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

from .transformer import TransformerConfig

#: The per-layer dense matmuls a LoRA delta can target, in forward order.
LORA_TARGETS = ("wqkv", "wo", "w1", "w2")

# Adapter names are identifiers, not free text: they become checkpoint
# directory suffixes, Prometheus label values, AND components of the
# engine's prefix-reuse registry salt — where a name containing "\x00"
# plus digits could forge another (name, generation) pair's key and
# alias two tenants' cached K/V. This charset makes the salt's
# "name\x00gen\x00" framing unambiguous by construction.
_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,127}$")

#: Tenant keys the serving plane claims for itself: ``base`` is the
#: adapter-less traffic class (quotas/metrics/in-flight accounting key
#: on it) and ``retired`` the metric-fold aggregate for evicted tenants
#: — an adapter under either name would conflate two traffic classes.
RESERVED_ADAPTER_NAMES = ("base", "retired")


def check_adapter_name(name: str) -> str:
    """Validate (and return) an adapter name; ``ValueError`` otherwise."""
    if not isinstance(name, str) or not _NAME_RE.match(name):
        raise ValueError(
            f"adapter name must match {_NAME_RE.pattern} (letters, "
            f"digits, '._-', max 128 chars), got {name!r}")
    if name in RESERVED_ADAPTER_NAMES:
        raise ValueError(
            f"adapter name {name!r} is reserved "
            f"({RESERVED_ADAPTER_NAMES}: the adapter-less traffic class "
            f"and the evicted-tenant metric fold)")
    return name


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    """Adapter shape knobs. ``rank`` is the low-rank width ``r``;
    ``alpha`` the usual LoRA numerator (applied delta is scaled by
    ``alpha / rank``); ``targets`` the per-layer matmuls carrying a
    delta (default: all four)."""

    rank: int = 4
    alpha: float = 8.0
    targets: Tuple[str, ...] = LORA_TARGETS

    def __post_init__(self):
        if self.rank < 1:
            raise ValueError(f"rank must be >= 1, got {self.rank}")
        if not self.alpha > 0:
            raise ValueError(f"alpha must be > 0, got {self.alpha}")
        targets = tuple(self.targets)
        if not targets:
            raise ValueError("targets must name at least one matmul")
        bad = [t for t in targets if t not in LORA_TARGETS]
        if bad:
            raise ValueError(
                f"unknown LoRA target(s) {bad}; supported: {LORA_TARGETS}")
        object.__setattr__(self, "targets", targets)

    @property
    def scaling(self) -> float:
        return self.alpha / self.rank


def target_shapes(cfg: TransformerConfig) -> Dict[str, Tuple[int, int]]:
    """``target -> (d_in, d_out)`` of the base matmuls a delta rides."""
    d = cfg.d_model
    return {"wqkv": (d, 3 * d), "wo": (d, d),
            "w1": (d, cfg.d_ff), "w2": (cfg.d_ff, d)}


def adapter_bytes(cfg: TransformerConfig, lora: LoraConfig) -> int:
    """Host/HBM bytes of ONE adapter (f32 A/B pairs) — the number the
    docs' memory math quotes against a full fine-tune."""
    shapes = target_shapes(cfg)
    per_layer = sum(4 * lora.rank * (shapes[t][0] + shapes[t][1])
                    for t in lora.targets)
    return cfg.n_layers * per_layer


def init_adapter(rng, cfg: TransformerConfig, lora: LoraConfig,
                 b_scale: float = 0.0) -> Dict:
    """Fresh adapter tree: ``A ~ N(0, 1/d_in)`` and ``B = 0`` (the
    standard LoRA init — the delta starts exactly zero). ``b_scale > 0``
    randomizes ``B`` instead (useful for tests/benches that need M
    DISTINCT tenants without running M fine-tunes)."""
    shapes = target_shapes(cfg)
    keys = jax.random.split(rng, 2 * cfg.n_layers * len(lora.targets))
    ki = iter(range(len(keys)))
    layers = []
    for _ in range(cfg.n_layers):
        layer = {}
        for t in lora.targets:
            d_in, d_out = shapes[t]
            a = (jax.random.normal(keys[next(ki)], (d_in, lora.rank))
                 * d_in ** -0.5)
            kb = keys[next(ki)]
            b = (jax.random.normal(kb, (lora.rank, d_out)) * b_scale
                 if b_scale else jnp.zeros((lora.rank, d_out)))
            layer[t] = {"a": a.astype(jnp.float32),
                        "b": jnp.asarray(b, jnp.float32)}
        layers.append(layer)
    return {"layers": layers}


def check_adapter(adapter: Any, cfg: TransformerConfig,
                  lora: LoraConfig) -> None:
    """Eagerly reject an adapter tree that does not fit (cfg, lora) —
    a shape mismatch must fail at load time with the culprit named, not
    surface as an XLA error inside a decode step."""
    shapes = target_shapes(cfg)
    layers = adapter.get("layers") if isinstance(adapter, dict) else None
    if layers is None or len(layers) != cfg.n_layers:
        raise ValueError(
            f"adapter tree must be {{'layers': [... x {cfg.n_layers}]}}, "
            f"got layers="
            f"{None if layers is None else len(layers)}")
    for li, layer in enumerate(layers):
        if set(layer) != set(lora.targets):
            raise ValueError(
                f"adapter layer {li} targets {sorted(layer)} != "
                f"configured {sorted(lora.targets)}")
        for t, pair in layer.items():
            d_in, d_out = shapes[t]
            if not isinstance(pair, dict) or set(pair) != {"a", "b"}:
                raise ValueError(
                    f"adapter layer {li} target {t!r} must be a "
                    f"{{'a', 'b'}} pair, got "
                    f"{sorted(pair) if isinstance(pair, dict) else type(pair).__name__}")
            a_shape = tuple(jnp.shape(pair["a"]))
            b_shape = tuple(jnp.shape(pair["b"]))
            if a_shape != (d_in, lora.rank) or b_shape != (lora.rank,
                                                           d_out):
                raise ValueError(
                    f"adapter layer {li} target {t!r}: a{a_shape} / "
                    f"b{b_shape} do not match expected "
                    f"a({d_in}, {lora.rank}) / b({lora.rank}, {d_out})")


def stack_adapters(adapters: Sequence[Any]) -> Any:
    """Stack N same-shaped adapter trees into one ``[N, ...]``-leaved
    table (the gather target of the batched application)."""
    if not adapters:
        raise ValueError("stack_adapters needs at least one adapter")
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *adapters)


def empty_adapter_table(cfg: TransformerConfig, lora: LoraConfig,
                        capacity: int) -> Any:
    """All-zero stacked table of ``capacity`` rows — a zero row IS the
    base model (delta 0), so unoccupied table rows are harmless even if
    a stale index ever gathered one."""
    if capacity < 1:
        raise ValueError(f"capacity must be >= 1, got {capacity}")
    shapes = target_shapes(cfg)
    layer = {t: {"a": jnp.zeros((capacity, shapes[t][0], lora.rank),
                                jnp.float32),
                 "b": jnp.zeros((capacity, lora.rank, shapes[t][1]),
                                jnp.float32)}
             for t in lora.targets}
    return {"layers": [jax.tree_util.tree_map(lambda x: x, layer)
                       for _ in range(cfg.n_layers)]}


# ---------------------------------------------------------------------------
# Batched application — the delta callbacks transformer._prompt_forward /
# _step_forward thread past every target matmul. Both run INSIDE the
# jitted generation programs; adapter_idx is a traced input (data, not a
# compile key).
# ---------------------------------------------------------------------------


def _table_rows(adapters: Any) -> int:
    for layer in adapters["layers"]:
        for pair in layer.values():
            return int(jnp.shape(pair["a"])[0])
    raise ValueError("adapter table has no target pairs")


def prompt_delta(adapters: Any, adapter_idx, lora: LoraConfig,
                 cfg: TransformerConfig):
    """Delta callback for the single-sequence prompt forward: ONE
    adapter (scalar ``adapter_idx``; ``-1`` = base → the matmul output
    passes through bit-unchanged via a ``where`` select)."""
    n = _table_rows(adapters)
    idx = jnp.asarray(adapter_idx, jnp.int32)
    safe = jnp.clip(idx, 0, n - 1)
    scale = jnp.asarray(lora.scaling, cfg.dtype)

    def delta(li, name, x, y):
        pair = adapters["layers"][li].get(name)
        if pair is None:
            return y
        a = pair["a"][safe].astype(cfg.dtype)      # [d_in, r]
        b = pair["b"][safe].astype(cfg.dtype)      # [r, d_out]
        return jnp.where(idx >= 0, y + ((x @ a) @ b) * scale, y)

    return delta


def step_delta(adapters: Any, adapter_idx, lora: LoraConfig,
               cfg: TransformerConfig):
    """Delta callback for the fixed-shape decode step: per-slot
    ``adapter_idx [S]`` gathers each row's A/B pair and applies the
    delta via two batched low-rank einsums. Every contraction stays
    within its slot row, so the per-slot independence (and therefore
    the alone-vs-mixed bit-identity) of ``decode_step`` is preserved."""
    n = _table_rows(adapters)
    idx = jnp.asarray(adapter_idx, jnp.int32)      # [S]
    active = idx >= 0
    safe = jnp.clip(idx, 0, n - 1)
    scale = jnp.asarray(lora.scaling, cfg.dtype)

    def delta(li, name, x, y):
        pair = adapters["layers"][li].get(name)
        if pair is None:
            return y
        a = pair["a"][safe].astype(cfg.dtype)      # [S, d_in, r]
        b = pair["b"][safe].astype(cfg.dtype)      # [S, r, d_out]
        xa = jnp.einsum("sd,sdr->sr", x, a)
        d = jnp.einsum("sr,sre->se", xa, b) * scale
        return jnp.where(active[:, None], y + d, y)

    return delta


def make_delta(kind: str, adapters: Any, adapter_idx, lora: LoraConfig,
               cfg: TransformerConfig):
    """Shared validation + dispatch for the four generation entry points
    (contiguous/paged × prefill/decode): ``kind`` is ``"prompt"`` or
    ``"step"``; returns ``None`` when no adapter table is given."""
    if adapters is None:
        return None
    if lora is None:
        raise ValueError(
            "adapters= needs lora=LoraConfig(...) (the rank/alpha/targets "
            "the table was built with)")
    builder = prompt_delta if kind == "prompt" else step_delta
    return builder(adapters, adapter_idx, lora, cfg)
