"""Tensor parallelism: Megatron-style sharded matmul pairs over a ``tp`` axis.

Net-new TPU capability (absent from the reference, SURVEY §2.4). The
canonical pattern keeps activations replicated across tp while weights are
sharded: a **column-parallel** matmul (out-features sharded, no
communication) feeds a **row-parallel** matmul (in-features sharded, one
``psum`` to recombine) — one collective per MLP/attention block, riding ICI.

These are functions over explicit param arrays (already local shards inside
``shard_map``); ``init_column/init_row`` build the local shard directly from
the tp rank so no full-size weight ever materializes.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def init_column(rng, d_in: int, d_out: int, axis_name: str = "tp",
                dtype=jnp.float32):
    """Local [d_in, d_out/S] shard of a column-parallel weight; each tp rank
    folds its index into the rng so shards differ but dp/sp replicas agree."""
    S = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    local = jax.random.fold_in(rng, r)
    scale = 1.0 / (d_in ** 0.5)
    return (jax.random.normal(local, (d_in, d_out // S)) * scale).astype(dtype)


def init_row(rng, d_in: int, d_out: int, axis_name: str = "tp",
             dtype=jnp.float32):
    """Local [d_in/S, d_out] shard of a row-parallel weight."""
    S = lax.axis_size(axis_name)
    r = lax.axis_index(axis_name)
    local = jax.random.fold_in(rng, r)
    scale = 1.0 / (d_in ** 0.5)
    return (jax.random.normal(local, (d_in // S, d_out)) * scale).astype(dtype)


def column_parallel(x, w):
    """[..., d_in] @ [d_in, d_out_local] -> [..., d_out_local]; no comm —
    the output stays sharded on its feature dim across tp."""
    return x @ w


def row_parallel(x_local, w, axis_name: str = "tp"):
    """[..., d_in_local] @ [d_in_local, d_out] -> psum -> replicated
    [..., d_out]: the single collective of the Megatron pair."""
    return lax.psum(x_local @ w, axis_name)
