"""Paged KV-cache: a block-table layout for the generation cache.

The contiguous cache (``transformer.init_kv_cache``) reserves ``max_len``
rows per slot, so concurrent-user capacity is bounded by the WORST-CASE
sequence length even when typical requests are short — the fragmentation
problem paged attention solves. Here the cache is a fixed pool of
``n_blocks`` blocks of ``block_size`` positions each
(``[L, n_blocks, block_size, H, dh]``); a slot owns a *list* of blocks
(its block-table row), "cache full" becomes "block pool empty", and slot
count decouples from ``max_len``: short requests hold only the blocks
they actually fill.

Two halves:

* **Device side** — :func:`init_paged_kv_cache` /
  :func:`paged_prefill` / :func:`paged_decode_step`: fixed-shape jitted
  programs that scatter/gather K/V *through the block table* (a
  ``[max_slots, max_blocks]`` int32 input, host-managed, passed per
  call). The attention math is bit-for-bit the contiguous path's: prefill
  runs the same self-contained ``flash_attention`` (logits never read the
  cache), and the decode gather reassembles each slot's
  ``[max_blocks·block_size, H, dh]`` view before the SAME
  ``_cached_attention`` einsum — so when the padded depths line up
  (``max_len % block_size == 0``) a generation stream is **bit-identical**
  across contiguous and paged layouts (pinned in
  ``tests/test_paged_kv.py``). A Pallas kernel that gathers blocks
  directly (no materialized per-slot view) sits behind ``kernel=True``
  (:mod:`horovod_tpu.ops.pallas_paged_attention`).

* **Host side** — :class:`BlockManager`: free-list allocation,
  per-block refcounts, and a prefix registry for copy-on-write sharing
  of full block-aligned prompt prefixes. A common system prompt is
  written once and *shared* by every stream whose prompt starts with it
  (refcounted); divergence is naturally copy-on-write because only FULL
  prompt-covered blocks are ever shared — a writer's first divergent
  position lands in the next (freshly allocated, private) block, and
  prefill writes aimed at shared blocks are redirected to the reserved
  trash block so a sharer can never perturb the registered bytes.

Physical block 0 is the **trash block**: never allocated, the target of
every redirected or inactive-slot write, and the padding entry of every
block-table row. Garbage landing there is masked out of every attention
by the per-slot length masking (exactly the contiguous cache's
rows-beyond-length contract).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .transformer import (TransformerConfig, _cached_attention,
                          _check_dense, _gen_weights, _prompt_forward,
                          _step_forward)

#: Physical block 0 — reserved, never allocated; see module docstring.
TRASH_BLOCK = 0


def blocks_for(n_tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``n_tokens`` cache positions."""
    return -(-int(n_tokens) // int(block_size))


def init_paged_kv_cache(cfg: TransformerConfig, n_blocks: int,
                        block_size: int, max_slots: int,
                        dtype: Any = None) -> Dict:
    """Fresh paged K/V pool: ``{"k", "v":
    [n_layers, n_blocks, block_size, n_heads, d_head], "lengths":
    [max_slots] int32}``.

    Block tables are NOT part of the device cache — they change at every
    admission and are host-managed (:class:`BlockManager`), passed into
    :func:`paged_prefill` / :func:`paged_decode_step` as int32 inputs.
    ``n_blocks`` includes the reserved trash block, so ``n_blocks - 1``
    blocks are usable; memory is ``2 · n_layers · n_blocks · block_size ·
    d_model`` elements regardless of ``max_slots``.
    """
    _check_dense(cfg, "init_paged_kv_cache")
    if n_blocks < 2:
        raise ValueError(
            f"n_blocks must be >= 2 (block 0 is the reserved trash "
            f"block), got {n_blocks}")
    if block_size < 1 or (block_size & (block_size - 1)):
        raise ValueError(
            f"block_size must be a power of two (prefill buckets are "
            f"powers of two and chunk the prompt by block), got "
            f"{block_size}")
    d_head = cfg.d_model // cfg.n_heads
    shape = (cfg.n_layers, n_blocks, block_size, cfg.n_heads, d_head)
    kv_dtype = cfg.dtype if dtype is None else dtype
    return {"k": jnp.zeros(shape, kv_dtype),
            "v": jnp.zeros(shape, kv_dtype),
            "lengths": jnp.zeros((max_slots,), jnp.int32)}


def paged_kv_cache_specs(cfg: TransformerConfig, mesh: Mesh) -> Dict:
    """PartitionSpec tree matching :func:`init_paged_kv_cache`: the head
    axis shards over ``tp`` (mirroring ``param_specs``' column-parallel
    wqkv, exactly as the contiguous ``kv_cache_specs``); blocks and
    positions stay replicated."""
    tp = "tp" if "tp" in set(mesh.axis_names) else None
    kv = P(None, None, None, tp, None)
    return {"k": kv, "v": kv, "lengths": P()}


def paged_prefill(params, tokens, cache: Dict, slot, write_row,
                  cfg: TransformerConfig, length=None, *,
                  adapters=None, adapter_idx=None,
                  lora=None) -> Tuple[Dict, Any]:
    """Full-prompt forward scattering every position's K/V through
    ``write_row`` into the block pool.

    Args:
      tokens: [T] int32 prompt at a compiled bucket width (power of two).
      slot: int32 scalar — which ``lengths`` row this stream owns.
      write_row: [max_blocks] int32 — physical block for each logical
        block of the sequence. Entries for SHARED prefix blocks (and for
        bucket padding beyond the slot's allocation) point at
        :data:`TRASH_BLOCK`, so a prefill can never write into a block
        another stream reads.
      length: true prompt length (defaults to ``T``).
      adapters / adapter_idx / lora: the LoRA hook, exactly as in the
        contiguous ``prefill`` (scalar ``adapter_idx``, ``-1`` = base).

    Returns ``(cache', logits [T, vocab] f32)``. The attention is the
    same self-contained ``flash_attention`` as the contiguous
    ``prefill`` — logits read nothing from the pool, so they are
    bit-identical to the contiguous layout's for the same prompt and
    bucket (the cross-layout contract ``tests/test_paged_kv.py`` pins).
    """
    _check_dense(cfg, "paged_prefill")
    from .lora import make_delta
    delta = make_delta("prompt", adapters,
                       -1 if adapter_idx is None else adapter_idx,
                       lora, cfg)
    params = _gen_weights(params)
    T = tokens.shape[0]
    bs = cache["k"].shape[2]
    max_blocks = write_row.shape[0]
    if T > max_blocks * bs:
        raise ValueError(
            f"prompt bucket {T} exceeds the table depth "
            f"{max_blocks} blocks × {bs}")
    # Block-aligned chunks of the bucket; the last may be partial (the
    # top bucket is max_len itself, which need not align). Chunk sizes
    # are static, so the scatter stays one fixed-shape program.
    chunks = [(j * bs, bs) for j in range(T // bs)]
    if T % bs:
        chunks.append((T - T % bs, T % bs))
    length = jnp.asarray(T if length is None else length, jnp.int32)
    slot = jnp.asarray(slot, jnp.int32)
    k_pool, v_pool = cache["k"], cache["v"]
    zero = jnp.zeros((), jnp.int32)     # x64 mode: indices must agree

    def store(li, k, v):
        nonlocal k_pool, v_pool
        li32 = jnp.asarray(li, jnp.int32)
        for j, (start, rows) in enumerate(chunks):
            idx = (li32, write_row[j], zero, zero, zero)
            k_pool = lax.dynamic_update_slice(
                k_pool, k[start:start + rows]
                .astype(k_pool.dtype)[None, None], idx)
            v_pool = lax.dynamic_update_slice(
                v_pool, v[start:start + rows]
                .astype(v_pool.dtype)[None, None], idx)

    logits = _prompt_forward(params, tokens, cfg, store, delta=delta)
    lengths = cache["lengths"].at[slot].set(length)
    return {"k": k_pool, "v": v_pool, "lengths": lengths}, logits


def paged_chunked_prefill(params, tokens, cache: Dict, slot, write_rows,
                          read_row, start, cfg: TransformerConfig,
                          length=None, chunk_blocks: int = 1, *,
                          adapters=None, adapter_idx=None,
                          lora=None) -> Tuple[Dict, Any]:
    """Chunked prefill: a ``lax.scan`` over fixed-shape chunks of
    ``C = chunk_blocks · block_size`` tokens whose attention reads K/V
    back OUT of the block pool through ``read_row`` — so a prefix-hit
    admission runs a SUFFIX-sized program that never recomputes the
    shared blocks, and a cold admission is the same program started at
    block 0.

    Args:
      tokens: [B] int32 at a compiled chunked bucket (``B % C == 0`` and
        ``B >= 2·C`` — see the unroll note below).
      write_rows: [B//C, chunk_blocks] int32 — physical block per chunk
        position; shared-prefix and padding entries point at
        :data:`TRASH_BLOCK` (the prefill write-hygiene contract).
      read_row: [max_blocks] int32 — the slot's FULL chain (hit blocks
        first, then the fresh blocks ``write_rows`` names), TRASH-padded.
      start: int32 scalar (traced) — absolute position of ``tokens[0]``;
        block-aligned (``hits · block_size``); 0 for a cold admission.
      length: true TOTAL sequence length (prefix + suffix; defaults to
        ``B``).

    Returns ``(cache', logits [B, vocab] f32)`` where row ``i`` scores
    absolute position ``start + i``.

    Bitwise contract: cold and hit admissions scan the IDENTICAL
    fixed-shape body jaxpr (chunk attention is always ``[C,
    max_blocks·block_size]`` against the gathered pool), so each trip
    compiles to the identical program and by induction suffix logits and
    freshly written pool bytes are BITWISE equal to the full-prompt
    scan's. This is a deliberately different numeric path from
    :func:`paged_prefill` (whose flash-attention logits are shape- and
    fusion-sensitive across bucket widths on XLA): a chunked engine is
    bit-identical to itself across hit depths, not to the non-chunked
    layouts. ``B >= 2·C`` is load-bearing — XLA fully unrolls a
    trip-count-1 ``scan`` and re-fuses the body, breaking the
    identical-program induction, so the engine never compiles a
    one-chunk bucket.
    """
    _check_dense(cfg, "paged_chunked_prefill")
    from .lora import make_delta
    delta = make_delta("prompt", adapters,
                       -1 if adapter_idx is None else adapter_idx,
                       lora, cfg)
    params = _gen_weights(params)
    B = tokens.shape[0]
    bs = cache["k"].shape[2]
    C = int(chunk_blocks) * bs
    if B % C or B < 2 * C:
        raise ValueError(
            f"chunked bucket {B} must be a multiple of chunk size {C} "
            f"and at least 2 chunks (XLA unrolls one-trip scans, which "
            f"breaks the hit-vs-cold bitwise contract)")
    n_chunks = B // C
    max_blocks = read_row.shape[0]
    if B > max_blocks * bs:
        raise ValueError(
            f"chunked bucket {B} exceeds the table depth "
            f"{max_blocks} blocks × {bs}")
    M = max_blocks * bs
    d_head = cfg.d_model // cfg.n_heads
    sm_scale = float(d_head) ** -0.5
    length = jnp.asarray(B if length is None else length, jnp.int32)
    slot = jnp.asarray(slot, jnp.int32)
    start = jnp.asarray(start, jnp.int32)
    zero = jnp.zeros((), jnp.int32)     # x64 mode: indices must agree
    kpos = jnp.arange(M, dtype=jnp.int32)

    def body(carry, xs):
        k_pool, v_pool = carry
        toks_c, wblocks, cstart = xs    # [C], [chunk_blocks], scalar
        qpos = cstart + jnp.arange(C, dtype=jnp.int32)

        def store(li, k, v):
            nonlocal k_pool, v_pool
            li32 = jnp.asarray(li, jnp.int32)
            for j in range(chunk_blocks):
                idx = (li32, wblocks[j], zero, zero, zero)
                k_pool = lax.dynamic_update_slice(
                    k_pool, k[j * bs:(j + 1) * bs]
                    .astype(k_pool.dtype)[None, None], idx)
                v_pool = lax.dynamic_update_slice(
                    v_pool, v[j * bs:(j + 1) * bs]
                    .astype(v_pool.dtype)[None, None], idx)

        def attend(li, q):
            # Gathers AFTER store: the chunk attends over everything
            # written so far (hit blocks included) plus itself; rows
            # past qpos are masked exactly like _cached_attention.
            kg = k_pool[li][read_row].reshape(
                M, cfg.n_heads, d_head)[None]
            vg = v_pool[li][read_row].reshape(
                M, cfg.n_heads, d_head)[None]
            s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                           kg.astype(jnp.float32)) * sm_scale
            s = jnp.where(
                qpos[None, None, :, None] >= kpos[None, None, None, :],
                s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            return jnp.einsum("bhqk,bkhd->bqhd", p,
                              vg.astype(jnp.float32))

        logits_c = _prompt_forward(params, toks_c, cfg, store,
                                   delta=delta, attend=attend)
        return (k_pool, v_pool), logits_c

    toks = tokens.reshape(n_chunks, C)
    cstarts = start + jnp.arange(n_chunks, dtype=jnp.int32) * C
    (k_pool, v_pool), logits = lax.scan(
        body, (cache["k"], cache["v"]), (toks, write_rows, cstarts))
    logits = logits.reshape(B, -1)
    lengths = cache["lengths"].at[slot].set(length)
    return {"k": k_pool, "v": v_pool, "lengths": lengths}, logits


def paged_decode_step(params, last_tokens, cache: Dict, positions,
                      block_tables, cfg: TransformerConfig, *,
                      kernel: bool = False,
                      interpret: Optional[bool] = None,
                      adapters=None, adapter_idx=None,
                      lora=None) -> Tuple[Dict, Any]:
    """One autoregressive step for every slot, through the block table.

    Args:
      last_tokens: [S] int32 per-slot previous token (fixed shape — one
        compiled program regardless of occupancy, as in ``decode_step``).
      positions: [S] int32 write index; ``-1`` = inactive (its scratch
        write is routed to whatever ``block_tables[s, 0]`` names — the
        trash block for unoccupied slots — and its output row is garbage
        to be ignored).
      block_tables: [S, max_blocks] int32 — per-slot physical block list,
        padded with :data:`TRASH_BLOCK` beyond the slot's allocation.
      kernel: gather K/V inside the Pallas paged decode-attention kernel
        (:func:`horovod_tpu.ops.pallas_paged_attention.
        paged_decode_attention`) instead of the pure-lax gather +
        ``_cached_attention`` fallback. The fallback is the reference:
        its einsum sees the SAME ``[S, max_blocks·bs, H, dh]`` view the
        contiguous cache holds natively, which is what makes paged and
        contiguous streams bit-identical; the kernel is allclose-pinned
        against it and gated off by default.

    Returns ``(cache', logits [S, vocab] f32)`` with the same per-slot
    row-independence contract as ``decode_step``.
    ``adapters``/``adapter_idx``/``lora`` are the per-slot LoRA hook,
    exactly as in the contiguous ``decode_step``.
    """
    _check_dense(cfg, "paged_decode_step")
    S = last_tokens.shape[0]
    from .lora import make_delta
    delta = make_delta(
        "step", adapters,
        jnp.full((S,), -1, jnp.int32) if adapter_idx is None
        else adapter_idx, lora, cfg)
    params = _gen_weights(params)
    d_head = cfg.d_model // cfg.n_heads
    bs = cache["k"].shape[2]
    max_blocks = block_tables.shape[1]
    active = positions >= 0
    pos = jnp.where(active, positions, 0).astype(jnp.int32)
    rows = jnp.arange(S, dtype=jnp.int32)
    phys = block_tables[rows, pos // bs]                # [S]
    off = (pos % bs).astype(jnp.int32)
    k_pool, v_pool = cache["k"], cache["v"]

    def mix(li, q, k, v):
        nonlocal k_pool, v_pool
        k_pool = k_pool.at[li, phys, off].set(k.astype(k_pool.dtype))
        v_pool = v_pool.at[li, phys, off].set(v.astype(v_pool.dtype))
        if kernel:
            from ..ops.pallas_paged_attention import paged_decode_attention
            return paged_decode_attention(
                q, k_pool[li], v_pool[li], block_tables, pos,
                interpret=interpret).astype(q.dtype)
        kg = k_pool[li][block_tables].reshape(
            S, max_blocks * bs, cfg.n_heads, d_head)
        vg = v_pool[li][block_tables].reshape(
            S, max_blocks * bs, cfg.n_heads, d_head)
        return _cached_attention(q, kg, vg, pos)

    logits = _step_forward(params, last_tokens, cfg, mix, delta=delta)
    lengths = jnp.where(active, pos + 1, cache["lengths"]
                        ).astype(jnp.int32)
    return {"k": k_pool, "v": v_pool, "lengths": lengths}, logits


def paged_verify_step(params, draft_tokens, cache: Dict, positions,
                      block_tables, cfg: TransformerConfig, *,
                      adapters=None, adapter_idx=None,
                      lora=None) -> Tuple[Dict, Any]:
    """Paged sibling of :func:`~.transformer.verify_step`: score
    ``W = k + 1`` speculative positions per slot in ONE forward against
    the block pool.

    Column ``j`` writes its K/V at logical position ``positions[s] + j``
    through the slot's block table; positions at/past the table's
    logical capacity are redirected to the reserved TRASH_BLOCK (the
    same write-hygiene idiom as the prefill's copy-on-write
    redirection), so padded tail columns can never corrupt a live
    block. Speculated writes always land in the slot's PRIVATE blocks:
    admission reserves every position the stream may write up front,
    and shared (copy-on-write prefix) blocks only ever cover full
    PROMPT blocks — strictly before any generated position — so a
    rejected draft's garbage rows need no block-ledger rollback; the
    next step simply overwrites them before they become readable.

    Returns ``(cache', logits [S, W, vocab] f32)`` with the same
    flattened-rows bit-identity contract as the contiguous
    ``verify_step`` (rows bitwise equal to sequential
    ``paged_decode_step``; tests/test_spec.py pins streams across
    layouts). Gather-fallback attention only — the Pallas decode kernel
    is single-query and allclose- (not bitwise-) pinned, so the engine
    refuses ``paged_kernel`` + speculation rather than mixing numerics
    mid-stream.
    """
    _check_dense(cfg, "paged_verify_step")
    S, W = draft_tokens.shape
    from .lora import make_delta
    aidx = (jnp.full((S,), -1, jnp.int32) if adapter_idx is None
            else adapter_idx)
    delta = make_delta("step", adapters, jnp.repeat(aidx, W), lora, cfg)
    params = _gen_weights(params)
    d_head = cfg.d_model // cfg.n_heads
    bs = cache["k"].shape[2]
    max_blocks = block_tables.shape[1]
    active = positions >= 0
    pos = jnp.where(active, positions, 0).astype(jnp.int32)
    rows = jnp.arange(S, dtype=jnp.int32)
    offs = jnp.arange(W, dtype=jnp.int32)   # x64 mode: indices must agree
    wpos = pos[:, None] + offs[None, :]                      # [S, W]
    valid = wpos < max_blocks * bs
    bidx = jnp.minimum(wpos // bs, max_blocks - 1)
    phys = jnp.where(valid, block_tables[rows[:, None], bidx],
                     TRASH_BLOCK)                            # [S, W]
    off = (wpos % bs).astype(jnp.int32)
    flat_pos = wpos.reshape(S * W)
    k_pool, v_pool = cache["k"], cache["v"]

    def mix(li, q, k, v):
        nonlocal k_pool, v_pool
        k2 = k.reshape(S, W, k.shape[-2], k.shape[-1])
        v2 = v.reshape(S, W, v.shape[-2], v.shape[-1])
        k_pool = k_pool.at[li, phys, off].set(k2.astype(k_pool.dtype))
        v_pool = v_pool.at[li, phys, off].set(v2.astype(v_pool.dtype))
        kg = k_pool[li][block_tables].reshape(
            S, max_blocks * bs, cfg.n_heads, d_head)
        vg = v_pool[li][block_tables].reshape(
            S, max_blocks * bs, cfg.n_heads, d_head)
        return _cached_attention(q, jnp.repeat(kg, W, axis=0),
                                 jnp.repeat(vg, W, axis=0), flat_pos)

    logits = _step_forward(params, draft_tokens.reshape(S * W), cfg, mix,
                           delta=delta)
    lengths = jnp.where(active, pos + 1, cache["lengths"]
                        ).astype(jnp.int32)
    return ({"k": k_pool, "v": v_pool, "lengths": lengths},
            logits.reshape(S, W, -1))


# ---------------------------------------------------------------------------
# Host-side block accounting: free list, refcounts, prefix registry.
# ---------------------------------------------------------------------------


def prefix_route_digest(tokens, block_size: int,
                        adapter: Optional[str] = None) -> Optional[str]:
    """Stable 16-hex digest of a prompt's FIRST full block under the
    tenant frame — the prefix-affine routing key.

    The frame mirrors the registry salt's tenant framing (``\\x00`` for
    base, ``"{adapter}\\x00"`` for a tenant) so two tenants' identical
    token blocks never share a digest. The adapter load-GENERATION is
    deliberately excluded: the digest is advisory placement only — a
    replica whose registry was salted under an older generation simply
    misses and recomputes, so a post-reload stale digest costs a cache
    miss, never a wrong byte. Returns ``None`` when the prompt has no
    full first block (nothing registerable → nothing to route on).
    """
    if len(tokens) < block_size:
        return None
    frame = b"\x00" if adapter is None else f"{adapter}\x00".encode()
    blk = np.ascontiguousarray(tokens[:block_size], dtype=np.int32)
    return hashlib.sha256(frame + blk.tobytes()).hexdigest()[:16]


class BlockManager:
    """Host-side allocator for the paged pool: free list + per-block
    refcounts + a prefix registry for copy-on-write prompt sharing.

    Refcount semantics: an allocated block starts at 1 (its owning
    stream); sharing a prefix block retains it (+1 per sharing stream);
    registering a block in the prefix registry pins it with one more
    ref, so a registered prefix survives its streams and serves future
    hits. A block returns to the free list only at refcount 0;
    :meth:`reclaim` evicts LRU registry entries (dropping their pin)
    when the pool runs dry. All methods are thread-safe, but the
    allocate/lookup/register flow assumes a single admitting thread (the
    engine loop) — concurrent readers only see consistent gauges.

    The registry keys are the raw token bytes of each block-aligned
    prefix (``tokens[:j·block_size].tobytes()``), so a hit requires the
    ENTIRE preceding prefix to match — exactly the condition under which
    the cached K/V (a causal function of the preceding tokens) is valid
    for the new stream. ``salt`` extends that condition to everything
    else the K/V is a function of: a multi-tenant engine passes the
    request's (adapter, load-generation) identity, because a LoRA
    delta changes the K/V a prompt writes — two tenants' identical
    token prefixes are NOT interchangeable bytes, and neither are one
    tenant's before/after a hot-reload.

    **Per-tenant ownership and budgets.** Every allocated block can
    carry an ``owner`` (the allocating tenant); the tenant salt already
    makes prefix sharing tenant-scoped, so a block has exactly ONE
    owner for its whole allocated life — shared-prefix retains are
    always same-tenant. :meth:`set_budget` caps a tenant's owned
    blocks; enforcement lives in the engine's admission path (door
    rejection + per-tenant starvation), the manager only does the
    ledger: :meth:`owned_count`, owner-filtered
    :meth:`offload_candidates` (a tenant over budget offloads its OWN
    coldest blocks first) and owner-filtered :meth:`reclaim` (it
    evicts its own registry residue, never another tenant's cache).
    """

    def __init__(self, n_blocks: int, block_size: int,
                 host_blocks: int = 0):
        if n_blocks < 2:
            raise ValueError(
                f"n_blocks must be >= 2 (block 0 is reserved), got "
                f"{n_blocks}")
        self._n = int(n_blocks)
        self._bs = int(block_size)
        self._ref = np.zeros(self._n, np.int64)
        self._ref[TRASH_BLOCK] = 1          # never allocated, never freed
        self._free: List[int] = list(range(self._n - 1, 0, -1))
        self._registry: "OrderedDict[bytes, int]" = OrderedDict()
        # Host tier: registry key -> opaque payload (the engine stages
        # the block's K/V bytes; the manager only does LRU accounting).
        self._host_cap = int(host_blocks)
        self._host: "OrderedDict[bytes, Any]" = OrderedDict()
        # First-block registry key -> advisory routing digest; kept
        # while the chain head lives in EITHER tier.
        self._route: Dict[bytes, str] = {}
        # Tenant ownership ledger: block -> owning tenant for the
        # block's allocated lifetime (registry pins included — a
        # tenant's cache residue counts against its budget), plus the
        # per-tenant owned counts and budgets the engine enforces.
        self._owner: Dict[int, str] = {}
        self._owned: Dict[str, int] = {}
        self._budgets: Dict[str, int] = {}
        self._lock = threading.Lock()

    # -- gauges ------------------------------------------------------------

    @property
    def block_size(self) -> int:
        return self._bs

    @property
    def usable(self) -> int:
        """Allocatable blocks (the pool minus the trash block)."""
        return self._n - 1

    @property
    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_count(self) -> int:
        with self._lock:
            return self.usable - len(self._free)

    @property
    def registry_size(self) -> int:
        with self._lock:
            return len(self._registry)

    def gauges(self) -> Dict:
        """The /stats block-pool block: plain ints, json-ready (the
        router sums these across replicas, so every value stays
        numeric). Host-tier keys are present even at ``host_blocks=0``
        so the exposition is stable across configurations."""
        with self._lock:
            free = len(self._free)
            host_used = len(self._host)
            return {"total": self.usable, "free": free,
                    "used": self.usable - free,
                    "registered_prefix_blocks": len(self._registry),
                    "host_total": self._host_cap,
                    "host_used": host_used,
                    "host_free": max(0, self._host_cap - host_used)}

    def tenant_gauges(self) -> Dict:
        """Per-tenant ownership view, SEPARATE from :meth:`gauges`
        (whose values the fleet router sums across replicas — they must
        stay scalar): owned device blocks and configured budgets by
        tenant, json-ready."""
        with self._lock:
            return {"owned": dict(sorted(self._owned.items())),
                    "budgets": dict(sorted(self._budgets.items()))}

    # -- tenant ownership / budgets -----------------------------------------

    def _own(self, b: int, owner: Optional[str]) -> None:
        """Stamp ``owner`` on block ``b`` (caller holds the lock)."""
        if owner is None:
            return
        self._owner[b] = owner
        self._owned[owner] = self._owned.get(owner, 0) + 1

    def _disown(self, b: int) -> None:
        """Clear block ``b``'s owner as it frees (caller holds the
        lock)."""
        owner = self._owner.pop(b, None)
        if owner is None:
            return
        n = self._owned.get(owner, 1) - 1
        if n > 0:
            self._owned[owner] = n
        else:
            self._owned.pop(owner, None)

    def set_budget(self, tenant: str, budget: Optional[int]) -> None:
        """Cap ``tenant``'s owned device blocks (``None`` = unlimited).
        Budget vs quota: a quota caps in-flight STREAMS, a budget caps
        the tenant's slice of the device pool — the resource that one
        long-context tenant can exhaust for everyone with a handful of
        streams."""
        if budget is not None and budget < 1:
            raise ValueError(
                f"block budget must be >= 1 or None, got {budget}")
        with self._lock:
            if budget is None:
                self._budgets.pop(tenant, None)
            else:
                self._budgets[tenant] = int(budget)

    def budget(self, tenant: str) -> Optional[int]:
        with self._lock:
            return self._budgets.get(tenant)

    def owned_count(self, tenant: str) -> int:
        """Device blocks currently owned by ``tenant`` — live stream
        allocations AND its registry-pinned prefix residue."""
        with self._lock:
            return self._owned.get(tenant, 0)

    # -- allocation --------------------------------------------------------

    def alloc(self, n: int, owner: Optional[str] = None) -> List[int]:
        """Take ``n`` fresh blocks (refcount 1 each), owned by
        ``owner`` when given. Callers check :attr:`free_count` (and
        :meth:`reclaim`) first; an empty pool here is a bookkeeping
        bug, not backpressure."""
        with self._lock:
            if n > len(self._free):
                raise RuntimeError(
                    f"block pool exhausted: asked {n}, free "
                    f"{len(self._free)} — admission must check "
                    f"free_count/reclaim first")
            out = [self._free.pop() for _ in range(n)]
            for b in out:
                self._ref[b] = 1
                self._own(b, owner)
            return out

    def retain(self, blocks: List[int]) -> None:
        """One more stream reference on each of ``blocks`` (prefix hit)."""
        with self._lock:
            for b in blocks:
                if self._ref[b] <= 0:
                    raise RuntimeError(
                        f"retain of unallocated block {b}")
                self._ref[b] += 1

    def release(self, blocks: List[int]) -> None:
        """Drop one reference per block; blocks at refcount 0 return to
        the free list. The trash block is silently skipped (table rows
        are padded with it)."""
        with self._lock:
            for b in blocks:
                if b == TRASH_BLOCK:
                    continue
                self._ref[b] -= 1
                if self._ref[b] < 0:
                    raise RuntimeError(f"double free of block {b}")
                if self._ref[b] == 0:
                    self._disown(b)
                    self._free.append(b)

    # -- prefix registry ---------------------------------------------------

    def _key(self, tokens: np.ndarray, j: int, salt: bytes) -> bytes:
        return salt + np.ascontiguousarray(
            tokens[:(j + 1) * self._bs], dtype=np.int32).tobytes()

    def lookup_prefix(self, tokens: np.ndarray,
                      salt: bytes = b"") -> List[int]:
        """Longest chain of registered full blocks matching the prompt's
        block-aligned prefix UNDER ``salt`` (the writer-identity key —
        see class docstring); touches hits MRU so reclaim evicts cold
        prefixes first."""
        with self._lock:
            hits: List[int] = []
            for j in range(len(tokens) // self._bs):
                key = self._key(tokens, j, salt)
                blk = self._registry.get(key)
                if blk is None:
                    break
                self._registry.move_to_end(key)
                hits.append(blk)
            return hits

    def register_prefix(self, tokens: np.ndarray, blocks: List[int],
                        n_full: int, salt: bytes = b"",
                        route_digest: Optional[str] = None) -> None:
        """Pin the prompt's first ``n_full`` blocks in the registry
        under ``salt`` (idempotent for already-registered chains).
        ``route_digest`` tags the chain's FIRST block key for
        prefix-affine routing. A cold re-registration supersedes any
        host-tier copy of the same key (bitwise-identical bytes by the
        chunked-prefill contract, so the device copy wins and the host
        slot frees up)."""
        with self._lock:
            for j in range(n_full):
                key = self._key(tokens, j, salt)
                if j == 0 and route_digest:
                    self._route[key] = route_digest
                self._host.pop(key, None)
                if key in self._registry:
                    self._registry.move_to_end(key)
                    continue
                self._registry[key] = blocks[j]
                self._ref[blocks[j]] += 1

    def reclaim(self, need_free: int,
                owner: Optional[str] = None) -> bool:
        """Evict registered prefixes, LRU-first, until ``need_free``
        blocks are free. Only entries whose block's SOLE reference is
        the registry pin are evicted — popping a stream-referenced entry
        frees nothing and would just wipe the cache for future
        admissions (a transiently starved request must not disable
        prefix reuse for everyone else). ``owner`` restricts the sweep
        to blocks that tenant owns: an over-budget tenant reclaims its
        OWN cache residue, never another tenant's. Returns whether the
        target was met; entries skipped here free up for a later sweep
        when their streams end."""
        with self._lock:
            if len(self._free) >= need_free:
                return True
            for key in list(self._registry):        # LRU → MRU order
                if len(self._free) >= need_free:
                    break
                blk = self._registry[key]
                if owner is not None and self._owner.get(blk) != owner:
                    continue
                if self._ref[blk] == 1:
                    del self._registry[key]
                    if key not in self._host:
                        self._route.pop(key, None)
                    self._ref[blk] = 0
                    self._disown(blk)
                    self._free.append(blk)
            return len(self._free) >= need_free

    # -- host tier ---------------------------------------------------------

    def host_lookup(self, tokens: np.ndarray, start_block: int,
                    salt: bytes = b"") -> List[Tuple[bytes, Any]]:
        """Contiguous run of host-tier entries continuing the device
        chain from logical block ``start_block`` — ``[(key, payload),
        ...]`` in chain order, touched MRU. The engine kicks an async
        prefetch for these; they are NOT readable by this admission."""
        with self._lock:
            out: List[Tuple[bytes, Any]] = []
            for j in range(int(start_block), len(tokens) // self._bs):
                key = self._key(tokens, j, salt)
                payload = self._host.get(key)
                if payload is None:
                    break
                self._host.move_to_end(key)
                out.append((key, payload))
            return out

    def offload_candidates(self, n: int,
                           owner: Optional[str] = None
                           ) -> List[Tuple[bytes, int]]:
        """Up to ``n`` coldest registry entries whose block's SOLE
        reference is the registry pin — the only ones whose device bytes
        are stable to copy (no stream can be writing them) and whose
        eviction frees a block. ``owner`` restricts the sweep to that
        tenant's blocks (the over-budget path: a tenant offloads its
        OWN coldest blocks first). Read-only: the engine snapshots the
        bytes, then :meth:`offload_commit` re-validates under the lock,
        so a hit that lands mid-copy simply cancels the offload."""
        if self._host_cap <= 0 or n <= 0:
            return []
        with self._lock:
            out: List[Tuple[bytes, int]] = []
            for key, blk in self._registry.items():     # LRU → MRU
                if len(out) >= n:
                    break
                if owner is not None and self._owner.get(blk) != owner:
                    continue
                if self._ref[blk] == 1:
                    out.append((key, blk))
            return out

    def offload_commit(self, key: bytes, payload: Any) -> bool:
        """Move a candidate to the host tier: drop the registry pin,
        free the device block, stage ``payload`` LRU-tracked. Refuses
        (returns False) if the entry was hit or evicted since
        :meth:`offload_candidates` — the payload would be stale
        bookkeeping, never a stale read, but we don't keep it."""
        with self._lock:
            blk = self._registry.get(key)
            if blk is None or self._ref[blk] != 1 or self._host_cap <= 0:
                return False
            del self._registry[key]
            self._ref[blk] = 0
            self._disown(blk)
            self._free.append(blk)
            self._host[key] = payload
            self._host.move_to_end(key)
            while len(self._host) > self._host_cap:
                old, _ = self._host.popitem(last=False)
                if old not in self._registry:
                    self._route.pop(old, None)
            return True

    def promote(self, key: bytes, blk: int) -> bool:
        """Install a prefetched payload's freshly written device block
        back into the registry, transferring the caller's alloc ref to
        the registry pin (the block arrives at refcount 1 from
        :meth:`alloc` and stays at 1 — registry-pinned, stream-free).
        Idempotent against the admission race: if the key was re-
        registered cold while the prefetch was in flight, the new block
        is freed and False returned — both copies hold bitwise-identical
        bytes, so either outcome is correct and no reader ever sees a
        stale row."""
        with self._lock:
            self._host.pop(key, None)
            if key in self._registry:
                self._ref[blk] = 0
                self._disown(blk)
                self._free.append(blk)
                return False
            self._registry[key] = blk
            return True

    def route_digests(self) -> Tuple[str, ...]:
        """Sorted unique advisory routing digests of every prefix chain
        resident in EITHER tier — what the replica advertises through
        /stats for prefix-affine dispatch."""
        with self._lock:
            return tuple(sorted(set(self._route.values())))
