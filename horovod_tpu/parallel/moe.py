"""Expert parallelism: top-1 gated MoE with all_to_all dispatch over ``ep``.

Net-new TPU capability (absent from the reference). GShard-style layout:
one expert per ep rank; each chip's tokens are routed by a learned gate,
packed into a static-capacity dispatch buffer [S, C, D] (XLA needs static
shapes — overflow tokens beyond capacity drop, standard MoE behavior),
exchanged with a single ``all_to_all`` so chip e receives every chip's
tokens for expert e, transformed by the local expert FFN, and returned by
the inverse ``all_to_all``; gate probabilities weight the combine.

The expert plane is a first-class mesh axis, not a side channel:
``create_hybrid_mesh(ep=E)`` names it, expert weights carry ``ep`` in
their PartitionSpecs (``parallel/transformer.py`` puts ``P('ep', …)`` on
w1/w2 when ``n_experts`` is set), and their gradients ride the SAME
spec-grouped collective plan as every other leaf
(``ops/fusion.plan_grad_sync``: expert grads psum over the axes they are
replicated across — never ``ep``, each rank owns its expert — while the
replicated gate syncs over the full mesh). No MoE-specific gradient code
exists anywhere.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


def moe_ffn(x, gate_w, w1, w2, *, axis_name: str = "ep",
            capacity_factor: float = 1.25):
    """Top-1 MoE feed-forward over tokens sharded across ``axis_name``.

    Args:
      x: [T_local, D] this chip's tokens.
      gate_w: [D, E] gate (replicated; E == axis size).
      w1: [D, F] local expert up-projection; w2: [F, D] down.
      capacity_factor: per-expert buffer = ceil(T_local/E · factor).

    Returns ([T_local, D], aux_loss) — aux_loss is the load-balancing loss
    (mean over experts of fraction_routed · mean_gate_prob · E²).
    """
    if capacity_factor <= 0:
        raise ValueError(
            f"capacity_factor must be > 0, got {capacity_factor} — a "
            f"non-positive capacity would silently drop every token")
    T, D = x.shape
    E = lax.axis_size(axis_name)
    C = max(1, int((T / E) * capacity_factor + 0.999))

    logits = x @ gate_w                               # [T, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)               # [T]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]

    # Position of each token within its expert's capacity buffer.
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.int32)        # [T, E]
    pos = jnp.cumsum(onehot, axis=0) * onehot                  # 1-based
    pos = jnp.sum(pos, axis=-1) - 1                            # [T], -1 pad
    keep = (pos >= 0) & (pos < C)

    # Pack: dispatch[e, c, :] = token routed to expert e at slot c.
    dispatch = jnp.zeros((E, C, D), x.dtype)
    dispatch = dispatch.at[expert, jnp.clip(pos, 0, C - 1)].add(
        jnp.where(keep[:, None], x, 0))

    # Exchange: chip r sends block e to chip e; receives [E, C, D] where
    # block s came from chip s.
    shuffled = lax.all_to_all(dispatch, axis_name, split_axis=0,
                              concat_axis=0, tiled=True)

    h = jax.nn.gelu(shuffled.reshape(-1, D) @ w1)
    out = (h @ w2).reshape(E, C, D)

    # Return to senders and unpack.
    returned = lax.all_to_all(out, axis_name, split_axis=0, concat_axis=0,
                              tiled=True)
    combined = returned[expert, jnp.clip(pos, 0, C - 1)]
    combined = jnp.where(keep[:, None], combined, 0)
    y = combined * gate[:, None].astype(x.dtype)

    # Load-balance auxiliary loss (Shazeer et al.): encourages uniform
    # routing; fraction of tokens per expert × mean gate prob per expert.
    frac = jnp.mean(onehot.astype(jnp.float32), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = jnp.sum(frac * mean_prob) * E
    return y, aux
