"""Ring attention: exact attention over sequences sharded across chips.

Net-new TPU capability (the reference predates long-context work, SURVEY
§5.7). The sequence is split along the ``sp`` mesh axis; each chip holds a
[B, T/S, H, D] shard of Q, K, V. K/V blocks rotate around the ring with
``ppermute`` (one ICI hop per step) while each chip accumulates its queries'
attention over every block with a numerically stable online softmax
(flash-attention-style running max / sum) — so the full [T, T] score matrix
never materializes and memory stays O(T/S · T/S) per step.

The ppermute rotation overlaps with the block computation under XLA's
scheduler; S steps complete the exact (optionally causal) result, bit-close
to dense attention (same math, different summation order).

Reference for the pattern: Liu et al., "Ring Attention with Blockwise
Transformers" (arXiv:2310.01889); implementation is original and
shard_map/ppermute-native.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _block_attn(q, k, v, mask, sm_scale):
    """One (q-block, kv-block) partial: returns (scores_exp, m_blk, pv).

    q: [B, Tq, H, D]; k/v: [B, Tk, H, D]; mask: [Tq, Tk] bool (True=keep).
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * sm_scale
    if mask is not None:
        scores = jnp.where(mask[None, None], scores, -1e30)
    m_blk = jnp.max(scores, axis=-1)                      # [B, H, Tq]
    p = jnp.exp(scores - m_blk[..., None])                # [B, H, Tq, Tk]
    l_blk = jnp.sum(p, axis=-1)                           # [B, H, Tq]
    pv = jnp.einsum("bhqk,bkhd->bqhd", p, v)              # [B, Tq, H, D]
    return m_blk, l_blk, pv


def ring_attention(q, k, v, *, axis_name: str = "sp", causal: bool = False,
                   sm_scale: Optional[float] = None):
    """Exact multi-head attention with K/V ring rotation over ``axis_name``.

    Args:
      q, k, v: [B, T_local, H, D] — this chip's sequence shard.
      axis_name: the sequence-parallel mesh axis (size S).
      causal: apply a causal mask using *global* positions (each chip's
        shard occupies rows [rank·T_local, (rank+1)·T_local)).
      sm_scale: softmax scale; default 1/sqrt(D).

    Returns [B, T_local, H, D]: this chip's rows of the exact attention
    output over the full sequence.
    """
    B, T, H, D = q.shape
    S = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    if sm_scale is None:
        sm_scale = 1.0 / (D ** 0.5)

    q32 = q.astype(jnp.float32)
    rows = rank * T + jnp.arange(T)                       # global q positions

    def step(s, carry):
        k_cur, v_cur, o, m, l = carry
        # Block s arrived from rank (rank - s) mod S.
        src = (rank - s) % S
        mask = None
        if causal:
            cols = src * T + jnp.arange(T)
            mask = rows[:, None] >= cols[None, :]
        m_blk, l_blk, pv = _block_attn(
            q32, k_cur.astype(jnp.float32), v_cur.astype(jnp.float32),
            mask, sm_scale)
        m_new = jnp.maximum(m, m_blk)
        alpha = jnp.exp(m - m_new)                        # rescale old accum
        beta = jnp.exp(m_blk - m_new)                     # rescale new block
        l_new = l * alpha + l_blk * beta
        o_new = (o * alpha.transpose(0, 2, 1)[..., None]
                 + pv * beta.transpose(0, 2, 1)[..., None])
        # Rotate K/V one hop around the ring (rank i -> i+1).
        perm = [(i, (i + 1) % S) for i in range(S)]
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, o_new, m_new, l_new

    o0 = jnp.zeros((B, T, H, D), jnp.float32)
    m0 = jnp.full((B, H, T), -1e30, jnp.float32)
    l0 = jnp.zeros((B, H, T), jnp.float32)
    _, _, o, m, l = lax.fori_loop(0, S, step, (k, v, o0, m0, l0))

    # Rows with no visible keys (can't happen with causal self-attention,
    # every row sees itself) would have l == 0; guard the division anyway.
    l = jnp.where(l == 0.0, 1.0, l)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, *, axis_name: str = "sp",
                      causal: bool = False,
                      sm_scale: Optional[float] = None):
    """All-to-all (DeepSpeed-Ulysses-style) sequence parallelism.

    Instead of rotating K/V, one ``all_to_all`` re-shards from
    sequence-split to head-split, attention runs locally over the FULL
    sequence with H/S heads per chip, and a second ``all_to_all`` restores
    sequence sharding. Two collectives total — cheaper than a ring when
    H ≥ S and the full T×T block fits; the ring wins for very long T.

    Shapes as :func:`ring_attention`; requires H divisible by the axis size.
    """
    B, T, H, D = q.shape
    S = lax.axis_size(axis_name)
    if H % S != 0:
        raise ValueError(f"heads {H} not divisible by sp axis {S}")

    # [B, T/S, H, D] -> [B, T, H/S, D]: split heads, gather sequence.
    def seq_to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    scores = jnp.einsum("bqhd,bkhd->bhqk",
                        qh.astype(jnp.float32), kh.astype(jnp.float32))
    scores *= (sm_scale if sm_scale is not None else 1.0 / (D ** 0.5))
    if causal:
        full_t = T * S
        pos = jnp.arange(full_t)
        scores = jnp.where(pos[:, None] >= pos[None, :], scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, vh.astype(jnp.float32))
    return heads_to_seq(out.astype(q.dtype))
