"""Multi-axis parallelism: dp/tp/pp/sp/ep over a hybrid mesh.

Net-new TPU capabilities beyond the dp-only reference (SURVEY §2.4):
ring/Ulysses sequence parallelism for long context, Megatron tensor
parallelism, GPipe pipeline parallelism, and GShard expert parallelism —
all as shard_map-native building blocks over `create_hybrid_mesh`.
"""

from .checkpoint import (  # noqa: F401
    restore_adapter,
    restore_sharded,
    save_adapter,
    save_sharded,
)
from .lora import (  # noqa: F401
    LoraConfig,
    adapter_bytes,
    check_adapter,
    check_adapter_name,
    init_adapter,
    stack_adapters,
)
from .kv_blocks import (  # noqa: F401
    BlockManager,
    blocks_for,
    init_paged_kv_cache,
    paged_decode_step,
    paged_kv_cache_specs,
    paged_prefill,
)
from .mesh import AXES, axis_size, create_hybrid_mesh  # noqa: F401
from .moe import moe_ffn  # noqa: F401
from .pipeline import gpipe, one_f_one_b  # noqa: F401
from .pp_transformer import (  # noqa: F401
    init_pp_params,
    make_pp_transformer_train_step,
    pp_param_specs,
)
from .ring import ring_attention, ulysses_attention  # noqa: F401
from .tp import (  # noqa: F401
    column_parallel,
    init_column,
    init_row,
    row_parallel,
)
from .transformer import (  # noqa: F401
    TransformerConfig,
    decode_step,
    forward,
    init_kv_cache,
    init_params,
    kv_cache_specs,
    make_parallel_train_step,
    param_specs,
    prefill,
)
