"""Keras adapter — parity with ``horovod/keras/__init__.py`` for Keras 3.

A reference user writes ``import horovod.keras as hvd``; this module gives
the same surface over the TPU-native core:

* :func:`DistributedOptimizer` — a **dynamically created subclass of the
  user's optimizer class** (keeping the class name so checkpoints restore
  without this framework installed — the reference's trick,
  ``keras/__init__.py:81-87``) whose ``apply_gradients`` averages gradients
  across ranks first (``keras/__init__.py:41-63`` overrode
  ``get_gradients``; Keras 3 hooks ``apply_gradients``).
* eager ``allreduce/allgather/broadcast(value)`` helpers
  (``keras/__init__.py:90-144`` ran them through ``K.get_session().run``;
  here they dispatch the framework's eager plane directly).
* ``broadcast_global_variables(model, root_rank)`` — weight sync from rank
  0 into a built Keras model.
* re-exported ``init/size/rank/local_rank`` process API.

Works with any Keras 3 backend (tensorflow / jax / torch): values cross
into the collective plane via numpy and return as numpy.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .. import runtime
from ..ops.collectives import Op
from ..utils.lr_schedule import LRScheduleCore, warmup_multiplier
from ..ops.collectives import allgather as _allgather
from ..ops.collectives import allreduce as _allreduce
from ..ops.collectives import broadcast as _broadcast
from ..optimizer import Compression  # noqa: F401  (compression= convenience)
from ..runtime import (  # noqa: F401  (re-exports, reference parity)
    init,
    is_initialized,
    local_rank,
    process_count,
    process_index,
    rank,
    shutdown,
    size,
)


def allreduce(value, average: bool = True, name: Optional[str] = None):
    """Eager allreduce of a value/array; returns numpy
    (parity: ``keras/__init__.py:117-126``)."""
    return np.asarray(_allreduce(np.asarray(value), average=average,
                                 name=name))


def allgather(value, name: Optional[str] = None):
    """Eager allgather along dim 0; returns numpy
    (parity: ``keras/__init__.py:129-136``)."""
    return np.asarray(_allgather(np.asarray(value), name=name))


def broadcast(value, root_rank: int = 0, name: Optional[str] = None):
    """Eager broadcast from ``root_rank``; returns numpy
    (parity: ``keras/__init__.py:139-144``)."""
    return np.asarray(_broadcast(np.asarray(value), root_rank=root_rank,
                                 name=name))


def broadcast_global_variables(model, root_rank: int = 0) -> None:
    """Sync a built Keras model's weights (and optimizer variables, if
    built) from ``root_rank`` (parity: ``keras/__init__.py:90-96`` +
    ``BroadcastGlobalVariablesCallback``)."""
    for v in model.weights:
        v.assign(broadcast(np.asarray(v), root_rank,
                           name=f"bcast.{v.path if hasattr(v, 'path') else v.name}"))
    opt = getattr(model, "optimizer", None)
    if opt is not None and getattr(opt, "built", False):
        for v in opt.variables:
            v.assign(broadcast(np.asarray(v), root_rank,
                               name=f"bcast.opt.{getattr(v, 'path', v.name)}"))


def DistributedOptimizer(optimizer, *, average: bool = True,
                         compression=None,
                         name: Optional[str] = None):
    """Wrap a Keras 3 optimizer so gradients are averaged across ranks
    before being applied.

    Returns an instance of a dynamically created subclass of
    ``type(optimizer)`` with the same class name, so saved configs/
    checkpoints deserialize with plain Keras when this framework is absent
    (reference: ``keras/__init__.py:81-87``). A no-op wrapper when
    ``size() == 1``. ``compression=hvd.Compression.bf16`` halves allreduce
    bytes (same semantics as the core optimizer wrapper).
    """
    import keras

    cls_name = optimizer.__class__.__name__
    compression = compression if compression is not None else Compression.none

    class _Distributed(optimizer.__class__):
        _hvd_average = average
        _hvd_compression = compression

        def apply(self, grads, trainable_variables=None):
            # `apply` is the single funnel in Keras 3: the TF trainer's
            # `apply_gradients` and the jax trainer's `stateless_apply`
            # both land here, so hooking it covers every backend's
            # compiled train step.
            if runtime.is_initialized() and runtime.size() > 1:
                grads = list(grads)
                variables = (list(trainable_variables)
                             if trainable_variables is not None
                             else list(self._trainable_variables))
                idx = [i for i, g in enumerate(grads) if g is not None]
                if idx:
                    reduced = self._hvd_allreduce_grads(
                        [grads[i] for i in idx],
                        [variables[i] for i in idx])
                    for i, g in zip(idx, reduced):
                        grads[i] = g
            return super().apply(grads, trainable_variables)

        def _hvd_allreduce_grads(self, grads, variables):
            """Allreduce the whole gradient list through ONE host callback.

            A single callback (not one per gradient) matters in
            multi-process worlds: independent per-tensor callbacks may
            execute in different orders on different ranks, each blocking
            on a different collective — a deadlock the reference's
            coordinator avoids because TF's enqueue is asynchronous
            (mpi_ops.cc:1752-1772). One callback per step keeps every rank
            announcing the same batch, and the async submit-all/wait-all
            inside feeds the coordinator's response fusion.
            """
            names = [f"grad.{getattr(v, 'path', v.name)}" for v in variables]

            def _reduce_all_np(*gs):
                arrs = [np.asarray(g) for g in gs]
                w = runtime.world()
                if w.coord is not None:
                    # Multi-process: overlap every announcement (fusion),
                    # then redeem in order.
                    compressed = [self._hvd_compression.compress(a)
                                  for a in arrs]
                    handles = [
                        w.coord.submit("allreduce", c, name,
                                       op=Op.AVERAGE if self._hvd_average
                                       else Op.SUM)
                        for (c, _), name in zip(compressed, names)]
                    outs = [
                        np.asarray(self._hvd_compression.decompress(
                            w.coord.wait(h), ctx))
                        for h, (_, ctx) in zip(handles, compressed)]
                else:
                    outs = []
                    for a, name in zip(arrs, names):
                        c, ctx = self._hvd_compression.compress(a)
                        out = _allreduce(c, average=self._hvd_average,
                                         name=name)
                        outs.append(np.asarray(
                            self._hvd_compression.decompress(out, ctx)))
                return tuple(np.ascontiguousarray(o.astype(a.dtype))
                             for o, a in zip(outs, arrs))

            # Keras compiles train steps per backend; bridge the collective
            # through the backend's host-callback mechanism so it works
            # inside tf.function / jax.jit, and directly when eager.
            backend = keras.backend.backend()
            if backend == "tensorflow":
                import tensorflow as tf
                if not tf.executing_eagerly():  # inside tf.function
                    outs = tf.py_function(
                        lambda *gs: [tf.constant(o) for o in
                                     _reduce_all_np(*[g.numpy()
                                                      for g in gs])],
                        list(grads), Tout=[g.dtype for g in grads])
                    for o, g in zip(outs, grads):
                        o.set_shape(g.shape)
                    return list(outs)
            elif backend == "jax":
                import jax as _jax
                import jax.core as _jcore
                if any(isinstance(g, _jcore.Tracer) for g in grads):
                    out_shapes = tuple(
                        _jax.ShapeDtypeStruct(g.shape, g.dtype)
                        for g in grads)
                    return list(_jax.pure_callback(
                        _reduce_all_np, out_shapes, *grads))
            outs = _reduce_all_np(*[keras.ops.convert_to_numpy(g)
                                    for g in grads])
            return [keras.ops.convert_to_tensor(o, dtype=g.dtype)
                    for o, g in zip(outs, grads)]

    _Distributed.__name__ = cls_name
    _Distributed.__qualname__ = cls_name

    config = optimizer.get_config()
    return _Distributed.from_config(config)


class BroadcastGlobalVariablesCallback:
    """Keras callback: broadcast model + optimizer state from ``root_rank``
    at train begin (parity: ``horovod/keras/callbacks.py:8-34``)."""

    def __new__(cls, root_rank: int = 0):
        import keras

        class _CB(keras.callbacks.Callback):
            def __init__(self, root):
                super().__init__()
                self.root_rank = root

            def on_train_begin(self, logs=None):
                broadcast_global_variables(self.model, self.root_rank)

        return _CB(root_rank)


class MetricAverageCallback:
    """Keras callback: average epoch-end metrics over ranks (parity:
    ``horovod/keras/callbacks.py:37-87``); place before callbacks that
    consume metrics (ReduceLROnPlateau, loggers)."""

    def __new__(cls):
        import keras

        class _CB(keras.callbacks.Callback):
            def on_epoch_end(self, epoch, logs=None):
                if not logs:
                    return
                for k, v in list(logs.items()):
                    if isinstance(v, (int, float, np.floating, np.integer)):
                        logs[k] = float(allreduce(
                            np.float32(v), average=True,
                            name=f"metric.{k}"))

        return _CB()


class LearningRateScheduleCallback:
    """Keras callback: LR = ``initial_lr * multiplier(epoch)`` between
    ``start_epoch`` and ``end_epoch`` (parity:
    ``horovod/keras/callbacks.py:90-199``). ``staircase=False`` adjusts
    every batch at fractional epochs; with ``momentum_correction`` the
    optimizer momentum is scaled by ``new_lr/old_lr`` for the adjusted
    batch and restored after it."""

    def __new__(cls, multiplier, start_epoch: int = 0,
                end_epoch: Optional[int] = None, staircase: bool = True,
                momentum_correction: bool = True,
                steps_per_epoch: Optional[int] = None):
        import keras

        # The schedule/momentum-correction math is shared with the core
        # callback layer (utils/lr_schedule.py); this adapter owns only the
        # Keras 3 optimizer-variable plumbing.
        core = LRScheduleCore(
            multiplier, start_epoch=start_epoch, end_epoch=end_epoch,
            staircase=staircase, momentum_correction=momentum_correction,
            steps_per_epoch=steps_per_epoch)

        class _CB(keras.callbacks.Callback):
            def __init__(self):
                super().__init__()
                self.core = core

            # -- optimizer plumbing (Keras 3 variables) -------------------
            def _get_lr(self):
                return float(keras.ops.convert_to_numpy(
                    self.model.optimizer.learning_rate))

            def _set_lr(self, v):
                self.model.optimizer.learning_rate = v

            def _get_momentum(self):
                m = getattr(self.model.optimizer, "momentum", None)
                return float(m) if m is not None else None

            def _set_momentum(self, v):
                self.model.optimizer.momentum = v

            # -- hooks (decisions delegated to the shared core) -----------
            def on_train_begin(self, logs=None):
                self.core.train_begin(self._get_lr())

            def on_epoch_begin(self, epoch, logs=None):
                self.core.epoch_begin(epoch)

            def on_train_batch_begin(self, batch, logs=None):
                new_lr = self.core.target_lr(batch)
                if new_lr is None:
                    return
                old_lr = self._get_lr()
                self._set_lr(new_lr)
                m = self.core.corrected_momentum(old_lr, new_lr,
                                                 self._get_momentum())
                if m is not None:
                    self._set_momentum(m)

            def on_train_batch_end(self, batch, logs=None):
                m = self.core.momentum_to_restore()
                if m is not None:
                    self._set_momentum(m)

            def on_epoch_end(self, epoch, logs=None):
                if logs is not None:
                    logs["lr"] = self._get_lr()

        return _CB()


class LearningRateWarmupCallback:
    """Keras callback: gradual warmup ``lr/size → lr`` over
    ``warmup_epochs`` (parity: ``horovod/keras/callbacks.py:202-259``;
    Goyal et al. 1706.02677)."""

    def __new__(cls, warmup_epochs: int = 5,
                momentum_correction: bool = True,
                steps_per_epoch: Optional[int] = None, verbose: int = 0):
        if not steps_per_epoch:
            raise ValueError("steps_per_epoch is required for warmup "
                             "(per-batch fractional-epoch adjustment)")

        cb = LearningRateScheduleCallback(
            warmup_multiplier(
                warmup_epochs, lambda: steps_per_epoch,
                lambda: size() if runtime.is_initialized() else 1),
            start_epoch=0, end_epoch=warmup_epochs,
            staircase=False, momentum_correction=momentum_correction,
            steps_per_epoch=steps_per_epoch)

        if verbose:
            base_epoch_end = cb.on_epoch_end

            def on_epoch_end(epoch, logs=None):
                base_epoch_end(epoch, logs)
                if epoch == warmup_epochs - 1 and (
                        not runtime.is_initialized()
                        or runtime.world().controller_rank == 0):
                    print(f"\nEpoch {epoch + 1}: finished gradual learning "
                          f"rate warmup to {cb._get_lr():g}.")

            cb.on_epoch_end = on_epoch_end
        return cb
