"""Train-step builder: the compiled data-parallel hot path.

Reference parity
----------------
The reference's training step is: TF computes per-replica gradients,
``DistributedOptimizer.compute_gradients`` allreduces each one
(``horovod/tensorflow/__init__.py:164-186``), then the wrapped optimizer
applies them — launched as one process per GPU (``README.md:62-64``).

TPU-native design
-----------------
One compiled SPMD program over the world mesh replaces the per-process
choreography: ``make_train_step`` returns a jitted ``shard_map`` function in
which each chip computes gradients on its batch shard, the
``DistributedOptimizer`` transformation does a fused ``psum`` over the
``"hvd"`` ICI axis (see ``ops/fusion.py`` for the 64 MiB bucketing parity),
and every chip applies identical updates. Parameters are replicated
(pure data parallelism, the reference's only strategy — SURVEY §2.4); the
batch is sharded on its leading axis.

All collectives live inside the compiled step, so there is no negotiation
latency floor (the reference pays a 5 ms tick per round,
``mpi_ops.cc:1295``); XLA schedules and overlaps the gradient all-reduce
with backprop.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from . import runtime
from .optimizer import Compression, DistributedOptimizer
from .runtime import AXIS


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    """Replicated training state: params + optimizer state (+ BN stats)."""

    step: jax.Array
    params: Any
    opt_state: Any
    batch_stats: Any = None


def cross_entropy_loss(logits, labels):
    """Mean softmax cross entropy over integer labels (float32 reduction)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))


def accuracy(logits, labels):
    return jnp.mean((jnp.argmax(logits, axis=-1) == labels)
                    .astype(jnp.float32))


# ---------------------------------------------------------------------------
# In-step gradient accumulation — the TPU-native ``backward_passes_per_step``
# (Sergeev & Del Balso 2018 §4; GPipe microbatching, Huang et al. 2019).
# The scan lives INSIDE the compiled SPMD program: gradients for N
# microbatches are summed on-device and the fused psum fires once per
# accumulated step, so interconnect traffic per sample drops by N and the
# per-chip batch can exceed HBM limits via the optional remat policy.
# ---------------------------------------------------------------------------

def _acc_dtype(dtype):
    """Accumulator dtype: fp32 for sub-fp32 floats (bf16 microbatch grads
    summed in bf16 lose ~3 bits over 4 microbatches), unchanged otherwise."""
    if jnp.issubdtype(dtype, jnp.floating) \
            and jnp.dtype(dtype).itemsize < 4:
        return jnp.float32
    return jnp.dtype(dtype)


def _split_microbatches(tree, n: int):
    """Reshape every leaf ``(B, ...) -> (n, B // n, ...)`` (leading-axis
    contiguous split; the mean over equal microbatches equals the full-batch
    mean regardless of row order)."""
    return jax.tree_util.tree_map(
        lambda x: jnp.reshape(x, (n, x.shape[0] // n) + x.shape[1:]), tree)


def _default_accum_unroll(accum_steps: int) -> int:
    """Scan unroll for the microbatch loop. On TPU the rolled ``while`` is
    right (compile time stays O(1) in N; XLA pipelines the body). XLA:CPU
    executes while-loop bodies WITHOUT intra-op parallelism — measured 8×
    slower per microbatch on the bench host — so off-TPU the loop is fully
    unrolled, trading compile time for the multi-core step."""
    return 1 if jax.default_backend() == "tpu" else accum_steps


def _accumulate_grads(vag: Callable, params, batch_stats, inputs, labels,
                      rng_for: Callable, accum_steps: int,
                      metrics_fn: Optional[Callable],
                      unroll: Optional[int] = None):
    """Scan ``accum_steps`` microbatches, summing gradients on-device.

    ``vag`` is ``jax.value_and_grad(loss, has_aux=True)`` with signature
    ``(params, batch_stats, inputs, labels, rng) -> ((loss, (logits,
    new_stats)), grads)``; ``rng_for(i)`` derives the i-th microbatch's
    dropout key. Returns ``(mean_loss, new_batch_stats, mean_grads,
    mean_extras)`` where the means are over microbatches — composed with the
    ``average=True`` world pmean downstream, gradients end up divided by the
    global microbatch count (``accum_steps × size``), exactly the full-batch
    scaling. Integer metric leaves (e.g. counts) keep the microbatch sum —
    the full-batch value — instead of a flooring integer mean.
    Gradients accumulate in fp32 when their dtype is narrower and
    are cast back after the mean; batch statistics thread sequentially
    through the microbatches (N momentum updates per step — the defined
    semantics for BN under accumulation, not bit-equal to one full-batch
    update).
    """
    n = accum_steps
    mb_in = _split_microbatches(inputs, n)
    mb_lab = _split_microbatches(labels, n)
    first = (jax.tree_util.tree_map(lambda x: x[0], mb_in),
             jax.tree_util.tree_map(lambda x: x[0], mb_lab))

    # Structure probe (no FLOPs): shapes/dtypes of grads, logits and metric
    # extras, to build type-stable zero carries for the scan.
    (_, (logits_s, _)), grads_s = jax.eval_shape(
        vag, params, batch_stats, first[0], first[1], rng_for(0))
    extras_s = (jax.eval_shape(metrics_fn, logits_s, first[1])
                if metrics_fn is not None else None)

    def _zeros(s):
        return jnp.zeros(s.shape, _acc_dtype(s.dtype))

    carry = (
        jax.tree_util.tree_map(_zeros, grads_s),
        batch_stats,
        jnp.zeros((), jnp.float32),
        (jax.tree_util.tree_map(_zeros, extras_s)
         if metrics_fn is not None else None),
    )

    def _body(carry, xs):
        gacc, stats, lacc, macc = carry
        i, x, y = xs
        (loss, (logits, new_stats)), grads = vag(
            params, stats, x, y, rng_for(i))
        gacc = jax.tree_util.tree_map(
            lambda a, g: a + g.astype(a.dtype), gacc, grads)
        lacc = lacc + loss.astype(jnp.float32)
        if metrics_fn is not None:
            macc = jax.tree_util.tree_map(
                lambda a, m: a + jnp.asarray(m).astype(a.dtype),
                macc, metrics_fn(logits, y))
        return (gacc,
                new_stats if new_stats is not None else stats,
                lacc, macc), None

    (gacc, stats, lacc, macc), _ = jax.lax.scan(
        _body, carry, (jnp.arange(n), mb_in, mb_lab),
        unroll=_default_accum_unroll(n) if unroll is None else unroll)

    inv = 1.0 / n
    grads = jax.tree_util.tree_map(
        lambda a, s: (a * jnp.asarray(inv, a.dtype)).astype(s.dtype),
        gacc, grads_s)

    def _mean_extra(a, s):
        # Integer metric leaves keep the microbatch SUM: jnp.asarray(1/n,
        # int_dtype) is 0 (same guard as fusion._prescale_array), and for a
        # count-style metric the sum over microbatches IS the full-batch
        # value the accum_steps=1 path reports.
        if not jnp.issubdtype(s.dtype, jnp.inexact):
            return a.astype(s.dtype)
        return (a * jnp.asarray(inv, a.dtype)).astype(s.dtype)

    extras = None
    if metrics_fn is not None:
        extras = jax.tree_util.tree_map(_mean_extra, macc, extras_s)
    return lacc * inv, stats, grads, extras


def _check_accum_batch(inputs, accum_steps: int, shards: int) -> None:
    """Leading-dim divisibility check for the accumulated step — raised
    eagerly with the full arithmetic instead of a reshape error from deep
    inside the trace."""
    leaves = jax.tree_util.tree_leaves(inputs)
    if not leaves:
        return
    rows = leaves[0].shape[0]
    if rows % (shards * accum_steps):
        raise ValueError(
            f"global batch of {rows} rows cannot be split into "
            f"{shards} shard(s) x {accum_steps} microbatches "
            f"(needs divisibility by {shards * accum_steps}); adjust the "
            f"batch size or accum_steps")


def _build_value_and_grad(model, loss_fn, remat):
    """Shared loss/grad builder for BOTH execution planes (the compiled
    SPMD step and the env-world grads half): variables-dict assembly,
    mutable batch_stats, dropout rng plumbing, optional remat wrap. One
    definition so a change to loss semantics cannot silently diverge the
    two planes."""

    def _loss(params, batch_stats, inputs, labels, step_rng):
        variables = {"params": params}
        if batch_stats is not None:
            variables["batch_stats"] = batch_stats
        out = model.apply(
            variables, inputs, train=True,
            mutable=["batch_stats"] if batch_stats is not None else [],
            rngs={"dropout": step_rng},
        )
        logits, new_vars = out if isinstance(out, tuple) else (out, {})
        loss = loss_fn(logits, labels)
        return loss, (logits, new_vars.get("batch_stats"))

    if remat:
        _loss = jax.checkpoint(
            _loss, policy=None if remat is True else remat)
    return jax.value_and_grad(_loss, has_aux=True)


def create_train_state(model, rng, sample_input, optimizer,
                       *, average: bool = True,
                       fusion_threshold: Optional[int] = None,
                       compression: Any = Compression.none,
                       zero: Optional[bool] = None,
                       wire_dtype=None,
                       overlap: Optional[bool] = None,
                       has_batch_stats: Optional[bool] = None,
                       mesh: Optional[jax.sharding.Mesh] = None,
                       param_specs=None,
                       model_kwargs: Optional[dict] = None) -> Tuple[
                           TrainState, optax.GradientTransformation]:
    """Initialize model + DistributedOptimizer state.

    Returns ``(state, dist_opt)`` where ``dist_opt`` is the optimizer wrapped
    with the fused gradient allreduce (``DistributedOptimizer``); its state is
    bit-identical to plain optax state so checkpoints restore without this
    framework (the Keras dynamic-subclass parity property,
    ``horovod/keras/__init__.py:81-87``).

    ``zero`` (default: ``HVD_ZERO``) wraps the optimizer with ZeRO-1
    sharded updates instead (``DistributedOptimizer(zero=True)``): the
    optimizer state is rank-sharded (1/size() per device) and the step
    must be built with ``make_train_step(zero=True)`` — which it picks up
    automatically from the optimizer's capability stamp.

    ``wire_dtype`` (default: ``HVD_WIRE_DTYPE``) and ``overlap`` (default:
    ``HVD_OVERLAP``) pass through to the ``DistributedOptimizer`` — the
    low-precision wire format and backward-overlapped bucket emission
    (``docs/performance.md`` "Overlap & wire formats").

    ``mesh=`` + ``param_specs=`` build the state for the N-D hybrid
    plane (``docs/performance.md`` "Hybrid dp×tp"): params are placed as
    global arrays laid out by the spec tree (``param_specs`` may be a
    callable ``params -> spec tree``), the optimizer carries the
    spec-grouped collective plan, and with ``zero=True`` its state
    shards over the mesh's ``dp`` axis for tp-sharded params too. Build
    the step with ``make_train_step`` as usual — it auto-detects the
    plane from the optimizer's stamp.
    """
    from .utils import config as _config
    if zero is None:
        zero = _config.zero_enabled()
    variables = model.init(rng, sample_input, **(model_kwargs or {}))
    params = variables.get("params", variables)
    batch_stats = variables.get("batch_stats")
    if has_batch_stats is not None and not has_batch_stats:
        batch_stats = None
    if param_specs is not None or mesh is not None:
        if param_specs is None or mesh is None:
            raise ValueError(
                "hybrid state needs BOTH mesh= and param_specs= — the "
                "mesh names the axes the specs refer to")
        specs = param_specs(params) if callable(param_specs) \
            else param_specs
        params = jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
            params, specs, is_leaf=lambda x: isinstance(x, P))
        if batch_stats is not None:
            batch_stats = jax.device_put(
                batch_stats, NamedSharding(mesh, P()))
        dist_opt = DistributedOptimizer(
            optimizer, average=average, fusion_threshold=fusion_threshold,
            compression=compression, zero=zero, wire_dtype=wire_dtype,
            overlap=overlap, mesh=mesh, param_specs=specs)
        state = TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=dist_opt.init(params),
            batch_stats=batch_stats,
        )
        return state, dist_opt
    dist_opt = DistributedOptimizer(
        optimizer, average=average, fusion_threshold=fusion_threshold,
        compression=compression, zero=zero, wire_dtype=wire_dtype,
        overlap=overlap)
    if (zero and runtime.is_initialized() and runtime.size() > 1
            and not runtime.world().env_world):
        # The ZeRO opt state is committed to the world mesh (stacked
        # shards, P(AXIS)); commit the replicated half to the same mesh so
        # the state is device-consistent from step 0 — and so these trees
        # work as restore TEMPLATES (restore_sharded lays leaves out from
        # the template's sharding, and a mixed dev0/mesh commitment would
        # be rejected by jit).
        rep = runtime.replicated_sharding()
        params = jax.device_put(params, rep)
        if batch_stats is not None:
            batch_stats = jax.device_put(batch_stats, rep)
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=dist_opt.init(params),
        batch_stats=batch_stats,
    )
    return state, dist_opt


def make_train_step(model,
                    dist_opt: optax.GradientTransformation,
                    loss_fn: Callable = cross_entropy_loss,
                    *,
                    mesh: Optional[jax.sharding.Mesh] = None,
                    axis_name: str = AXIS,
                    donate: bool = True,
                    metrics_fn: Optional[Callable] = None,
                    accum_steps: int = 1,
                    accum_unroll: Optional[int] = None,
                    remat: Any = False,
                    guard_nonfinite: Optional[bool] = None,
                    zero: Optional[bool] = None,
                    overlap: Optional[bool] = None,
                    param_specs=None,
                    batch_spec=None,
                    _value_and_grad: Optional[Callable] = None):
    """Build the compiled SPMD train step.

    The returned function has signature ``step(state, batch) -> (state,
    metrics)`` where ``batch = (inputs, labels)`` is sharded on its leading
    axis over the world mesh and ``state`` is replicated. ``metrics`` (loss,
    plus ``metrics_fn(logits, labels)`` extras) are already globally averaged
    via ``pmean`` — the in-step equivalent of ``MetricAverageCallback``
    (``horovod/keras/callbacks.py:37-87``).

    ``accum_steps=N`` is the TPU-native ``backward_passes_per_step``
    (Sergeev & Del Balso 2018 §4): each shard's batch slice is split into N
    microbatches scanned INSIDE the compiled program, gradients are summed
    on-device (fp32 accumulation for sub-fp32 grads) and the fused psum
    fires **once** per accumulated step on the microbatch-mean tree — so
    the global batch can grow N× without growing peak activation memory or
    interconnect traffic per step. The step owns the ``1/N`` scaling; leave
    the ``DistributedOptimizer`` at its default ``accum_steps=1``.
    ``accum_unroll`` overrides the microbatch-scan unroll (default: rolled
    on TPU, fully unrolled elsewhere — see ``_default_accum_unroll``).

    ``remat`` checkpoints each microbatch's forward pass (``jax.checkpoint``;
    pass ``True`` or a ``jax.checkpoint_policies`` policy) — activations are
    recomputed during backprop, trading ~⅓ more FLOPs for microbatch-sized
    rather than batch-sized activation memory (GPipe, Huang et al. 2019).

    ``guard_nonfinite`` (default: ``HVD_GUARD_NONFINITE``) arms the in-jit
    bad-step guard: the world-wide all-finite flag is derived from the
    ALREADY-reduced fusion buckets (same psum round, zero extra
    collectives — :func:`~horovod_tpu.ops.fusion.fused_allreduce`) and a
    non-finite gradient tree on ANY replica leaves params, opt_state and
    batch_stats bit-unchanged (skip-step; the step counter still
    advances, so the next step's dropout keys differ). The step's metrics
    gain a replica-identical ``bad_step`` scalar (1.0 = skipped) and the
    other metric values are zeroed on skipped steps so a NaN loss cannot
    poison the epoch mean; ``Trainer.fit`` turns consecutive skips into
    rollback/abort containment (``HVD_MAX_BAD_STEPS``).

    ``zero`` (default: ``HVD_ZERO``, or auto-detected from a
    ``DistributedOptimizer(zero=True)`` optimizer) runs the ZeRO-1
    sharded-update plane: the gradient exchange is one fused
    reduce-scatter + one all-gather per bucket (no full-tree all-reduce),
    the optimizer state rides the step rank-sharded (``P(AXIS)`` stacked
    shards — 1/size() of the bytes per device), and every replica's
    params stay bit-identical. Composes with ``accum_steps`` (the scatter
    still fires once per accumulated step), ``remat``, and
    ``guard_nonfinite`` (the world-wide all-finite flag rides the
    all-gather the updated shards already take — zero extra collectives —
    and a skip leaves the SHARDED opt state bit-unchanged).

    ``overlap`` (default: ``HVD_OVERLAP``, or the optimizer's stamp) arms
    backward-overlapped bucket collectives: a one-time traced-jaxpr probe
    (:func:`~horovod_tpu.ops.fusion.probe_grad_order`, cached per input
    shapes) records the order the backward pass materializes each
    gradient leaf, and the fused exchange issues one collective per
    bucket in that order behind ``optimization_barrier`` pins — so XLA
    schedules each bucket's wire time behind the remaining backward
    compute instead of serializing one post-backward blob. Total
    collective count is unchanged (overlap reorders, never adds); on the
    ZeRO plane bucket membership is pinned by the plan and only emission
    order changes. Composes with ``wire_dtype`` on the optimizer
    (``docs/performance.md`` "Overlap & wire formats").

    ``param_specs`` (with ``mesh`` an N-D hybrid mesh from
    ``create_hybrid_mesh``) runs the step on the hybrid dp×tp plane: the
    state's params are global arrays laid out by the spec tree, the
    gradient exchange is the spec-grouped collective plan (tp-sharded
    weight grads psum over ``dp`` only; replicated leaves over the full
    mesh), ZeRO shards the optimizer state over ``dp`` for tp-sharded
    params too, and ``accum_steps``/``guard_nonfinite``/``overlap``/the
    optimizer's ``wire_dtype`` all compose unchanged. Auto-detected from
    a ``DistributedOptimizer(mesh=, param_specs=)`` stamp — build the
    state with ``create_train_state(mesh=, param_specs=)`` and this knob
    resolves itself. ``batch_spec`` overrides the batch layout (default:
    leading axis over ``dp``/``ep``). ``_value_and_grad`` swaps the flax
    loss builder for a custom ``(params, batch_stats, inputs, labels,
    rng) -> ((loss, (logits, new_stats)), grads)`` — the hook
    ``parallel/transformer.py`` re-targets through so both families run
    ONE step implementation. Single-controller only.
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    stamp_mesh = getattr(dist_opt.update, "mesh", None)
    hybrid = param_specs is not None \
        or getattr(dist_opt.update, "hybrid", False)
    if hybrid:
        if param_specs is None:
            param_specs = getattr(dist_opt.update, "param_specs", None)
        if mesh is None:
            mesh = stamp_mesh
        if mesh is None or param_specs is None:
            raise ValueError(
                "hybrid step needs BOTH mesh= and param_specs= (or a "
                "DistributedOptimizer(mesh=, param_specs=) whose stamps "
                "supply them)")
        if stamp_mesh is not None and mesh is not stamp_mesh:
            raise ValueError(
                "make_train_step(mesh=...) differs from the mesh this "
                "DistributedOptimizer was built for — the collective "
                "plan is keyed to one mesh; pass the same object")
        if runtime.is_initialized() and runtime.world().env_world:
            raise ValueError(
                "the hybrid dp×tp plane is single-controller only: the "
                "tpurun env-world has no tp axis for compiled collectives "
                "to span — run one process driving all chips")
    zero_stamped = getattr(dist_opt.update, "zero", False)
    if zero is None:
        from .utils import config as _config
        zero = zero_stamped or _config.zero_enabled()
    if zero and not zero_stamped:
        raise ValueError(
            "zero=True (or HVD_ZERO=1) requires a ZeRO-sharded optimizer: "
            "the step's opt-state sharding specs come from its "
            "partitioned state — build it with "
            "DistributedOptimizer(opt, zero=True) / partition_optimizer "
            "(create_train_state(zero=True) does this for you)")
    if zero_stamped and not zero:
        raise ValueError(
            "this DistributedOptimizer was built with zero=True — its "
            "state is rank-sharded and the step must be built with "
            "make_train_step(zero=True) (leave zero unset to auto-detect)")
    if guard_nonfinite is None:
        from .utils import config as _config
        guard_nonfinite = _config.guard_nonfinite()
    if guard_nonfinite and not getattr(dist_opt.update,
                                       "supports_finite_out", False):
        raise ValueError(
            "guard_nonfinite requires a DistributedOptimizer-wrapped "
            "optimizer: the all-finite flag is derived inside its fused "
            "allreduce so every replica agrees on the skip decision with "
            "no extra collective; a plain optax transformation has no "
            "such channel (wrap it with "
            "horovod_tpu.DistributedOptimizer(...))")
    if accum_steps > 1 and getattr(dist_opt.update, "accum_steps", 1) > 1:
        raise ValueError(
            "accum_steps is set on BOTH make_train_step and "
            "DistributedOptimizer — the gradients would be divided by N "
            "twice; set it in one place (make_train_step owns the "
            "microbatch scan and its 1/N)")
    if overlap is None:
        from .utils import config as _config
        overlap = bool(getattr(dist_opt.update, "overlap", False)) \
            or _config.overlap_enabled()
    if overlap and not getattr(dist_opt.update, "supports_grad_order",
                               False):
        raise ValueError(
            "overlap=True (or HVD_OVERLAP=1) requires a "
            "DistributedOptimizer-wrapped optimizer: the backward-"
            "completion order is threaded into its fused collective "
            "traversal (the grad_order channel); a plain optax "
            "transformation has no collectives to overlap (wrap it with "
            "horovod_tpu.DistributedOptimizer(...))")
    mesh = mesh if mesh is not None else runtime.mesh()
    if _value_and_grad is not None:
        if remat:
            raise ValueError(
                "a custom _value_and_grad owns its own remat policy "
                "(wrap the loss before differentiating) — "
                "make_train_step(remat=) only applies to the flax model "
                "path")
        vag = _value_and_grad
    else:
        vag = _build_value_and_grad(model, loss_fn, remat)

    if hybrid:
        hybrid_axes = tuple(mesh.axis_names)
        if batch_spec is None:
            ba = tuple(a for a in ("dp", "ep") if a in hybrid_axes)
            batch_spec = P(ba if len(ba) > 1
                           else (ba[0] if ba else None))
        # Dropout rng folds the BATCH-plane position (dp/sp/ep) only: tp
        # ranks replicate the same rows and must draw identical masks or
        # the activations they exchange would diverge.
        rng_axes = tuple(
            a for e in batch_spec if e is not None
            for a in ((e,) if isinstance(e, str) else e))
        metric_axes: Any = hybrid_axes
    else:
        rng_axes = (axis_name,)
        metric_axes = axis_name

    # Backward-completion probe (overlap mode): one abstract trace per
    # input-shape signature, host-side and OUTSIDE the step trace, so the
    # jitted program reads a plain static tuple. The order is a pure
    # function of the traced program — identical across processes and
    # across re-traces of the same shapes, so the jit cache key does not
    # need to carry it.
    _overlap_probe: dict = {"key": None, "order": None}

    def _probe_overlap(state, inputs, labels):
        if not overlap:
            return None
        key = (
            tuple((tuple(jnp.shape(l)), str(jnp.result_type(l)))
                  for l in jax.tree_util.tree_leaves(state.params)),
            tuple((tuple(jnp.shape(l)), str(jnp.result_type(l)))
                  for l in jax.tree_util.tree_leaves((inputs, labels))),
        )
        if key != _overlap_probe["key"]:
            from .ops.fusion import probe_grad_order
            _overlap_probe["order"] = probe_grad_order(
                lambda p: vag(p, state.batch_stats, inputs, labels,
                              jax.random.PRNGKey(0))[1], state.params)
            _overlap_probe["key"] = key
        return None

    def _overlap_kwargs(grads):
        """Static grad_order kwarg for the optimizer update (trace time).
        Falls back to flatten order — plan-order emission with barrier
        pins, still unmergeable and deterministic — when the probe could
        not rank the leaves or the tree carries sparse leaves (whose
        flatten arity differs from the probe's)."""
        if not overlap:
            return {}
        from .optimizer import _is_sparse_leaf
        n = len(jax.tree_util.tree_leaves(grads, is_leaf=_is_sparse_leaf))
        order = _overlap_probe["order"]
        if order is None or len(order) != n:
            order = tuple(range(n))
        return {"grad_order": order}

    def _step(state: TrainState, inputs, labels):
        # Fresh dropout mask per step and per rank: fold the step counter
        # and rank into the key (identical masks every step would starve
        # the dropped units of gradient for the whole run). On the hybrid
        # plane only the batch-plane axes fold in (tp ranks share masks).
        step_rng = jax.random.fold_in(jax.random.PRNGKey(0), state.step)
        for _a in rng_axes:
            step_rng = jax.random.fold_in(
                step_rng, jax.lax.axis_index(_a))
        if accum_steps == 1:
            (loss, (logits, new_stats)), grads = vag(
                state.params, state.batch_stats, inputs, labels, step_rng)
            extras = (metrics_fn(logits, labels)
                      if metrics_fn is not None else None)
        else:
            loss, new_stats, grads, extras = _accumulate_grads(
                vag, state.params, state.batch_stats, inputs, labels,
                lambda i: jax.random.fold_in(step_rng, i),
                accum_steps, metrics_fn, unroll=accum_unroll)
        # DistributedOptimizer performs the fused allreduce over `axis_name`
        # — on the accumulated (microbatch-mean) tree, once per step.
        upd_kwargs = _overlap_kwargs(grads)
        if guard_nonfinite:
            finite_out: dict = {}
            updates, new_opt_state = dist_opt.update(
                grads, state.opt_state, state.params,
                finite_out=finite_out, **upd_kwargs)
            all_finite = finite_out["all_finite"]
        else:
            updates, new_opt_state = dist_opt.update(
                grads, state.opt_state, state.params, **upd_kwargs)
        new_params = optax.apply_updates(state.params, updates)
        new_stats = new_stats if new_stats is not None else state.batch_stats
        metrics = {"loss": jax.lax.pmean(loss, metric_axes)}
        if extras is not None:
            metrics.update(jax.tree_util.tree_map(
                lambda m: jax.lax.pmean(m, metric_axes), extras))
        if guard_nonfinite:
            # Skip-step select: a scalar where() per leaf, which XLA fuses
            # into the update elementwise ops — params/opt_state/batch_stats
            # are bit-unchanged when any replica saw NaN/Inf. all_finite is
            # replica-identical by construction (derived from the psum'd
            # buckets), so every replica takes the same branch and NO extra
            # collective is needed for the decision itself.
            def _keep(new, old):
                return jnp.where(all_finite, new, old)
            new_params = jax.tree_util.tree_map(
                _keep, new_params, state.params)
            new_opt_state = jax.tree_util.tree_map(
                _keep, new_opt_state, state.opt_state)
            if state.batch_stats is not None:
                new_stats = jax.tree_util.tree_map(
                    _keep, new_stats, state.batch_stats)
            # Metric hygiene: a skipped step's loss/extras are NaN-bearing
            # by definition — zero them so the trainer's epoch accumulator
            # stays finite (it divides by the GOOD step count), and expose
            # the flag itself (already identical on every replica; a pmean
            # here would add the very all-reduce the guard is pinned not
            # to add).
            metrics = jax.tree_util.tree_map(
                lambda m: jnp.where(all_finite, m,
                                    jnp.zeros_like(m)), metrics)
            metrics["bad_step"] = 1.0 - all_finite.astype(jnp.float32)
        new_state = TrainState(
            step=state.step + 1,
            params=new_params,
            opt_state=new_opt_state,
            batch_stats=new_stats,
        )
        return new_state, metrics

    if hybrid:
        # Hybrid plane: one jit per state structure, specs resolved lazily
        # from the live state (the opt-state layout is only known once the
        # state exists — same pattern as the 1-D ZeRO plane below).
        ba0 = batch_spec[0] if len(batch_spec) else None
        lead_axes = () if ba0 is None else (
            (ba0,) if isinstance(ba0, str) else tuple(ba0))
        n_lead = 1
        for _a in lead_axes:
            n_lead *= int(mesh.shape[_a])
        _hy_exec: dict = {}

        def _hy_jitted(state: TrainState):
            key = (jax.tree_util.tree_structure(state.params),
                   jax.tree_util.tree_structure(state.opt_state),
                   state.batch_stats is not None)
            fn = _hy_exec.get(key)
            if fn is None:
                pspecs = param_specs(state.params) \
                    if callable(param_specs) else param_specs
                ospecs = _hybrid_opt_specs(dist_opt, state.opt_state,
                                           pspecs)
                st_spec = TrainState(step=P(), params=pspecs,
                                     opt_state=ospecs, batch_stats=P())
                fn = jax.jit(
                    lambda s, x, y: jax.shard_map(
                        _step, mesh=mesh,
                        in_specs=(st_spec, batch_spec, batch_spec),
                        out_specs=(st_spec, P()),
                        check_vma=False,
                    )(s, x, y),
                    donate_argnums=(0,) if donate else ())
                _hy_exec[key] = fn
            return fn

        def hybrid_step(state: TrainState, batch):
            inputs, labels = batch
            if accum_steps > 1:
                _check_accum_batch(inputs, accum_steps, n_lead)
            _probe_overlap(state, inputs, labels)
            return _hy_jitted(state)(state, inputs, labels)

        hybrid_step.lower = lambda state, batch: (
            _probe_overlap(state, *batch)
            or _hy_jitted(state).lower(state, *batch))
        return hybrid_step

    def _sharded(state, inputs, labels):
        return jax.shard_map(
            _step, mesh=mesh,
            in_specs=(P(), P(axis_name), P(axis_name)),
            out_specs=P(),
            check_vma=False,
        )(state, inputs, labels)

    jitted = jax.jit(_sharded, donate_argnums=(0,) if donate else ())

    if _is_env_world(mesh):
        return _make_env_world_step(model, dist_opt, loss_fn, mesh,
                                    axis_name, metrics_fn,
                                    accum_steps=accum_steps,
                                    accum_unroll=accum_unroll, remat=remat,
                                    guard_nonfinite=guard_nonfinite,
                                    zero=zero)

    n_shards = int(mesh.shape[axis_name]) if accum_steps > 1 else 1

    if zero:
        # ZeRO plane: the optimizer state rides the step rank-sharded —
        # its stacked [size, shard] leaves get P(axis) in/out specs so
        # each device holds (and the donate reuses) 1/size of the bytes.
        # The spec tree depends on the wrapped optimizer's state
        # STRUCTURE, known only when the state first arrives; built once
        # per structure and cached.
        _zero_exec: dict = {}

        def _zero_jitted(state: TrainState):
            key = jax.tree_util.tree_structure(state.opt_state)
            fn = _zero_exec.get(key)
            if fn is None:
                ospec = _zero_state_spec(state.opt_state, axis_name)
                st_spec = TrainState(step=P(), params=P(),
                                     opt_state=ospec, batch_stats=P())
                fn = jax.jit(
                    lambda s, x, y: jax.shard_map(
                        _step, mesh=mesh,
                        in_specs=(st_spec, P(axis_name), P(axis_name)),
                        out_specs=(st_spec, P()),
                        check_vma=False,
                    )(s, x, y),
                    donate_argnums=(0,) if donate else ())
                _zero_exec[key] = fn
            return fn

        def step(state: TrainState, batch):
            inputs, labels = batch
            if accum_steps > 1:
                _check_accum_batch(inputs, accum_steps, n_shards)
            _probe_overlap(state, inputs, labels)
            return _zero_jitted(state)(state, inputs, labels)

        step.lower = lambda state, batch: (
            _probe_overlap(state, *batch)
            or _zero_jitted(state).lower(state, *batch))
        return step

    @functools.wraps(jitted)
    def step(state: TrainState, batch):
        inputs, labels = batch
        if accum_steps > 1:
            _check_accum_batch(inputs, accum_steps, n_shards)
        _probe_overlap(state, inputs, labels)
        return jitted(state, inputs, labels)

    # AOT handle (jax .lower convention): lets callers inspect the compiled
    # artifact — e.g. count the all-reduce ops to verify fusion bucketing
    # survived compilation (tests/test_fusion.py pins this; with
    # accum_steps > 1 the count proves the psum sits outside the scan).
    step.lower = lambda state, batch: (
        _probe_overlap(state, *batch) or jitted.lower(state, *batch))
    return step


def _zero_state_spec(opt_state, axis_name: str):
    """PartitionSpec tree for a ZeRO optimizer state: ``P(axis)`` on the
    stacked ``[nshards, shard_len]`` shard leaves (leading axis split one
    shard per rank), ``P()`` on everything else (scalars like Adam's step
    count stay replicated)."""
    from .optimizer import ZeroShardedState

    def _one(zs: ZeroShardedState):
        shard_shapes = set(zs.plan.shard_shapes())
        inner = jax.tree_util.tree_map(
            lambda l: P(axis_name)
            if tuple(getattr(l, "shape", ())) in shard_shapes else P(),
            zs.inner)
        return ZeroShardedState(inner=inner, plan=zs.plan)

    return jax.tree_util.tree_map(
        _one, opt_state,
        is_leaf=lambda x: isinstance(x, ZeroShardedState))


def _hybrid_opt_specs(dist_opt, opt_state, pspecs):
    """PartitionSpec tree for a hybrid-plane optimizer state. ZeRO states
    spec their stacked leaves by bucket (``P(dp, shard_axes)`` — the
    leaf→bucket mapping reuses the canonicalization's contiguous-run
    logic, since two buckets can share a stacked shape with different
    specs); replicated-update states mirror the PARAM specs leaf-for-leaf
    (a tp-sharded weight's momentum shards over tp too), with scalar
    state (Adam's count) replicated."""
    from .optimizer import ZeroShardedState, _zero_shard_leaf_buckets
    from .ops.fusion import zero_stacked_spec

    def _is_z(x):
        return isinstance(x, ZeroShardedState)

    if any(_is_z(l) for l in jax.tree_util.tree_leaves(
            opt_state, is_leaf=_is_z)):
        def _one(zs: "ZeroShardedState"):
            ids = _zero_shard_leaf_buckets(zs.inner, zs.plan)
            _, td = jax.tree_util.tree_flatten(zs.inner)
            specs = [P() if b is None else zero_stacked_spec(zs.plan, b)
                     for b in ids]
            return ZeroShardedState(inner=td.unflatten(specs),
                                    plan=zs.plan)
        return jax.tree_util.tree_map(_one, opt_state, is_leaf=_is_z)
    inner = getattr(dist_opt.update, "inner_transform", None) or dist_opt
    return optax.tree_map_params(
        inner, lambda _, s: s, opt_state, pspecs,
        transform_non_params=lambda _: P())


def _is_env_world(mesh) -> bool:
    """True in tpurun env-world mode: independent JAX processes whose world
    size (launcher env) exceeds the local mesh — compiled collectives cannot
    cross processes, so gradients must ride the host coordination plane
    (exactly the reference's model: per-process TF graphs + MPI allreduce)."""
    if not runtime.is_initialized():
        return False
    w = runtime.world()
    return w.env_world and w.coord is not None


def _env_wire_np(dist_opt):
    """Resolve the optimizer's wire stamp for the host coordination plane:
    bf16 payloads ride the coordinator wire natively (its reduction widens
    to f32 and narrows back — the same fp32-accumulation guarantee the
    compiled plane pins); fp8 has no host wire dtype and is rejected with
    the remedy named rather than silently training at full precision."""
    import numpy as np
    wire_name = getattr(dist_opt.update, "wire_dtype", "fp32")
    if wire_name == "fp8":
        raise ValueError(
            "wire_dtype='fp8' is compiled-plane only: the host "
            "coordinator wire carries bf16 (reduced with f32 "
            "accumulation) but has no fp8 dtype — use wire_dtype='bf16' "
            "under tpurun")
    if wire_name == "bf16":
        return np.dtype(jnp.bfloat16)
    return None


def _env_wire_cast(payload, wire_np):
    """Cast one host bucket payload onto the wire dtype; returns
    ``(payload, orig_dtype_or_None)`` — the receive side casts back so
    everything downstream of the wire stays full precision."""
    import numpy as np
    if (wire_np is not None
            and np.issubdtype(payload.dtype, np.floating)
            and payload.dtype.itemsize > wire_np.itemsize):
        return payload.astype(wire_np), payload.dtype
    return payload, None


_env_exchange_metrics = None


def _obs_exchange(n_submits: int, n_bytes: int, tag: int) -> None:
    """Host-plane collective telemetry for the env-world step: the
    compiled planes' collectives live inside XLA where nothing host-side
    can count them, but here every exchange IS a host submit — one
    counter bump per step (aggregated, not per bucket) plus a
    flight-recorder event, so a dead rank's post-mortem shows whether it
    died inside an exchange and how much wire the job was moving.

    ``tag`` is the 1-based exchange counter (the collective-name
    namespace), NOT the trainer's global step — the event deliberately
    records it under ``tag=`` so a dump's ``last_step`` (derived from
    the newest ``step``-bearing event) never misreports an exchange
    tag as a completed training step."""
    global _env_exchange_metrics
    if _env_exchange_metrics is None:
        from .obs.registry import registry as _registry_fn
        reg = _registry_fn()
        _env_exchange_metrics = (
            reg.counter("hvd_collective_submits_total",
                        "Host-plane collective submissions (env-world "
                        "gradient/metric exchanges)"),
            reg.counter("hvd_collective_bytes_total",
                        "Bytes submitted to host-plane collectives "
                        "(post wire-cast, padding included)"))
    _env_exchange_metrics[0].inc(n_submits)
    _env_exchange_metrics[1].inc(n_bytes)
    from .obs import flightrec
    flightrec.record("exchange", tag=tag, submits=n_submits,
                     bytes=n_bytes)


def _make_env_world_step(model, dist_opt, loss_fn, mesh, axis_name,
                         metrics_fn, accum_steps: int = 1,
                         accum_unroll: Optional[int] = None,
                         remat: Any = False,
                         guard_nonfinite: bool = False,
                         zero: bool = False):
    """Env-world train step: jit(grads) → host fused allreduce → jit(apply).

    The host gradient exchange INTERPRETS the gradient-sync plan stamped
    on the optimizer (``dist_opt.update.exchange_plan`` →
    :func:`~horovod_tpu.ops.fusion.plan_exchange`): the same ``GradSync``
    data the compiled executors read, so bucket membership and averaging
    denominators can never drift between the ICI-psum and
    coordinator-wire executors — one planner, two executors. Membership
    follows the same fusion scan as the compiled path (64 MiB /
    same-dtype / order-preserving, ``HOROVOD_FUSION_THRESHOLD``), so the
    reference's tensor-fusion contract (``docs/tensor-fusion.md``) holds
    for this plane too. ``accum_steps``
    scans microbatches inside the jitted gradient half exactly like the
    single-controller step, and the per-step host round trip count is
    unchanged — the accumulated tree rides one fused exchange, which is the
    whole point of ``backward_passes_per_step`` on a negotiated plane.

    ``guard_nonfinite`` checks the REDUCED host buckets (the averaged sum
    already carries every rank's NaN/Inf, so all ranks agree) and skips
    the jitted apply half entirely on a bad step — params/opt_state stay
    the same arrays, the step counter advances, and ``bad_step`` rides
    the metrics dict exactly like the compiled plane.

    ``zero`` routes the exchange through the coordinator's
    ``reducescatter`` instead: each rank receives the reduced 1/size
    slice of every fused bucket, updates its LOCAL optimizer-state shard
    (this process physically holds only its own ``[1, shard_len]`` slice
    — true 1/size host memory), and the updated shards ride one
    ``allgather`` back into the full update tree. Same bytes on the wire
    as the all-reduce (reduce-scatter + all-gather IS the ring
    all-reduce), two host rounds instead of one. With the guard, each
    rank's local finite verdict rides the update all-gather (one extra
    ELEMENT, not an extra collective) so every rank takes the same skip
    decision — a skipped step discards the speculative shard update and
    keeps opt state bit-unchanged.
    """
    from .ops.fusion import plan_exchange

    w = runtime.world()
    vag = _build_value_and_grad(model, loss_fn, remat)
    wire_np = _env_wire_np(dist_opt)
    # The stamped planner (DistributedOptimizer carries it); a plain
    # optax optimizer falls back to the same planner at default knobs.
    exchange_plan = getattr(dist_opt.update, "exchange_plan", None) \
        or plan_exchange

    def _grads(state: TrainState, inputs, labels):
        step_rng = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(0), state.step),
            w.controller_rank)
        if accum_steps == 1:
            (loss, (logits, new_stats)), grads = vag(
                state.params, state.batch_stats, inputs, labels, step_rng)
            extras = (metrics_fn(logits, labels)
                      if metrics_fn is not None else {})
        else:
            loss, new_stats, grads, extras = _accumulate_grads(
                vag, state.params, state.batch_stats, inputs, labels,
                lambda i: jax.random.fold_in(step_rng, i),
                accum_steps, metrics_fn, unroll=accum_unroll)
            extras = extras if extras is not None else {}
        return loss, extras, new_stats, grads

    def _apply(state: TrainState, grads, new_stats):
        updates, new_opt_state = dist_opt.update(
            grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        return TrainState(
            step=state.step + 1, params=new_params,
            opt_state=new_opt_state,
            batch_stats=new_stats if new_stats is not None
            else state.batch_stats)

    # Both halves run under shard_map over the 1-device local mesh so the
    # world axis is bound: models built with axis_name (cross-replica
    # BatchNorm) trace lax.pmean(AXIS) inside _grads, and dist_opt's
    # in-trace psum appears in _apply. Over one local device both are the
    # identity — the real cross-rank averaging is the host-plane fused
    # allreduce between the two calls.
    grads_jit = jax.jit(jax.shard_map(
        _grads, mesh=mesh, in_specs=(P(), P(AXIS), P(AXIS)),
        out_specs=P(), check_vma=False))
    apply_jit = jax.jit(jax.shard_map(
        _apply, mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
        check_vma=False))
    counter = {"n": 0}

    if zero:
        return _make_env_world_zero_step(
            dist_opt, grads_jit, counter, w,
            accum_steps=accum_steps, guard_nonfinite=guard_nonfinite,
            wire_np=wire_np)

    def step(state: TrainState, batch):
        import numpy as np
        inputs, labels = batch
        if accum_steps > 1:
            _check_accum_batch(inputs, accum_steps, 1)
        loss, extras, new_stats, grads = grads_jit(state, inputs, labels)

        # Host-plane fused gradient averaging (the MPI_Allreduce analog).
        # Every bucket and metric is SUBMITTED before anything is waited on:
        # overlapped announcements negotiate concurrently and the
        # coordinator answers them in fused response frames — the
        # ComputeAsync concurrency model that feeds fusion in the reference
        # (mpi_ops.cc:1752-1772, 1395-1422). One synchronous round trip per
        # step instead of one per bucket.
        from .ops.collectives import Op
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        counter["n"] += 1
        tag = counter["n"]
        # Interpret the stamped GradSync plan: membership AND
        # denominators come from the one planner the compiled executors
        # read. The coordinator's AVERAGE op realizes denom == world
        # size; any other denominator rides an explicit post-scale.
        buckets, syncs = exchange_plan(leaves, world_size=w.size)
        handles = []
        wire_origs = []
        post_scale = []
        xbytes = 0
        for bi, bucket in enumerate(buckets):
            sync = syncs[bucket[0]]
            if len(bucket) == 1:
                payload = np.asarray(leaves[bucket[0]])
            else:
                payload = np.concatenate(
                    [np.ravel(np.asarray(leaves[j])) for j in bucket])
            payload, orig = _env_wire_cast(payload, wire_np)
            wire_origs.append(orig)
            if sync.denom == w.size:
                op, scale = Op.AVERAGE, None
            else:
                op, scale = Op.SUM, 1.0 / sync.denom
            post_scale.append(scale)
            xbytes += payload.nbytes
            handles.append(w.coord.submit(
                "allreduce", payload, f"grad.{tag}.{bi}", op=op))
        metric_handles = {"loss": w.coord.submit(
            "allreduce", np.asarray(loss, np.float32),
            f"metric.loss.{tag}", op=Op.AVERAGE)}
        for k, v in extras.items():
            metric_handles[k] = w.coord.submit(
                "allreduce", np.asarray(v, np.float32),
                f"metric.{k}.{tag}", op=Op.AVERAGE)
        _obs_exchange(len(handles) + len(metric_handles), xbytes, tag)

        reduced = [None] * len(leaves)
        all_finite = True
        for bi, bucket in enumerate(buckets):
            out = np.asarray(w.coord.wait(handles[bi]))
            if wire_origs[bi] is not None:
                # Off the wire, back to full precision: the coordinator
                # reduced the bf16 payload in f32 and narrowed once; the
                # gradient tree downstream stays in its original dtype.
                out = out.astype(wire_origs[bi])
            if post_scale[bi] is not None:
                # The plan's denominator, when the coordinator's AVERAGE
                # couldn't realize it directly.
                out = out * np.asarray(post_scale[bi], out.dtype)
            if guard_nonfinite and np.issubdtype(out.dtype, np.inexact):
                # Checked while still flat — one pass per REDUCED bucket,
                # mirroring the compiled plane's in-trace check. The
                # coordinator's average propagates any rank's NaN/Inf, so
                # this flag is identical on every rank by construction.
                all_finite = all_finite and bool(np.all(np.isfinite(out)))
            if len(bucket) == 1:
                j = bucket[0]
                reduced[j] = out.reshape(leaves[j].shape)
            else:
                off = 0
                for j in bucket:
                    n = leaves[j].size
                    reduced[j] = out[off:off + n].reshape(leaves[j].shape)
                    off += n
        grads = jax.tree_util.tree_unflatten(treedef, reduced)

        if guard_nonfinite and not all_finite:
            # Skip-step: drain the metric collectives (every rank
            # submitted them — the protocol must stay balanced), zero the
            # NaN-bearing values, advance only the step counter.
            for h in metric_handles.values():
                w.coord.wait(h)
            metrics = {k: np.zeros((), np.float32) for k in metric_handles}
            metrics["bad_step"] = np.ones((), np.float32)
            return dataclasses.replace(state, step=state.step + 1), metrics

        state = apply_jit(state, grads, new_stats)
        metrics = {k: w.coord.wait(h) for k, h in metric_handles.items()}
        if guard_nonfinite:
            metrics["bad_step"] = np.zeros((), np.float32)
        return state, metrics

    return step


def _make_env_world_zero_step(dist_opt, grads_jit, counter, w,
                              accum_steps: int,
                              guard_nonfinite: bool,
                              wire_np=None):
    """The ZeRO half of the env-world plane (see
    :func:`_make_env_world_step`): coordinator reduce-scatter → jitted
    local-shard optimizer update → coordinator all-gather of the updated
    shards (+ the guard's finite flag) → jitted apply. ``wire_np`` (bf16)
    casts the scatter payloads on send; the received shard is cast back
    to its original dtype BEFORE the jitted shard update — fp32 shard
    accumulation, mirroring the compiled plane — while the update
    all-gather stays full-precision so every rank rebuilds bit-identical
    params."""
    import numpy as np

    from .ops.collectives import Op
    from .optimizer import ZeroShardedState

    @jax.jit
    def zero_update_jit(state: TrainState, grad_shards):
        # plan is the state's static aux data — a trace-time constant.
        from .ops.fusion import shard_params
        plan = state.opt_state.plan
        gs = tuple(g.reshape(1, -1) for g in grad_shards)
        ps = tuple(p.reshape(1, -1) for p in shard_params(
            state.params, plan, rank=w.controller_rank))
        upd, new_inner = dist_opt.update.inner_update(
            gs, state.opt_state.inner, ps)
        return tuple(u.reshape(-1) for u in upd), new_inner

    @jax.jit
    def zero_apply_jit(state: TrainState, new_inner, updates, new_stats):
        new_params = optax.apply_updates(state.params, updates)
        return TrainState(
            step=state.step + 1, params=new_params,
            opt_state=ZeroShardedState(inner=new_inner,
                                       plan=state.opt_state.plan),
            batch_stats=new_stats if new_stats is not None
            else state.batch_stats)

    def step(state: TrainState, batch):
        from .ops.fusion import _unfuse_flat
        from .optimizer import _is_sparse_leaf
        inputs, labels = batch
        if accum_steps > 1:
            _check_accum_batch(inputs, accum_steps, 1)
        loss, extras, new_stats, grads = grads_jit(state, inputs, labels)

        if any(_is_sparse_leaf(l) for l in jax.tree_util.tree_leaves(
                grads, is_leaf=_is_sparse_leaf)):
            # This plane flattens grads itself (dist_opt.update's densify
            # wrapper is bypassed), so honor the stamp here — or fail with
            # the remedy named instead of a np.asarray TypeError below.
            if not getattr(dist_opt.update, "sparse_as_dense", False):
                raise ValueError(
                    "ZeRO sharded updates require dense gradients: an "
                    "IndexedSlices leaf cannot be flattened into "
                    "rank-sharded buckets — build the optimizer with "
                    "DistributedOptimizer(zero=True, sparse_as_dense="
                    "True), or use the replicated optimizer for sparse "
                    "models")
            grads = jax.tree_util.tree_map(
                lambda l: l.to_dense() if _is_sparse_leaf(l) else l,
                grads, is_leaf=_is_sparse_leaf)

        plan = state.opt_state.plan
        if plan.nshards != w.size:
            raise ValueError(
                f"ZeRO optimizer state was partitioned for a world of "
                f"{plan.nshards} but this env-world has {w.size} rank(s) "
                f"— initialize the state after hvd.init() under the "
                f"launcher (or restore through restore_sharded, which "
                f"re-shards)")
        leaves = plan.treedef.flatten_up_to(grads)
        counter["n"] += 1
        tag = counter["n"]
        # User-driven accumulation (DistributedOptimizer(accum_steps=N)):
        # fold the 1/N into the flat bucket before the scatter, exactly
        # where the compiled plane's prescale sits.
        pres = getattr(dist_opt.update, "accum_steps", 1)

        handles = []
        wire_origs = []
        xbytes = 0
        for bi, bucket in enumerate(plan.buckets):
            if len(bucket) == 1:
                flat = np.ravel(np.asarray(leaves[bucket[0]]))
            else:
                flat = np.concatenate(
                    [np.ravel(np.asarray(leaves[j])) for j in bucket])
            if pres > 1 and np.issubdtype(flat.dtype, np.inexact):
                if flat.dtype.itemsize < 4:
                    # Sub-fp32 buckets scale in fp32, one cast at the end
                    # (same rule as fusion._prescale_array).
                    flat = (flat.astype(np.float32)
                            * np.float32(1.0 / pres)).astype(flat.dtype)
                else:
                    flat = flat * flat.dtype.type(1.0 / pres)
            flat, orig = _env_wire_cast(flat, wire_np)
            wire_origs.append(orig)
            pad = plan.padded[bi] - plan.sizes[bi]
            if pad:
                flat = np.concatenate([flat, np.zeros(pad, flat.dtype)])
            xbytes += flat.nbytes
            handles.append(w.coord.submit(
                "reducescatter", flat, f"zgrad.{tag}.{bi}",
                op=Op.AVERAGE))
        metric_handles = {"loss": w.coord.submit(
            "allreduce", np.asarray(loss, np.float32),
            f"metric.loss.{tag}", op=Op.AVERAGE)}
        for k, v in extras.items():
            metric_handles[k] = w.coord.submit(
                "allreduce", np.asarray(v, np.float32),
                f"metric.{k}.{tag}", op=Op.AVERAGE)
        _obs_exchange(len(handles) + len(metric_handles), xbytes, tag)

        shards = [np.asarray(w.coord.wait(h)) for h in handles]
        shards = [s if wire_origs[bi] is None
                  else s.astype(wire_origs[bi])
                  for bi, s in enumerate(shards)]
        local_finite = True
        if guard_nonfinite:
            # Mirrors the compiled plane: the reduced shard carries every
            # rank's NaN/Inf for the slice THIS rank owns; the verdict
            # for the whole tree is the AND over ranks, which rides the
            # update all-gather below.
            for s in shards:
                if np.issubdtype(s.dtype, np.inexact):
                    local_finite = local_finite and \
                        bool(np.all(np.isfinite(s)))

        upd_shards, new_inner = zero_update_jit(
            state, tuple(jnp.asarray(s) for s in shards))

        flag_bucket = None
        if guard_nonfinite:
            flag_bucket = next(
                (i for i in range(len(plan.buckets))
                 if np.issubdtype(np.dtype(plan.dtypes[plan.buckets[i][0]]),
                                  np.inexact)), None)
        gather_handles = []
        for bi in range(len(plan.buckets)):
            payload = np.asarray(upd_shards[bi])
            if bi == flag_bucket:
                payload = np.concatenate(
                    [payload, np.asarray([1.0 if local_finite else 0.0],
                                         payload.dtype)])
            gather_handles.append(w.coord.submit(
                "allgather", payload, f"zupd.{tag}.{bi}"))

        flats = []
        all_finite = local_finite
        for bi in range(len(plan.buckets)):
            out = np.asarray(w.coord.wait(gather_handles[bi]))
            if bi == flag_bucket:
                s = plan.shard_len(bi)
                blocks = out.reshape(w.size, s + 1)
                all_finite = bool(np.all(
                    blocks[:, -1].astype(np.float64) > 0.5))
                out = blocks[:, :s].reshape(-1)
            flats.append(out[:plan.sizes[bi]])

        if guard_nonfinite and not all_finite:
            # Skip-step: the speculative shard update is discarded (opt
            # state stays the same arrays), the drained metrics keep the
            # protocol balanced, only the step counter advances.
            for h in metric_handles.values():
                w.coord.wait(h)
            metrics = {k: np.zeros((), np.float32) for k in metric_handles}
            metrics["bad_step"] = np.ones((), np.float32)
            return dataclasses.replace(state, step=state.step + 1), metrics

        updates = _unfuse_flat([jnp.asarray(f) for f in flats], plan)
        state = zero_apply_jit(state, new_inner, updates, new_stats)
        metrics = {k: w.coord.wait(h) for k, h in metric_handles.items()}
        if guard_nonfinite:
            metrics["bad_step"] = np.zeros((), np.float32)
        return state, metrics

    return step


def make_eval_step(model, *, mesh: Optional[jax.sharding.Mesh] = None,
                   axis_name: str = AXIS,
                   loss_fn: Callable = cross_entropy_loss):
    """Compiled eval step: globally averaged loss + accuracy (the analog of
    the reference's allreduced final eval,
    ``keras_imagenet_resnet50.py:150``)."""
    mesh = mesh if mesh is not None else runtime.mesh()

    def _eval(state: TrainState, inputs, labels):
        variables = {"params": state.params}
        if state.batch_stats is not None:
            variables["batch_stats"] = state.batch_stats
        logits = model.apply(variables, inputs, train=False)
        return {
            "loss": jax.lax.pmean(loss_fn(logits, labels), axis_name),
            "accuracy": jax.lax.pmean(accuracy(logits, labels), axis_name),
        }

    def _sharded(state, inputs, labels):
        return jax.shard_map(
            _eval, mesh=mesh,
            in_specs=(P(), P(axis_name), P(axis_name)),
            out_specs=P(),
            check_vma=False,
        )(state, inputs, labels)

    jitted = jax.jit(_sharded)

    if _is_env_world(mesh):
        # Independent processes: the in-step pmean is the identity over the
        # 1-device local mesh, so the cross-rank average must ride the host
        # plane — same split as the env-world train step. All metrics are
        # submitted before any is waited (they fuse).
        import numpy as np
        from .ops.collectives import Op
        w = runtime.world()
        counter = {"n": 0}

        def step(state: TrainState, batch):
            inputs, labels = batch
            local = jitted(state, inputs, labels)
            counter["n"] += 1
            tag = counter["n"]
            handles = {k: w.coord.submit(
                "allreduce", np.asarray(v, np.float32),
                f"evalmetric.{k}.{tag}", op=Op.AVERAGE)
                for k, v in local.items()}
            return {k: w.coord.wait(h) for k, h in handles.items()}

        return step

    def step(state: TrainState, batch):
        inputs, labels = batch
        return jitted(state, inputs, labels)

    return step


def shard_batch(batch, mesh: Optional[jax.sharding.Mesh] = None):
    """Place a global host batch onto the world, leading axis split across
    ranks. In env-world mode (independent processes) each process takes its
    own contiguous slice — the multi-process encoding of the same split."""
    return make_batch_placer(mesh)(batch)


def make_batch_placer(mesh: Optional[jax.sharding.Mesh] = None) -> Callable:
    """Build a reusable host-batch placer (the hoisted form of
    :func:`shard_batch`): the mesh lookup, env-world probe and
    ``NamedSharding`` construction happen ONCE, and the returned callable
    just ``device_put``s — so a per-batch loop (eval, prefetch) does no
    re-sharding bookkeeping on the host per batch."""
    mesh = mesh if mesh is not None else runtime.mesh()
    if _is_env_world(mesh):
        w = runtime.world()

        def _slice_batch(batch):
            def _slice(x):
                per = x.shape[0] // w.size
                r = w.controller_rank
                return jax.device_put(x[r * per:(r + 1) * per])
            return jax.tree_util.tree_map(_slice, batch)
        return _slice_batch
    sharding = NamedSharding(mesh, P(AXIS))

    def _place(batch):
        return jax.tree_util.tree_map(
            lambda x: jax.device_put(x, sharding), batch)
    return _place
