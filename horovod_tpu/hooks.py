"""Hook-style training integration — the third integration pattern.

Reference parity: the reference shows three ways to train with Horovod —
raw ``MonitoredTrainingSession`` loops (``examples/tensorflow_mnist.py``),
Keras ``model.fit`` + callbacks (``examples/keras_mnist.py``), and
**Estimator + SessionRunHooks** (``examples/tensorflow_mnist_estimator.py:
145-191``: ``BroadcastGlobalVariablesHook``, ``StopAtStepHook``,
``LoggingTensorHook``, rank-0-only ``model_dir``). This module is the
TPU-native equivalent of the third: a ``SessionRunHook``-shaped protocol, a
``MonitoredTrainingLoop`` that drives the compiled step through hooks, and a
compact ``Estimator`` façade.

The framework's other two patterns live in :class:`horovod_tpu.Trainer`
(fit + callbacks) and plain loops over ``make_train_step``.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence

import numpy as np

from . import runtime
from .training import TrainState, shard_batch


class TrainingHook:
    """SessionRunHook protocol (reference ``tf.train.SessionRunHook``
    lifecycle used by ``BroadcastGlobalVariablesHook``,
    ``horovod/tensorflow/__init__.py:93-124``)."""

    def begin(self, loop: "MonitoredTrainingLoop"): ...

    def after_create_session(self, loop: "MonitoredTrainingLoop"): ...

    def before_run(self, loop: "MonitoredTrainingLoop", step: int): ...

    def after_run(self, loop: "MonitoredTrainingLoop", step: int,
                  metrics: Dict[str, Any]): ...

    def end(self, loop: "MonitoredTrainingLoop"): ...


class BroadcastGlobalVariablesHook(TrainingHook):
    """Broadcast initial state from ``root_rank`` once the loop starts
    (parity: ``hvd.BroadcastGlobalVariablesHook``, built in ``begin()``,
    run in ``after_create_session`` — ``__init__.py:93-124``)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def after_create_session(self, loop):
        from .optimizer import broadcast_global_variables
        if runtime.is_initialized() and runtime.size() > 1:
            loop.state = broadcast_global_variables(
                loop.state, root_rank=self.root_rank)


class StopAtStepHook(TrainingHook):
    """Stop after ``last_step`` global steps (reference
    ``tf.train.StopAtStepHook``, ``tensorflow_mnist_estimator.py:169``)."""

    def __init__(self, last_step: int):
        self.last_step = last_step

    def after_run(self, loop, step, metrics):
        if step + 1 >= self.last_step:
            loop.request_stop()


class LoggingHook(TrainingHook):
    """Print metrics every ``every_n_steps``, rank 0 only (reference
    ``tf.train.LoggingTensorHook``, ``tensorflow_mnist_estimator.py:170-173``;
    rank-0 verbosity convention ``keras_imagenet_resnet50.py:59``)."""

    def __init__(self, every_n_steps: int = 10):
        self.every_n_steps = every_n_steps
        self._t0 = None

    def begin(self, loop):
        self._t0 = time.perf_counter()

    def after_run(self, loop, step, metrics):
        if (step + 1) % self.every_n_steps:
            return
        if runtime.is_initialized() and runtime.world().controller_rank != 0:
            return
        dt = time.perf_counter() - self._t0
        msg = " ".join(f"{k}={float(np.asarray(v)):.4f}"
                       for k, v in metrics.items())
        print(f"step {step + 1} [{dt:.1f}s] {msg}", flush=True)


class CheckpointSaverHook(TrainingHook):
    """Rank-0-only periodic checkpointing (the reference's Estimator writes
    checkpoints only where ``model_dir`` is set, which is rank 0 —
    ``tensorflow_mnist_estimator.py:145-147``, ``README.md:78-80``)."""

    def __init__(self, checkpoint_dir: str, save_steps: int = 100):
        self.checkpoint_dir = checkpoint_dir
        self.save_steps = save_steps

    def after_run(self, loop, step, metrics):
        if (step + 1) % self.save_steps == 0:
            from .trainer import save_checkpoint
            save_checkpoint(self.checkpoint_dir, loop.state)

    def end(self, loop):
        from .trainer import save_checkpoint
        save_checkpoint(self.checkpoint_dir, loop.state)


class MonitoredTrainingLoop:
    """Drive a compiled train step through hooks (the
    ``MonitoredTrainingSession`` analog: hooks observe/steer the loop, the
    loop owns the state)."""

    def __init__(self, train_step: Callable, state: TrainState,
                 hooks: Sequence[TrainingHook] = ()):
        self.train_step = train_step
        self.state = state
        self.hooks: List[TrainingHook] = list(hooks)
        self._stop = False
        self.global_step = 0

    def request_stop(self):
        self._stop = True

    @property
    def should_stop(self) -> bool:
        return self._stop

    def run(self, data: Iterable) -> TrainState:
        """Run until the data iterable ends or a hook requests stop; the
        iterable yields global host batches (sharded here)."""
        for h in self.hooks:
            h.begin(self)
        for h in self.hooks:
            h.after_create_session(self)
        # Check the stop flag BEFORE pulling the next batch: a hook's
        # request_stop in after_run must not cost the input pipeline one
        # extra (discarded) batch — with Estimator.train's repeating stream
        # a trailing `for` check would always over-fetch.
        it = iter(data)
        while not self._stop:
            try:
                batch = next(it)
            except StopIteration:
                break
            step = self.global_step
            for h in self.hooks:
                h.before_run(self, step)
            self.state, metrics = self.train_step(self.state,
                                                  shard_batch(batch))
            for h in self.hooks:
                h.after_run(self, step, metrics)
            self.global_step += 1
        for h in self.hooks:
            h.end(self)
        return self.state


class Estimator:
    """Compact Estimator façade over the hook loop (reference usage shape:
    ``tf.estimator.Estimator(model_fn, model_dir).train(input_fn, steps,
    hooks)``, ``tensorflow_mnist_estimator.py:145-191``).

    ``model_dir`` should be set on rank 0 only (pass ``None`` elsewhere), as
    in the reference; a :class:`BroadcastGlobalVariablesHook` keeps the other
    ranks consistent.
    """

    def __init__(self, model, optimizer, *,
                 model_dir: Optional[str] = None,
                 sample_input, rng=None,
                 loss_fn: Optional[Callable] = None,
                 metrics_fn: Optional[Callable] = None):
        import jax
        from . import training
        self.model = model
        self.model_dir = model_dir
        self._training = training
        kwargs = {}
        if loss_fn is not None:
            kwargs["loss_fn"] = loss_fn
        self.state, self._dist_opt = training.create_train_state(
            model, rng if rng is not None else jax.random.PRNGKey(0),
            sample_input, optimizer)
        self._train_step = training.make_train_step(
            model, self._dist_opt, metrics_fn=metrics_fn, **kwargs)
        self._eval_step = training.make_eval_step(model, **kwargs)

    def train(self, input_fn: Callable[[], Iterable],
              steps: Optional[int] = None,
              hooks: Sequence[TrainingHook] = ()) -> "Estimator":
        hooks = list(hooks)
        if steps is not None:
            hooks.append(StopAtStepHook(steps))
        if self.model_dir is not None:
            hooks.append(CheckpointSaverHook(self.model_dir))
        loop = MonitoredTrainingLoop(self._train_step, self.state, hooks)

        def _stream():
            while True:
                yielded = False
                for b in input_fn():
                    yielded = True
                    yield b
                if steps is None or not yielded:
                    return  # single pass when steps unbounded / empty data

        self.state = loop.run(_stream())
        return self

    def evaluate(self, input_fn: Callable[[], Iterable]) -> Dict[str, float]:
        """Weighted-mean eval over ``input_fn()`` batches, globally averaged
        in-step (the reference's allreduced final eval,
        ``keras_imagenet_resnet50.py:150``)."""
        import jax
        totals: Dict[str, float] = {}
        rows_total = 0
        for batch in input_fn():
            rows = int(np.shape(jax.tree_util.tree_leaves(batch)[0])[0])
            metrics = self._eval_step(self.state, shard_batch(batch))
            for k, v in metrics.items():
                totals[k] = totals.get(k, 0.0) + rows * float(np.asarray(v))
            rows_total += rows
        return {k: v / max(rows_total, 1) for k, v in totals.items()}
