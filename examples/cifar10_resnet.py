"""CIFAR-10 ResNet v1/v2 — parity with ``examples/keras-cifar10-resnet.py``
(reference): selectable depth/version, the staged LR schedule
(keras-cifar10-resnet.py lr_schedule: ×1 → ×1e-1 @80 → ×1e-2 @120 →
×1e-3 @160 → ×0.5e-3 @180), tensor fusion of conv gradients.

    python examples/cifar10_resnet.py --depth 20 --version 1 --epochs 2
"""

import argparse

import jax
import jax.numpy as jnp

import common  # noqa: E402,F401  (sys.path bootstrap)
import horovod_tpu as hvd
from horovod_tpu import callbacks, models, training, trainer as T

from common import load_cifar10, batches


def lr_multiplier(epoch: int) -> float:
    """The reference's staged schedule (keras-cifar10-resnet.py:75-95),
    expressed as a multiplier of the base LR."""
    if epoch >= 180:
        return 0.5e-3
    if epoch >= 160:
        return 1e-3
    if epoch >= 120:
        return 1e-2
    if epoch >= 80:
        return 1e-1
    return 1.0


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--depth", type=int, default=20)
    p.add_argument("--version", type=int, default=1, choices=(1, 2))
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-per-chip", type=int, default=32)
    args = p.parse_args()

    hvd.init()
    (x_train, y_train), (x_test, y_test) = load_cifar10()
    global_batch = args.batch_per_chip * hvd.size()
    steps_per_epoch = len(x_train) // global_batch

    make = (models.cifar_resnet_v1 if args.version == 1
            else models.cifar_resnet_v2)
    model = make(args.depth, dtype=jnp.bfloat16, axis_name=hvd.AXIS)

    opt = callbacks.hyper_sgd(1e-1 * hvd.size(), momentum=0.9)
    state, dist_opt = training.create_train_state(
        model, jax.random.PRNGKey(0), jnp.zeros((2, 32, 32, 3)), opt)
    step = training.make_train_step(model, dist_opt)
    eval_step = training.make_eval_step(model)

    tr = T.Trainer(step, state, eval_step=eval_step,
                   steps_per_epoch=steps_per_epoch)
    tr.fit(
        batches(x_train, y_train, global_batch),
        epochs=args.epochs,
        callbacks=[
            callbacks.BroadcastGlobalVariablesCallback(0),
            callbacks.MetricAverageCallback(),
            callbacks.LearningRateWarmupCallback(
                warmup_epochs=min(5, args.epochs),
                steps_per_epoch=steps_per_epoch),
            callbacks.LearningRateScheduleCallback(
                lr_multiplier, start_epoch=min(5, args.epochs)),
        ],
        eval_data=batches(x_test, y_test, global_batch, shuffle=False),
    )


if __name__ == "__main__":
    main()
