"""Shared example utilities: dataset loading with synthetic fallback.

The reference examples download MNIST/CIFAR via Keras; in a no-egress
environment we load from a local directory when present
(``HVD_DATA_DIR``) and otherwise generate a deterministic synthetic
stand-in with the same shapes — the examples' structure (the part that
demonstrates the framework) is unchanged.
"""

from __future__ import annotations

import os
import sys

# Allow `python examples/<x>.py` from a raw checkout (no install step —
# the reference requires `pip install horovod` first; we don't).
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

import numpy as np


def _synthetic(n, shape, classes, seed):
    rng = np.random.RandomState(seed)
    # A learnable task: labels depend linearly on the input so loss
    # actually decreases (pure noise would plateau instantly).
    x = rng.randn(n, *shape).astype(np.float32)
    w = rng.randn(int(np.prod(shape)), classes).astype(np.float32)
    y = np.argmax(x.reshape(n, -1) @ w, axis=1).astype(np.int32)
    return x, y


def load_mnist(n_train=4096, n_test=512):
    d = os.environ.get("HVD_DATA_DIR")
    if d and os.path.exists(os.path.join(d, "mnist.npz")):
        with np.load(os.path.join(d, "mnist.npz")) as f:
            return ((f["x_train"].reshape(-1, 784).astype(np.float32) / 255.0,
                     f["y_train"].astype(np.int32)),
                    (f["x_test"].reshape(-1, 784).astype(np.float32) / 255.0,
                     f["y_test"].astype(np.int32)))
    return (_synthetic(n_train, (784,), 10, 0),
            _synthetic(n_test, (784,), 10, 1))


def load_cifar10(n_train=4096, n_test=512):
    d = os.environ.get("HVD_DATA_DIR")
    if d and os.path.exists(os.path.join(d, "cifar10.npz")):
        with np.load(os.path.join(d, "cifar10.npz")) as f:
            return ((f["x_train"].astype(np.float32) / 255.0,
                     f["y_train"].astype(np.int32).ravel()),
                    (f["x_test"].astype(np.float32) / 255.0,
                     f["y_test"].astype(np.int32).ravel()))
    return (_synthetic(n_train, (32, 32, 3), 10, 0),
            _synthetic(n_test, (32, 32, 3), 10, 1))


def batches(x, y, global_batch, *, seed=0, shuffle=True):
    """Zero-arg-callable factory over (x, y) host batches of ``global_batch``."""
    def gen():
        idx = np.arange(len(x))
        if shuffle:
            np.random.RandomState(seed).shuffle(idx)
        for i in range(0, len(idx) - global_batch + 1, global_batch):
            sel = idx[i:i + global_batch]
            yield x[sel], y[sel]
    return gen
