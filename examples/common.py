"""Shared example utilities: dataset loading with synthetic fallback.

The reference examples download MNIST/CIFAR via Keras; in a no-egress
environment we load from a local directory when present
(``HVD_DATA_DIR``) and otherwise generate a deterministic synthetic
stand-in with the same shapes — the examples' structure (the part that
demonstrates the framework) is unchanged.
"""

from __future__ import annotations

import os
import sys

# Allow `python examples/<x>.py` from a raw checkout (no install step —
# the reference requires `pip install horovod` first; we don't).
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

import numpy as np

from horovod_tpu.data import _synthetic  # noqa: F401  (imagenet example)
from horovod_tpu.data import load_dataset  # framework-level loader


def load_mnist(n_train=4096, n_test=512):
    train, test, _ = load_dataset("mnist", n_train=n_train, n_test=n_test)
    return train, test


def load_cifar10(n_train=4096, n_test=512):
    train, test, _ = load_dataset("cifar10", n_train=n_train,
                                  n_test=n_test)
    return train, test


def batches(x, y, global_batch, *, seed=0, shuffle=True):
    """Zero-arg-callable factory over (x, y) host batches of ``global_batch``."""
    def gen():
        idx = np.arange(len(x))
        if shuffle:
            np.random.RandomState(seed).shuffle(idx)
        for i in range(0, len(idx) - global_batch + 1, global_batch):
            sel = idx[i:i + global_batch]
            yield x[sel], y[sel]
    return gen
