"""MNIST with a raw training loop — parity with
``examples/tensorflow_mnist.py`` (reference): init → scale LR by size →
wrap optimizer in DistributedOptimizer → broadcast initial state →
rank-0-only checkpointing.

Run single-controller (all local chips form the world):
    python examples/mnist.py
"""

import jax
import jax.numpy as jnp
import numpy as np

import common  # noqa: E402,F401  (sys.path bootstrap)
import horovod_tpu as hvd
from horovod_tpu import models, training, trainer as T
from horovod_tpu.callbacks import hyper_sgd

from common import load_mnist, batches


def main():
    # 1. Initialize the world (tensorflow_mnist.py:69 `hvd.init()`).
    hvd.init()

    (x_train, y_train), (x_test, y_test) = load_mnist()
    global_batch = 64 * hvd.size() // hvd.size() * hvd.size()  # divisible

    model = models.MnistCNN()
    # 2. Scale LR by world size (tensorflow_mnist.py:78 `0.001 * hvd.size()`).
    opt = hyper_sgd(0.05 * hvd.size(), momentum=0.9)
    # 3. DistributedOptimizer: fused gradient allreduce (tensorflow_mnist.py:81).
    state, dist_opt = training.create_train_state(
        model, jax.random.PRNGKey(0), jnp.zeros((2, 784)), opt)
    step = training.make_train_step(model, dist_opt,
                                    metrics_fn=lambda lg, lb: {
                                        "accuracy": training.accuracy(lg, lb)})
    eval_step = training.make_eval_step(model)

    # 4. Broadcast initial state from rank 0 (BroadcastGlobalVariablesHook,
    #    tensorflow_mnist.py:87-90).
    state = hvd.broadcast_parameters(state, root_rank=0)

    tr = T.Trainer(step, state, eval_step=eval_step)
    tr.fit(batches(x_train, y_train, global_batch), epochs=2,
           eval_data=batches(x_test, y_test, global_batch, shuffle=False))

    # 5. Rank-0-only checkpoint (tensorflow_mnist.py:106-108 checkpoint_dir).
    path = T.save_checkpoint("/tmp/hvd_mnist_ckpt", tr.state)
    if path:
        print("checkpoint:", path)


if __name__ == "__main__":
    main()
