"""Skip-gram word2vec — parity with ``examples/tensorflow_word2vec.py``
(reference): embedding gradients travel as IndexedSlices, so their
"allreduce" is the two-allgather sparse path
(``horovod/tensorflow/__init__.py:61-72``). This example uses the raw
shard_map API (not Trainer) to show the lower-level surface.

    python examples/word2vec.py
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import common  # noqa: E402,F401  (sys.path bootstrap)
import horovod_tpu as hvd
from horovod_tpu import models
from horovod_tpu.ops.fusion import fused_allreduce

VOCAB = 5000
DIM = 64
BATCH_PER_CHIP = 128
NEG = 8


def main():
    hvd.init()
    size = hvd.size()
    model = models.SkipGram(vocab_size=VOCAB, embedding_size=DIM)

    rng = np.random.RandomState(0)
    center = jnp.asarray(rng.randint(0, VOCAB, (BATCH_PER_CHIP * size,)))
    context = jnp.asarray(rng.randint(0, VOCAB, (BATCH_PER_CHIP * size,)))
    neg = jnp.asarray(rng.randint(0, VOCAB, (BATCH_PER_CHIP * size, NEG)))

    params = model.init(jax.random.PRNGKey(0), center[:2], context[:2],
                        neg[:2])["params"]
    opt = optax.sgd(0.5)
    opt_state = opt.init(params)

    def train_step(params, opt_state, center, context, neg):
        def loss_fn(p):
            return model.apply({"params": p}, center, context, neg)
        loss, grads = jax.value_and_grad(loss_fn)(params)

        # The embedding gradient is sparse: only the batch's rows are
        # touched. Re-encode it as IndexedSlices (the form TF produces
        # natively) so the sparse two-allgather path is exercised.
        emb_grad = grads["embeddings"]
        touched = jnp.concatenate([center])  # rows hit by the fwd pass
        grads = dict(grads)
        grads["embeddings"] = models.embedding_grads_as_slices(
            emb_grad, touched)

        # Sparse leaves -> allgather(values)+allgather(indices); dense
        # leaves -> fused psum (DistributedOptimizer semantics inline).
        grads = fused_allreduce(grads, average=True)
        grads["embeddings"] = grads["embeddings"].to_dense()

        updates, opt_state2 = opt.update(grads, opt_state, params)
        params2 = optax.apply_updates(params, updates)
        return params2, opt_state2, jax.lax.pmean(loss, hvd.AXIS)

    step = jax.jit(jax.shard_map(
        train_step, mesh=hvd.mesh(),
        in_specs=(P(), P(), P(hvd.AXIS), P(hvd.AXIS), P(hvd.AXIS)),
        out_specs=(P(), P(), P()),
        check_vma=False,
    ))

    for i in range(10):
        params, opt_state, loss = step(params, opt_state, center, context,
                                       neg)
        if hvd.rank() == 0 and i % 2 == 0:
            print(f"step {i} loss {float(loss):.4f}")


if __name__ == "__main__":
    main()
