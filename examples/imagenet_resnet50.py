"""ImageNet ResNet-50 — the north-star workload. Parity with
``examples/keras_imagenet_resnet50.py`` (reference): Goyal et al. recipe
(batch 32/worker, base_lr·size, 5-epoch warmup, ×0.1 decay @ 30/60/80,
weight decay), checkpoint-resume with the epoch broadcast from rank 0
(keras_imagenet_resnet50.py:47-56), rank-0 checkpointing, allreduced
final eval (keras_imagenet_resnet50.py:150).

Without an ImageNet tree on disk this runs on synthetic data — structure
and collectives are identical.

    python examples/imagenet_resnet50.py --epochs 2 --image 64
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np

import common  # noqa: E402,F401  (sys.path bootstrap)
import horovod_tpu as hvd
from horovod_tpu import callbacks, models, training, trainer as T

from common import _synthetic, batches


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--warmup-epochs", type=int, default=1)
    p.add_argument("--batch-per-chip", type=int, default=32)  # ref: 32/worker
    p.add_argument("--base-lr", type=float, default=0.0125)   # ref: 0.0125
    p.add_argument("--wd", type=float, default=5e-5)          # ref: 5e-5
    p.add_argument("--image", type=int, default=64)
    p.add_argument("--classes", type=int, default=100)
    p.add_argument("--ckpt-dir", default="/tmp/hvd_resnet50_ckpt")
    p.add_argument("--compression", choices=["none", "bf16"], default="none",
                   help="gradient compression for the allreduce "
                        "(bf16 halves interconnect bytes at scale)")
    p.add_argument("--accum-steps", type=int, default=1,
                   help="in-step gradient accumulation (microbatches per "
                        "fused allreduce; docs/performance.md)")
    args = p.parse_args()

    hvd.init()
    verbose = hvd.rank() == 0  # rank-0 verbosity (keras_imagenet_resnet50.py:59)

    global_batch = args.batch_per_chip * hvd.size()
    x_train, y_train = _synthetic(
        max(global_batch * 4, 256), (args.image, args.image, 3),
        args.classes, 0)
    steps_per_epoch = len(x_train) // global_batch

    model = models.resnet50(num_classes=args.classes, dtype=jnp.bfloat16,
                            axis_name=hvd.AXIS)
    # lr = base_lr * size (keras_imagenet_resnet50.py:113); SGD momentum 0.9
    # + weight decay 5e-5.
    import optax
    opt = optax.inject_hyperparams(
        lambda learning_rate, momentum: optax.chain(
            optax.add_decayed_weights(args.wd),
            optax.sgd(learning_rate, momentum=momentum)),
    )(learning_rate=args.base_lr * hvd.size(), momentum=0.9)

    state, dist_opt = training.create_train_state(
        model, jax.random.PRNGKey(0),
        jnp.zeros((2, args.image, args.image, 3)), opt,
        compression=(hvd.Compression.bf16 if args.compression == "bf16"
                     else hvd.Compression.none))
    step = training.make_train_step(model, dist_opt,
                                    accum_steps=args.accum_steps)
    eval_step = training.make_eval_step(model)

    # Checkpoint-resume: rank 0 scans for the latest checkpoint and the
    # epoch number is broadcast so every rank resumes in lockstep
    # (keras_imagenet_resnet50.py:47-56).
    resume_step = T.latest_checkpoint_step(args.ckpt_dir) or 0
    resume_step = int(hvd.broadcast(jnp.asarray(resume_step), root_rank=0,
                                    name="resume_epoch"))
    initial_epoch = resume_step // max(steps_per_epoch, 1)
    if resume_step:
        state = T.restore_checkpoint(args.ckpt_dir, state)
        if verbose:
            print(f"resumed from step {resume_step} (epoch {initial_epoch})")

    tr = T.Trainer(step, state, eval_step=eval_step,
                   steps_per_epoch=steps_per_epoch, verbose=verbose)

    # Async checkpointing: the epoch boundary pays only the device→host
    # snapshot; the orbax write overlaps the next epoch's steps
    # (docs/performance.md). The wait() below is the durability barrier.
    ckpt_writer = T.AsyncCheckpointer()

    class CheckpointCallback(callbacks.Callback):
        def on_epoch_end(self, epoch, logs=None):
            T.save_checkpoint(args.ckpt_dir, self.trainer.state,
                              writer=ckpt_writer)  # rank-0 only

    # Staged decay ×0.1 @ 30/60/80 (keras_imagenet_resnet50.py:118-122).
    def decay(epoch):
        if epoch >= 80:
            return 1e-3
        if epoch >= 60:
            return 1e-2
        if epoch >= 30:
            return 1e-1
        return 1.0

    try:
        tr.fit(
            batches(x_train, y_train, global_batch),
            epochs=args.epochs,
            initial_epoch=initial_epoch,
            callbacks=[
                callbacks.BroadcastGlobalVariablesCallback(0),
                callbacks.MetricAverageCallback(),
                callbacks.LearningRateWarmupCallback(
                    warmup_epochs=args.warmup_epochs,
                    steps_per_epoch=steps_per_epoch, verbose=int(verbose)),
                callbacks.LearningRateScheduleCallback(
                    decay, start_epoch=args.warmup_epochs),
                CheckpointCallback(),
            ],
        )
    finally:
        ckpt_writer.close()  # every epoch checkpoint durable before eval/exit

    # Allreduced final eval (keras_imagenet_resnet50.py:150).
    ev = eval_step(tr.state, training.shard_batch(
        (jnp.asarray(x_train[:global_batch]),
         jnp.asarray(y_train[:global_batch]))))
    score = hvd.allreduce(ev["accuracy"], name="final_eval")
    if verbose:
        print("final eval accuracy (allreduced):", float(score))


if __name__ == "__main__":
    main()
