"""MNIST with the full callback stack — parity with
``examples/keras_mnist_advanced.py`` (reference): gradual LR warmup,
metric averaging across ranks, broadcast at train start, rank-0 verbosity.

    python examples/mnist_advanced.py
"""

import jax
import jax.numpy as jnp

import common  # noqa: E402,F401  (sys.path bootstrap)
import horovod_tpu as hvd
from horovod_tpu import callbacks, models, training, trainer as T

from common import load_mnist, batches


def main():
    hvd.init()
    (x_train, y_train), (x_test, y_test) = load_mnist()
    global_batch = 64 * hvd.size()
    epochs = 4
    steps_per_epoch = len(x_train) // global_batch

    model = models.MnistCNN()
    # Scale LR by size; warmup brings it up gradually (keras_mnist_advanced.py
    # lr=1.0*size + LearningRateWarmupCallback).
    opt = callbacks.hyper_sgd(0.05 * hvd.size(), momentum=0.9)
    state, dist_opt = training.create_train_state(
        model, jax.random.PRNGKey(0), jnp.zeros((2, 784)), opt)
    step = training.make_train_step(model, dist_opt)
    eval_step = training.make_eval_step(model)

    tr = T.Trainer(step, state, eval_step=eval_step,
                   steps_per_epoch=steps_per_epoch)
    tr.fit(
        batches(x_train, y_train, global_batch),
        epochs=epochs,
        callbacks=[
            # Broadcast initial state (keras_mnist_advanced.py:73-76).
            callbacks.BroadcastGlobalVariablesCallback(0),
            # Average metrics across ranks (keras_mnist_advanced.py:87-91).
            callbacks.MetricAverageCallback(),
            # Warmup lr/size -> lr over 3 epochs (keras_mnist_advanced.py:93).
            callbacks.LearningRateWarmupCallback(
                warmup_epochs=3, steps_per_epoch=steps_per_epoch, verbose=1),
        ],
        eval_data=batches(x_test, y_test, global_batch, shuffle=False),
    )


if __name__ == "__main__":
    main()
