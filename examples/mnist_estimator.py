"""MNIST with the Estimator/hook integration — parity with
``examples/tensorflow_mnist_estimator.py`` (reference): model built behind
an Estimator, training driven by hooks (broadcast, stop-at-step, logging),
rank-0-only ``model_dir`` checkpointing, allreduced final eval.

Run single-controller (all local chips form the world):
    python examples/mnist_estimator.py
or one process per chip:
    tpurun -np 4 python examples/mnist_estimator.py
"""

import argparse

import jax.numpy as jnp

import common  # noqa: F401  (sys.path bootstrap)
import horovod_tpu as hvd
from horovod_tpu import models, training
from horovod_tpu.callbacks import hyper_sgd
from horovod_tpu.hooks import (BroadcastGlobalVariablesHook, Estimator,
                               LoggingHook)

from common import load_mnist, batches


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=200,
                   help="total optimizer steps across the world")
    args = p.parse_args()

    # 1. Initialize the world (tensorflow_mnist_estimator.py:155).
    hvd.init()

    (x_train, y_train), (x_test, y_test) = load_mnist()
    global_batch = 64 * hvd.size()

    # 2. Rank-0-only model_dir (tensorflow_mnist_estimator.py:145-147:
    #    "save checkpoints only on worker 0 to prevent corruption").
    model_dir = "/tmp/hvd_mnist_estimator" if hvd.rank() == 0 else None

    # 3. LR scaled by world size (tensorflow_mnist_estimator.py:120).
    est = Estimator(
        models.MnistCNN(),
        hyper_sgd(0.05 * hvd.size(), momentum=0.9),
        model_dir=model_dir,
        sample_input=jnp.zeros((2, 784)),
        metrics_fn=lambda lg, lb: {"accuracy": training.accuracy(lg, lb)},
    )

    # 4. Hooks: broadcast initial state from rank 0 + rank-0 logging
    #    (tensorflow_mnist_estimator.py:160-173; StopAtStepHook comes from
    #    steps=).
    est.train(
        batches(x_train, y_train, global_batch),
        steps=max(args.steps // hvd.size(), 1),
        hooks=[BroadcastGlobalVariablesHook(0), LoggingHook(every_n_steps=20)],
    )

    # 5. Globally averaged eval (tensorflow_mnist_estimator.py:186-190).
    metrics = est.evaluate(batches(x_test, y_test, global_batch,
                                   shuffle=False))
    if hvd.rank() == 0:
        print("eval:", {k: round(v, 4) for k, v in metrics.items()})


if __name__ == "__main__":
    main()
