"""CIFAR-10 simple CNN — parity with ``examples/keras-cifar10-cnn.py``
(reference): two conv blocks + dense head, LR scaled by world size.

    python examples/cifar10_cnn.py --epochs 2
"""

import argparse

import flax.linen as nn
import jax
import jax.numpy as jnp

import common  # noqa: E402,F401  (sys.path bootstrap)
import horovod_tpu as hvd
from horovod_tpu import callbacks, training, trainer as T

from common import load_cifar10, batches


class Cifar10CNN(nn.Module):
    """The reference's 4-conv Keras CNN (keras-cifar10-cnn.py:36-59)."""

    num_classes: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        for filters in (32, 32):
            x = nn.Conv(filters, (3, 3), padding="SAME")(x)
            x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        for filters in (64, 64):
            x = nn.Conv(filters, (3, 3), padding="SAME")(x)
            x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Dropout(0.25, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(512)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-per-chip", type=int, default=32)
    args = p.parse_args()

    hvd.init()
    (x_train, y_train), (x_test, y_test) = load_cifar10()
    global_batch = args.batch_per_chip * hvd.size()

    model = Cifar10CNN()
    opt = callbacks.hyper_sgd(0.01 * hvd.size(), momentum=0.9)
    state, dist_opt = training.create_train_state(
        model, jax.random.PRNGKey(0), jnp.zeros((2, 32, 32, 3)), opt)
    step = training.make_train_step(model, dist_opt)
    eval_step = training.make_eval_step(model)

    tr = T.Trainer(step, state, eval_step=eval_step)
    tr.fit(batches(x_train, y_train, global_batch), epochs=args.epochs,
           callbacks=[callbacks.BroadcastGlobalVariablesCallback(0),
                      callbacks.MetricAverageCallback()],
           eval_data=batches(x_test, y_test, global_batch, shuffle=False))


if __name__ == "__main__":
    main()
