"""Long-context transformer LM over a hybrid dp×sp×tp mesh — the net-new
capability layer beyond the reference (SURVEY §5.7: the reference predates
sequence parallelism; this shows ring attention + Megatron sharding + data
parallelism composing on one device mesh, the "How to Scale Your Model"
recipe).

Run single-controller (all local chips form the mesh):
    python examples/transformer_lm.py
    python examples/transformer_lm.py --dp 2 --sp 2 --tp 2   # 8 chips
A synthetic copy task (predict the previous token) verifies learning.
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import common  # noqa: F401  (sys.path bootstrap)
from horovod_tpu.parallel import (TransformerConfig, create_hybrid_mesh,
                                  make_parallel_train_step)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=0,
                   help="data-parallel ways (0 = all devices)")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel ways (ring attention)")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel ways (Megatron column/row)")
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--d-model", type=int, default=128)
    args = p.parse_args()

    n = len(jax.devices())
    dp = args.dp or max(n // (args.sp * args.tp), 1)
    if dp * args.sp * args.tp > n:
        raise SystemExit(f"mesh {dp}x{args.sp}x{args.tp} needs more than "
                         f"{n} devices")

    cfg = TransformerConfig(vocab=256, d_model=args.d_model, n_heads=8,
                            n_layers=2, d_ff=4 * args.d_model,
                            dtype=jnp.bfloat16)
    mesh = create_hybrid_mesh(dp=dp, sp=args.sp, tp=args.tp)
    print(f"mesh: dp={dp} sp={args.sp} tp={args.tp} "
          f"({dp * args.sp * args.tp}/{n} devices), seq={args.seq}")

    init_state, step = make_parallel_train_step(cfg, mesh, optax.adam(3e-3))
    params, opt_state = init_state(jax.random.PRNGKey(0))

    # Synthetic task: predict the PREVIOUS token (causal attention can
    # solve it exactly; random labels could not be learned).
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab,
                                     (args.batch, args.seq)), jnp.int32)
    labels = jnp.roll(tokens, 1, axis=1)

    losses = []
    for i in range(args.steps):
        params, opt_state, loss = step(params, opt_state, tokens, labels)
        losses.append(float(loss))
        if i % 10 == 0 or i == args.steps - 1:
            print(f"step {i:4d} loss {losses[-1]:.4f}", flush=True)
    assert losses[-1] < losses[0], (losses[0], losses[-1])
    print(f"OK: loss {losses[0]:.3f} -> {losses[-1]:.3f}")


if __name__ == "__main__":
    main()
