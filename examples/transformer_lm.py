"""Long-context transformer LM over a hybrid mesh — the net-new capability
layer beyond the reference (SURVEY §5.7: the reference predates sequence
parallelism; this shows ring attention + Megatron sharding + data/pipeline
parallelism composing on one device mesh, the "How to Scale Your Model"
recipe).

Run single-controller (all local chips form the mesh):
    python examples/transformer_lm.py
    python examples/transformer_lm.py --dp 2 --sp 2 --tp 2   # 8 chips
    python examples/transformer_lm.py --dp 2 --pp 2 --tp 2   # pipelined
A synthetic copy task (predict the previous token) verifies learning.
``--pp`` selects the pipelined family (1F1B schedule,
``parallel/pp_transformer.py``); it composes with dp and tp but not sp/ep.
"""

import argparse

import jax
import jax.numpy as jnp
import numpy as np
import optax

import common  # noqa: F401  (sys.path bootstrap)
from horovod_tpu.parallel import (TransformerConfig, create_hybrid_mesh,
                                  make_parallel_train_step,
                                  make_pp_transformer_train_step)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=0,
                   help="data-parallel ways (0 = all devices)")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel ways (ring attention)")
    p.add_argument("--tp", type=int, default=1,
                   help="tensor-parallel ways (Megatron column/row)")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel stages (1F1B schedule; "
                        "composes with dp/tp, not sp)")
    p.add_argument("--microbatches", type=int, default=4,
                   help="pipeline microbatches per step (--pp > 1)")
    p.add_argument("--seq", type=int, default=128)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--d-model", type=int, default=128)
    p.add_argument("--checkpoint-dir", default=None,
                   help="save the SHARDED (params, opt_state) trees here "
                        "every --checkpoint-every steps "
                        "(parallel/checkpoint.py: each array written with "
                        "its NamedSharding layout)")
    p.add_argument("--checkpoint-every", type=int, default=10)
    p.add_argument("--resume", action="store_true",
                   help="resume from the newest checkpoint in "
                        "--checkpoint-dir (restores onto the mesh layout "
                        "and continues at the saved step)")
    args = p.parse_args()

    n = len(jax.devices())
    if args.pp > 1 and args.sp > 1:
        raise SystemExit("--pp composes with dp/tp, not sp")
    if args.checkpoint_dir and args.pp > 1:
        # Reject loudly rather than complete a long run with zero
        # checkpoints written: sharded save/restore covers the non-pp
        # family for now (parallel/checkpoint.py).
        raise SystemExit("--checkpoint-dir covers the non-pp family for "
                         "now (the 1F1B state is not yet wired through "
                         "save_sharded)")
    dp = args.dp or max(n // (args.sp * args.tp * args.pp), 1)
    if dp * args.sp * args.tp * args.pp > n:
        raise SystemExit(
            f"mesh {dp}x{args.sp}x{args.tp}x{args.pp} needs more than "
            f"{n} devices")

    cfg = TransformerConfig(vocab=256, d_model=args.d_model, n_heads=8,
                            n_layers=2 * max(args.pp, 1),
                            d_ff=4 * args.d_model,
                            dtype=jnp.bfloat16)
    kw = dict(dp=dp, sp=args.sp, tp=args.tp, pp=args.pp)
    mesh = create_hybrid_mesh(**kw)
    print(f"mesh: dp={dp} sp={args.sp} tp={args.tp} pp={args.pp} "
          f"({dp * args.sp * args.tp * args.pp}/{n} devices), "
          f"seq={args.seq}")

    if args.pp > 1:
        init_state, step = make_pp_transformer_train_step(
            cfg, mesh, optax.adam(3e-3),
            n_microbatches=args.microbatches)
    else:
        init_state, step = make_parallel_train_step(cfg, mesh,
                                                    optax.adam(3e-3))
    params, opt_state = init_state(jax.random.PRNGKey(0))

    # Synthetic task: predict the PREVIOUS token (causal attention can
    # solve it exactly; random labels could not be learned).
    rng = np.random.RandomState(0)
    tokens = jnp.asarray(rng.randint(0, cfg.vocab,
                                     (args.batch, args.seq)), jnp.int32)
    labels = jnp.roll(tokens, 1, axis=1)

    start = 0
    if args.resume:
        if args.pp > 1:
            raise SystemExit("--resume covers the non-pp family for now")
        if not args.checkpoint_dir:
            raise SystemExit("--resume requires --checkpoint-dir")
        from horovod_tpu.parallel import restore_sharded
        params, opt_state, start = restore_sharded(
            args.checkpoint_dir, params, opt_state)
        print(f"resumed from step {start}")
        if start >= args.steps:
            print(f"nothing to do: checkpoint step {start} >= "
                  f"--steps {args.steps}")
            return

    losses = []
    for i in range(start, args.steps):
        params, opt_state, loss = step(params, opt_state, tokens, labels)
        losses.append(float(loss))
        if i % 10 == 0 or i == args.steps - 1:
            print(f"step {i:4d} loss {losses[-1]:.4f}", flush=True)
        if (args.checkpoint_dir and args.pp == 1
                and (i + 1) % args.checkpoint_every == 0):
            from horovod_tpu.parallel import save_sharded
            save_sharded(args.checkpoint_dir, i + 1, params, opt_state,
                         max_to_keep=3)
    assert losses[-1] < losses[0], (losses[0], losses[-1])
    print(f"OK: loss {losses[0]:.3f} -> {losses[-1]:.3f}")


if __name__ == "__main__":
    main()
