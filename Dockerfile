# TPU-VM image for horovod_tpu (parity: the reference ships CUDA+NCCL+OpenMPI
# Dockerfiles; the TPU analog needs only the jax TPU wheel — no MPI, no sshd
# fan-out, the launcher is in-repo).
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
        build-essential make \
    && rm -rf /var/lib/apt/lists/*

# jax[tpu] resolves libtpu on TPU VMs; CPU fallback works everywhere else.
RUN pip install --no-cache-dir "jax[tpu]" -f \
        https://storage.googleapis.com/jax-releases/libtpu_releases.html \
    && pip install --no-cache-dir flax optax orbax-checkpoint chex pytest

WORKDIR /opt/horovod_tpu
COPY . .
RUN make -C horovod_tpu/coord && pip install --no-cache-dir -e .

# Sanity: the suite runs CPU-only inside the container (reference CI shape).
# RUN python -m pytest tests/ -q

ENTRYPOINT ["/bin/bash"]
