#!/usr/bin/env bash
# CI pipeline (parity: reference .travis.yml — build the native core, run the
# collective test suite under a multi-"rank" world, then shrunken examples
# end-to-end, .travis.yml:77-108).
set -euo pipefail
cd "$(dirname "$0")"

echo "== build native coordination core =="
make -C horovod_tpu/coord

echo "== native core threaded selftest (plain + ThreadSanitizer) =="
make -C horovod_tpu/coord selftest tsan

echo "== unit + multi-process test suite (8-device virtual CPU mesh) =="
# -m 'not slow' mirrors the tier-1 gate: the slow-marked AOT TPU
# cross-compile evidence test takes ~8 min on a CPU host (run
# tests/test_overlap.py directly for it), and the multi-node world-4
# launcher drill is ~70 s of subprocess spawns the np=3 test already
# covers (run tests/test_launcher.py directly). --durations=15 keeps the
# tier-1 wall-budget regression surface visible: the suite must stay
# well under its 870 s cap, so the slowest tests are named on every run.
python -m pytest tests/ -q -m 'not slow' --durations=15

echo "== compat leg: pre-export all_gather_invariant resolution =="
# The version-matrix stand-in for this single-jax image (README "Version
# matrix"): force the private-symbol fallback utils/compat.py keeps for
# older jax and re-run the collective sweeps that depend on it.
HVD_COMPAT_LEVEL=private python -m pytest tests/test_collectives.py -q

echo "== shrunken examples end-to-end (integration tests) =="
run_cpu() {
  PYTHONPATH= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 "$@"
}
run_cpu python examples/mnist.py
run_cpu python examples/mnist_estimator.py --steps 32
run_cpu python examples/mnist_advanced.py
run_cpu python examples/cifar10_cnn.py --epochs 1
run_cpu python examples/word2vec.py
run_cpu python examples/transformer_lm.py --dp 2 --sp 2 --tp 2 --steps 12 --seq 64
run_cpu python examples/transformer_lm.py --dp 2 --pp 2 --tp 2 --steps 12 --seq 64
run_cpu python examples/imagenet_resnet50.py --epochs 1 --image 32 --batch-per-chip 4 \
  --ckpt-dir "$(mktemp -d)"

echo "== serving smoke: warm the buckets, 200 QPS for 5 s, assert the drop gate =="
# The serving plane's CI contract (docs/inference.md): the engine must
# pre-compile every bucket, sustain the target rate with mixed batch
# sizes, drop ZERO in-deadline requests, and produce a non-empty p50/p99
# report — serve_bench exits nonzero on any violation.
run_cpu timeout -k 10 180 python bin/serve_bench.py --qps 200 --duration 5

echo "== serving smoke: continuous-batching generation (TTFT + tokens/sec gate) =="
# The generation plane's CI contract (docs/inference.md "Generation"):
# prefill/decode buckets pre-compile, open-loop prompt arrivals sustain
# the rate with slots joining/leaving mid-flight, ZERO in-deadline drops,
# nonzero aggregate tokens/sec, and a non-empty p50/p99 TTFT report —
# serve_bench exits nonzero on any violation.
run_cpu timeout -k 10 240 python bin/serve_bench.py --mode generate \
  --qps 20 --duration 5 --deadline-ms 5000
# The slow-marked HTTP /generate drills (chunked streaming, healthz
# lifecycle) run here, outside the tier-1 marker filter.
timeout -k 10 300 python -m pytest tests/test_generate.py -q

echo "== serving smoke: paged KV cache (same gate, block-table layout) =="
# Identical qps/duration/gates as the contiguous generation smoke — the
# paged engine must clear the same bar (docs/inference.md "Paged KV
# cache").
run_cpu timeout -k 10 240 python bin/serve_bench.py --mode generate \
  --qps 20 --duration 5 --deadline-ms 5000 --kv-layout paged --block-size 16

echo "== paged capacity: more concurrent streams at EQUAL cache bytes =="
# The ROADMAP item-2 success metric: at a FIXED KV-cache byte budget
# (--cache-mb sizes both layouts from the same budget), a burst of short
# prompts must reach strictly higher peak concurrency on the paged
# engine than on the contiguous one (whose slot count the worst-case
# max_len reservation caps).
rm -f /tmp/hvd_cap_contig.json /tmp/hvd_cap_paged.json
run_cpu timeout -k 10 240 python bin/serve_bench.py --mode generate \
  --qps 400 --duration 1 --deadline-ms 0 --cache-mb 0.5 --max-len 128 \
  --kv-layout contiguous --json /tmp/hvd_cap_contig.json
run_cpu timeout -k 10 240 python bin/serve_bench.py --mode generate \
  --qps 400 --duration 1 --deadline-ms 0 --cache-mb 0.5 --max-len 128 \
  --kv-layout paged --block-size 16 --json /tmp/hvd_cap_paged.json
python - <<'PYEOF'
import json
c = json.loads(open("/tmp/hvd_cap_contig.json").read().splitlines()[-1])
p = json.loads(open("/tmp/hvd_cap_paged.json").read().splitlines()[-1])
assert c["cache_bytes"] == p["cache_bytes"], (c["cache_bytes"],
                                              p["cache_bytes"])
print(f"capacity @ {c['cache_bytes']} cache bytes: contiguous peak "
      f"{c['peak_concurrent_streams']} (slots {c['max_slots']}), paged "
      f"peak {p['peak_concurrent_streams']} (slots {p['max_slots']})")
assert p["peak_concurrent_streams"] > c["peak_concurrent_streams"], \
    "paged engine must sustain MORE concurrent streams at equal cache bytes"
print("PAGED CAPACITY OK")
PYEOF

echo "== prefix reuse: nonzero hits, bit-identical streams vs no-reuse =="
# Same seeded prompt mix (16-token shared system prefix) with reuse on
# vs off: the reuse run must actually HIT the prefix cache, and the
# completion-order-free digest of every greedy stream must be identical
# — sharing saves memory, never changes a token.
rm -f /tmp/hvd_px_on.json /tmp/hvd_px_off.json
run_cpu timeout -k 10 240 python bin/serve_bench.py --mode generate \
  --qps 20 --duration 5 --deadline-ms 0 --kv-layout paged --block-size 16 \
  --prefix-tokens 16 --prefix-reuse --json /tmp/hvd_px_on.json
run_cpu timeout -k 10 240 python bin/serve_bench.py --mode generate \
  --qps 20 --duration 5 --deadline-ms 0 --kv-layout paged --block-size 16 \
  --prefix-tokens 16 --json /tmp/hvd_px_off.json
python - <<'PYEOF'
import json
on = json.loads(open("/tmp/hvd_px_on.json").read().splitlines()[-1])
off = json.loads(open("/tmp/hvd_px_off.json").read().splitlines()[-1])
assert on["completed"] == on["sent"] and off["completed"] == off["sent"]
assert on["prefix_hits_total"] > 0, "prefix cache never hit"
assert off["prefix_hits_total"] == 0
assert on["stream_digest"] == off["stream_digest"], \
    "prefix sharing changed a token stream"
print(f"prefix reuse: {on['prefix_hits_total']} hits, digests identical")
print("PREFIX REUSE OK")
PYEOF

echo "== KV hierarchy: one digest across cold / hit / host-tier / disaggregated legs =="
# ISSUE 18 acceptance, bit-identity at every tier: ONE seeded workload
# (3 rotating 96-token system prefixes, 60% shared traffic) replayed
# against four shapes of the SAME chunked-prefill program — ample pool
# (high hit rate, suffix-sized prefills), starved pool (chains
# reclaimed every admission -> every arrival cold), tight pool + host
# tier (chains survive by offload/prefetch roundtrip), and a 2-process
# disaggregated fleet with prefix-affine dispatch. Every leg completes
# everything; every leg emits the IDENTICAL stream digest.
KVH="--mode generate --qps 20 --duration 5 --deadline-ms 0"
KVH="$KVH --kv-layout paged --block-size 16 --prefix-tokens 96"
KVH="$KVH --prefix-count 3 --gen-tokens 16 --prefix-reuse"
KVH="$KVH --chunked-prefill --prefix-mix 0.6"
rm -f /tmp/hvd_kvh_hit.json /tmp/hvd_kvh_cold.json \
      /tmp/hvd_kvh_tier.json /tmp/hvd_kvh_fleet.json
run_cpu timeout -k 10 240 python bin/serve_bench.py $KVH \
  --json /tmp/hvd_kvh_hit.json
run_cpu timeout -k 10 240 python bin/serve_bench.py $KVH \
  --n-blocks 12 --json /tmp/hvd_kvh_cold.json
run_cpu timeout -k 10 240 python bin/serve_bench.py $KVH \
  --n-blocks 20 --host-blocks 64 --json /tmp/hvd_kvh_tier.json
run_cpu timeout -k 10 300 python bin/serve_bench.py $KVH \
  --replicas 2 --replica-procs --json /tmp/hvd_kvh_fleet.json
python - <<'PYEOF'
import json

def rows(path):
    return [json.loads(l) for l in open(path).read().splitlines()]

hit = rows("/tmp/hvd_kvh_hit.json")[-1]
cold = rows("/tmp/hvd_kvh_cold.json")[-1]
tier = rows("/tmp/hvd_kvh_tier.json")[-1]
frows = rows("/tmp/hvd_kvh_fleet.json")
fpt = [r for r in frows if "stream_digest" in r][-1]
fleet = [r for r in frows if r.get("fleet") is True][-1]
for leg in (hit, cold, tier, fpt):
    assert leg["completed"] == leg["sent"], (leg["completed"], leg["sent"])
# The tentpole in one line: four legs, four hit depths and tiers, ONE
# digest — a prefix hit, a host roundtrip, or a remote replica may
# change WHERE tokens come from, never which tokens.
digests = {d["stream_digest"] for d in (hit, cold, tier, fpt)}
assert len(digests) == 1, digests
# Hit leg really skips prefix-hit compute (suffix-sized programs).
assert hit["prefix_hit_rate"] > 0.5, hit["prefix_hit_rate"]
assert hit["prefill_chunks_skipped_total"] > 0
assert hit["ttft_hit_p50_ms"] is not None
assert hit["ttft_cold_p50_ms"] is not None
# Cold leg: the starved pool reclaims every chain, so nothing hits and
# every chunk is computed — strictly more prefill work, same digest.
assert cold["prefix_hit_rate"] < 0.2, cold["prefix_hit_rate"]
assert cold["prefill_chunks_skipped_total"] == 0, cold
assert cold["prefill_chunks_total"] > hit["prefill_chunks_total"]
# Tier leg: blocks actually moved host-ward AND back, books balanced.
assert tier["kv_offload_blocks_total"] > 0, tier
assert tier["kv_prefetch_blocks_total"] > 0, tier
assert tier["prefix_hit_rate"] > 0.5, tier["prefix_hit_rate"]
b = tier["blocks"]
assert b["free"] + b["used"] == b["total"], b
assert b["host_used"] + b["host_free"] == b["host_total"], b
# Disaggregated leg: the router sorted prefix-holding replicas first.
pd = fleet.get("prefix_dispatch") or {}
assert pd.get("affine", 0) > 0, fleet
print(f"hit leg: hit_rate {hit['prefix_hit_rate']:.2f}, "
      f"{hit['prefill_chunks_skipped_total']} chunks skipped, ttft "
      f"hit/cold p50 {hit['ttft_hit_p50_ms']:.2f}/"
      f"{hit['ttft_cold_p50_ms']:.2f} ms")
print(f"cold leg: {cold['prefill_chunks_total']} chunks computed "
      f"(hit leg {hit['prefill_chunks_total']})")
print(f"tier leg: offload {tier['kv_offload_blocks_total']} / prefetch "
      f"{tier['kv_prefetch_blocks_total']} blocks, hit_rate "
      f"{tier['prefix_hit_rate']:.2f}")
print(f"fleet leg: prefix_dispatch {pd}")
print("KV HIERARCHY DIGESTS OK")
PYEOF

echo "== KV hierarchy: host tier raises effective capacity under chain thrash =="
# ISSUE 18 acceptance, capacity: two 96-token prefix chains rotate
# through a device pool that holds only ONE (11 usable blocks), with
# prefill-bound traffic at d_model 512 — the regime the hierarchy is
# built for, where chunk compute dominates block copies — and a tiny
# admission queue. Device-only: each admission reclaims (DESTROYS) the
# other chain, nearly every arrival prefills cold holding a private
# full-length chain, the queue backs up, blocks_exhausted rejections
# pile up. Host-tiered: the same pressure OFFLOADS the chain, the next
# arrival prefetches it back and hits — strictly fewer rejections and
# more completions from the very same device pool.
KVC="--mode generate --qps 80 --duration 5 --deadline-ms 0"
KVC="$KVC --kv-layout paged --block-size 16 --slots 4 --n-blocks 12"
KVC="$KVC --max-queue 8 --model-dim 512 --prefix-tokens 96"
KVC="$KVC --prefix-count 2 --gen-tokens 1 --prefix-reuse"
KVC="$KVC --chunked-prefill --prefix-mix 1.0"
rm -f /tmp/hvd_kvc_tier.json /tmp/hvd_kvc_dev.json
# Both legs overload by design (rejections are the measurement), and
# serve_bench exits nonzero on drops — the verdict lives in the
# assertions below, not the exit codes.
run_cpu timeout -k 10 240 python bin/serve_bench.py $KVC \
  --host-blocks 16 --json /tmp/hvd_kvc_tier.json || true
run_cpu timeout -k 10 240 python bin/serve_bench.py $KVC \
  --json /tmp/hvd_kvc_dev.json || true
python - <<'PYEOF'
import json
tier = json.loads(open("/tmp/hvd_kvc_tier.json").read().splitlines()[-1])
dev = json.loads(open("/tmp/hvd_kvc_dev.json").read().splitlines()[-1])
# The device-only run must actually be block-starved for the
# comparison to mean anything.
assert dev["rejected_blocks_exhausted"] > 0, dev
assert tier["rejected_blocks_exhausted"] < dev["rejected_blocks_exhausted"], (
    tier["rejected_blocks_exhausted"], dev["rejected_blocks_exhausted"])
assert tier["completed"] > dev["completed"], (
    tier["completed"], dev["completed"])
# The mechanism, not just the outcome: the tier leg preserved its
# chains (hits) where the device-only leg destroyed them (misses)...
assert tier["prefix_hit_rate"] > 0.8, tier["prefix_hit_rate"]
assert dev["prefix_hit_rate"] < 0.5, dev["prefix_hit_rate"]
# ...by round-tripping blocks through the host tier, books balanced.
assert tier["kv_offload_blocks_total"] > 0, tier
assert tier["kv_prefetch_blocks_total"] > 0, tier
for leg in (tier, dev):
    b = leg["blocks"]
    assert b["free"] + b["used"] == b["total"], b
    assert b["host_used"] + b["host_free"] == b["host_total"], b
print(f"device-only: {dev['completed']}/{dev['sent']} completed, "
      f"{dev['rejected_blocks_exhausted']} blocks_exhausted, hit_rate "
      f"{dev['prefix_hit_rate']:.2f}")
print(f"host-tiered: {tier['completed']}/{tier['sent']} completed, "
      f"{tier['rejected_blocks_exhausted']} blocks_exhausted, hit_rate "
      f"{tier['prefix_hit_rate']:.2f}, offload "
      f"{tier['kv_offload_blocks_total']} / prefetch "
      f"{tier['kv_prefetch_blocks_total']}")
print("KV HIERARCHY CAPACITY OK")
PYEOF

echo "== KV hierarchy: new tests stay inside the tier-1 wall budget =="
# The edge-geometry suite rides tier-1 (~430 s of headroom under the
# 870 s cap today); this guard fails the PR that lets it creep toward
# three-digit seconds, and --durations names the offenders.
run_cpu timeout -k 10 120 python -m pytest tests/test_kv_hierarchy.py \
  -q --durations=8 -p no:cacheprovider

echo "== serving fleet: closed-loop autoscaler drill (spike -> grow -> drain -> shrink) =="
# ISSUE 13 acceptance: a traffic spike one replica cannot absorb must
# (a) fire >= 1 grow scale-event and recover queue depth to 0, then
# once traffic stops (b) drain the extra replicas losing ZERO admitted
# streams and shrink back to min replicas — and the fleet's
# completion-order-free stream digest must be IDENTICAL to a
# single-replica run of the same seeded traffic (drain/dispatch may
# move streams between replicas, never change a token).
rm -f /tmp/hvd_fleet_ref.json /tmp/hvd_fleet_auto.json
run_cpu timeout -k 10 300 python bin/serve_bench.py --mode generate \
  --qps 150 --duration 6 --deadline-ms 0 --slots 1 --gen-tokens 32 \
  --max-queue 2000 --json /tmp/hvd_fleet_ref.json
run_cpu timeout -k 10 300 python bin/serve_bench.py --mode generate \
  --qps 150 --duration 6 --deadline-ms 0 --slots 1 --gen-tokens 32 \
  --max-queue 2000 --replicas 3 --autoscale --json /tmp/hvd_fleet_auto.json
python - <<'PYEOF'
import json
auto_lines = [json.loads(l) for l in open("/tmp/hvd_fleet_auto.json")]
row = [l for l in auto_lines if "stream_digest" in l][-1]
fleet = [l for l in auto_lines if l.get("fleet")][-1]
ref = [json.loads(l) for l in open("/tmp/hvd_fleet_ref.json")
       if "stream_digest" in l][-1]
assert row["completed"] == row["sent"], (row["completed"], row["sent"])
assert row["overload_drops"] == 0 and row["failed"] == 0, row
assert fleet["scale_events"]["grow"] >= 1, \
    f"spike never grew the fleet: {fleet['scale_events']}"
assert fleet["queue_depth_final"] == 0, \
    f"queue depth never recovered: {fleet['queue_depth_final']}"
assert fleet["ready_final"] == fleet["min_replicas"] == 1, \
    f"fleet did not shrink back to min: {fleet}"
assert fleet["drained_lost_streams"] == 0, fleet
assert row["stream_digest"] == ref["stream_digest"], \
    "fleet dispatch/drain changed a token stream"
print(f"autoscaler closed loop OK: grow x{fleet['scale_events']['grow']}"
      f" -> depth 0 -> shrink x{fleet['scale_events']['shrink']} to "
      f"{fleet['ready_final']} replica(s), {row['completed']} streams, "
      f"0 lost, digest == single-replica run")
print("FLEET AUTOSCALER OK")
PYEOF

echo "== multi-tenant adapters: per-tenant digest drill (2 LoRA tenants + base, ONE engine) =="
# ISSUE 14 acceptance: 2 adapters + base traffic through one engine —
# a mixed-adapter decode batch is ONE compiled program and every
# tenant's stream must be bit-identical to a single-tenant reference
# run of the same seeded schedule (--adapter-only replays the schedule
# submitting only that tenant). Digests are completion-order-free, so
# batch composition can differ arbitrarily; tokens may not.
rm -f /tmp/hvd_mt_mix.json /tmp/hvd_mt_base.json /tmp/hvd_mt_a0.json /tmp/hvd_mt_a1.json
run_cpu timeout -k 10 240 python bin/serve_bench.py --mode generate \
  --qps 20 --duration 5 --deadline-ms 0 --adapters 2 --json /tmp/hvd_mt_mix.json
for t in base a0 a1; do
  run_cpu timeout -k 10 240 python bin/serve_bench.py --mode generate \
    --qps 20 --duration 5 --deadline-ms 0 --adapters 2 --adapter-only $t \
    --json /tmp/hvd_mt_$t.json
done
python - <<'PYEOF'
import json
mix = [json.loads(l) for l in open("/tmp/hvd_mt_mix.json")][-1]
assert mix["completed"] == mix["sent"] and mix["failed"] == 0, mix
assert mix["adapters_resident"] == 2, mix.get("adapters_resident")
for t in ("base", "a0", "a1"):
    solo = [json.loads(l) for l in open(f"/tmp/hvd_mt_{t}.json")][-1]
    assert solo["completed"] == solo["sent"] and solo["failed"] == 0, solo
    assert solo["tenant_sent"][t] == mix["tenant_sent"][t], \
        f"{t}: schedule replay drifted ({solo['tenant_sent']} vs {mix['tenant_sent']})"
    assert mix["stream_digests"][t] == solo["stream_digests"][t], \
        f"tenant {t}: mixed-batch stream differs from its single-tenant run"
# per-tenant latency split must be populated for every tenant
for t in ("base", "a0", "a1"):
    assert mix["tenants"][t]["generations_total"] == mix["tenant_completed"][t], mix["tenants"]
print("multi-tenant digests OK: base/a0/a1 each bit-identical mixed vs solo "
      f"({mix['completed']} streams mixed)")
PYEOF

echo "== serving chaos drill: replica_kill mid-stream -> deterministic stream failover =="
# ISSUE 15 acceptance: a replica killed mid-stream strands ZERO client
# streams — the router re-dispatches every stranded stream to a
# surviving replica and replays it with the already-emitted prefix
# suppressed, so every client-visible stream is bit-identical to an
# unkilled single-replica run of the same seeded traffic. Pinned for
# greedy adapter-bearing traffic (failover re-retains the LoRA row on
# the destination replica) AND seeded-sampling traffic; the killed
# replica leaves a flight-recorder post-mortem naming its in-flight
# streams. slots=2/gen-tokens=32 keeps streams long enough that the
# least-load dispatch actually spreads traffic onto r1 before the kill.
rm -f /tmp/hvd_fo_aref.json /tmp/hvd_fo_akill.json \
      /tmp/hvd_fo_sref.json /tmp/hvd_fo_skill.json
FR_SERVE="$(mktemp -d)"
export FR_SERVE
run_cpu timeout -k 10 300 python bin/serve_bench.py --mode generate \
  --qps 60 --duration 3 --deadline-ms 0 --slots 2 --gen-tokens 32 \
  --adapters 1 --adapter-mix 0,1 --json /tmp/hvd_fo_aref.json
HVD_FLIGHTREC_DIR="$FR_SERVE" \
run_cpu timeout -k 10 300 python bin/serve_bench.py --mode generate \
  --qps 60 --duration 3 --deadline-ms 0 --slots 2 --gen-tokens 32 \
  --adapters 1 --adapter-mix 0,1 --replicas 2 \
  --chaos 'replica_kill=r1@stream=3' --json /tmp/hvd_fo_akill.json
run_cpu timeout -k 10 300 python bin/serve_bench.py --mode generate \
  --qps 60 --duration 3 --deadline-ms 0 --slots 2 --gen-tokens 32 \
  --temperature 0.7 --json /tmp/hvd_fo_sref.json
HVD_FLIGHTREC_DIR="$FR_SERVE" \
run_cpu timeout -k 10 300 python bin/serve_bench.py --mode generate \
  --qps 60 --duration 3 --deadline-ms 0 --slots 2 --gen-tokens 32 \
  --temperature 0.7 --replicas 2 \
  --chaos 'replica_kill=r1@stream=3' --json /tmp/hvd_fo_skill.json
python - <<'PYEOF'
import glob, json, os
def rows(path):
    return [json.loads(l) for l in open(path)]
for ref_p, kill_p, label in (
        ("/tmp/hvd_fo_aref.json", "/tmp/hvd_fo_akill.json",
         "greedy+adapter"),
        ("/tmp/hvd_fo_sref.json", "/tmp/hvd_fo_skill.json", "seeded")):
    ref = [r for r in rows(ref_p) if "stream_digest" in r][-1]
    kill_rows = rows(kill_p)
    row = [r for r in kill_rows if "stream_digest" in r][-1]
    fleet = [r for r in kill_rows if r.get("fleet")][-1]
    assert row["completed"] == row["sent"] and row["failed"] == 0, \
        (label, row["completed"], row["sent"], row["failed"])
    assert row["overload_drops"] == 0 and row["deadline_drops"] == 0, \
        (label, row)
    assert fleet["failover"]["resumed"] >= 1, (label, fleet["failover"])
    assert fleet["failover"]["exhausted"] == 0, (label, fleet["failover"])
    assert fleet["stranded"] >= 1, (label, fleet)
    assert fleet["drained_lost_streams"] == 0, (label, fleet)
    # The kill actually landed on r1 (its dispatch history folded into
    # the bounded "retired" series on eviction).
    assert fleet["dispatch"].get("retired", 0) >= 1, (label, fleet)
    assert row["stream_digests"] == ref["stream_digests"], \
        f"{label}: failover changed a client-visible token stream"
    print(f"{label}: {fleet['stranded']} stranded -> "
          f"{fleet['failover']['resumed']} resumed, 0 exhausted, "
          f"digests identical to unkilled single-replica run")
dumps = glob.glob(os.environ["FR_SERVE"] + "/hvd_flightrec.rank*.json")
assert dumps, "killed replica left no flight-recorder post-mortem"
body = open(dumps[0]).read()
assert "serve_crash" in body and "replica_kill" in body, \
    f"post-mortem names neither the crash nor the drill: {body[:200]}"
print("post-mortem OK: dead replica dumped its in-flight streams")
print("SERVING FAILOVER OK")
PYEOF

echo "== out-of-process replicas: SIGKILL a subprocess replica mid-stream -> cross-process failover =="
# ISSUE 16 acceptance: the fault-tolerance plane crossed a real process
# boundary. A 3-replica SUBPROCESS fleet (each member a `python -m
# horovod_tpu.serve.proc_replica` worker behind a ProcReplicaClient)
# takes the same seeded traffic as a thread fleet; the chaos clause
# SIGKILLs r1's worker process mid-stream (a dead pid, not a flipped
# flag). Pinned: zero lost streams, >=1 failover resume, and every
# client-visible stream digest IDENTICAL to the unkilled THREAD-fleet
# reference — bit-identity across both topologies and a real SIGKILL.
# The dead child leaves its serve_crash post-mortem in its PER-REPLICA
# dump dir ($FR_PROC/r1), written before the SIGKILL lands.
rm -f /tmp/hvd_proc_tref.json /tmp/hvd_proc_kill.json
FR_PROC="$(mktemp -d)"
export FR_PROC
run_cpu timeout -k 10 420 python bin/serve_bench.py --mode generate \
  --qps 60 --duration 3 --deadline-ms 0 --slots 2 --gen-tokens 32 \
  --replicas 3 --json /tmp/hvd_proc_tref.json
HVD_FLIGHTREC_DIR="$FR_PROC" \
run_cpu timeout -k 10 420 python bin/serve_bench.py --mode generate \
  --qps 60 --duration 3 --deadline-ms 0 --slots 2 --gen-tokens 32 \
  --replicas 3 --replica-procs \
  --chaos 'replica_proc_kill=r1@stream=3' --json /tmp/hvd_proc_kill.json
python - <<'PYEOF'
import glob, json, os
def rows(path):
    return [json.loads(l) for l in open(path)]
ref = [r for r in rows("/tmp/hvd_proc_tref.json")
       if "stream_digest" in r][-1]
kill_rows = rows("/tmp/hvd_proc_kill.json")
row = [r for r in kill_rows if "stream_digest" in r][-1]
fleet = [r for r in kill_rows if r.get("fleet")][-1]
# The topology stamp makes the cross-topology comparison self-checking.
assert ref["topology"] == "thread" and row["topology"] == "process", \
    (ref.get("topology"), row.get("topology"))
assert row["completed"] == row["sent"] and row["failed"] == 0, \
    (row["completed"], row["sent"], row["failed"])
assert row["overload_drops"] == 0 and row["deadline_drops"] == 0, row
assert fleet["failover"]["resumed"] >= 1, fleet["failover"]
assert fleet["failover"]["exhausted"] == 0, fleet["failover"]
assert fleet["stranded"] >= 1, fleet
assert fleet["drained_lost_streams"] == 0, fleet
# The SIGKILL actually landed on a member (its dispatch history folded
# into the bounded "retired" series when the dead pid was evicted).
assert fleet["dispatch"].get("retired", 0) >= 1, fleet
assert row["stream_digests"] == ref["stream_digests"], \
    "process-kill failover changed a client-visible token stream vs " \
    "the thread-fleet reference"
print(f"proc fleet: {fleet['stranded']} stranded -> "
      f"{fleet['failover']['resumed']} resumed, 0 exhausted; digests "
      f"identical to the unkilled thread fleet")
# The dead CHILD's post-mortem: per-replica dump dir, serve_crash event
# naming the in-flight streams, written before the self-SIGKILL.
dumps = glob.glob(os.environ["FR_PROC"] + "/r1/hvd_flightrec.rank*.json")
assert dumps, "SIGKILLed child left no flight-recorder post-mortem"
body = open(dumps[0]).read()
assert "serve_crash" in body and "replica_proc_kill" in body, \
    f"child post-mortem names neither the crash nor the drill: {body[:200]}"
print("post-mortem OK: dead child dumped its in-flight streams before "
      "the SIGKILL")
print("OUT-OF-PROCESS FAILOVER OK")
PYEOF

echo "== speculative decoding: greedy digests spec-on == spec-off, accept rate + tokens/step pinned =="
# ISSUE 17 acceptance: --spec-k 4 drafts with the self-speculative
# n-gram proposer and scores k+1 positions in ONE verify forward.
# Pinned: (a) greedy speculated streams digest-IDENTICAL to the spec-off
# reference on BOTH KV layouts (bit-identity is the contract, not a
# tolerance — and paged greedy digests equal contiguous ones, so one
# reference covers both), (b) spec_accept_rate > 0 (tiny greedy models
# settle into repeating cycles the drafter catches — speculation
# actually fired), (c) effective tokens per decode step > 1.0
# (speculation actually emitted multi-token steps, counting no-draft
# fallback steps against it).
rm -f /tmp/hvd_spec_off.json /tmp/hvd_spec_on.json /tmp/hvd_spec_paged.json
run_cpu timeout -k 10 240 python bin/serve_bench.py --mode generate \
  --qps 20 --duration 5 --deadline-ms 0 --gen-tokens 32 \
  --json /tmp/hvd_spec_off.json
run_cpu timeout -k 10 240 python bin/serve_bench.py --mode generate \
  --qps 20 --duration 5 --deadline-ms 0 --gen-tokens 32 --spec-k 4 \
  --json /tmp/hvd_spec_on.json
run_cpu timeout -k 10 240 python bin/serve_bench.py --mode generate \
  --qps 20 --duration 5 --deadline-ms 0 --gen-tokens 32 --spec-k 4 \
  --kv-layout paged --block-size 16 --json /tmp/hvd_spec_paged.json
python - <<'PYEOF'
import json
off = [json.loads(l) for l in open("/tmp/hvd_spec_off.json")][-1]
on = [json.loads(l) for l in open("/tmp/hvd_spec_on.json")][-1]
paged = [json.loads(l) for l in open("/tmp/hvd_spec_paged.json")][-1]
assert off["spec_k"] == 0 and off["spec_accept_rate"] is None, off["spec_k"]
for run, label in ((on, "contiguous"), (paged, "paged")):
    assert run["completed"] == run["sent"] and run["failed"] == 0, \
        (label, run["completed"], run["sent"], run["failed"])
    assert run["spec_k"] == 4, (label, run["spec_k"])
    assert run["stream_digest"] == off["stream_digest"], \
        f"{label}: speculation changed a greedy token stream"
    assert run["spec_accept_rate"] and run["spec_accept_rate"] > 0, \
        (label, run["spec_accept_rate"])
    assert run["tokens_per_step"] and run["tokens_per_step"] > 1.0, \
        (label, run["tokens_per_step"])
    print(f"{label}: digest == spec-off reference, accept_rate "
          f"{run['spec_accept_rate']:.3f}, "
          f"{run['tokens_per_step']:.2f} tokens/step")
print("SPECULATIVE DECODING OK")
PYEOF

echo "== speculative decoding chaos: SIGKILL a subprocess replica mid-speculated-stream =="
# ISSUE 17 acceptance (failover half): a speculated stream's failover
# envelope must replay BIT-identically after a real process death. A
# 3-member subprocess fleet speculates (--spec-k rides the child spec);
# the chaos clause SIGKILLs r1 mid-stream. Pinned: zero lost streams,
# >=1 resume, every client-visible stream digest IDENTICAL to the
# spec-off single-engine reference (speculation AND cross-process
# failover, together, changed no token), and the fleet still reports a
# nonzero acceptance rate aggregated from the children's /stats.
rm -f /tmp/hvd_spec_fo_ref.json /tmp/hvd_spec_fo_kill.json
run_cpu timeout -k 10 420 python bin/serve_bench.py --mode generate \
  --qps 60 --duration 3 --deadline-ms 0 --slots 2 --gen-tokens 32 \
  --json /tmp/hvd_spec_fo_ref.json
run_cpu timeout -k 10 420 python bin/serve_bench.py --mode generate \
  --qps 60 --duration 3 --deadline-ms 0 --slots 2 --gen-tokens 32 \
  --replicas 3 --replica-procs --spec-k 4 \
  --chaos 'replica_proc_kill=r1@stream=3' --json /tmp/hvd_spec_fo_kill.json
python - <<'PYEOF'
import json
ref = [json.loads(l) for l in open("/tmp/hvd_spec_fo_ref.json")
       if "stream_digest" in l][-1]
kill_rows = [json.loads(l) for l in open("/tmp/hvd_spec_fo_kill.json")]
row = [r for r in kill_rows if "stream_digest" in r][-1]
fleet = [r for r in kill_rows if r.get("fleet")][-1]
assert ref["spec_k"] == 0 and row["spec_k"] == 4, \
    (ref["spec_k"], row["spec_k"])
assert row["completed"] == row["sent"] and row["failed"] == 0, \
    (row["completed"], row["sent"], row["failed"])
assert fleet["failover"]["resumed"] >= 1, fleet["failover"]
assert fleet["failover"]["exhausted"] == 0, fleet["failover"]
assert fleet["stranded"] >= 1, fleet
assert fleet["drained_lost_streams"] == 0, fleet
assert fleet["dispatch"].get("retired", 0) >= 1, fleet
assert row["stream_digests"] == ref["stream_digests"], \
    "speculation + process-kill failover changed a client-visible " \
    "token stream vs the spec-off reference"
assert fleet["spec_accept_rate"] and fleet["spec_accept_rate"] > 0, \
    fleet["spec_accept_rate"]
print(f"spec fleet: {fleet['stranded']} stranded -> "
      f"{fleet['failover']['resumed']} resumed, 0 exhausted; digests "
      f"identical to the spec-off unkilled reference; fleet accept_rate "
      f"{fleet['spec_accept_rate']:.3f}")
print("SPECULATIVE FAILOVER OK")
PYEOF

echo "== multi-tenant adapters: hot-evict under traffic (refusal while referenced, zero lost streams) =="
run_cpu timeout -k 10 240 python - <<'PYEOF'
import time
import jax, jax.numpy as jnp
from horovod_tpu import serve
from horovod_tpu.parallel.transformer import TransformerConfig, init_params
from horovod_tpu.parallel.lora import LoraConfig, init_adapter

cfg = TransformerConfig(vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                        dtype=jnp.float32, unembed_dtype=jnp.float32,
                        attn_backend="xla")
params = init_params(jax.random.PRNGKey(0), cfg)
lora = LoraConfig(rank=2)
reg = serve.AdapterRegistry(cfg, lora, capacity=2)
reg.load("a0", init_adapter(jax.random.PRNGKey(1), cfg, lora, b_scale=0.5))
reg.load("a1", init_adapter(jax.random.PRNGKey(2), cfg, lora, b_scale=0.5))
eng = serve.GenerationEngine(
    params, cfg,
    serve.GenerationConfig(max_slots=2, max_len=64,
                           default_max_new_tokens=48), adapters=reg)
ref = eng.generate([5, 4, 3], adapter="a0", timeout=120)   # quiet reference
h = eng.submit([5, 4, 3], adapter="a0", max_new_tokens=48)  # long live stream
# The row reference is taken AT SUBMIT (caller's thread), so the evict
# attempt races nothing: the refcount holds until the stream completes.
try:
    reg.evict("a0")
    raise SystemExit("FAIL: evict succeeded while a live stream references a0")
except RuntimeError as e:
    assert "referenced" in str(e), e
r = h.result(120)
assert r["tokens"] == ref["tokens"], \
    "FAIL: eviction attempt perturbed a live stream"
reg.evict("a0")                         # stream done: refcount 0, allowed
assert "a0" not in reg.resident()
n_compiled = len(eng._compiled)
reg.load("a2", init_adapter(jax.random.PRNGKey(3), cfg, lora, b_scale=0.5))
out = eng.generate([5, 4, 3], adapter="a2", timeout=120)    # row reused
assert out["n_tokens"] > 0 and len(eng._compiled) == n_compiled, \
    "FAIL: hot load recompiled"
eng.shutdown()
print("hot-evict drill OK: refusal while referenced, stream finished "
      f"bit-identical ({r['n_tokens']} tokens), row reused with no recompile")
PYEOF

echo "== SLO fairness: starvation drill (chatty tenant saturates, quiet tenant's TTFT holds) =="
# ISSUE 19 acceptance: equal weights {chatty:1, quiet:1}, chatty (base)
# at ~59x the quiet tenant's arrival rate, 300 qps against 2 decode
# slots — the chatty backlog is hundreds deep by design. Under FIFO
# the quiet tenant's TTFT is that backlog's drain time (minutes);
# under WDRR it is its own near-empty line. Pinned: every quiet
# stream completes, its p50 TTFT holds a 10 s SLO, and the chatty
# tenant is throttled — NOT failed (deadline 0, huge queue: zero
# drops, zero failures for either tenant).
rm -f /tmp/hvd_fair.json
run_cpu timeout -k 10 240 python bin/serve_bench.py --mode generate \
  --qps 300 --duration 2 --deadline-ms 0 --slots 2 --gen-tokens 8 \
  --max-queue 4096 --adapters 1 --adapter-mix 59,1 \
  --tenant-weights base:1,a0:1 --tenant-slo-ms a0:10000 \
  --json /tmp/hvd_fair.json
python - <<'PYEOF'
import json
row = [json.loads(l) for l in open("/tmp/hvd_fair.json")][-1]
assert row["failed"] == 0 and row["overload_drops"] == 0, row
sent, done = row["tenant_sent"], row["tenant_completed"]
assert sent["base"] > 10 * sent["a0"] > 0, \
    f"traffic shape degenerate, drill proves nothing: {sent}"
assert done["a0"] == sent["a0"], \
    f"quiet tenant starved: {done['a0']}/{sent['a0']} completed"
assert done["base"] == sent["base"], \
    f"chatty tenant was FAILED, not throttled: {done['base']}/{sent['base']}"
p50 = row["tenant_ttft_ms"]["a0"]["p50"]
assert p50 <= row["tenant_slo_ms"]["a0"], \
    f"quiet tenant p50 TTFT {p50:.0f} ms blew its " \
    f"{row['tenant_slo_ms']['a0']:.0f} ms SLO behind the chatty backlog"
assert row["tenants"]["a0"]["slo_ttft_target_ms"] == 10000.0, row["tenants"]
print(f"fairness OK: quiet {done['a0']}/{sent['a0']} complete, "
      f"p50 TTFT {p50:.0f} ms <= 10000 ms SLO while chatty sent "
      f"{sent['base']} ({done['base']} complete, 0 failed)")
print("STARVATION DRILL OK")
PYEOF

echo "== SLO preemption: priority evictions stay digest-pinned (slots=1, mixed classes) =="
# ISSUE 19 acceptance: a0 in priority class 1 over ONE decode slot —
# every a0 arrival evicts the running base stream, which later resumes
# with its emitted prefix replayed suppressed-and-verified. Pinned:
# preemptions actually happened, none exhausted (the drill raises the
# retry budget so an unlucky eviction streak can't flake the run), and
# BOTH tenants' digests are bit-identical to their single-tenant
# replays of the same seeded schedule — eviction is invisible in the
# streams, visible only in the counters.
rm -f /tmp/hvd_pre_mix.json /tmp/hvd_pre_base.json /tmp/hvd_pre_a0.json
for only in "" base a0; do
  out=mix; flags=""
  if [ -n "$only" ]; then out=$only; flags="--adapter-only $only"; fi
  run_cpu timeout -k 10 240 python bin/serve_bench.py --mode generate \
    --qps 100 --duration 3 --deadline-ms 0 --slots 1 --gen-tokens 16 \
    --max-queue 4096 --adapters 1 --adapter-mix 4,1 \
    --priority-mix a0:1 --preempt-retries 1000 $flags \
    --json /tmp/hvd_pre_$out.json
done
python - <<'PYEOF'
import json
mix = [json.loads(l) for l in open("/tmp/hvd_pre_mix.json")][-1]
assert mix["completed"] == mix["sent"] and mix["failed"] == 0, mix
assert mix["preemptions"] >= 1, \
    f"priority class 1 over one slot never evicted: {mix['preemptions']}"
assert mix["preempt_exhausted"] == 0, mix
for t in ("base", "a0"):
    solo = [json.loads(l) for l in open(f"/tmp/hvd_pre_{t}.json")][-1]
    assert solo["completed"] == solo["sent"] and solo["failed"] == 0, solo
    assert solo["tenant_sent"][t] == mix["tenant_sent"][t], \
        f"{t}: schedule replay drifted"
    assert mix["stream_digests"][t] == solo["stream_digests"][t], \
        f"tenant {t}: preemption changed a client-visible token stream"
print(f"preemption OK: {mix['preemptions']} evictions, "
      f"{mix['preempt_resumed']} resumed, 0 exhausted; base and a0 "
      f"digests identical to their uninterrupted solo runs")
print("PREEMPTION DIGEST OK")
PYEOF

echo "== SLO budgets: per-tenant blocks_exhausted rejects ONE tenant, neighbors admit =="
run_cpu timeout -k 10 240 python - <<'PYEOF'
import jax, jax.numpy as jnp
from horovod_tpu import serve
from horovod_tpu.exceptions import ServerOverloadedError
from horovod_tpu.parallel.transformer import TransformerConfig, init_params
from horovod_tpu.parallel.lora import LoraConfig, init_adapter

cfg = TransformerConfig(vocab=64, d_model=32, n_heads=2, n_layers=2, d_ff=64,
                        dtype=jnp.float32, unembed_dtype=jnp.float32,
                        attn_backend="xla")
params = init_params(jax.random.PRNGKey(0), cfg)
lora = LoraConfig(rank=2)
reg = serve.AdapterRegistry(cfg, lora, capacity=2)
reg.load("a0", init_adapter(jax.random.PRNGKey(1), cfg, lora, b_scale=0.5))
reg.load("a1", init_adapter(jax.random.PRNGKey(2), cfg, lora, b_scale=0.5))
eng = serve.GenerationEngine(
    params, cfg,
    serve.GenerationConfig(max_slots=4, max_len=64,
                           default_max_new_tokens=16, kv_layout="paged",
                           block_size=16,
                           tenant_block_budgets={"a0": 2}), adapters=reg)
# a0's worst case: blocks_for(3 + 16 - 1) = 2 == its whole budget, so a
# SECOND in-flight a0 stream must be rejected at the door — blocks
# exhausted for a0 ALONE, with a usable backoff hint...
h0 = eng.submit([5, 4, 3], adapter="a0")
try:
    eng.submit([6, 5, 4], adapter="a0")
    raise SystemExit("FAIL: second a0 stream fit in a 2-block budget")
except ServerOverloadedError as e:
    assert "blocks_exhausted" in str(e), e
    assert 50.0 <= e.retry_after_ms <= 30000.0, e.retry_after_ms
# ...while the neighbors' doors never move: base and a1 admit at the
# same instant a0 is budget-starved (the isolation half).
hb = eng.submit([6, 5, 4])
h1 = eng.submit([6, 5, 4], adapter="a1")
for h in (h0, hb, h1):
    assert h.result(120)["n_tokens"] == 16
assert eng.stats()["rejected_blocks_exhausted"] >= 1
assert eng.stats()["blocks_by_tenant"]["budgets"] == {"a0": 2}
# Drained: the ledger released a0's headroom and it admits again.
r = eng.generate([5, 4, 3], adapter="a0", timeout=120)
assert r["n_tokens"] == 16
eng.shutdown()
print("budget isolation OK: a0 rejected blocks_exhausted (retry hint "
      "attached) while base and a1 admitted; headroom returned on drain")
print("BUDGET ISOLATION OK")
PYEOF

echo "== striped host reduce (multi-core validation, gated on nproc) =="
if [ "$(nproc)" -gt 1 ]; then
  # On a >=4-core host, striping must not LOSE to the serial reduce at
  # coordinator scale (docs/coordination.md "Star-plane throughput under
  # load"); on 2-3 cores the script measures and reports (median of
  # rounds) without asserting — the 4-way stripe needs 4 cores for the
  # claim to even apply, and loaded 2-core CI runners were flaking the
  # bound without any product change.
  python tests/striping_bench.py
else
  echo "skip: single-core host — striping is neutral by construction here"
  echo "      (correctness is covered by tests/test_coord.py; the"
  echo "       multi-core perf claim is marked unmeasured in"
  echo "       docs/coordination.md until CI lands on a multi-core host)"
fi

echo "== container image (gated on docker availability) =="
if command -v docker >/dev/null 2>&1; then
  docker build -t horovod-tpu-ci .
  docker run --rm horovod-tpu-ci \
    python -m horovod_tpu.launcher -np 2 --cpu python tests/launcher_worker.py
else
  echo "skip: no docker daemon in this environment — the Dockerfile builds"
  echo "      from the baked-in wheels only; multi-host wiring is"
  echo "      documented in docs/running.md"
fi

echo "== tpurun launcher smoke (2 ranks, env-world) =="
python -m horovod_tpu.launcher -np 2 --cpu python tests/launcher_worker.py

# Flight-recorder hygiene for every chaos leg below: dumps default to
# the cwd, so a previous run's hvd_flightrec.rank*.json in the repo root
# could satisfy a pinned grep/assert from THIS run's leg (and stale
# dumps mask real post-mortems). Clean them, then point the default dump
# dir at a tmp dir — legs that pin dump CONTENTS still set their own
# HVD_FLIGHTREC_DIR inline, which overrides the export.
rm -f hvd_flightrec.rank*.json
HVD_FLIGHTREC_DIR="$(mktemp -d)"
export HVD_FLIGHTREC_DIR

echo "== fault-injection smoke: kill rank 2 at step 3, recover via --restarts 1 =="
# The anti-hang drill (docs/fault_tolerance.md): rank 2 is SIGKILLed mid
# -training; the coordinator must ABORT the world (WorkerFailureError, no
# hang), tpurun must relaunch it once, and run_with_recovery must resume
# from the last committed step and finish. The hard `timeout` is the
# assertion — a regression that reintroduces the reference's dead-rank
# hang fails CI here instead of wedging it.
FT_DIR=$(mktemp -d)
HVD_FAULT_SPEC=rank=2:kill@step=3 HVD_ELASTIC_DIR="$FT_DIR" \
HVD_HEARTBEAT_TIMEOUT=10 HVD_TOTAL_STEPS=6 \
  timeout -k 10 300 \
  python -m horovod_tpu.launcher -np 4 --cpu --restarts 1 \
  python tests/elastic_worker.py
# And without --restarts the same drill must FAIL FAST (nonzero AND not
# a timeout kill): exit 124/137 would mean the job HUNG until `timeout`
# shot it — the exact regression this leg exists to catch.
FT_DIR2=$(mktemp -d)
set +e
HVD_FAULT_SPEC=rank=2:kill@step=3 HVD_ELASTIC_DIR="$FT_DIR2" \
HVD_HEARTBEAT_TIMEOUT=10 HVD_TOTAL_STEPS=6 \
  timeout -k 10 180 \
  python -m horovod_tpu.launcher -np 4 --cpu \
  python tests/elastic_worker.py
ft_rc=$?
set -e
if [ "$ft_rc" -eq 0 ]; then
  echo "FAIL: killed-rank world exited 0 without restarts" >&2
  exit 1
elif [ "$ft_rc" -eq 124 ] || [ "$ft_rc" -eq 137 ]; then
  echo "FAIL: killed-rank world HUNG until timeout killed it (rc=$ft_rc)" >&2
  exit 1
fi
rm -rf "$FT_DIR" "$FT_DIR2"

echo "== chaos leg: post-commit checkpoint truncation -> verified fallback restore =="
# ISSUE 4 acceptance (a): ckpt:truncate@step=3 tears the step-3 checkpoint
# strictly AFTER its two-phase commit (marker on disk), then rank 2 is
# killed — the restarted world must DISCARD the torn-but-committed step
# via the integrity-manifest walk, resume from verified step 2, and still
# finish bit-identical to an uninterrupted run. A regression that trusts
# the marker without verifying bytes restores garbage and diverges here.
CH_REF=$(mktemp -d); CH_DIR=$(mktemp -d)
HVD_ELASTIC_DIR="$CH_REF" HVD_TOTAL_STEPS=6 \
  timeout -k 10 300 \
  python -m horovod_tpu.launcher -np 4 --cpu \
  python tests/elastic_worker.py 2>&1 | tee /tmp/chaos_ref.out
HVD_FAULT_SPEC=ckpt:truncate@step=3,rank=2:kill@step=3 \
HVD_ELASTIC_DIR="$CH_DIR" HVD_HEARTBEAT_TIMEOUT=10 HVD_TOTAL_STEPS=6 \
  timeout -k 10 300 \
  python -m horovod_tpu.launcher -np 4 --cpu --restarts 1 \
  python tests/elastic_worker.py 2>&1 | tee /tmp/chaos_run.out
grep -q "resuming from verified step 2" /tmp/chaos_run.out || {
  echo "FAIL: fallback walk never fired — the torn commit was trusted" >&2
  exit 1
}
REF_SUM=$(grep -o "FINAL [0-9.]*" /tmp/chaos_ref.out | sort -u)
CH_SUM=$(grep -o "FINAL [0-9.]*" /tmp/chaos_run.out | sort -u)
if [ -z "$REF_SUM" ] || [ "$REF_SUM" != "$CH_SUM" ]; then
  echo "FAIL: post-recovery params diverge from uninterrupted run" >&2
  echo "  reference: $REF_SUM" >&2
  echo "  chaos:     $CH_SUM" >&2
  exit 1
fi
rm -rf "$CH_REF" "$CH_DIR"

echo "== chaos leg: NaN-injection -> bit-exact skip-step, HLO all-reduce count pinned =="
# ISSUE 4 acceptance (b)+(c): one non-finite microbatch leaves params
# BIT-identical (the in-jit guard gates the update), flags bad_step=1,
# the next finite batch trains normally, and arming the guard adds ZERO
# all-reduces to the lowered step.
run_cpu timeout -k 10 300 python - <<'EOF'
import re
import flax.linen as nn
import jax, jax.numpy as jnp, numpy as np, optax
import horovod_tpu as hvd
from horovod_tpu import training

class M(nn.Module):
    @nn.compact
    def __call__(self, x, train=True):
        return nn.Dense(10)(nn.relu(nn.Dense(16)(x)))

hvd.init()
model = M()
state, opt = training.create_train_state(
    model, jax.random.PRNGKey(0), jnp.zeros((2, 8)), optax.adam(1e-3))
step = training.make_train_step(model, opt, guard_nonfinite=True,
                                donate=False)
rng = np.random.RandomState(0)
x = rng.randn(16, 8).astype(np.float32)
y = rng.randint(0, 10, (16,))
x[3] = np.nan
before = jax.tree_util.tree_map(np.asarray, state.params)
s2, m = step(state, (x, y))
assert float(m["bad_step"]) == 1.0, m
for a, b in zip(jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, s2.params)),
        jax.tree_util.tree_leaves(before)):
    np.testing.assert_array_equal(a, b)
x2 = rng.randn(16, 8).astype(np.float32)
s3, m2 = step(s2, (x2, y))
assert float(m2["bad_step"]) == 0.0, m2
n_guard = len(re.findall(r"\ball_reduce\b",
                         step.lower(s2, (x2, y)).as_text()))
bare = training.make_train_step(model, opt, guard_nonfinite=False,
                                donate=False)
n_bare = len(re.findall(r"\ball_reduce\b",
                        bare.lower(s2, (x2, y)).as_text()))
assert n_guard == n_bare, (n_guard, n_bare)
print(f"NaN smoke OK: skip-step bit-exact, all_reduce count {n_guard} "
      f"unchanged by guard")
EOF

echo "== telemetry leg: /metrics exposition on the generation engine (ISSUE 12) =="
# curl the serving /metrics route during a generation smoke and pin the
# NAMED series the fleet tooling keys on (docs/observability.md):
# the TTFT histogram buckets and the paged KV block-pool gauges.
run_cpu timeout -k 10 240 python - <<'EOF'
import subprocess, urllib.request
import jax, jax.numpy as jnp
from horovod_tpu import serve
from horovod_tpu.obs.registry import parse_exposition
from horovod_tpu.parallel.transformer import TransformerConfig, init_params

cfg = TransformerConfig(vocab=32, d_model=16, n_heads=2, n_layers=2,
                        d_ff=32, dtype=jnp.float32,
                        unembed_dtype=jnp.float32, attn_backend="xla")
params = init_params(jax.random.PRNGKey(0), cfg)
eng = serve.GenerationEngine(params, cfg, serve.GenerationConfig(
    max_slots=2, max_len=16, default_max_new_tokens=4,
    kv_layout="paged", block_size=4))
eng.warmup()
for _ in range(3):
    eng.generate([3, 1, 4, 1, 5], timeout=60)
with serve.HttpServer(generate=eng) as srv:
    url = f"http://{srv.host}:{srv.port}/metrics"
    try:
        body = subprocess.run(["curl", "-sf", url], check=True,
                              capture_output=True).stdout.decode()
    except (FileNotFoundError, subprocess.CalledProcessError):
        body = urllib.request.urlopen(url).read().decode()
parsed = parse_exposition(body)
names = {k[0] for k in parsed}
for want in ("hvd_generate_ttft_seconds_bucket", "hvd_kv_blocks_free",
             "hvd_kv_blocks_total", "hvd_tokens_generated_total",
             "hvd_requests_total", "hvd_uptime_seconds"):
    assert want in names, f"missing series {want}: {sorted(names)}"
assert parsed[("hvd_tokens_generated_total",
               (("engine", "generate"),))] >= 3
assert body.count("# TYPE hvd_generations_total counter") == 1
eng.shutdown()
print(f"GENERATION /metrics OK: {len(parsed)} series, valid exposition")
EOF

echo "== telemetry leg: scrape 2 live training ranks + tpurun --metrics-summary =="
# A 2-rank env-world Trainer job with HVD_METRICS_PORT set: both rank
# listeners (base+0, base+1) must serve exposition text WHILE the job
# trains, and the one-shot fleet poller must aggregate them into one
# "2/2 ranks up" line — the PR-9 supervisor's first real fleet view.
rm -f /tmp/rank0_metrics.txt /tmp/rank1_metrics.txt /tmp/fleet_line.out
TL_PORT=$(python -c 'import socket; s=socket.socket(); s.bind(("127.0.0.1",0)); print(s.getsockname()[1]); s.close()')
HVD_METRICS_PORT=$TL_PORT HVD_METRICS_HOST=127.0.0.1 \
HVD_STEP_SLEEP_MS=300 HVD_TOTAL_STEPS=60 \
  timeout -k 10 180 \
  python -m horovod_tpu.launcher -np 2 --cpu \
  python tests/obs_worker.py > /tmp/telemetry_train.out 2>&1 &
TL_PID=$!
trap 'kill "$TL_PID" 2>/dev/null || true' EXIT
# NB: scrape to files, grep the files — `curl | grep -q` under pipefail
# flakes on grep's early-exit SIGPIPE back into curl.
tl_ok=""
for _ in $(seq 1 120); do
  curl -sf "http://127.0.0.1:$TL_PORT/metrics" \
    -o /tmp/rank0_metrics.txt 2>/dev/null || true
  curl -sf "http://127.0.0.1:$((TL_PORT+1))/metrics" \
    -o /tmp/rank1_metrics.txt 2>/dev/null || true
  # Nonzero step counts: the counters REGISTER at Trainer construction,
  # so a zero-valued match would race the first actual step (and the
  # first exchange, which registers the collective counters).
  if grep -Eq 'hvd_steps_total\{rank="0"\} [1-9]' /tmp/rank0_metrics.txt \
       2>/dev/null \
     && grep -Eq 'hvd_steps_total\{rank="1"\} [1-9]' \
       /tmp/rank1_metrics.txt 2>/dev/null; then
    tl_ok=1; break
  fi
  sleep 0.5
done
[ -n "$tl_ok" ] || {
  echo "FAIL: training ranks never served /metrics" >&2
  cat /tmp/telemetry_train.out >&2
  exit 1
}
for series in hvd_step_seconds_bucket hvd_samples_total \
              hvd_collective_submits_total hvd_world_size; do
  grep -q "$series" /tmp/rank0_metrics.txt || {
    echo "FAIL: rank 0 /metrics missing series $series" >&2
    exit 1
  }
done
python -m horovod_tpu.launcher -np 2 --metrics-summary \
  --metrics-port "$TL_PORT" | tee /tmp/fleet_line.out
grep -q "fleet: 2/2 ranks up" /tmp/fleet_line.out || {
  echo "FAIL: --metrics-summary did not aggregate both ranks" >&2
  exit 1
}
wait "$TL_PID" || {
  echo "FAIL: telemetry training job exited nonzero" >&2
  cat /tmp/telemetry_train.out >&2
  exit 1
}
trap - EXIT
echo "TRAINING /metrics + fleet summary OK"

echo "== telemetry leg: rank kill leaves a flight-recorder post-mortem =="
# rank=1:kill@step=3 SIGKILLs rank 1 mid-training. The drilled rank's
# dump (written by the fault injector, standing in for the platform's
# SIGTERM-before-SIGKILL notice) must name its final completed step;
# the SURVIVOR's dump (triggered by the WorkerFailureError abort) must
# name the dead rank — post-mortems from files, not stdout greps.
FR_DIR=$(mktemp -d)
set +e
HVD_FAULT_SPEC=rank=1:kill@step=3 HVD_FLIGHTREC_DIR="$FR_DIR" \
HVD_HEARTBEAT_TIMEOUT=10 HVD_TOTAL_STEPS=8 \
  timeout -k 10 180 \
  python -m horovod_tpu.launcher -np 2 --cpu \
  python tests/obs_worker.py > /tmp/telemetry_kill.out 2>&1
fr_rc=$?
set -e
if [ "$fr_rc" -eq 0 ] || [ "$fr_rc" -eq 124 ]; then
  echo "FAIL: kill drill rc=$fr_rc (0 = fault never fired, 124 = hang)" >&2
  cat /tmp/telemetry_kill.out >&2
  exit 1
fi
python - "$FR_DIR" <<'EOF'
import json, sys
d = sys.argv[1]
dead = json.load(open(f"{d}/hvd_flightrec.rank1.json"))
assert dead["last_step"] == 3, dead["last_step"]
assert "kill" in dead["reason"], dead["reason"]
assert any(e["kind"] == "step" and e["step"] == 3
           for e in dead["events"]), dead["events"][-5:]
survivor = json.load(open(f"{d}/hvd_flightrec.rank0.json"))
assert "rank 1" in survivor["reason"], survivor["reason"]
print(f"FLIGHT RECORDER OK: dead rank's last step "
      f"{dead['last_step']}, survivor names the dead rank")
EOF
rm -rf "$FR_DIR"

echo "== live-resize chaos leg: shrink 4 -> 2 in place (quiesce, recommit, re-shard — no restart) =="
# ISSUE 9 acceptance: resize:shrink=2@step=3 must quiesce at a step
# boundary, recommit through the two-phase elastic commit, re-shard in
# place and resume — the log pins quiesce -> recommit -> re-shard and must
# contain NO relaunch line (resize is not a restart), and the final
# checksum must match an uninterrupted 2-rank run bit-for-bit (the
# worker's gradient sums are exact dyadic rationals, invariant to how the
# world splits them).
RS_REF=$(mktemp -d); RS_DIR=$(mktemp -d)
HVD_ELASTIC_DIR="$RS_REF" HVD_TOTAL_STEPS=6 \
  timeout -k 10 300 \
  python -m horovod_tpu.launcher -np 2 --cpu \
  python tests/resize_worker.py 2>&1 | tee /tmp/resize_ref.out
HVD_FAULT_SPEC=resize:shrink=2@step=3 HVD_ELASTIC_DIR="$RS_DIR" \
HVD_HEARTBEAT_TIMEOUT=10 HVD_TOTAL_STEPS=6 \
  timeout -k 10 300 \
  python -m horovod_tpu.launcher -np 4 --cpu --restarts 1 \
  python tests/resize_worker.py 2>&1 | tee /tmp/resize_run.out
# The no-restart pin is the WORKERS' "resuming ... without restart" line:
# it is printed by every surviving rank the instant the in-place re-shard
# completes. (tpurun also prints "resize is not a restart" once its
# commit-confirmation probe lands, but a drill this short can finish
# inside the probe window — the worker line is the deterministic truth.)
for want in "resize: quiesced at step" \
            "recommitting and canonicalizing" \
            "re-sharded optimizer state in place onto world 2" \
            "without restart"; do
  grep -q "$want" /tmp/resize_run.out || {
    echo "FAIL: resize log missing \"$want\" — the quiesce protocol did" \
         "not run" >&2
    exit 1
  }
done
if grep -q "relaunching" /tmp/resize_run.out; then
  echo "FAIL: the shrink took the RESTART path — live resize must keep" \
       "surviving ranks' processes" >&2
  exit 1
fi
RS_REF_SUM=$(grep -o "FINAL [0-9.]*" /tmp/resize_ref.out | sort -u || true)
RS_RUN_SUM=$(grep -o "FINAL [0-9.]*" /tmp/resize_run.out | sort -u || true)
if [ -z "$RS_REF_SUM" ] || [ "$RS_REF_SUM" != "$RS_RUN_SUM" ]; then
  echo "FAIL: live-shrunk run diverges from uninterrupted 2-rank run" >&2
  echo "  reference: $RS_REF_SUM" >&2
  echo "  resized:   $RS_RUN_SUM" >&2
  exit 1
fi
rm -rf "$RS_REF" "$RS_DIR"

echo "== live-resize chaos leg: grow 2 -> 4 under --restarts 0 (resize is not a restart) =="
# The grow leg runs with ZERO restarts budget: if the resize were secretly
# a relaunch, the launch would fail — finishing at world 4 with the
# uninterrupted 4-rank checksum proves the joiners were spawned into the
# LIVE world (state over the wire via elastic.resize_join, no disk).
RG_REF=$(mktemp -d); RG_DIR=$(mktemp -d)
HVD_ELASTIC_DIR="$RG_REF" HVD_TOTAL_STEPS=8 \
  timeout -k 10 300 \
  python -m horovod_tpu.launcher -np 4 --cpu \
  python tests/resize_worker.py 2>&1 | tee /tmp/resize_grow_ref.out
HVD_FAULT_SPEC=resize:grow=2@step=3 HVD_ELASTIC_DIR="$RG_DIR" \
HVD_HEARTBEAT_TIMEOUT=10 HVD_TOTAL_STEPS=8 \
  timeout -k 10 300 \
  python -m horovod_tpu.launcher -np 2 --cpu --restarts 0 --max-np 4 \
  python tests/resize_worker.py 2>&1 | tee /tmp/resize_grow.out
grep -q "joining world 4" /tmp/resize_grow.out || {
  echo "FAIL: no rank joined the grown world over the wire" >&2
  exit 1
}
RG_N=$(grep -c "FINAL" /tmp/resize_grow.out || true)
if [ "$RG_N" -ne 4 ]; then
  echo "FAIL: expected 4 FINAL lines after the grow, got $RG_N" >&2
  exit 1
fi
RG_REF_SUM=$(grep -o "FINAL [0-9.]*" /tmp/resize_grow_ref.out | sort -u || true)
RG_RUN_SUM=$(grep -o "FINAL [0-9.]*" /tmp/resize_grow.out | sort -u || true)
if [ -z "$RG_REF_SUM" ] || [ "$RG_REF_SUM" != "$RG_RUN_SUM" ]; then
  echo "FAIL: live-grown run diverges from uninterrupted 4-rank run" >&2
  echo "  reference: $RG_REF_SUM" >&2
  echo "  resized:   $RG_RUN_SUM" >&2
  exit 1
fi
rm -rf "$RG_REF" "$RG_DIR"

echo "== live-resize chaos leg: resize racing a kill -> verified-restore fallback =="
# A rank SIGKILLed while a resize is in flight: the in-place path must be
# ABANDONED and the world fail over to the supervised restart, resuming
# from the quiesce recommit via the verified restore walk.
RK_DIR=$(mktemp -d)
HVD_FAULT_SPEC=resize:shrink=2@step=3,rank=1:kill@step=4 \
HVD_ELASTIC_DIR="$RK_DIR" HVD_HEARTBEAT_TIMEOUT=10 HVD_TOTAL_STEPS=6 \
  timeout -k 10 300 \
  python -m horovod_tpu.launcher -np 4 --cpu --restarts 1 \
  python tests/resize_worker.py 2>&1 | tee /tmp/resize_race.out
# (No grep on tpurun's "ABANDONED" line: whether the supervisor had even
# adopted the pending resize when the kill lands is timing-dependent —
# the invariant is the recovery itself, pinned below.)
grep -q "recovery: resumed from committed step" /tmp/resize_race.out || {
  echo "FAIL: the killed resize never fell back to the verified restore" \
       "walk" >&2
  exit 1
}
RK_SUM=$(grep -o "FINAL [0-9.]*" /tmp/resize_race.out | sort -u || true)
if [ "$(echo "$RK_SUM" | wc -l)" -ne 1 ] || [ -z "$RK_SUM" ]; then
  echo "FAIL: ranks disagree on final params after the raced resize" >&2
  exit 1
fi
rm -rf "$RK_DIR"

echo "== tpurun multi-node smoke (2 simulated hosts x 2 ranks, shared coordinator) =="
# The mpirun -H host1:2,host2:2 analog (docs/running.md): two launcher
# invocations on localhost forming one world of 4 over the coordinator.
MN_PORT=$(python -c 'import socket; s=socket.socket(); s.bind(("127.0.0.1",0)); print(s.getsockname()[1]); s.close()')
python -m horovod_tpu.launcher -np 2 --cpu --nnodes 2 --node-rank 0 \
  --coordinator 127.0.0.1:"$MN_PORT" python tests/launcher_worker.py &
MN_PID=$!
# If node 1 fails, set -e exits this script — kill the backgrounded node 0
# too or its ranks sit blocked on collectives holding the stdout pipe open.
trap 'kill "$MN_PID" 2>/dev/null || true' EXIT
python -m horovod_tpu.launcher -np 2 --cpu --nnodes 2 --node-rank 1 \
  --coordinator 127.0.0.1:"$MN_PORT" python tests/launcher_worker.py
wait "$MN_PID"
trap - EXIT

echo "== driver contracts =="
PYTHONPATH= JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python __graft_entry__.py
HVD_BENCH_SMOKE=1 PYTHONPATH= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 python bench.py
HVD_BENCH_SMOKE=1 PYTHONPATH= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 python bench.py --scaling

echo "== perf smoke: gradient accumulation end-to-end (docs/performance.md) =="
# The accumulated step must complete and report nonzero throughput, and the
# JSON line must carry the accum_steps knob so BENCH_*.json artifacts are
# attributable. (--model pins the conv line only; smoke mode swaps in the
# steps-capped cifar20 config.)
HVD_BENCH_SMOKE=1 PYTHONPATH= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python bench.py --model resnet50 --accum-steps 2 | tee /tmp/bench_accum.json
python - <<'EOF'
import json
line = json.loads(open("/tmp/bench_accum.json").read().strip().splitlines()[-1])
assert line["value"] > 0, f"zero throughput: {line}"
assert line["accum_steps"] == 2, f"accum_steps knob not recorded: {line}"
print(f"accum smoke OK: {line['value']} {line['unit']} @ accum_steps=2")
EOF

echo "== zero smoke: ZeRO-1 vs replicated parity + world-resize restore =="
# ISSUE 5 acceptance: K steps with zero=True must match the replicated
# optimizer's params to dtype tolerance, the lowered step must contain
# one reduce-scatter + one all-gather per fusion bucket and ZERO
# full-tree all-reduces, and a ZeRO checkpoint committed at world 8 must
# verify and RESUME at world 4 (re-sharded canonical restore,
# docs/checkpointing.md).
run_cpu timeout -k 10 300 python - <<'EOF'
import re, tempfile
import flax.linen as nn
import jax, jax.numpy as jnp, numpy as np, optax
import horovod_tpu as hvd
from horovod_tpu import elastic, training
from horovod_tpu.parallel import checkpoint as ckpt
from horovod_tpu.optimizer import zero_to_canonical

class M(nn.Module):
    @nn.compact
    def __call__(self, x, train=True):
        return nn.Dense(10)(nn.relu(nn.Dense(16)(x)))

def build(zero):
    state, opt = training.create_train_state(
        M(), jax.random.PRNGKey(0), jnp.zeros((2, 8)), optax.adam(1e-2),
        zero=zero)
    return state, training.make_train_step(M(), opt, donate=False)

hvd.init()
rng = np.random.RandomState(0)
rs, rstep = build(False)
zs, zstep = build(True)
for i in range(3):
    b = (rng.randn(16, 8).astype(np.float32), rng.randint(0, 10, (16,)))
    rs, _ = rstep(rs, b)
    zs, zm = zstep(zs, b)
for a, b2 in zip(jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, zs.params)),
        jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, rs.params))):
    np.testing.assert_allclose(a, b2, rtol=2e-5, atol=1e-6)
txt = zstep.lower(zs, b).as_text()
nb = len(zs.opt_state.plan.buckets)
counts = (len(re.findall(r"\breduce_scatter\b", txt)),
          len(re.findall(r"\ball_gather\b", txt)),
          len(re.findall(r"\ball_reduce\b", txt)))
assert counts == (nb, nb, 1), (counts, nb)  # the 1 is the loss pmean

d = tempfile.mkdtemp()
es = elastic.ElasticState(zs.params, zs.opt_state, step=3, directory=d,
                          commit_every=1)
path = es.commit()
assert ckpt.verify_checkpoint(path) is True
canon = jax.tree_util.tree_map(
    np.asarray, zero_to_canonical(zs.opt_state).inner)

devs = jax.devices()
hvd.shutdown(); hvd.init(devices=devs[:4])
assert hvd.size() == 4
s4, opt4 = training.create_train_state(
    M(), jax.random.PRNGKey(9), jnp.zeros((2, 8)), optax.adam(1e-2),
    zero=True)
es2 = elastic.ElasticState(s4.params, s4.opt_state, directory=d)
es2.restore()
assert es2.step == 3, es2.step
for a, b2 in zip(jax.tree_util.tree_leaves(jax.tree_util.tree_map(
        np.asarray, zero_to_canonical(es2.opt_state).inner)),
        jax.tree_util.tree_leaves(canon)):
    np.testing.assert_array_equal(a, b2)
st = training.TrainState(step=jnp.asarray(3, jnp.int32),
                         params=es2.params, opt_state=es2.opt_state,
                         batch_stats=None)
st2, m = training.make_train_step(M(), opt4, donate=False)(
    st, (rng.randn(16, 8).astype(np.float32), rng.randint(0, 10, (16,))))
assert np.isfinite(float(m["loss"])) and int(st2.step) == 4
print(f"zero smoke OK: parity over 3 steps, HLO rs/ag/ar={counts} for "
      f"{nb} bucket(s), world 8 -> 4 restore bit-exact and resumed")
EOF

echo "== overlap smoke: overlapped bf16-wire parity + HLO count/dtype pins (ISSUE 6) =="
# ISSUE 6 acceptance: 3 steps with overlap=1 wire_dtype=bf16 must match the
# non-overlapped fp32 run within wire tolerance on BOTH the fused-allreduce
# and ZeRO planes, the bucket-collective count must be UNCHANGED by overlap
# (it reorders, never adds), the emission must be barrier-chained in
# backward-completion order, and the wire cast must be visible in HLO
# (bf16 collective operands) without changing any count.
run_cpu timeout -k 10 300 env HVD_OVERLAP=1 HVD_WIRE_DTYPE=bf16 python - <<'EOF'
import os, re
import flax.linen as nn
import jax, jax.numpy as jnp, numpy as np, optax
import horovod_tpu as hvd
from horovod_tpu import training

class M(nn.Module):
    @nn.compact
    def __call__(self, x, train=True):
        h = x
        for _ in range(3):
            h = nn.relu(nn.Dense(64)(h))
        return nn.Dense(10)(h)

def build(zero, wire, overlap):
    state, opt = training.create_train_state(
        M(), jax.random.PRNGKey(0), jnp.zeros((2, 8)), optax.adam(1e-2),
        zero=zero, wire_dtype=wire, overlap=overlap, fusion_threshold=8000)
    return state, training.make_train_step(M(), opt, donate=False,
                                           overlap=overlap)

hvd.init()
assert os.environ["HVD_OVERLAP"] == "1"  # env defaults are what ship
rng = np.random.RandomState(0)
batches = [(rng.randn(16, 8).astype(np.float32), rng.randint(0, 10, (16,)))
           for _ in range(3)]
for zero in (False, True):
    # The reference pins wire_dtype="fp32" EXPLICITLY: with HVD_WIRE_DTYPE
    # exported above, a None would resolve the env default and the
    # "fp32 run" would silently ride bf16 too.
    rs, rstep = build(zero, "fp32", False)
    ws, wstep = build(zero, "bf16", True)
    for b in batches:
        rs, rm = rstep(rs, b)
        ws, wm = wstep(ws, b)
        np.testing.assert_allclose(float(wm["loss"]), float(rm["loss"]),
                                   rtol=5e-3)
    for a, b2 in zip(jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(np.asarray, ws.params)),
            jax.tree_util.tree_leaves(
            jax.tree_util.tree_map(np.asarray, rs.params))):
        np.testing.assert_allclose(a, b2, rtol=5e-2, atol=4e-2)
    # Count pin: overlap reorders, never adds — same collective counts as
    # the non-overlapped plan at the same threshold, wire on or off.
    b = batches[0]
    plain = rstep.lower(rs, b).as_text()
    over = wstep.lower(ws, b).as_text()
    for pat in (r"\ball_reduce\b", r"\breduce_scatter\b", r"\ball_gather\b"):
        n_p, n_o = len(re.findall(pat, plain)), len(re.findall(pat, over))
        assert n_p == n_o, (pat, n_p, n_o)
    if zero:
        nb = len(ws.opt_state.plan.buckets)
        assert len(re.findall(r"\breduce_scatter\b", over)) == nb
        # Wire pin: every scatter operand rides bf16; the update gather
        # stays f32 (replicas end bit-identical).
        scatters = re.findall(
            r"stablehlo\.reduce_scatter(?:[^\n]*\n)+?\s*\}\) : \(tensor<([^>]+)>",
            over)
        assert scatters and all(t.endswith("xbf16") for t in scatters), scatters
    else:
        assert len(re.findall(r"optimization_barrier", over)) >= 1
        assert "xbf16" in over  # cast-on-send reached the lowered module
print("overlap smoke OK: bf16-wire overlap matches fp32 within tolerance "
      "on both modes, collective counts unchanged, wire dtype pinned")
EOF

echo "== overlap smoke: env-world plane (tpurun, coordinator bf16 wire) =="
timeout -k 10 300 python -m horovod_tpu.launcher -np 2 --cpu \
  python tests/overlap_worker.py

echo "== perf smoke: bench records overlap/wire knobs + per-phase attribution =="
HVD_BENCH_SMOKE=1 PYTHONPATH= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python bench.py --model resnet50 --overlap --wire-dtype bf16 \
  | tee /tmp/bench_overlap.json
python - <<'EOF'
import json
line = json.loads(open("/tmp/bench_overlap.json").read().strip().splitlines()[-1])
assert line["value"] > 0, f"zero throughput: {line}"
assert line["overlap"] is True, f"overlap knob not recorded: {line}"
assert line["wire_dtype"] == "bf16", f"wire_dtype knob not recorded: {line}"
phases = line.get("phases")
assert phases and "collective_share" in phases and "backward_share" in phases, \
    f"phase attribution block missing: {line}"
print(f"bench overlap smoke OK: {line['value']} {line['unit']}, phases={phases}")
EOF

echo "== perf smoke: bench --zero records the knob + peak bytes =="
HVD_BENCH_SMOKE=1 PYTHONPATH= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python bench.py --model resnet50 --zero | tee /tmp/bench_zero.json
python - <<'EOF'
import json
line = json.loads(open("/tmp/bench_zero.json").read().strip().splitlines()[-1])
assert line["value"] > 0, f"zero throughput: {line}"
assert line["zero"] is True, f"zero knob not recorded: {line}"
print(f"bench --zero smoke OK: {line['value']} {line['unit']}")
EOF

echo "== hybrid smoke: dp×tp ZeRO parity vs 1-D + mesh-reshape restore (ISSUE 8) =="
# ISSUE 8 acceptance: a 3-step (dp=2,tp=2) hybrid run with --zero
# --overlap --wire-dtype bf16 must match the 1-D dp=4 fp32 reference on
# the same global batch within the documented wire tolerance, and a
# (dp=2,tp=2) ZeRO checkpoint must restore-and-resume at (dp=4,tp=2)
# through the unchanged elastic commit (the 2-D canonical form).
run_cpu timeout -k 10 300 python - <<'EOF'
import tempfile
import jax, jax.numpy as jnp, numpy as np, optax
import horovod_tpu as hvd
from horovod_tpu import elastic, training
from horovod_tpu.optimizer import zero_to_canonical
from horovod_tpu.parallel import checkpoint as ckpt, create_hybrid_mesh
from horovod_tpu.parallel.transformer import (TransformerConfig,
                                              make_parallel_train_step)

hvd.init()
cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                        d_ff=64, dtype=jnp.float32,
                        unembed_dtype=jnp.float32, attn_backend="xla")
rng = np.random.RandomState(0)
tokens = jnp.asarray(rng.randint(0, 64, (8, 16)), jnp.int32)
labels = jnp.roll(tokens, -1, axis=1)

def run(mesh, **kw):
    init_state, step = make_parallel_train_step(cfg, mesh,
                                                optax.adam(1e-2), **kw)
    p, o = init_state(jax.random.PRNGKey(3))
    losses = []
    for _ in range(3):
        p, o, loss = step(p, o, tokens, labels)
        losses.append(float(loss))
    return losses, p, o, step

ref_losses, ref_p, _, _ = run(
    create_hybrid_mesh(dp=4, devices=jax.devices()[:4]),
    zero=True, wire_dtype="fp32")
hyb_losses, hyb_p, hyb_o, _ = run(
    create_hybrid_mesh(dp=2, tp=2, devices=jax.devices()[:4]),
    zero=True, overlap=True, wire_dtype="bf16")
np.testing.assert_allclose(hyb_losses, ref_losses, rtol=5e-3)
for a, b in zip(jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, hyb_p)),
        jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, ref_p))):
    np.testing.assert_allclose(a, b, rtol=5e-2, atol=4e-2)

d = tempfile.mkdtemp()
es = elastic.ElasticState(hyb_p, hyb_o, step=3, directory=d,
                          commit_every=1)
path = es.commit()
assert ckpt.verify_checkpoint(path) is True
canon = jax.tree_util.tree_map(np.asarray,
                               zero_to_canonical(hyb_o).inner)
mesh2 = create_hybrid_mesh(dp=4, tp=2)
init2, step2 = make_parallel_train_step(cfg, mesh2, optax.adam(1e-2),
                                        zero=True)
p2, o2 = init2(jax.random.PRNGKey(9))
assert o2.plan.nshards == 4
es2 = elastic.ElasticState(p2, o2, directory=d)
es2.restore()
assert es2.step == 3, es2.step
for a, b in zip(jax.tree_util.tree_leaves(jax.tree_util.tree_map(
        np.asarray, zero_to_canonical(es2.opt_state).inner)),
        jax.tree_util.tree_leaves(canon)):
    np.testing.assert_array_equal(a, b)
p3, o3, loss3 = step2(es2.params, es2.opt_state, tokens, labels)
assert np.isfinite(float(loss3))
print(f"hybrid smoke OK: (dp=2,tp=2) zero+overlap+bf16 matches dp=4 fp32 "
      f"over 3 steps, (2,2)->(4,2) restore bit-exact and resumed "
      f"(loss {float(loss3):.4f})")
EOF

echo "== perf smoke: bench records the tp/mesh knobs on the hybrid line =="
HVD_BENCH_SMOKE=1 PYTHONPATH= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python bench.py --model transformer_lm --tp 2 --zero \
  | tee /tmp/bench_hybrid.json
python - <<'EOF'
import json
line = json.loads(open("/tmp/bench_hybrid.json").read().strip().splitlines()[-1])
assert line["value"] > 0, f"zero throughput: {line}"
assert line["tp"] == 2, f"tp knob not recorded: {line}"
assert line["mesh"] == "dp4,tp2", f"mesh knob not recorded: {line}"
assert line["zero"] is True, f"zero knob not recorded: {line}"
print(f"bench hybrid smoke OK: {line['value']} {line['unit']} @ {line['mesh']}")
EOF

echo "== 3-D smoke: dp×tp×pp pipelined train vs pure-dp reference (ISSUE 20) =="
# ISSUE 20 acceptance: a 3-step (dp=2,tp=2,pp=2) pipelined run with
# --overlap --wire-dtype bf16 must match the dp=8 fp32 reference (the
# NON-pipelined family, same global weights grafted across layouts)
# within the documented wire tolerance — every gradient plane
# interpreting the one spec-grouped GradSync plan.
run_cpu timeout -k 10 300 python - <<'EOF'
import jax, jax.numpy as jnp, numpy as np, optax
from horovod_tpu.parallel import create_hybrid_mesh
from horovod_tpu.parallel.pp_transformer import make_pp_transformer_train_step
from horovod_tpu.parallel.transformer import (TransformerConfig,
                                              make_parallel_train_step)

cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                        d_ff=64, dtype=jnp.float32,
                        unembed_dtype=jnp.float32, attn_backend="xla")
rng = np.random.RandomState(0)
tokens = jnp.asarray(rng.randint(0, 64, (8, 16)), jnp.int32)
labels = jnp.roll(tokens, -1, axis=1)

mesh3d = create_hybrid_mesh(dp=2, tp=2, pp=2)
init3d, step3d = make_pp_transformer_train_step(
    cfg, mesh3d, optax.adam(1e-2), n_microbatches=2,
    overlap=True, wire_dtype="bf16")
p, o = init3d(jax.random.PRNGKey(3))
src = jax.tree_util.tree_map(np.asarray, p)
losses3d = []
for _ in range(3):
    p, o, loss = step3d(p, o, tokens, labels)
    losses3d.append(float(loss))

# Same global weights on the dp=8 reference: unstack the [S, lps, ...]
# stage layout into the per-layer list the core family carries.
lps = cfg.n_layers // 2
flat = {"embed": src["embed"], "lnf": src["lnf"],
        "layers": [{k: src["stages"][k][s, i] for k in src["stages"]}
                   for s in range(2) for i in range(lps)]}
init8, step8 = make_parallel_train_step(cfg, create_hybrid_mesh(dp=8),
                                        optax.adam(1e-2))
p8, o8 = init8(jax.random.PRNGKey(9))
p8 = jax.tree_util.tree_map(
    lambda tpl, v: jax.device_put(jnp.asarray(v), tpl.sharding), p8, flat)
losses8 = []
for _ in range(3):
    p8, o8, loss = step8(p8, o8, tokens, labels)
    losses8.append(float(loss))
np.testing.assert_allclose(losses3d, losses8, rtol=5e-3)

ref = jax.tree_util.tree_map(np.asarray, p8)
back = {"embed": ref["embed"], "lnf": ref["lnf"],
        "stages": {k: np.stack([np.stack(
            [ref["layers"][s * lps + i][k] for i in range(lps)])
            for s in range(2)]) for k in src["stages"]}}
for a, b in zip(jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, p)),
        jax.tree_util.tree_leaves(back)):
    np.testing.assert_allclose(a, b, rtol=5e-2, atol=4e-2)
print(f"3-D smoke OK: (dp=2,tp=2,pp=2) overlap+bf16 matches dp=8 fp32 "
      f"over 3 steps (final loss {losses3d[-1]:.4f})")
EOF

echo "== plan smoke: env-world wires exactly the stamped plan's bytes (tpurun) =="
timeout -k 10 300 python -m horovod_tpu.launcher -np 2 --cpu \
  python tests/plan_worker.py

echo "== perf smoke: bench records the pp/mesh knobs on the pipelined line =="
HVD_BENCH_SMOKE=1 PYTHONPATH= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python bench.py --model transformer_lm --mesh dp=2,tp=2,pp=2 \
  | tee /tmp/bench_3d.json
python - <<'EOF'
import json
line = json.loads(open("/tmp/bench_3d.json").read().strip().splitlines()[-1])
assert line["value"] > 0, f"zero throughput: {line}"
assert line["tp"] == 2 and line["pp"] == 2, f"mesh knobs not recorded: {line}"
assert line["mesh"] == "dp2,tp2,pp2", f"mesh desc wrong: {line}"
assert line["ep"] == 1, f"ep field missing: {line}"
print(f"bench 3-D smoke OK: {line['value']} {line['unit']} @ {line['mesh']}")
EOF

# Final sweep: launcher legs above write flight-recorder dumps into the
# repo root when they die mid-drill; a leftover would be committed by the
# next contributor's `git add -A`.
rm -f hvd_flightrec.rank*.json

echo "CI OK"
