#!/usr/bin/env python
"""Load generator for :mod:`horovod_tpu.serve` — the latency/throughput
curve behind the serving numbers in ``docs/inference.md``.

Open-loop (arrival times are scheduled at the target rate regardless of
completion — closed-loop generators hide overload by self-throttling,
the classic coordinated-omission trap), mixed request sizes, per-request
deadline. For each target QPS it reports achieved throughput, e2e
latency p50/p99, batch-fill ratio, and the two drop classes the
backpressure contract distinguishes (overload rejects vs deadline
expiries).

    JAX_PLATFORMS=cpu python bin/serve_bench.py --qps 200 --duration 5
    python bin/serve_bench.py --qps 50,100,200,400 --duration 10  # curve

``--mode generate`` drives the continuous-batching generation engine
instead (a small transformer LM, mixed prompt lengths): per operating
point it reports p50/p99 **time-to-first-token**, per-user and aggregate
tokens/sec, and decode-slot occupancy — and prints one JSON line per
point (``peak_bytes_per_chip`` from the same ``memory_stats`` probe
``bench.py`` uses, KV-cache bytes, peak concurrent streams, block-pool
and prefix-cache gauges) so the fixed-HBM capacity claims are checkable
from the bench row. ``--json FILE`` additionally appends the lines to a
file (the ci.sh capacity/prefix legs parse it).

    JAX_PLATFORMS=cpu python bin/serve_bench.py --mode generate \
        --qps 20 --duration 5

``--kv-layout paged`` (with ``--block-size``/``--n-blocks``/
``--prefix-reuse``/``--prefix-tokens``) serves the paged KV cache;
``--cache-mb`` fixes the KV-cache byte budget and derives the layout's
capacity from it (contiguous: slots = budget ÷ full-depth reservation;
paged: pool = budget ÷ block bytes, slots = what the pool can hold of
typical requests) — the concurrent-streams-capacity comparison at equal
cache bytes.

``--adapters N`` serves multi-tenant traffic: N seeded LoRA fine-tunes
(tenants ``a0..aN-1``) loaded next to the ``base`` model, arrivals drawn
per ``--adapter-mix`` weights from per-tenant deterministic prompt
streams. EVERY generate-mode JSON line then stamps the adapter fields
(``adapters``, ``adapter_mix``, ``tenant_sent``/``tenant_completed``)
and a per-tenant ``stream_digests`` map extending the PR-11 digest —
``--adapter-only TENANT`` replays the SAME arrival schedule submitting
only that tenant's requests, so ci.sh can pin each tenant's mixed-batch
digest against its single-tenant reference run.

``--replicas N`` serves the generate load through a ``FleetRouter`` of
N engine replicas (least-depth dispatch, one front door); adding
``--autoscale`` starts at ``--min-replicas`` and lets the queue-depth
``FleetAutoscaler`` grow toward N under load and drain-shrink back when
traffic stops. Fleet runs append the per-point rows PLUS one final
``{"fleet": true, ...}`` summary line (scale events, final membership,
dispatch split, lost streams) — the ci.sh closed-loop autoscaler drill
asserts grow >= 1, shrink back to the floor, zero lost streams, and a
``stream_digest`` identical to the single-replica run of the same
seeded traffic.

``--chaos CLAUSE`` arms the serving-plane fault injector
(``testing/faults.py``) for the run: ``replica_kill=r1@stream=3`` kills
replica r1's engine loop at its 3rd admitted stream,
``replica_hang=...`` wedges it instead, ``slow_step=MS`` slows every
decode iteration. With ``--replicas N`` the FleetRouter's deterministic
failover must then resume every stranded stream bit-identically — the
ci.sh serving chaos drill compares the per-tenant ``stream_digests``
against an unkilled single-replica reference and asserts
``failover.resumed >= 1`` with zero lost streams. ``--temperature`` /
``--top-k`` switch the traffic to seeded sampling (per-request seeds
are a pure function of the tenant + arrival index, so digests stay
run-to-run comparable) — failover bit-identity is pinned for greedy
AND sampled streams.

Exit status is nonzero if any *in-deadline* request was dropped at the
configured operating point — the regression gate ci.sh's serve smokes
rely on (the generate smoke additionally requires nonzero tokens/sec).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _percentile(xs, q):
    return float(np.percentile(xs, q * 100)) if xs else float("nan")


def _peak_bytes_per_chip():
    """Per-chip peak HBM bytes from the runtime's allocator stats, or
    None where the backend keeps none (CPU) — the same probe bench.py
    records, so the fixed-HBM capacity claim is checkable from the JSON
    row."""
    import jax
    try:
        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 — stats are best-effort telemetry
        return None
    if not stats:
        return None
    peak = stats.get("peak_bytes_in_use")
    return int(peak) if peak is not None else None


# The generate-mode bench model (vocab/d_model/heads/layers below):
# bytes per cached token position = 2 (K and V) · n_layers · d_model · 4
# (f32) — the unit both layouts' capacity math is written in.
_GEN_MODEL = dict(vocab=256, d_model=64, n_heads=4, n_layers=2, d_ff=128)
_GEN_BYTES_PER_TOKEN = 2 * _GEN_MODEL["n_layers"] * _GEN_MODEL["d_model"] * 4


def _gen_model(args):
    """Bench model dims. ``--model-dim`` widens the model (d_ff = 2·d)
    so a drill can sit in the regime the KV hierarchy is built for:
    prefill compute per chunk much larger than a block copy, as on a
    real accelerator. Default keeps the historical tiny model."""
    d = int(getattr(args, "model_dim", 0) or 0)
    if not d:
        return dict(_GEN_MODEL)
    return dict(vocab=256, d_model=d, n_heads=4, n_layers=2, d_ff=2 * d)


def _gen_bpt(args):
    m = _gen_model(args)
    return 2 * m["n_layers"] * m["d_model"] * 4


def _gen_capacity(args):
    """Resolve (max_slots, n_blocks, cache_bytes) for the generate
    engine. With ``--cache-mb`` the budget is FIXED and capacity derives
    from the layout — the whole point of the paged comparison:

    * contiguous: each slot reserves ``max_len`` positions, so
      slots = budget // (max_len · bytes/token);
    * paged: the pool is budget // (block_size · bytes/token) blocks —
      the reserved trash block is charged AGAINST the budget (usable
      capacity is one block less), not added on top — and slots = how
      many TYPICAL requests (longest bench prompt + generated tokens)
      the usable pool holds, capped at 64 so the decode program stays
      small on a CPU host.
    """
    if not args.cache_mb:
        n_blocks = args.n_blocks if args.n_blocks else None
        return args.slots, n_blocks, None
    bpt = _gen_bpt(args)
    budget = int(args.cache_mb * 2 ** 20)
    if args.kv_layout == "contiguous":
        slots = max(1, budget // (args.max_len * bpt))
        return slots, None, slots * args.max_len * bpt
    block_bytes = args.block_size * bpt
    n_blocks = max(2, budget // block_bytes)
    # Typical request: the longest bench prompt (prefix + 16) plus the
    # generated tokens (the last sampled token needs no cache write).
    typical = args.prefix_tokens + 16 + args.gen_tokens - 1
    per_req = -(-typical // args.block_size)
    slots = max(1, min(64, (n_blocks - 1) // per_req))
    return slots, n_blocks, n_blocks * block_bytes


def _build_engine(args):
    import jax
    import flax.linen as nn

    from horovod_tpu import serve

    class _BenchMLP(nn.Module):
        """Small but not trivial: two matmuls deep enough that XLA_EXECUTE
        is visible on the timeline, small enough that a laptop CPU clears
        hundreds of QPS — the bench measures the serving plane, not the
        model."""

        @nn.compact
        def __call__(self, x, train=False):
            x = nn.Dense(256)(x)
            x = nn.relu(x)
            x = nn.Dense(256)(x)
            x = nn.relu(x)
            return nn.Dense(10)(x)

    model = _BenchMLP()
    item_shape = (args.features,)
    variables = model.init(jax.random.PRNGKey(0),
                           np.zeros((1,) + item_shape, np.float32))
    cfg = serve.ServeConfig(max_batch=args.max_batch,
                            batch_timeout_ms=args.batch_timeout_ms,
                            max_queue=args.max_queue,
                            default_deadline_ms=args.deadline_ms)
    eng = serve.Engine(lambda v, x: model.apply(v, x, train=False),
                       variables, item_shape=item_shape, config=cfg)
    t0 = time.monotonic()
    eng.warmup()
    print(f"warmup: {len(serve.bucket_sizes(args.max_batch))} buckets "
          f"pre-compiled in {time.monotonic() - t0:.2f} s")
    return eng


def _bench_tenants(args):
    """Tenant names + normalized arrival weights for this run:
    ``base`` plus ``a0..aN-1`` (uniform unless ``--adapter-mix``)."""
    tenants = ["base"] + [f"a{i}" for i in range(args.adapters)]
    if args.adapter_mix:
        weights = [float(w) for w in args.adapter_mix.split(",")]
        if len(weights) != len(tenants) or any(w < 0 for w in weights) \
                or not sum(weights) > 0:
            raise SystemExit(
                f"--adapter-mix needs {len(tenants)} non-negative "
                f"comma-separated weights (base first, then "
                f"{tenants[1:]}), got {args.adapter_mix!r}")
    else:
        weights = [1.0] * len(tenants)
    total = sum(weights)
    return tenants, [w / total for w in weights]


def _parse_tenant_map(spec: str, what: str, cast):
    """``"base:1,a0:4"`` → ``{"base": cast("1"), "a0": cast("4")}`` —
    the shared parser behind --tenant-weights / --priority-mix /
    --tenant-slo-ms. Raises SystemExit with a usable message (argparse
    p.error re-raises it) on malformed pairs."""
    out = {}
    if not spec:
        return out
    for pair in spec.split(","):
        name, sep, val = pair.partition(":")
        name = name.strip()
        if not sep or not name:
            raise SystemExit(
                f"{what} must be comma-separated tenant:value pairs "
                f"(e.g. 'base:1,a0:4'), got {pair!r}")
        try:
            out[name] = cast(val)
        except ValueError:
            raise SystemExit(f"{what}: bad value {val!r} for {name!r}")
    return out


def _bench_adapters(args, cfg):
    """The run's LoRA plane: (lora_cfg, {name: host adapter tree}) —
    seeded, B randomized so the M tenants are genuinely DISTINCT
    fine-tunes (distinct streams, checkable digests)."""
    if not args.adapters:
        return None, None
    import jax

    from horovod_tpu.parallel.lora import LoraConfig, init_adapter
    lora = LoraConfig(rank=args.adapter_rank)
    trees = {f"a{i}": init_adapter(jax.random.PRNGKey(100 + i), cfg,
                                   lora, b_scale=0.5)
             for i in range(args.adapters)}
    return lora, trees


def _build_gen_engine(args):
    import jax
    import jax.numpy as jnp

    from horovod_tpu.parallel.transformer import (TransformerConfig,
                                                  init_params)
    from horovod_tpu import serve

    # Small but real: the bench measures the serving plane (slot churn,
    # prefill/decode interleave, streaming), not model quality.
    cfg = TransformerConfig(**_gen_model(args), dtype=jnp.float32,
                            unembed_dtype=jnp.float32, attn_backend="xla")
    params = init_params(jax.random.PRNGKey(0), cfg)
    slots, n_blocks, cache_bytes = _gen_capacity(args)
    gcfg = serve.GenerationConfig(
        max_slots=slots, max_len=args.max_len,
        max_queue=args.max_queue, default_deadline_ms=args.deadline_ms,
        default_max_new_tokens=args.gen_tokens,
        kv_layout=args.kv_layout,
        # SLO-aware multi-tenancy knobs (empty maps = neutral policy;
        # GenerationConfig treats None and absent alike). These are
        # plain JSON-able dicts, so subprocess replica specs carry them
        # through dataclasses.asdict(gcfg) unchanged.
        **({"tenant_weights": args.tenant_weights_map}
           if args.tenant_weights_map else {}),
        **({"tenant_priorities": args.priority_mix_map}
           if args.priority_mix_map else {}),
        **({"tenant_slo_ttft_ms": args.tenant_slo_ms_map}
           if args.tenant_slo_ms_map else {}),
        preempt_retries=args.preempt_retries,
        **({"block_size": args.block_size, "n_blocks": n_blocks,
            "prefix_reuse": args.prefix_reuse,
            "paged_kernel": args.paged_kernel,
            "chunked_prefill": args.chunked_prefill,
            "chunk_blocks": args.chunk_blocks,
            "host_blocks": args.host_blocks,
            "host_admission": args.host_admission}
           if args.kv_layout == "paged" else {}))
    if cache_bytes is None:
        if args.kv_layout == "paged":
            cache_bytes = (gcfg.resolved_n_blocks * gcfg.block_size
                           * _gen_bpt(args))
        else:
            cache_bytes = slots * args.max_len * _gen_bpt(args)
    lora, adapter_trees = _bench_adapters(args, cfg)
    spec_cfg = serve.SpecConfig(k=args.spec_k) if args.spec_k else None

    def _registry():
        if not adapter_trees:
            return None
        reg = serve.AdapterRegistry(cfg, lora,
                                    capacity=len(adapter_trees))
        for name, tree in sorted(adapter_trees.items()):
            reg.load(name, tree)
        return reg

    if args.replicas > 1 or args.autoscale or args.replica_procs:
        # Fleet mode: N replicas (each its own slots/block pool — and
        # its own adapter table — over the SHARED read-only params)
        # behind one FleetRouter. --autoscale starts at --min-replicas
        # and lets the queue-depth control loop grow toward --replicas;
        # static fleets warm all N up front. --replica-procs swaps the
        # thread-engine factory for subprocess workers — each child
        # re-derives the SAME params from the spec's seed, so stream
        # digests stay comparable across topologies.
        if args.replica_procs:
            import dataclasses
            spec = {
                "model": dict(_gen_model(args), dtype="float32",
                              unembed_dtype="float32",
                              attn_backend="xla"),
                "seed": 0,
                "generation": dataclasses.asdict(gcfg),
            }
            if spec_cfg is not None:
                spec["spec"] = spec_cfg.to_spec()
            if adapter_trees:
                # Seeds, not bytes: each child re-derives the SAME
                # trees _bench_adapters built here (PRNGKey(100+i),
                # b_scale=0.5), so per-tenant digests stay comparable
                # across thread and subprocess topologies.
                spec["adapters"] = {
                    "rank": args.adapter_rank, "alpha": lora.alpha,
                    "capacity": len(adapter_trees),
                    "entries": [{"name": f"a{i}", "seed": 100 + i,
                                 "b_scale": 0.5}
                                for i in range(args.adapters)],
                }
            factory = serve.spawn_replica_factory(spec)
        else:
            factory = lambda name: serve.GenerationEngine(  # noqa: E731
                params, cfg, gcfg, adapters=_registry(), spec=spec_cfg)
        initial = args.min_replicas if args.autoscale else args.replicas
        eng = serve.FleetRouter(
            factory=factory, initial=initial,
            # Subprocess children boot with EVERY tenant resident (the
            # spec carries them), so the lazy-load path has nothing to
            # do — and couldn't ship a host tree over HTTP anyway.
            adapter_source=(adapter_trees.__getitem__
                            if adapter_trees and not args.replica_procs
                            else None))
        eng.bench_cache_bytes = cache_bytes    # per REPLICA (pool grows
        t0 = time.monotonic()                  # with the fleet)
        warmed = eng.warmup()
        print(f"warmup [{args.kv_layout}, fleet {len(warmed)} replica(s) "
              f"x slots={slots}]: pre-compiled in "
              f"{time.monotonic() - t0:.2f} s")
        if args.autoscale:
            eng.bench_autoscaler = serve.FleetAutoscaler(
                eng, min_replicas=args.min_replicas,
                max_replicas=args.replicas,
                high_watermark=args.scale_high,
                low_watermark=args.scale_low,
                breach_up=2, breach_down=2,
                cooldown_s=1.0, interval_s=0.25).start()
        return eng
    eng = serve.GenerationEngine(params, cfg, gcfg, adapters=_registry(),
                                 spec=spec_cfg)
    eng.bench_cache_bytes = cache_bytes      # stamped into the JSON rows
    t0 = time.monotonic()
    warmed = eng.warmup()
    n_verify = sum(1 for k in warmed
                   if isinstance(k, tuple) and k and k[0] == "verify")
    print(f"warmup [{args.kv_layout}, slots={slots}]: decode + "
          f"{len(warmed) - 1 - n_verify} prefill buckets"
          f"{f' + {n_verify} verify' if n_verify else ''} "
          f"pre-compiled in {time.monotonic() - t0:.2f} s")
    return eng


def _stream_digest(streams):
    import hashlib
    return hashlib.sha256(repr(sorted(streams)).encode()).hexdigest()


def run_gen_point(eng, qps: float, duration: float,
                  rng: np.random.RandomState, args) -> tuple:
    """One generation operating point: open-loop prompt arrivals; TTFT
    and per-user tokens/sec come from the engine-stamped result dicts
    (submit → first token / first → last token). ``--prefix-tokens N``
    prepends a fixed N-token system prompt to every request (the
    traffic-class shape ``--prefix-reuse`` amortizes).

    Multi-tenant runs (``--adapters N``) draw each arrival's tenant from
    the ``--adapter-mix`` weights with a DEDICATED selection RNG and its
    prompt from a per-tenant seeded RNG — so tenant ``t``'s k-th request
    is identical in every run of the same knobs, whatever the other
    tenants did. ``--adapter-only t`` replays the same schedule but
    submits only ``t``'s requests: the single-tenant reference whose
    per-tenant digest a mixed run must match. Returns
    ``(row, streams_by_tenant)``."""
    from horovod_tpu.exceptions import (DeadlineExceededError,
                                        ServerOverloadedError)
    gen0 = eng.stats().get("generation") or {}
    n = max(1, int(qps * duration))
    period = 1.0 / qps
    # Deterministic across runs and independent of the arrival RNG, so
    # reuse-on vs reuse-off runs see the SAME system prompt.
    # --prefix-count rotates round-robin over K distinct prefixes (the
    # first one keeps the historical seed, so count=1 digests are
    # unchanged); K long prefixes make the registered working set
    # exceed a tight device pool and exercise offload/prefetch.
    sys_prefixes = [np.random.RandomState(1234 if j == 0 else 4100 + j)
                    .randint(1, 255, size=args.prefix_tokens).tolist()
                    for j in range(max(1, args.prefix_count))]
    # --prefix-mix: which arrivals carry the shared system prompt. A
    # DEDICATED seeded RNG, drawn every arrival regardless of the
    # verdict, so the tenant/prompt streams (and their digests) are
    # identical across mix settings.
    mix_rng = np.random.RandomState(97)
    tenants, weights = _bench_tenants(args)
    # Tenant selection and per-tenant prompts ride their own RNGs; the
    # base-only path keeps drawing prompts from the caller's rng so the
    # single-tenant digests of existing ci legs are unchanged.
    pick_rng = np.random.RandomState(4321)
    prompt_rngs = ({"base": rng} if len(tenants) == 1
                   else {t: np.random.RandomState(7000 + i)
                         for i, t in enumerate(tenants)})
    handles = []
    overload = 0
    sent_by_tenant = {t: 0 for t in tenants}
    shared_sent = 0
    seen_prefixes = set()
    start = time.monotonic()
    for i in range(n):
        delay = start + i * period - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        t = (tenants[0] if len(tenants) == 1
             else tenants[pick_rng.choice(len(tenants), p=weights)])
        trng = prompt_rngs[t]
        draw = mix_rng.random_sample()
        shared = args.prefix_tokens > 0 and draw < args.prefix_mix
        pfx_idx = shared_sent % len(sys_prefixes)
        if shared:
            shared_sent += 1
        prompt = (sys_prefixes[pfx_idx] if shared else []) + trng.randint(
            1, 255, size=trng.randint(4, 17)).tolist()
        if args.adapter_only and t != args.adapter_only:
            continue        # reference run: same schedule, one tenant
        # Hit-vs-cold TTFT split: the FIRST shared-prefix arrival of
        # the point pays the cold prefill (it registers the prefix);
        # later shared arrivals should prefill only their suffix. A
        # second operating point on the same engine inherits the
        # registry, so its "cold" sample is really a hit — the split is
        # a smoke number; prefix_hit_rate is the precise check.
        cls = "cold"
        if shared:
            cls = "cold" if pfx_idx not in seen_prefixes else "hit"
            seen_prefixes.add(pfx_idx)
        sent_by_tenant[t] += 1
        try:
            kw = {} if t == "base" else {"adapter": t}
            if args.temperature > 0:
                # Seeded sampling: the seed is a pure function of the
                # tenant and its arrival index, so the k-th request of
                # tenant t samples the SAME stream in every run of the
                # same knobs — sampled digests stay as comparable across
                # runs (and across failover replays) as greedy ones.
                from horovod_tpu.serve import SamplingParams
                kw["sampling"] = SamplingParams(
                    temperature=args.temperature, top_k=args.top_k,
                    seed=9000 + 131 * tenants.index(t) + sent_by_tenant[t])
            handles.append((t, cls, eng.submit(prompt, **kw)))
        except ServerOverloadedError:
            overload += 1
    ttft_ms, tps_user, tokens_out = [], [], 0
    ttft_cls = {"hit": [], "cold": []}
    expired, failed = 0, 0
    streams = []
    streams_by_tenant = {t: [] for t in tenants}
    done_by_tenant = {t: 0 for t in tenants}
    ttft_by_tenant = {t: [] for t in tenants}
    for t, cls, h in handles:
        try:
            r = h.result(timeout=120)
            ttft_ms.append(r["ttft_ms"])
            ttft_by_tenant[t].append(r["ttft_ms"])
            ttft_cls[cls].append(r["ttft_ms"])
            tokens_out += r["n_tokens"]
            streams.append(tuple(r["tokens"]))
            streams_by_tenant[t].append(tuple(r["tokens"]))
            done_by_tenant[t] += 1
            if r["tokens_per_sec"] is not None:
                tps_user.append(r["tokens_per_sec"])
        except DeadlineExceededError:
            expired += 1
        except Exception:
            failed += 1
    wall = time.monotonic() - start
    snap = eng.stats()
    # Completion-order-free digest of every completed stream: identical
    # prompts + greedy sampling must give an identical digest whatever
    # the batch composition was — the ci.sh prefix-reuse leg pins
    # reuse-on == reuse-off through this field.
    digest = _stream_digest(streams)
    gen = snap["generation"]
    row = {
        "qps_target": qps,
        # The requests actually SUBMITTED (an --adapter-only reference
        # run skips other tenants' arrivals by design).
        "sent": sum(sent_by_tenant.values()),
        "completed": len(ttft_ms),
        "ttft_p50_ms": _percentile(ttft_ms, 0.50),
        "ttft_p99_ms": _percentile(ttft_ms, 0.99),
        "tokens_per_sec": tokens_out / wall,
        "tps_user_p50": _percentile(tps_user, 0.50),
        "overload_drops": overload,
        "deadline_drops": expired,
        "failed": failed,
        "slot_fill": snap["batch_fill_ratio"],
        # Capacity / memory telemetry (the fixed-HBM claims):
        "kv_layout": snap["kv_layout"],
        "max_slots": snap["max_slots"],
        "max_len": snap["max_len"],
        "cache_bytes": getattr(eng, "bench_cache_bytes", None),
        "peak_concurrent_streams": snap["peak_active_slots"],
        "peak_bytes_per_chip": _peak_bytes_per_chip(),
        "rejected_slots_full": snap["rejected_slots_full"],
        "rejected_blocks_exhausted": snap["rejected_blocks_exhausted"],
        "prefix_hits_total": gen["prefix_hits_total"],
        "prefix_misses_total": gen["prefix_misses_total"],
        "prefix_hit_blocks_total": gen["prefix_hit_blocks_total"],
        # KV memory hierarchy (chunked prefill + host tier): the
        # per-point hit rate from the counter DELTAS (the cumulative
        # totals above smear points), the hit-vs-cold TTFT split of
        # THIS point's completed requests, and the tier traffic. None
        # where a class saw no completion (json-clean, never NaN).
        "prefix_mix": args.prefix_mix,
        "prefix_count": max(1, args.prefix_count),
        "prefix_hit_rate": (
            lambda h, m: (h / (h + m)) if (h + m) > 0 else None)(
                gen["prefix_hits_total"]
                - gen0.get("prefix_hits_total", 0),
                gen["prefix_misses_total"]
                - gen0.get("prefix_misses_total", 0)),
        "ttft_hit_p50_ms": (_percentile(ttft_cls["hit"], 0.50)
                            if ttft_cls["hit"] else None),
        "ttft_cold_p50_ms": (_percentile(ttft_cls["cold"], 0.50)
                             if ttft_cls["cold"] else None),
        "chunked_prefill": bool(snap.get("chunked_prefill", False)),
        "host_blocks": args.host_blocks,
        "kv_offload_blocks_total": gen.get("kv_offload_blocks_total", 0),
        "kv_prefetch_blocks_total": gen.get("kv_prefetch_blocks_total", 0),
        "prefill_chunks_total": gen.get("prefill_chunks_total", 0),
        "prefill_chunks_skipped_total":
            gen.get("prefill_chunks_skipped_total", 0),
        "last_prefill_bucket": snap.get("last_prefill_bucket"),
        "stream_digest": digest,
        # Multi-tenant adapter fields — stamped in EVERY generate row
        # (zeros/base-only when --adapters is off) so a consumer never
        # key-errors across operating modes.
        "adapters": args.adapters,
        "adapter_mix": dict(zip(tenants, weights)),
        "adapter_only": args.adapter_only or None,
        # Traffic shape + injected faults + replica topology, so a
        # digest-bearing row is self-describing about what produced it
        # (cross-topology digest comparison = grep topology + digest).
        "temperature": args.temperature,
        "chaos": args.chaos or None,
        "topology": "process" if args.replica_procs else "thread",
        "tenant_sent": sent_by_tenant,
        "tenant_completed": done_by_tenant,
        # Bench-side per-tenant TTFT percentiles (of THIS point's
        # completions — the engine's snapshot percentiles are
        # engine-lifetime and, in fleet mode, per-replica): the numbers
        # the ci.sh starvation drill bounds for the quiet tenant.
        "tenant_ttft_ms": {
            t: {"p50": _percentile(xs, 0.50), "p99": _percentile(xs, 0.99)}
            for t, xs in ttft_by_tenant.items() if xs},
        "stream_digests": {t: _stream_digest(s)
                           for t, s in streams_by_tenant.items()},
        "rejected_tenant_quota": snap.get("rejected_tenant_quota", 0),
        "tenants": snap.get("tenants") or {},
        # SLO-aware multi-tenancy fields — stamped in EVERY generate row
        # (zeros / empty maps when the knobs are off) so consumers never
        # key-error across modes. Preemption counters are cumulative
        # over the engine's life, like the prefix counters above.
        "tenant_weights": args.tenant_weights_map or {},
        "priority_mix": args.priority_mix_map or {},
        "tenant_slo_ms": args.tenant_slo_ms_map or {},
        "preemptions": gen.get("preemptions_total", 0),
        "preempt_resumed": gen.get("preempt_resumed_total", 0),
        "preempt_exhausted": gen.get("preempt_exhausted_total", 0),
        # Speculative-decoding fields — stamped in EVERY generate row
        # (k=0 / None ratios when --spec-k is off) so consumers never
        # key-error across modes. Cumulative over the engine's life,
        # like the prefix counters above.
        "spec_k": int(snap.get("spec_k") or 0),
        "spec_accept_rate": (snap.get("spec") or {}).get("accept_rate"),
        "tokens_per_step": (snap.get("spec") or {}).get("tokens_per_step"),
    }
    if snap.get("adapters_resident") is not None:
        row["adapters_resident"] = snap["adapters_resident"]
    if snap["kv_layout"] == "paged" and "block_size" in snap:
        row["block_size"] = snap["block_size"]
        row["blocks"] = snap.get("blocks")
    if "fleet" in snap:
        # Fleet rows: membership and the autoscaler's decisions AT ROW
        # END (cumulative), so a spike row shows the grow it caused.
        row["replicas_ready"] = snap["fleet"]["n_ready"]
        row["replicas"] = snap["fleet"]["replicas"]
        row["scale_events"] = snap["fleet"]["scale_events"]
        row["dispatch"] = snap["fleet"]["dispatch_total"]
        row["failover"] = snap["fleet"]["failover_total"]
        row["stranded"] = snap["fleet"]["streams_stranded_total"]
        if "adapter_dispatch" in snap["fleet"]:
            row["adapter_dispatch"] = snap["fleet"]["adapter_dispatch"]
        if "prefix_dispatch" in snap["fleet"]:
            row["prefix_dispatch"] = snap["fleet"]["prefix_dispatch"]
    return row, streams_by_tenant


def run_point(eng, qps: float, duration: float, rng: np.random.RandomState,
              item_shape) -> dict:
    """Drive one operating point; returns its row of the curve."""
    from horovod_tpu.exceptions import (DeadlineExceededError,
                                        ServerOverloadedError)
    snap0 = eng.stats()
    n = max(1, int(qps * duration))
    period = 1.0 / qps
    futures = []
    overload = 0
    start = time.monotonic()
    for i in range(n):
        due = start + i * period
        delay = due - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        x = rng.randn(*item_shape).astype(np.float32)
        try:
            fut = eng.submit(x)
            # Stamp completion ON the done callback — collecting results
            # after the send loop would otherwise credit early responses
            # with the whole send phase's wall time.
            fut.t_done = None
            fut.add_done_callback(
                lambda f, t=time.monotonic: setattr(f, "t_done", t()))
            futures.append((fut, time.monotonic()))
        except ServerOverloadedError:
            overload += 1
    lat_ms, expired, failed = [], 0, 0
    for fut, t_sub in futures:
        try:
            fut.result(timeout=60)
            # result() can return a hair before the done callback fires
            # (set_result notifies waiters under the lock, runs callbacks
            # after releasing it) — give the stamp a moment before
            # falling back to now (the fallback smears by microseconds).
            for _ in range(1000):
                if fut.t_done is not None:
                    break
                time.sleep(0)
            lat_ms.append(((fut.t_done or time.monotonic()) - t_sub) * 1e3)
        except DeadlineExceededError:
            expired += 1
        except Exception:
            failed += 1
    wall = time.monotonic() - start
    snap = eng.stats()
    d_rows = snap["batch_rows_total"] - snap0["batch_rows_total"]
    d_live = (snap["batch_live_rows_total"]
              - snap0["batch_live_rows_total"])
    return {
        "qps_target": qps,
        "qps_achieved": len(lat_ms) / wall,
        "sent": n,
        "completed": len(lat_ms),
        "p50_ms": _percentile(lat_ms, 0.50),
        "p99_ms": _percentile(lat_ms, 0.99),
        "overload_drops": overload,
        "deadline_drops": expired,
        "failed": failed,
        "batch_fill": (d_live / d_rows) if d_rows else None,
    }


def main():
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--mode", choices=("predict", "generate"),
                   default="predict",
                   help="predict: single-shot Engine; generate: the "
                        "continuous-batching GenerationEngine")
    p.add_argument("--qps", default="200",
                   help="target request rate; comma-separate for a curve")
    p.add_argument("--duration", type=float, default=5.0,
                   help="seconds per operating point")
    p.add_argument("--features", type=int, default=64)
    p.add_argument("--max-batch", type=int, default=32)
    p.add_argument("--batch-timeout-ms", type=float, default=5.0)
    p.add_argument("--max-queue", type=int, default=512)
    p.add_argument("--deadline-ms", type=float, default=1000.0,
                   help="per-request deadline (0 disables)")
    p.add_argument("--slots", type=int, default=8,
                   help="[generate] concurrent decode slots")
    p.add_argument("--max-len", type=int, default=128,
                   help="[generate] KV-cache depth (prompt + generated)")
    p.add_argument("--gen-tokens", type=int, default=16,
                   help="[generate] tokens generated per request")
    p.add_argument("--kv-layout", choices=("contiguous", "paged"),
                   default="contiguous",
                   help="[generate] KV-cache layout: per-slot max_len "
                        "reservation vs block-table paging")
    p.add_argument("--block-size", type=int, default=16,
                   help="[generate, paged] positions per KV block")
    p.add_argument("--n-blocks", type=int, default=0,
                   help="[generate, paged] pool size incl. the trash "
                        "block (0 = match the contiguous footprint)")
    p.add_argument("--prefix-reuse", action="store_true",
                   help="[generate, paged] share full block-aligned "
                        "prompt prefixes copy-on-write")
    p.add_argument("--paged-kernel", action="store_true",
                   help="[generate, paged] Pallas paged decode-attention "
                        "kernel where supported")
    p.add_argument("--prefix-tokens", type=int, default=0,
                   help="[generate] fixed system-prompt tokens prepended "
                        "to every request (the prefix-reuse traffic "
                        "shape)")
    p.add_argument("--model-dim", type=int, default=0,
                   help="override the bench model width (d_ff = 2*dim; "
                        "0 keeps the default tiny model). Wider models "
                        "put the bench in the regime where prefill "
                        "compute dominates KV block copies")
    p.add_argument("--prefix-count", type=int, default=1,
                   help="number of distinct shared system prefixes rotated "
                        "round-robin across shared arrivals. >1 grows the "
                        "registered-prefix working set past a tight device "
                        "pool so the host tier's offload/prefetch path runs")
    p.add_argument("--prefix-mix", type=float, default=1.0,
                   help="[generate, --prefix-tokens] fraction of "
                        "requests carrying the shared system prompt "
                        "(default 1.0 = all, the old behavior); the JSON "
                        "row stamps the per-point prefix hit rate and "
                        "the hit-vs-cold TTFT split")
    p.add_argument("--chunked-prefill", action="store_true",
                   help="[generate, paged, --prefix-reuse] chunked "
                        "prefill: the compiled program starts at the "
                        "first non-shared block, reading hit blocks' "
                        "K/V from the pool instead of recomputing "
                        "(docs/inference.md 'KV memory hierarchy')")
    p.add_argument("--chunk-blocks", type=int, default=1,
                   help="[generate, --chunked-prefill] blocks per "
                        "prefill scan chunk (power of two)")
    p.add_argument("--host-blocks", type=int, default=0,
                   help="[generate, paged, --prefix-reuse] host-tier "
                        "block pool: cold registered-prefix blocks "
                        "offload to pinned host memory and prefetch "
                        "back at admission (0 = device-only)")
    p.add_argument("--host-admission", choices=("wait", "miss"),
                   default="wait",
                   help="[generate, --host-blocks] admission policy "
                        "while a host-tier prefetch is in flight: wait "
                        "(hold the request for the full hit) or miss "
                        "(admit now, recompute the prefix)")
    p.add_argument("--adapters", type=int, default=0,
                   help="[generate] seeded LoRA fine-tunes (tenants "
                        "a0..aN-1) loaded next to the base model; every "
                        "JSON row then stamps the per-tenant fields "
                        "(docs/inference.md 'Multi-tenant adapters')")
    p.add_argument("--adapter-rank", type=int, default=4,
                   help="[generate, --adapters] LoRA rank of the bench "
                        "fine-tunes")
    p.add_argument("--adapter-mix", default="",
                   help="[generate, --adapters] comma-separated arrival "
                        "weights, base first then a0..aN-1 (default "
                        "uniform)")
    p.add_argument("--adapter-only", default="",
                   help="[generate, --adapters] replay the same arrival "
                        "schedule submitting ONLY this tenant's requests "
                        "(base|aK) — the single-tenant digest reference "
                        "the ci.sh multi-tenant drill compares against")
    p.add_argument("--tenant-weights", default="",
                   help="[generate] fair-scheduling weights as "
                        "tenant:weight pairs, e.g. 'base:1,a0:4' — a0 "
                        "then gets ~4x base's decode admissions under "
                        "contention (docs/inference.md 'Fair "
                        "scheduling, budgets, and preemption')")
    p.add_argument("--priority-mix", default="",
                   help="[generate] strict priority classes as "
                        "tenant:priority pairs, e.g. 'a0:1' — higher "
                        "classes admit first and may preempt lower "
                        "(unnamed tenants are class 0)")
    p.add_argument("--tenant-slo-ms", default="",
                   help="[generate] per-tenant TTFT SLO targets as "
                        "tenant:ms pairs, e.g. 'base:500,a0:150' — "
                        "misses burn the hvd_tenant_slo_* series and "
                        "steer SLO-aware fleet dispatch")
    p.add_argument("--preempt-retries", type=int, default=3,
                   help="[generate] evictions a stream survives before "
                        "preempted_exhausted (GenerationConfig."
                        "preempt_retries); the ci.sh preemption drill "
                        "raises it so a digest-pinned run can never "
                        "fail on an unlucky eviction streak")
    p.add_argument("--replicas", type=int, default=1,
                   help="[generate] engine replicas behind one "
                        "FleetRouter (static fleet; with --autoscale "
                        "this is the GROW CEILING instead)")
    p.add_argument("--replica-procs", action="store_true",
                   help="[generate] run each fleet replica as a "
                        "SUBPROCESS worker (python -m horovod_tpu.serve."
                        "proc_replica) behind a ProcReplicaClient, "
                        "instead of an in-process engine thread — the "
                        "same seeded traffic then exercises the serving "
                        "plane across a real process boundary; every "
                        "JSON row stamps topology: 'process' so digest "
                        "comparisons across topologies are one grep "
                        "(docs/inference.md 'Process replicas')")
    p.add_argument("--autoscale", action="store_true",
                   help="[generate] start at --min-replicas and let the "
                        "queue-depth FleetAutoscaler grow/shrink the "
                        "fleet between --min-replicas and --replicas "
                        "(docs/inference.md 'Serving fleet')")
    p.add_argument("--min-replicas", type=int, default=1,
                   help="[generate, --autoscale] fleet floor")
    p.add_argument("--scale-high", type=float, default=4.0,
                   help="[generate, --autoscale] grow watermark: queued "
                        "work per ready replica")
    p.add_argument("--scale-low", type=float, default=0.5,
                   help="[generate, --autoscale] shrink watermark")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="[generate] sampling temperature (0 = greedy); "
                        ">0 switches every request to seeded sampling "
                        "with a per-(tenant, arrival-index) seed, so "
                        "stream digests stay run-to-run comparable")
    p.add_argument("--top-k", type=int, default=0,
                   help="[generate, --temperature>0] top-k cutoff "
                        "(0 = full vocab)")
    p.add_argument("--spec-k", type=int, default=0,
                   help="[generate] speculative decoding: draft up to K "
                        "tokens per decode step with the self-speculative "
                        "n-gram drafter and score them in one verify "
                        "forward (0 = off). Greedy streams stay digest-"
                        "identical to a spec-off run; needs the gather "
                        "decode path (incompatible with --paged-kernel)")
    p.add_argument("--chaos", default="",
                   help="[generate] serving-plane HVD_FAULT_SPEC clause(s) "
                        "armed for this run, e.g. "
                        "'replica_kill=r1@stream=3' — the deterministic-"
                        "failover drill knob (docs/fault_tolerance.md "
                        "'Serving failures')")
    p.add_argument("--cache-mb", type=float, default=0,
                   help="[generate] fixed KV-cache byte budget; derives "
                        "slots (contiguous) or pool+slots (paged) — the "
                        "equal-bytes capacity comparison (0 = use "
                        "--slots)")
    p.add_argument("--json", default="",
                   help="[generate] append one JSON line per operating "
                        "point to this file")
    args = p.parse_args()
    if args.deadline_ms == 0:
        args.deadline_ms = None
    if args.replicas < 1:
        p.error("--replicas must be >= 1")
    if args.min_replicas < 1:
        p.error("--min-replicas must be >= 1 (a fleet of zero serves "
                "nothing)")
    if args.autoscale and args.min_replicas > args.replicas:
        p.error("--min-replicas must be <= --replicas (the grow ceiling)")
    if args.adapters < 0:
        p.error("--adapters must be >= 0")
    if args.adapters and args.mode != "generate":
        p.error("--adapters applies to --mode generate only")
    if args.replica_procs and args.mode != "generate":
        p.error("--replica-procs applies to --mode generate only")
    if args.spec_k < 0:
        p.error("--spec-k must be >= 0 (0 = speculation off)")
    if args.spec_k:
        if args.mode != "generate":
            p.error("--spec-k applies to --mode generate only")
        if args.paged_kernel:
            p.error("--spec-k needs the gather decode path: drop "
                    "--paged-kernel (the Pallas kernel is allclose-"
                    "pinned, not bitwise, so it cannot honor the "
                    "spec-off greedy digest contract)")
    if args.temperature < 0:
        p.error("--temperature must be >= 0 (0 = greedy)")
    if args.top_k < 0:
        p.error("--top-k must be >= 0 (0 = full vocab)")
    if args.chaos:
        if args.mode != "generate":
            p.error("--chaos applies to --mode generate only (serving-"
                    "plane clauses fire inside the generation engine "
                    "loop)")
        from horovod_tpu.testing import faults
        try:
            clauses = faults.parse_spec(args.chaos)
        except faults.FaultSpecError as e:
            p.error(str(e))
        if not any(f.target == "serve" for f in clauses):
            p.error(f"--chaos {args.chaos!r} has no serving-plane clause "
                    f"(replica_kill= / replica_hang= / "
                    f"replica_proc_kill= / slow_step=) — training-plane "
                    f"drills belong to tpurun, not the bench")
        if any(f.action == "replica_proc_kill" for f in clauses) \
                and not args.replica_procs:
            # In a thread fleet the clause would fire inside THIS
            # process's engine loop and SIGKILL the whole bench — the
            # drill only means anything when the victim is a child.
            p.error("--chaos replica_proc_kill needs --replica-procs: "
                    "the clause SIGKILLs the replica's own PROCESS, "
                    "which in a thread fleet is the bench itself")
        if any(f.action in ("replica_kill", "replica_hang",
                            "replica_proc_kill")
               for f in clauses) \
                and args.replicas <= 1 and not args.autoscale:
            # A bare engine's serve_name stays "engine" — a clause
            # targeting r0/r1 could never fire, and the run would read
            # as a passed drill that never drilled anything.
            p.error("--chaos replica_kill/replica_hang needs a fleet "
                    "(--replicas >= 2 or --autoscale): replica names "
                    "are stamped by the FleetRouter, and a kill drill "
                    "without a surviving replica has nothing to fail "
                    "over to")
        # Armed via the one env knob every injection rides — the engine
        # loops read it, so this must land BEFORE engines are built.
        os.environ["HVD_FAULT_SPEC"] = args.chaos
        faults.reset()
    if args.adapter_mix and not args.adapters:
        p.error("--adapter-mix needs --adapters N")
    if not 0.0 <= args.prefix_mix <= 1.0:
        p.error("--prefix-mix must be in [0, 1]")
    if args.model_dim and (args.model_dim < 4 or args.model_dim % 4):
        p.error("--model-dim must be a positive multiple of 4 (the "
                "bench model has 4 heads)")
    if args.prefix_count < 1:
        p.error("--prefix-count must be >= 1")
    if args.prefix_count > 1 and not args.prefix_tokens:
        p.error("--prefix-count > 1 needs --prefix-tokens N")
    if args.prefix_mix != 1.0:
        if args.mode != "generate":
            p.error("--prefix-mix applies to --mode generate only")
        if not args.prefix_tokens:
            p.error("--prefix-mix needs --prefix-tokens N (without a "
                    "shared system prompt there is nothing to mix)")
    if args.chunked_prefill or args.host_blocks:
        what = "--chunked-prefill" if args.chunked_prefill \
            else "--host-blocks"
        if args.mode != "generate" or args.kv_layout != "paged":
            p.error(f"{what} needs --mode generate --kv-layout paged")
        if not args.prefix_reuse:
            p.error(f"{what} needs --prefix-reuse (its whole point is "
                    f"the prefix cache)")
    if args.chunk_blocks < 1:
        p.error("--chunk-blocks must be >= 1")
    if args.host_blocks < 0:
        p.error("--host-blocks must be >= 0")
    try:
        args.tenant_weights_map = _parse_tenant_map(
            args.tenant_weights, "--tenant-weights", float)
        args.priority_mix_map = _parse_tenant_map(
            args.priority_mix, "--priority-mix", int)
        args.tenant_slo_ms_map = _parse_tenant_map(
            args.tenant_slo_ms, "--tenant-slo-ms", float)
    except SystemExit as e:
        p.error(str(e))
    if (args.tenant_weights_map or args.priority_mix_map
            or args.tenant_slo_ms_map) and args.mode != "generate":
        p.error("--tenant-weights/--priority-mix/--tenant-slo-ms apply "
                "to --mode generate only")
    if args.mode == "generate":
        try:
            # ONE naming/weights rule — the same call the run schedule
            # uses; fail fast, before model build + warmup.
            tenants, _ = _bench_tenants(args)
        except SystemExit as e:
            p.error(str(e))
        if args.adapter_only and args.adapter_only not in tenants:
            p.error(f"--adapter-only must be one of {tenants} "
                    f"(set --adapters first)")
        for what, m in (("--tenant-weights", args.tenant_weights_map),
                        ("--priority-mix", args.priority_mix_map),
                        ("--tenant-slo-ms", args.tenant_slo_ms_map)):
            bad = [t for t in m if t not in tenants]
            if bad:
                p.error(f"{what} names unknown tenant(s) {bad} — this "
                        f"run's tenants are {tenants} (set --adapters)")
    elif args.adapter_only:
        p.error("--adapter-only applies to --mode generate only")

    if args.mode == "generate":
        run_generate(args)
        return

    eng = _build_engine(args)
    rng = np.random.RandomState(0)
    points = [float(q) for q in str(args.qps).split(",")]
    hdr = (f"{'qps→':>8}{'qps':>9}{'p50 ms':>9}{'p99 ms':>9}"
           f"{'fill':>7}{'overload':>10}{'deadline':>10}")
    print(hdr)
    dropped_in_deadline = 0
    for q in points:
        row = run_point(eng, q, args.duration, rng, (args.features,))
        # Overload rejects and execution failures hit requests that were
        # still within deadline — the drops the gate counts. Deadline
        # expiries are the contract working as specified, reported but
        # not gated.
        dropped_in_deadline += row["overload_drops"] + row["failed"]
        fill = row["batch_fill"]
        print(f"{row['qps_target']:>8.0f}{row['qps_achieved']:>9.1f}"
              f"{row['p50_ms']:>9.2f}{row['p99_ms']:>9.2f}"
              f"{(fill if fill is not None else 0):>7.2f}"
              f"{row['overload_drops']:>10}{row['deadline_drops']:>10}")
        if not (np.isfinite(row["p50_ms"]) and np.isfinite(row["p99_ms"])):
            print("FAIL: empty latency report (no request completed)")
            eng.shutdown(drain=False)
            sys.exit(1)
    eng.shutdown()
    if dropped_in_deadline:
        print(f"FAIL: {dropped_in_deadline} in-deadline requests dropped")
        sys.exit(1)
    print("SERVE BENCH OK")


def _fleet_settle(eng, args, lost_streams: int, streams_by_tenant=None):
    """The closed loop's back half: traffic has stopped, so the
    autoscaler must DRAIN the extra replicas (finishing every admitted
    stream) and shrink back to the floor. Waits for the membership to
    settle, then returns the fleet summary row the ci.sh drill asserts
    on (grow >= 1, shrink to min, zero lost streams)."""
    scaler = getattr(eng, "bench_autoscaler", None)
    if scaler is not None:      # a static fleet has nothing to shrink
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            c = eng.counts()
            if (c["ready"] <= args.min_replicas and c["warming"] == 0
                    and c["draining"] == 0):
                break
            time.sleep(0.25)
        scaler.stop()
    snap = eng.stats()
    row = {
        "fleet": True,
        "autoscale": bool(args.autoscale),
        "min_replicas": args.min_replicas,
        "max_replicas": args.replicas,
        "ready_final": snap["fleet"]["n_ready"],
        "draining_final": snap["fleet"]["n_draining"],
        "queue_depth_final": snap["queue_depth"],
        "scale_events": snap["fleet"]["scale_events"],
        "dispatch": snap["fleet"]["dispatch_total"],
        "drained_lost_streams": lost_streams,
        # The failover plane's whole-run verdict (ISSUE 15 chaos drill):
        # every stranded stream must be resumed (bit-identically) or
        # counted exhausted — never silently lost.
        "failover": snap["fleet"]["failover_total"],
        "stranded": snap["fleet"]["streams_stranded_total"],
        "chaos": args.chaos or None,
        "topology": "process" if args.replica_procs else "thread",
        "spec_k": int(snap.get("spec_k") or 0),
        "spec_accept_rate": (snap.get("spec") or {}).get("accept_rate"),
        "tokens_per_step": (snap.get("spec") or {}).get("tokens_per_step"),
    }
    if streams_by_tenant is not None:
        # Per-tenant digest map over the WHOLE run (all operating
        # points): the summary-line form of the per-row maps, so a CI
        # drill can compare tenants across whole runs in one line.
        row["stream_digests"] = {t: _stream_digest(s)
                                 for t, s in streams_by_tenant.items()}
    if "adapter_dispatch" in snap["fleet"]:
        row["adapter_dispatch"] = snap["fleet"]["adapter_dispatch"]
    if "prefix_dispatch" in snap["fleet"]:
        row["prefix_dispatch"] = snap["fleet"]["prefix_dispatch"]
    return row


def run_generate(args):
    import json

    eng = _build_gen_engine(args)
    fleet = hasattr(eng, "counts")      # FleetRouter duck-type marker
    rng = np.random.RandomState(0)
    points = [float(q) for q in str(args.qps).split(",")]
    hdr = (f"{'qps→':>8}{'done':>7}{'ttft p50':>10}{'ttft p99':>10}"
           f"{'tok/s':>9}{'tok/s/u':>9}{'fill':>7}{'overload':>10}"
           f"{'deadline':>10}")
    print(hdr)
    dropped_in_deadline = 0
    failed_total = 0
    total_tps = 0.0
    all_streams: dict = {}
    for q in points:
        row, streams_by_tenant = run_gen_point(eng, q, args.duration,
                                               rng, args)
        for t, s in streams_by_tenant.items():
            all_streams.setdefault(t, []).extend(s)
        dropped_in_deadline += row["overload_drops"] + row["failed"]
        failed_total += row["failed"]
        total_tps += row["tokens_per_sec"]
        print(f"{row['qps_target']:>8.0f}{row['completed']:>7}"
              f"{row['ttft_p50_ms']:>10.2f}{row['ttft_p99_ms']:>10.2f}"
              f"{row['tokens_per_sec']:>9.1f}{row['tps_user_p50']:>9.1f}"
              f"{(row['slot_fill'] or 0):>7.2f}"
              f"{row['overload_drops']:>10}{row['deadline_drops']:>10}")
        print(json.dumps(row))
        if args.json:
            with open(args.json, "a") as f:
                f.write(json.dumps(row) + "\n")
        if not (np.isfinite(row["ttft_p50_ms"])
                and np.isfinite(row["ttft_p99_ms"])):
            print("FAIL: empty TTFT report (no request completed)")
            eng.shutdown(drain=False)
            sys.exit(1)
    if fleet:
        fleet_row = _fleet_settle(eng, args, failed_total, all_streams)
        print(json.dumps(fleet_row))
        if args.json:
            with open(args.json, "a") as f:
                f.write(json.dumps(fleet_row) + "\n")
    if args.spec_k:
        sp = eng.stats().get("spec") or {}
        ar, tps = sp.get("accept_rate"), sp.get("tokens_per_step")
        print(f"spec: k={args.spec_k}"
              f" accept_rate={ar if ar is None else round(ar, 4)}"
              f" tokens_per_step={tps if tps is None else round(tps, 3)}")
    eng.shutdown()
    if dropped_in_deadline:
        print(f"FAIL: {dropped_in_deadline} in-deadline requests dropped")
        sys.exit(1)
    if not total_tps > 0:
        print("FAIL: zero aggregate tokens/sec")
        sys.exit(1)
    print("SERVE BENCH OK")


if __name__ == "__main__":
    main()
