#!/usr/bin/env python
"""Per-op device-time profile of the benchmark training step.

The measurement tool behind the ResNet-50 roofline analysis in
``docs/benchmarks.md``: runs the same compiled train step as ``bench.py``,
captures one multi-step dispatch under ``jax.profiler.trace``, and
aggregates the per-HLO device events (``hlo_category``,
``device_duration_ps``, ``model_flops``, ``raw_bytes_accessed``) into a
per-step table — device-busy breakdown by category, then the top ops.

    python bin/profile_step.py --model resnet50
    python bin/profile_step.py --model resnet50 --conv-backend fused
    python bin/profile_step.py --model transformer_lm

Real-TPU only (the per-op device track needs the TPU profiler plugin).
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402


def _capture(run_dispatch):
    """Run ``run_dispatch`` once under the profiler. The callable is a full
    bench ``measure`` (compile + warmup + timed dispatches); compilation is
    host-side and invisible to the device track, so the report divides by
    the TOTAL device steps executed (warmup + iters) x steps_per_call."""
    d = tempfile.mkdtemp(prefix="hvdprof")
    with jax.profiler.trace(d):
        run_dispatch()
    files = sorted(glob.glob(d + "/**/*.trace.json.gz", recursive=True))
    if not files:
        raise SystemExit("no trace produced (TPU profiler plugin missing?)")
    with gzip.open(files[-1]) as fh:
        tr = json.load(fh)
    return tr["traceEvents"]


def _track(events, track_name):
    tids = set()
    for e in events:
        if (e.get("ph") == "M" and e.get("name") == "thread_name"
                and e["args"].get("name") == track_name):
            tids.add((e["pid"], e["tid"]))
    return [e for e in events
            if e.get("ph") == "X" and (e.get("pid"), e.get("tid")) in tids]


def _xla_op_events(events):
    """Events on the device 'XLA Ops' thread."""
    return _track(events, "XLA Ops")


def _dispatch_count(events):
    """How many launches of the dominant executable the trace captured
    ('XLA Modules' track) — the robust step divisor: traces can start
    mid-run and buffer limits can drop early dispatches, so trusting the
    requested warmup+iters count mis-scales every per-step number."""
    mods = collections.defaultdict(lambda: [0, 0.0])
    for e in _track(events, "XLA Modules"):
        m = mods[e["name"]]
        m[0] += 1
        m[1] += float(e.get("dur", 0.0))
    if not mods:
        return None
    return max(mods.values(), key=lambda m: m[1])[0]


# Control-flow parents whose device time ENCLOSES their body ops — the
# body is attributed separately on the same track, so counting the parent
# double-books every nested op (a lax.scan-driven step would double).
_PARENT_OPS = {"while", "conditional", "call"}


def report(events, steps_per_call, requested_dispatches):
    n_disp = _dispatch_count(events) or requested_dispatches
    k = steps_per_call * n_disp
    print(f"(trace captured {n_disp} dispatches x {steps_per_call} steps)")
    cats = collections.defaultdict(lambda: [0.0, 0, 0])  # ps, flops, bytes
    ops = collections.defaultdict(lambda: [0.0, 0, 0, "", 0])
    t_min, t_max = float("inf"), 0.0
    busy = 0.0
    for e in _xla_op_events(events):
        if re.sub(r"\.\d+$", "", e["name"]) in _PARENT_OPS:
            continue
        a = e["args"]
        dur = int(a.get("device_duration_ps", 0))
        off = int(a.get("device_offset_ps", 0))
        t_min = min(t_min, off)
        t_max = max(t_max, off + dur)
        busy += dur
        fl = int(a.get("model_flops", 0) or 0)
        by = int(a.get("raw_bytes_accessed", 0) or 0)
        cat = a.get("hlo_category", e["name"])
        cats[cat][0] += dur
        cats[cat][1] += fl
        cats[cat][2] += by
        name = a.get("long_name", e["name"]).split(" = ")[0]
        # Collapse instances: %fusion.123 -> fusion, keep pallas kernel ids
        key = re.sub(r"\.\d+$", "", name.lstrip("%"))
        o = ops[key]
        o[0] += dur
        o[1] += fl
        o[2] += by
        o[3] = cat
        o[4] += 1

    # A trace with no per-op device track (CPU backend, or a TPU plugin
    # that dropped the 'XLA Ops' thread) yields busy == 0; a trace that
    # missed every module dispatch yields k == 0. Either way every
    # per-step figure below would divide by zero — fail with the remedy
    # instead of a bare ZeroDivisionError.
    if k == 0:
        raise SystemExit(
            "profile_step: trace captured 0 dispatches of the step on the "
            "'XLA Modules' track — the profiler likely started after the "
            "run or the buffer dropped them; re-run with more --steps or "
            "on a quieter host")
    if busy == 0:
        raise SystemExit(
            "profile_step: no per-op device time on the 'XLA Ops' track — "
            "this tool needs the TPU profiler plugin's device events "
            "(JAX_PLATFORMS=cpu traces carry none); run on a real TPU, or "
            "use bench.py for host-side wall-clock numbers")
    env = (t_max - t_min) / 1e12
    print(f"device busy: {busy/1e12/k*1e3:.2f} ms/step "
          f"(envelope {env/k*1e3:.2f}); idle = {(env - busy/1e12)/k*1e3:.2f} ms")
    print(f"{'category':<28}{'ms/step':>9}{'%busy':>7}{'TFLOP/s':>9}"
          f"{'GB/s':>8}")
    for cat, (ps, fl, by) in sorted(cats.items(), key=lambda kv: -kv[1][0]):
        s = ps / 1e12
        print(f"{cat:<28}{s/k*1e3:>9.2f}{ps/busy*100:>7.1f}"
              f"{fl/s/1e12 if s else 0:>9.1f}{by/s/1e9 if s else 0:>8.0f}")
    print()
    print(f"top ops (per step): {'ms':>8} {'TF/s':>7} {'GB/s':>6}  n  "
          f"category / name")
    for name, (ps, fl, by, cat, n) in sorted(
            ops.items(), key=lambda kv: -kv[1][0])[:24]:
        s = ps / 1e12
        print(f"{'':>8}{s/k*1e3:>10.3f} {fl/s/1e12 if s else 0:>7.1f} "
              f"{by/s/1e9 if s else 0:>6.0f} {n//k if k else n:>3}  "
              f"{cat} / {name[:70]}")


def timeline_host_report(path):
    """Host-plane attribution from a ``HOROVOD_TIMELINE`` Chrome trace.

    The device-side tables above say where MXU time goes; this says what
    the HOST was doing meanwhile: ``H2D`` rows come from the prefetch
    thread (input staging), ``CKPT_SNAPSHOT``/``CKPT_WRITE`` from the
    checkpoint path. A run whose summed H2D time approaches its wall clock
    is input-bound — grow the prefetch depth or the input workers before
    touching the model; large CKPT_WRITE with small CKPT_SNAPSHOT means
    async checkpointing is doing its job (the write overlaps training).
    """
    with open(path) as fh:
        text = fh.read()
    try:
        events = json.loads(text)
    except json.JSONDecodeError:
        # The trace is a terminated JSON array only after Timeline.close();
        # a still-running or killed run leaves "[{...},\n{...},\n" — apply
        # the trailing-comma-tolerant completion Chrome's viewer uses.
        events = json.loads(text.rstrip().rstrip(",") + "]")
    open_ev = {}
    totals = collections.defaultdict(lambda: [0.0, 0])  # name -> [us, n]
    t_min, t_max = float("inf"), 0.0
    for e in events:
        if not isinstance(e, dict) or "ph" not in e:
            continue
        ts = e.get("ts")
        if ts is not None:
            t_min, t_max = min(t_min, ts), max(t_max, ts)
        if e["ph"] == "B":
            open_ev.setdefault(e["pid"], []).append((e["name"], ts))
        elif e["ph"] == "E":
            stack = open_ev.get(e["pid"])
            if stack:
                name, ts0 = stack.pop()
                totals[name][0] += ts - ts0
                totals[name][1] += 1
    host = {k: v for k, v in totals.items()
            if k in ("H2D", "CKPT_SNAPSHOT", "CKPT_WRITE")}
    if not host:
        raise SystemExit(
            f"profile_step: no host-plane phases (H2D/CKPT_*) in {path} — "
            "run training with HOROVOD_TIMELINE set, prefetch enabled "
            "(Trainer(prefetch>=1) passes the world sharding through) "
            "and/or an AsyncCheckpointer attached")
    span_ms = (t_max - t_min) / 1e3
    print(f"host-plane phases ({path}; trace span {span_ms:.1f} ms):")
    print(f"{'phase':<16}{'total ms':>10}{'n':>6}{'mean ms':>10}"
          f"{'% span':>8}")
    for name, (us, n) in sorted(host.items(), key=lambda kv: -kv[1][0]):
        ms = us / 1e3
        print(f"{name:<16}{ms:>10.2f}{n:>6}{ms / n:>10.2f}"
              f"{100 * ms / span_ms if span_ms else 0:>8.1f}")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet50")
    p.add_argument("--conv-backend", default="xla",
                   choices=["xla", "fused"])
    p.add_argument("--steps", type=int, default=None,
                   help="steps per dispatch (default: the bench config)")
    p.add_argument("--timeline", default=None, metavar="FILE",
                   help="summarize host-plane phases (H2D, CKPT_*) from a "
                        "HOROVOD_TIMELINE trace instead of profiling — "
                        "works on any host, no TPU needed")
    args = p.parse_args()

    if args.timeline:
        timeline_host_report(args.timeline)
        return

    import bench

    if args.model == "transformer_lm":
        cfg = bench._lm_config()
        if args.steps:
            cfg["steps_per_call"] = args.steps
        cfg["warmup"], cfg["iters"], cfg["rounds"] = 2, 1, 1
        events = _capture(lambda: bench.measure_lm(cfg))
        report(events, cfg["steps_per_call"],
               cfg["warmup"] + cfg["iters"])
        return

    cfg = bench._bench_config(args.model)
    if args.conv_backend != "xla":
        # Same mislabel guard as bench.py: a run that silently profiles
        # stock convs must not be recorded as a fused measurement.
        if args.model not in ("resnet50", "resnet101") \
                or cfg["model"] not in ("resnet50", "resnet101"):
            raise SystemExit(
                "--conv-backend fused applies to resnet50/resnet101 on "
                "real TPU only")
    cfg["conv_backend"] = args.conv_backend
    if args.steps:
        cfg["steps_per_call"] = args.steps
    cfg["warmup"], cfg["iters"], cfg["rounds"] = 2, 1, 1
    events = _capture(lambda: bench.measure(cfg=cfg))
    report(events, cfg["steps_per_call"],
           cfg["warmup"] + cfg["iters"])


if __name__ == "__main__":
    main()
