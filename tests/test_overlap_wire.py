"""Backward-overlapped bucket collectives + low-precision wire formats
(ISSUE 6 tentpole).

The contract under test: with ``overlap=True`` the compiled train step
issues one collective per bucket in backward-completion order behind
``optimization_barrier`` pins — each early bucket's collective is
SCHEDULED before the last backward op of the compiled module, the
emission order follows the schedule exactly, and the total collective
count equals the non-overlapped plan (overlap reorders, never adds).
With ``wire_dtype`` the collectives run in bf16/fp8 with fp32 scales and
fp32 result accumulation (HLO-pinned operand dtypes), training matches
the fp32-wire path within documented tolerance, and ``zero=True``
composes with compression instead of raising.
"""

import re

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu import training
from horovod_tpu.ops import fusion


class _MLP(nn.Module):
    """Three equal-width hidden layers: uniform leaf sizes make the greedy
    bucket count independent of visit order, so plan-vs-schedule count
    equality is exact (the acceptance invariant)."""

    @nn.compact
    def __call__(self, x, train=True):
        h = x
        for _ in range(3):
            h = nn.relu(nn.Dense(64)(h))
        return nn.Dense(10)(h)


# Threshold that splits the MLP into several buckets (64x64 fp32 kernels
# are 16 KiB — above it, so they close buckets).
_THRESH = 8000


def _build(overlap=None, wire_dtype=None, zero=False,
           fusion_threshold=_THRESH, guard=None, accum=1, opt=None):
    hvd.init()
    model = _MLP()
    state, dist_opt = training.create_train_state(
        model, jax.random.PRNGKey(0), jnp.zeros((2, 8)),
        opt or optax.adam(1e-2), zero=zero, wire_dtype=wire_dtype,
        fusion_threshold=fusion_threshold)
    step = training.make_train_step(
        model, dist_opt, donate=False, overlap=overlap,
        guard_nonfinite=guard, accum_steps=accum)
    return state, dist_opt, step


def _batch(rows=16, seed=0, nan_at=None):
    rng = np.random.RandomState(seed)
    x = rng.randn(rows, 8).astype(np.float32)
    if nan_at is not None:
        x[nan_at] = np.nan
    return x, rng.randint(0, 10, (rows,))


def _np_tree(tree):
    return jax.tree_util.tree_map(np.asarray, tree)


def _lowered_text(step, state, batch):
    return step.lower(state, batch).as_text()


def _compiled_lines(step, state, batch):
    return step.lower(state, batch).compile().as_text().splitlines()


def _bucket_ar_positions(lines):
    """(line index, element count) of every non-scalar all-reduce in the
    compiled module — the gradient bucket collectives (scalar all-reduces
    are the loss/metric pmeans)."""
    out = []
    for i, line in enumerate(lines):
        m = re.search(r"= \S*?f32\[([0-9,]+)\][^=]* all-reduce(?:-start)?\(",
                      line)
        if m:
            n = 1
            for d in m.group(1).split(","):
                n *= int(d)
            out.append((i, n))
    return out


def _last_dot(lines):
    return max(i for i, line in enumerate(lines)
               if re.search(r"= \S+ dot\(", line))


# ---------------------------------------------------------------------------
# Schedule: probe + determinism (ISSUE 6 satellite).
# ---------------------------------------------------------------------------

def test_probe_grad_order_ranks_last_layer_first():
    """A sequential MLP back-propagates its LAST layer first: the probe
    must rank the final Dense's leaves before the first Dense's."""

    def loss(p, x):
        h = x
        for i in range(3):
            h = jnp.tanh(h @ p[f"w{i}"])
        return jnp.sum(h)

    p = {f"w{i}": jnp.zeros((8, 8)) for i in range(3)}
    order = fusion.probe_grad_order(
        lambda q: jax.grad(loss)(q, jnp.ones((4, 8))), p)
    assert order is not None
    # flatten order is w0, w1, w2; completion order is the reverse.
    assert order == (2, 1, 0)


def test_probe_handles_literal_grad_leaves():
    """A leaf the loss never reads lowers its cotangent to a jaxpr Literal
    (unhashable on this jax) — the probe must degrade it to flatten order,
    not crash (review finding: TypeError on `pos.get(Literal)`)."""

    def loss(p):
        return jnp.sum(p["w"] * 2.0)  # p["unused"] never read

    p = {"unused": jnp.float32(1.0), "w": jnp.ones((3,))}
    order = fusion.probe_grad_order(lambda q: jax.grad(loss)(q), p)
    assert order is not None
    assert sorted(order) == [0, 1]


def test_schedule_deterministic_and_cached():
    """Same (shapes, dtypes, threshold, grad-order) -> identical bucket
    order, served from cache — the cross-process determinism the emission
    chain relies on (every SPMD replica derives the same schedule from
    the same traced program)."""
    leaves = [jnp.zeros((n,), jnp.float32) for n in (100, 200, 300, 400)]
    order = (3, 2, 1, 0)
    first = fusion.plan_schedule(leaves, order, fusion_threshold=1 << 11)
    hits = fusion._schedule_cached.cache_info().hits
    again = fusion.plan_schedule(leaves, order, fusion_threshold=1 << 11)
    assert again == first
    assert fusion._schedule_cached.cache_info().hits == hits + 1
    # Buckets walk the completion order, not flatten order.
    assert first.buckets[0][0] == 3
    # A different order is a different schedule, not a stale hit.
    other = fusion.plan_schedule(leaves, (0, 1, 2, 3),
                                 fusion_threshold=1 << 11)
    assert other.buckets != first.buckets


def test_env_threshold_flip_invalidates_schedule(monkeypatch):
    leaves = [jnp.zeros((8,)), jnp.zeros((8,))]
    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "0")
    assert fusion.plan_schedule(leaves, (1, 0)).buckets == ((1,), (0,))
    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", str(1 << 20))
    assert fusion.plan_schedule(leaves, (1, 0)).buckets == ((1, 0),)


def test_plan_schedule_rejects_non_permutation():
    leaves = [jnp.zeros((8,)), jnp.zeros((8,))]
    with pytest.raises(ValueError, match="permutation"):
        fusion.plan_schedule(leaves, (0, 0))


def test_zero_emit_order_is_readiness_sorted_and_membership_free():
    """ZeRO overlap reorders EMISSION only: the plan (sharded-state layout,
    checkpoint canonical form) is untouched."""
    params = {"a": jnp.zeros((16,)), "b": jnp.zeros((16,)),
              "c": jnp.zeros((16,))}
    plan = fusion.plan_zero(params, 8, fusion_threshold=0)
    # Backward completes c, b, a (reverse flatten): bucket order follows.
    emit = fusion.zero_emit_order(plan, (2, 1, 0))
    assert emit == (2, 1, 0)
    assert fusion.zero_emit_order(plan, None) == (0, 1, 2)
    # Same plan object either way — membership is pinned.
    assert plan.buckets == ((0,), (1,), (2,))


# ---------------------------------------------------------------------------
# HLO pins: counts, placement, emission order (acceptance criteria).
# ---------------------------------------------------------------------------

def test_overlap_keeps_collective_count():
    """Overlap reorders, never adds: lowered collective counts are equal
    with and without overlap, and the compiled module neither merges nor
    splits the overlapped buckets (the barrier chain blocks the
    combiner)."""
    state, _, plain = _build(overlap=None)
    _, _, over = _build(overlap=True)
    b = _batch()
    n_plain = len(re.findall(r"\ball_reduce\b",
                             _lowered_text(plain, state, b)))
    low = over.lower(state, b)
    n_over = len(re.findall(r"\ball_reduce\b", low.as_text()))
    assert n_over == n_plain
    n_compiled = len(re.findall(r" all-reduce(?:-start)?\(",
                                low.compile().as_text()))
    assert n_compiled == n_over


def test_overlap_schedules_buckets_before_last_backward_op():
    """The acceptance pin: with overlap on, the early buckets' all-reduces
    are SCHEDULED before the last backward op of the compiled module
    (their gradients completed, so the wire rides while the rest of the
    backward still computes); a default-threshold single blob can only
    run after the entire backward."""
    b = _batch()
    # Default threshold: one post-backward blob.
    state, _, blob = _build(overlap=None, fusion_threshold=None)
    lines = _compiled_lines(blob, state, b)
    blob_ars = _bucket_ar_positions(lines)
    assert len(blob_ars) == 1
    assert blob_ars[0][0] > _last_dot(lines), (
        "the fused blob should depend on the whole backward")
    # Overlapped multi-bucket schedule: early buckets land inside the
    # backward. (The last-completing bucket necessarily trails the final
    # backward op — its gradients ARE that op's output.)
    state, _, over = _build(overlap=True)
    lines = _compiled_lines(over, state, b)
    over_ars = _bucket_ar_positions(lines)
    assert len(over_ars) >= 3
    last_dot = _last_dot(lines)
    before = [p for p, _ in over_ars if p < last_dot]
    assert len(before) >= 2, (over_ars, last_dot)


def test_overlap_emission_follows_schedule_order():
    """The barrier chain pins cross-bucket issue order: the LOWERED
    module's bucket all-reduces appear exactly in the schedule's
    completion order (identified by flat element count), with one
    chaining ``optimization_barrier`` between consecutive buckets. (The
    compiled-module print can't pin this on CPU — XLA:CPU elides
    opt-barriers after scheduling; on TPU they survive to fence the
    collective combiner and fix the issue order.)"""
    b = _batch()
    state, _, over = _build(overlap=True)
    # Expected order: rebuild the schedule from the SAME loss/grad builder
    # the step probes.
    vag = training._build_value_and_grad(
        _MLP(), training.cross_entropy_loss, False)
    vag_grads = jax.tree_util.tree_leaves(state.params)
    order = fusion.probe_grad_order(
        lambda p: vag(p, None, jnp.asarray(b[0]), jnp.asarray(b[1]),
                      jax.random.PRNGKey(0))[1], state.params)
    assert order is not None and len(order) == len(vag_grads)
    sched = fusion.plan_schedule(vag_grads, order,
                                 fusion_threshold=_THRESH)
    expect_sizes = [sum(int(np.prod(vag_grads[j].shape)) for j in bucket)
                    for bucket in sched.buckets]
    txt = _lowered_text(over, state, b)
    got_sizes = [_flat_size(t)
                 for t in _op_operand_types(txt, r"all_reduce")
                 if t != "f32"]  # drop the scalar loss pmean
    assert got_sizes == expect_sizes, (got_sizes, expect_sizes)
    assert len(re.findall(r"optimization_barrier", txt)) == \
        len(expect_sizes) - 1


def test_zero_overlap_keeps_plan_and_counts():
    """ZeRO + overlap: same reduce-scatter/all-gather counts as the
    non-overlapped plan, bucket membership identical (the plan IS the
    sharded state layout), scatters emitted in readiness order."""
    b = _batch()
    state, _, plain = _build(zero=True)
    state2, _, over = _build(zero=True, overlap=True)
    assert state.opt_state.plan == state2.opt_state.plan
    nb = len(state.opt_state.plan.buckets)

    def _counts(step, st):
        txt = _lowered_text(step, st, b)
        return (len(re.findall(r"\breduce_scatter\b", txt)),
                len(re.findall(r"\ball_gather\b", txt)),
                len(re.findall(r"\ball_reduce\b", txt)))

    assert _counts(plain, state) == (nb, nb, 1)
    assert _counts(over, state2) == (nb, nb, 1)


# ---------------------------------------------------------------------------
# Wire formats: HLO dtype pins.
# ---------------------------------------------------------------------------

def _op_operand_types(txt, op):
    """Operand tensor types of every ``op`` application in lowered
    stablehlo text, in trace order. Region-carrying ops (all_reduce,
    reduce_scatter) put the type signature on the region-closing line;
    single-line ops (all_gather) carry it inline — either way it is the
    first ``: (tensor<...>`` after the op name. The ``stablehlo.`` prefix
    keys on applications only (attributes like ``all_gather_dim`` must
    not double-count)."""
    out = []
    for m in re.finditer(r"stablehlo\." + op, txt):
        t = re.search(r":\s*\(tensor<([^>]+)>", txt[m.end():m.end() + 8000])
        if t:
            out.append(t.group(1))
    return out


def _flat_size(mlir_type):
    """Element count of a tensor type string like ``64x64xf32``."""
    n = 1
    for part in mlir_type.split("x")[:-1]:
        n *= int(part)
    return n


def test_bf16_wire_pins_operand_dtype_and_count():
    """Cast-on-send, pattern-pinned: every gradient bucket's all-reduce
    operand is bf16, the count is unchanged vs the fp32 wire (a wire cast
    must never merge or split buckets), and the loss pmean stays f32."""
    b = _batch()
    state, _, plain = _build()
    state, _, wired = _build(wire_dtype="bf16")
    txt_plain = _lowered_text(plain, state, b)
    txt = _lowered_text(wired, state, b)
    n = len(re.findall(r"\ball_reduce\b", txt_plain))
    assert len(re.findall(r"\ball_reduce\b", txt)) == n
    types = _op_operand_types(txt, r"all_reduce")
    assert len(types) == n
    bf16 = [t for t in types if t.endswith("xbf16")]
    # All bucket collectives ride bf16; the scalar loss pmean stays f32.
    assert len(bf16) == n - 1, types


def test_bf16_wire_zero_scatter_dtype_pinned():
    """ZeRO plane: every reduce-scatter operand rides bf16; the update
    all-gather stays full precision (replicas must end bit-identical)."""
    b = _batch()
    state, _, step = _build(zero=True, wire_dtype="bf16")
    txt = _lowered_text(step, state, b)
    nb = len(state.opt_state.plan.buckets)
    rs_types = _op_operand_types(txt, r"reduce_scatter")
    assert len(rs_types) == nb
    assert all(t.endswith("xbf16") for t in rs_types), rs_types
    ag_types = _op_operand_types(txt, r"all_gather")
    assert len(ag_types) == nb
    assert all(t.endswith("xf32") for t in ag_types), ag_types


def test_fp8_wire_adds_exactly_one_pmax_per_bucket():
    """fp8's dynamic scale needs a world-consistent per-bucket amax: one
    scalar pmax per bucket is the ONLY collective any wire format adds
    (documented in docs/performance.md)."""
    b = _batch()
    state, _, plain = _build()
    n_plain = len(re.findall(r"\ball_reduce\b",
                             _lowered_text(plain, state, b)))
    state, _, f8 = _build(wire_dtype="fp8")
    txt = _lowered_text(f8, state, b)
    n_buckets = n_plain - 1  # minus the loss pmean
    assert len(re.findall(r"\ball_reduce\b", txt)) == n_plain + n_buckets
    types = _op_operand_types(txt, r"all_reduce")
    assert sum(t.endswith("xf8E4M3FN") for t in types) == n_buckets, types


# ---------------------------------------------------------------------------
# Parity: low-precision wire vs fp32 wire, both planes.
# ---------------------------------------------------------------------------

def _run(step, state, steps=4):
    losses = []
    for i in range(steps):
        state, m = step(state, _batch(seed=i))
        losses.append(float(m["loss"]))
    return state, losses


@pytest.mark.parametrize("mode", ["allreduce", "zero"])
def test_bf16_wire_matches_fp32_within_tolerance(mode):
    """Documented tolerance (docs/performance.md): bf16 wire loses only
    the one quantization on send (scales and accumulation are fp32), so
    a few training steps track the fp32-wire run to bf16 resolution."""
    zero = mode == "zero"
    state_r, _, step_r = _build(zero=zero)
    state_w, _, step_w = _build(zero=zero, wire_dtype="bf16")
    state_r, loss_r = _run(step_r, state_r)
    state_w, loss_w = _run(step_w, state_w)
    np.testing.assert_allclose(loss_w, loss_r, rtol=5e-3)
    # Params: adam scales each step by lr regardless of grad magnitude, so
    # a wire-resolution grad perturbation can move a coordinate by up to
    # ~lr per step before momentum smooths it — tolerance is steps x lr
    # (4 x 1e-2), the bound docs/performance.md documents.
    for a, b2 in zip(jax.tree_util.tree_leaves(_np_tree(state_w.params)),
                     jax.tree_util.tree_leaves(_np_tree(state_r.params))):
        np.testing.assert_allclose(a, b2, rtol=5e-2, atol=4e-2)


@pytest.mark.parametrize("mode", ["allreduce", "zero"])
def test_fp8_wire_matches_fp32_within_tolerance(mode):
    """fp8 e4m3 keeps 3 mantissa bits: coarser, but the dynamic per-bucket
    scale keeps values in range — training stays close over a few steps."""
    zero = mode == "zero"
    state_r, _, step_r = _build(zero=zero)
    state_w, _, step_w = _build(zero=zero, wire_dtype="fp8")
    state_r, loss_r = _run(step_r, state_r)
    state_w, loss_w = _run(step_w, state_w)
    np.testing.assert_allclose(loss_w, loss_r, rtol=5e-2)
    for a, b2 in zip(jax.tree_util.tree_leaves(_np_tree(state_w.params)),
                     jax.tree_util.tree_leaves(_np_tree(state_r.params))):
        np.testing.assert_allclose(a, b2, rtol=5e-1, atol=5e-2)


def test_overlap_is_bit_exact_vs_plain_fp32():
    """Overlap only reorders emission (barriers + schedule): with the same
    fp32 wire the training trajectory must agree to float tolerance.
    (Bucket membership changes, so the reduction grouping — and thus the
    last-ulp rounding — may differ; allclose, not bit-equal.)"""
    state_r, _, step_r = _build()
    state_o, _, step_o = _build(overlap=True)
    state_r, loss_r = _run(step_r, state_r)
    state_o, loss_o = _run(step_o, state_o)
    np.testing.assert_allclose(loss_o, loss_r, rtol=1e-6)
    for a, b2 in zip(jax.tree_util.tree_leaves(_np_tree(state_o.params)),
                     jax.tree_util.tree_leaves(_np_tree(state_r.params))):
        np.testing.assert_allclose(a, b2, rtol=1e-5, atol=1e-7)


def test_replicas_bit_identical_after_zero_wire_gather():
    """Acceptance: zero=True + compression/wire keeps replicas
    bit-identical after the update all-gather — every device holds the
    same params bytes."""
    state, _, step = _build(zero=True, wire_dtype="bf16")
    state, _ = _run(step, state, steps=2)
    for leaf in jax.tree_util.tree_leaves(state.params):
        shards = leaf.addressable_shards
        ref = np.asarray(shards[0].data)
        for s in shards[1:]:
            np.testing.assert_array_equal(np.asarray(s.data), ref)


# ---------------------------------------------------------------------------
# Compositions: guard + accum + overlap + wire.
# ---------------------------------------------------------------------------

def test_guard_skip_bit_stable_under_overlap_and_wire():
    """The full stack: a NaN batch leaves params AND the sharded opt state
    bit-unchanged with overlap + bf16 wire armed (the skip decision rides
    the same channels as before — no new collectives, no divergence)."""
    state, _, step = _build(zero=True, overlap=True, wire_dtype="bf16",
                            guard=True)
    before_p = _np_tree(state.params)
    before_o = _np_tree(state.opt_state)
    s2, m = step(state, _batch(nan_at=3))
    assert float(m["bad_step"]) == 1.0
    for a, b2 in zip(jax.tree_util.tree_leaves(_np_tree(s2.params)),
                     jax.tree_util.tree_leaves(before_p)):
        np.testing.assert_array_equal(a, b2)
    for a, b2 in zip(jax.tree_util.tree_leaves(_np_tree(s2.opt_state)),
                     jax.tree_util.tree_leaves(before_o)):
        np.testing.assert_array_equal(a, b2)
    # The next finite batch trains.
    s3, m2 = step(s2, _batch(seed=5))
    assert float(m2["bad_step"]) == 0.0


def test_guard_adds_zero_collectives_with_overlap_and_wire():
    b = _batch()
    state, dist_opt, _ = _build(zero=True, overlap=True, wire_dtype="bf16")
    model = _MLP()

    def _counts(g):
        step = training.make_train_step(model, dist_opt, donate=False,
                                        overlap=True, guard_nonfinite=g)
        txt = _lowered_text(step, state, b)
        return (len(re.findall(r"\breduce_scatter\b", txt)),
                len(re.findall(r"\ball_gather\b", txt)),
                len(re.findall(r"\ball_reduce\b", txt)))

    assert _counts(True) == _counts(False)


def test_accum_composes_with_overlap_and_wire():
    """One scatter per ACCUMULATED step, wire or not, overlapped or not —
    and parity with the replicated fp32 path holds to wire tolerance."""
    state_r, _, step_r = _build(accum=2)
    state_w, _, step_w = _build(accum=2, overlap=True, wire_dtype="bf16")
    b = _batch(rows=32)
    state_r, _ = step_r(state_r, b)
    state_w, _ = step_w(state_w, b)
    # One adam step can move a coordinate by up to ~lr either way under a
    # wire-resolution grad difference: atol spans 2 x lr.
    for a, b2 in zip(jax.tree_util.tree_leaves(_np_tree(state_w.params)),
                     jax.tree_util.tree_leaves(_np_tree(state_r.params))):
        np.testing.assert_allclose(a, b2, rtol=5e-2, atol=2.5e-2)
    txt = _lowered_text(step_w, state_w, b)
    n_plain = len(re.findall(r"\ball_reduce\b",
                             _lowered_text(step_r, state_r, b)))
    assert len(re.findall(r"\ball_reduce\b", txt)) == n_plain


# ---------------------------------------------------------------------------
# Prescale precision (ISSUE 6 satellite): fp32 prescale for sub-fp32
# buckets.
# ---------------------------------------------------------------------------

def test_prescale_applies_in_fp32_for_bf16_buckets():
    """`fused_allreduce(prescale=)` on bf16 leaves must match the fp32
    reference to one final rounding: scale in fp32, cast once at the end.
    The old dtype-cast prescale (bf16(1/3) then bf16 multiply) double-
    rounds and misses for values this test pins."""
    rng = np.random.RandomState(0)
    vals = rng.randn(257).astype(np.float32)
    x = jnp.asarray(vals, jnp.bfloat16)
    p = 1.0 / 3.0
    scaled = fusion._prescale_array(x, p)
    assert scaled.dtype == jnp.bfloat16
    want = (np.asarray(x, np.float32) * np.float32(p)).astype(jnp.bfloat16)
    np.testing.assert_array_equal(np.asarray(scaled), np.asarray(want))
    # And the old behavior provably differs somewhere on this input (the
    # fix is observable, not vacuous).
    old = np.asarray(
        (x * jnp.asarray(p, jnp.bfloat16)))
    assert not np.array_equal(old, np.asarray(want))


def test_prescale_integer_leaves_untouched():
    x = jnp.arange(8, dtype=jnp.int32)
    np.testing.assert_array_equal(
        np.asarray(fusion._prescale_array(x, 0.5)), np.arange(8))


# ---------------------------------------------------------------------------
# API guards.
# ---------------------------------------------------------------------------

def test_unknown_wire_dtype_raises_eagerly():
    with pytest.raises(ValueError, match="wire_dtype"):
        hvd.DistributedOptimizer(optax.sgd(0.1), wire_dtype="fp16x")


def test_compression_plus_wire_raises_on_allreduce_plane():
    with pytest.raises(ValueError, match="pick one"):
        hvd.DistributedOptimizer(optax.sgd(0.1),
                                 compression=hvd.Compression.bf16,
                                 wire_dtype="bf16")


def test_overlap_requires_distributed_optimizer():
    hvd.init()
    with pytest.raises(ValueError, match="overlap"):
        training.make_train_step(_MLP(), optax.adam(1e-2), overlap=True)


def test_env_defaults_arm_overlap_and_wire(monkeypatch):
    monkeypatch.setenv("HVD_OVERLAP", "1")
    monkeypatch.setenv("HVD_WIRE_DTYPE", "bf16")
    state, dist_opt, step = _build()
    assert getattr(dist_opt.update, "overlap", False) is True
    assert getattr(dist_opt.update, "wire_dtype", None) == "bf16"
    txt = _lowered_text(step, state, _batch())
    assert _op_operand_types(txt, r"all_reduce")
    assert any(t.endswith("xbf16")
               for t in _op_operand_types(txt, r"all_reduce"))
    monkeypatch.delenv("HVD_OVERLAP")
    monkeypatch.delenv("HVD_WIRE_DTYPE")
    _, dist_opt, _ = _build()
    assert getattr(dist_opt.update, "overlap", True) is False
    assert getattr(dist_opt.update, "wire_dtype", None) == "fp32"
