"""Collective correctness by algebraic identity (reference test model:
``horovod/tensorflow/mpi_ops_test.py`` — expected values derived from
rank/size, dtype×dim product sweeps, fused variants, per-root broadcast;
SURVEY §4)."""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import horovod_tpu as hvd

DTYPES = [jnp.float32, jnp.float64, jnp.int32, jnp.int64]  # mpi_ops_test.py:92
DIMS = [1, 2, 3]


def _world_step(fn):
    """shard_map a per-rank function over the world mesh (the compiled
    context every in-trace collective runs in)."""
    return jax.jit(jax.shard_map(
        fn, mesh=hvd.mesh(), in_specs=P("hvd"), out_specs=P()))


def _stacked(x_np):
    """Per-rank stacked input: leading dim == size, one slice per rank."""
    return jax.device_put(x_np, NamedSharding(hvd.mesh(), P("hvd")))


# ---------------------------------------------------------------------------
# Allreduce: sum of per-rank tensors == sum of slices (mpi_ops_test.py:85-114
# checks allreduce(seeded random) == tensor * size; with distinct per-rank
# values the identity generalizes to the exact slice sum).
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype,dim", list(itertools.product(DTYPES, DIMS)))
def test_allreduce_in_trace(dtype, dim):
    size = hvd.size()
    shape = (size,) + (4,) * dim
    rng = np.random.RandomState(1234)
    x = rng.randint(-10, 10, size=shape).astype(dtype)

    out = _world_step(lambda t: hvd.allreduce(t[0], average=False))(
        _stacked(x))
    np.testing.assert_allclose(np.asarray(out), x.sum(axis=0), rtol=1e-6)


def test_allreduce_average_in_trace():
    size = hvd.size()
    x = np.arange(size * 8, dtype=np.float32).reshape(size, 8)
    out = _world_step(lambda t: hvd.allreduce(t[0], average=True))(
        _stacked(x))
    np.testing.assert_allclose(np.asarray(out), x.mean(axis=0), rtol=1e-6)


@pytest.mark.parametrize("dtype", DTYPES)
def test_allreduce_eager_per_rank(dtype):
    size = hvd.size()
    x = np.arange(size * 6).reshape(size, 6).astype(dtype)
    out = hvd.allreduce(_stacked(x), average=False)
    np.testing.assert_allclose(np.asarray(out), x.sum(axis=0), rtol=1e-6)


def test_allreduce_eager_replicated():
    # Every rank contributes the same tensor → sum == tensor * size
    # (exactly the mpi_ops_test.py:85-114 identity).
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    out = hvd.allreduce(x, average=False)
    np.testing.assert_allclose(np.asarray(out), x * hvd.size(), rtol=1e-6)
    out = hvd.allreduce(x, average=True)
    np.testing.assert_allclose(np.asarray(out), x, rtol=1e-6)


def test_allreduce_extra_ops():
    size = hvd.size()
    x = np.arange(size, dtype=np.float32).reshape(size, 1)
    mx = _world_step(lambda t: hvd.allreduce(t[0], op=hvd.Op.MAX))(_stacked(x))
    mn = _world_step(lambda t: hvd.allreduce(t[0], op=hvd.Op.MIN))(_stacked(x))
    assert float(mx[0]) == size - 1
    assert float(mn[0]) == 0


# ---------------------------------------------------------------------------
# Fused variants: many allreduces in flight at once get bucketed
# (mpi_ops_test.py:116-148 builds all ops before one session.run).
# ---------------------------------------------------------------------------

def test_allreduce_fused_many_tensors():
    size = hvd.size()
    rng = np.random.RandomState(7)
    tensors = [rng.randn(size, 5, 3).astype(np.float32) for _ in range(17)]

    def step(*ts):
        return hvd.grouped_allreduce([t[0] for t in ts], average=False)

    fn = jax.jit(jax.shard_map(
        step, mesh=hvd.mesh(),
        in_specs=tuple(P("hvd") for _ in tensors),
        out_specs=P()))
    outs = fn(*[_stacked(t) for t in tensors])
    for out, t in zip(outs, tensors):
        np.testing.assert_allclose(np.asarray(out), t.sum(axis=0), rtol=1e-5)


def test_allreduce_fused_mixed_dtype_preserves_values():
    size = hvd.size()
    a = np.ones((size, 4), np.float32)
    b = (2 * np.ones((size, 4))).astype(np.int32)
    c = (3 * np.ones((size, 4))).astype(np.float32)

    def step(ta, tb, tc):
        return hvd.grouped_allreduce([ta[0], tb[0], tc[0]], average=False)

    fn = jax.jit(jax.shard_map(
        step, mesh=hvd.mesh(), in_specs=(P("hvd"),) * 3, out_specs=P()))
    ra, rb, rc = fn(_stacked(a), _stacked(b), _stacked(c))
    np.testing.assert_array_equal(np.asarray(ra), a.sum(axis=0))
    np.testing.assert_array_equal(np.asarray(rb), b.sum(axis=0))
    np.testing.assert_array_equal(np.asarray(rc), c.sum(axis=0))
    assert rb.dtype == jnp.int32


# ---------------------------------------------------------------------------
# Allgather: output = per-rank blocks in rank order (mpi_ops_test.py:358-394
# gathers per-rank constant blocks and checks slice-by-slice).
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype,dim", list(itertools.product(DTYPES, DIMS)))
def test_allgather_in_trace(dtype, dim):
    size = hvd.size()
    block = (2,) + (3,) * (dim - 1) if dim > 1 else (2,)
    x = np.stack([np.full(block, r) for r in range(size)]).astype(dtype)

    out = _world_step(lambda t: hvd.allgather(t[0]))(_stacked(x))
    out = np.asarray(out)
    assert out.shape == (size * block[0],) + block[1:]
    for r in range(size):
        np.testing.assert_array_equal(
            out[r * block[0]:(r + 1) * block[0]], np.full(block, r))


def test_allgather_eager_per_rank():
    size = hvd.size()
    x = np.stack([np.full((2, 3), r, np.float32) for r in range(size)])
    out = np.asarray(hvd.allgather(_stacked(x)))
    assert out.shape == (2 * size, 3)
    for r in range(size):
        np.testing.assert_array_equal(out[2 * r:2 * r + 2], x[r])


def test_allgather_ragged_in_trace():
    """Variable first dims per rank (mpi_ops_test.py:396-442) under XLA
    static shapes: pad-to-max + negotiated sizes vector."""
    size = hvd.size()
    max_rows = size + 1
    # rank r contributes r+1 rows of value r
    x = np.zeros((size, max_rows, 2), np.float32)
    for r in range(size):
        x[r, :r + 1, :] = r

    def step(t):
        valid = jax.lax.axis_index("hvd") + 1
        return hvd.allgather_ragged(t[0], valid, max_rows)

    gathered, sizes = _world_step(step)(_stacked(x))
    gathered, sizes = np.asarray(gathered), np.asarray(sizes)
    np.testing.assert_array_equal(sizes, np.arange(1, size + 1))
    for r in range(size):
        block = gathered[r * max_rows:(r + 1) * max_rows]
        np.testing.assert_array_equal(block[:r + 1], np.full((r + 1, 2), r))
        np.testing.assert_array_equal(block[r + 1:],
                                      np.zeros((max_rows - r - 1, 2)))


def test_allgather_ragged_validates_sizes():
    """valid_size/max_size are NOT advisory (VERDICT r3 weak #6): an input
    with more rows than max_size, or a concrete valid_size outside
    [0, max_size], must fail with the coordinator's ALLGATHER error
    wording (negotiated-size parity, mpi_ops.cc:345-405) — never silently
    truncate."""
    size = hvd.size()
    x = np.zeros((size, 4, 2), np.float32)

    # Tensor wider than max_size: cannot truncate.
    def step_too_wide(t):
        return hvd.allgather_ragged(t[0], 2, 3)  # 4 rows > max_size 3

    with pytest.raises(ValueError, match="Mismatched ALLGATHER"):
        _world_step(step_too_wide)(_stacked(x))

    # Concrete oversized valid_size: would silently drop rows.
    def step_oversized_valid(t):
        return hvd.allgather_ragged(t[0], 9, 4)

    with pytest.raises(ValueError, match="Mismatched ALLGATHER"):
        _world_step(step_oversized_valid)(_stacked(x))

    # Traced out-of-range valid_size cannot raise inside jit: it must
    # CLAMP (mask stays sane, sizes stay <= max_size), not corrupt.
    def step_traced(t):
        valid = jax.lax.axis_index("hvd") + 100  # way past max_size 4
        return hvd.allgather_ragged(t[0], valid, 4)

    gathered, sizes = _world_step(step_traced)(_stacked(x))
    assert int(np.max(np.asarray(sizes))) <= 4
    assert np.asarray(gathered).shape == (4 * size, 2)


# ---------------------------------------------------------------------------
# Broadcast: result equals the root's tensor for every root rank
# (mpi_ops_test.py:480-512).
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("dtype", DTYPES + [jnp.bool_])
def test_broadcast_in_trace_all_roots(dtype):
    size = hvd.size()
    if dtype == jnp.bool_:
        x = np.stack([np.full((3, 2), r % 2, bool) for r in range(size)])
    else:
        x = np.stack([np.full((3, 2), r) for r in range(size)]).astype(dtype)
    for root in range(size):
        out = _world_step(lambda t: hvd.broadcast(t[0], root_rank=root))(
            _stacked(x))
        np.testing.assert_array_equal(np.asarray(out), x[root])
        assert out.dtype == x.dtype


def test_broadcast_eager_per_rank():
    size = hvd.size()
    x = np.stack([np.full((4,), r, np.float32) for r in range(size)])
    for root in (0, size - 1):
        out = hvd.broadcast(_stacked(x), root_rank=root)
        np.testing.assert_array_equal(np.asarray(out), x[root])


# ---------------------------------------------------------------------------
# rank()/size() in both contexts (mpi_ops_test.py reads launcher env;
# ours derive from the mesh).
# ---------------------------------------------------------------------------

def test_rank_and_size():
    assert hvd.size() == len(jax.devices())
    assert hvd.local_rank() == 0
    assert hvd.rank() == 0  # controller rank outside compiled code

    ranks = np.asarray(_world_step(
        lambda t: hvd.allgather(jnp.reshape(hvd.rank(), (1,)) + 0 * t[0][:1, 0]))(
        _stacked(np.zeros((hvd.size(), 2, 2), np.float32))))
    np.testing.assert_array_equal(ranks, np.arange(hvd.size()))


def test_not_initialized_error():
    import horovod_tpu.runtime as rt
    saved = rt._world
    rt._world = None
    try:
        with pytest.raises(hvd.NotInitializedError):
            hvd.size()
    finally:
        rt._world = saved


# ---------------------------------------------------------------------------
# TPU-era extras.
# ---------------------------------------------------------------------------

def test_alltoall_in_trace():
    size = hvd.size()
    # rank r sends block (r, c) to rank c; after all_to_all, rank r holds
    # blocks (c, r) for all c.
    x = np.arange(size * size, dtype=np.float32).reshape(size, size, 1)

    def step(t):
        local = t[0]  # [size, 1] — row r of the matrix
        return hvd.allgather(hvd.alltoall(local))

    out = np.asarray(_world_step(step)(_stacked(x)))
    # rank r's post-alltoall block is column r → gathered = x.T flattened
    np.testing.assert_array_equal(
        out.reshape(size, size), x.reshape(size, size).T)


def test_reducescatter_in_trace():
    size = hvd.size()
    x = np.stack([np.arange(size * 2, dtype=np.float32) + r
                  for r in range(size)])

    def step(t):
        return hvd.allgather(hvd.reducescatter(t[0]))

    out = np.asarray(_world_step(step)(_stacked(x)))
    np.testing.assert_allclose(out, x.sum(axis=0))


def test_alltoall_eager_single_controller():
    """Eager alltoall on a world-sharded array: per-rank block b of rank s
    lands as slot s of rank b (global view preserved by the out sharding)."""
    size = hvd.size()
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    # Global [size*size]: rank r holds r*size..(r+1)*size-1, block c = one
    # element; after the exchange rank r holds [c*size+r for c in ranks].
    x = jax.device_put(np.arange(size * size, dtype=np.float32),
                       NamedSharding(hvd.mesh(), P(hvd.AXIS)))
    out = np.asarray(hvd.alltoall(x))
    expect = np.arange(size * size).reshape(size, size).T.reshape(-1)
    np.testing.assert_array_equal(out, expect)


def test_reducescatter_eager_single_controller():
    size = hvd.size()
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    x = jax.device_put(np.ones(size * size, np.float32),
                       NamedSharding(hvd.mesh(), P(hvd.AXIS)))
    out = np.asarray(hvd.reducescatter(x))
    # Per-rank block [1] = sum over ranks; global out [size].
    np.testing.assert_allclose(out, np.full((size,), size, np.float32))
    avg = np.asarray(hvd.reducescatter(x, average=True))
    np.testing.assert_allclose(avg, np.ones((size,), np.float32))


def test_alltoall_eager_requires_sharded_input():
    with pytest.raises(ValueError, match="sharded over the world axis"):
        hvd.alltoall(np.ones(hvd.size() ** 2, np.float32))


def test_broadcast_repairs_nan_on_nonroot_ranks():
    """Broadcast must deliver the root's values even when non-root ranks
    hold NaN/Inf — re-syncing diverged replicas is its main job (§5.4)."""
    size = hvd.size()
    x = np.stack([np.full((3,), 1.0 if r == 0 else np.nan, np.float32)
                  for r in range(size)])
    out = _world_step(lambda t: hvd.broadcast(t[0], root_rank=0))(_stacked(x))
    np.testing.assert_array_equal(np.asarray(out), np.ones((3,)))


def test_broadcast_root_rank_out_of_range():
    with pytest.raises(ValueError, match="out of range"):
        hvd.broadcast(np.ones(3), root_rank=hvd.size())


def test_sparse_allreduce_rejects_unsupported_op():
    from horovod_tpu.ops.sparse import IndexedSlices
    s = IndexedSlices(jnp.ones((1, 2)), jnp.zeros((1,), jnp.int32), (4, 2))
    with pytest.raises(ValueError, match="not supported for sparse"):
        hvd.allreduce(s, op=hvd.Op.MAX)
