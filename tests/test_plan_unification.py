"""ISSUE 20 — one plan, every plane.

Pins the unified spec-grouped collective plan (`plan_grad_sync` →
`GradSync`) against its per-leaf empirical reference
(`parallel.mesh.grad_sync_by_spec`), the pipelined transformer's
interpretation of it on the full 3-D dp×tp×pp mesh (allclose vs the dp=8
reference from identical global weights), the HLO contract (one
collective per plan bucket; overlap/wire add zero), and the env-world
planner (`plan_exchange`) the host executor interprets.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu.ops import fusion
from horovod_tpu.parallel import create_hybrid_mesh
from horovod_tpu.parallel.mesh import grad_sync_by_spec
from horovod_tpu.parallel.pp_transformer import (
    make_pp_transformer_train_step, pp_param_specs)
from horovod_tpu.parallel.transformer import TransformerConfig


def _flatten_specs(specs):
    return jax.tree_util.tree_flatten(
        specs, is_leaf=lambda x: isinstance(x, P))[0]


# ---------------------------------------------------------------------------
# Plan/denominator parity: the fused GradSync interpretation must equal the
# per-leaf empirical walk bitwise, across mesh shapes and leaf kinds
# (replicated, tp col/row, pp-owned stage leaves under pp-skip, ep experts).
# ---------------------------------------------------------------------------

def _grid_case(mesh_kw, skip):
    mesh = create_hybrid_mesh(**mesh_kw)
    axes = set(mesh.axis_names)
    tp = "tp" if "tp" in axes else None
    specs = {"rep_v": P(), "rep_m": P(None, None)}
    shapes = {"rep_v": (16,), "rep_m": (8, 8)}
    if tp:
        specs["col"] = P(None, "tp")
        shapes["col"] = (8, 8)
        specs["row"] = P("tp", None)
        shapes["row"] = (8, 8)
    if "pp" in axes:
        specs["stage"] = P("pp", None)
        shapes["stage"] = (2, 8)
        specs["stage_tp"] = P("pp", None, tp)
        shapes["stage_tp"] = (2, 4, 2)
    if "ep" in axes:
        specs["expert"] = P("ep", None, None)
        shapes["expert"] = (2, 4, 4)
    rng = np.random.RandomState(7)
    grads = {k: jnp.asarray(rng.randn(*shapes[k]), jnp.float32)
             for k in specs}
    grads = jax.tree_util.tree_map(
        lambda g, s: jax.device_put(g, NamedSharding(mesh, s)),
        grads, specs, is_leaf=lambda x: isinstance(x, P))
    return mesh, specs, grads, skip


@pytest.mark.parametrize("mesh_kw,skip", [
    (dict(dp=8), ()),
    (dict(dp=4, tp=2), ()),
    (dict(dp=2, tp=2, pp=2), ("pp",)),
    (dict(dp=4, ep=2), ()),
], ids=["dp8", "dp4tp2", "dp2tp2pp2-ppskip", "dp4ep2"])
@pytest.mark.parametrize("threshold", [0, None], ids=["perleaf", "fused"])
def test_gradsync_plan_matches_empirical_reference(mesh_kw, skip, threshold):
    mesh, specs, grads, skip = _grid_case(mesh_kw, skip)
    mesh_axes = tuple(mesh.axis_names)
    syncs = fusion.plan_grad_sync(_flatten_specs(specs), mesh,
                                  skip_axes=skip)

    def body(g):
        ref = grad_sync_by_spec(g, specs, mesh_axes, skip_axes=skip)
        fused = fusion.fused_allreduce(
            g, average=True, fusion_threshold=threshold, reduce_axes=syncs)
        return ref, fused

    ref, fused = jax.jit(jax.shard_map(
        body, mesh=mesh, in_specs=(specs,), out_specs=(specs, specs),
        check_vma=False))(grads)
    # Bitwise: the fused plan folds 1/denom into a pre-psum scale while
    # the reference divides after — exact for the power-of-two axis sizes
    # every mesh here uses; fusion itself is elementwise-invariant.
    for k in specs:
        np.testing.assert_array_equal(np.asarray(ref[k]),
                                      np.asarray(fused[k]), err_msg=k)


def test_grad_sync_by_spec_stays_exported():
    """The empirical reference must survive the refactor as a module-scope
    re-export (the pp step body no longer calls it)."""
    import horovod_tpu.parallel.pp_transformer as ppt
    assert ppt.grad_sync_by_spec is grad_sync_by_spec


def test_plan_exchange_membership_and_denoms():
    """The env-world planner: same membership as the classic fusion scan,
    every denominator == the world size (what the coordinator's AVERAGE
    op realizes) — the data `training._make_env_world_step` interprets."""
    rng = np.random.RandomState(0)
    leaves = [np.asarray(rng.randn(*s), np.float32)
              for s in [(4, 4), (64,), (2, 3)]]
    leaves.append(np.zeros((5,), np.int32))  # dtype break
    buckets, syncs = fusion.plan_exchange(leaves, world_size=4)
    assert buckets == fusion.plan_buckets(leaves)
    assert len(syncs) == len(leaves)
    assert all(s.denom == 4 and s.psum and not s.shard for s in syncs)
    # Threshold riding the stamp: per-leaf buckets at 0.
    b0, _ = fusion.plan_exchange(leaves, world_size=4, fusion_threshold=0)
    assert len(b0) == len(leaves)


def test_distributed_optimizer_stamps_exchange_plan():
    from horovod_tpu.optimizer import DistributedOptimizer
    opt = DistributedOptimizer(optax.sgd(0.1), fusion_threshold=0)
    leaves = [np.ones((3,), np.float32), np.ones((3,), np.float32)]
    buckets, syncs = opt.update.exchange_plan(leaves, world_size=2)
    assert len(buckets) == 2  # the stamped threshold (0) is interpreted
    assert syncs[0].denom == 2


# ---------------------------------------------------------------------------
# The 3-D mesh: pipelined transformer on (dp=2, tp=2, pp=2).
# ---------------------------------------------------------------------------

CFG = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
           dtype=jnp.float32, unembed_dtype=jnp.float32,
           attn_backend="xla")


def _flat_from_pp(pp_params, n_stages, lps):
    """Convert the pipeline layout ([S, lps, ...] stacked stages) to the
    core family's per-layer list — identical global weights, so the two
    families must compute the same function."""
    layers = []
    st = pp_params["stages"]
    for s in range(n_stages):
        for i in range(lps):
            layers.append({k: np.asarray(st[k][s, i]) for k in st})
    return {"embed": np.asarray(pp_params["embed"]),
            "lnf": np.asarray(pp_params["lnf"]), "layers": layers}


@pytest.fixture(scope="module")
def pp3d():
    mesh = create_hybrid_mesh(dp=2, tp=2, pp=2)
    cfg = TransformerConfig(**CFG)
    cache = {}

    def build(**kw):
        key = tuple(sorted(kw.items()))
        if key not in cache:
            cache[key] = make_pp_transformer_train_step(
                cfg, mesh, optax.sgd(0.1), n_microbatches=2, **kw)
        return cache[key]

    return mesh, cfg, build


def _batch():
    rng = np.random.RandomState(3)
    tokens = jnp.asarray(rng.randint(0, 64, (8, 16)), jnp.int32)
    return tokens, jnp.roll(tokens, -1, axis=1)


def _run(build, n_steps=2, **kw):
    init_state, step = build(**kw)
    p, o = init_state(jax.random.PRNGKey(0))
    tokens, labels = _batch()
    losses = []
    for _ in range(n_steps):
        p, o, loss = step(p, o, tokens, labels)
        losses.append(float(loss))
    return losses, jax.tree_util.tree_map(np.asarray, p), (p, o, step)


def test_3d_step_matches_dp8_reference(pp3d):
    """(dp=2, tp=2, pp=2) training == pure-dp training of the SAME model
    from identical global weights: 2 SGD steps allclose (rtol 2e-4 — fp32
    with different collective/reduction orders), cross-FAMILY (the dp=8
    reference is parallel.transformer, per-layer layout)."""
    from horovod_tpu.parallel.transformer import make_parallel_train_step
    mesh, cfg, build = pp3d
    pp_losses, pp_p, _ = _run(build)

    init_state, step = make_parallel_train_step(
        cfg, create_hybrid_mesh(dp=8), optax.sgd(0.1))
    p0, o0 = init_state(jax.random.PRNGKey(1))
    # Identical global weights: graft the pp init onto the reference's
    # shardings (sgd state carries no param-shaped leaves to translate).
    pp_init, _ = build()
    src, _ = pp_init(jax.random.PRNGKey(0))
    flat = _flat_from_pp(jax.tree_util.tree_map(np.asarray, src),
                         n_stages=2, lps=cfg.n_layers // 2)
    p = jax.tree_util.tree_map(
        lambda tpl, v: jax.device_put(jnp.asarray(v), tpl.sharding),
        p0, flat)
    tokens, labels = _batch()
    ref_losses = []
    for _ in range(2):
        p, o0, loss = step(p, o0, tokens, labels)
        ref_losses.append(float(loss))
    np.testing.assert_allclose(pp_losses, ref_losses, rtol=2e-5)

    ref_pp_layout = {
        "embed": np.asarray(p["embed"]), "lnf": np.asarray(p["lnf"]),
        "stages": {k: np.stack(
            [np.stack([np.asarray(p["layers"][s * (cfg.n_layers // 2) + i][k])
                       for i in range(cfg.n_layers // 2)]) for s in range(2)])
            for k in ("ln1", "wqkv", "wo", "ln2", "w1", "w2")}}
    for a, b in zip(jax.tree_util.tree_leaves(pp_p),
                    jax.tree_util.tree_leaves(ref_pp_layout)):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-6)


def test_pp_overlap_bit_identical_and_zero_parity(pp3d):
    """overlap=True must be a pure reorder (bit-identical params), and
    zero=True (the spec-grouped ZeroPlan with pp as a real shard axis)
    must match the replicated update to fp32 tolerance."""
    _, _, build = pp3d
    _, base_p, _ = _run(build)
    _, over_p, _ = _run(build, overlap=True)
    for a, b in zip(jax.tree_util.tree_leaves(base_p),
                    jax.tree_util.tree_leaves(over_p)):
        np.testing.assert_array_equal(a, b)
    zl, zero_p, _ = _run(build, zero=True)
    for a, b in zip(jax.tree_util.tree_leaves(base_p),
                    jax.tree_util.tree_leaves(zero_p)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_pp_wire_bf16_tracks_fp32(pp3d):
    _, _, build = pp3d
    base_l, base_p, _ = _run(build)
    wire_l, wire_p, _ = _run(build, wire_dtype="bf16", overlap=True)
    np.testing.assert_allclose(wire_l, base_l, rtol=5e-3)
    for a, b in zip(jax.tree_util.tree_leaves(base_p),
                    jax.tree_util.tree_leaves(wire_p)):
        np.testing.assert_allclose(a, b, rtol=5e-2, atol=4e-2)


# ---------------------------------------------------------------------------
# HLO pins: one collective per plan bucket; overlap/wire add zero; the
# guard adds exactly its two documented scalar pmins; ZeRO rides one
# rs/ag pair per plan bucket.
# ---------------------------------------------------------------------------

def _counts(txt):
    return {p: len(re.findall(rf"\b{p}\b", txt))
            for p in ("reduce_scatter", "all_gather", "all_reduce")}


def _lowered(build, **kw):
    init_state, step = build(**kw)
    p, o = init_state(jax.random.PRNGKey(0))
    tokens, labels = _batch()
    return _counts(step.lower(p, o, tokens, labels).as_text()), (p, o)


def test_pp_hlo_one_collective_per_plan_bucket(pp3d):
    mesh, cfg, build = pp3d
    init_state, _ = build()
    params, _ = init_state(jax.random.PRNGKey(0))
    leaves = jax.tree_util.tree_leaves(params)
    syncs = fusion.plan_grad_sync(
        _flatten_specs(pp_param_specs(mesh)), mesh, skip_axes=("pp",))
    nb = len(fusion.plan_buckets(leaves, None, groups=syncs))
    assert nb == 2 and len(leaves) == 8  # head+norm group, tp-matrix group
    cd, _ = _lowered(build)
    c0, _ = _lowered(build, fusion_threshold=0)
    # fusion_threshold=0 degrades to one collective per LEAF; the default
    # plan emits one per BUCKET — the delta is exactly the fused leaves.
    assert c0["all_reduce"] - cd["all_reduce"] == len(leaves) - nb
    assert c0["reduce_scatter"] == cd["reduce_scatter"]
    assert c0["all_gather"] == cd["all_gather"]


def test_pp_hlo_overlap_wire_add_zero_collectives(pp3d):
    _, _, build = pp3d
    cd, _ = _lowered(build)
    cw, _ = _lowered(build, overlap=True, wire_dtype="bf16")
    assert cw == cd, (cd, cw)


def test_pp_hlo_guard_adds_two_scalar_pmins(pp3d):
    _, _, build = pp3d
    cd, _ = _lowered(build)
    cg, _ = _lowered(build, guard_nonfinite=True)
    # +1 pmin over tp (the tp-sharded bucket reduces over dp only; its
    # finite flag needs the missing-axes fold) and +1 pmin over pp (no
    # allreduce-plane bucket ever reduces over pp).
    assert cg["all_reduce"] - cd["all_reduce"] == 2, (cd, cg)
    assert cg["reduce_scatter"] == cd["reduce_scatter"]
    assert cg["all_gather"] == cd["all_gather"]


def test_pp_hlo_zero_rs_ag_per_plan_bucket(pp3d):
    _, _, build = pp3d
    cz, (p, o) = _lowered(build, zero=True)
    nb = len(o.plan.buckets)
    # pp rides the ZeroPlan as a shard axis: three spec groups on the
    # (dp, pp, tp) mesh (replicated head; pp-owned norms; pp×tp matrices).
    assert nb == 3
    assert cz["reduce_scatter"] == nb
    assert cz["all_gather"] == nb
