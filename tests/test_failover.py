"""Deterministic stream failover (ISSUE 15): the FleetRouter's
strand-and-resume plane, the serving-plane fault-injection grammar, the
retry budget, and the overload backoff hints.

Router failover LOGIC runs against fake generation engines whose token
streams are a pure function of the prompt — exactly the determinism
contract real seeded engines provide, at zero compile cost — so the
bulk of this file is milliseconds of host-side control flow. ONE
real-engine drill (the tier-1 budget rule) pins the end-to-end claim:
a ``replica_kill`` fault mid-stream strands zero streams and every
client-visible stream is bit-identical to an uninterrupted
single-engine run, with the dead replica leaving a flight-recorder
post-mortem naming its in-flight streams. The heavier open-loop chaos
drill lives in ci.sh (serve_bench --chaos), not here.
"""

import glob
import json
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from horovod_tpu import serve
from horovod_tpu.exceptions import (DeadlineExceededError,
                                    FailoverExhaustedError,
                                    ServerOverloadedError)
from horovod_tpu.obs.registry import parse_exposition, render
from horovod_tpu.serve.engine import ReadinessMixin
from horovod_tpu.serve.generate import GenerationHandle
from horovod_tpu.serve.metrics import FleetMetrics, ServeMetrics
from horovod_tpu.serve.router import FleetRouter
from horovod_tpu.testing import faults


# ---------------------------------------------------------------------------
# Fake generation engines: tokens are a pure function of the prompt, so
# a replay on ANY replica reproduces the stream — the property seeded
# real engines provide, without a single compile.
# ---------------------------------------------------------------------------

class _Cfg:
    default_deadline_ms = None


class _FakeGenEngine(ReadinessMixin):
    """Emits ``max_new_tokens`` tokens ``prompt[-1]+1, +2, ...``.

    ``strand_after=k`` emits k tokens and then goes silent WITHOUT
    finishing or failing the handle — the crashed-replica shape (a dead
    process delivers nothing; only the router's liveness verdict can
    wake the stream). ``fail_after=k`` emits k tokens then fails the
    handle with ``fail_with`` — the engine-loop-error shape.
    ``diverge`` offsets every token by 100: a replica that breaks the
    determinism contract."""

    def __init__(self, warmed=True, load=0, reject=None,
                 strand_after=None, fail_after=None, fail_with=None,
                 fail_always=False, finish_after=None, diverge=False):
        self._queue = []
        self._warmed = warmed
        self._closed = False
        self._load = load
        self._cfg = _Cfg()
        self.reject = reject
        self.reject_count = 0
        self.strand_after = strand_after
        self.fail_after = fail_after
        self.fail_with = fail_with or RuntimeError("engine loop error")
        self.fail_always = fail_always   # keep failing on every submit
        self.finish_after = finish_after  # truncated-but-"done" replay
        self.diverge = diverge
        self.alive_flag = True
        self.submits = []

    def load(self):
        return self._load

    def loop_alive(self, stall_s=0.0):
        return self.alive_flag

    def submit(self, tokens, *, max_new_tokens=4, sampling=None,
               eos_id=None, deadline_ms=None, adapter=None):
        if self.reject is not None:
            self.reject_count += 1
            raise self.reject
        self.submits.append({"tokens": list(tokens),
                             "deadline_ms": deadline_ms,
                             "adapter": adapter})
        off = 100 if self.diverge else 0
        toks = [int(tokens[-1]) + 1 + i + off
                for i in range(max_new_tokens)]
        h = GenerationHandle()
        if self.strand_after is not None:
            for t in toks[:self.strand_after]:
                h._emit(t)
            self.strand_after = None    # a later replay runs clean
        elif self.fail_after is not None:
            for t in toks[:self.fail_after]:
                h._emit(t)
            h._fail(self.fail_with)
            if not self.fail_always:
                self.fail_after = None
        elif self.finish_after is not None:
            short = toks[:self.finish_after]
            for t in short:
                h._emit(t)
            h._finish({"tokens": short, "finish_reason": "length",
                       "n_tokens": len(short)})
        else:
            for t in toks:
                h._emit(t)
            h._finish({"tokens": toks, "finish_reason": "length",
                       "n_tokens": len(toks)})
        return h

    def warmup(self):
        self._warmed = True

    def shutdown(self, drain=True, timeout=None):
        self._closed = True

    def stats(self):
        return {}

    def prom_collect(self):
        return {}, []


def _router(*engines, **kw):
    # poll_interval_s=0: tests deliver liveness verdicts via poll() —
    # deterministic, no background sweep racing the assertions.
    kw.setdefault("poll_interval_s", 0)
    kw.setdefault("failover_backoff_s", 0.001)
    return FleetRouter(engines=list(engines), **kw)


@pytest.fixture
def fault_spec(monkeypatch):
    """Arm HVD_FAULT_SPEC for one test; always disarm the fired-set."""
    def arm(spec):
        monkeypatch.setenv("HVD_FAULT_SPEC", spec)
        faults.reset()
    yield arm
    faults.reset()


# ---------------------------------------------------------------------------
# Failover on fakes: the router contracts.
# ---------------------------------------------------------------------------

class TestFailover:
    def test_dead_replica_strands_nothing_stream_resumes_bit_identical(self):
        e0 = _FakeGenEngine(load=0, strand_after=2)
        e1 = _FakeGenEngine(load=5)
        router = _router(e0, e1)
        h = router.submit([7], max_new_tokens=4)
        # e0 (least loaded) took the stream, emitted 2 tokens, froze.
        deadline = time.monotonic() + 5
        while len(h._tokens) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert h._tokens == [8, 9] and not h.done()
        e0.alive_flag = False
        router.poll()           # the liveness verdict: strand-and-resume
        r = h.result(timeout=5)
        # The client's single stream: the replayed prefix was suppressed
        # (never re-emitted) and the tail continued bit-identically.
        assert r["tokens"] == [8, 9, 10, 11]
        assert h._tokens == [8, 9, 10, 11]
        assert r["failovers"] == 1
        assert e1.submits and e1.submits[0]["tokens"] == [7]
        assert router._metrics.failover_counts() == {"resumed": 1,
                                                     "exhausted": 0}
        assert router._metrics.stranded_count() == 1
        assert router.counts()["ready"] == 1    # e0 evicted, not drained
        router.shutdown()

    def test_engine_loop_error_fails_over_without_liveness_verdict(self):
        # A stream-level engine failure (the loop delivered an error
        # through the handle) re-dispatches immediately — no poll needed.
        e0 = _FakeGenEngine(load=0, fail_after=1)
        e1 = _FakeGenEngine(load=5)
        router = _router(e0, e1)
        r = router.submit([3], max_new_tokens=3).result(timeout=5)
        assert r["tokens"] == [4, 5, 6]
        assert r["failovers"] == 1
        assert router._metrics.failover_counts()["resumed"] == 1
        router.shutdown()

    def test_retry_budget_exhausts_loudly_never_loops(self):
        # The budget counts replicas the stream may FAIL ON: a sick
        # survivor burning every re-dispatch exhausts after exactly
        # failover_retries of them.
        e0 = _FakeGenEngine(load=0, strand_after=1)
        e1 = _FakeGenEngine(load=5, fail_after=0, fail_always=True)
        router = _router(e0, e1, failover_retries=2)
        h = router.submit([5], max_new_tokens=4)
        deadline = time.monotonic() + 5
        while len(h._tokens) < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        e0.alive_flag = False
        router.poll()
        with pytest.raises(FailoverExhaustedError, match="re-submit"):
            h.result(timeout=5)
        # Exactly the budget's worth of re-dispatches — no storm — and
        # the client kept the tokens it had, none double-emitted.
        assert len(e1.submits) == 2
        assert h._tokens == [6]
        # None of the re-dispatches verified its replayed prefix (the
        # sick replica failed before reproducing it), so the outcome is
        # ONE exhausted, zero resumed — the labels partition verdicts.
        assert router._metrics.failover_counts() == {"resumed": 0,
                                                     "exhausted": 1}
        # Each failed host is one strand event: e0's death + 2 sick
        # re-dispatches.
        assert router._metrics.stranded_count() == 3
        router.shutdown()

    def test_overload_waits_on_the_hint_without_burning_the_budget(self):
        # Fleet overload during failover is the FLEET's condition: the
        # stream naps on the rejection's retry_after_ms hint, bounded
        # by the failover_overload_wait_s wall clock — the re-dispatch
        # budget is never consumed, and the naps follow the hint (far
        # fewer attempts than the backoff floor would produce).
        reject = ServerOverloadedError("queue full")
        reject.retry_after_ms = 10.0
        e0 = _FakeGenEngine(load=0, strand_after=1)
        e1 = _FakeGenEngine(load=5, reject=reject)
        router = _router(e0, e1, failover_retries=2,
                         failover_overload_wait_s=0.08)
        h = router.submit([5], max_new_tokens=4)
        deadline = time.monotonic() + 5
        while len(h._tokens) < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        e0.alive_flag = False
        router.poll()
        with pytest.raises(FailoverExhaustedError, match="re-submit"):
            h.result(timeout=5)
        # ~0.08 s of 10 ms naps: several attempts, nowhere near the
        # ~80 a 1 ms backoff floor would have made — and no re-dispatch
        # ever succeeded, so the budget shows zero consumed.
        assert 2 <= e1.reject_count <= 30
        assert h._tokens == [6]
        assert router._metrics.failover_counts() == {"resumed": 0,
                                                     "exhausted": 1}
        assert router._metrics.stranded_count() == 1
        router.shutdown()

    def test_diverging_replay_fails_loudly_never_mis_continues(self):
        e0 = _FakeGenEngine(load=0, strand_after=2)
        e1 = _FakeGenEngine(load=5, diverge=True)
        router = _router(e0, e1)
        h = router.submit([7], max_new_tokens=4)
        deadline = time.monotonic() + 5
        while len(h._tokens) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        e0.alive_flag = False
        router.poll()
        with pytest.raises(FailoverExhaustedError, match="diverged"):
            h.result(timeout=5)
        # The suppression cursor VERIFIES the replay: the client saw no
        # diverging token and no duplicate of its emitted prefix — and
        # the outcome labels PARTITION verdicts: a diverging re-dispatch
        # counts exhausted alone, never also resumed.
        assert h._tokens == [8, 9]
        assert router._metrics.failover_counts() == {"resumed": 0,
                                                     "exhausted": 1}
        router.shutdown()

    def test_replay_finishing_short_of_the_prefix_is_divergence(self):
        # A replay that ends BEFORE reproducing what the client already
        # holds is divergence by omission — terminal, never a silent
        # truncation of the client's stream.
        e0 = _FakeGenEngine(load=0, strand_after=2)
        e1 = _FakeGenEngine(load=5, finish_after=1)
        router = _router(e0, e1)
        h = router.submit([7], max_new_tokens=4)
        deadline = time.monotonic() + 5
        while len(h._tokens) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        e0.alive_flag = False
        router.poll()
        with pytest.raises(FailoverExhaustedError, match="finished"):
            h.result(timeout=5)
        assert h._tokens == [8, 9]
        assert router._metrics.failover_counts() == {"resumed": 0,
                                                     "exhausted": 1}
        router.shutdown()

    def test_failover_avoids_the_replica_the_stream_just_failed_on(self):
        # A SICK-but-alive replica (fails every stream, loop thread
        # survives, queue empties so its load reads lowest) must not
        # eat the whole retry budget while a healthy replica sits idle.
        sick = _FakeGenEngine(load=0, fail_after=1, fail_always=True)
        healthy = _FakeGenEngine(load=9)
        router = _router(sick, healthy)
        r = router.submit([3], max_new_tokens=3).result(timeout=5)
        assert r["tokens"] == [4, 5, 6]
        assert r["failovers"] == 1          # one hop: sick -> healthy
        assert len(healthy.submits) == 1
        assert router._metrics.failover_counts() == {"resumed": 1,
                                                     "exhausted": 0}
        router.shutdown()

    def test_submit_time_value_error_passes_through_untouched(self):
        # A malformed request is rejected AT SUBMIT, synchronously —
        # the REQUEST's failure, raised to the caller before any stream
        # exists; the router must not burn failover attempts on it.
        e0 = _FakeGenEngine(load=0, reject=ValueError("bad prompt"))
        e1 = _FakeGenEngine(load=5)
        router = _router(e0, e1)
        with pytest.raises(ValueError, match="bad prompt"):
            router.submit([3])
        assert not e1.submits
        assert router._metrics.stranded_count() == 0
        router.shutdown()

    def test_mid_stream_value_error_is_a_replica_fault_and_fails_over(self):
        # An error event from a replica that already ADMITTED the
        # stream is the replica's fault whatever the exception type —
        # an engine loop throwing ValueError on an admitted stream must
        # not be misread as a request verdict (submit-time validation
        # already happened).
        e0 = _FakeGenEngine(load=0, fail_after=1,
                            fail_with=ValueError("loop bug"))
        e1 = _FakeGenEngine(load=5)
        router = _router(e0, e1)
        r = router.submit([3], max_new_tokens=3).result(timeout=5)
        assert r["tokens"] == [4, 5, 6]
        assert r["failovers"] == 1
        assert router._metrics.failover_counts() == {"resumed": 1,
                                                     "exhausted": 0}
        router.shutdown()

    def test_eviction_racing_the_dispatch_register_window_strands_nothing(
            self):
        # A replica evicted BETWEEN the submit that admitted a stream
        # and the router registering it: the eviction's strand sweep
        # snapshotted streams before registration, so nobody else will
        # ever deliver that death verdict — the router must self-check
        # membership after registering and deliver it itself (without
        # the check, the client's handle waits forever on a replica
        # that no longer exists).
        router_box = []

        class _EvictDuringSubmit(_FakeGenEngine):
            def submit(self, *a, **kw):
                h = super().submit(*a, **kw)
                # Die and get swept before the router can register the
                # stream this submit just admitted.
                self.alive_flag = False
                router_box[0].poll()
                return h

        e0 = _EvictDuringSubmit(load=0, strand_after=1)
        e1 = _FakeGenEngine(load=5)
        router = _router(e0, e1)
        router_box.append(router)
        r = router.submit([3], max_new_tokens=3).result(timeout=5)
        assert r["tokens"] == [4, 5, 6]
        assert r["failovers"] == 1
        assert router._metrics.failover_counts()["resumed"] == 1
        assert router.counts()["ready"] == 1
        router.shutdown()

    def test_single_shot_future_fleets_stay_untracked(self):
        class _Single(ReadinessMixin):
            def __init__(self):
                self._queue = []
                self._warmed = True
                self._closed = False

            def load(self):
                return 0

            def submit(self, *a, **kw):
                return "a-future"

            def shutdown(self, drain=True, timeout=None):
                pass

        router = _router(_Single())
        assert router.submit("x") == "a-future"
        assert router._live_streams == {}
        router.shutdown()

    def test_failover_retries_validated(self):
        with pytest.raises(ValueError, match="failover_retries"):
            _router(_FakeGenEngine(), failover_retries=0)


class TestDeadlineThroughFailover:
    def test_replay_keeps_the_original_absolute_deadline(self):
        # The re-dispatched submit carries the REMAINING time of the
        # submit-time deadline — failover never resets the clock.
        e0 = _FakeGenEngine(load=0, strand_after=1)
        e1 = _FakeGenEngine(load=5)
        router = _router(e0, e1)
        h = router.submit([5], max_new_tokens=3, deadline_ms=60000.0)
        deadline = time.monotonic() + 5
        while len(h._tokens) < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        e0.alive_flag = False
        router.poll()
        r = h.result(timeout=5)
        assert r["tokens"] == [6, 7, 8]
        replayed = e1.submits[0]["deadline_ms"]
        assert replayed is not None and 0 < replayed < 60000.0
        router.shutdown()

    def test_deadline_expiry_during_failover_is_deadline_not_overload(self):
        # Every surviving replica rejects; the stream's ORIGINAL
        # deadline passes while failover backs off — the verdict is
        # DeadlineExceededError at the submit-time deadline, exactly as
        # if the stream had expired in a queue.
        e0 = _FakeGenEngine(load=0, strand_after=1)
        e1 = _FakeGenEngine(load=5,
                            reject=ServerOverloadedError("queue full"))
        router = _router(e0, e1, failover_retries=1000,
                         failover_backoff_s=0.01)
        h = router.submit([5], deadline_ms=150.0)
        deadline = time.monotonic() + 5
        while len(h._tokens) < 1 and time.monotonic() < deadline:
            time.sleep(0.005)
        e0.alive_flag = False
        router.poll()
        with pytest.raises(DeadlineExceededError, match="deadline"):
            h.result(timeout=10)
        assert router._metrics.failover_counts()["resumed"] == 0
        router.shutdown()


# ---------------------------------------------------------------------------
# The serving-plane fault grammar + hook.
# ---------------------------------------------------------------------------

class TestServeFaultSpec:
    def test_grammar_accepts_the_documented_forms(self):
        fs = faults.parse_spec(
            "replica_kill=r1@stream=3,replica_hang=r0@stream=2@epoch=1,"
            "slow_step=50,rank=1:kill@step=3")
        kill, hang, slow, rank = fs
        assert (kill.target, kill.action, kill.name, kill.stream) == \
            ("serve", "replica_kill", "r1", 3)
        assert (hang.action, hang.name, hang.stream, hang.epoch) == \
            ("replica_hang", "r0", 2, 1)
        assert (slow.action, slow.value) == ("slow_step", 50)
        assert rank.target == "rank"    # mixes with the training grammar

    @pytest.mark.parametrize("bad", [
        "replica_kill=r1",                  # no @stream: could never fire
        "replica_kill=r1@stream=0",         # stream counts are 1-based
        "replica_kill=@stream=3",           # no replica name
        "replica_hang=r0@bogus=1",          # unknown condition
        "replica_kill=r1@stream=x",         # non-integer stream
        "slow_step=0",                      # a 0ms delay is a spec bug
        "slow_step=abc",
        "slow_step=50@stream=2",            # slow_step is unconditional
    ])
    def test_grammar_rejects_loudly(self, bad):
        with pytest.raises(faults.FaultSpecError):
            faults.parse_spec(bad)

    def test_serve_hook_fires_once_on_the_named_replica(self, fault_spec):
        fault_spec("replica_kill=r1@stream=2")
        assert faults.serve_hook("r1", 1) is None   # not yet at stream 2
        assert faults.serve_hook("r0", 9) is None   # wrong replica
        assert faults.serve_hook("r1", 2) == "kill"
        assert faults.serve_hook("r1", 3) is None   # fired exactly once

    def test_serve_hook_hang_and_slow_step(self, fault_spec):
        fault_spec("replica_hang=r0@stream=1")
        assert faults.serve_hook("r0", 1) == "hang"
        fault_spec("slow_step=30")
        t0 = time.monotonic()
        assert faults.serve_hook("anything", 0) is None
        assert time.monotonic() - t0 >= 0.025   # slept ~30ms, every call


# ---------------------------------------------------------------------------
# Metrics + backoff hints.
# ---------------------------------------------------------------------------

class TestFailoverMetrics:
    def test_series_pre_seeded_and_validated(self):
        m = FleetMetrics()
        parsed = dict(((n, tuple(sorted(labels.items()))), v)
                      for n, labels, v in m.registry.collect()[1])
        assert parsed[("hvd_failover_total",
                       (("outcome", "resumed"),))] == 0.0
        assert parsed[("hvd_failover_total",
                       (("outcome", "exhausted"),))] == 0.0
        assert parsed[("hvd_streams_stranded_total", ())] == 0.0
        m.on_stranded(2)
        m.on_failover("resumed")
        assert m.stranded_count() == 2
        assert m.failover_counts() == {"resumed": 1, "exhausted": 0}
        with pytest.raises(ValueError, match="outcome"):
            m.on_failover("lost")
        body = render(*m.registry.collect())
        assert ("hvd_failover_total",
                (("outcome", "resumed"),)) in parse_exposition(body)

    def test_retry_after_ms_tracks_the_measured_service_rate(self):
        m = ServeMetrics()
        # No response yet: the 1 s default, clamped.
        assert m.retry_after_ms(10) == 1000.0
        # 100 responses over ~10 s -> 10/s; 19 queued + self ~= 2 s.
        m.responses_total = 100
        m._t0 = time.monotonic() - 10.0
        assert 1800.0 <= m.retry_after_ms(19) <= 2200.0
        assert m.retry_after_ms(0) >= 50.0          # clamp floor
        assert m.retry_after_ms(10 ** 9) == 30000.0  # clamp ceiling

    def test_fleet_overload_carries_the_soonest_drain_hint(self):
        e_slow = ServerOverloadedError("full")
        e_slow.retry_after_ms = 700.0
        e_fast = ServerOverloadedError("full")
        e_fast.retry_after_ms = 300.0
        router = _router(_FakeGenEngine(load=0, reject=e_slow),
                         _FakeGenEngine(load=1, reject=e_fast))
        with pytest.raises(ServerOverloadedError) as ei:
            router.submit([1])
        assert ei.value.retry_after_ms == 300.0
        router.shutdown()

    def test_http_503_carries_retry_after_hint(self):
        class _OverloadedEngine(ReadinessMixin):
            _warmed = True

            def __init__(self):
                self._queue = []

            def infer(self, x, deadline_ms=None):
                err = ServerOverloadedError("queue full")
                err.retry_after_ms = 2500.0
                raise err

        with serve.HttpServer(engine=_OverloadedEngine()) as srv:
            req = urllib.request.Request(
                f"http://{srv.host}:{srv.port}/predict",
                data=json.dumps({"inputs": [1.0]}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            err = ei.value
            body = json.loads(err.read())
            assert err.code == 503
            assert body["retryable"] is True
            assert body["retry_after_ms"] == 2500.0
            assert err.headers["Retry-After"] == "3"   # ceil(2.5 s)


# ---------------------------------------------------------------------------
# Adapter prewarming on scale-up (ROADMAP item 5 REMAINING).
# ---------------------------------------------------------------------------

class _FakeRegistry:
    def __init__(self):
        self.rows = {}
        self.quotas = {}

    def resident(self):
        return tuple(self.rows)

    def quota(self, name):
        return self.quotas.get(name)


class _FakeAdapterEngine(_FakeGenEngine):
    def __init__(self, **kw):
        super().__init__(**kw)
        self.adapters = _FakeRegistry()

    def load_adapter(self, name, tree, quota=None):
        self.adapters.rows[name] = tree
        self.adapters.quotas[name] = quota

    def adapter_names(self):
        return tuple(self.adapters.rows)


class TestAdapterPrewarm:
    def test_scale_up_seeds_resident_set_with_quotas(self):
        e0 = _FakeAdapterEngine()
        e0.load_adapter("a0", "tree:a0", quota=3)
        e0.load_adapter("a1", "tree:a1")        # quota-free tenant
        router = _router(e0, factory=lambda name: _FakeAdapterEngine(),
                         adapter_source=lambda n: f"tree:{n}")
        grown = router.add_replica(warm=False)
        # The grown replica starts RESIDENT (not filling by affinity
        # misses), and the PR-14 rule holds: quotas carried along, so a
        # seeded copy never mints a quota-free tenant.
        assert grown.engine.adapters.rows == {"a0": "tree:a0",
                                              "a1": "tree:a1"}
        assert grown.engine.adapters.quotas == {"a0": 3, "a1": None}
        router.shutdown()

    def test_no_adapter_source_means_no_seeding(self):
        e0 = _FakeAdapterEngine()
        e0.load_adapter("a0", "tree:a0")
        router = _router(e0, factory=lambda name: _FakeAdapterEngine())
        grown = router.add_replica(warm=False)
        assert grown.engine.adapters.rows == {}
        router.shutdown()


# ---------------------------------------------------------------------------
# ONE real-engine kill drill: the end-to-end bit-identity claim.
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model():
    import jax
    import jax.numpy as jnp
    from horovod_tpu.parallel.transformer import (TransformerConfig,
                                                  init_params)
    cfg = TransformerConfig(vocab=32, d_model=16, n_heads=2, n_layers=2,
                            d_ff=32, dtype=jnp.float32,
                            unembed_dtype=jnp.float32, attn_backend="xla")
    return cfg, init_params(jax.random.PRNGKey(0), cfg)


def _real_engine(model):
    cfg, params = model
    eng = serve.GenerationEngine(params, cfg, serve.GenerationConfig(
        max_slots=2, max_len=16, default_max_new_tokens=6))
    # Budget shortcut (the test_fleet.py pattern): compiles happen
    # lazily on the one prompt bucket these prompts hit.
    eng._warmed = True
    return eng


class TestLoopLiveness:
    def test_idle_loop_is_never_stale_a_wedged_busy_loop_is(self, model):
        # An idle engine parks in the untimed queue wait BY DESIGN: its
        # raw beat age must never read as a wedge — not even at the
        # instant new work lands (the stall clock starts at the first
        # busy observation, giving the loop stall_s to wake). A loop
        # observed busy with no beat progress past stall_s IS a wedge.
        eng = _real_engine(model)
        try:
            time.sleep(0.05)
            assert eng.loop_alive(0.01)         # idle: stale beat is fine
            assert eng.loop_alive(0.01)         # ...and does not flap
            eng._held.append(object())          # simulate stuck work
            assert eng.loop_alive(0.04)         # first busy observation
            time.sleep(0.08)
            assert not eng.loop_alive(0.04)     # no progress: wedged
            eng._held.clear()
            assert eng.loop_alive(0.04)         # idle again: recovered
        finally:
            eng.shutdown(drain=False)


class TestRealKillDrill:
    def test_replica_kill_mid_stream_resumes_bit_identical(
            self, model, fault_spec, monkeypatch, tmp_path):
        monkeypatch.setenv("HVD_FLIGHTREC_DIR", str(tmp_path))
        prompts = [[int(t) for t in p] for p in
                   np.random.RandomState(7).randint(1, 32, size=(6, 4))]
        # Greedy AND seeded-sampling streams in the same drill.
        samplings = [None if i % 2 == 0 else
                     serve.SamplingParams(temperature=0.8, seed=40 + i)
                     for i in range(len(prompts))]
        ref = _real_engine(model)
        try:
            ref_streams = sorted(
                tuple(ref.generate(p, sampling=s, timeout=60)["tokens"])
                for p, s in zip(prompts, samplings))
        finally:
            ref.shutdown()
        fault_spec("replica_kill=r1@stream=2")
        router = FleetRouter(engines=[_real_engine(model),
                                      _real_engine(model)],
                             poll_interval_s=0.05)
        try:
            handles = [router.submit(p, sampling=s)
                       for p, s in zip(prompts, samplings)]
            results = [h.result(timeout=60) for h in handles]
            # Zero stranded streams, every token stream bit-identical to
            # the uninterrupted single-engine run.
            assert sorted(tuple(r["tokens"]) for r in results) \
                == ref_streams
            assert router._metrics.failover_counts()["resumed"] >= 1
            assert router._metrics.failover_counts()["exhausted"] == 0
            assert router._metrics.stranded_count() >= 1
            assert sum(r["failovers"] for r in results) \
                == router._metrics.failover_counts()["resumed"]
            # The killed replica left membership without drain...
            assert router.counts() == {"ready": 1, "warming": 0,
                                       "draining": 0, "dead": 0}
        finally:
            router.shutdown()
        # ...and left its post-mortem: the flight-recorder dump names
        # the in-flight streams the failover plane had to resume.
        dumps = glob.glob(str(tmp_path / "hvd_flightrec.rank*.json"))
        assert dumps, "killed replica left no flight-recorder dump"
        events = json.loads(open(dumps[0]).read())["events"]
        crash = [e for e in events if e["kind"] == "serve_crash"]
        assert crash and crash[0]["replica"] == "r1"
        assert crash[0]["inflight"], "post-mortem names no in-flight stream"
