"""Hook/Estimator integration tests (the reference's Estimator +
SessionRunHook pattern, ``tensorflow_mnist_estimator.py:145-191``)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import models, training
from horovod_tpu.hooks import (BroadcastGlobalVariablesHook,
                               CheckpointSaverHook, Estimator, LoggingHook,
                               MonitoredTrainingLoop, StopAtStepHook,
                               TrainingHook)


def _toy_batch(n=16, key=0):
    rng = np.random.RandomState(key)
    x = rng.randn(n, 784).astype(np.float32)
    w = rng.randn(784, 10).astype(np.float32)
    y = np.argmax(x @ w, axis=1)
    return jnp.asarray(x), jnp.asarray(y)


def _make_step(lr=0.05):
    model = models.MnistCNN()
    state, dist_opt = training.create_train_state(
        model, jax.random.PRNGKey(0), jnp.zeros((2, 784)), optax.sgd(lr))
    return training.make_train_step(model, dist_opt), state


class TestMonitoredTrainingLoop:
    def test_hooks_fire_in_order(self):
        step, state = _make_step()
        calls = []

        class Recorder(TrainingHook):
            def begin(self, loop):
                calls.append("begin")

            def after_create_session(self, loop):
                calls.append("acs")

            def before_run(self, loop, s):
                calls.append(f"before{s}")

            def after_run(self, loop, s, metrics):
                calls.append(f"after{s}")
                assert "loss" in metrics

            def end(self, loop):
                calls.append("end")

        loop = MonitoredTrainingLoop(step, state, [Recorder()])
        loop.run([_toy_batch()] * 2)
        assert calls == ["begin", "acs", "before0", "after0",
                         "before1", "after1", "end"]
        assert loop.global_step == 2

    def test_stop_at_step(self):
        step, state = _make_step()
        loop = MonitoredTrainingLoop(step, state, [StopAtStepHook(3)])
        loop.run([_toy_batch()] * 10)
        assert loop.global_step == 3

    def test_checkpoint_saver_hook(self, tmp_path):
        from horovod_tpu.trainer import latest_checkpoint_step
        step, state = _make_step()
        loop = MonitoredTrainingLoop(
            step, state,
            [CheckpointSaverHook(str(tmp_path), save_steps=2),
             StopAtStepHook(4)])
        loop.run([_toy_batch()] * 10)
        # Saves at steps 2, 4, and at end() (state.step == 4).
        assert latest_checkpoint_step(str(tmp_path)) == 4


class TestEstimator:
    def _estimator(self, model_dir=None):
        return Estimator(
            models.MnistCNN(), optax.sgd(0.05), model_dir=model_dir,
            sample_input=jnp.zeros((2, 784)),
            metrics_fn=lambda lg, lb: {
                "accuracy": training.accuracy(lg, lb)})

    def test_train_steps_and_evaluate(self):
        est = self._estimator()
        batch = _toy_batch()

        def input_fn():
            return iter([batch] * 4)

        est.train(input_fn, steps=6,
                  hooks=[BroadcastGlobalVariablesHook(0),
                         LoggingHook(every_n_steps=100)])
        assert int(est.state.step) == 6  # stream repeats until StopAtStep
        metrics = est.evaluate(input_fn)
        assert set(metrics) == {"loss", "accuracy"}
        assert np.isfinite(metrics["loss"])

    def test_train_learns(self):
        est = self._estimator()
        batch = _toy_batch(32)

        def input_fn():
            return iter([batch] * 8)

        before = est.evaluate(input_fn)["loss"]
        est.train(input_fn, steps=16)
        after = est.evaluate(input_fn)["loss"]
        assert after < before, (before, after)

    def test_model_dir_checkpoints(self, tmp_path):
        from horovod_tpu.trainer import latest_checkpoint_step
        est = self._estimator(model_dir=str(tmp_path))
        est.train(lambda: iter([_toy_batch()] * 3), steps=3)
        assert latest_checkpoint_step(str(tmp_path)) == 3
