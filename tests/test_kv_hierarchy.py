"""KV memory hierarchy tests: the chunked-prefill bitwise contract at
the model layer (suffix program's logits vs the full-prompt program's),
engine-level cold-vs-hit stream identity across the edge geometries
(partial last shared block, suffix shorter than one block, hit chain at
the slot's block budget with the suffix bucket overhanging max_len),
the host tier's offload → prefetch roundtrip under real pool pressure
(wait AND miss admission policies — an offloaded chain admits as a
miss, never a stale read), prefix-affine fleet routing over advertised
digests, the subprocess heartbeat-liveness plane, and the tier-labeled
``hvd_kv_blocks_*`` exposition.

All CPU and deliberately tiny (tier-1 budget): the same module-scoped
model as tests/test_paged_kv.py; every engine compiles at most one
decode program and two chunked-prefill buckets (8 and 16). The timed
capacity/TTFT drills (hit-vs-cold TTFT gap, blocks_exhausted below the
device-only run under sustained load) live in ci.sh via serve_bench —
they are wall-clock claims, not unit contracts.
"""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import serve
from horovod_tpu.parallel.kv_blocks import (TRASH_BLOCK, BlockManager,
                                            init_paged_kv_cache,
                                            paged_chunked_prefill,
                                            prefix_route_digest)
from horovod_tpu.parallel.transformer import TransformerConfig, init_params
from horovod_tpu.serve.engine import ReadinessMixin
from horovod_tpu.serve.fleet import heartbeat_liveness
from horovod_tpu.serve.proc_replica import ProcReplicaClient
from horovod_tpu.serve.router import FleetRouter
from horovod_tpu.serve.spec import SpecConfig

CFG = dict(vocab=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
           dtype=jnp.float32, unembed_dtype=jnp.float32,
           attn_backend="xla")

PROMPT = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5]   # 11 tokens; 2 full blocks @ 4
CHAIN = PROMPT[:8]                           # exactly the registrable chain


@pytest.fixture(scope="module")
def model():
    cfg = TransformerConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


def _engine(params, cfg, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_len", 16)
    kw.setdefault("default_max_new_tokens", 4)
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("block_size", 4)
    kw.setdefault("prefix_reuse", True)
    kw.setdefault("chunked_prefill", True)
    spec = kw.pop("spec", None)
    return serve.GenerationEngine(params, cfg,
                                  serve.GenerationConfig(**kw), spec=spec)


def _gen(eng):
    return dict(eng.stats()["generation"])


class TestChunkedModelLayer:
    def test_suffix_logits_bitwise_equal_full_program(self, model):
        """THE skip-compute contract: the suffix program (start at the
        first non-shared block, hit K/V read from the pool via the read
        row) produces logits BITWISE-equal to the full-prompt chunked
        program's rows for the same positions, and writes byte-identical
        K/V into its fresh blocks. Geometries: partial last block,
        suffix of one token, chain at the slot budget (suffix bucket
        overhanging the prompt), and a 2-block chunk."""
        cfg, params = model
        bs, max_len = 4, 16
        for prompt_len, hit_blocks, full_b, suf_b, cb, seed in (
                (11, 2, 16, 8, 1, 0),     # partial last shared block
                (9, 2, 16, 8, 1, 1),      # 1-token suffix
                (15, 3, 16, 8, 1, 3),     # budget chain, 12+8 > max_len
                (13, 2, 16, 16, 2, 2)):   # 2-block chunks
            C = cb * bs
            rng = np.random.RandomState(seed)
            prompt = rng.randint(0, cfg.vocab, (prompt_len,)).astype(np.int32)
            max_blocks = max_len // bs
            start = hit_blocks * bs
            n_chain = -(-prompt_len // bs)
            chain = list(range(1, 1 + n_chain))
            pc = init_paged_kv_cache(cfg, 16, bs, 2)
            row = np.zeros((max_blocks,), np.int32)
            row[:n_chain] = chain
            wrows = np.zeros((full_b // C, cb), np.int32)
            wrows.reshape(-1)[:n_chain] = chain
            toks = np.zeros((full_b,), np.int32)
            toks[:prompt_len] = prompt
            pc, lg_full = jax.jit(
                lambda p, t, c, w, r: paged_chunked_prefill(
                    p, t, c, 0, w, r, 0, cfg, length=prompt_len,
                    chunk_blocks=cb))(params, toks, pc, wrows, row)
            fresh = list(range(1 + n_chain,
                               1 + n_chain + (n_chain - hit_blocks)))
            rrow = np.zeros((max_blocks,), np.int32)
            rrow[:hit_blocks] = chain[:hit_blocks]
            rrow[hit_blocks:n_chain] = fresh
            wsuf = np.zeros((suf_b // C, cb), np.int32)
            wsuf.reshape(-1)[:len(fresh)] = fresh
            suf_len = prompt_len - start
            tsuf = np.zeros((suf_b,), np.int32)
            tsuf[:suf_len] = prompt[start:]
            pc2, lg_suf = jax.jit(
                lambda p, t, c, w, r: paged_chunked_prefill(
                    p, t, c, 1, w, r, start, cfg, length=prompt_len,
                    chunk_blocks=cb))(params, tsuf, pc, wsuf, rrow)
            np.testing.assert_array_equal(
                np.asarray(lg_full)[start:prompt_len],
                np.asarray(lg_suf)[:suf_len])
            for li in range(cfg.n_layers):
                for j, fb in enumerate(fresh):
                    src = chain[hit_blocks + j]
                    rows = min(bs, prompt_len - (hit_blocks + j) * bs)
                    np.testing.assert_array_equal(
                        np.asarray(pc["k"])[li, src, :rows],
                        np.asarray(pc2["k"])[li, fb, :rows])
                    np.testing.assert_array_equal(
                        np.asarray(pc["v"])[li, src, :rows],
                        np.asarray(pc2["v"])[li, fb, :rows])

    def test_single_trip_bucket_rejected(self, model):
        """XLA fully unrolls a 1-trip scan into a shape-specialized
        program — the fixed-shape-body equality argument dies with it,
        so the model layer refuses the geometry outright."""
        cfg, params = model
        pc = init_paged_kv_cache(cfg, 8, 4, 1)
        with pytest.raises(ValueError, match="trip"):
            paged_chunked_prefill(params, np.zeros((4,), np.int32), pc, 0,
                                  np.zeros((1, 1), np.int32),
                                  np.zeros((4,), np.int32), 0, cfg,
                                  length=3)


class TestChunkedConfig:
    def test_validation(self):
        with pytest.raises(ValueError, match="prefix_reuse"):
            serve.GenerationConfig(kv_layout="paged", block_size=4,
                                   chunked_prefill=True)
        with pytest.raises(ValueError, match="power of two"):
            serve.GenerationConfig(kv_layout="paged", block_size=4,
                                   prefix_reuse=True, chunked_prefill=True,
                                   chunk_blocks=3)
        # max_len must leave every chunked bucket >= 2 scan trips
        with pytest.raises(ValueError, match="chunk"):
            serve.GenerationConfig(kv_layout="paged", block_size=4,
                                   max_len=16, max_slots=2,
                                   prefix_reuse=True, chunked_prefill=True,
                                   chunk_blocks=4)
        gc = serve.GenerationConfig(kv_layout="paged", block_size=4,
                                    max_len=16, max_slots=2,
                                    prefix_reuse=True, chunked_prefill=True,
                                    chunk_blocks=2)
        assert gc.chunk_tokens == 8


class TestChunkedEngineGeometry:
    """Cold-run/hit-run pairs of the SAME prompt in a fresh engine per
    geometry: the hit admission must compile/execute the SUFFIX bucket
    (pinned via last_prefill_bucket) and stream the cold run's exact
    tokens."""

    def _cold_hit(self, params, cfg, prompt, **kw):
        eng = _engine(params, cfg, **kw)
        try:
            cold = eng.generate(prompt, timeout=60)
            b_cold = eng.stats()["last_prefill_bucket"]
            g0 = _gen(eng)
            hit = eng.generate(prompt, timeout=60)
            snap = eng.stats()
            g1 = _gen(eng)
            assert hit["tokens"] == cold["tokens"], (cold, hit)
            return (b_cold, snap["last_prefill_bucket"],
                    g1["prefill_chunks_total"] - g0["prefill_chunks_total"],
                    g1["prefill_chunks_skipped_total"]
                    - g0["prefill_chunks_skipped_total"],
                    g1["prefix_hits_total"] - g0["prefix_hits_total"])
        finally:
            eng.shutdown()

    def test_partial_last_shared_block(self, model):
        """11-token prompt, 2 registered blocks: the hit skips both full
        chunks and re-prefills only the 3-token partial tail — suffix
        bucket 8, not the cold run's 16."""
        cfg, params = model
        b_cold, b_hit, chunks, skipped, hits = self._cold_hit(
            params, cfg, PROMPT)
        assert (b_cold, b_hit) == (16, 8)
        assert (chunks, skipped, hits) == (2, 2, 1)

    def test_prompt_equals_chain_keeps_one_suffix_token(self, model):
        """A prompt that IS the registered chain: the hit cap must hold
        back one chunk so at least one prompt token remains in the
        suffix to score the sampled row — never a zero-length suffix
        program."""
        cfg, params = model
        b_cold, b_hit, chunks, skipped, hits = self._cold_hit(
            params, cfg, CHAIN)
        assert (b_cold, b_hit) == (8, 8)
        assert (chunks, skipped) == (2, 1)     # one chunk held back

    def test_suffix_shorter_than_one_block(self, model):
        """Chain + a single token: the suffix is 1 token, still drawn
        from the smallest >=2-trip bucket."""
        cfg, params = model
        b_cold, b_hit, chunks, skipped, hits = self._cold_hit(
            params, cfg, CHAIN + [7])
        assert (b_cold, b_hit) == (16, 8)
        assert (chunks, skipped) == (2, 2)

    def test_hit_chain_at_slot_budget(self, model):
        """15-token prompt with max_new=1: the 3-block hit chain plus
        one fresh block fills the slot budget exactly, and the suffix
        bucket overhangs max_len (start 12 + bucket 8 = 20 > 16) — the
        overhang rows are masked padding, never a wrong byte."""
        cfg, params = model
        p15 = PROMPT + [7, 2, 7, 1]
        eng = _engine(params, cfg)
        try:
            cold = eng.generate(p15, timeout=60, max_new_tokens=1)
            hit = eng.generate(p15, timeout=60, max_new_tokens=1)
            snap = eng.stats()
            assert hit["tokens"] == cold["tokens"]
            assert snap["last_prefill_bucket"] == 8
            g = _gen(eng)
            assert g["prefix_hit_blocks_total"] == 3
        finally:
            eng.shutdown()

    def test_seeded_sampling_digest_identical(self, model):
        cfg, params = model
        eng = _engine(params, cfg)
        samp = serve.SamplingParams(temperature=0.7, top_k=8, seed=11)
        try:
            cold = eng.generate(PROMPT, timeout=60, sampling=samp)
            hit = eng.generate(PROMPT, timeout=60, sampling=samp)
            assert hit["tokens"] == cold["tokens"]
            assert eng.stats()["last_prefill_bucket"] == 8
        finally:
            eng.shutdown()

    def test_spec_on_matches_spec_off(self, model):
        """Speculation composes with chunked prefill: greedy streams are
        identical spec-on vs spec-off, cold AND hit."""
        cfg, params = model
        plain = _engine(params, cfg)
        spec = _engine(params, cfg, spec=SpecConfig(k=2))
        try:
            for eng in (plain, spec):       # cold then hit in each
                eng.generate(PROMPT, timeout=60)
            p_hit = plain.generate(PROMPT, timeout=60)
            s_hit = spec.generate(PROMPT, timeout=60)
            assert p_hit["tokens"] == s_hit["tokens"]
            assert spec.stats()["last_prefill_bucket"] == 8
        finally:
            plain.shutdown()
            spec.shutdown()


class TestHostTier:
    def test_offload_prefetch_roundtrip_and_registry_survival(self, model):
        """Pool pressure offloads the cold registered chain to host
        instead of dropping it; the next shared admission prefetches it
        back and streams the cold run's exact tokens. The device-only
        engine under the SAME pressure loses the chain (the re-run is a
        miss) — the registry-capacity raise the host tier buys. Tier
        gauges account for every block on both sides of the roundtrip."""
        cfg, params = model
        pressure = ([7 + (i % 20) for i in range(12)],
                    [11 + (i % 17) for i in range(12)])
        tiered = _engine(params, cfg, max_slots=1, n_blocks=8,
                         host_blocks=8)
        device = _engine(params, cfg, max_slots=1, n_blocks=8)
        try:
            cold = tiered.generate(PROMPT, timeout=60)
            device.generate(PROMPT, timeout=60)
            for p in pressure:              # force free < need at admit
                tiered.generate(p, timeout=60)
                device.generate(p, timeout=60)
            snap = tiered.stats()
            assert snap["generation"]["kv_offload_blocks_total"] > 0
            assert snap["blocks"]["host_used"] > 0
            assert (snap["blocks"]["host_used"] + snap["blocks"]["host_free"]
                    == snap["blocks"]["host_total"])
            g0t, g0d = _gen(tiered), _gen(device)
            hit = tiered.generate(PROMPT, timeout=60)
            device_re = device.generate(PROMPT, timeout=60)
            assert hit["tokens"] == cold["tokens"]
            assert device_re["tokens"] == cold["tokens"]
            g1t, g1d = _gen(tiered), _gen(device)
            # host tier: chain survived as a (prefetched) hit; device
            # only: the pressure evicted it — a full-recompute miss
            assert g1t["kv_prefetch_blocks_total"] > 0
            assert (g1t["prefix_hits_total"]
                    - g0t["prefix_hits_total"]) == 1
            assert (g1d["prefix_misses_total"]
                    - g0d["prefix_misses_total"]) == 1
            snap = tiered.stats()
            assert (snap["blocks"]["free"] + snap["blocks"]["used"]
                    == snap["blocks"]["total"])
        finally:
            tiered.shutdown()
            device.shutdown()

    def test_miss_policy_admits_without_waiting_never_stale(self, model):
        """host_admission="miss" (the eviction-racing-admission edge):
        an admission whose chain sits in the host tier does NOT wait —
        it recomputes the suffix from the device hits it has (here:
        none), streaming the cold tokens exactly. The kicked prefetch
        still lands, so the NEXT admission hits."""
        cfg, params = model
        eng = _engine(params, cfg, max_slots=1, n_blocks=8, host_blocks=8,
                      host_admission="miss")
        try:
            cold = eng.generate(PROMPT, timeout=60)
            for p in ([7 + (i % 20) for i in range(12)],
                      [11 + (i % 17) for i in range(12)]):
                eng.generate(p, timeout=60)
            assert _gen(eng)["kv_offload_blocks_total"] > 0
            g0 = _gen(eng)
            racing = eng.generate(PROMPT, timeout=60)   # chain on host
            assert racing["tokens"] == cold["tokens"]
            g1 = _gen(eng)
            assert (g1["prefix_misses_total"]
                    - g0["prefix_misses_total"]) == 1   # admitted as miss
            again = eng.generate(PROMPT, timeout=60)    # prefetch landed
            assert again["tokens"] == cold["tokens"]
            g2 = _gen(eng)
            assert (g2["prefix_hits_total"] - g1["prefix_hits_total"]) == 1
        finally:
            eng.shutdown()

    def test_block_manager_host_accounting(self):
        """Manager-level tier accounting: offload is two-phase (a hit
        landing mid-copy cancels the commit), promote moves the
        allocation back, register pops the host copy, and the gauges
        cover every block in both tiers at every step."""
        bm = BlockManager(6, 4, host_blocks=4)
        toks = np.arange(8, dtype=np.int32)
        blocks = bm.alloc(2)
        bm.register_prefix(toks, blocks, 2,
                           route_digest=prefix_route_digest(toks, 4))
        bm.release(blocks)
        cands = bm.offload_candidates(2)
        assert len(cands) == 2
        for key, blk in cands:
            assert bm.offload_commit(key, {"blk": blk})
        g = bm.gauges()
        assert g["host_used"] == 2 and g["free"] == 5
        assert bm.lookup_prefix(toks) == []             # device side empty
        cont = bm.host_lookup(toks, 0)
        assert len(cont) == 2
        # promote the first back; the chain continues host-side
        key0, payload0 = cont[0]
        blk = bm.alloc(1)[0]
        bm.promote(key0, blk)
        assert bm.lookup_prefix(toks) == [blk]
        assert bm.host_lookup(toks, 1)                  # j=1 still on host
        g = bm.gauges()
        assert g["host_used"] == 1
        assert g["free"] + g["used"] == g["total"]
        assert bm.route_digests() == (prefix_route_digest(toks, 4),)
        # a re-register of the same chain pops the host leftovers
        fresh = bm.alloc(2)
        bm.register_prefix(toks, [blk] + fresh[:1], 2)
        assert bm.gauges()["host_used"] == 0


class _PrefixFake(ReadinessMixin):
    """Router-contract fake advertising a registered-prefix digest set
    (the `/stats` surface ProcReplicaClient mirrors)."""

    def __init__(self, digests=(), bs=4, load=0, warmed=True):
        self._queue = []
        self._warmed = warmed
        self._closed = False
        self._load = load
        self._digests = tuple(digests)
        self.route_block_size = bs
        self.submits = []

    def load(self):
        return self._load

    def prefix_digests(self):
        return self._digests

    def submit(self, *a, **kw):
        self.submits.append((a, kw))
        return "accepted"

    def warmup(self):
        self._warmed = True

    def shutdown(self, drain=True, timeout=None):
        self._closed = True

    def stats(self):
        return {"requests_total": len(self.submits), "queue_depth": 0}

    def prom_collect(self):
        return ({}, [])


class TestPrefixAffineRouting:
    def test_affine_replica_outranks_load(self):
        toks = np.arange(8, dtype=np.int32)
        d = prefix_route_digest(toks, 4)
        warm = _PrefixFake(digests=(d,), load=9)
        cold = _PrefixFake(load=0)
        router = FleetRouter(engines=[warm, cold])
        router.submit(toks)
        # r0 advertises the prompt's first-block digest: it wins the
        # dispatch despite carrying 9x the load.
        assert warm.submits and not cold.submits
        assert router._metrics.prefix_dispatch_counts() == {
            "affine": 1, "miss": 0}
        assert router.stats()["fleet"]["prefix_dispatch"] == {
            "affine": 1, "miss": 0}

    def test_non_matching_digest_counts_a_miss(self):
        toks = np.arange(8, dtype=np.int32)
        other = prefix_route_digest(np.arange(8, 16, dtype=np.int32), 4)
        adv = _PrefixFake(digests=(other,), load=5)
        lo = _PrefixFake(load=0)
        router = FleetRouter(engines=[adv, lo])
        router.submit(toks)
        assert lo.submits and not adv.submits
        assert router._metrics.prefix_dispatch_counts() == {
            "affine": 0, "miss": 1}

    def test_salt_framing_keeps_tenants_apart(self):
        """The digest is framed exactly like the registry key: the same
        tokens under a different adapter hash differently, so affinity
        can never alias across tenants."""
        toks = np.arange(8, dtype=np.int32)
        assert (prefix_route_digest(toks, 4)
                != prefix_route_digest(toks, 4, adapter="t1"))
        assert (prefix_route_digest(toks, 4, adapter="t1")
                != prefix_route_digest(toks, 4, adapter="t2"))
        # sub-block prompts have no routable first block
        assert prefix_route_digest(toks[:3], 4) is None

    def test_unroutable_prompt_skips_the_plane(self):
        """No digests advertised / no routable first block: dispatch is
        plain least-load and the outcome counter never moves."""
        adv = _PrefixFake(load=5)                  # nothing registered
        lo = _PrefixFake(load=0)
        router = FleetRouter(engines=[adv, lo])
        router.submit(np.arange(8, dtype=np.int32))
        router.submit(np.arange(2, dtype=np.int32))   # sub-block
        assert len(lo.submits) == 2
        assert router._metrics.prefix_dispatch_counts() == {}
        assert "prefix_dispatch" not in router.stats()["fleet"]


class TestHeartbeatLiveness:
    def test_stale_heartbeat_flips_aborted(self, tmp_path):
        ready = str(tmp_path / "r0.ready")
        c = ProcReplicaClient("r0", None, port=1, ready_file=ready,
                              heartbeat_timeout_s=0.5)
        # no heartbeat file yet: booting reads FRESH, not dead
        assert c._heartbeat_stale() is False
        hb = ready + ".hb"
        with open(hb, "w") as f:
            f.write("{}")
        assert c._heartbeat_stale() is False
        c.loop_alive = lambda: True          # keep aborted() off HTTP
        alive = heartbeat_liveness(c)
        assert alive() is True
        past = time.time() - 5.0
        os.utime(hb, (past, past))           # the worker went silent
        assert c._heartbeat_stale() is True
        assert c.aborted() is True
        assert alive() is False

    def test_factory_exposes_liveness_hooks(self, tmp_path):
        from horovod_tpu.serve.proc_replica import spawn_replica_factory
        factory = spawn_replica_factory({"model": dict(CFG)},
                                        run_dir=str(tmp_path))
        assert factory.clients == {}
        assert factory.liveness_factory("never-spawned") is None


class TestTierExposition:
    def test_tier_labeled_block_gauges(self, model):
        """The exposition splits the pool by tier WITHOUT renaming the
        pinned unlabeled series: hvd_kv_blocks_total stays (ci.sh pins
        it), and tier="device"/"host" samples account for every block."""
        cfg, params = model
        eng = _engine(params, cfg, n_blocks=8, host_blocks=4)
        try:
            snap = eng.stats()
            _meta, samples = eng.prom_collect()
            by = {}
            for name, labels, value in samples:
                by[(name, labels.get("tier"))] = value
            for short in ("total", "free", "used"):
                name = f"hvd_kv_blocks_{short}"
                assert (name, None) in by            # pinned series
                assert (name, "device") in by and (name, "host") in by
                assert by[(name, None)] == by[(name, "device")]
            assert by[("hvd_kv_blocks_total", "host")] == 4
            assert (by[("hvd_kv_blocks_used", "host")]
                    + by[("hvd_kv_blocks_free", "host")] == 4)
            # one valid exposition: single TYPE line per family
            text = eng.prom_metrics()
            assert text.count("# TYPE hvd_kv_blocks_total ") == 1
            for counter in ("hvd_kv_offload_blocks_total",
                            "hvd_kv_prefetch_blocks_total",
                            "hvd_prefill_chunks_total",
                            "hvd_prefill_chunks_skipped_total"):
                assert f"# TYPE {counter} counter" in text
            assert "hvd_kv_prefetch_seconds" in text
            assert snap["chunked_prefill"] is True
        finally:
            eng.shutdown()
