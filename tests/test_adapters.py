"""Multi-tenant LoRA adapter tests: model-layer delta math (merged-weight
parity, base-row bit-identity), the AdapterRegistry load/evict/refcount
discipline, engine-level mixed-batch bit-identity with the compile-cache
pinned at the base-only count, per-tenant quota rejection with its own
reason, manifest-CRC-verified adapter restore, and adapter-affine fleet
routing on fake engines.

Budget-conscious (tier-1 sits ~430s of the 870s cap): the same tiny
module-scoped model as tests/test_paged_kv.py, every prompt in ONE
prefill bucket, engines shared through module fixtures wherever a test
only reads streams; the open-loop digest drills and the hot-evict-under-
traffic leg live in ci.sh, not here.
"""

import json
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import serve
from horovod_tpu.exceptions import (CheckpointCorruptError,
                                    ServerOverloadedError)
from horovod_tpu.parallel.checkpoint import restore_adapter, save_adapter
from horovod_tpu.parallel.lora import (LoraConfig, adapter_bytes,
                                       check_adapter, init_adapter,
                                       stack_adapters, target_shapes)
from horovod_tpu.parallel.transformer import (TransformerConfig,
                                              decode_step, init_kv_cache,
                                              init_params, prefill)
from horovod_tpu.serve.adapters import AdapterRegistry
from horovod_tpu.serve.engine import ReadinessMixin
from horovod_tpu.serve.router import FleetRouter

CFG = dict(vocab=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
           dtype=jnp.float32, unembed_dtype=jnp.float32,
           attn_backend="xla")

# 9 tokens → the 16 bucket for every engine in this module (one decode +
# one prefill compile per engine, as in test_paged_kv.py).
PROMPT = [3, 1, 4, 1, 5, 9, 2, 6, 5]


@pytest.fixture(scope="module")
def model():
    cfg = TransformerConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


@pytest.fixture(scope="module")
def lora_setup(model):
    cfg, _ = model
    lora = LoraConfig(rank=2)
    ads = {f"a{i}": init_adapter(jax.random.PRNGKey(1 + i), cfg, lora,
                                 b_scale=0.5)
           for i in range(2)}
    return lora, ads


def _engine(params, cfg, adapters=None, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("max_len", 16)
    kw.setdefault("default_max_new_tokens", 6)
    return serve.GenerationEngine(params, cfg,
                                  serve.GenerationConfig(**kw),
                                  adapters=adapters)


@pytest.fixture(scope="module")
def engines(model, lora_setup):
    """One plain engine + one adapter engine sharing a registry with
    a0/a1 resident — shared by every stream-reading test (results are
    deterministic per request; counter-exact tests build their own)."""
    cfg, params = model
    lora, ads = lora_setup
    reg = AdapterRegistry(cfg, lora, capacity=3)
    for name, tree in sorted(ads.items()):
        reg.load(name, tree)
    engs = {"plain": _engine(params, cfg),
            "adapter": _engine(params, cfg, adapters=reg)}
    yield engs
    for e in engs.values():
        e.shutdown()


class TestLoraConfigAndTrees:
    def test_config_validation(self):
        with pytest.raises(ValueError, match="rank"):
            LoraConfig(rank=0)
        with pytest.raises(ValueError, match="alpha"):
            LoraConfig(alpha=0)
        with pytest.raises(ValueError, match="target"):
            LoraConfig(targets=())
        with pytest.raises(ValueError, match="wq_typo"):
            LoraConfig(targets=("wq_typo",))
        assert LoraConfig(rank=4, alpha=8).scaling == 2.0

    def test_check_adapter_names_culprit(self, model, lora_setup):
        cfg, _ = model
        lora, ads = lora_setup
        check_adapter(ads["a0"], cfg, lora)         # fits
        with pytest.raises(ValueError, match="layers"):
            check_adapter({"layers": ads["a0"]["layers"][:1]}, cfg, lora)
        bad = {"layers": [dict(l) for l in ads["a0"]["layers"]]}
        bad["layers"][1] = dict(bad["layers"][1])
        bad["layers"][1]["wo"] = {"a": np.zeros((3, 2), np.float32),
                                  "b": bad["layers"][1]["wo"]["b"]}
        with pytest.raises(ValueError, match="layer 1 target 'wo'"):
            check_adapter(bad, cfg, lora)
        # wrong inner keys (a foreign export) name the culprit too —
        # never a bare KeyError
        bad["layers"][1]["wo"] = {"A": np.zeros((2, 2)),
                                  "B": np.zeros((2, 2))}
        with pytest.raises(ValueError, match="layer 1 target 'wo'"):
            check_adapter(bad, cfg, lora)
        # memory math: rank-r delta bytes = 4·r·Σ(d_in + d_out) per layer
        shapes = target_shapes(cfg)
        want = cfg.n_layers * sum(
            4 * lora.rank * (shapes[t][0] + shapes[t][1])
            for t in lora.targets)
        assert adapter_bytes(cfg, lora) == want

    def test_adapters_require_lora_config(self, model, lora_setup):
        cfg, params = model
        _, ads = lora_setup
        table = stack_adapters([ads["a0"]])
        cache = init_kv_cache(cfg, 2, 16)
        with pytest.raises(ValueError, match="lora="):
            prefill(params, np.asarray(PROMPT, np.int32), cache, 0, cfg,
                    adapters=table, adapter_idx=0)


class TestModelLayer:
    def test_merged_parity_and_base_bit_identity(self, model, lora_setup):
        """The two numerical contracts in one pass: (a) adapter_idx=0
        matches a base-path run over MERGED weights W + (alpha/r)·A@B
        (allclose — association order differs), and (b) adapter_idx=-1
        rows are BIT-identical to a run without any adapter table (the
        where-select guarantee, not y + 0.0)."""
        cfg, params = model
        lora, ads = lora_setup
        table = stack_adapters([ads["a0"], ads["a1"]])
        toks = np.asarray(PROMPT[:6], np.int32)
        cache = init_kv_cache(cfg, 2, 16)
        merged = {"embed": params["embed"], "lnf": params["lnf"],
                  "layers": []}
        for li, layer in enumerate(params["layers"]):
            nl = dict(layer)
            for t, pair in ads["a0"]["layers"][li].items():
                nl[t] = layer[t] + lora.scaling * (pair["a"] @ pair["b"])
            merged["layers"].append(nl)

        # Two compiled programs per phase, each reused twice (adapter_idx
        # is a traced arg — the same no-new-compile property the engine
        # rides): base/merged share one, -1/0 table runs share the other.
        pf = jax.jit(lambda p, t, c: prefill(p, t, c, 0, cfg))
        pf_a = jax.jit(lambda p, t, c, i: prefill(
            p, t, c, 0, cfg, adapters=table, adapter_idx=i, lora=lora))
        c_b, l_b = pf(params, toks, cache)
        c_n, l_n = pf_a(params, toks, cache, -1)
        np.testing.assert_array_equal(np.asarray(l_n), np.asarray(l_b))
        c_m, l_m = pf(merged, toks, cache)
        c_t, l_t = pf_a(params, toks, cache, 0)
        np.testing.assert_allclose(np.asarray(l_t), np.asarray(l_m),
                                   rtol=2e-5, atol=1e-5)
        assert not np.array_equal(np.asarray(l_t), np.asarray(l_b))

        last = np.array([7, 0], np.int32)
        pos = np.array([6, -1], np.int32)
        dec = jax.jit(lambda p, l, c, q: decode_step(p, l, c, q, cfg))
        dec_a = jax.jit(lambda p, l, c, q, i: decode_step(
            p, l, c, q, cfg, adapters=table, adapter_idx=i, lora=lora))
        _, d_b = dec(params, last, c_b, pos)
        _, d_n = dec_a(params, last, c_n, pos,
                       np.array([-1, -1], np.int32))
        np.testing.assert_array_equal(np.asarray(d_n), np.asarray(d_b))
        _, d_m = dec(merged, last, c_m, pos)
        _, d_t = dec_a(params, last, c_t, pos,
                       np.array([0, -1], np.int32))
        np.testing.assert_allclose(np.asarray(d_t)[0], np.asarray(d_m)[0],
                                   rtol=2e-5, atol=1e-5)
        # the mixed row 1 (base) is bit-equal to the no-table run's row 1
        np.testing.assert_array_equal(np.asarray(d_t)[1],
                                      np.asarray(d_b)[1])


class TestAdapterRegistry:
    def test_load_evict_refcount_quota_drill(self, model, lora_setup):
        cfg, _ = model
        lora, ads = lora_setup
        reg = AdapterRegistry(cfg, lora, capacity=2)
        assert reg.resident() == ()
        i0 = reg.load("a0", ads["a0"], quota=3)
        assert reg.index_of("a0") == i0 and reg.quota("a0") == 3
        # the table row carries the adapter's bytes
        row = reg.table()["layers"][0]["wqkv"]["a"][i0]
        np.testing.assert_array_equal(
            np.asarray(row), np.asarray(ads["a0"]["layers"][0]["wqkv"]["a"]))
        reg.load("a1", ads["a1"])
        with pytest.raises(ValueError, match="full"):
            reg.load("a2", ads["a0"])
        # refcount discipline: retained rows refuse evict AND hot-reload
        assert reg.retain("a0") == i0
        with pytest.raises(RuntimeError, match="referenced"):
            reg.evict("a0")
        with pytest.raises(RuntimeError, match="referenced"):
            reg.load("a0", ads["a1"])
        reg.release("a0")
        with pytest.raises(RuntimeError, match="unretained"):
            reg.release("a0")
        reg.evict("a0")
        with pytest.raises(ValueError, match="resident"):
            reg.retain("a0")
        with pytest.raises(ValueError, match="no adapter"):
            reg.evict("a0")
        reg.load("a2", ads["a0"])               # freed row reused
        assert reg.resident() == ("a1", "a2")
        # quotas: "base" is a quotable tenant, evict drops the quota
        reg.set_quota("base", 2)
        assert reg.quota("base") == 2
        reg.set_quota("base", None)
        assert reg.quota("base") is None
        with pytest.raises(ValueError, match="quota"):
            reg.set_quota("a1", 0)
        g = reg.gauges()
        assert g["capacity"] == 2 and g["resident"] == 2
        assert g["loads_total"] == 3 and g["evictions_total"] == 1

    def test_adapter_names_are_validated(self, model, lora_setup):
        """One identifier grammar everywhere a name travels (paths,
        labels, prefix-reuse salts): a name embedding NUL + digits could
        otherwise forge another (name, generation) salt and alias two
        tenants' cached K/V."""
        from horovod_tpu.parallel.checkpoint import adapter_path
        cfg, _ = model
        lora, ads = lora_setup
        reg = AdapterRegistry(cfg, lora, capacity=1)
        for bad in ("", "a\x001", "a/b", ".hidden", "a" * 129, 7,
                    "base", "retired"):
            with pytest.raises(ValueError, match="adapter name"):
                reg.load(bad, ads["a0"])
            with pytest.raises(ValueError, match="adapter name"):
                adapter_path("/tmp", bad)
        # "base" stays quotable as the adapter-less traffic class even
        # though no adapter may claim the name
        reg.set_quota("base", 2)
        assert reg.quota("base") == 2
        assert reg.load("Ok-name.v2", ads["a0"]) == 0


class TestEngineMultiTenant:
    def test_mixed_batch_bit_identity(self, engines):
        """THE acceptance contract: each tenant's stream is bit-identical
        alone, in a mixed-adapter batch, and interleaved with base
        traffic — and base traffic through an adapter-enabled engine is
        bit-identical to a plain engine's."""
        plain, eng = engines["plain"], engines["adapter"]
        base_ref = plain.generate(PROMPT, timeout=60)
        alone = {t: eng.generate(PROMPT, adapter=t, timeout=60)
                 for t in ("a0", "a1")}
        assert alone["a0"]["tokens"] != base_ref["tokens"]
        assert alone["a0"]["tokens"] != alone["a1"]["tokens"]
        assert eng.generate(PROMPT, timeout=60)["tokens"] \
            == base_ref["tokens"]
        n0 = len(eng._compiled)
        hs = [eng.submit(PROMPT, adapter="a0"),
              eng.submit(PROMPT, adapter="a1"),
              eng.submit(PROMPT)]
        res = [h.result(60) for h in hs]
        assert res[0]["tokens"] == alone["a0"]["tokens"]
        assert res[1]["tokens"] == alone["a1"]["tokens"]
        assert res[2]["tokens"] == base_ref["tokens"]
        assert res[0]["tenant"] == "a0" and res[2]["tenant"] == "base"
        # compile-cache pin: the mixed batch compiled NOTHING new, and
        # the adapter engine's cache matches the plain engine's exactly
        assert len(eng._compiled) == n0
        assert set(eng._compiled_ids) == set(plain._compiled_ids)

    def test_seeded_sampling_bit_identity(self, engines):
        samp = serve.SamplingParams(temperature=0.7, top_k=8, seed=11)
        eng = engines["adapter"]
        alone = eng.generate(PROMPT, adapter="a0", sampling=samp,
                             timeout=60)
        hs = [eng.submit(PROMPT, adapter="a0", sampling=samp),
              eng.submit(PROMPT, adapter="a1", sampling=samp)]
        assert hs[0].result(60)["tokens"] == alone["tokens"]

    def test_quota_rejection_split_and_release(self, model, lora_setup):
        """Over-quota rejection is its own reason (tenant_quota) next to
        slots_full/blocks_exhausted, counted in /stats and the labeled
        hvd_rejected_total — own PAGED engine (counter-exact, and it
        exercises the paged adapter arg path)."""
        cfg, params = model
        lora, ads = lora_setup
        reg = AdapterRegistry(cfg, lora, capacity=2)
        reg.load("a0", ads["a0"], quota=1)
        eng = _engine(params, cfg, adapters=reg, kv_layout="paged",
                      block_size=4)
        try:
            h1 = eng.submit(PROMPT, adapter="a0", max_new_tokens=8)
            with pytest.raises(ServerOverloadedError, match="quota"):
                eng.submit(PROMPT, adapter="a0")
            assert h1.result(60)["n_tokens"] == 8
            # quota released with the stream; base stays unlimited
            assert eng.generate(PROMPT, adapter="a0",
                                timeout=60)["n_tokens"] >= 1
            snap = eng.stats()
            assert snap["rejected_tenant_quota"] == 1
            assert snap["rejected_overload"] == 1
            assert snap["rejected_slots_full"] == 0
            assert snap["adapter_table"]["refcounts"]["a0"] == 0
            meta, samples = eng.prom_collect()
            quota_samples = [v for name, labels, v in samples
                             if name == "hvd_rejected_total"
                             and labels.get("reason") == "tenant_quota"]
            assert quota_samples == [1.0]
        finally:
            eng.shutdown()

    def test_prefix_reuse_is_tenant_salted(self, model, lora_setup,
                                           engines):
        """A prompt's cached K/V is a function of the weights that wrote
        it: tenant a0's registered prefix must NOT serve base (or other
        tenants') identical token prefixes, and a reloaded adapter under
        the same name must not hit its predecessor's K/V (the salt
        carries the load generation)."""
        from horovod_tpu.parallel.kv_blocks import BlockManager
        bm = BlockManager(4, 4)
        toks = np.arange(4, dtype=np.int32)
        blocks = bm.alloc(1)
        bm.register_prefix(toks, blocks, 1, salt=b"t1\x00")
        assert bm.lookup_prefix(toks) == []          # base: different salt
        assert bm.lookup_prefix(toks, salt=b"t1\x00") == blocks
        # The framing attack: a 4-aligned adapter salt spelled as int32
        # token values must NOT let base traffic hit the adapter's
        # blocks — the engine's base frame (b"\x00") can never byte-
        # equal a key whose salt starts with a name character.
        name_salt = b"abcdefghijklm\x001\x00"        # 16 bytes, 4-aligned
        bm2 = BlockManager(6, 4)
        t_blocks = bm2.alloc(1)
        tenant_toks = np.array([5, 6, 7, 8], np.int32)
        bm2.register_prefix(tenant_toks, t_blocks, 1, salt=name_salt)
        attack = np.concatenate([np.frombuffer(name_salt, "<i4"),
                                 tenant_toks]).astype(np.int32)
        assert bm2.lookup_prefix(attack, salt=b"\x00") == []
        # ... and the unframed b"" salt WOULD alias (the bug the frame
        # closes): once the attacker's own first block is registered,
        # the chain walk crosses into the tenant's registered block.
        a_blk = bm2.alloc(1)
        bm2.register_prefix(attack, a_blk, 1, salt=b"")
        assert bm2.lookup_prefix(attack, salt=b"") == [a_blk[0],
                                                       t_blocks[0]]
        assert bm2.lookup_prefix(attack, salt=b"\x00") == []

        cfg, params = model
        lora, ads = lora_setup
        reg = AdapterRegistry(cfg, lora, capacity=2)
        reg.load("a0", ads["a0"])
        eng = _engine(params, cfg, adapters=reg, kv_layout="paged",
                      block_size=4, prefix_reuse=True)
        try:
            a0_first = eng.generate(PROMPT, adapter="a0", timeout=60)
            # base with the SAME token prefix: must MISS a0's registered
            # blocks and produce the plain engine's stream bit-exactly
            base = eng.generate(PROMPT, timeout=60)
            ref = engines["plain"].generate(PROMPT, timeout=60)
            assert base["tokens"] == ref["tokens"], \
                "base stream read a tenant's adapter-delta'd KV prefix"
            # each identity hits its OWN prefix: streams unchanged
            assert eng.generate(PROMPT, timeout=60)["tokens"] \
                == ref["tokens"]
            a0_hit = eng.generate(PROMPT, adapter="a0", timeout=60)
            assert a0_hit["tokens"] == a0_first["tokens"]
            snap = eng.stats()
            assert snap["generation"]["prefix_misses_total"] == 2
            assert snap["generation"]["prefix_hits_total"] == 2
            # hot-reload under the SAME name: new generation, new salt —
            # the first request after the reload must MISS, never attend
            # over the predecessor's K/V
            hits_before = eng.stats()["generation"]["prefix_hits_total"]
            reg.evict("a0")
            reg.load("a0", ads["a1"])       # different weights, same name
            reloaded = eng.generate(PROMPT, adapter="a0", timeout=60)
            snap = eng.stats()
            assert snap["generation"]["prefix_hits_total"] == hits_before
            assert reloaded["tokens"] != a0_first["tokens"]
        finally:
            eng.shutdown()

    def test_unknown_adapter_and_no_registry_errors(self, engines):
        with pytest.raises(ValueError, match="load"):
            engines["adapter"].submit(PROMPT, adapter="nope")
        with pytest.raises(ValueError, match="AdapterRegistry"):
            engines["plain"].submit(PROMPT, adapter="a0")
        assert engines["plain"].adapter_names() is None
        assert engines["plain"].adapters_resident() is None

    def test_evict_folds_tenant_metric_state(self, model, lora_setup):
        """Tenant churn is bounded: evicting an adapter folds its
        counters into tenant="retired" and drops its recorders and
        labeled series (the FleetMetrics.forget_replica discipline) —
        counters stay monotone, reservoirs don't accumulate forever."""
        cfg, params = model
        lora, ads = lora_setup
        reg = AdapterRegistry(cfg, lora, capacity=2)
        reg.load("a0", ads["a0"])
        eng = _engine(params, cfg, adapters=reg)
        try:
            r = eng.generate(PROMPT, adapter="a0", timeout=60)
            gens = eng.stats()["tenants"]["a0"]["generations_total"]
            assert gens == 1
            reg.evict("a0")
            snap = eng.stats()
            assert "a0" not in snap["tenants"]
            assert snap["tenants"]["retired"]["generations_total"] == 1
            assert snap["tenants"]["retired"]["tokens_generated_total"] \
                == r["n_tokens"]
            text = eng.prom_metrics()
            assert 'tenant="a0"' not in text
            assert ('hvd_tenant_generations_total{engine="generate",'
                    'tenant="retired"} 1') in text
        finally:
            eng.shutdown()

    def test_tenant_stats_metrics_and_healthz(self, engines):
        eng = engines["adapter"]
        # Self-sufficient traffic (the shared engines fixture makes no
        # traffic guarantee — this test must pass in isolation too).
        for t in (None, "a0", "a1"):
            eng.generate(PROMPT, adapter=t, timeout=60)
        snap = eng.stats()
        assert snap["adapters_resident"] == 2
        assert snap["adapter_table"]["names"] == ["a0", "a1"]
        for t in ("a0", "a1", "base"):
            assert snap["tenants"][t]["generations_total"] >= 1
            assert snap["tenants"][t]["ttft_p50"] is not None
        text = eng.prom_metrics()
        assert 'hvd_tenant_ttft_seconds_bucket' in text
        assert 'tenant="a0"' in text and 'tenant="base"' in text
        assert 'hvd_adapters_resident' in text
        assert text.count('# TYPE hvd_tenant_ttft_seconds ') == 1
        with serve.HttpServer(generate=eng) as srv:
            url = f"http://{srv.host}:{srv.port}/healthz"
            try:
                resp = urllib.request.urlopen(url, timeout=5)
                body = json.loads(resp.read())
            except urllib.error.HTTPError as e:   # 503 while unwarmed
                body = json.loads(e.read())
            assert body["adapters_resident"] == 2


class TestAdapterCheckpoint:
    def test_roundtrip_and_corrupt_restore(self, model, lora_setup,
                                           tmp_path):
        cfg, _ = model
        lora, ads = lora_setup
        d = str(tmp_path)
        save_adapter(d, "a0", ads["a0"])
        back = restore_adapter(d, "a0")
        check_adapter(back, cfg, lora)      # restored tree still fits
        for x, y in zip(jax.tree_util.tree_leaves(ads["a0"]),
                        jax.tree_util.tree_leaves(back)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        with pytest.raises(FileNotFoundError, match="a1"):
            restore_adapter(d, "a1")
        # corrupt one data byte → CheckpointCorruptError naming the path
        import os
        victim = max((os.path.join(r, f)
                      for r, _, fs in os.walk(os.path.join(
                          d, "adapter_a0")) for f in fs
                      if "manifest" not in f and not f.endswith(".json")),
                     key=os.path.getsize)
        with open(victim, "r+b") as f:
            f.seek(12)
            b = f.read(1)
            f.seek(12)
            f.write(bytes([(b[0] + 1) % 256]))
        with pytest.raises(CheckpointCorruptError, match="adapter_a0"):
            restore_adapter(d, "a0")


# ---------------------------------------------------------------------------
# Adapter-affine fleet routing: pure host-side control flow, fake engines
# (the test_fleet.py discipline — XLA buys nothing here).
# ---------------------------------------------------------------------------


class _FakeRegistry:
    """Just enough registry surface for the router's quota walk."""

    def __init__(self, names, quotas=None):
        self._names = list(names)
        self._quotas = dict(quotas or {})

    def resident(self):
        return tuple(self._names)

    def quota(self, name):
        return self._quotas.get(name)


class _FakeEngine(ReadinessMixin):
    def __init__(self, load=0, adapters=None, quotas=None):
        self._queue = []
        self._warmed = True
        self._closed = False
        self._load = load
        self._resident = adapters           # None = no registry
        self.adapters = (None if adapters is None
                         else _FakeRegistry(adapters, quotas))
        self.submits = []
        self.loaded = []
        self.loaded_quotas = {}

    def load(self):
        return self._load

    def submit(self, *a, **kw):
        self.submits.append((a, kw))
        return "accepted"

    def adapter_names(self):
        return None if self._resident is None else tuple(self._resident)

    def adapters_resident(self):
        names = self.adapter_names()
        return None if names is None else len(names)

    def load_adapter(self, name, tree, quota=None):
        if self._resident is None:
            raise ValueError("engine has no AdapterRegistry")
        self._resident.append(name)
        self.adapters._names.append(name)
        if quota is not None:
            self.adapters._quotas[name] = quota
        self.loaded.append(name)
        self.loaded_quotas[name] = quota

    def stats(self):
        return {"requests_total": len(self.submits), "queue_depth": 0}

    def shutdown(self, drain=True, timeout=None):
        self._closed = True

    def prom_collect(self):
        return ({}, [])


def _raise_overloaded(*a, **kw):
    raise ServerOverloadedError("queue full")


def _raise_valueerror(*a, **kw):
    raise ValueError("malformed prompt")


class TestAffineRouting:
    def test_resident_replica_preferred_over_lower_load(self):
        """Affinity first, load-count tiebreak unchanged WITHIN the
        resident set; non-adapter requests keep pure least-load."""
        warm = _FakeEngine(load=5, adapters=["a0"])
        warm2 = _FakeEngine(load=9, adapters=["a0"])
        cold = _FakeEngine(load=0, adapters=[])
        router = FleetRouter(engines=[warm, warm2, cold])
        assert router.submit("x", adapter="a0") == "accepted"
        assert warm.submits and not warm2.submits and not cold.submits
        router.submit("y")                      # least load, no adapter
        assert cold.submits
        assert router._metrics.adapter_dispatch_counts() == {
            "affine": 1, "miss": 0}
        assert router.adapters_resident() == 1

    def test_miss_lazy_loads_via_source(self):
        source_calls = []

        def source(name):
            source_calls.append(name)
            return {"layers": []}

        lo = _FakeEngine(load=0, adapters=[])
        hi = _FakeEngine(load=7, adapters=[])
        router = FleetRouter(engines=[lo, hi], adapter_source=source)
        assert router.submit("x", adapter="a9") == "accepted"
        assert lo.loaded == ["a9"] and source_calls == ["a9"]
        assert not hi.submits
        assert router._metrics.adapter_dispatch_counts()["miss"] == 1
        # second request for a9: now resident → affine, no new load
        router.submit("y", adapter="a9")
        assert source_calls == ["a9"]
        assert router._metrics.adapter_dispatch_counts()["affine"] == 1

    def test_miss_without_source_raises_named_valueerror(self):
        router = FleetRouter(engines=[_FakeEngine(load=0, adapters=[])])
        with pytest.raises(ValueError, match="a7"):
            router.submit("x", adapter="a7")
        # a fleet of registry-less engines can't host adapters at all:
        # the lazy load reaches the engine, whose own refusal surfaces
        router2 = FleetRouter(engines=[_FakeEngine(load=0)],
                              adapter_source=lambda name: {"layers": []})
        with pytest.raises(ValueError, match="AdapterRegistry"):
            router2.submit("x", adapter="a7")
        assert router2.adapters_resident() is None

    def test_overloaded_resident_replica_stays_retryable(self):
        """A resident replica rejecting on LOAD plus a registry-less
        replica must surface as retryable overload, not as the
        hosting ValueError — backpressure on a hosting-capable replica
        clears; 'cannot host' does not."""
        busy = _FakeEngine(load=0, adapters=["a0"])
        busy.submit = _raise_overloaded
        hostless = _FakeEngine(load=1)          # no registry
        router = FleetRouter(engines=[busy, hostless],
                             adapter_source=lambda n: {"layers": []})
        with pytest.raises(ServerOverloadedError):
            router.submit("x", adapter="a0")

    def test_lazy_load_bounded_to_one_per_dispatch(self):
        """An overloaded burst must not replicate the adapter into
        every table on the failover walk (rows are never auto-evicted):
        at most ONE replica is seeded per dispatch, and the retry —
        backpressure is retryable — seeds the next one on demand."""
        full = _FakeEngine(load=0, adapters=[])
        full.submit = _raise_overloaded
        spare = _FakeEngine(load=1, adapters=[])
        third = _FakeEngine(load=2, adapters=[])
        router = FleetRouter(engines=[full, third, spare],
                             adapter_source=lambda n: {"layers": []})
        # full (least load) gets the one lazy load, rejects; the other
        # miss candidates are SKIPPED, so the fleet answers retryable
        # overload with spare/third untouched.
        with pytest.raises(ServerOverloadedError):
            router.submit("x", adapter="a5")
        assert full.loaded == ["a5"]
        assert spare.loaded == [] and third.loaded == []
        # the retry prefers the (still overloaded) resident replica,
        # then seeds exactly ONE more on demand — the least-loaded miss
        assert router.submit("x", adapter="a5") == "accepted"
        assert spare.loaded == ["a5"] and third.loaded == []

    def test_evict_race_fails_over_to_other_resident_replica(self):
        """A dispatch losing an evict race (resident at snapshot time,
        gone by submit — the engine's retain raises ValueError) must
        fail over to another resident replica, not error terminally."""
        class _EvictedEngine(_FakeEngine):
            def submit(self, *a, **kw):
                raise ValueError(
                    "adapter 'a0' is not resident — load() it first")

        raced = _EvictedEngine(load=0, adapters=["a0"])
        healthy = _FakeEngine(load=5, adapters=["a0"])
        router = FleetRouter(engines=[raced, healthy])
        assert router.submit("x", adapter="a0") == "accepted"
        assert healthy.submits
        # a genuinely malformed NON-adapter request still raises
        router2 = FleetRouter(engines=[_FakeEngine(load=0)])
        router2.replicas()[0].engine.submit = _raise_valueerror
        with pytest.raises(ValueError, match="malformed"):
            router2.submit("x")

    def test_lazy_load_propagates_tenant_quota(self):
        """A lazy load must not mint a quota-free copy of the adapter:
        the quota rides over from a replica that already hosts it."""
        capped = _FakeEngine(load=0, adapters=["a0"], quotas={"a0": 5})
        capped.submit = _raise_overloaded
        fresh = _FakeEngine(load=1, adapters=[])
        router = FleetRouter(engines=[capped, fresh],
                             adapter_source=lambda n: {"layers": []})
        assert router.submit("x", adapter="a0") == "accepted"
        assert fresh.loaded_quotas == {"a0": 5}

    def test_lazy_load_race_with_concurrent_submit(self):
        """A concurrent submit that loaded (and is streaming on) the
        same adapter makes the registry refuse our redundant reload
        with RuntimeError — the dispatch must proceed, not error."""
        class _RacyEngine(_FakeEngine):
            def load_adapter(self, name, tree, quota=None):
                # the race: someone else loaded it between our residency
                # check and the load
                self._resident.append(name)
                raise RuntimeError(
                    f"adapter {name!r} is referenced by 1 live stream(s)")

        racy = _RacyEngine(load=0, adapters=[])
        router = FleetRouter(engines=[racy],
                             adapter_source=lambda n: {"layers": []})
        assert router.submit("x", adapter="a3") == "accepted"
        assert racy.submits

    def test_fleet_gauge_and_poller_line(self, monkeypatch):
        """hvd_fleet_adapters_resident rides the fleet registry and the
        FleetPoller serving line folds it in as 'adapters=K resident' —
        from the SAME labeled parse as the rest of the line (no second
        scrape)."""
        router = FleetRouter(engines=[
            _FakeEngine(adapters=["a0", "a1"]),
            _FakeEngine(adapters=["a1"])])
        text = router.prom_metrics()
        assert "hvd_fleet_adapters_resident 2" in text
        from horovod_tpu.obs import summary
        from horovod_tpu.obs.registry import parse_exposition
        fake = parse_exposition(
            'hvd_fleet_replicas{state="ready"} 2\n'
            'hvd_queue_depth{replica="r0"} 3\n'
            'hvd_fleet_adapters_resident 2\n')
        calls = []
        monkeypatch.setattr(
            summary, "scrape_exposition",
            lambda *a, **k: calls.append(a) or fake)
        poller = summary.FleetPoller("h", 9100, 1)
        line = poller.line()
        assert poller.last_mode == "serving"
        assert "adapters=2 resident" in line
        assert len(calls) == 1              # one scrape per poll
