"""Worker for the tpurun end-to-end test: public-API collectives across an
env-world (one independent JAX process per rank, the reference's process
model) over the host coordination plane."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

import horovod_tpu as hvd  # noqa: E402


def main():
    hvd.init()
    r, s = hvd.rank(), hvd.size()

    out = hvd.allreduce(jnp.full((4,), float(r + 1)), average=False, name="x")
    assert np.allclose(np.asarray(out), sum(i + 1 for i in range(s))), out

    avg = hvd.allreduce(jnp.full((2,), float(r)), average=True, name="avg")
    assert np.allclose(np.asarray(avg), sum(range(s)) / s), avg

    g = hvd.allgather(jnp.full((r + 1, 2), float(r)), name="g")
    assert g.shape == (sum(i + 1 for i in range(s)), 2), g.shape

    b = hvd.broadcast(jnp.asarray([r * 1.0, 2.0]), root_rank=0, name="b")
    assert np.allclose(np.asarray(b), [0.0, 2.0]), b

    sync = hvd.broadcast_parameters({"w": jnp.full((3,), float(r))},
                                    root_rank=0)
    assert np.allclose(np.asarray(sync["w"]), 0.0)

    # Object collectives (host-side metadata over the eager plane).
    meta = hvd.broadcast_object(
        {"epoch": 7, "note": "resume"} if r == 0 else None, root_rank=0)
    assert meta == {"epoch": 7, "note": "resume"}, meta
    objs = hvd.allgather_object({"rank": r, "payload": "x" * (r + 1)})
    assert [o["rank"] for o in objs] == list(range(s)), objs
    assert objs[-1]["payload"] == "x" * s, objs

    # Env-world training: the compiled step's gradient exchange must ride
    # the host plane (split jit-grads -> fused host allreduce -> jit-apply),
    # keeping replicas bit-synchronized — the reference's per-process-TF +
    # MPI-allreduce model.
    import optax
    from horovod_tpu import models, training

    model = models.MnistCNN()
    state, dist_opt = training.create_train_state(
        model, jax.random.PRNGKey(0), jnp.zeros((2, 784)), optax.sgd(0.01))
    step = training.make_train_step(model, dist_opt)
    rng = np.random.RandomState(7)  # same seed everywhere = same global batch
    x = rng.randn(8 * s, 784).astype(np.float32)
    w_true = rng.randn(784, 10).astype(np.float32)
    y = np.argmax(x @ w_true, axis=1)  # learnable task, not pure noise
    global_batch = (jnp.asarray(x), jnp.asarray(y))
    losses = []
    for _ in range(6):
        state, metrics = step(state, training.shard_batch(global_batch))
        losses.append(float(np.asarray(metrics["loss"])))
    assert losses[-1] < losses[0], losses

    # Cross-replica BatchNorm model under env-world: lax.pmean(axis_name)
    # inside the model must resolve in the jitted grads step (the axis is
    # bound by shard_map over the local mesh; the cross-rank part rides the
    # host plane).
    bn_model = models.cifar_resnet_v1(8, dtype=jnp.float32,
                                      axis_name=hvd.AXIS)
    bn_state, bn_opt = training.create_train_state(
        bn_model, jax.random.PRNGKey(1),
        jnp.zeros((2, 16, 16, 3), jnp.float32), optax.sgd(0.05))
    bn_step = training.make_train_step(bn_model, bn_opt)
    xb = rng.randn(2 * s, 16, 16, 3).astype(np.float32)
    yb = rng.randint(0, 10, size=(2 * s,))
    bn_batch = (jnp.asarray(xb), jnp.asarray(yb))
    for _ in range(2):
        bn_state, bn_metrics = bn_step(bn_state,
                                       training.shard_batch(bn_batch))
    assert np.isfinite(float(np.asarray(bn_metrics["loss"])))

    # Replicas must hold identical params after host-plane averaging.
    checksum = np.asarray(
        sum(float(jnp.sum(jnp.abs(l)))
            for l in jax.tree_util.tree_leaves(state.params)),
        np.float64).reshape(1)
    all_sums = np.asarray(hvd.allgather(jnp.asarray(checksum), name="sync"))
    assert np.allclose(all_sums, all_sums[0]), all_sums

    print(f"rank {r}/{s}: LAUNCHER OK", flush=True)
    hvd.shutdown()


if __name__ == "__main__":
    main()
