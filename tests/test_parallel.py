"""Multi-axis parallelism tests on the 8-device CPU mesh: each sharded
implementation is checked against a dense single-device reference computed
on the gathered data (algebraic-identity style, SURVEY §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from horovod_tpu import parallel
from horovod_tpu.parallel import (
    TransformerConfig,
    create_hybrid_mesh,
    gpipe,
    make_parallel_train_step,
    make_pp_transformer_train_step,
    moe_ffn,
    one_f_one_b,
    ring_attention,
    ulysses_attention,
)


def _dense_attention(q, k, v, causal):
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (q.shape[-1] ** 0.5)
    if causal:
        t = q.shape[1]
        pos = jnp.arange(t)
        scores = jnp.where(pos[:, None] >= pos[None, :], scores, -1e30)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        B, T, H, D, S = 2, 16, 4, 8, 4
        rng = np.random.RandomState(0)
        q, k, v = (jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
                   for _ in range(3))
        expected = _dense_attention(q, k, v, causal)

        mesh = create_hybrid_mesh(sp=S, devices=jax.devices()[:S])
        f = jax.jit(jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, axis_name="sp",
                                           causal=causal),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"), check_vma=False))
        out = f(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_ulysses_matches_dense(self, causal):
        B, T, H, D, S = 2, 16, 4, 8, 4
        rng = np.random.RandomState(1)
        q, k, v = (jnp.asarray(rng.randn(B, T, H, D), jnp.float32)
                   for _ in range(3))
        expected = _dense_attention(q, k, v, causal)

        mesh = create_hybrid_mesh(sp=S, devices=jax.devices()[:S])
        f = jax.jit(jax.shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, axis_name="sp",
                                              causal=causal),
            mesh=mesh, in_specs=(P(None, "sp"),) * 3,
            out_specs=P(None, "sp"), check_vma=False))
        out = f(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   rtol=2e-4, atol=2e-5)


class TestTensorParallel:
    def test_column_row_pair_matches_dense(self):
        """column @ row with psum == the unsharded two-layer matmul."""
        D, F, S = 8, 16, 4
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(3, D), jnp.float32)
        w1 = jnp.asarray(rng.randn(D, F), jnp.float32)
        w2 = jnp.asarray(rng.randn(F, D), jnp.float32)
        expected = (x @ w1) @ w2

        mesh = create_hybrid_mesh(tp=S, devices=jax.devices()[:S])
        f = jax.jit(jax.shard_map(
            lambda x, w1, w2: parallel.row_parallel(
                parallel.column_parallel(x, w1), w2, axis_name="tp"),
            mesh=mesh,
            in_specs=(P(), P(None, "tp"), P("tp", None)),
            out_specs=P(), check_vma=False))
        np.testing.assert_allclose(np.asarray(f(x, w1, w2)),
                                   np.asarray(expected), rtol=1e-4)


class TestMoE:
    def test_tokens_routed_and_transformed(self):
        T, D, F, E = 16, 8, 16, 4
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(E * T, D), jnp.float32)
        gate = jnp.asarray(rng.randn(D, E), jnp.float32)
        w1 = jnp.asarray(rng.randn(E, D, F), jnp.float32) * 0.1
        w2 = jnp.asarray(rng.randn(E, F, D), jnp.float32) * 0.1

        mesh = create_hybrid_mesh(ep=E, devices=jax.devices()[:E])
        f = jax.jit(jax.shard_map(
            lambda x, g, w1, w2: moe_ffn(x, g, w1[0], w2[0],
                                         axis_name="ep",
                                         capacity_factor=4.0),
            mesh=mesh,
            in_specs=(P("ep"), P(), P("ep", None, None),
                      P("ep", None, None)),
            out_specs=(P("ep"), P()), check_vma=False))
        y, aux = f(x, gate, w1, w2)
        assert y.shape == x.shape
        assert np.isfinite(np.asarray(y)).all()
        assert float(aux) > 0

        # Reference: with ample capacity, each token goes through its
        # argmax expert's FFN scaled by the gate prob.
        probs = jax.nn.softmax(x @ gate, axis=-1)
        eidx = jnp.argmax(probs, axis=-1)
        expected = []
        for i in range(x.shape[0]):
            e = int(eidx[i])
            h = jax.nn.gelu(x[i] @ w1[e])
            expected.append((h @ w2[e]) * probs[i, e])
        np.testing.assert_allclose(np.asarray(y), np.asarray(expected),
                                   rtol=2e-4, atol=2e-5)


class TestPipeline:
    def test_gpipe_matches_sequential(self):
        """4-stage pipeline over microbatches == applying all 4 stage
        functions in order on each microbatch."""
        S, M, mb, D = 4, 6, 3, 8
        rng = np.random.RandomState(0)
        ws = jnp.asarray(rng.randn(S, D, D), jnp.float32) * 0.3
        x = jnp.asarray(rng.randn(M, mb, D), jnp.float32)

        def stage_fn(w, a):
            return jnp.tanh(a @ w)

        expected = x
        for s in range(S):
            expected = jnp.tanh(expected @ ws[s])

        mesh = create_hybrid_mesh(pp=S, devices=jax.devices()[:S])
        f = jax.jit(jax.shard_map(
            lambda w, x: gpipe(stage_fn, w[0], x, axis_name="pp"),
            mesh=mesh, in_specs=(P("pp", None, None), P()),
            out_specs=P(), check_vma=False))
        out = f(ws, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected),
                                   rtol=1e-5, atol=1e-6)

    def test_gpipe_differentiable(self):
        S, M, mb, D = 4, 4, 2, 4
        rng = np.random.RandomState(1)
        ws = jnp.asarray(rng.randn(S, D, D), jnp.float32) * 0.3
        x = jnp.asarray(rng.randn(M, mb, D), jnp.float32)

        mesh = create_hybrid_mesh(pp=S, devices=jax.devices()[:S])

        def loss_fn(w_local, x):
            out = gpipe(lambda w, a: jnp.tanh(a @ w), w_local[0], x,
                        axis_name="pp")
            # Sum-of-squares loss; pmean for identical value on all stages.
            return jax.lax.pmean(jnp.mean(out * out), "pp")

        g = jax.jit(jax.shard_map(
            jax.grad(loss_fn), mesh=mesh,
            in_specs=(P("pp", None, None), P()),
            out_specs=P("pp", None, None), check_vma=False))(ws, x)
        assert g.shape == ws.shape
        # Every stage's weight must receive gradient signal.
        norms = np.asarray(jnp.sum(jnp.abs(g), axis=(1, 2)))
        assert (norms > 0).all(), norms


class TestOneFOneB:
    """1F1B-style memory-bounded pipeline training: loss and EVERY stage's
    parameter gradients must match sequential autodiff exactly (the
    schedule only reorders work; recompute-in-VJP must not change math)."""

    def _run(self, S, M, mb=3, D=8, seed=0):
        rng = np.random.RandomState(seed)
        ws = jnp.asarray(rng.randn(S, D, D), jnp.float32) * 0.3
        x = jnp.asarray(rng.randn(M, mb, D), jnp.float32)
        y = jnp.asarray(rng.randn(M, mb, D), jnp.float32)

        def stage_fn(w, a):
            return jnp.tanh(a @ w)

        def loss_fn(act, yy):
            return jnp.mean((act - yy) ** 2)

        def full_loss(ws_all):
            total = 0.0
            for m in range(M):
                a = x[m]
                for s in range(S):
                    a = jnp.tanh(a @ ws_all[s])
                total = total + loss_fn(a, y[m])
            return total / M

        mesh = create_hybrid_mesh(pp=S, devices=jax.devices()[:S])

        def wrapped(w, xx, yy):
            loss, grads = one_f_one_b(stage_fn, w[0], xx, yy, loss_fn,
                                      axis_name="pp")
            return loss, grads[None]

        f = jax.jit(jax.shard_map(
            wrapped, mesh=mesh,
            in_specs=(P("pp", None, None), P(), P()),
            out_specs=(P(), P("pp", None, None)), check_vma=False))
        loss, grads = f(ws, x, y)
        return (float(loss), np.asarray(grads),
                float(full_loss(ws)), np.asarray(jax.grad(full_loss)(ws)))

    def test_matches_sequential_autodiff(self):
        loss, grads, eloss, egrads = self._run(S=4, M=6)
        np.testing.assert_allclose(loss, eloss, rtol=1e-5)
        np.testing.assert_allclose(grads, egrads, rtol=1e-4, atol=1e-6)

    def test_fewer_microbatches_than_stages(self):
        loss, grads, eloss, egrads = self._run(S=4, M=2, seed=3)
        np.testing.assert_allclose(loss, eloss, rtol=1e-5)
        np.testing.assert_allclose(grads, egrads, rtol=1e-4, atol=1e-6)

    def test_two_stages(self):
        loss, grads, eloss, egrads = self._run(S=2, M=8, seed=5)
        np.testing.assert_allclose(loss, eloss, rtol=1e-5)
        np.testing.assert_allclose(grads, egrads, rtol=1e-4, atol=1e-6)

    def test_bf16_activations(self):
        """The carry buffers must track the activation dtype — bf16
        microbatches (the low-precision large-M regime 1F1B targets) must
        trace and produce finite f32 param grads."""
        S, M, mb, D = 4, 5, 2, 8
        rng = np.random.RandomState(2)
        ws = jnp.asarray(rng.randn(S, D, D), jnp.float32) * 0.3
        x = jnp.asarray(rng.randn(M, mb, D), jnp.bfloat16)
        y = jnp.asarray(rng.randn(M, mb, D), jnp.bfloat16)

        def stage_fn(w, a):
            return jnp.tanh(a @ w.astype(jnp.bfloat16))

        def loss_fn(act, yy):
            return jnp.mean(
                (act.astype(jnp.float32) - yy.astype(jnp.float32)) ** 2)

        mesh = create_hybrid_mesh(pp=S, devices=jax.devices()[:S])

        def wrapped(w, xx, yy):
            loss, grads = one_f_one_b(stage_fn, w[0], xx, yy, loss_fn,
                                      axis_name="pp")
            return loss, grads[None]

        loss, grads = jax.jit(jax.shard_map(
            wrapped, mesh=mesh,
            in_specs=(P("pp", None, None), P(), P()),
            out_specs=(P(), P("pp", None, None)), check_vma=False))(ws, x, y)
        assert np.isfinite(float(loss))
        g = np.asarray(grads, np.float32)
        assert np.isfinite(g).all()
        assert (np.abs(g).sum(axis=(1, 2)) > 0).all()  # every stage learns

    def test_head_params_and_input_grads_match_sequential(self):
        """The trainable loss head's grads (last stage) and the input
        cotangents (stage 0) must equal sequential autodiff — the paths
        the pipelined transformer's embedding training rides."""
        S, M, mb, D = 4, 5, 3, 8
        rng = np.random.RandomState(0)
        ws = jnp.asarray(rng.randn(S, D, D), jnp.float32) * 0.3
        head = jnp.asarray(rng.randn(D, D), jnp.float32) * 0.2
        x = jnp.asarray(rng.randn(M, mb, D), jnp.float32)
        y = jnp.asarray(rng.randn(M, mb, D), jnp.float32)

        def stage_fn(w, a):
            return jnp.tanh(a @ w)

        def loss_fn(act, yy, h):
            return jnp.mean((act @ h - yy) ** 2)

        def full_loss(ws_all, h, xx):
            total = 0.0
            for m in range(M):
                a = xx[m]
                for s in range(S):
                    a = jnp.tanh(a @ ws_all[s])
                total = total + loss_fn(a, y[m], h)
            return total / M

        egw, egh, egx = jax.grad(full_loss, argnums=(0, 1, 2))(ws, head, x)

        mesh = create_hybrid_mesh(pp=S, devices=jax.devices()[:S])

        def wrapped(w, h, xx, yy):
            loss, gw, gh, gx = one_f_one_b(
                stage_fn, w[0], xx, yy, loss_fn, axis_name="pp",
                head_params=h, return_input_grads=True)
            return (loss, gw[None], jax.lax.psum(gh, "pp"),
                    jax.lax.psum(gx, "pp"))

        loss, gw, gh, gx = jax.jit(jax.shard_map(
            wrapped, mesh=mesh,
            in_specs=(P("pp", None, None), P(), P(), P()),
            out_specs=(P(), P("pp", None, None), P(), P()),
            check_vma=False))(ws, head, x, y)
        np.testing.assert_allclose(float(loss),
                                   float(full_loss(ws, head, x)), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(egw),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(gh), np.asarray(egh),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(egx),
                                   rtol=1e-4, atol=1e-6)

    def test_training_loop_converges(self):
        """SGD on the 1F1B gradients reduces the loss (the grads are not
        just numerically right once; they drive optimization)."""
        S, M, mb, D = 4, 4, 4, 6
        rng = np.random.RandomState(7)
        ws = jnp.asarray(rng.randn(S, D, D), jnp.float32) * 0.3
        x = jnp.asarray(rng.randn(M, mb, D), jnp.float32)
        y = jnp.asarray(rng.randn(M, mb, D), jnp.float32) * 0.1

        def stage_fn(w, a):
            return jnp.tanh(a @ w)

        def loss_fn(act, yy):
            return jnp.mean((act - yy) ** 2)

        mesh = create_hybrid_mesh(pp=S, devices=jax.devices()[:S])

        def train_step(w, xx, yy):
            loss, g = one_f_one_b(stage_fn, w[0], xx, yy, loss_fn,
                                  axis_name="pp")
            return loss, (w[0] - 0.5 * g)[None]

        f = jax.jit(jax.shard_map(
            train_step, mesh=mesh,
            in_specs=(P("pp", None, None), P(), P()),
            out_specs=(P(), P("pp", None, None)), check_vma=False))
        losses = []
        for _ in range(30):
            loss, ws = f(ws, x, y)
            losses.append(float(loss))
        assert losses[-1] < 0.5 * losses[0], losses


class TestPPTransformer:
    """Pipelined transformer (dp x pp x tp over one_f_one_b): the sharded
    pipelined loss must equal a direct sequential implementation of the
    same architecture on the same parameter values, and training must
    reduce the loss."""

    CFG = dict(vocab=64, d_model=32, n_heads=4, n_layers=4, d_ff=64,
               dtype=jnp.float32, unembed_dtype=jnp.float32,
               attn_backend="xla")

    def _reference_loss(self, params, tokens, labels, cfg):
        """Non-pipelined, non-sharded forward from the pp param layout."""
        from horovod_tpu.parallel.transformer import _rms_norm
        st = params["stages"]
        S, lps = st["wqkv"].shape[:2]
        d_head = cfg.d_model // cfg.n_heads
        x = params["embed"][tokens]
        for s in range(S):
            for i in range(lps):
                h = _rms_norm(x, st["ln1"][s, i])
                # head-major qkv layout (see pp_transformer._block)
                qkv = (h @ st["wqkv"][s, i]).reshape(
                    x.shape[0], x.shape[1], cfg.n_heads, 3, d_head)
                attn = _dense_attention(qkv[..., 0, :], qkv[..., 1, :],
                                        qkv[..., 2, :], causal=True)
                x = x + attn.reshape(x.shape[0], x.shape[1], -1) \
                    @ st["wo"][s, i]
                h = _rms_norm(x, st["ln2"][s, i])
                x = x + jax.nn.gelu(h @ st["w1"][s, i]) @ st["w2"][s, i]
        h = _rms_norm(x, params["lnf"])
        logits = h @ params["embed"].T
        logp = jax.nn.log_softmax(logits, axis=-1)
        return float(jnp.mean(-jnp.take_along_axis(
            logp, labels[..., None], axis=-1)))

    @pytest.mark.parametrize("mesh_axes", [dict(dp=2, pp=2, tp=2),
                                           dict(dp=2, pp=4),
                                           dict(pp=2)])
    def test_loss_matches_sequential_reference(self, mesh_axes):
        cfg = TransformerConfig(**self.CFG)
        n_dev = int(np.prod(list(mesh_axes.values())))
        mesh = create_hybrid_mesh(devices=jax.devices()[:n_dev],
                                  **mesh_axes)
        init_state, step = make_pp_transformer_train_step(
            cfg, mesh, optax.sgd(0.0), n_microbatches=4)  # lr 0: loss probe
        params, opt_state = init_state(jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, cfg.vocab, (8, 8)), jnp.int32)
        labels = jnp.roll(tokens, -1, axis=1)
        _, _, loss = step(params, opt_state, tokens, labels)
        host_params = jax.tree_util.tree_map(np.asarray, params)
        host_params = jax.tree_util.tree_map(jnp.asarray, host_params)
        expect = self._reference_loss(host_params, tokens, labels, cfg)
        np.testing.assert_allclose(float(loss), expect, rtol=2e-5,
                                   atol=1e-6)

    def test_sgd_step_invariant_to_tp_size(self):
        """One SGD step from identical params must land on identical
        params at tp=2 and tp=1 — pins the BACKWARD pass across mesh
        shapes (an SGD probe catches any constant gradient mis-scaling
        that scale-invariant Adam hides; this exact bug shipped once:
        the tp psum-transpose doubled every tp-sharded weight's grad)."""
        cfg = TransformerConfig(**self.CFG)
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, cfg.vocab, (8, 8)), jnp.int32)
        labels = jnp.roll(tokens, -1, axis=1)

        results = {}
        for tp in (1, 2):
            kw = dict(pp=2)
            if tp > 1:
                kw["tp"] = tp
            mesh = create_hybrid_mesh(devices=jax.devices()[:2 * tp], **kw)
            init_state, step = make_pp_transformer_train_step(
                cfg, mesh, optax.sgd(0.1), n_microbatches=4)
            params, opt_state = init_state(jax.random.PRNGKey(0))
            params, _, loss = step(params, opt_state, tokens, labels)
            results[tp] = (float(loss),
                           jax.tree_util.tree_map(np.asarray, params))
        assert results[1][0] == pytest.approx(results[2][0], rel=1e-5)
        flat1 = jax.tree_util.tree_leaves(results[1][1])
        flat2 = jax.tree_util.tree_leaves(results[2][1])
        for a, b in zip(flat1, flat2):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-6)

    def test_trains_dp_pp_tp(self):
        cfg = TransformerConfig(**self.CFG)
        mesh = create_hybrid_mesh(dp=2, pp=2, tp=2)
        init_state, step = make_pp_transformer_train_step(
            cfg, mesh, optax.adam(1e-2), n_microbatches=4)
        params, opt_state = init_state(jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, cfg.vocab, (16, 8)), jnp.int32)
        labels = jnp.roll(tokens, -1, axis=1)
        losses = []
        for _ in range(12):
            params, opt_state, loss = step(params, opt_state, tokens,
                                           labels)
            losses.append(float(loss))
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < 0.7 * losses[0], losses


class TestParallelTransformer:
    def test_sgd_step_invariant_to_tp_size(self):
        """Same SGD-probe as the pipelined family: one step from identical
        params at tp=2 vs tp=1 must produce identical params (backward
        pass pinned across mesh shapes)."""
        cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4,
                                n_layers=2, d_ff=64, dtype=jnp.float32,
                                unembed_dtype=jnp.float32,
                                attn_backend="xla")
        rng = np.random.RandomState(1)
        tokens = jnp.asarray(rng.randint(0, cfg.vocab, (4, 16)), jnp.int32)
        labels = jnp.roll(tokens, -1, axis=1)
        results = {}
        for tp in (1, 2):
            mesh = create_hybrid_mesh(tp=tp, devices=jax.devices()[:tp])
            init_state, step = make_parallel_train_step(
                cfg, mesh, optax.sgd(0.1))
            params, opt_state = init_state(jax.random.PRNGKey(3))
            params, _, loss = step(params, opt_state, tokens, labels)
            results[tp] = (float(loss),
                           jax.tree_util.tree_map(np.asarray, params))
        assert results[1][0] == pytest.approx(results[2][0], rel=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(results[1][1]),
                        jax.tree_util.tree_leaves(results[2][1])):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-6)

    def test_dp_tp_sp_train_step(self):
        cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                                d_ff=64, dtype=jnp.float32)
        mesh = create_hybrid_mesh(dp=2, sp=2, tp=2)
        init_state, step = make_parallel_train_step(
            cfg, mesh, optax.adam(1e-2))
        params, opt_state = init_state(jax.random.PRNGKey(0))

        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, cfg.vocab, (4, 16)), jnp.int32)
        labels = jnp.roll(tokens, -1, axis=1)
        losses = []
        for _ in range(5):
            params, opt_state, loss = step(params, opt_state, tokens, labels)
            losses.append(float(loss))
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0], losses

    def test_sp_only_train_step(self):
        """Sequence-parallel-only mesh (no dp axis) must build a valid
        batch spec."""
        cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=1,
                                d_ff=64, dtype=jnp.float32)
        mesh = create_hybrid_mesh(sp=4, devices=jax.devices()[:4])
        init_state, step = make_parallel_train_step(
            cfg, mesh, optax.adam(1e-2))
        params, opt_state = init_state(jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        tokens = jnp.asarray(rng.randint(0, cfg.vocab, (2, 16)), jnp.int32)
        params, opt_state, loss = step(params, opt_state, tokens,
                                       jnp.roll(tokens, -1, axis=1))
        assert np.isfinite(float(loss))

    def test_n_experts_must_match_ep_axis(self):
        cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=1,
                                d_ff=64, n_experts=8, dtype=jnp.float32)
        mesh = create_hybrid_mesh(dp=4, ep=2)
        with pytest.raises(ValueError, match="n_experts"):
            make_parallel_train_step(cfg, mesh, optax.adam(1e-2))

    def test_dp_ep_moe_train_step(self):
        cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, n_layers=1,
                                d_ff=64, n_experts=4, dtype=jnp.float32)
        mesh = create_hybrid_mesh(dp=2, ep=4)
        init_state, step = make_parallel_train_step(
            cfg, mesh, optax.adam(1e-2))
        params, opt_state = init_state(jax.random.PRNGKey(0))

        rng = np.random.RandomState(0)
        # Batch shards over dp×ep = 8.
        tokens = jnp.asarray(rng.randint(0, cfg.vocab, (8, 8)), jnp.int32)
        labels = jnp.roll(tokens, -1, axis=1)
        losses = []
        for _ in range(5):
            params, opt_state, loss = step(params, opt_state, tokens, labels)
            losses.append(float(loss))
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0], losses


class TestChunkedLoss:
    """loss_chunk: the online chunked cross-entropy must match the dense
    log_softmax path exactly — loss value AND one full train step's
    resulting params — while never materializing [*, vocab] logits."""

    CFG = dict(vocab=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
               dtype=jnp.float32, unembed_dtype=jnp.float32,
               attn_backend="xla")

    def _one_step(self, loss_chunk):
        from horovod_tpu.parallel.transformer import (
            TransformerConfig, make_parallel_train_step)
        from jax.sharding import Mesh
        cfg = TransformerConfig(**self.CFG, loss_chunk=loss_chunk)
        mesh = Mesh(np.array(jax.devices()[:2]), ("dp",))
        init_state, step = make_parallel_train_step(
            cfg, mesh, optax.sgd(0.1))
        params, opt_state = init_state(jax.random.PRNGKey(3))
        rng = np.random.RandomState(1)
        tokens = jnp.asarray(rng.randint(0, 64, (4, 16)), jnp.int32)
        labels = jnp.roll(tokens, -1, axis=1)
        params, opt_state, loss = step(params, opt_state, tokens, labels)
        return float(loss), jax.tree_util.tree_map(np.asarray, params)

    def test_matches_dense_loss_and_step(self):
        dense_loss, dense_params = self._one_step(0)
        for chunk in (16, 32, 64):
            c_loss, c_params = self._one_step(chunk)
            np.testing.assert_allclose(c_loss, dense_loss, rtol=1e-5,
                                       atol=1e-6, err_msg=f"chunk={chunk}")
            jax.tree_util.tree_map(
                lambda a, b: np.testing.assert_allclose(
                    a, b, rtol=2e-5, atol=2e-6), c_params, dense_params)

    def test_chunk_must_divide_vocab(self):
        from horovod_tpu.parallel.transformer import (
            TransformerConfig, chunked_nll)
        cfg = TransformerConfig(**self.CFG, loss_chunk=48)
        with pytest.raises(ValueError, match="divide vocab"):
            chunked_nll(jnp.zeros((2, 4, 32)), jnp.zeros((64, 32)),
                        jnp.zeros((2, 4), jnp.int32), cfg)

    def test_out_of_range_labels_match_dense(self):
        """ADVICE r4 #1: a padding/ignore-index label (e.g. -1 or vocab)
        must produce the SAME per-token nll as the dense path (which clips
        via take_along_axis) — toggling loss_chunk must not change the
        loss on any input."""
        from horovod_tpu.parallel.transformer import (
            TransformerConfig, chunked_nll)
        cfg = TransformerConfig(**self.CFG, loss_chunk=16)
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(2, 4, 32), jnp.float32)
        embed = jnp.asarray(rng.randn(64, 32) * 0.1, jnp.float32)
        labels = jnp.asarray([[-1, 0, 63, 64], [7, -5, 100, 1]],
                             jnp.int32)

        logits = x @ embed.T
        logp = jax.nn.log_softmax(logits, axis=-1)
        dense = -jnp.take_along_axis(
            logp, jnp.clip(labels, 0, 63)[..., None], axis=-1)[..., 0]
        got = chunked_nll(x, embed, labels, cfg)
        np.testing.assert_allclose(np.asarray(got), np.asarray(dense),
                                   rtol=1e-5, atol=1e-6)


class TestPackedQKVAttention:
    """The packed-qkv kernel branch (d_head=128, pallas backend) must
    compute the same function as the xla-backend split path INSIDE the
    sharded train step — including under tensor parallelism, where heads
    shard and n_heads_local differs from n_heads."""

    def _two_steps(self, backend, mesh_axes):
        from horovod_tpu.parallel.transformer import (
            TransformerConfig, make_parallel_train_step)
        from horovod_tpu.parallel.mesh import create_hybrid_mesh
        cfg = TransformerConfig(vocab=64, d_model=256, n_heads=2,
                                n_layers=2, d_ff=128, dtype=jnp.float32,
                                unembed_dtype=jnp.float32,
                                attn_backend=backend)  # d_head = 128
        n_dev = int(np.prod(list(mesh_axes.values())))
        mesh = create_hybrid_mesh(devices=jax.devices()[:n_dev],
                                  **mesh_axes)
        init_state, step = make_parallel_train_step(cfg, mesh,
                                                    optax.sgd(0.1))
        params, opt = init_state(jax.random.PRNGKey(7))
        rng = np.random.RandomState(3)
        tokens = jnp.asarray(rng.randint(0, 64, (4, 256)), jnp.int32)
        labels = jnp.roll(tokens, -1, axis=1)
        params, opt, l1 = step(params, opt, tokens, labels)
        _, _, l2 = step(params, opt, tokens, labels)
        return float(l1), float(l2)

    @pytest.mark.parametrize("mesh_axes", [dict(dp=2), dict(dp=2, tp=2)])
    def test_matches_xla_backend(self, mesh_axes):
        xla = self._two_steps("xla", mesh_axes)
        packed = self._two_steps("pallas", mesh_axes)
        np.testing.assert_allclose(packed, xla, rtol=1e-4, atol=1e-5)
