"""tpurun end-to-end: launch N ranks of a public-API worker and check every
rank's collectives (the reference CI's mpirun-based integration shape,
``.travis.yml:84-108``)."""

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
WORKER = os.path.join(HERE, "launcher_worker.py")


def test_tpurun_three_ranks():
    env = dict(os.environ, PYTHONPATH="")
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.launcher", "-np", "3", "--cpu",
         sys.executable, WORKER],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stdout + out.stderr
    for r in range(3):
        assert f"rank {r}/3: LAUNCHER OK" in out.stdout, out.stdout
