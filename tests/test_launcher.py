"""tpurun end-to-end: launch N ranks of a public-API worker and check every
rank's collectives (the reference CI's mpirun-based integration shape,
``.travis.yml:84-108``)."""

import os
import subprocess
import sys

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
WORKER = os.path.join(HERE, "launcher_worker.py")


def _free_port() -> int:
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_tpurun_three_ranks():
    env = dict(os.environ, PYTHONPATH="")
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.launcher", "-np", "3", "--cpu",
         sys.executable, WORKER],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stdout + out.stderr
    for r in range(3):
        assert f"rank {r}/3: LAUNCHER OK" in out.stdout, out.stdout


@pytest.mark.subprocess_env(
    reason="this image's jaxlib CPU backend rejects jax.distributed "
           "multiprocess computations ('Multiprocess computations "
           "aren't implemented on the CPU backend'); verified failing "
           "on the seed tree")
def test_tpurun_multi_node_simulated():
    """Two tpurun invocations with --nnodes 2 (localhost standing in for
    two hosts) must form ONE world of 2 ranks over the shared coordinator
    (the mpirun -H host1,host2 analog)."""
    import re
    port = _free_port()
    env = dict(os.environ, PYTHONPATH="", XLA_FLAGS="")
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.launcher", "-np", "1",
             "--cpu", "--nnodes", "2", "--node-rank", str(i),
             "--coordinator", f"127.0.0.1:{port}", "--jax-distributed",
             sys.executable, os.path.join(HERE, "jd_worker.py")],
            cwd=ROOT, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    outs = [p.communicate(timeout=240)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
    found = re.findall(r"rank (\d+): JD OK", "".join(outs))
    assert sorted(found) == ["0", "1"], outs


@pytest.mark.subprocess_env(
    reason="this image's jaxlib CPU backend rejects jax.distributed "
           "multiprocess computations ('Multiprocess computations "
           "aren't implemented on the CPU backend'); verified failing "
           "on the seed tree")
def test_tpurun_jax_distributed():
    """--jax-distributed: compiled collectives span processes (global mesh
    + Gloo on CPU); the two ranks must train in lockstep."""
    env = dict(os.environ, PYTHONPATH="", XLA_FLAGS="")  # 1 device/proc
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.launcher", "-np", "2", "--cpu",
         "--jax-distributed",
         sys.executable, os.path.join(HERE, "jd_worker.py")],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stdout + out.stderr
    # Concurrent writers can interleave on one line; match by pattern.
    import re
    found = re.findall(r"rank (\d+): JD OK checksum ([0-9.]+)", out.stdout)
    assert len(found) == 2, out.stdout
    assert {r for r, _ in found} == {"0", "1"}, found
    assert len({c for _, c in found}) == 1, f"replicas diverged: {found}"


@pytest.mark.slow
def test_tpurun_multi_node_coord_plane_world4():
    # slow: ~70 s of subprocess spawns on the 1-core CI host, with the
    # np=3 single-node test above covering the launcher + coord plane in
    # tier-1; the full suite sits within seconds of the 870 s wall
    # budget, so the multi-node variant runs standalone / on demand
    # (`pytest tests/test_launcher.py`).
    """The full multi-host operational story (mpirun -H host1:2,host2:2
    analog, reference docs/running.md:15-45): two tpurun invocations on
    localhost, each spawning np=2 ranks with --nnodes 2 and a shared
    --coordinator, must form ONE world of 4 with node-rank arithmetic
    (node r owns global ranks 2r, 2r+1) and complete every public-API
    collective across the "hosts" over the host coordination plane."""
    import re
    port = _free_port()
    env = dict(os.environ, PYTHONPATH="", XLA_FLAGS="")
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.launcher", "-np", "2",
             "--cpu", "--nnodes", "2", "--node-rank", str(i),
             "--coordinator", f"127.0.0.1:{port}",
             sys.executable, WORKER],
            cwd=ROOT, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    outs = [p.communicate(timeout=360)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
    # Rank arithmetic: node 0 hosts ranks {0,1}, node 1 hosts {2,3}; all
    # report a world of 4.
    for node, expect in ((0, {"0", "1"}), (1, {"2", "3"})):
        found = set(re.findall(r"rank (\d+)/4: LAUNCHER OK", outs[node]))
        assert found == expect, (node, outs[node])


@pytest.mark.subprocess_env(
    reason="keras fit under a tpurun subprocess world does not reach "
           "a decreasing loss on this image's jax/jaxlib CPU build; "
           "verified failing on the seed tree")
def test_tpurun_multi_node_keras_fit():
    """Keras fit across two simulated hosts (nnodes 2, np 1 each): the
    broadcast callback + per-step gradient allreduce ride the shared
    coordinator across the node boundary (the reference's multi-node
    mpirun keras story, .travis.yml:93-108 + docs/running.md:15-45)."""
    port = _free_port()
    env = dict(os.environ, PYTHONPATH="", XLA_FLAGS="",
               KERAS_BACKEND="jax")
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.launcher", "-np", "1",
             "--cpu", "--nnodes", "2", "--node-rank", str(i),
             "--coordinator", f"127.0.0.1:{port}",
             sys.executable, os.path.join(HERE, "keras_worker.py")],
            cwd=ROOT, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    outs = [p.communicate(timeout=360)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
