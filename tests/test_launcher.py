"""tpurun end-to-end: launch N ranks of a public-API worker and check every
rank's collectives (the reference CI's mpirun-based integration shape,
``.travis.yml:84-108``)."""

import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
WORKER = os.path.join(HERE, "launcher_worker.py")


def test_tpurun_three_ranks():
    env = dict(os.environ, PYTHONPATH="")
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.launcher", "-np", "3", "--cpu",
         sys.executable, WORKER],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stdout + out.stderr
    for r in range(3):
        assert f"rank {r}/3: LAUNCHER OK" in out.stdout, out.stdout


def test_tpurun_multi_node_simulated():
    """Two tpurun invocations with --nnodes 2 (localhost standing in for
    two hosts) must form ONE world of 2 ranks over the shared coordinator
    (the mpirun -H host1,host2 analog)."""
    import socket
    import re
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    env = dict(os.environ, PYTHONPATH="", XLA_FLAGS="")
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.launcher", "-np", "1",
             "--cpu", "--nnodes", "2", "--node-rank", str(i),
             "--coordinator", f"127.0.0.1:{port}", "--jax-distributed",
             sys.executable, os.path.join(HERE, "jd_worker.py")],
            cwd=ROOT, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
        for i in range(2)
    ]
    outs = [p.communicate(timeout=240)[0] for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out
    found = re.findall(r"rank (\d+): JD OK", "".join(outs))
    assert sorted(found) == ["0", "1"], outs


def test_tpurun_jax_distributed():
    """--jax-distributed: compiled collectives span processes (global mesh
    + Gloo on CPU); the two ranks must train in lockstep."""
    env = dict(os.environ, PYTHONPATH="", XLA_FLAGS="")  # 1 device/proc
    out = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.launcher", "-np", "2", "--cpu",
         "--jax-distributed",
         sys.executable, os.path.join(HERE, "jd_worker.py")],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=240)
    assert out.returncode == 0, out.stdout + out.stderr
    # Concurrent writers can interleave on one line; match by pattern.
    import re
    found = re.findall(r"rank (\d+): JD OK checksum ([0-9.]+)", out.stdout)
    assert len(found) == 2, out.stdout
    assert {r for r, _ in found} == {"0", "1"}, found
    assert len({c for _, c in found}) == 1, f"replicas diverged: {found}"
