"""Speculative decoding (ISSUE 17): drafter units, acceptance rules,
verify-forward bitwise parity against sequential decode, engine-level
greedy digest identity across layouts, and the compile-cache pin.

All CPU and deliberately tiny (the tier-1 budget is nearly full): one
module-scoped model shared by every engine, engines built lazily per
layout and shut down once at module teardown, NO engine warmup (lazy
compiles cover exactly the buckets the prompts touch). The open-loop
spec benches and the subprocess-failover replay drill live in ci.sh.

The load-bearing claim everything here leans on: ``verify_step`` folds
the W = k+1 query columns onto the slot axis and runs the SAME compiled
dense/attention ops as ``decode_step``, so its logits and cache writes
are BITWISE equal to W sequential decode steps (jit vs jit) — not
allclose-equal. That is what lets the engine mix verify and plain
decode programs mid-stream without perturbing a greedy digest.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu import serve
from horovod_tpu.parallel.kv_blocks import (TRASH_BLOCK,
                                            init_paged_kv_cache,
                                            paged_decode_step,
                                            paged_prefill,
                                            paged_verify_step)
from horovod_tpu.parallel.lora import LoraConfig, init_adapter
from horovod_tpu.parallel.transformer import (TransformerConfig,
                                              decode_step, init_kv_cache,
                                              init_params, prefill,
                                              verify_step)
from horovod_tpu.serve.spec import (NgramProposer, SpecConfig,
                                    accept_greedy, accept_sampled)

CFG = dict(vocab=32, d_model=16, n_heads=2, n_layers=2, d_ff=32,
           dtype=jnp.float32, unembed_dtype=jnp.float32,
           attn_backend="xla")


@pytest.fixture(scope="module")
def model():
    cfg = TransformerConfig(**CFG)
    params = init_params(jax.random.PRNGKey(0), cfg)
    return cfg, params


# -- drafter ----------------------------------------------------------------


class TestNgramProposer:
    def test_repeated_ngram_proposes_continuation(self):
        p = NgramProposer()
        ctx = np.array([5, 6, 7, 9, 5, 6, 7])
        # Suffix 3-gram [5,6,7] occurred at 0; what followed was 9,5,6.
        np.testing.assert_array_equal(p.propose(ctx, 3), [9, 5, 6])

    def test_most_recent_occurrence_wins(self):
        p = NgramProposer()
        # Suffix [1,2] occurs at 0 (followed by 9) and at 3 (followed
        # by 8): recent repetition predicts — 8 must lead.
        ctx = np.array([1, 2, 9, 1, 2, 8, 1, 2])
        assert p.propose(ctx, 1).tolist() == [8]

    def test_no_match_and_short_context_are_empty(self):
        p = NgramProposer()
        assert p.propose(np.array([1, 2, 3, 4]), 3).size == 0
        assert p.propose(np.array([7]), 3).size == 0
        assert p.propose(np.array([1, 2, 1, 2]), 0).size == 0

    def test_proposal_truncates_to_k_and_to_context_end(self):
        p = NgramProposer()
        ctx = np.array([3, 4, 5, 6, 3, 4])
        # Match at 0, continuation [5, 6, 3, 4] capped at k.
        assert p.propose(ctx, 2).tolist() == [5, 6]
        # ...and never reads past the end of the context.
        assert p.propose(ctx, 99).tolist() == [5, 6, 3, 4]

    def test_min_ngram_gates_single_token_matches(self):
        ctx = np.array([9, 3, 1, 2, 3])
        assert NgramProposer().propose(ctx, 2).tolist() == [1, 2]
        assert NgramProposer(min_ngram=2).propose(ctx, 2).size == 0

    def test_window_validation(self):
        with pytest.raises(ValueError):
            NgramProposer(max_ngram=2, min_ngram=3)


class TestSpecConfig:
    def test_roundtrip(self):
        c = SpecConfig(k=6, max_ngram=4, min_ngram=2)
        assert SpecConfig.from_spec(c.to_spec()) == c

    def test_validation(self):
        with pytest.raises(ValueError):
            SpecConfig(k=0)
        with pytest.raises(ValueError):
            SpecConfig(min_ngram=5, max_ngram=2)

    def test_custom_drafter_not_serialisable(self):
        class D:
            def propose(self, context, k):
                return np.empty((0,), np.int64)

        c = SpecConfig(k=2, drafter=D())
        assert c.make_drafter() is c.drafter
        with pytest.raises(ValueError):
            c.to_spec()


# -- acceptance rules -------------------------------------------------------


def _onehot_rows(tokens, vocab=8):
    rows = np.full((len(tokens), vocab), -10.0)
    for j, t in enumerate(tokens):
        rows[j, t] = 10.0
    return rows


class TestAcceptance:
    def test_greedy_full_accept_emits_bonus(self):
        rows = _onehot_rows([3, 4, 5, 6])
        toks, hits = accept_greedy(rows, [3, 4, 5])
        assert toks == [3, 4, 5, 6] and hits == 3

    def test_greedy_mismatch_stops_at_correction(self):
        rows = _onehot_rows([3, 7, 5, 6])
        toks, hits = accept_greedy(rows, [3, 4, 5])
        # Row 1's argmax corrects the draft; later rows sit on a false
        # context and must never be read.
        assert toks == [3, 7] and hits == 1

    def test_sampled_preserves_target_distribution(self):
        """The rejection rule's whole point: the marginal over the first
        emitted token equals the target distribution EXACTLY, however
        bad the draft. Chi-square over a deterministic seeded run; the
        0.999 critical value for df=7 is 24.32."""
        vocab = 8
        p = np.arange(1.0, vocab + 1.0)
        p /= p.sum()
        logits = np.log(p)
        rows = np.stack([logits, logits])      # 1 draft + bonus row
        rng = np.random.default_rng(0)
        draft_token = 2                        # p[2] ~ 0.083: mostly rejected
        n = 4000
        counts = np.zeros(vocab)
        for _ in range(n):
            toks, _ = accept_sampled(rows, [draft_token],
                                     lambda r: np.exp(r) / np.exp(r).sum(),
                                     rng)
            counts[toks[0]] += 1
        chi2 = float((((counts - n * p) ** 2) / (n * p)).sum())
        assert chi2 < 24.32, (chi2, counts / n, p)

    def test_sampled_is_a_pure_function_of_the_rng(self):
        rows = np.random.RandomState(3).randn(4, 8)
        probs = lambda r: (lambda e: e / e.sum())(np.exp(r - r.max()))
        a = accept_sampled(rows, [1, 2, 3], probs,
                           np.random.default_rng(42))
        b = accept_sampled(rows, [1, 2, 3], probs,
                           np.random.default_rng(42))
        assert a == b

    def test_sampled_point_mass_edge_accepts_draft(self):
        # Target distribution IS the point mass on the draft token: the
        # residual is empty and the only lawful emission is the draft.
        p = np.zeros(8)
        p[5] = 1.0
        rows = np.stack([np.log(np.maximum(p, 1e-300))] * 2)
        toks, hits = accept_sampled(rows, [5], lambda r: p,
                                    np.random.default_rng(0))
        assert toks[0] == 5 and hits >= 1


# -- verify forward: bitwise parity with sequential decode ------------------


def _greedy_chain(params, cfg, dec, cache0, last0, pos0, w):
    """W sequential jit'd decode steps from (cache0, last0, pos0):
    returns (tokens consumed, per-step logits, final cache)."""
    cache, last, pos = cache0, last0.copy(), pos0.copy()
    toks, logs = [last.copy()], []
    for _ in range(w):
        cache, lg = dec(params, last, cache, pos)
        lg = np.asarray(lg)
        logs.append(lg)
        last = lg.argmax(-1).astype(np.int32)
        toks.append(last.copy())
        pos = pos + (pos >= 0)
    return np.stack(toks[:w], axis=1), np.stack(logs, axis=1), cache


class TestVerifyBitwiseParity:
    def test_contiguous_verify_matches_sequential(self, model):
        cfg, params = model
        S, L, W = 2, 5, 4
        cache = init_kv_cache(cfg, S, 32)
        pre = jax.jit(lambda p, t, c, s: prefill(p, t, c, s, cfg, length=L))
        rng = np.random.RandomState(1)
        plog = []
        for s in range(S):
            toks = rng.randint(0, cfg.vocab, (8,)).astype(np.int32)
            cache, lg = pre(params, toks, cache, s)
            plog.append(np.asarray(lg)[L - 1])
        last = np.stack(plog).argmax(-1).astype(np.int32)
        pos = np.full((S,), L, np.int32)

        dec = jax.jit(lambda p, t, c, q: decode_step(p, t, c, q, cfg))
        drafts, ref_logits, ref_cache = _greedy_chain(
            params, cfg, dec, cache, last, pos, W)

        ver = jax.jit(lambda p, t, c, q: verify_step(p, t, c, q, cfg))
        vcache, vlog = ver(params, drafts, cache, pos)
        # Bitwise, not allclose: the digest contract rests on it.
        np.testing.assert_array_equal(np.asarray(vlog), ref_logits)
        np.testing.assert_array_equal(np.asarray(vcache["k"]),
                                      np.asarray(ref_cache["k"]))
        np.testing.assert_array_equal(np.asarray(vcache["v"]),
                                      np.asarray(ref_cache["v"]))

    def test_paged_verify_matches_sequential(self, model):
        cfg, params = model
        S, L, W, bs, nb = 2, 5, 4, 4, 16
        max_blocks = 4                       # 16 positions per slot
        cache = init_paged_kv_cache(cfg, nb, bs, S)
        tables = np.full((S, max_blocks), TRASH_BLOCK, np.int32)
        tables[0] = [1, 2, 3, 4]
        tables[1] = [5, 6, 7, 8]
        pre = jax.jit(lambda p, t, c, s, wr: paged_prefill(
            p, t, c, s, wr, cfg, length=L))
        rng = np.random.RandomState(1)
        plog = []
        for s in range(S):
            toks = rng.randint(0, cfg.vocab, (8,)).astype(np.int32)
            cache, lg = pre(params, toks, cache, s, tables[s])
            plog.append(np.asarray(lg)[L - 1])
        last = np.stack(plog).argmax(-1).astype(np.int32)
        pos = np.full((S,), L, np.int32)

        dec = jax.jit(lambda p, t, c, q, bt: paged_decode_step(
            p, t, c, q, bt, cfg))
        cache_d, last_d, pos_d = cache, last.copy(), pos.copy()
        ref_logits = []
        drafts = [last.copy()]
        for _ in range(W):
            cache_d, lg = dec(params, last_d, cache_d, pos_d, tables)
            lg = np.asarray(lg)
            ref_logits.append(lg)
            last_d = lg.argmax(-1).astype(np.int32)
            drafts.append(last_d.copy())
            pos_d = pos_d + 1
        drafts = np.stack(drafts[:W], axis=1)

        ver = jax.jit(lambda p, t, c, q, bt: paged_verify_step(
            p, t, c, q, bt, cfg))
        vcache, vlog = ver(params, drafts, cache, pos, tables)
        np.testing.assert_array_equal(np.asarray(vlog),
                                      np.stack(ref_logits, axis=1))
        np.testing.assert_array_equal(np.asarray(vcache["k"]),
                                      np.asarray(cache_d["k"]))
        np.testing.assert_array_equal(np.asarray(vcache["v"]),
                                      np.asarray(cache_d["v"]))

    def test_verify_tail_past_max_len_is_dropped(self, model):
        """Contiguous verify near the cache edge: writes at wpos >=
        max_len ride XLA's drop-out-of-bounds scatter mode — rows
        INSIDE the cache must come out exactly as a plain decode step
        wrote them, with nothing wrapped or clobbered."""
        cfg, params = model
        S, max_len = 2, 8
        cache = init_kv_cache(cfg, S, max_len)
        pre = jax.jit(lambda p, t, c, s: prefill(p, t, c, s, cfg, length=6))
        rng = np.random.RandomState(2)
        for s in range(S):
            cache, _ = pre(params,
                           rng.randint(0, cfg.vocab, (8,)).astype(np.int32),
                           cache, s)
        pos = np.full((S,), 7, np.int32)     # one writable row left
        last = np.array([3, 4], np.int32)
        dec = jax.jit(lambda p, t, c, q: decode_step(p, t, c, q, cfg))
        ref_cache, ref_lg = dec(params, last, cache, pos)
        drafts = np.stack([last, np.array([9, 9], np.int32),
                           np.array([11, 11], np.int32)], axis=1)
        ver = jax.jit(lambda p, t, c, q: verify_step(p, t, c, q, cfg))
        vcache, vlog = ver(params, drafts, cache, pos)
        np.testing.assert_array_equal(np.asarray(vlog)[:, 0],
                                      np.asarray(ref_lg))
        np.testing.assert_array_equal(np.asarray(vcache["k"]),
                                      np.asarray(ref_cache["k"]))


# -- engine: digest identity, fallback, compile surface ---------------------


PROMPTS = ([1, 2, 3, 1, 2, 3, 1, 2],        # self-repeating: drafts hit
           [5, 6, 7, 8],
           [9, 9, 9, 9, 9])


def _collect(eng, temperature=0.0, seed=11, max_new=10):
    hs = [eng.submit(p, max_new_tokens=max_new,
                     sampling=serve.SamplingParams(
                         temperature=temperature,
                         top_k=8 if temperature > 0 else 0,
                         seed=seed + i))
          for i, p in enumerate(PROMPTS)]
    return [h.result(timeout=120) for h in hs]


@pytest.fixture(scope="module")
def engines(model):
    """Lazy per-layout engine pairs (plain, spec) over the shared
    params; nothing warms up — lazy compiles cover only the buckets the
    prompts touch."""
    cfg, params = model
    built = {}

    def lora_reg():
        lora = LoraConfig(rank=2)
        reg = serve.AdapterRegistry(cfg, lora, capacity=2)
        reg.load("a0", init_adapter(jax.random.PRNGKey(100), cfg, lora,
                                    b_scale=0.5))
        return reg

    def get(layout, spec=None, **kw):
        key = (layout, None if spec is None else id(spec))
        if key not in built:
            gkw = dict(max_slots=2, max_len=32, default_max_new_tokens=10)
            if layout.startswith("paged"):
                gkw.update(kv_layout="paged", block_size=4, n_blocks=64)
            built[key] = serve.GenerationEngine(
                params, cfg, serve.GenerationConfig(**gkw),
                adapters=(lora_reg() if layout == "paged_adapter"
                          else None),
                spec=spec, **kw)
        return built[key]

    yield get
    for eng in built.values():
        eng.shutdown(drain=False)


SPEC = SpecConfig(k=4)


class TestEngineDigests:
    @pytest.mark.parametrize("layout", ["contiguous", "paged",
                                        "paged_adapter"])
    def test_greedy_streams_identical_spec_vs_plain(self, engines, layout):
        plain, spec = engines(layout), engines(layout, SPEC)
        kw = {"adapter": "a0"} if layout == "paged_adapter" else {}
        for p in PROMPTS:
            a = plain.submit(p, max_new_tokens=10, **kw).result(120)
            b = spec.submit(p, max_new_tokens=10, **kw).result(120)
            assert a["tokens"] == b["tokens"], (layout, p)
            assert a["finish_reason"] == b["finish_reason"]
            assert a["spec_accept_rate"] is None
            assert b["spec_accept_rate"] is not None

    def test_acceptance_fires_on_repetitive_prompt(self, engines):
        spec = engines("contiguous", SPEC)
        _collect(spec)
        snap = spec.stats()
        sp = snap["spec"]
        assert snap["spec_k"] == 4
        assert sp["draft_tokens_total"] > 0
        assert sp["accept_rate"] > 0
        assert sp["tokens_per_step"] > 1.0
        assert sp["emitted_tokens_total"] > sp["steps_total"]

    def test_sampled_streams_run_to_run_deterministic(self, engines):
        spec = engines("contiguous", SPEC)
        a = _collect(spec, temperature=0.8)
        b = _collect(spec, temperature=0.8)
        assert [r["tokens"] for r in a] == [r["tokens"] for r in b]

    def test_hostile_drafter_cannot_change_a_stream(self, engines, model):
        """Acceptance-0 path: a drafter proposing garbage (plus
        out-of-vocab ids the engine must filter) costs wasted verify
        rows, never a token. Liveness: every step still emits >= 1."""
        cfg, _ = model

        class Hostile:
            def propose(self, context, k):
                return np.array([cfg.vocab - 1 - int(context[-1]) % 2,
                                 cfg.vocab + 7, -3], np.int64)[:k]

        plain = engines("contiguous")
        bad = engines("contiguous", SpecConfig(k=3, drafter=Hostile()))
        for p in PROMPTS:
            a = plain.submit(p, max_new_tokens=10).result(120)
            b = bad.submit(p, max_new_tokens=10).result(120)
            assert a["tokens"] == b["tokens"], p

    def test_empty_drafter_falls_back_to_plain_decode(self, engines):
        """A drafter with nothing to say must leave the engine on the
        ONE-TOKEN decode program — speculation is never a liveness
        dependency — while spec accounting still counts the steps."""
        class Mute:
            def propose(self, context, k):
                return np.empty((0,), np.int64)

        eng = engines("contiguous", SpecConfig(k=2, drafter=Mute()))
        plain = engines("contiguous")
        for p in PROMPTS:
            a = plain.submit(p, max_new_tokens=6).result(120)
            b = eng.submit(p, max_new_tokens=6).result(120)
            assert a["tokens"] == b["tokens"]
        sp = eng.stats()["spec"]
        assert sp["steps_total"] > 0 and sp["draft_tokens_total"] == 0
        assert sp["tokens_per_step"] == 1.0

    def test_compile_cache_grows_by_exactly_one_verify_bucket(
            self, engines):
        """The compile-surface pin: after identical traffic, the spec
        engine's executable set is the plain engine's plus exactly ONE
        key — ("verify", k+1)."""
        plain, spec = engines("contiguous"), engines("contiguous", SPEC)
        _collect(plain)
        _collect(spec)
        extra = set(spec._compiled) - set(plain._compiled)
        assert extra == {("verify", SPEC.k + 1)}, extra
        assert set(plain._compiled) - set(spec._compiled) == set()

    def test_spec_refuses_paged_kernel_and_oversized_k(self, model):
        cfg, params = model
        with pytest.raises(ValueError, match="paged_kernel"):
            serve.GenerationEngine(
                params, cfg,
                serve.GenerationConfig(max_slots=2, max_len=32,
                                       kv_layout="paged", block_size=4,
                                       paged_kernel=True),
                spec=SpecConfig(k=2))
        with pytest.raises(ValueError, match="max_len"):
            serve.GenerationEngine(
                params, cfg,
                serve.GenerationConfig(max_slots=2, max_len=4),
                spec=SpecConfig(k=4))
