"""Live elastic resize (ISSUE 9 tentpole): grow/shrink the world in place.

The contract under test: a run that receives ``resize@step=N`` quiesces at
a step boundary, recommits through the two-phase elastic commit,
canonicalizes ZeRO state host-side, re-forms the mesh and re-shards the
optimizer state in place via ``zero_from_canonical`` — and ends
BIT-IDENTICAL to a run that instead restored the quiesce commit at the
final world size through the (already proven world-agnostic) disk path
and trained the same remaining batches. Covered for 1-D dp ZeRO and
hybrid (dp, tp) meshes; plus the correctness fallback (a failed in-place
re-shard restores the quiesce recommit via the verified walk), the
trainer-loop quiesce hook, the env-world local-shard math, and eager
rejection of malformed ``resize:*`` fault specs. The multi-process drills
(tpurun shrink/grow/racing-kill) run as ci.sh chaos legs.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import elastic, training
from horovod_tpu.optimizer import (ZeroShardedState, zero_from_canonical,
                                   zero_to_canonical)
from horovod_tpu.parallel import create_hybrid_mesh
from horovod_tpu.testing import faults


class _MLP(nn.Module):
    @nn.compact
    def __call__(self, x, train=True):
        return nn.Dense(10)(nn.relu(nn.Dense(16)(x)))


def _batch(seed=0, rows=16):
    rng = np.random.RandomState(seed)
    return rng.randn(rows, 8).astype(np.float32), rng.randint(0, 10, (rows,))


def _np_tree(tree):
    return jax.tree_util.tree_map(np.asarray, tree)


def _assert_equal(got, want):
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(got),
            jax.tree_util.tree_leaves_with_path(want)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=jax.tree_util.keystr(kp))


def _assert_close(got, want, rtol=1e-5, atol=1e-7):
    for (kp, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(got),
            jax.tree_util.tree_leaves_with_path(want)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=rtol, atol=atol,
                                   err_msg=jax.tree_util.keystr(kp))


def _build_dp(world, key=0):
    """Fresh world of `world` devices + a ZeRO train state/step on it."""
    hvd.shutdown()
    hvd.init(devices=jax.devices()[:world])
    model = _MLP()
    state, opt = training.create_train_state(
        model, jax.random.PRNGKey(key), jnp.zeros((2, 8)),
        optax.adam(1e-2), zero=True)
    step = training.make_train_step(model, opt, donate=False)
    return state, step


def _canon(opt_state):
    return _np_tree(zero_to_canonical(opt_state).inner)


# ---------------------------------------------------------------------------
# Fault-spec grammar: malformed resize specs are rejected eagerly.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("bad", [
    "resize:shrink@step=3",         # missing value
    "resize:shrink=0@step=3",       # zero delta
    "resize:world=-2@step=3",       # negative target
    "resize:world=2",               # missing @step: could never fire
    "resize:shrink=x@step=3",       # non-integer value
    "resize:kill@step=3",           # non-resize action on resize target
    "rank=1:shrink=2@step=3",       # resize action on rank target
    "coord:world=2@step=1",         # resize action on coord target
    "ckpt:grow=2@step=1",           # resize action on ckpt target
])
def test_malformed_resize_specs_rejected(bad):
    with pytest.raises(faults.FaultSpecError):
        faults.parse_spec(bad)


def test_resize_spec_forms_parse():
    fs = faults.parse_spec(
        "resize:shrink=2@step=3,resize:grow=4@step=5@epoch=1,"
        "resize:world=8@step=7")
    assert [(f.action, f.value, f.step, f.epoch) for f in fs] == [
        ("shrink", 2, 3, 0), ("grow", 4, 5, 1), ("world", 8, 7, 0)]


def test_resize_hook_semantics(monkeypatch):
    monkeypatch.setenv(faults.ENV_VAR, "resize:shrink=2@step=3")
    faults.reset()
    assert faults.resize_hook(2, 4) is None
    assert faults.resize_hook(3, 4) == 2
    assert faults.resize_hook(3, 4) is None  # fires once per epoch
    monkeypatch.setenv(faults.ENV_VAR, "resize:shrink=4@step=0")
    faults.reset()
    with pytest.raises(faults.FaultSpecError, match="at least 1 rank"):
        faults.resize_hook(0, 4)  # resolves to world 0: loud, not clamped


def test_request_validations():
    es = elastic.ElasticState({"w": jnp.zeros((4,))}, None)
    rc = elastic.ResizeCoordinator(es)
    with pytest.raises(ValueError, match=">= 1"):
        rc.request(0)
    rc.request(hvd.size())          # no-op: already that size
    assert rc.poll(0) is None


# ---------------------------------------------------------------------------
# The in-place re-shard, dp-only ZeRO: resized run == disk-restore
# reference bit-for-bit, and ~= fully uninterrupted final-world run.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("old_world,new_world", [(8, 4), (4, 8)])
def test_resize_midrun_matches_restore_reference_bitwise(
        tmp_path, monkeypatch, old_world, new_world):
    pre = [_batch(seed=i) for i in range(2)]
    post = [_batch(seed=10 + i) for i in range(2)]
    try:
        # --- resized run: old world, live resize at step 2, finish ------
        monkeypatch.setenv(faults.ENV_VAR,
                           f"resize:world={new_world}@step=2")
        faults.reset()
        state, step = _build_dp(old_world)
        for b in pre:
            state, _ = step(state, b)
        es = elastic.ElasticState(state.params, state.opt_state,
                                  step=int(state.step),
                                  directory=str(tmp_path), commit_every=1)
        holder = {}

        def rebuild(target):
            model = _MLP()
            st, opt = training.create_train_state(
                model, jax.random.PRNGKey(9), jnp.zeros((2, 8)),
                optax.adam(1e-2), zero=True)
            holder["step"] = training.make_train_step(model, opt,
                                                      donate=False)
            return elastic.Rebuilt(params=st.params, opt_state=st.opt_state,
                                   train_step=holder["step"])

        rc = elastic.ResizeCoordinator(es, rebuild=rebuild)
        req = rc.poll(int(state.step))
        assert req is not None and req.target_world == new_world
        assert rc.due(int(state.step))
        rebuilt = rc.execute(req)
        assert hvd.size() == new_world
        assert es.opt_state.plan.nshards == new_world
        assert rc.resizes_completed == 1
        st2 = training.TrainState(
            step=jnp.asarray(es.step, jnp.int32), params=es.params,
            opt_state=es.opt_state, batch_stats=None)
        for b in post:
            st2, _ = rebuilt.train_step(st2, b)
        resized_params = _np_tree(st2.params)
        resized_canon = _canon(st2.opt_state)

        # --- reference: restore the quiesce commit at new_world through
        # the (already world-agnostic) DISK path, same remaining batches.
        ref_state, ref_step = _build_dp(new_world, key=7)
        es_ref = elastic.ElasticState(ref_state.params, ref_state.opt_state,
                                      directory=str(tmp_path))
        es_ref.restore()
        assert es_ref.step == 2
        st3 = training.TrainState(
            step=jnp.asarray(es_ref.step, jnp.int32), params=es_ref.params,
            opt_state=es_ref.opt_state, batch_stats=None)
        for b in post:
            st3, _ = ref_step(st3, b)
        _assert_equal(resized_params, _np_tree(st3.params))
        _assert_equal(resized_canon, _canon(st3.opt_state))

        # --- and a fully uninterrupted run at the final world stays
        # within fp reassociation noise of the resized one.
        un_state, un_step = _build_dp(new_world)
        for b in pre + post:
            un_state, _ = un_step(un_state, b)
        _assert_close(resized_params, _np_tree(un_state.params),
                      rtol=2e-4, atol=1e-6)
    finally:
        hvd.shutdown()
        hvd.init()  # restore the full test world for the rest of the suite


# ---------------------------------------------------------------------------
# Hybrid (dp, tp): the 2-D canonical form re-shards across a dp resize.
# ---------------------------------------------------------------------------


class _TpMLP(nn.Module):
    feat: int = 32

    @nn.compact
    def __call__(self, x, train=True):
        try:
            tp = int(jax.lax.axis_size("tp"))
            bound = True
        except Exception:  # noqa: BLE001 — outside the tp mesh
            tp, bound = 1, False
        w1 = self.param("w1", nn.initializers.lecun_normal(),
                        (8, self.feat // tp))
        w2 = self.param("w2", nn.initializers.lecun_normal(),
                        (self.feat // tp, 10))
        b = self.param("b", nn.initializers.zeros, (10,))
        y = jax.nn.relu(x @ w1) @ w2
        if bound:
            y = jax.lax.psum(y, "tp")
        return y + b


def _specs(mesh):
    return {"w1": P(None, "tp"), "w2": P("tp", None), "b": P()}


def _build_hybrid(dp, tp, key=0):
    hvd.shutdown()
    hvd.init(devices=jax.devices()[:dp * tp])
    mesh = create_hybrid_mesh(dp=dp, tp=tp,
                              devices=jax.devices()[:dp * tp])
    state, opt = training.create_train_state(
        _TpMLP(), jax.random.PRNGKey(key), jnp.zeros((2, 8)),
        optax.adam(1e-2), mesh=mesh, param_specs=_specs(mesh), zero=True)
    step = training.make_train_step(_TpMLP(), opt, donate=False)
    return state, step


def test_hybrid_resize_midrun_matches_restore_reference(tmp_path):
    """(dp=4, tp=2) live-resizes to (dp=2, tp=2): the 2-D canonical form
    re-shards in place, bit-identical to the disk-restore reference."""
    pre = [_batch(seed=i) for i in range(2)]
    post = [_batch(seed=20 + i) for i in range(1)]
    try:
        state, step = _build_hybrid(4, 2)
        for b in pre:
            state, _ = step(state, b)
        es = elastic.ElasticState(state.params, state.opt_state,
                                  step=int(state.step),
                                  directory=str(tmp_path), commit_every=1)
        holder = {}

        def rebuild(target):
            assert target == 4
            mesh = create_hybrid_mesh(dp=target // 2, tp=2,
                                      devices=jax.devices()[:target])
            st, opt = training.create_train_state(
                _TpMLP(), jax.random.PRNGKey(5), jnp.zeros((2, 8)),
                optax.adam(1e-2), mesh=mesh, param_specs=_specs(mesh),
                zero=True)
            holder["step"] = training.make_train_step(_TpMLP(), opt,
                                                      donate=False)
            return elastic.Rebuilt(params=st.params,
                                   opt_state=st.opt_state,
                                   train_step=holder["step"])

        rc = elastic.ResizeCoordinator(es, rebuild=rebuild)
        rc.request(4)
        req = rc.poll(int(state.step))
        assert req is not None and rc.due(int(state.step))
        rebuilt = rc.execute(req)
        st2 = training.TrainState(
            step=jnp.asarray(es.step, jnp.int32), params=es.params,
            opt_state=es.opt_state, batch_stats=None)
        for b in post:
            st2, _ = rebuilt.train_step(st2, b)
        resized_params = _np_tree(st2.params)
        resized_canon = _canon(st2.opt_state)

        ref_state, ref_step = _build_hybrid(2, 2, key=3)
        es_ref = elastic.ElasticState(ref_state.params,
                                      ref_state.opt_state,
                                      directory=str(tmp_path))
        es_ref.restore()
        assert es_ref.step == 2
        st3 = training.TrainState(
            step=jnp.asarray(es_ref.step, jnp.int32), params=es_ref.params,
            opt_state=es_ref.opt_state, batch_stats=None)
        for b in post:
            st3, _ = ref_step(st3, b)
        _assert_equal(resized_params, _np_tree(st3.params))
        _assert_equal(resized_canon, _canon(st3.opt_state))
    finally:
        hvd.shutdown()
        hvd.init()


# ---------------------------------------------------------------------------
# Correctness fallback: a failed in-place re-shard restores the quiesce
# recommit through the VERIFIED walk instead of crashing the world.
# ---------------------------------------------------------------------------


def test_resize_falls_back_to_verified_restore(tmp_path, monkeypatch):
    try:
        state, step = _build_dp(8)
        state, _ = step(state, _batch())
        saved_params = _np_tree(state.params)
        saved_canon = _canon(state.opt_state)
        es = elastic.ElasticState(state.params, state.opt_state,
                                  step=int(state.step),
                                  directory=str(tmp_path), commit_every=1)

        def rebuild(target):
            st, opt = training.create_train_state(
                _MLP(), jax.random.PRNGKey(11), jnp.zeros((2, 8)),
                optax.adam(1e-2), zero=True)
            return st.params, st.opt_state

        rc = elastic.ResizeCoordinator(es, rebuild=rebuild)
        boom = {"n": 0}
        real = elastic._place_params

        def broken_place(host, template):
            boom["n"] += 1
            if boom["n"] == 1:
                raise RuntimeError("synthetic re-shard failure")
            return real(host, template)

        monkeypatch.setattr(elastic, "_place_params", broken_place)
        rc.request(4)
        req = rc.poll(1)
        rc.execute(req)
        # Fallback engaged: world resized, values came from the VERIFIED
        # quiesce recommit on disk, bit-equal to the pre-resize state.
        assert hvd.size() == 4
        assert rc.resizes_completed == 1
        assert es.step == 1
        _assert_equal(_np_tree(es.params), saved_params)
        _assert_equal(_canon(es.opt_state), saved_canon)
    finally:
        hvd.shutdown()
        hvd.init()


def test_oversized_grow_rejected_before_teardown(tmp_path):
    """A grow target beyond the visible device count must reject BEFORE
    the old world is torn down — the job keeps training at its old size
    instead of dying mid-run on a typo'd target."""
    try:
        state, step = _build_dp(4)
        es = elastic.ElasticState(state.params, state.opt_state,
                                  step=1, directory=str(tmp_path),
                                  commit_every=1)
        rc = elastic.ResizeCoordinator(
            es, rebuild=lambda t: (state.params, state.opt_state))
        rc.request(12)   # only 8 devices exist
        req = rc.poll(1)
        with pytest.raises(ValueError, match="devices available"):
            rc.execute(req)
        # World untouched, pending cleared (the raise happens once).
        assert hvd.size() == 4
        assert rc.poll(2) is None
    finally:
        hvd.shutdown()
        hvd.init()


def test_zero_resize_without_rebuild_raises(tmp_path):
    try:
        state, step = _build_dp(8)
        state, _ = step(state, _batch())
        es = elastic.ElasticState(state.params, state.opt_state,
                                  step=1, directory=str(tmp_path))
        rc = elastic.ResizeCoordinator(es)  # no rebuild
        rc.request(4)
        req = rc.poll(1)
        with pytest.raises(ValueError, match="rebuild"):
            rc.execute(req)
    finally:
        hvd.shutdown()
        hvd.init()


# ---------------------------------------------------------------------------
# Trainer-loop quiesce hook.
# ---------------------------------------------------------------------------


def test_trainer_quiesce_hook_resizes_between_epochs(tmp_path):
    from horovod_tpu.trainer import Trainer
    try:
        state, step = _build_dp(8)
        es = elastic.ElasticState(state.params, state.opt_state,
                                  step=0, directory=str(tmp_path),
                                  commit_every=1)
        holder = {}

        def rebuild(target):
            st, opt = training.create_train_state(
                _MLP(), jax.random.PRNGKey(2), jnp.zeros((2, 8)),
                optax.adam(1e-2), zero=True)
            holder["step"] = training.make_train_step(_MLP(), opt,
                                                      donate=False)
            return elastic.Rebuilt(params=st.params,
                                   opt_state=st.opt_state,
                                   train_step=holder["step"])

        rc = elastic.ResizeCoordinator(es, rebuild=rebuild)
        trainer = Trainer(step, state, steps_per_epoch=2, verbose=False,
                          prefetch=0, resize=rc)
        rc.request(4)

        def data():
            return [_batch(seed=i) for i in range(2)]

        trainer.fit(data, epochs=2)
        # The resize executed at the first step boundary (ending epoch 0
        # early), and epoch 1 trained on the re-formed world.
        assert hvd.size() == 4
        assert rc.resizes_completed == 1
        assert trainer.train_step is holder["step"]
        assert int(trainer.state.step) >= 3
        assert len(trainer.history) == 2
    finally:
        hvd.shutdown()
        hvd.init()


def test_trainer_resize_does_not_truncate_inferred_epoch_length(tmp_path):
    """A resize-truncated first epoch must not be recorded as the inferred
    steps_per_epoch — later epochs would silently train a fraction of the
    data forever."""
    from horovod_tpu.trainer import Trainer
    try:
        state, step = _build_dp(8)
        es = elastic.ElasticState(state.params, state.opt_state,
                                  step=0, directory=str(tmp_path),
                                  commit_every=1)
        holder = {}

        def rebuild(target):
            st, opt = training.create_train_state(
                _MLP(), jax.random.PRNGKey(2), jnp.zeros((2, 8)),
                optax.adam(1e-2), zero=True)
            holder["step"] = training.make_train_step(_MLP(), opt,
                                                      donate=False)
            return elastic.Rebuilt(params=st.params,
                                   opt_state=st.opt_state,
                                   train_step=holder["step"])

        rc = elastic.ResizeCoordinator(es, rebuild=rebuild)
        trainer = Trainer(step, state, verbose=False, prefetch=0,
                          resize=rc)  # steps_per_epoch INFERRED
        rc.request(4)

        def data():
            return [_batch(seed=i) for i in range(4)]

        trainer.fit(data, epochs=2)
        # Epoch 0 was cut at step 1 by the resize; epoch 1 must still run
        # the full 4-batch stream and only THEN pin the epoch length.
        assert rc.resizes_completed == 1
        assert trainer.steps_per_epoch == 4
        assert int(trainer.state.step) == 5  # 1 pre-resize + 4 in epoch 1
    finally:
        hvd.shutdown()
        hvd.init()


# ---------------------------------------------------------------------------
# Env-world local-shard math (no subprocesses: the slicing itself).
# ---------------------------------------------------------------------------


def test_env_local_shard_canonical_roundtrip():
    """The env-world re-shard path: canonical -> per-rank [1, shard_len]
    rows must equal the corresponding rows of the full stacked re-stack,
    for every rank and across a world change."""
    state, step = None, None
    try:
        state, step = _build_dp(8)
        state, _ = step(state, _batch())
        full = state.opt_state
        canon = zero_to_canonical(full)
        plan = full.plan

        def row(zs, r):
            ids = elastic._env_local_buckets(zs)  # on local templates only
            leaves = jax.tree_util.tree_leaves(zs.inner)
            return ids, leaves

        # Build a synthetic local-shard template for each rank: row r of
        # every stacked leaf (what partition_optimizer's env-world init
        # materializes), then re-shard the canonical form onto it.
        from horovod_tpu import runtime as rt
        for r in (0, 3, 7):
            local_inner = jax.tree_util.tree_map(
                lambda l: np.asarray(l)[r:r + 1]
                if np.ndim(l) == 2 and np.shape(l)[0] == plan.nshards
                else np.asarray(l), full.inner)
            template = ZeroShardedState(inner=local_inner, plan=plan)
            assert elastic._zs_is_local(template)
            # _env_from_canonical slices the CURRENT rank's row; fake it.
            import unittest.mock as mock
            fake = mock.Mock()
            fake.controller_rank = r
            with mock.patch.object(rt, "world", return_value=fake):
                resharded = elastic._env_from_canonical(canon.inner,
                                                        template)
            _assert_equal(_np_tree(resharded.inner), _np_tree(local_inner))
        # And the full-stack path agrees with zero_from_canonical.
        back = zero_from_canonical(canon.inner, full)
        _assert_equal(_np_tree(back.inner), _np_tree(full.inner))
    finally:
        hvd.shutdown()
        hvd.init()


def test_full_stacked_state_is_not_local():
    try:
        state, step = _build_dp(8)
        assert not elastic._zs_is_local(state.opt_state)
    finally:
        hvd.shutdown()
        hvd.init()
