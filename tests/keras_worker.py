"""Worker for the multi-process Keras fit test (run under `tpurun -np 2`).

The reference CI's Keras analog: `.travis.yml:93-108` runs keras examples
under `mpirun -np 2`. Here Keras (jax backend) jits its train step, so each
gradient exchange crosses into the env-world coordination plane through the
adapter's single pure_callback bridge; ranks start from DIFFERENT seeds and
train on DIFFERENT data shards — only the broadcast callback plus the
per-step gradient allreduce can make them converge to identical weights.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("KERAS_BACKEND", "jax")

import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import keras

import horovod_tpu as hvd
import horovod_tpu.keras as hvd_keras

hvd.init()
assert hvd.process_count() == 2, hvd.process_count()
rank = hvd.rank()

keras.utils.set_random_seed(100 + rank)  # deliberately divergent init
model = keras.Sequential([
    keras.layers.Input((4,)),
    keras.layers.Dense(8, activation="relu"),
    keras.layers.Dense(3),
])
model.compile(
    optimizer=hvd_keras.DistributedOptimizer(
        keras.optimizers.SGD(learning_rate=0.05)),
    loss="sparse_categorical_crossentropy")

rng = np.random.RandomState(rank)  # different shard per rank
x = rng.randn(64, 4).astype(np.float32)
w = np.random.RandomState(42).randn(4, 3).astype(np.float32)
y = np.argmax(x @ w, axis=1)

h = model.fit(x, y, epochs=3, batch_size=16, verbose=0,
              callbacks=[hvd_keras.BroadcastGlobalVariablesCallback(0),
                         hvd_keras.MetricAverageCallback()])
losses = h.history["loss"]
assert losses[-1] < losses[0], losses

# Weights must be bit-identical across ranks: broadcast aligned the starts,
# the averaged gradients kept every step in lockstep.
digest = np.concatenate([np.asarray(v).ravel() for v in model.get_weights()])
gathered = np.asarray(hvd.allgather(
    jnp.asarray(digest.reshape(1, -1)), name="keras.digest"))
assert gathered.shape[0] == 2, gathered.shape
max_dev = float(np.abs(gathered[0] - gathered[1]).max())
assert max_dev < 1e-6, max_dev

# Metric averaging crossed processes too (losses differ per shard before
# averaging; after MetricAverageCallback both ranks log the same number).
peer_losses = np.asarray(hvd.allgather(
    jnp.asarray([[losses[-1]]], jnp.float32), name="keras.loss"))
assert abs(float(peer_losses[0, 0]) - float(peer_losses[1, 0])) < 1e-6

print(f"rank {rank}: KERAS_FIT_OK loss={losses[0]:.4f}->{losses[-1]:.4f} "
      f"weight_dev={max_dev:.2e}", flush=True)
hvd.shutdown()
