"""Test harness: an 8-device virtual CPU mesh plays the role of
``mpirun -np N`` on localhost (reference CI: ``.travis.yml:91`` runs
``mpirun -np 2 python mpi_ops_test.py`` CPU-only; SURVEY §4 implication).

Must run before any jax backend initialization: forces the CPU platform with
8 virtual devices so the world mesh has 8 "ranks" without TPU hardware.
"""

import os
import tempfile

# Flight-recorder dumps (kill drills, abort post-mortems) default to the
# cwd — a suite run from the repo root would litter it with stale
# hvd_flightrec.rank*.json files that mask REAL post-mortems (and could
# satisfy a later run's pinned asserts). Park them in a tmp dir unless
# the caller pinned one.
if "HVD_FLIGHTREC_DIR" not in os.environ:
    # (Not setdefault: its default arg is evaluated eagerly, which would
    # leak one orphan temp dir per run whenever the caller pinned a dir.)
    os.environ["HVD_FLIGHTREC_DIR"] = tempfile.mkdtemp(
        prefix="hvd_flightrec_")

_flag = "--xla_force_host_platform_device_count=8"
_existing = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _existing:
    os.environ["XLA_FLAGS"] = (_existing + " " + _flag).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# The reference's collectives cover 9 dtypes incl. float64/int64
# (mpi_ops.cc:476-510); enable x64 so the sweeps exercise them.
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402

import horovod_tpu as hvd  # noqa: E402


def pytest_configure(config):
    # The tier-1 CI invocation deselects `-m 'not slow'`; register the
    # marker so using it is not an unknown-marker warning.
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 budgeted run "
                   "(multi-minute compiles / hardware-evidence tests)")
    config.addinivalue_line(
        "markers", "subprocess_env(reason=...): tpurun-subprocess tests "
                   "that cannot pass in THIS environment for a named "
                   "infrastructure reason (not a product bug) — skipped "
                   "unless HVD_SUBPROCESS_ENV_TESTS=1, so tier-1 reads "
                   "green-or-real instead of known-dead dots")


def pytest_collection_modifyitems(config, items):
    # subprocess_env: skip with the site's named environment reason so the
    # tier-1 report distinguishes "this environment can't run it" from a
    # real failure. Set HVD_SUBPROCESS_ENV_TESTS=1 (e.g. on a TPU VM or an
    # image whose jaxlib supports what the test needs) to run them anyway.
    if os.environ.get("HVD_SUBPROCESS_ENV_TESTS") == "1":
        return
    for item in items:
        m = item.get_closest_marker("subprocess_env")
        if m is None:
            continue
        reason = m.kwargs.get("reason") or (m.args[0] if m.args else
                                            "environment cannot run "
                                            "tpurun-subprocess worlds")
        item.add_marker(pytest.mark.skip(
            reason=f"subprocess_env: {reason} "
                   f"(HVD_SUBPROCESS_ENV_TESTS=1 overrides)"))


@pytest.fixture(scope="session", autouse=True)
def _world():
    hvd.init()
    yield
    hvd.shutdown()
