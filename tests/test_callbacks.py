"""Callback parity tests (reference: ``horovod/keras/callbacks.py``):
warmup formula endpoints, momentum correction restore, metric averaging,
broadcast at train begin, and checkpoint save/restore round-trip."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu import callbacks, models, trainer as trainer_mod, training


def _mnist_setup(lr=0.1, momentum=0.9):
    model = models.MnistCNN()
    state, dist_opt = training.create_train_state(
        model, jax.random.PRNGKey(0), jnp.zeros((2, 784)),
        callbacks.hyper_sgd(lr, momentum=momentum))
    step = training.make_train_step(model, dist_opt, donate=False)
    return model, state, step


def _toy_batches(n_batches=2, n=16):
    rng = np.random.RandomState(0)
    return [(jnp.asarray(rng.randn(n, 784), jnp.float32),
             jnp.asarray(rng.randint(0, 10, size=(n,))))
            for _ in range(n_batches)]


class TestHyperparams:
    def test_get_set_lr(self):
        _, state, _ = _mnist_setup(lr=0.25)
        assert callbacks.get_hyperparam(state.opt_state,
                                        "learning_rate") == 0.25
        new = callbacks.set_hyperparam(state.opt_state, "learning_rate", 0.5)
        assert callbacks.get_hyperparam(new, "learning_rate") == 0.5


class TestWarmup:
    def test_warmup_endpoints(self):
        """lr'(0) == lr/size and lr'(warmup end) == lr
        (callbacks.py:202-233 math recap)."""
        _, state, step = _mnist_setup(lr=0.8)
        t = trainer_mod.Trainer(step, state, steps_per_epoch=2, verbose=False)
        warmup = callbacks.LearningRateWarmupCallback(
            warmup_epochs=2, steps_per_epoch=2, momentum_correction=False)
        batches = _toy_batches(2)
        size = hvd.size()

        lrs = []
        orig_batch_begin = warmup.on_batch_begin

        def spy(batch, logs=None):
            orig_batch_begin(batch, logs)
            lrs.append(callbacks.get_hyperparam(
                t.state.opt_state, "learning_rate"))
        warmup.on_batch_begin = spy

        t.fit(lambda: batches, epochs=2, callbacks=[warmup])
        # First adjusted batch: epoch'=(0 + 1/steps) → lr/size*(eps*(size-1)/w+1)
        expected_first = 0.8 / size * ((0.5) * (size - 1) / 2 + 1)
        np.testing.assert_allclose(lrs[0], expected_first, rtol=1e-6)
        # Last batch of warmup: epoch' hits warmup_epochs exactly → full lr.
        np.testing.assert_allclose(lrs[-1], 0.8, rtol=1e-6)

    def test_momentum_correction_restores(self):
        _, state, step = _mnist_setup(lr=0.4, momentum=0.9)
        t = trainer_mod.Trainer(step, state, steps_per_epoch=2, verbose=False)
        cb = callbacks.LearningRateScheduleCallback(
            multiplier=lambda e: 0.5, start_epoch=0, staircase=True,
            momentum_correction=True)
        momenta = []

        class Probe(callbacks.Callback):
            def on_batch_begin(self, batch, logs=None):
                momenta.append(("begin", callbacks.get_hyperparam(
                    t.state.opt_state, "momentum")))

            def on_batch_end(self, batch, logs=None):
                momenta.append(("end", callbacks.get_hyperparam(
                    t.state.opt_state, "momentum")))

        # Order matters: cb adjusts on batch begin before Probe reads.
        t.fit(lambda: _toy_batches(2), epochs=1, callbacks=[cb, Probe()])
        # During first batch momentum was scaled by new_lr/old_lr = 0.5 …
        assert momenta[0] == ("begin", pytest.approx(0.45))
        # … and restored after the batch (callbacks.py:168-172).
        assert momenta[1] == ("end", pytest.approx(0.9))
        # Batch 1 (staircase, not batch 0): untouched.
        assert momenta[2] == ("begin", pytest.approx(0.9))

    def test_constant_multiplier_staircase(self):
        _, state, step = _mnist_setup(lr=1.0)
        t = trainer_mod.Trainer(step, state, steps_per_epoch=1, verbose=False)
        cb = callbacks.LearningRateScheduleCallback(
            multiplier=0.1, start_epoch=1, momentum_correction=False)
        history = t.fit(lambda: _toy_batches(1), epochs=2, callbacks=[cb])
        assert history[0]["lr"] == pytest.approx(1.0)   # epoch 0: untouched
        assert history[1]["lr"] == pytest.approx(0.1)   # epoch 1: 1.0 * 0.1


class TestReduceLROnPlateau:
    def test_reduces_after_patience(self):
        _, state, step = _mnist_setup(lr=1.0)
        t = trainer_mod.Trainer(step, state, verbose=False)
        cb = callbacks.ReduceLROnPlateauCallback(
            monitor="val_loss", factor=0.5, patience=2)
        cb.set_trainer(t)
        cb.on_epoch_end(0, {"val_loss": 1.0})   # best
        cb.on_epoch_end(1, {"val_loss": 1.2})   # wait 1
        cb.on_epoch_end(2, {"val_loss": 1.1})   # wait 2 -> reduce
        assert callbacks.get_hyperparam(
            t.state.opt_state, "learning_rate") == pytest.approx(0.5)
        cb.on_epoch_end(3, {"val_loss": 0.5})   # new best, no change
        assert callbacks.get_hyperparam(
            t.state.opt_state, "learning_rate") == pytest.approx(0.5)


class TestMetricAverage:
    def test_scalar_metrics_averaged(self):
        cb = callbacks.MetricAverageCallback()
        logs = {"loss": 2.0, "acc": np.float32(0.5), "name": "skip-me"}
        cb.on_epoch_end(0, logs)
        # Single-controller world: every rank contributes the same value, so
        # the average is the identity — but the collective must execute.
        assert logs["loss"] == pytest.approx(2.0)
        assert logs["acc"] == pytest.approx(0.5)
        assert logs["name"] == "skip-me"


class TestBroadcastCallback:
    def test_state_broadcast_noop_single_controller(self):
        _, state, step = _mnist_setup()
        t = trainer_mod.Trainer(step, state, verbose=False)
        before = np.asarray(
            jax.tree_util.tree_leaves(t.state.params)[0]).copy()
        cb = callbacks.BroadcastGlobalVariablesCallback(0)
        cb.set_trainer(t)
        cb.on_train_begin()
        after = np.asarray(jax.tree_util.tree_leaves(t.state.params)[0])
        np.testing.assert_allclose(before, after)


class TestCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        _, state, step = _mnist_setup()
        batch = _toy_batches(1)[0]
        state, _ = step(state, training.shard_batch(batch))
        path = trainer_mod.save_checkpoint(str(tmp_path), state)
        assert path is not None and os.path.exists(path)
        assert trainer_mod.latest_checkpoint_step(str(tmp_path)) == 1

        _, fresh, _ = _mnist_setup()
        restored = trainer_mod.restore_checkpoint(str(tmp_path), fresh)
        for a, b in zip(jax.tree_util.tree_leaves(state.params),
                        jax.tree_util.tree_leaves(restored.params)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-6)
        assert int(restored.step) == 1

    def test_retention_keeps_newest(self, tmp_path):
        _, state, _ = _mnist_setup()
        for s in range(5):
            trainer_mod.save_checkpoint(str(tmp_path), state, step=s,
                                        max_to_keep=2)
        kept = sorted(n for n in os.listdir(tmp_path)
                      if n.startswith("ckpt_"))
        assert kept == ["ckpt_3", "ckpt_4"], kept
        assert trainer_mod.latest_checkpoint_step(str(tmp_path)) == 4

    def test_retention_survives_rollback_resume(self, tmp_path):
        """Resuming from a rolled-back step: the just-written (lower-step)
        checkpoint must survive retention; stale higher-step leftovers go
        first (retention is by write recency, not step number)."""
        _, state, _ = _mnist_setup()
        for s in (80, 90, 100):
            trainer_mod.save_checkpoint(str(tmp_path), state, step=s)
        path = trainer_mod.save_checkpoint(str(tmp_path), state, step=60,
                                           max_to_keep=2)
        assert os.path.exists(path), "just-written checkpoint was deleted"
        kept = sorted(n for n in os.listdir(tmp_path)
                      if n.startswith("ckpt_"))
        assert "ckpt_60" in kept and len(kept) == 2, kept
