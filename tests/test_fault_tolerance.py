"""Fault-tolerance drills: injected rank death, heartbeat-silence abort,
fail-fast after ABORT, launcher supervision/escalation, and
checkpoint-recovery restart equivalence.

The reference's failure story is the motivation: a dead rank hangs
``MPI_Allreduce`` forever and ``CheckForStalledTensors`` only warns
(``mpi_ops.cc:1153-1196``). Every test here runs with a hard deadline —
a regression that reintroduces the hang FAILS instead of wedging CI.
"""

import os
import re
import signal
import socket
import subprocess
import sys
import time

import pytest

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
ELASTIC_WORKER = os.path.join(HERE, "elastic_worker.py")
FAULT_WORKER = os.path.join(HERE, "fault_worker.py")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _tpurun(np_, worker, *, env=None, extra_args=(), timeout=240):
    full_env = dict(os.environ, PYTHONPATH="", XLA_FLAGS="")
    full_env.update(env or {})
    return subprocess.run(
        [sys.executable, "-m", "horovod_tpu.launcher", "-np", str(np_),
         "--cpu", *extra_args, sys.executable, worker],
        cwd=ROOT, env=full_env, capture_output=True, text=True,
        timeout=timeout)


# ---------------------------------------------------------------------------
# (a) injected rank death: all ranks exit nonzero within the deadline.
# ---------------------------------------------------------------------------

def test_killed_rank_aborts_world_no_hang(tmp_path):
    """SIGKILL rank 2 of 4 at step 3: the coordinator must broadcast an
    ABORT naming rank 2 and every rank must exit (nonzero) promptly —
    the communicate() deadline IS the no-hang assertion."""
    t0 = time.monotonic()
    out = _tpurun(
        4, ELASTIC_WORKER,
        env={"HVD_FAULT_SPEC": "rank=2:kill@step=3",
             "HVD_ELASTIC_DIR": str(tmp_path),
             "HVD_HEARTBEAT_TIMEOUT": "10",
             "HVD_TOTAL_STEPS": "6"},
        timeout=180)
    elapsed = time.monotonic() - t0
    assert out.returncode != 0, out.stdout + out.stderr
    combined = out.stdout + out.stderr
    assert "worker failure: rank 2" in combined, combined
    # Well under HVD_HEARTBEAT_TIMEOUT + 10 s once startup is discounted:
    # death is detected via the disconnect path, not the heartbeat sweep.
    # (The bound is generous for a loaded 2-core CI host where 4 JAX
    # processes contend for startup; the reference's behavior here is
    # literally infinite.)
    assert elapsed < 150, f"abort took {elapsed:.0f}s — hang regression?"
    # Nobody should have printed a FINAL line: training never completed.
    assert "FINAL" not in out.stdout, out.stdout


def test_silent_rank_heartbeat_abort(tmp_path):
    """A rank that goes SILENT (heartbeats muted, process alive) must be
    declared dead after HVD_HEARTBEAT_TIMEOUT — the path a plain kill
    cannot exercise because the kernel closes a dead process's socket."""
    out = _tpurun(
        2, ELASTIC_WORKER,
        env={"HVD_FAULT_SPEC": "rank=1:mute@step=1",
             "HVD_ELASTIC_DIR": str(tmp_path),
             "HVD_HEARTBEAT_TIMEOUT": "5",
             "HVD_TOTAL_STEPS": "4"},
        timeout=180)
    assert out.returncode != 0, out.stdout + out.stderr
    combined = out.stdout + out.stderr
    assert "went silent" in combined, combined
    assert "worker failure: rank 1" in combined, combined


# ---------------------------------------------------------------------------
# (b) fail-fast after ABORT (and stalled-name reuse stays fail-fast).
# ---------------------------------------------------------------------------

def test_abort_fail_fast_and_stalled_name_reuse():
    """Direct two-rank world (no launcher, so rank 0 is free to finish its
    checks after rank 1 dies): rank 0 must see StalledError, then a
    WorkerFailureError naming rank 1, and every later submit must fail
    fast instead of hanging."""
    port = _free_port()
    base = dict(os.environ, PYTHONPATH="", JAX_PLATFORMS="cpu",
                HVD_SIZE="2", HVD_COORD_ADDR=f"127.0.0.1:{port}",
                HVD_HEARTBEAT_TIMEOUT="30")
    procs = []
    for rank in range(2):
        env = dict(base, HVD_RANK=str(rank))
        if rank == 0:
            env["HOROVOD_STALL_TIMEOUT"] = "2"
        procs.append(subprocess.Popen(
            [sys.executable, FAULT_WORKER], cwd=ROOT, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=120)[0])
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert procs[0].returncode == 0, outs[0]
    assert procs[1].returncode == 1, outs[1]  # deliberate os._exit(1)
    for marker in ("STALL OK", "ABORT OK", "FAULT OK"):
        assert marker in outs[0], outs[0]


# ---------------------------------------------------------------------------
# launcher supervision: sibling teardown + terminate->kill escalation.
# ---------------------------------------------------------------------------

def test_launcher_kills_sigterm_ignoring_sibling():
    """Worker rank 0 fails immediately; rank 1 IGNORES SIGTERM and sleeps.
    The supervisor must escalate to SIGKILL after the grace period and
    return promptly — the seed's terminate()-only cleanup left such a
    worker running forever."""
    from horovod_tpu import launcher
    script = (
        "import os, signal, time\n"
        "if os.environ['HVD_RANK'] == '0':\n"
        "    raise SystemExit(3)\n"
        "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
        "time.sleep(120)\n"
    )
    t0 = time.monotonic()
    rc = launcher.launch(2, [sys.executable, "-c", script], cpu=True)
    elapsed = time.monotonic() - t0
    assert rc == 3
    assert elapsed < launcher.TERMINATE_GRACE_SECS + 15, (
        f"supervision took {elapsed:.0f}s — escalation broken?")


# ---------------------------------------------------------------------------
# (c) checkpoint-recovery restart: final params match an uninterrupted run.
# ---------------------------------------------------------------------------

def _final_lines(stdout: str):
    return dict(re.findall(r"rank (\d+)/\d+: (FINAL [0-9.]+ step \d+)",
                           stdout))


def test_run_with_recovery_matches_uninterrupted(tmp_path):
    """Kill rank 1 at step 3, relaunch once (tpurun --restarts 1), resume
    from the committed step: the final params must be bit-identical to an
    uninterrupted run (the elastic acceptance drill)."""
    steps_env = {"HVD_TOTAL_STEPS": "6", "HVD_HEARTBEAT_TIMEOUT": "10"}

    clean = _tpurun(
        2, ELASTIC_WORKER,
        env=dict(steps_env, HVD_ELASTIC_DIR=str(tmp_path / "clean")),
        timeout=240)
    assert clean.returncode == 0, clean.stdout + clean.stderr
    clean_final = _final_lines(clean.stdout)
    assert set(clean_final) == {"0", "1"}, clean.stdout

    faulty = _tpurun(
        2, ELASTIC_WORKER,
        env=dict(steps_env,
                 HVD_ELASTIC_DIR=str(tmp_path / "faulty"),
                 HVD_FAULT_SPEC="rank=1:kill@step=3"),
        extra_args=("--restarts", "1"),
        timeout=300)
    assert faulty.returncode == 0, faulty.stdout + faulty.stderr
    combined = faulty.stdout + faulty.stderr
    assert "worker failure: rank 1" in combined, combined
    assert "resumed from committed step" in faulty.stdout, faulty.stdout
    faulty_final = _final_lines(faulty.stdout)
    assert faulty_final == clean_final, (
        f"recovered run diverged:\nclean={clean_final}\n"
        f"faulty={faulty_final}")


# ---------------------------------------------------------------------------
# unit-level satellites: fault-spec parsing, from_env validation.
# ---------------------------------------------------------------------------

def test_fault_spec_parser():
    from horovod_tpu.testing import faults
    spec = faults.parse_spec(
        "rank=2:kill@step=5, coord:delay_ms=500, "
        "rank=0:mute@step=3@epoch=1, coord:mute@step=2")
    assert [f.action for f in spec] == ["kill", "delay_ms", "mute", "mute"]
    assert spec[0].rank == 2 and spec[0].step == 5 and spec[0].epoch == 0
    assert spec[1].target == "coord" and spec[1].value == 500
    assert spec[2].epoch == 1
    assert spec[3].target == "coord" and spec[3].step == 2
    for bad in ("rank:kill@step=1", "rank=x:kill@step=1", "rank=1:boom",
                "coord:delay_ms=abc", "rank=1:kill@banana=2", "rank=1:",
                "coord:delay_ms=50@step=3",  # delay has no step context
                "rank=1:kill",               # step-scoped but no @step:
                "coord:mute@epoch=1"):       # could never fire
        with pytest.raises(faults.FaultSpecError):
            faults.parse_spec(bad)


def test_from_env_malformed_addr(monkeypatch):
    from horovod_tpu.coord.client import CoordClient
    monkeypatch.setenv("HVD_COORD_ADDR", "127.0.0.1:notaport")
    with pytest.raises(ValueError, match="not an integer"):
        CoordClient.from_env(rank=0, size=2)
    monkeypatch.setenv("HVD_COORD_ADDR", "127.0.0.1:99999")
    with pytest.raises(ValueError, match="outside"):
        CoordClient.from_env(rank=0, size=2)


def test_sigint_forwarded_to_workers():
    """Ctrl-C on tpurun must tear the workers down (SIGINT handling —
    the seed only handled SIGTERM)."""
    script = "import time\ntime.sleep(120)\n"
    env = dict(os.environ, PYTHONPATH="")
    p = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.launcher", "-np", "2", "--cpu",
         sys.executable, "-c", script],
        cwd=ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    time.sleep(3.0)  # let it spawn the workers
    p.send_signal(signal.SIGINT)
    t0 = time.monotonic()
    out, _ = p.communicate(timeout=30)
    assert time.monotonic() - t0 < 25
    assert p.returncode != 0, out
